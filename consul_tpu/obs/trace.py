"""Lightweight distributed tracing for the RPC mesh.

A *span* is one timed operation on one node; a *trace* is the tree of
spans sharing a trace id, stitched across processes by (a) propagating
the (trace_id, span_id) pair in an optional ``"Trace"`` field of the
msgpack request envelope (rpc/wire.py helpers, sent by rpc/pool.py,
honored by rpc/server.py) and (b) *backhauling* the spans a remote
server finished while handling a forwarded request in an optional
``"Spans"`` field of the response envelope.  The backhaul means the
originating agent's ring holds the COMPLETE trace — http root, the
forward hop, the leader-side raft apply and FSM dispatch — without any
out-of-band collector.

Context propagation is a ``contextvars.ContextVar``: task-local, and
``asyncio.create_task`` snapshots the creating task's context, so a
span opened around an ``await`` is visible to everything the awaited
code spawns.  The raft durability pump runs outside any request
context, so consensus/raft.py stashes the submitting request's context
by log index and re-activates it around ``fsm.apply`` (see
``Raft._apply_committed``).

Overhead when idle: one ContextVar read per potential child span
(~100ns); no locks taken until a span actually finishes.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

# Trace context of the current task: None outside any traced request.
_current: contextvars.ContextVar[Optional["SpanContext"]] = \
    contextvars.ContextVar("consul_trace", default=None)

# Ring/buffer bounds (see Tracer): small enough that a debug-enabled
# agent under heavy traffic stays O(MB), large enough for a test or an
# operator paging through recent requests.
MAX_OPEN_TRACES = 512     # distinct trace ids with unfinished spans
MAX_SPANS_PER_TRACE = 64  # runaway-recursion guard
RING_TRACES = 256         # finished traces kept for /v1/agent/traces


def _new_id() -> str:
    return os.urandom(8).hex()


class SpanContext:
    """Immutable (trace_id, span_id) pair — what crosses the wire."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str) -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"SpanContext({self.trace_id}/{self.span_id})"


class Span:
    """One in-flight operation.  Created via the module helpers
    (``root_span``/``child_span``/``server_span``), finished exactly
    once via ``finish()`` (idempotent).  While open it is installed as
    the current context so children nest under it."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "_t0", "duration_ms", "tags", "error", "_token",
                 "_tracer", "_is_root")

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional[SpanContext],
                 tags: Optional[Dict[str, Any]] = None,
                 is_root: bool = False) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = parent.trace_id if parent else _new_id()
        self.span_id = _new_id()
        self.parent_id = parent.span_id if parent else None
        self.start = time.time()
        self._t0 = time.monotonic()
        self.duration_ms: Optional[float] = None
        self.tags: Dict[str, Any] = dict(tags) if tags else {}
        self.error: Optional[str] = None
        self._is_root = is_root
        self._token = _current.set(SpanContext(self.trace_id, self.span_id))

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def set_error(self, exc: BaseException) -> None:
        self.error = f"{type(exc).__name__}: {exc}"

    def finish(self) -> None:
        if self.duration_ms is not None:
            return  # already finished
        self.duration_ms = (time.monotonic() - self._t0) * 1000.0
        try:
            _current.reset(self._token)
        except ValueError:
            # Finished from a different context than it was opened in
            # (e.g. a callback); restoring the parent is best-effort.
            _current.set(None)
        self._tracer._record(self)

    def to_wire(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "TraceID": self.trace_id, "SpanID": self.span_id,
            "ParentID": self.parent_id, "Name": self.name,
            "Node": self._tracer.node_name,
            "Start": self.start, "DurationMs": self.duration_ms,
        }
        if self.tags:
            d["Tags"] = self.tags
        if self.error:
            d["Error"] = self.error
        return d


class Tracer:
    """Process-global span collector.

    Finished spans buffer per trace id until the trace's ROOT span (a
    span opened with no parent on this node) finishes, at which point
    the whole trace moves to a bounded deque served by
    ``/v1/agent/traces``.  Spans belonging to a *remote* root (opened
    here with a wire parent) never promote to the ring locally; the RPC
    server layer calls ``take()`` to pull them into the response
    envelope, and the caller's tracer ``ingest()``s them.
    """

    def __init__(self) -> None:
        self.node_name: str = ""
        self.enabled: bool = True
        self._lock = threading.Lock()
        self._bufs: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=RING_TRACES)

    # -- collection (called from Span.finish) ------------------------------

    def _record(self, span: Span) -> None:
        if not self.enabled:
            return
        wire = span.to_wire()
        with self._lock:
            buf = self._bufs.get(span.trace_id)
            if buf is None:
                if len(self._bufs) >= MAX_OPEN_TRACES:
                    self._bufs.popitem(last=False)  # evict oldest open
                buf = self._bufs[span.trace_id] = []
            if len(buf) < MAX_SPANS_PER_TRACE:
                buf.append(wire)
            if span._is_root:
                self._bufs.pop(span.trace_id, None)
                self._ring.append({"TraceID": span.trace_id, "Spans": buf})

    # -- cross-process stitching -------------------------------------------

    def take(self, trace_id: str) -> List[Dict[str, Any]]:
        """Pop the buffered spans for a trace (server side of the span
        backhaul: they ride home in the response envelope)."""
        with self._lock:
            return self._bufs.pop(trace_id, [])

    def ingest(self, spans: List[Dict[str, Any]]) -> None:
        """Re-home spans backhauled from a remote server into the local
        buffers, so the eventual root finish captures them."""
        if not self.enabled or not spans:
            return
        with self._lock:
            for wire in spans:
                tid = wire.get("TraceID")
                if not tid:
                    continue
                buf = self._bufs.get(tid)
                if buf is None:
                    if len(self._bufs) >= MAX_OPEN_TRACES:
                        self._bufs.popitem(last=False)
                    buf = self._bufs[tid] = []
                if len(buf) < MAX_SPANS_PER_TRACE:
                    buf.append(wire)

    # -- read side ----------------------------------------------------------

    def traces(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Most-recent finished traces, newest first."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:max(0, int(limit))]

    def clear(self) -> None:
        with self._lock:
            self._bufs.clear()
            self._ring.clear()


tracer = Tracer()


# -- context helpers ---------------------------------------------------------

def current_context() -> Optional[SpanContext]:
    return _current.get()


def set_context(ctx: Optional[SpanContext]) -> "contextvars.Token":
    """Install a context directly (raft apply path); pair with
    ``reset_context``."""
    return _current.set(ctx)


def reset_context(token: "contextvars.Token") -> None:
    try:
        _current.reset(token)
    except ValueError:
        _current.set(None)


# -- span constructors -------------------------------------------------------

def root_span(name: str, tags: Optional[Dict[str, Any]] = None) -> Span:
    """Start a new trace (HTTP/DNS edge).  Always returns a span."""
    return Span(tracer, name, parent=None, tags=tags, is_root=True)


def child_span(name: str,
               tags: Optional[Dict[str, Any]] = None) -> Optional[Span]:
    """Child of the current context, or None when nothing is being
    traced — callers guard with ``if span is not None`` (or just
    ``finish_span(span)``)."""
    ctx = _current.get()
    if ctx is None:
        return None
    return Span(tracer, name, parent=ctx, tags=tags)


def server_span(name: str, remote: SpanContext,
                tags: Optional[Dict[str, Any]] = None) -> Span:
    """Server side of a forwarded RPC: child of a WIRE parent.  Never a
    root — its spans are backhauled via ``Tracer.take``."""
    return Span(tracer, name, parent=remote, tags=tags)


def finish_span(span: Optional[Span],
                exc: Optional[BaseException] = None) -> None:
    """None-tolerant finish, with optional error capture."""
    if span is None:
        return
    if exc is not None:
        span.set_error(exc)
    span.finish()
