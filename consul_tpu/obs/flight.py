"""SWIM kernel flight recorder — host side.

The jitted gossip kernel (gossip/kernel.py) accumulates one row of
per-round counters into a small HBM ring (``FlightRing``) INSIDE the
scan body — no host transfer per round.  The gossip plane drains the
ring in amortized batches (every ``DRAIN_EVERY_DISPATCHES`` dispatches
= ``DRAIN_EVERY_DISPATCHES * STEPS_PER_TICK`` rounds, >= 64) with a
single device->host copy, and hands the rows to the
``FlightRecorder`` here, which

- keeps a bounded host-side timeline for ``/v1/agent/flight``,
- folds deltas into the ``utils.telemetry`` registry as
  ``consul.flight.*`` counters/gauges (so they show up in statsd,
  the inmem dump, and the Prometheus exposition).

This module deliberately does NOT import jax: the agent process serves
``/v1/agent/flight`` from bridge frames without a kernel context.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

# Column layout of one flight row — the kernel (gossip/kernel.py) builds
# rows in EXACTLY this order; keep the two in lockstep.
FLIGHT_COLS = (
    "round",             # kernel round counter at row write
    "probes",            # direct probes fired this round
    "acks_missed",       # direct probes whose ack window closed empty
    "indirect_probes",   # indirect (k-rescue) escalations
    "suspect_new",       # fresh suspicion verdict timers armed
    "alive_events",      # refutations applied (suspect -> alive)
    "dead_events",       # dead verdicts fired (incl. false positives)
    "join_rumors",       # slots still in join/bootstrap phase
    "queue_occupancy",   # occupied rumor slots (active verdicts)
    "dissem_bytes",      # gossip payload bytes pushed this round
    "drops",             # cumulative rescue-slot drops delta
    "members",           # live member count after the round
)
N_COLS = len(FLIGHT_COLS)

# Columns folded into the registry as monotonic counters (per-round
# deltas summed over the drained window) vs. sampled gauges (last row).
_COUNTER_COLS = ("probes", "acks_missed", "indirect_probes", "suspect_new",
                 "alive_events", "dead_events", "dissem_bytes", "drops")
_GAUGE_COLS = ("round", "join_rumors", "queue_occupancy", "members")

TIMELINE_ROWS = 4096  # bounded host-side history for /v1/agent/flight


def fold_summary(metrics: Any, summary: Dict[str, Any]) -> None:
    """Mirror a REMOTE recorder's ``wire()["summary"]`` into a local
    registry as ``consul.flight.*`` gauges.

    The recorder proper lives in the gossip-plane process and folds
    into *that* process's registry; the agent calls this at scrape
    time (``/v1/agent/metrics?format=prometheus``) so its exposition
    carries the flight series too.  Everything is a gauge here — the
    counter columns arrive as cumulative totals, and re-counting them
    locally would double-book deltas across the two processes."""
    for c in FLIGHT_COLS + ("rows_recorded", "rows_overflowed"):
        if c in summary:
            metrics.set_gauge(("consul", "flight", c), summary[c])


class FlightRecorder:
    """Host-side sink for drained flight rings.

    ``ingest(rows, cursor)`` takes the full ring (shape [R, N_COLS],
    any array-like of ints) plus the kernel's monotonically increasing
    write cursor, extracts only the rows written since the previous
    drain (in write order, handling wraparound), and accounts for
    overflow when more than R rounds elapsed between drains.
    """

    def __init__(self, metrics: Optional[Any] = None) -> None:
        if metrics is None:
            from consul_tpu.utils.telemetry import metrics as _global
            metrics = _global
        self._metrics = metrics
        self._lock = threading.Lock()
        self._timeline: "deque[Dict[str, int]]" = deque(maxlen=TIMELINE_ROWS)
        self._totals: Dict[str, int] = {c: 0 for c in _COUNTER_COLS}
        self._last: Dict[str, int] = {}
        self._last_cursor = 0
        self._overflowed = 0  # rows lost to ring wrap between drains

    @property
    def last_cursor(self) -> int:
        """Kernel cursor as of the last drain (lets the drainer skip a
        device sync when nothing new was written)."""
        with self._lock:
            return self._last_cursor

    # -- drain path ---------------------------------------------------------

    def ingest(self, rows: Sequence[Sequence[int]], cursor: int) -> int:
        """Fold one drained ring into the timeline/registry.  Returns
        the number of new rows consumed."""
        cursor = int(cursor)
        ring_len = len(rows)
        dropped = 0
        with self._lock:
            new = cursor - self._last_cursor
            if new <= 0 or ring_len == 0:
                self._last_cursor = max(cursor, self._last_cursor)
                return 0
            if new > ring_len:
                # Rows overwritten before this drain: counted, never
                # silently lost (consul.flight.dropped).
                dropped = new - ring_len
                self._overflowed += dropped
                new = ring_len
            # Ring order: the kernel writes row i at slot i % R, so the
            # oldest retained row sits at slot (cursor - new) % R.
            start = (cursor - new) % ring_len
            picked: List[Dict[str, int]] = []
            for k in range(new):
                raw = rows[(start + k) % ring_len]
                picked.append({c: int(raw[j])
                               for j, c in enumerate(FLIGHT_COLS)})
            for rec in picked:
                self._timeline.append(rec)
                for c in _COUNTER_COLS:
                    self._totals[c] += rec[c]
            self._last = dict(picked[-1])
            self._last_cursor = cursor
            window = {c: sum(r[c] for r in picked) for c in _COUNTER_COLS}
            last = self._last
        # Registry updates outside the lock (sinks may do I/O: statsd).
        for c in _COUNTER_COLS:
            if window[c]:
                self._metrics.incr_counter(("consul", "flight", c), window[c])
        for c in _GAUGE_COLS:
            self._metrics.set_gauge(("consul", "flight", c), last[c])
        if dropped:
            self._metrics.incr_counter(("consul", "flight", "dropped"),
                                       dropped)
        if self._overflowed:
            self._metrics.set_gauge(("consul", "flight", "overflowed"),
                                    self._overflowed)
        return len(picked)

    # -- read side ----------------------------------------------------------

    def timeline(self, limit: int = 256) -> List[Dict[str, int]]:
        """Most recent per-round rows, oldest first."""
        with self._lock:
            out = list(self._timeline)
        return out[-max(0, int(limit)):]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            # Gauge columns from the last row; counter columns are the
            # all-time totals (the last row's per-round delta must not
            # shadow them).
            s: Dict[str, Any] = {c: self._last.get(c, 0)
                                 for c in _GAUGE_COLS}
            s.update(self._totals)
            s["rows_recorded"] = self._last_cursor
            s["rows_overflowed"] = self._overflowed
            return s

    def wire(self, limit: int = 256) -> Dict[str, Any]:
        """Bridge/HTTP payload for /v1/agent/flight."""
        rows = self.timeline(limit)
        return {"cols": list(FLIGHT_COLS),
                "rows": [[r[c] for c in FLIGHT_COLS] for r in rows],
                "summary": self.summary()}
