"""Serving-plane per-endpoint request statistics.

The telemetry registry's ``AggregateSample`` keeps count/sum/min/max
only — no percentiles — so the serving plane records each request's
latency here too: a per-endpoint counter plus a bounded ring of recent
latency samples, from which p50/p99 are computed at scrape time.
``/v1/agent/metrics?format=prometheus`` renders the snapshot as a
labeled counter + summary family (the JSON form stays the raw inmem
interval list for compatibility)::

    consul_http_requests_total{endpoint="kvs"} 1234
    consul_http_request_ms{endpoint="kvs",quantile="0.5"} 1.2
    consul_http_request_ms{endpoint="kvs",quantile="0.99"} 4.8

Endpoint names are the HTTP handler names (``kvs``, ``status_leader``,
…) for edge-served requests, and hot-op names (``kv_get``, ``kv_put``,
…) for requests served to SO_REUSEPORT workers through the gateway —
both planes land in the one master-process registry.
"""

from __future__ import annotations

from typing import Any, Dict, List

_WINDOW = 1024  # recent-latency ring size per endpoint


class EndpointStats:
    def __init__(self, window: int = _WINDOW) -> None:
        self._window = window
        self._stats: Dict[str, Dict[str, Any]] = {}

    def record(self, name: str, ms: float) -> None:
        st = self._stats.get(name)
        if st is None:
            st = self._stats[name] = {
                "count": 0, "sum_ms": 0.0,
                "ring": [0.0] * self._window, "filled": 0, "next": 0}
        st["count"] += 1
        st["sum_ms"] += ms
        ring = st["ring"]
        ring[st["next"]] = ms
        st["next"] = (st["next"] + 1) % self._window
        if st["filled"] < self._window:
            st["filled"] += 1

    def reset(self) -> None:
        self._stats.clear()

    @staticmethod
    def _pct(sorted_lat: List[float], q: float) -> float:
        return sorted_lat[min(len(sorted_lat) - 1,
                              int(q * len(sorted_lat)))]

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{endpoint: {count, sum_ms, p50_ms, p99_ms}} over the
        retained window (percentiles) / process lifetime (counts)."""
        out: Dict[str, Dict[str, float]] = {}
        for name, st in self._stats.items():
            lat = sorted(st["ring"][: st["filled"]])
            row = {"count": st["count"],
                   "sum_ms": round(st["sum_ms"], 3)}
            if lat:
                row["p50_ms"] = round(self._pct(lat, 0.50), 3)
                row["p99_ms"] = round(self._pct(lat, 0.99), 3)
            out[name] = row
        return out

    def prom_families(self) -> tuple:
        """(counter_rows, summary_families) for obs.prom rendering:
        counter_rows is ``[(labels, value)]`` for
        ``consul_http_requests_total``; summary_families follow the
        render_prometheus ``summaries=`` shape."""
        counter_rows = []
        summaries = []
        for name, row in sorted(self.snapshot().items()):
            labels = {"endpoint": name}
            counter_rows.append((labels, float(row["count"])))
            if "p50_ms" in row:
                summaries.append({
                    "name": "consul_http_request_ms",
                    "help": "Recent request latency per endpoint (ms).",
                    "labels": labels,
                    "quantiles": [(0.5, row["p50_ms"]),
                                  (0.99, row["p99_ms"])],
                    "sum": row["sum_ms"], "count": float(row["count"]),
                })
        return counter_rows, summaries


reqstats = EndpointStats()
