"""Device & kernel observatory: the instrument, instrumented.

The observability stack covers the protocol (flight recorder,
detection-latency banks, SLO board), the edge (reqstats), and the
consensus plane (raftstats) — this module covers the layer the repo
exists for: the accelerator running the SWIM kernel.  A ``DevStats``
instance rides on the gossip plane (gossip/plane.py) and collects:

* **dispatch telemetry** — host-monotonic latency histograms per jit
  dispatch class (``round_step``, ``sharded_round``, ``multidc_outer``,
  ``drain``), plus a rounds/s EWMA gauge refreshed every dispatch.
  The hists observe every dispatch (two clock reads — far cheaper than
  the dispatch itself); the heavier device sampling below rides the
  plane's flight-drain cadence instead.
* **device telemetry** — per-device HBM bytes-in-use / bytes-limit via
  ``Device.memory_stats()`` plus a live-buffer census over
  ``jax.live_arrays()``.  Both degrade gracefully: CPU backends report
  no ``memory_stats`` (the HBM gauges are simply absent), and a
  process without jax reports no devices at all.
* **compile telemetry** — per-callable compile wall time, persistent-
  cache hit/miss counters (detected by counting cache-dir entries
  around the compile — a fresh compile persists new entries, a hit
  does not), and lowered ``cost_analysis()`` FLOPs / bytes-accessed
  estimates.  From these a **roofline-utilization gauge** is derived:
  achieved HBM traffic (bytes/round x rounds/s) over the BENCH_NOTES
  §1c effective ceiling — computed, never hand-maintained.  The same
  derivation (:func:`roofline_utilization`) is the one bench.py,
  tools/profile_kernel.py, and ``/v1/agent/profile`` report, so every
  profiling path agrees on one figure.

Conventions, matching the rest of obs/:

* host-side plain-int banks (the raftstats/HistRecorder contract —
  never wrap), no locks (single event loop), and **no module-level jax
  import** — the agent process renders wire payloads without a kernel;
  only the device-sampling helpers import jax, lazily, and degrade.

The whole observatory compiles out for A/B overhead runs:
``CONSUL_TPU_DEV_OBS=0`` makes ``enabled()`` false, the plane then
carries ``_dev = None`` and every hot-path hook is one
attribute-is-None test (BENCH_NOTES.md §11 measures the delta).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from consul_tpu.obs.raftstats import LatencyHist
from consul_tpu.version import VERSION

# Dense-regime roofline inputs (BENCH_NOTES.md §1c): every
# non-quiescent round materializes the S×N belief matrix ~5 times
# (1 read + 3 shifted reads + 1 write) at the chip's measured
# effective ~185 GB/s.  Single source of truth — bench.py imports
# these rather than restating the prose.
EFFECTIVE_HBM_GBPS = 185.0
DENSE_PASSES_PER_ROUND = 5

# Analytic dense passes per dissemination strategy (BENCH_NOTES §13):
# swar/planes materialize the aged matrix, the three rolled pins, and
# the output (~5); prefused commutes the age tick across the rolls so
# the aged copy never lands (~4); the fused Pallas kernel reads each
# block once and writes it once (~2).  cost_analysis() supersedes all
# of these when a lowering lands (DevStats.bytes_per_round).
DENSE_PASSES_BY_DISSEM = {"swar": DENSE_PASSES_PER_ROUND,
                          "planes": DENSE_PASSES_PER_ROUND,
                          "prefused": 4, "fused": 2}

# Jit dispatch classes the plane (and bench) attribute latency to.
# ``multidc_outer`` is reserved for the multi-DC outer jit
# (gossip/multidc.py run_multidc_rounds — bench regime today, a
# multi-DC plane tomorrow); its ladder renders zero-count until then
# so dashboards see the full schema.
DISPATCH_CLASSES: Tuple[str, ...] = ("round_step", "sharded_round",
                                     "multidc_outer", "drain")

_EWMA_ALPHA = 0.2   # rounds/s gauge smoothing per dispatch sample


def enabled() -> bool:
    """Observatory switch: CONSUL_TPU_DEV_OBS=0 compiles it out (the
    A/B leg of the BENCH_NOTES §11 overhead measurement)."""
    return os.environ.get("CONSUL_TPU_DEV_OBS", "1").lower() not in (
        "0", "false", "no")


# -- the shared roofline derivation (bench / profile / agent) -------------

def dense_bytes_per_round(slots: int, n: int,
                          dissem: str = "swar") -> float:
    """HBM bytes one dense (non-quiescent) round moves: the §1c
    analytic estimate (strategy-aware, DENSE_PASSES_BY_DISSEM) used
    until a lowered cost_analysis() refines it."""
    passes = DENSE_PASSES_BY_DISSEM.get(dissem, DENSE_PASSES_PER_ROUND)
    return float(passes) * float(slots) * float(n)


def roofline_utilization(bytes_per_round: float, rounds_per_sec: float,
                         ceiling_gbps: float = EFFECTIVE_HBM_GBPS
                         ) -> Optional[float]:
    """Achieved HBM bandwidth over the effective ceiling, as a 0..1
    fraction (can exceed 1 when the workload takes the quiescent fast
    path and skips the dense passes the estimate assumes).  None when
    either input is unknown/zero."""
    if not bytes_per_round or not rounds_per_sec:
        return None
    if bytes_per_round < 0 or rounds_per_sec < 0 or ceiling_gbps <= 0:
        return None
    return (bytes_per_round * rounds_per_sec) / (ceiling_gbps * 1e9)


# -- device sampling (lazy jax; degrades to absent) -----------------------

def device_rows() -> List[Dict[str, Any]]:
    """One row per local device: platform/kind, HBM occupancy when the
    backend exposes ``memory_stats()`` (CPU returns None — the hbm_*
    keys are then absent, not zero), and a live-buffer census over
    ``jax.live_arrays()`` (bytes of a multi-device array are split
    evenly across its devices).  Returns [] when jax is unavailable."""
    try:
        import jax
    except Exception:
        return []
    census: Dict[int, List[float]] = {}
    try:
        for arr in jax.live_arrays():
            try:
                devs = list(arr.devices())
                nb = float(getattr(arr, "nbytes", 0) or 0) / max(
                    1, len(devs))
                for d in devs:
                    c = census.setdefault(d.id, [0, 0.0])
                    c[0] += 1
                    c[1] += nb
            except Exception:
                continue  # array deleted mid-iteration
    except Exception:
        census = {}
    rows: List[Dict[str, Any]] = []
    try:
        devices = jax.devices()
    except Exception:
        return []
    for d in devices:
        row: Dict[str, Any] = {
            "id": int(d.id),
            "platform": str(getattr(d, "platform", "")),
            "kind": str(getattr(d, "device_kind", "")),
        }
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            if stats.get("bytes_in_use") is not None:
                row["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
            limit = (stats.get("bytes_limit")
                     or stats.get("bytes_reservable_limit"))
            if limit:
                row["hbm_bytes_limit"] = int(limit)
        cnt, nb = census.get(int(d.id), [0, 0.0])
        row["live_buffers"] = int(cnt)
        row["live_buffer_bytes"] = int(nb)
        rows.append(row)
    return rows


def cache_entries(cache_dir: str) -> Optional[int]:
    """Entry count of the persistent compile cache directory (None when
    unset/absent) — counted before/after a compile, the delta tells a
    cache hit (no new entries persisted) from a miss."""
    if not cache_dir:
        return None
    try:
        return sum(1 for _ in os.scandir(cache_dir))
    except OSError:
        return None


def jax_version() -> str:
    """Installed jax version WITHOUT importing jax (metadata read only
    — the agent process must stay kernel-free)."""
    try:
        from importlib import metadata
        return metadata.version("jax")
    except Exception:
        return "absent"


def build_info(backend: str) -> Dict[str, str]:
    return {"version": VERSION, "jax_version": jax_version(),
            "backend": backend}


def build_info_families(backend: str) -> List[Dict[str, Any]]:
    """Standard Prometheus hygiene gauges, NOT gated on ``enabled()``:
    ``consul_build_info`` (constant 1, identity in the labels) and
    ``consul_up`` (a scrape that renders at all is up — the gauge
    exists so absence alerts are writable)."""
    return [
        {"name": "consul_build_info",
         "help": "Build identity; constant 1, identity in the labels.",
         "rows": [(build_info(backend), 1.0)]},
        {"name": "consul_up",
         "help": "Agent liveness: 1 while the scrape endpoint serves.",
         "rows": [({}, 1.0)]},
    ]


# -- the observatory ------------------------------------------------------

class DevStats:
    """Per-plane device/kernel observatory (module docstring).  All
    writes happen on the plane's event loop; reads ship over the bridge
    as the ``device`` frame."""

    def __init__(self) -> None:
        self.dispatch: Dict[str, LatencyHist] = {
            cls: LatencyHist(
                "consul_kernel_dispatch_ms",
                "Host-monotonic jit dispatch latency by dispatch "
                "class, milliseconds.")
            for cls in DISPATCH_CLASSES}
        self.rounds_per_sec_ewma = 0.0
        self._ewma_last_t: Optional[float] = None
        self.compile_wall_s: Dict[str, float] = {}
        self.cache_hits = 0
        self.cache_misses = 0
        # callable -> {"flops": f, "bytes_accessed": b} from a lowered
        # cost_analysis(); estimates for ONE dispatch (steps rounds).
        self.cost: Dict[str, Dict[str, float]] = {}
        # Session geometry for the analytic roofline fallback and for
        # normalizing per-dispatch cost estimates to per-round.
        self._slots = 0
        self._n = 0
        self._steps_per_dispatch = 1
        self._ndev = 1
        self._dissem = "swar"
        # Device rows sampled on the plane's flight-drain cadence (the
        # census walks every live array — too heavy per dispatch).
        self._device_rows: List[Dict[str, Any]] = []
        self._device_sampled_at = 0.0

    # -- hot-path hooks (each guarded by one `is not None` at the call
    # -- site; everything here is O(small)) -------------------------------

    def note_dispatch(self, cls: str, ms: float, rounds: int,
                      now: Optional[float] = None) -> None:
        """One completed jit dispatch of ``rounds`` kernel rounds that
        took ``ms`` host-monotonic milliseconds.  ``rounds > 0``
        refreshes the rounds/s EWMA from the inter-dispatch wall time
        (the plane idles between ticks, so in-dispatch rate would
        overstate throughput)."""
        h = self.dispatch.get(cls)
        if h is None:
            h = self.dispatch[cls] = LatencyHist(
                "consul_kernel_dispatch_ms",
                "Host-monotonic jit dispatch latency by dispatch "
                "class, milliseconds.")
        h.observe(ms)
        if rounds <= 0:
            return
        t = time.monotonic() if now is None else now
        if self._ewma_last_t is not None:
            dt = t - self._ewma_last_t
            if dt > 0:
                inst = rounds / dt
                if self.rounds_per_sec_ewma:
                    self.rounds_per_sec_ewma += _EWMA_ALPHA * (
                        inst - self.rounds_per_sec_ewma)
                else:
                    self.rounds_per_sec_ewma = inst
        self._ewma_last_t = t

    def note_drain(self, ms: float) -> None:
        """A flight/hist drain's host transfer completed (rides the
        ``drain`` dispatch class; no EWMA contribution)."""
        self.note_dispatch("drain", ms, 0)

    # -- compile / session bookkeeping (cold path) ------------------------

    def set_session(self, slots: int, n: int, steps_per_dispatch: int,
                    ndev: int = 1, dissem: str = "swar") -> None:
        self._slots = int(slots)
        self._n = int(n)
        self._steps_per_dispatch = max(1, int(steps_per_dispatch))
        self._ndev = max(1, int(ndev))
        self._dissem = str(dissem)

    def note_compile(self, name: str, wall_s: float,
                     cache_hit: Optional[bool] = None) -> None:
        """A callable finished its warmup compile in ``wall_s`` seconds;
        ``cache_hit`` is the persistent-cache verdict (None = the cache
        dir could not be probed — neither counter moves)."""
        self.compile_wall_s[name] = round(float(wall_s), 3)
        if cache_hit is True:
            self.cache_hits += 1
        elif cache_hit is False:
            self.cache_misses += 1

    def note_cost(self, name: str, cost: Any,
                  steps: Optional[int] = None) -> None:
        """Record a lowered/compiled ``cost_analysis()`` estimate for
        one dispatch of ``steps`` rounds.  jax returns a dict (Lowered)
        or a one-element list of dicts (Compiled) with ``"flops"`` and
        ``"bytes accessed"`` keys — both shapes accepted; anything else
        is ignored (cost analysis is best-effort across backends)."""
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not isinstance(cost, dict):
            return
        flops = cost.get("flops")
        nbytes = cost.get("bytes accessed", cost.get("bytes_accessed"))
        row: Dict[str, float] = {}
        if flops is not None:
            row["flops"] = float(flops)
        if nbytes is not None:
            row["bytes_accessed"] = float(nbytes)
        if not row:
            return
        if steps:
            row["steps"] = float(steps)
        self.cost[name] = row

    # -- device sampling (flight-drain cadence) ---------------------------

    def sample_devices(self) -> None:
        """Refresh the cached per-device rows (called by the plane on
        the flight-drain cadence and before serving a device query)."""
        self._device_rows = device_rows()
        self._device_sampled_at = time.time()

    # -- derived roofline -------------------------------------------------

    def bytes_per_round(self) -> Tuple[Optional[float], str]:
        """(bytes one round moves, provenance): the lowered
        cost_analysis estimate when one landed (normalized per round),
        else the §1c dense analytic from the session geometry."""
        for row in self.cost.values():
            b = row.get("bytes_accessed")
            if b:
                steps = row.get("steps") or self._steps_per_dispatch
                return b / max(1.0, steps), "cost_analysis"
        if self._slots and self._n:
            return dense_bytes_per_round(self._slots, self._n,
                                         self._dissem), "dense"
        return None, "unknown"

    def roofline(self) -> Dict[str, Any]:
        bpr, source = self.bytes_per_round()
        util = roofline_utilization(bpr or 0.0, self.rounds_per_sec_ewma)
        return {
            "bytes_per_round": None if bpr is None else round(bpr, 1),
            "bytes_source": source,
            "rounds_per_sec_ewma": round(self.rounds_per_sec_ewma, 2),
            "ceiling_gbps": EFFECTIVE_HBM_GBPS,
            "utilization": None if util is None else round(util, 6),
        }

    # -- read side --------------------------------------------------------

    def wire(self) -> Dict[str, Any]:
        """JSON twin payload (/v1/agent/device body, minus the agent's
        build row)."""
        if not self._device_rows:
            self.sample_devices()
        return {
            "dispatch": {cls: h.wire()
                         for cls, h in self.dispatch.items()},
            "rounds_per_sec_ewma": round(self.rounds_per_sec_ewma, 2),
            "compile": {
                "wall_s": dict(self.compile_wall_s),
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "cost": {k: dict(v) for k, v in self.cost.items()},
            },
            "roofline": self.roofline(),
            "devices": list(self._device_rows),
            "devices_sampled_at": self._device_sampled_at,
        }

    def prom_families(self) -> Tuple[List[Dict[str, Any]],
                                     List[Dict[str, Any]],
                                     List[Dict[str, Any]]]:
        """(histograms, labeled_gauges, labeled_counters) for the
        scrape.  Dispatch ladders are always emitted (zero-count
        included) so dashboards see the full schema before traffic;
        HBM gauges appear only on backends that report memory_stats."""
        hists = []
        disp_rows = []
        for cls in sorted(self.dispatch):
            fam = self.dispatch[cls].family()
            fam["labels"] = {"class": cls}
            hists.append(fam)
            disp_rows.append(({"class": cls},
                              float(self.dispatch[cls].count)))
        gauges: List[Dict[str, Any]] = [{
            "name": "consul_kernel_rounds_per_sec",
            "help": "Kernel rounds per second, EWMA over dispatches.",
            "rows": [({}, round(self.rounds_per_sec_ewma, 2))],
        }]
        if self.compile_wall_s:
            gauges.append({
                "name": "consul_kernel_compile_wall_seconds",
                "help": "Warmup compile wall time per callable, "
                        "seconds.",
                "rows": [({"callable": k}, v) for k, v in
                         sorted(self.compile_wall_s.items())]})
        flop_rows = [({"callable": k}, v["flops"])
                     for k, v in sorted(self.cost.items())
                     if "flops" in v]
        byte_rows = [({"callable": k}, v["bytes_accessed"])
                     for k, v in sorted(self.cost.items())
                     if "bytes_accessed" in v]
        if flop_rows:
            gauges.append({
                "name": "consul_kernel_cost_flops",
                "help": "Lowered cost_analysis FLOPs estimate per "
                        "dispatch, by callable.",
                "rows": flop_rows})
        if byte_rows:
            gauges.append({
                "name": "consul_kernel_cost_bytes_accessed",
                "help": "Lowered cost_analysis bytes-accessed estimate "
                        "per dispatch, by callable.",
                "rows": byte_rows})
        util = self.roofline()["utilization"]
        if util is not None:
            gauges.append({
                "name": "consul_kernel_roofline_utilization",
                "help": "Achieved HBM traffic over the effective "
                        "bandwidth ceiling (BENCH_NOTES §1c), 0..1.",
                "rows": [({}, util)]})
        hbm_use, hbm_lim, buf_cnt, buf_bytes = [], [], [], []
        for row in self._device_rows:
            labels = {"device": str(row["id"])}
            if "hbm_bytes_in_use" in row:
                hbm_use.append((labels, float(row["hbm_bytes_in_use"])))
            if "hbm_bytes_limit" in row:
                hbm_lim.append((labels, float(row["hbm_bytes_limit"])))
            buf_cnt.append((labels, float(row["live_buffers"])))
            buf_bytes.append((labels, float(row["live_buffer_bytes"])))
        if hbm_use:
            gauges.append({
                "name": "consul_device_hbm_bytes_in_use",
                "help": "Device memory in use (Device.memory_stats), "
                        "bytes.",
                "rows": hbm_use})
        if hbm_lim:
            gauges.append({
                "name": "consul_device_hbm_bytes_limit",
                "help": "Device memory limit (Device.memory_stats), "
                        "bytes.",
                "rows": hbm_lim})
        if buf_cnt:
            gauges.append({
                "name": "consul_device_live_buffers",
                "help": "Live jax arrays resident on the device.",
                "rows": buf_cnt})
            gauges.append({
                "name": "consul_device_live_buffer_bytes",
                "help": "Bytes of live jax arrays resident on the "
                        "device.",
                "rows": buf_bytes})
        counters: List[Dict[str, Any]] = [
            {"name": "consul_kernel_dispatches_total",
             "help": "Jit dispatches by dispatch class.",
             "rows": disp_rows},
            {"name": "consul_kernel_compile_cache_hits_total",
             "help": "Warmup compiles served from the persistent "
                     "compilation cache.",
             "rows": [({}, float(self.cache_hits))]},
            {"name": "consul_kernel_compile_cache_misses_total",
             "help": "Warmup compiles that compiled fresh (and "
                     "persisted new cache entries).",
             "rows": [({}, float(self.cache_misses))]},
        ]
        return hists, gauges, counters


def stats_rows(wire: Dict[str, Any]) -> Dict[str, str]:
    """String-valued rows for /v1/agent/self Stats (the ``consul
    info`` convention), derived from a ``device`` frame payload —
    pure dict math so the agent renders it without a kernel."""
    if not wire or not wire.get("enabled"):
        return {"enabled": "false"} if wire else {}
    disp = wire.get("dispatch") or {}
    comp = wire.get("compile") or {}
    roof = wire.get("roofline") or {}
    step = disp.get("round_step") or disp.get("sharded_round") or {}
    return {
        "enabled": "true",
        "rounds_per_sec_ewma": str(wire.get("rounds_per_sec_ewma", 0)),
        "dispatch_p50_ms": str(step.get("p50_ms")),
        "dispatches": str(sum(int(d.get("count", 0) or 0)
                              for d in disp.values())),
        "compile_cache_hits": str(comp.get("cache_hits", 0)),
        "compile_cache_misses": str(comp.get("cache_misses", 0)),
        "roofline_utilization": str(roof.get("utilization")),
        "devices": str(len(wire.get("devices") or [])),
    }
