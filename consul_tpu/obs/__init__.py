"""Observability: distributed tracing, kernel flight recorder, and
Prometheus exposition.

Three surfaces, one subsystem:

- ``obs.trace``  — request-scoped spans propagated through the msgpack
  RPC envelope (agent -> server -> leader -> raft -> FSM), collected in
  a bounded in-memory ring served at ``/v1/agent/traces``.
- ``obs.flight`` — per-round SWIM kernel counters accumulated inside
  the jit step into an HBM ring and drained by the gossip plane in
  amortized batches; exposed via the metrics registry and
  ``/v1/agent/flight``.
- ``obs.prom``   — text-format rendering of the ``utils.telemetry``
  registry at ``/v1/agent/metrics?format=prometheus``.
"""
