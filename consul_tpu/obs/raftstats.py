"""Consensus-plane observatory: raft/replication + anti-entropy stats.

The gossip kernel has a flight recorder and detection-latency banks
(obs/flight.py, obs/hist.py) and the HTTP edge has reqstats — this
module gives the consensus plane the same treatment.  A ``RaftStats``
instance rides on each ``RaftNode`` (consensus/raft.py) and collects:

* latency histograms — append→quorum-ack, commit→FSM-apply, snapshot
  install, and the leader-lease renewal margin (how much lease window
  was left each time it renewed or served a read);
* per-peer replication state — last-contact send stamp plus
  failed/recovered RPC counters (match-index lag is computed against
  the live node at read time, not stored);
* a bounded leadership/election/lease event timeline ring — the
  consensus-plane black box an incident bundle drains.

``AntiEntropyStats`` (module singleton ``aestats``) does the same for
the agent's catalog sync loop (agent/local.py): sync duration
histogram and per-kind failure counters; the pending-ops gauge is
computed from live ``LocalState`` at scrape time.

Conventions, matching the rest of obs/:

* histogram banks are host-side cumulative counts in plain Python
  ints — the PR 5 HistRecorder convention's int64 banks, which never
  wrap (the device-side wrap dance doesn't apply: there is no 32-bit
  accumulator anywhere in this path);
* everything here runs on the agent's single event loop, so there are
  no locks (same discipline as obs/reqstats.py);
* no jax imports — the agent process renders these without a kernel.

The whole observatory can be compiled out for A/B overhead runs:
``CONSUL_TPU_RAFT_OBS=0`` in the environment makes ``enabled()``
false, RaftNode then carries ``obs = None`` and every hot-path hook is
one attribute-is-None test (BENCH_NOTES.md §10 measures the delta).
"""

from __future__ import annotations

import os
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Tuple

# Millisecond bucket ladder shared by every consensus-plane latency
# histogram.  Cumulative counts over these edges render directly as a
# Prometheus histogram family (obs/prom.py ``histograms=``).
MS_EDGES: Tuple[float, ...] = (0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                               50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0)
TIMELINE_CAP = 256    # leadership/election/lease events retained
_PENDING_CAP = 1024   # in-flight append/commit stamps (leak guard)

# Forward sink for the journey ledger (obs/journey.py): journey owns
# the append→quorum measurement made HERE rather than re-stamping the
# raft path — obs/journey.py sets this at import (it imports us, so
# the reverse import would be a cycle).  None when the ledger is
# compiled out or never imported; note_commit's forward is then one
# None test.
journey_sink: Optional[Any] = None


def enabled() -> bool:
    """Observatory switch: CONSUL_TPU_RAFT_OBS=0 compiles it out (the
    A/B leg of the bench overhead measurement)."""
    return os.environ.get("CONSUL_TPU_RAFT_OBS", "1").lower() not in (
        "0", "false", "no")


def _le(edge: float) -> str:
    return str(int(edge)) if edge == int(edge) else repr(edge)


class LatencyHist:
    """Fixed-edge cumulative millisecond histogram.

    ``observe(ms, n=1)`` is the only write; banks are plain ints so a
    bucket legitimately holding more than 2**32 observations stays
    exact (the wrap-aware HistRecorder contract, minus the device
    drain — tests/test_raft_obs.py holds this to the same bar).
    """

    __slots__ = ("name", "help", "edges", "_counts", "_sum", "_count")

    def __init__(self, name: str, help_text: str,
                 edges: Optional[Tuple[float, ...]] = None) -> None:
        self.name = name
        self.help = help_text
        # Custom edges let non-latency distributions (e.g. apply-batch
        # entry counts, PR 11) reuse the same bank/render machinery.
        self.edges = MS_EDGES if edges is None else tuple(edges)
        self._counts = [0] * len(self.edges)
        self._sum = 0.0
        self._count = 0

    def observe(self, ms: float, n: int = 1) -> None:
        self._count += n
        self._sum += ms * n
        i = bisect_left(self.edges, ms)
        if i < len(self._counts):
            self._counts[i] += n
        # else: overflow — counted only by the +Inf bucket (count)

    @property
    def count(self) -> int:
        return self._count

    def family(self) -> Dict[str, Any]:
        """obs/prom.py ``histograms=`` family shape."""
        cum = 0
        buckets = []
        for edge, c in zip(self.edges, self._counts):
            cum += c
            buckets.append((_le(edge), cum))
        return {"name": self.name, "help": self.help, "buckets": buckets,
                "sum": round(self._sum, 3), "count": self._count}

    def quantile_ms(self, q: float) -> Optional[float]:
        """Upper bucket edge covering quantile ``q`` (None until data;
        observations past the last edge report that edge — an
        operator-facing bound, not an exact percentile)."""
        if self._count == 0:
            return None
        need = q * self._count
        cum = 0
        for edge, c in zip(self.edges, self._counts):
            cum += c
            if cum >= need:
                return edge
        return self.edges[-1]

    def wire(self) -> Dict[str, Any]:
        return {"count": self._count, "sum_ms": round(self._sum, 3),
                "p50_ms": self.quantile_ms(0.50),
                "p99_ms": self.quantile_ms(0.99)}


class RaftStats:
    """Per-RaftNode consensus observatory (module docstring)."""

    def __init__(self, node_id: str = "") -> None:
        self.node_id = node_id
        self.append_quorum = LatencyHist(
            "consul_raft_append_quorum_ms",
            "Leader append flush to quorum commit, milliseconds.")
        self.commit_apply = LatencyHist(
            "consul_raft_commit_apply_ms",
            "Entry commit to local FSM apply, milliseconds.")
        self.snapshot_install = LatencyHist(
            "consul_raft_snapshot_install_ms",
            "Snapshot send (leader) / restore (follower), milliseconds.")
        self.lease_margin = LatencyHist(
            "consul_raft_lease_margin_ms",
            "Leader-lease window remaining at renewal/read, milliseconds.")
        self.elections_started = 0
        self.leadership_gained = 0
        self.leadership_lost = 0
        self.events_total = 0
        self._append_pending: Dict[int, float] = {}      # index -> t_flush
        self._commit_pending: List[Tuple[int, float]] = []  # (idx, t_commit)
        self._peers: Dict[str, Dict[str, Any]] = {}
        self._events: List[Dict[str, Any]] = []
        self._ev_next = 0
        self._lease_was_valid = False

    # -- raft hot-path hooks (every call is O(small)) -----------------------

    def note_append(self, index: int) -> None:
        """A flushed leader batch ending at ``index`` hit the log."""
        if len(self._append_pending) < _PENDING_CAP:
            self._append_pending[index] = time.monotonic()

    def note_commit(self, commit_index: int) -> None:
        """commit_index advanced (leader quorum or follower header)."""
        now = time.monotonic()
        if self._append_pending:
            for idx in [i for i in self._append_pending if i <= commit_index]:
                ms = (now - self._append_pending.pop(idx)) * 1000.0
                self.append_quorum.observe(ms)
                if journey_sink is not None:
                    journey_sink.note_quorum(ms)
        if len(self._commit_pending) < _PENDING_CAP:
            self._commit_pending.append((commit_index, now))

    def note_applied(self, applied_index: int) -> None:
        """The FSM caught up through ``applied_index``."""
        if not self._commit_pending:
            return
        now = time.monotonic()
        keep = []
        for idx, t0 in self._commit_pending:
            if idx <= applied_index:
                self.commit_apply.observe((now - t0) * 1000.0)
            else:
                keep.append((idx, t0))
        self._commit_pending = keep

    def _peer(self, peer: str) -> Dict[str, Any]:
        st = self._peers.get(peer)
        if st is None:
            st = self._peers[peer] = {"last_contact": 0.0, "failed": 0,
                                      "recovered": 0, "in_retry": False}
        return st

    def peer_ok(self, peer: str, sent: float) -> None:
        """Same-term AppendEntries response from ``peer`` for the round
        sent at monotonic ``sent``."""
        st = self._peer(peer)
        if sent > st["last_contact"]:
            st["last_contact"] = sent
        if st["in_retry"]:
            st["in_retry"] = False
            st["recovered"] += 1

    def peer_fail(self, peer: str) -> None:
        """Replication RPC to ``peer`` failed (transport or timeout)."""
        st = self._peer(peer)
        st["failed"] += 1
        st["in_retry"] = True

    def lease_observe(self, remaining_ms: float, term: int) -> None:
        """Sample the lease window at a renewal or lease-path read;
        <= 0 means the lease does not currently hold.  Validity
        transitions land on the timeline."""
        valid = remaining_ms > 0.0
        if valid:
            self.lease_margin.observe(remaining_ms)
        if valid != self._lease_was_valid:
            self._lease_was_valid = valid
            self.event("lease-acquired" if valid else "lease-lost",
                       term=term)

    # -- leadership/election/lease timeline ---------------------------------

    def event(self, kind: str, **detail: Any) -> None:
        ev: Dict[str, Any] = {"t": time.time(), "kind": kind}
        ev.update(detail)
        self.events_total += 1
        if len(self._events) < TIMELINE_CAP:
            self._events.append(ev)
        else:
            self._events[self._ev_next] = ev
            self._ev_next = (self._ev_next + 1) % TIMELINE_CAP

    def timeline(self) -> List[Dict[str, Any]]:
        """Retained events, oldest first."""
        if len(self._events) < TIMELINE_CAP:
            return list(self._events)
        return self._events[self._ev_next:] + self._events[:self._ev_next]

    def note_election(self, term: int) -> None:
        self.elections_started += 1
        self.event("election-start", term=term)

    def note_leader(self, term: int) -> None:
        self.leadership_gained += 1
        self.event("leader-elected", term=term)

    def note_deposed(self, term: int, leader: Optional[str]) -> None:
        self.leadership_lost += 1
        self.event("leader-deposed", term=term, leader=leader or "")
        self.lease_observe(0.0, term)  # the lease is gone with the role

    def note_new_leader(self, term: int, leader: str) -> None:
        self.event("new-leader", term=term, leader=leader)

    # -- read side ----------------------------------------------------------

    def hists(self) -> List[LatencyHist]:
        return [self.append_quorum, self.commit_apply,
                self.snapshot_install, self.lease_margin]

    def peer_rows(self, node: Any) -> List[Dict[str, Any]]:
        """Per-peer replication rows; lag/age computed against the live
        node so the scrape never reads stale gauges."""
        now = time.monotonic()
        last = node.last_log_index()
        rows = []
        for peer in sorted(self._peers):
            st = self._peers[peer]
            lc = st["last_contact"]
            rows.append({
                "peer": peer,
                "match_lag_entries": max(
                    0, last - node.match_index.get(peer, 0)),
                "last_contact_age_ms": (round((now - lc) * 1000.0, 3)
                                        if lc else None),
                "rpc_failed": st["failed"],
                "rpc_recovered": st["recovered"],
            })
        return rows

    def wire(self, node: Any) -> Dict[str, Any]:
        return {
            "histograms": {h.name: h.wire() for h in self.hists()},
            "counters": {
                "elections_started": self.elections_started,
                "leadership_gained": self.leadership_gained,
                "leadership_lost": self.leadership_lost,
                "timeline_events_total": self.events_total,
            },
            "peers": self.peer_rows(node),
            "timeline": self.timeline(),
        }

    def stats_rows(self) -> Dict[str, str]:
        """String-valued rows for raft.stats() — the ``consul info`` /
        ``/v1/agent/self`` convention."""
        return {
            "append_quorum_p50_ms": str(self.append_quorum.quantile_ms(0.5)),
            "commit_apply_p50_ms": str(self.commit_apply.quantile_ms(0.5)),
            "lease_margin_p50_ms": str(self.lease_margin.quantile_ms(0.5)),
            "elections_started": str(self.elections_started),
            "leadership_gained": str(self.leadership_gained),
            "leadership_lost": str(self.leadership_lost),
            "timeline_events": str(self.events_total),
        }


def prom_families(node: Any) -> Tuple[List[Dict[str, Any]],
                                      List[Dict[str, Any]],
                                      List[Dict[str, Any]]]:
    """(histograms, labeled_gauges, labeled_counters) for the scrape,
    from a live RaftNode carrying a RaftStats at ``node.obs``.  The
    histogram families are always emitted (zero-count ladders included)
    so dashboards see the full schema before traffic."""
    obs = getattr(node, "obs", None)
    if obs is None:
        return [], [], []
    hists = [h.family() for h in obs.hists()]
    lag_rows, age_rows, fail_rows, rec_rows = [], [], [], []
    for row in obs.peer_rows(node):
        labels = {"peer": row["peer"]}
        lag_rows.append((labels, float(row["match_lag_entries"])))
        if row["last_contact_age_ms"] is not None:
            age_rows.append((labels, row["last_contact_age_ms"]))
        fail_rows.append((labels, float(row["rpc_failed"])))
        rec_rows.append((labels, float(row["rpc_recovered"])))
    gauges = []
    if lag_rows:
        gauges.append({"name": "consul_raft_peer_match_lag_entries",
                       "help": "Entries the peer's match index trails the "
                               "leader's last log index by.",
                       "rows": lag_rows})
    if age_rows:
        gauges.append({"name": "consul_raft_peer_last_contact_age_ms",
                       "help": "Milliseconds since the peer last "
                               "acknowledged a replication round.",
                       "rows": age_rows})
    counters = []
    if fail_rows:
        counters.append({"name": "consul_raft_peer_rpc_failed_total",
                         "help": "Failed replication RPCs per peer.",
                         "rows": fail_rows})
    if rec_rows:
        counters.append({"name": "consul_raft_peer_rpc_recovered_total",
                         "help": "Replication rounds that succeeded after "
                                 "one or more failures, per peer.",
                         "rows": rec_rows})
    return hists, gauges, counters


def telemetry(node: Any, local: Any = None) -> Dict[str, Any]:
    """JSON payload of /v1/operator/raft/telemetry: raft stats + the
    observatory + anti-entropy state.  ``node`` may be None (client
    mode) and the observatory may be compiled out — the route then
    reports what it can."""
    out: Dict[str, Any] = {"enabled": enabled()}
    if node is not None:
        out["raft"] = node.stats()
        obs = getattr(node, "obs", None)
        if obs is not None:
            out.update(obs.wire(node))
    ae: Dict[str, Any] = aestats.wire()
    if local is not None:
        ae["pending_ops"] = local.pending_ops()
    out["antientropy"] = ae
    return out


class AntiEntropyStats:
    """Catalog anti-entropy observatory (agent/local.py hooks)."""

    _KINDS = ("diff", "service_register", "service_deregister",
              "check_register", "check_deregister")

    def __init__(self) -> None:
        self.sync = LatencyHist(
            "consul_antientropy_sync_ms",
            "Full anti-entropy pass (diff + push) duration, milliseconds.")
        self.syncs_total = 0
        self.failures: Dict[str, int] = {}

    def sync_done(self, ms: float) -> None:
        self.syncs_total += 1
        self.sync.observe(ms)

    def failure(self, kind: str) -> None:
        self.failures[kind] = self.failures.get(kind, 0) + 1

    def families(self) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
        """(histograms, labeled_counters) for the scrape; every failure
        kind is emitted (zeros included) so the family schema is stable."""
        rows = [({"kind": k}, float(self.failures.get(k, 0)))
                for k in self._KINDS]
        return [self.sync.family()], [{
            "name": "consul_antientropy_failures_total",
            "help": "Anti-entropy sync failures by operation kind.",
            "rows": rows,
        }]

    def wire(self) -> Dict[str, Any]:
        return {"sync": self.sync.wire(), "syncs_total": self.syncs_total,
                "failures": {k: self.failures.get(k, 0)
                             for k in self._KINDS}}


# Process-global anti-entropy stats, mirroring obs.reqstats.reqstats
# (one agent per process; call sites go through the module attribute so
# tests can swap it).
aestats = AntiEntropyStats()
