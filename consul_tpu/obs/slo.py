"""Rolling SLO burn-rate tracking over the detection-latency bank.

The paper's headline acceptance gate — p99 failure-detection time
within the Lifeguard bound — is an OFFLINE crossval check
(gossip/crossval.py).  This module turns it into a live SLO: the plane
feeds every drained ``detect``-bank delta (obs/hist.py) into a
``SloTracker`` configured with an objective in rounds (default: the
params' worst-case Lifeguard suspicion window), and the tracker keeps

- cumulative attainment: fraction of ALL detections at or under the
  objective,
- windowed attainment over the last ``window`` non-empty drains,
- the burn rate: ``(1 - windowed attainment) / (1 - target)`` — the
  standard error-budget burn multiple (1.0 = burning exactly the
  budget; > 1 = on track to violate the SLO).

Served as ``/v1/agent/slo`` through the plane bridge; no jax imports
here (the agent process renders it without a kernel context).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional, Sequence

DEFAULT_WINDOW_DRAINS = 32


class SloTracker:
    """Attainment/burn-rate over per-drain detection-latency deltas.

    ``objective_rounds``: detections at <= this latency (in rounds) are
    within SLO.  ``attainment_target``: the objective's target fraction
    (0.99 = "99% of detections within the bound").
    """

    def __init__(self, objective_rounds: int,
                 attainment_target: float = 0.99,
                 window: int = DEFAULT_WINDOW_DRAINS) -> None:
        if objective_rounds < 0:
            raise ValueError("objective_rounds must be >= 0")
        if not 0.0 < attainment_target < 1.0:
            raise ValueError("attainment_target must be in (0, 1)")
        self.objective_rounds = int(objective_rounds)
        self.attainment_target = float(attainment_target)
        self._lock = threading.Lock()
        # (n_total, n_within) per non-empty drain, newest last.
        self._window: "deque[tuple]" = deque(maxlen=max(1, int(window)))
        self._total = 0
        self._within = 0

    def observe(self, detect_delta: Sequence[int]) -> int:
        """Fold one drained delta of the detect bank (per-bucket new
        observation counts; bucket i = latency i rounds).  Returns the
        number of new detections consumed."""
        counts = [int(c) for c in detect_delta]
        n = sum(counts)
        if n <= 0:
            return 0
        cut = min(self.objective_rounds + 1, len(counts))
        within = sum(counts[:cut])
        with self._lock:
            self._total += n
            self._within += within
            self._window.append((n, within))
        return n

    # -- read side ----------------------------------------------------------

    def _attainment(self, total: int, within: int) -> Optional[float]:
        return None if total == 0 else within / total

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            total, within = self._total, self._within
            wt = sum(n for n, _ in self._window)
            ww = sum(w for _, w in self._window)
        att = self._attainment(total, within)
        watt = self._attainment(wt, ww)
        burn = 0.0
        if watt is not None:
            burn = (1.0 - watt) / (1.0 - self.attainment_target)
        return {
            "objective_rounds": self.objective_rounds,
            "attainment_target": self.attainment_target,
            "detections": total,
            "attainment": att,
            "window_detections": wt,
            "window_attainment": watt,
            "burn_rate": burn,
        }


class SloBoard:
    """Per-scenario SLO trackers sharing one objective.

    The nemesis observatory (gossip/nemesis.py) attributes every
    drained detection delta to the scenario active when it was
    observed; the board keeps an independent ``SloTracker`` per label
    so each failure mode gets its own attainment + burn-rate readout
    (``/v1/agent/slo`` ``scenarios`` key).  Trackers are created
    lazily on first observation — a scenario that never detected
    anything is absent, not a zero row."""

    def __init__(self, objective_rounds: int,
                 attainment_target: float = 0.99,
                 window: int = DEFAULT_WINDOW_DRAINS) -> None:
        self._objective = int(objective_rounds)
        self._target = float(attainment_target)
        self._window = int(window)
        self._lock = threading.Lock()
        self._trackers: Dict[str, SloTracker] = {}

    def observe(self, scenario: str, detect_delta: Sequence[int]) -> int:
        if not scenario:
            return 0
        with self._lock:
            tr = self._trackers.get(scenario)
            if tr is None:
                tr = self._trackers[scenario] = SloTracker(
                    self._objective, self._target, self._window)
        return tr.observe(detect_delta)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            trackers = dict(self._trackers)
        return {scn: tr.snapshot() for scn, tr in sorted(trackers.items())}
