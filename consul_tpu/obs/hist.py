"""Detection-latency observatory — host side of the kernel histograms.

The jitted gossip kernel (gossip/kernel.py, ``HistBank``) accumulates
fixed-bucket integer histograms in HBM INSIDE the scan body — no host
transfer per round:

- ``detect``  — detection latency in rounds (``fail_round`` -> the dead
  verdict firing), one-round-wide buckets,
- ``dwell``   — suspicion dwell time (episode start -> verdict, dead OR
  refuted),
- ``refute``  — refutation latency (episode start -> refute applied),
- ``spread``  — dissemination spread per rumor: members holding the
  episode's verdict at slot GC, log2-bucketed.

The banks are CUMULATIVE counters (never reset on device); the plane
drains them on its flight cadence and hands them to ``HistRecorder``
here, which keeps the true cumulative view for Prometheus histogram
exposition (obs/prom.py ``histograms=``) and returns per-drain deltas
for the SLO burn-rate tracker (obs/slo.py).

The device banks are **int32 and wrap** (JAX x64 stays off — see the
SwimState wrap convention in gossip/kernel.py): at paper scale a hot
bucket passes 2**31 well inside a long run.  The drain is therefore
wrap-aware: deltas are computed modulo 2**32 on the raw 32-bit view
(exact as long as one drain interval adds < 2**31 per bucket — hours
of observations vs a sub-second drain cadence), and the recorder
accumulates them into host-side int64 banks, which never wrap.  All
read paths (percentiles, families, summary) use the int64 view, so a
device wrap is invisible downstream.

Bucket layouts (keep gossip/kernel.py in lockstep):

- latency banks (``LATENCY_BUCKETS`` wide): bucket ``i`` holds
  observations of exactly ``i`` rounds for ``i < LATENCY_BUCKETS - 1``;
  the top bucket is the overflow (``>= LATENCY_BUCKETS - 1``).  One
  round per bucket means the bank reconstructs the exact multiset below
  the overflow — ``percentile()`` is bit-for-bit the crossval oracle's
  ``pct`` on the same observations.
- spread bank (``SPREAD_BUCKETS`` wide): bucket ``k`` holds rumors whose
  holder count has bit_length ``k`` (``0``, then ``[2^(k-1), 2^k-1]``)
  — integer shift-and-count on device, no float ops, so the sharded and
  unsharded banks stay bit-identical.

This module deliberately does NOT import jax: the agent process renders
``/v1/agent/slo`` and the Prometheus histograms from bridge frames
without a kernel context.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

LATENCY_BUCKETS = 256
SPREAD_BUCKETS = 32

# Bank name -> (metric name, help text).  Order = exposition order.
BANK_METRICS = {
    "detect": ("consul.swim.detection_latency_rounds",
               "Rounds from a node's failure to its dead verdict firing."),
    "dwell": ("consul.swim.suspicion_dwell_rounds",
              "Rounds a suspicion episode stayed open before its verdict "
              "(dead or refuted)."),
    "refute": ("consul.swim.refutation_latency_rounds",
               "Rounds from episode start to the subject's refutation."),
    "spread": ("consul.swim.spread_members",
               "Members holding an episode's verdict when its slot was "
               "recycled (log2 buckets)."),
}
_LATENCY_BANKS = ("detect", "dwell", "refute")

# Exposed `le` edges: powers of two for the one-round latency banks
# (the fine 256-bucket bank collapses exactly onto them), bit_length
# boundaries for the spread bank.  Each edge maps to the last fine
# bucket it covers (le >= means cum = counts[:idx+1].sum()).
_LATENCY_EDGES = [1, 2, 4, 8, 16, 32, 64, 128]
_SPREAD_EDGES = [(str(2 ** k - 1), k) for k in range(1, SPREAD_BUCKETS)]


def _edges(name: str) -> List[tuple]:
    if name == "spread":
        return [("0", 0)] + _SPREAD_EDGES
    return [(str(e), e) for e in _LATENCY_EDGES]


class HistRecorder:
    """Host-side sink for drained histogram banks.

    ``ingest(banks)`` takes a dict of bank name -> cumulative bucket
    counts (any array-like of ints, straight off the device), computes
    the per-drain deltas modulo 2**32 (the device banks are int32 and
    wrap — module docstring), folds them into a host-side int64
    cumulative view that never wraps, and returns the deltas (new
    observations since the previous drain) for the SLO tracker.

    A shape change (bank layout reconfigured) resets that bank's
    history; a recorder must otherwise live exactly as long as the
    device banks it drains (the plane creates both together).
    """

    _WRAP = np.int64(2) ** 32

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._banks: Dict[str, np.ndarray] = {}   # true cumulative, i64
        self._raw: Dict[str, np.ndarray] = {}     # last device view, u32

    # -- drain path ---------------------------------------------------------

    def ingest(self, banks: Dict[str, Sequence[int]],
               scenario: Optional[str] = None) -> Dict[str, np.ndarray]:
        """``scenario``: attribute this drain's deltas to a nemesis
        scenario (gossip/nemesis.py) as well — the deltas additionally
        fold into ``"<name>@<scenario>"`` banks, which ``families()``
        exposes as scenario-labeled Prometheus series and the SLO board
        reads per scenario.  The wrap bookkeeping (``_raw``) stays
        keyed by the bare bank name: there is ONE physical device bank
        regardless of which scenario is active when it drains."""
        deltas: Dict[str, np.ndarray] = {}
        with self._lock:
            for name, counts in banks.items():
                # reduce the device view to its 32 low bits so int32
                # (possibly negative after a wrap) and uint32 inputs
                # difference identically
                cur = np.asarray(counts, dtype=np.int64) & (self._WRAP - 1)
                prev = self._raw.get(name)
                if prev is None or prev.shape != cur.shape:
                    prev = np.zeros_like(cur)
                    self._banks[name] = np.zeros_like(cur)
                delta = (cur - prev) % self._WRAP
                deltas[name] = delta
                self._raw[name] = cur
                self._banks[name] = self._banks[name] + delta
                if scenario:
                    key = f"{name}@{scenario}"
                    bank = self._banks.get(key)
                    if bank is None or bank.shape != delta.shape:
                        bank = np.zeros_like(delta)
                    self._banks[key] = bank + delta
        return deltas

    # -- read side ----------------------------------------------------------

    def counts(self, name: str) -> np.ndarray:
        with self._lock:
            bank = self._banks.get(name)
            return (np.array([], dtype=np.int64) if bank is None
                    else bank.copy())

    def percentile(self, name: str, q: float) -> Optional[float]:
        """Exact percentile over the recorded multiset (one-round-wide
        buckets; overflow-bucket observations count at the bucket floor).
        Linear interpolation — identical to crossval's ``pct``, computed
        from cumulative counts without materializing the multiset (the
        wrap-aware banks legitimately exceed 2**31 observations)."""
        counts = self.counts(name)
        total = int(counts.sum())
        if total == 0:
            return None
        cum = np.cumsum(counts)
        # np.percentile 'linear': rank q/100*(n-1) = k + f; the value at
        # sorted index i is the first bucket whose cumulative count
        # exceeds i
        rank = (q / 100.0) * (total - 1)
        lo_i = int(np.floor(rank))
        hi_i = int(np.ceil(rank))
        lo = int(np.searchsorted(cum, lo_i, side="right"))
        hi = int(np.searchsorted(cum, hi_i, side="right"))
        return float(lo + (hi - lo) * (rank - lo_i))

    def scenarios(self) -> List[str]:
        """Sorted nemesis scenario labels with attributed banks."""
        with self._lock:
            return sorted({k.split("@", 1)[1] for k in self._banks
                           if "@" in k})

    @staticmethod
    def _one_family(name: str, metric: str, help_text: str,
                    counts: np.ndarray,
                    labels: Optional[Dict[str, str]]) -> Dict[str, Any]:
        cum = np.cumsum(counts)
        buckets = [(le, int(cum[min(idx, len(cum) - 1)]))
                   for le, idx in _edges(name)]
        if name == "spread":
            # bit_length buckets: value floor of bucket k is 2^(k-1)
            floors = np.concatenate(
                [[0], 2 ** np.arange(counts.shape[0] - 1)])
            total_sum = int((counts * floors).sum())
        else:
            total_sum = int((counts * np.arange(counts.shape[0])).sum())
        fam: Dict[str, Any] = {
            "name": metric,
            "help": help_text,
            "buckets": buckets,
            "sum": total_sum,
            "count": int(counts.sum()),
        }
        if labels:
            fam["labels"] = dict(labels)
        return fam

    def families(self) -> List[Dict[str, Any]]:
        """Prometheus histogram families over the cumulative banks.

        ``sum`` is exact below the overflow bucket; overflow
        observations contribute the bucket floor (a lower bound).

        Scenario-attributed banks (``ingest(..., scenario=...)``) emit
        additional families with the SAME metric name and a
        ``{"scenario": ...}`` label set, right after their unlabeled
        aggregate (obs/prom.py emits HELP/TYPE once per name)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            banks = {n: b.copy() for n, b in self._banks.items()}
        scns = sorted({k.split("@", 1)[1] for k in banks if "@" in k})
        for name, (metric, help_text) in BANK_METRICS.items():
            counts = banks.get(name)
            if counts is None:
                continue
            out.append(self._one_family(name, metric, help_text, counts,
                                        None))
            for scn in scns:
                sc_counts = banks.get(f"{name}@{scn}")
                if sc_counts is not None:
                    out.append(self._one_family(
                        name, metric, help_text, sc_counts,
                        {"scenario": scn}))
        return out

    def summary(self, scenario: Optional[str] = None) -> Dict[str, Any]:
        """Latency percentiles for /v1/agent/slo (None until data).
        ``scenario``: read the scenario-attributed banks instead of the
        aggregate."""
        suffix = f"@{scenario}" if scenario else ""
        s: Dict[str, Any] = {}
        for name in _LATENCY_BANKS:
            key = name + suffix
            s[name] = {
                "count": int(self.counts(key).sum()),
                "p50_rounds": self.percentile(key, 50),
                "p99_rounds": self.percentile(key, 99),
            }
        return s
