"""Governing registry of every ``CONSUL_TPU_*`` environment gate.

One place to answer "what knobs does this process read from the
environment?" — the table-drift vet pass (tools/vet/table_drift.py,
``check_env_gates``) holds the rest of the tree to it:

- every ``CONSUL_TPU_*`` string literal anywhere in the tree must be a
  registered gate (a typo'd gate name reads as "unset" forever and no
  runtime check ever notices);
- each gate's canonical reader module must still reference it (a gate
  whose reader moved or died is dead configuration);
- the README's environment-gate table must document exactly this set.

Keep descriptions to one line; the authoritative semantics live at the
reader, named in each description.
"""

from typing import Dict

ENV_GATES: Dict[str, str] = {
    "CONSUL_TPU_DEV_OBS":
        "=0 compiles out the device/kernel observatory (obs/devstats.py)",
    "CONSUL_TPU_RAFT_OBS":
        "=0 compiles out the consensus observatory (obs/raftstats.py)",
    "CONSUL_TPU_JOURNEY":
        "=0 compiles out the transition-journey ledger (obs/journey.py)",
    "CONSUL_TPU_JOURNEY_BUDGET_MS":
        "journey wake-budget threshold in ms, default 250 (obs/journey.py)",
    "CONSUL_TPU_AUTOTUNE":
        "=0 ignores persisted autotune verdicts at boot (obs/tuner.py)",
    "CONSUL_TPU_AUTOTUNE_DIR":
        "overrides where autotune artifacts are read/written (obs/tuner.py)",
    "CONSUL_TPU_COMPILE_CACHE":
        "overrides the persistent jax compile-cache dir (gossip/plane.py)",
    "CONSUL_TPU_DYN_REPORT":
        "path the vet-dyn pytest plugin writes its leak report to "
        "(tools/vet/dyn.py)",
    "CONSUL_TPU_DYN_NANS":
        "=1 turns on jax debug_nans in the vet-dyn sanitized slice "
        "(tools/vet/dyn.py)",
    "CONSUL_TPU_DYN_INTERLEAVE":
        "=1 installs the forced-interleave Future shim: a task switch "
        "at every await (tools/vet/dyn.py)",
    "CONSUL_TPU_DYN_CANCEL":
        "=1 runs the cancel-injection sweep: cancel a victim task at "
        "each await point (tools/vet/dyn.py)",
}
