"""End-to-end transition journey observatory (the PR-18 fused path).

PR 18 fused the membership→catalog write path and proved a
detection→watcher-visible p99 inside the bench_fuse A/B harness; this
module makes that measurement ALWAYS ON: a causal ledger that stamps
each member transition at every stage of the fused pipeline —

    detect         device detect round → host-visible verdict (the
                   flight ring's dispatch stamp to the plane queueing
                   the member event)
    drain          event queued → evbatch frame flushed (the flight
                   drain cadence wait)
    decode         evbatch flush → membership backend frame decode
    enqueue        backend on_event → reconcile queue put
    submit         reconcile enqueue → BATCH raft submit (queue wait +
                   linger + op build)
    append_quorum  leader append flush → quorum commit (forwarded from
                   the PR-9 RaftStats bank, not re-measured)
    fsm_apply      BATCH envelope decode + sub-apply on the FSM
    render         batch-boundary health-byte cache re-render
    wake           raft submit → first long-poll served fresh data
                   (post watcher re-query — the point an external
                   client measures)

— and folds the stage deltas into per-stage ``LatencyHist`` banks plus
an end-to-end detection→visible histogram, with a bounded ring of
recent per-transition journey records for debugging.

Stamp carriage: the plane folds ``detect``/``drain`` at queue/flush
time and rides ``[t_detect, t_flush, detect_ms]`` on each evbatch
event (``jt`` key, monotonic floats — only comparable in-process,
which is every test/bench harness; the decode hook clamps negative
cross-process deltas to "unknown").  The membership backend attaches
the running record to the ``Node`` object; ``membership_notify`` and
the reconciler carry it to the flush, which arms ONE in-flight batch
(a single reconcile loop per leader — no overlap), the consensus/FSM/
render/wake hooks stamp into the armed batch, and ``close()`` after
the raft ack folds everything — parking the batch for its watcher
wake when the flush coroutine resumes first (read surfaces lag by at
most that one parked batch).  Transitions injected directly into
``membership_notify`` (bench_fuse, chaos, obs_smoke) have no plane
stamps: their journey starts at ``enqueue`` — which is exactly the
harness's own t0, so the journey e2e histogram agrees with the
harness-measured latency (the ±20% acceptance bar).

Conventions, matching obs/raftstats.py:

* compiled out with ``CONSUL_TPU_JOURNEY=0`` — the module singleton
  ``journey`` is then None and every hot-path hook is one
  attribute-is-None test (priced in BENCH_NOTES.md §17 against the
  <2% bar, the PR-9/10 convention);
* banks are plain-int cumulative counts over ``MS_EDGES``; everything
  runs on the agent's single event loop (no locks except inside the
  reused SloTracker);
* no jax imports;
* the ledger is process-global: in-process multi-node harnesses
  (bench_fuse, chaos) fold every node's consensus/FSM stages into one
  ledger, which is what their gates want.

The end-to-end budget gets the same SLO treatment detection latency
has: a ``SloTracker`` whose objective is one drain cadence of
wall-time (``CONSUL_TPU_JOURNEY_BUDGET_MS``, default 250 ms — the
PR-18 "health visible within one drain cadence" target), fed one
bucket-delta per closed batch so ``/v1/operator/journey`` reports
attainment and burn rate.
"""

from __future__ import annotations

import os
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Tuple

from consul_tpu.obs import raftstats as _raftstats
from consul_tpu.obs.raftstats import MS_EDGES, LatencyHist
from consul_tpu.obs.slo import SloTracker

# The governing stage enum — table-drift vetted against the prom label
# enumeration in tools/obs_smoke.py and tests/test_journey.py (journey
# stage union group).  Order is pipeline order; it is also the render
# order of the stage-labeled histogram ladder.
STAGES: Tuple[str, ...] = ("detect", "drain", "decode", "enqueue",
                           "submit", "append_quorum", "fsm_apply",
                           "render", "wake")

RECORDS_CAP = 1024        # per-transition journey records retained
DEFAULT_BUDGET_MS = 250.0  # one drain cadence of wall time (PR-18 bar)


def enabled() -> bool:
    """Ledger switch: CONSUL_TPU_JOURNEY=0 compiles it out (every
    hook then short-circuits on ``journey is None``)."""
    return os.environ.get("CONSUL_TPU_JOURNEY", "1").lower() not in (
        "0", "false", "no")


def budget_ms() -> float:
    try:
        return float(os.environ.get("CONSUL_TPU_JOURNEY_BUDGET_MS",
                                    DEFAULT_BUDGET_MS))
    except ValueError:
        return DEFAULT_BUDGET_MS


class JourneyStats:
    """Process-global journey ledger (module docstring)."""

    def __init__(self, budget: Optional[float] = None) -> None:
        self.budget_ms = budget_ms() if budget is None else float(budget)
        self.stage: Dict[str, LatencyHist] = {
            s: LatencyHist(
                "consul_journey_stage_ms",
                "Per-stage transition latency over the fused "
                "membership->catalog path, milliseconds.")
            for s in STAGES
        }
        self.e2e = LatencyHist(
            "consul_journey_e2e_ms",
            "End-to-end transition latency, detection (or injection) "
            "to watcher-visible, milliseconds.")
        self.transitions_total = 0
        self.wakeless_total = 0   # closed without a watcher-wake stamp
        self.aborted_total = 0    # armed batches discarded (submit fail)
        # SLO on the e2e budget: objective = the largest MS_EDGES
        # bucket fully inside the budget (SloTracker speaks bucket
        # indices — "rounds" — so we translate ms edges to indices).
        self._slo_cut = max(0, bisect_left(
            MS_EDGES, self.budget_ms + 1e-9) - 1)
        self.slo = SloTracker(objective_rounds=self._slo_cut)
        self._slo_delta = [0] * (len(MS_EDGES) + 1)
        # Bounded ring of per-transition records, oldest overwritten.
        self._records: List[Dict[str, Any]] = []
        self._rec_next = 0
        # The single in-flight armed batch (one reconcile loop per
        # leader process): None between flushes.
        self._armed: Optional[Dict[str, Any]] = None
        # A closed batch still waiting for its watcher wake: the flush
        # coroutine resumes from the raft ack BEFORE the woken watcher
        # tasks get scheduled, so close() parks the batch here and the
        # first fresh-data long-poll return (or the next arm, as the
        # wakeless fallback) finalizes it.
        self._pending: Optional[Dict[str, Any]] = None

    # -- pipeline-side folds (plane / backend / server hooks) ---------------

    def stage_observe(self, stage: str, ms: float) -> None:
        """Fold one measured stage delta; negative deltas (cross-process
        monotonic clocks) are dropped, not clamped, so the banks only
        ever hold real in-process measurements."""
        if ms >= 0.0:
            self.stage[stage].observe(ms)

    # -- armed-batch protocol (reconcile flush owns the lifecycle) ----------

    def arm(self, records: List[Dict[str, Any]], t_submit: float) -> None:
        """One reconcile flush is in flight: ``records`` are the
        per-member journey dicts riding the batch (keys ``name``,
        ``t0``, ``t_enq``, ``stages``).  A previous batch still parked
        waiting for its wake is finalized wakeless first — its watchers
        never long-polled."""
        if self._pending is not None:
            self._finalize(self._pending, None)
            self._pending = None
        self._armed = {"records": records, "t_submit": t_submit,
                       "quorum_ms": None, "fsm_apply_ms": None,
                       "render_ms": None, "t_wake": None}

    def note_quorum(self, ms: float) -> None:
        """Forwarded from RaftStats.note_commit (PR-9 append→quorum
        bank) — folds the consensus stage and binds the armed batch's
        first ack."""
        self.stage_observe("append_quorum", ms)
        a = self._armed
        if a is not None and a["quorum_ms"] is None:
            a["quorum_ms"] = ms

    def note_fsm_apply(self, ms: float) -> None:
        """A BATCH envelope finished its sub-applies on an FSM."""
        self.stage_observe("fsm_apply", ms)
        a = self._armed
        if a is not None and a["fsm_apply_ms"] is None:
            a["fsm_apply_ms"] = ms

    def note_render(self, ms: float) -> None:
        """The batch-boundary health-byte cache re-render completed."""
        self.stage_observe("render", ms)
        a = self._armed
        if a is not None and a["render_ms"] is None:
            a["render_ms"] = ms

    def note_wake(self) -> None:
        """A long-poll returned fresh data.  A parked (closed, not yet
        woken) batch finalizes with this stamp; otherwise the first
        wake after arming binds the in-flight batch — both one branch
        on the hot path."""
        if self._pending is not None:
            p = self._pending
            self._pending = None
            self._finalize(p, time.monotonic())
            return
        a = self._armed
        if a is not None and a["t_wake"] is None:
            a["t_wake"] = time.monotonic()

    def abort(self) -> None:
        """The armed batch's raft submit failed — discard it."""
        if self._armed is not None:
            self._armed = None
            self.aborted_total += 1

    def close(self) -> None:
        """The armed batch's raft submit returned.  If a watcher
        already woke mid-flight the batch finalizes now; otherwise it
        parks until the first fresh-data long-poll return (the flush
        coroutine resumes from the raft ack before the woken watcher
        tasks run) or, failing that, the next arm."""
        a = self._armed
        if a is None:
            return
        self._armed = None
        a["t_close"] = time.monotonic()
        if a["t_wake"] is not None:
            self._finalize(a, a["t_wake"])
        else:
            if self._pending is not None:
                self._finalize(self._pending, None)
            self._pending = a

    def _finalize(self, a: Dict[str, Any],
                  t_wake: Optional[float]) -> None:
        """Fold the batch's submit/wake stages and each member's
        end-to-end latency, push ring records, feed the SLO tracker.
        ``t_wake`` None means no watcher ever woke: the close stamp
        bounds e2e and the batch counts as wakeless."""
        t_submit = a["t_submit"]
        wake_ms = ((t_wake - t_submit) * 1000.0
                   if t_wake is not None else None)
        if wake_ms is not None:
            self.stage_observe("wake", wake_ms)
        else:
            self.wakeless_total += 1
        t_end = t_wake if t_wake is not None else a["t_close"]
        delta = self._slo_delta
        for i in range(len(delta)):
            delta[i] = 0
        for rec in a["records"]:
            submit_ms = (t_submit - rec.get("t_enq", rec["t0"])) * 1000.0
            self.stage_observe("submit", submit_ms)
            e2e_ms = max(0.0, (t_end - rec["t0"]) * 1000.0)
            self.e2e.observe(e2e_ms)
            delta[min(bisect_left(MS_EDGES, e2e_ms), len(MS_EDGES))] += 1
            self.transitions_total += 1
            stages = dict(rec.get("stages") or {})
            stages["submit"] = round(submit_ms, 3)
            if a["quorum_ms"] is not None:
                stages["append_quorum"] = round(a["quorum_ms"], 3)
            if a["fsm_apply_ms"] is not None:
                stages["fsm_apply"] = round(a["fsm_apply_ms"], 3)
            if a["render_ms"] is not None:
                stages["render"] = round(a["render_ms"], 3)
            if wake_ms is not None:
                stages["wake"] = round(wake_ms, 3)
            self._record({"name": rec.get("name", ""),
                          "wall": time.time(),
                          "e2e_ms": round(e2e_ms, 3),
                          "stages": stages})
        self.slo.observe(delta)

    def _record(self, row: Dict[str, Any]) -> None:
        if len(self._records) < RECORDS_CAP:
            self._records.append(row)
        else:
            self._records[self._rec_next] = row
            self._rec_next = (self._rec_next + 1) % RECORDS_CAP

    # -- read side ----------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        """Retained per-transition records, oldest first."""
        if len(self._records) < RECORDS_CAP:
            return list(self._records)
        return (self._records[self._rec_next:]
                + self._records[:self._rec_next])

    def e2e_quantile_records(self, q: float) -> Optional[float]:
        """Exact quantile over the retained records' raw e2e values —
        the bench/test comparison path (bucket-edge quantiles can't hit
        a ±20% agreement bar; raw samples can)."""
        vals = sorted(r["e2e_ms"] for r in self._records)
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    def stage_sums(self) -> Dict[str, float]:
        """Per-stage cumulative milliseconds — the chaos detectability
        gate diffs these across the fault window."""
        return {s: round(self.stage[s]._sum, 3) for s in STAGES}

    def families(self) -> Tuple[List[Dict[str, Any]],
                                List[Dict[str, Any]]]:
        """(histograms, labeled_counters) for the scrape.  One
        stage-labeled histogram ladder (every stage's labelset always
        emitted, zeros included, sharing one HELP/TYPE block) plus the
        unlabeled e2e family and the transition counters."""
        hists = []
        for s in STAGES:
            fam = self.stage[s].family()
            fam["labels"] = {"stage": s}
            hists.append(fam)
        hists.append(self.e2e.family())
        counters = [
            {"name": "consul_journey_transitions_total",
             "help": "Member transitions closed through the journey "
                     "ledger, by outcome.",
             "rows": [({"outcome": "visible"}, float(
                          self.transitions_total)),
                      ({"outcome": "aborted"}, float(
                          self.aborted_total))]},
            {"name": "consul_journey_wakeless_total",
             "help": "Journey batches closed without observing a "
                     "watcher-wake signal.",
             "rows": [({}, float(self.wakeless_total))]},
        ]
        return hists, counters

    def wire(self, recent: int = 32) -> Dict[str, Any]:
        """JSON payload of /v1/operator/journey (and the debug-bundle
        journey/telemetry.json member)."""
        return {
            "enabled": True,
            "budget_ms": self.budget_ms,
            "stages": {s: self.stage[s].wire() for s in STAGES},
            "e2e": self.e2e.wire(),
            "e2e_records_p99_ms": self.e2e_quantile_records(0.99),
            "slo": self.slo.snapshot(),
            "transitions_total": self.transitions_total,
            "wakeless_total": self.wakeless_total,
            "aborted_total": self.aborted_total,
            "records": self.records()[-max(0, int(recent)):],
        }

    def reset(self) -> None:
        """Zero every bank/ring (bench legs isolate measurements)."""
        self.__init__(budget=self.budget_ms)
        _install(self)


def disabled_wire() -> Dict[str, Any]:
    """Route/bundle shell when the ledger is compiled out."""
    return {"enabled": False, "budget_ms": budget_ms()}


def _install(j: Optional["JourneyStats"]) -> None:
    """Point the raftstats forward sink at the live ledger (raftstats
    can't import this module — it would be a cycle — so the sink is a
    module attribute over there that we own)."""
    _raftstats.journey_sink = j


# Process-global ledger, mirroring obs.raftstats.aestats: one agent (or
# one in-process test cluster) per process; call sites go through the
# module attribute so tests can swap it.  None when compiled out.
journey: Optional[JourneyStats] = JourneyStats() if enabled() else None
_install(journey)
