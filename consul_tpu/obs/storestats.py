"""Device state-store observatory: apply/match dispatch + table health.

PR 11's device-resident KV table (state/device_store.py) gets the same
treatment the kernel plane got in obs/devstats.py: host-monotonic
dispatch-latency histograms bracketed exactly like ``plane._dispatch()``
(wall time around the jit call including fetching the verdicts, which
forces the device work), plus batch-shape and table-health series.

Families (all behind the existing ``CONSUL_TPU_DEV_OBS`` gate — one
switch for everything device-side):

* ``consul_store_dispatch_ms{class=store_apply|watch_match}`` — jit
  dispatch latency histograms per dispatch class;
* ``consul_store_apply_batch_entries`` — committed entries per apply
  batch (count-edged histogram — the LatencyHist bank machinery with
  entry-count edges instead of the ms ladder);
* ``consul_store_applied_entries_total`` / ``consul_watch_fired_total``
  / ``consul_watch_match_events_total`` — throughput counters;
* ``consul_store_divergence_total`` — host/device lockstep violations
  (the crossval contract says this stays 0);
* ``consul_store_table_full_total`` — probe-window exhaustion
  degradations (host unaffected, device row dropped);
* ``consul_store_occupancy{state=live|tombstone}`` /
  ``consul_store_capacity`` / ``consul_watch_registered`` gauges;
* ``consul_watch_match_backend`` — the bridge auto-gate's live
  decision (1 device matcher, 0 host radix walk), so a scrape shows
  which leg production batches actually take on this backend.

Conventions match the rest of obs/: plain-int banks (no 32-bit wrap
anywhere host-side), no jax imports (gauge reads take pre-fetched ints,
the bridge does the one jit reduction), no locks (single event loop),
and ``enabled()`` compiled-out-to-``None`` hot paths.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from consul_tpu.obs.raftstats import LatencyHist

# Entry-count edges for the apply-batch-size histogram.
BATCH_EDGES: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256,
                                  512, 1024, 2048, 4096)

DISPATCH_CLASSES: Tuple[str, ...] = ("store_apply", "watch_match")


def enabled() -> bool:
    """Rides the device-observatory gate: CONSUL_TPU_DEV_OBS=0 compiles
    the store observatory out with the kernel one."""
    return os.environ.get("CONSUL_TPU_DEV_OBS", "1").lower() not in (
        "0", "false", "no")


class StoreStats:
    """Per-bridge device-store observatory (module docstring)."""

    def __init__(self) -> None:
        self.dispatch: Dict[str, LatencyHist] = {
            cls: LatencyHist(
                "consul_store_dispatch_ms",
                "Host-monotonic jit dispatch latency of the device "
                "state store, by dispatch class, milliseconds.")
            for cls in DISPATCH_CLASSES}
        self.batch_entries = LatencyHist(
            "consul_store_apply_batch_entries",
            "Committed entries per device apply batch.",
            edges=BATCH_EDGES)
        self.applied_entries = 0
        self.fired_watchers = 0
        self.match_events = 0
        self.divergence = 0
        self.watch_registered = 0
        # Watch-matching backend decision (DeviceStoreBridge auto-gate):
        # None until the first batch decides; then True = device
        # matcher, False = host radix walk.
        self.match_backend_device: Optional[bool] = None

    # -- hot-path hooks (one is-not-None test at each call site) ------

    def note_apply(self, ms: float, entries: int) -> None:
        self.dispatch["store_apply"].observe(ms)
        self.batch_entries.observe(float(entries))
        self.applied_entries += entries

    def note_match(self, ms: float, events: int, fired: int) -> None:
        self.dispatch["watch_match"].observe(ms)
        self.match_events += events
        self.fired_watchers += fired

    # -- scrape assembly ----------------------------------------------

    def families(self, occupancy: Optional[Tuple[int, int, int]] = None,
                 capacity: int = 0
                 ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]],
                            List[Dict[str, Any]]]:
        """(histograms, labeled_gauges, labeled_counters) in the
        obs/prom.py family shapes (the devstats.prom_families idiom).
        ``occupancy`` is the bridge's (live, tombstone, degraded)
        pre-fetched at scrape time — no device work in here."""
        hists: List[Dict[str, Any]] = []
        for cls in sorted(self.dispatch):
            fam = self.dispatch[cls].family()
            fam["labels"] = {"class": cls}
            hists.append(fam)
        hists.append(self.batch_entries.family())

        gauges: List[Dict[str, Any]] = [{
            "name": "consul_watch_registered",
            "help": "KV watches currently registered.",
            "rows": [({}, float(self.watch_registered))],
        }]
        if self.match_backend_device is not None:
            gauges.append({
                "name": "consul_watch_match_backend",
                "help": "Watch-matching backend the bridge auto-gate "
                        "selected: 1 = device matcher, 0 = host radix "
                        "walk (BENCH_WATCH.json crossover).",
                "rows": [({}, 1.0 if self.match_backend_device else 0.0)]})
        if capacity:
            gauges.append({
                "name": "consul_store_capacity",
                "help": "Device KV table slot capacity.",
                "rows": [({}, float(capacity))]})
        if occupancy is not None:
            live, tomb, _deg = occupancy
            gauges.append({
                "name": "consul_store_occupancy",
                "help": "Device KV table slots in use, by state.",
                "rows": [({"state": "live"}, float(live)),
                         ({"state": "tombstone"}, float(tomb))]})

        counters: List[Dict[str, Any]] = [
            {"name": "consul_store_applied_entries_total",
             "help": "KV entries applied through the device store.",
             "rows": [({}, float(self.applied_entries))]},
            {"name": "consul_watch_fired_total",
             "help": "Watchers fired by the device matcher.",
             "rows": [({}, float(self.fired_watchers))]},
            {"name": "consul_watch_match_events_total",
             "help": "Mutation events evaluated by the device matcher.",
             "rows": [({}, float(self.match_events))]},
            {"name": "consul_store_divergence_total",
             "help": "Host/device verdict or fired-set divergences "
                     "(lockstep contract: stays 0).",
             "rows": [({}, float(self.divergence))]},
        ]
        if occupancy is not None and occupancy[2]:
            counters.append({
                "name": "consul_store_table_full_total",
                "help": "SETs dropped by the device table on probe-"
                        "window exhaustion (host store unaffected).",
                "rows": [({}, float(occupancy[2]))]})
        return hists, gauges, counters
