"""Observatory-driven autotuning control plane (ROADMAP item 2).

Four PRs of observatories (detection-latency SLO, raft/replication
telemetry, device/kernel devstats, store stats) feed humans; this
module makes them feed the system, generalizing Lifeguard's pattern —
a failure detector that consumes its own local observability to adapt
its timeouts — to every standing chip-decidable knob in the plane.

Three pieces, all deterministic and offline-capable:

1. **Knob registry** (``KNOBS``): every standing knob with its default,
   the evidence it is decided from, and a pure decision rule.  The
   registry is the governing table for the ``autotune-knob`` vet group
   (tools/vet/table_drift.py): every consumer declares the knobs it
   applies in a ``TUNED_FIELDS`` literal, and the union must equal this
   dict's key set — a knob added anywhere without tuner coverage fails
   ``make vet``.

2. **Evidence adapters**: parse the existing artifacts — the bench
   regime cache (``.bench_last_success.json`` + ``BENCH_r*.json``
   last-known-good payloads, incl. the ``_Timeline`` phase records and
   ``roofline_utilization``), ``BENCH_WATCH.json`` (watch-match A/B +
   crossover sweep), ``BENCH_SERVE.json`` (serving-plane worker A/B),
   ``CHAOS.json`` (fault-detectability verdicts), and the live
   device/reqstats JSON twins — into one uniform evidence table where
   every row carries a platform stamp and a freshness stamp.

3. **Decision engine** (``settle``): evidence table + backend
   fingerprint -> a per-platform verdict file persisted next to the
   XLA compile cache.  Same inputs => byte-identical verdict (``make
   tune-check`` insists).  Consumed at plane/server boot via
   ``resolve`` with a strict resolution order — explicit flag >
   persisted verdict > registry default — and re-settled automatically
   when the backend fingerprint (platform x topology x jax version)
   changes.

Staleness is judged against the *evidence epoch* (the newest stamp in
the table), not the wall clock, so settling twice over the same
artifacts cannot disagree across a date boundary.  Platform stamps are
compared by class: ``axon``/``tpu`` are one chip class (the bench
cache convention), and a CPU smoke measurement never decides a chip
knob (or vice versa).

Observability of the tuner itself: ``/v1/operator/autotune`` JSON (the
agent merges its own resolution with the plane's ``autotune`` bridge
frame), ``consul_autotune_*`` Prometheus families (``prom_families``),
and the ``autotune/verdict.json`` debug-bundle member.

Kill switch: ``CONSUL_TPU_AUTOTUNE=0`` ignores persisted verdicts
everywhere (flags and defaults still resolve).  ``CONSUL_TPU_AUTOTUNE_DIR``
overrides the verdict directory (tests point it at a temp dir so a
developer's ``make tune`` verdict never leaks into a unit boot).
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

VERDICT_FORMAT = 1

# Evidence older than this relative to the newest row in the SAME table
# is rejected — a stale measurement must not outvote a fresh one taken
# after a kernel rewrite.  Judged against the evidence epoch, never the
# wall clock (determinism).
MAX_EVIDENCE_AGE_S = 90 * 24 * 3600.0

# Platform classes: the bench cache treats axon/tpu/untagged as one
# chip class (bench.py _same_platform_class); "" stamps are neutral
# (host-side measurements like the serving A/B or chaos detectability).
_CHIP_PLATFORMS = ("axon", "tpu")

# Valid dissemination strategies a verdict may carry (mirrors the
# governing membership in gossip/params.py __post_init__; the vet
# dissem group's K02 pass pins stray literals).
DISSEM_CHOICES = ("swar", "planes", "prefused", "fused")

# Hardcoded CPU floor for the device watch matcher, duplicated from
# state/device_store.WATCH_DEVICE_MIN_CPU (importing it would pull jax
# into every resolve).  Used only when no measured sweep artifact
# exists; the bridge passes its own constant as the fallback anyway.
DEFAULT_WATCH_DEVICE_MIN = 1 << 16

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# -- evidence ----------------------------------------------------------------


@dataclass(frozen=True)
class Evidence:
    """One measured fact: a flat key, a JSON-able value, the artifact
    it came from, and the platform/freshness stamps admission is
    judged on."""

    key: str
    value: Any
    source: str            # artifact basename or adapter name
    platform: str = ""     # "" = platform-neutral (host-side)
    stamp_unix: float = 0.0


def _same_platform_class(a: str, b: str) -> bool:
    return a == b or (a in _CHIP_PLATFORMS and b in _CHIP_PLATFORMS)


class EvidenceTable:
    """Admission-filtered evidence for one fingerprint: foreign-platform
    rows and stale rows are rejected (and counted), duplicates resolve
    newest-stamp-wins, lookups are deterministic."""

    def __init__(self, rows: Sequence[Evidence], platform: str) -> None:
        self.platform = platform
        rows = sorted(rows, key=lambda r: (r.key, r.source, r.stamp_unix))
        self.epoch = max((r.stamp_unix for r in rows), default=0.0)
        self.rejected: List[Tuple[Evidence, str]] = []
        admissible: Dict[str, Evidence] = {}
        for r in rows:
            if r.platform and not _same_platform_class(r.platform, platform):
                self.rejected.append((r, "foreign-platform"))
                continue
            if r.stamp_unix < self.epoch - MAX_EVIDENCE_AGE_S:
                self.rejected.append((r, "stale"))
                continue
            prev = admissible.get(r.key)
            if prev is None or r.stamp_unix >= prev.stamp_unix:
                admissible[r.key] = r
        self.rows: Dict[str, Evidence] = admissible

    def get(self, key: str) -> Optional[Evidence]:
        return self.rows.get(key)

    def value(self, key: str, default: Any = None) -> Any:
        r = self.rows.get(key)
        return default if r is None else r.value

    def match(self, prefix: str) -> List[Evidence]:
        return [self.rows[k] for k in sorted(self.rows)
                if k.startswith(prefix)]


# -- evidence adapters -------------------------------------------------------

# bench.py metric-name shape (bench _METRIC_RE, kept in lockstep there):
# swim_{gossip|multidc}_rounds_per_sec_{n}_nodes[_churn{p}ppm][_{d}dc]
# [_hot{h}][_planes|_prefused|_fused][_flight][_shard{d}][_nem_{scn}]
_BENCH_RE = re.compile(
    r"^swim_(gossip|multidc)_rounds_per_sec_(\d+)_nodes"
    r"(?:_churn(\d+)ppm)?(?:_(\d+)dc)?(?:_hot(\d+))?"
    r"(_planes|_prefused|_fused)?(_flight)?"
    r"(?:_shard(\d+))?(?:_nem_([a-z0-9_]+))?$")


def parse_bench_metric(name: str) -> Optional[Dict[str, Any]]:
    """Bench metric name -> regime properties (None = not a bench
    rounds/s metric)."""
    name = name.rpartition(":")[2]  # strip a non-chip platform prefix
    m = _BENCH_RE.match(name)
    if m is None:
        return None
    return {
        "variant": m.group(1),
        "n": int(m.group(2)),
        "churn_ppm": int(m.group(3)) if m.group(3) is not None else 1000,
        "strategy": (m.group(6).lstrip("_") if m.group(6) is not None
                     else "swar"),
        "hot": int(m.group(5)) if m.group(5) is not None else 0,
        "flight": m.group(7) is not None,
        "shard": int(m.group(8)) if m.group(8) is not None else 0,
        "nemesis": m.group(9) or "",
    }


def _bench_rows(metric: str, entry: Dict[str, Any],
                source: str) -> List[Evidence]:
    """One bench result dict -> evidence rows (rounds/s + compile +
    roofline + per-phase _Timeline totals)."""
    plat = str(entry.get("platform", "") or "")
    stamp = float(entry.get("measured_unix", 0) or 0)
    tail = metric.rpartition(":")[2]
    rows = [Evidence(f"bench.rps.{tail}", float(entry.get("value", 0.0)),
                     source, plat, stamp)]
    if entry.get("compile_s") is not None:
        rows.append(Evidence(f"bench.compile_s.{tail}",
                             float(entry["compile_s"]), source, plat, stamp))
    if entry.get("roofline_utilization") is not None:
        rows.append(Evidence(f"bench.roofline.{tail}",
                             float(entry["roofline_utilization"]),
                             source, plat, stamp))
    phases: Dict[str, float] = {}
    for ev in entry.get("phases") or []:
        if isinstance(ev, dict) and "phase" in ev:
            phases[str(ev["phase"])] = (phases.get(str(ev["phase"]), 0.0)
                                        + float(ev.get("dur_s", 0.0)))
    for phase in sorted(phases):
        rows.append(Evidence(f"bench.phase_s.{tail}.{phase}",
                             round(phases[phase], 6), source, plat, stamp))
    return rows


def adapt_bench_cache(root: str = REPO_ROOT) -> List[Evidence]:
    """`.bench_last_success.json` (the per-regime last-known-good cache
    bench.py maintains) + the BENCH_r*.json round payloads' embedded
    ``regimes`` / ``regimes_last_known_good`` tables."""
    rows: List[Evidence] = []
    path = os.path.join(root, ".bench_last_success.json")
    cache = _read_json(path)
    if isinstance(cache, dict) and "metric" not in cache:
        for metric in sorted(cache):
            entry = cache[metric]
            if isinstance(entry, dict) and "value" in entry:
                rows += _bench_rows(metric, entry, os.path.basename(path))
    for rpath in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        payload = _read_json(rpath)
        parsed = (payload or {}).get("parsed") or {}
        for tab in ("regimes", "regimes_last_known_good"):
            for _regime, entry in sorted((parsed.get(tab) or {}).items()):
                if isinstance(entry, dict) and entry.get("metric"):
                    rows += _bench_rows(str(entry["metric"]), entry,
                                        os.path.basename(rpath))
    return rows


def adapt_watch(root: str = REPO_ROOT) -> List[Evidence]:
    """BENCH_WATCH.json: per-tier host/device ms-per-batch medians plus
    the ``--sweep`` crossover record (tools/watchstorm.py)."""
    path = os.path.join(root, "BENCH_WATCH.json")
    payload = _read_json(path)
    if not isinstance(payload, dict):
        return []
    src = os.path.basename(path)
    plat = str(payload.get("platform", "") or "")
    stamp = _mtime(path)
    rows: List[Evidence] = []
    for tier in payload.get("tiers") or []:
        w = tier.get("watches")
        if w is None:
            continue
        for k in ("host_ms_per_batch", "device_ms_per_batch"):
            if tier.get(k) is not None:
                rows.append(Evidence(f"watch.{k}.{int(w)}",
                                     float(tier[k]), src, plat, stamp))
    sweep = payload.get("sweep")
    if isinstance(sweep, dict):
        rows.append(Evidence("watch.sweep_max",
                             int(sweep.get("hi", 0) or 0), src, plat, stamp))
        if sweep.get("crossover_watches") is not None:
            rows.append(Evidence("watch.crossover_watches",
                                 int(sweep["crossover_watches"]),
                                 src, plat, stamp))
    return rows


def adapt_serve(root: str = REPO_ROOT) -> List[Evidence]:
    """BENCH_SERVE.json (tools/bench_serve.py): per-worker-count KV
    throughput + tail latency.  Host-side serving — platform-neutral."""
    path = os.path.join(root, "BENCH_SERVE.json")
    payload = _read_json(path)
    if not isinstance(payload, dict):
        return []
    src, stamp = os.path.basename(path), _mtime(path)
    rows: List[Evidence] = []
    for run, ops in sorted((payload.get("runs") or {}).items()):
        m = re.match(r"^workers=(\d+)$", run)
        if m is None or not isinstance(ops, dict):
            continue
        w = int(m.group(1))
        get = ops.get("kv_get") or {}
        if get.get("req_per_sec") is not None:
            rows.append(Evidence(f"serve.kv_get_rps.workers{w}",
                                 float(get["req_per_sec"]), src, "", stamp))
        if get.get("p99_ms") is not None:
            rows.append(Evidence(f"serve.kv_get_p99_ms.workers{w}",
                                 float(get["p99_ms"]), src, "", stamp))
    return rows


def adapt_fuse(root: str = REPO_ROOT) -> List[Evidence]:
    """BENCH_FUSE.json (tools/bench_fuse.py): batched-reconcile A/B —
    raft entries per health transition and detection→watcher-visible
    latency per batch tier vs the sequential per-agent loop.  Host-side
    raft + rendering — platform-neutral."""
    path = os.path.join(root, "BENCH_FUSE.json")
    payload = _read_json(path)
    if not isinstance(payload, dict):
        return []
    src, stamp = os.path.basename(path), _mtime(path)
    rows: List[Evidence] = []
    for run, st in sorted((payload.get("runs") or {}).items()):
        if not isinstance(st, dict):
            continue
        m = re.match(r"^batch=(\d+)$", run)
        tier = f"batch{int(m.group(1))}" if m else (
            "sequential" if run == "sequential" else None)
        if tier is None:
            continue
        for k in ("entries_per_transition", "p50_ms", "p99_ms"):
            if st.get(k) is not None:
                rows.append(Evidence(f"fuse.{k}.{tier}", float(st[k]),
                                     src, "", stamp))
        # Journey stage attribution (obs/journey.py): where a
        # transition's end-to-end time went, per tier — lets the knob
        # rules reason about the dominant stage instead of only the
        # headline p99.  Absent for runs recorded before the ledger
        # (or with CONSUL_TPU_JOURNEY=0).
        jy = st.get("journey")
        if isinstance(jy, dict):
            for k in ("e2e_p50_ms", "e2e_p99_ms"):
                if jy.get(k) is not None:
                    rows.append(Evidence(f"fuse.journey_{k}.{tier}",
                                         float(jy[k]), src, "", stamp))
            for sname, share in sorted(
                    (jy.get("stage_share") or {}).items()):
                rows.append(Evidence(
                    f"fuse.journey_stage_share.{sname}.{tier}",
                    float(share), src, "", stamp))
    return rows


def adapt_chaos(root: str = REPO_ROOT) -> List[Evidence]:
    """CHAOS.json (tools/chaos_campaign.py): per-scenario pass/detected
    verdicts.  The campaign runs on the CPU harness but exercises
    host-side raft timing — platform-neutral."""
    path = os.path.join(root, "CHAOS.json")
    payload = _read_json(path)
    if not isinstance(payload, dict):
        return []
    src, stamp = os.path.basename(path), _mtime(path)
    rows: List[Evidence] = []
    for sc in payload.get("scenarios") or []:
        name = sc.get("scenario")
        if not name:
            continue
        det = sc.get("detection") or {}
        rows.append(Evidence(f"chaos.detected.{name}",
                             bool(det.get("detected")), src, "", stamp))
        rows.append(Evidence(f"chaos.pass.{name}", bool(sc.get("pass")),
                             src, "", stamp))
    if payload.get("passed") is not None:
        rows.append(Evidence("chaos.passed", bool(payload["passed"]),
                             src, "", stamp))
    return rows


def adapt_device_telemetry(payload: Dict[str, Any], platform: str = "",
                           stamp_unix: float = 0.0,
                           source: str = "device_telemetry",
                           ) -> List[Evidence]:
    """The device/kernel observatory JSON twin (/v1/agent/device body
    or a bundle's device/telemetry.json): compile wall census, HBM
    occupancy, rounds/s EWMA, roofline."""
    rows: List[Evidence] = []
    if not isinstance(payload, dict):
        return rows
    compile_ = payload.get("compile") or {}
    for what, wall in sorted((compile_.get("wall_s") or {}).items()):
        rows.append(Evidence(f"device.compile_s.{what}", float(wall),
                             source, platform, stamp_unix))
    if payload.get("rounds_per_sec_ewma") is not None:
        rows.append(Evidence("device.rounds_per_sec_ewma",
                             float(payload["rounds_per_sec_ewma"]),
                             source, platform, stamp_unix))
    roof = payload.get("roofline") or {}
    if isinstance(roof, dict) and roof.get("utilization") is not None:
        rows.append(Evidence("device.roofline_utilization",
                             float(roof["utilization"]),
                             source, platform, stamp_unix))
    for i, dev in enumerate(payload.get("devices") or []):
        if isinstance(dev, dict) and dev.get("bytes_in_use") is not None:
            rows.append(Evidence(f"device.hbm_bytes_in_use.{i}",
                                 float(dev["bytes_in_use"]),
                                 source, platform, stamp_unix))
    return rows


def adapt_reqstats(payload: Dict[str, Any], stamp_unix: float = 0.0,
                   source: str = "reqstats") -> List[Evidence]:
    """A reqstats snapshot ({endpoint: {count, p50_ms, p99_ms, ...}},
    obs/reqstats.py): serving-plane tail latency census."""
    rows: List[Evidence] = []
    if not isinstance(payload, dict):
        return rows
    for endpoint in sorted(payload):
        st = payload[endpoint]
        if not isinstance(st, dict):
            continue
        for k in ("p50_ms", "p99_ms"):
            if st.get(k) is not None:
                rows.append(Evidence(f"req.{k}.{endpoint}", float(st[k]),
                                     source, "", stamp_unix))
    return rows


def gather_evidence(root: str = REPO_ROOT) -> List[Evidence]:
    """Every offline artifact adapter over one repo checkout.  Missing
    artifacts contribute nothing (the rules fall back to defaults)."""
    return (adapt_bench_cache(root) + adapt_watch(root)
            + adapt_serve(root) + adapt_fuse(root) + adapt_chaos(root))


def _read_json(path: str) -> Any:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _mtime(path: str) -> float:
    try:
        return round(os.stat(path).st_mtime, 3)
    except OSError:
        return 0.0


# -- decision rules ----------------------------------------------------------
#
# Each rule is pure: (EvidenceTable, fingerprint) -> (value, [evidence
# keys used], reason) or None when the table holds nothing admissible
# for it (the engine then records the registry default).  Rules compare
# regimes AT THE SAME UNIVERSE SIZE, largest size first — a 640-node
# smoke must not decide against a 16384-node measurement.

_MIN_GAIN = 1.02  # >=2% measured improvement to move off a default


def _rps_by(table: EvidenceTable, want: Callable[[Dict[str, Any]], bool],
            group: Callable[[Dict[str, Any]], Any],
            ) -> Dict[int, Dict[Any, Tuple[float, str]]]:
    """Admissible bench rounds/s rows matching ``want``, bucketed by
    universe size then by ``group(props)`` -> (value, evidence key)."""
    out: Dict[int, Dict[Any, Tuple[float, str]]] = {}
    for r in table.match("bench.rps."):
        props = parse_bench_metric(r.key[len("bench.rps."):])
        if props is None or not want(props):
            continue
        out.setdefault(props["n"], {})[group(props)] = (float(r.value),
                                                        r.key)
    return out


def _lan_baseline(p: Dict[str, Any]) -> bool:
    """The standing-LAN regime family A/B rules compare within: gossip
    variant, default churn, no flight/nemesis riders."""
    return (p["variant"] == "gossip" and p["churn_ppm"] == 1000
            and not p["flight"] and not p["nemesis"])


def _rule_dissem(table: EvidenceTable, fp: Dict[str, Any]):
    by_n = _rps_by(table,
                   lambda p: (_lan_baseline(p) and p["hot"] == 0
                              and p["shard"] == 0),
                   lambda p: p["strategy"])
    for n in sorted(by_n, reverse=True):
        cands = by_n[n]
        if len(cands) < 2:
            continue
        best = max(sorted(cands), key=lambda s: cands[s][0])
        base = cands.get("swar", cands[best])
        if best != "swar" and cands[best][0] < base[0] * _MIN_GAIN:
            best = "swar"   # not a measured win — keep the default
        used = [cands[s][1] for s in sorted(cands)]
        return (best, used,
                f"best rounds/s among {sorted(cands)} at n={n}: "
                f"{cands[best][0]:.1f}")
    return None


def _rule_hot_slots(table: EvidenceTable, fp: Dict[str, Any]):
    by_n = _rps_by(table,
                   lambda p: (p["variant"] == "gossip"
                              and p["churn_ppm"] == 10
                              and p["strategy"] == "swar"
                              and p["shard"] == 0 and not p["flight"]
                              and not p["nemesis"]),
                   lambda p: p["hot"])
    for n in sorted(by_n, reverse=True):
        cands = by_n[n]
        if 0 not in cands or len(cands) < 2:
            continue
        base = cands[0]
        best = max(sorted(cands), key=lambda h: cands[h][0])
        if best != 0 and cands[best][0] < base[0] * _MIN_GAIN:
            best = 0        # within noise of the full-sweep default
        used = [cands[h][1] for h in sorted(cands)]
        return (int(best), used,
                f"hot-slot A/B at n={n}: " + ", ".join(
                    f"hot{h}={cands[h][0]:.1f}" for h in sorted(cands)))
    return None


def _rule_shard_devices(table: EvidenceTable, fp: Dict[str, Any]):
    by_n = _rps_by(table,
                   lambda p: (_lan_baseline(p) and p["hot"] == 0
                              and p["strategy"] == "swar"),
                   lambda p: p["shard"] or 1)
    for n in sorted(by_n, reverse=True):
        cands = by_n[n]
        if len(cands) < 2:
            continue
        best = max(sorted(cands), key=lambda d: cands[d][0])
        if best != 1 and cands[best][0] < cands.get(
                1, cands[best])[0] * _MIN_GAIN:
            best = 1
        used = [cands[d][1] for d in sorted(cands)]
        return (int(best), used,
                f"shard ladder at n={n}: " + ", ".join(
                    f"d{d}={cands[d][0]:.1f}" for d in sorted(cands)))
    return None


def _rule_fused_nb(table: EvidenceTable, fp: Dict[str, Any]):
    # No standing fused_nb sweep artifact exists yet; a future bench
    # regime family ("bench.fused_nb.<nb>" rows) decides this.
    cands = {int(r.key.rpartition(".")[2]): (float(r.value), r.key)
             for r in table.match("bench.fused_nb.")
             if r.key.rpartition(".")[2].isdigit()}
    if len(cands) < 2:
        return None
    best = max(sorted(cands), key=lambda nb: cands[nb][0])
    return (int(best), [cands[nb][1] for nb in sorted(cands)],
            f"fused column-block sweep: nb={best} fastest")


def _rule_unroll(table: EvidenceTable, fp: Dict[str, Any]):
    # Same contract as fused_nb: decided only once an unroll sweep
    # artifact exists ("bench.unroll.<k>" rows).
    cands = {int(r.key.rpartition(".")[2]): (float(r.value), r.key)
             for r in table.match("bench.unroll.")
             if r.key.rpartition(".")[2].isdigit()}
    if len(cands) < 2:
        return None
    best = max(sorted(cands), key=lambda k: cands[k][0])
    return (int(best), [cands[k][1] for k in sorted(cands)],
            f"scan unroll sweep: unroll={best} fastest")


def _rule_flight_drain_every(table: EvidenceTable, fp: Dict[str, Any]):
    """Flight-recorder A/B (churn0 quiescent regime, with/without the
    ring): if the recorder costs >5% rounds/s, halve the host-transfer
    cadence by doubling the dispatch interval.  The journey ledger's
    drain-stage attribution (fuse.journey_stage_share.drain.*) argues
    the other direction: transitions spending most of their end-to-end
    time queued for the event flush want a SHORTER cadence regardless
    of recorder overhead."""
    jr = None
    jtiers: Dict[int, Any] = {}
    for r in table.match("fuse.journey_stage_share.drain.batch"):
        suffix = r.key.rpartition("batch")[2]
        if suffix.isdigit():
            jtiers[int(suffix)] = r
    if jtiers:
        jr = jtiers[max(jtiers)]
    by_n = _rps_by(table,
                   lambda p: (p["variant"] == "gossip"
                              and p["churn_ppm"] == 0
                              and p["strategy"] == "swar"
                              and p["hot"] == 0 and p["shard"] == 0
                              and not p["nemesis"]),
                   lambda p: p["flight"])
    for n in sorted(by_n, reverse=True):
        cands = by_n[n]
        if True not in cands or False not in cands:
            continue
        off, on = cands[False][0], cands[True][0]
        overhead = 0.0 if off <= 0 else max(0.0, 1.0 - on / off)
        every = 32 if overhead > 0.05 else 16
        used = [cands[False][1], cands[True][1]]
        reason = (f"flight overhead {overhead * 100:.1f}% at n={n} "
                  f"(off={off:.1f}, on={on:.1f} rounds/s)")
        if jr is not None and float(jr.value) > 0.5:
            every = max(8, every // 2)
            used.append(jr.key)
            reason += (f"; journey: drain stage carries "
                       f"{float(jr.value) * 100:.0f}% of transition "
                       "time — cadence halved")
        return (every, used, reason)
    if jr is not None and float(jr.value) > 0.5:
        return (8, [jr.key],
                f"journey: drain stage carries "
                f"{float(jr.value) * 100:.0f}% of transition time (no "
                "recorder A/B measured) — cadence cut to 8")
    return None


def _rule_http_workers(table: EvidenceTable, fp: Dict[str, Any]):
    cands = {int(r.key.rpartition("workers")[2]): (float(r.value), r.key)
             for r in table.match("serve.kv_get_rps.workers")}
    if len(cands) < 2:
        return None
    best = max(sorted(cands), key=lambda w: cands[w][0])
    if best != 1 and cands[best][0] < cands.get(1, cands[best])[0] * _MIN_GAIN:
        best = 1
    return (int(best), [cands[w][1] for w in sorted(cands)],
            "serving A/B: " + ", ".join(
                f"workers={w} {cands[w][0]:.0f} req/s"
                for w in sorted(cands)))


def _rule_device_store(table: EvidenceTable, fp: Dict[str, Any]):
    """Chip-class backends take the device store (batched apply + the
    device matcher amortize); on CPU the host walk wins at every
    measured watch tier, so it stays off unless flagged."""
    on = fp.get("platform") not in ("cpu", "")
    return (bool(on), ["fingerprint.platform"],
            f"platform {fp.get('platform')!r} is "
            + ("chip-class" if on else "host-class"))


def _rule_watch_device_min(table: EvidenceTable, fp: Dict[str, Any]):
    cross = table.get("watch.crossover_watches")
    if cross is not None:
        return (int(cross.value), [cross.key],
                "measured host/device crossover (watchstorm --sweep)")
    hi = table.get("watch.sweep_max")
    if hi is not None and int(hi.value) > 0:
        floor = max(DEFAULT_WATCH_DEVICE_MIN, 2 * int(hi.value))
        return (floor, [hi.key],
                f"device never won below the sweep cap ({int(hi.value)}); "
                "floor set above it")
    return None


def _rule_lease_timeout_floor(table: EvidenceTable, fp: Dict[str, Any]):
    """Lease-timeout floor vs the chaos detection floor: the lease fast
    path is only safe while the raft observatory demonstrably DETECTS
    clock faults burning the lease window (CHAOS.json).  All lease
    scenarios detected => the auto lease window stands (floor 0);
    any undetected => disable the lease read path (-1, the RaftConfig
    sentinel) until detectability is restored."""
    lease_scenarios = ("clock_skew", "clock_jump", "fsync_stall")
    rows = [table.get(f"chaos.detected.{s}") for s in lease_scenarios]
    rows = [r for r in rows if r is not None]
    if not rows:
        return None
    undetected = sorted(r.key.rpartition(".")[2] for r in rows
                        if not bool(r.value))
    if undetected:
        return (-1.0, [r.key for r in rows],
                f"lease-burn scenarios {undetected} NOT detected by the "
                "raft observatory — lease reads disabled")
    return (0.0, [r.key for r in rows],
            f"all {len(rows)} lease-burn scenarios detected; auto lease "
            "window (election_timeout_min) stands")


def _rule_reconcile_batch_max(table: EvidenceTable, fp: Dict[str, Any]):
    """Batched-reconcile tier vs the sequential loop (BENCH_FUSE.json):
    take the largest measured batch tier that holds BOTH acceptance
    bars — ≥10× fewer raft entries per transition AND a p99 no worse
    than the sequential loop (5% noise allowance).  No tier holding
    both ⇒ the default stands, explicitly recorded as a measured
    decision."""
    seq = table.get("fuse.p99_ms.sequential")
    cands: Dict[int, Tuple[float, str]] = {}
    for r in table.match("fuse.entries_per_transition.batch"):
        suffix = r.key.rpartition("batch")[2]
        if suffix.isdigit():
            cands[int(suffix)] = (float(r.value), r.key)
    if seq is None or not cands:
        return None
    used = [seq.key]
    ok: List[int] = []
    for n in sorted(cands):
        p99 = table.get(f"fuse.p99_ms.batch{n}")
        if p99 is None:
            continue
        used += [cands[n][1], p99.key]
        if cands[n][0] <= 0.1 and float(p99.value) <= float(seq.value) * 1.05:
            ok.append(n)
    if len(used) < 3:
        return None  # no tier has both metrics — nothing admissible
    if not ok:
        return (64, used,
                "no batch tier held >=10x entry reduction at a "
                "non-regressed p99; default stands")
    best = max(ok)
    reason = (f"batch={best}: {cands[best][0]:.3f} entries/transition, "
              f"p99 {table.get(f'fuse.p99_ms.batch{best}').value:.1f} ms "
              f"vs sequential {float(seq.value):.1f} ms")
    # Journey stage attribution at the chosen tier (obs/journey.py):
    # name the dominant stage so the verdict records WHERE the batch
    # tier's remaining latency lives, not just that the bar held.
    shares = {r.key.split(".")[2]: float(r.value)
              for r in table.match("fuse.journey_stage_share.")
              if r.key.endswith(f".batch{best}")}
    if shares:
        dom = max(sorted(shares), key=lambda s: shares[s])
        used.append(f"fuse.journey_stage_share.{dom}.batch{best}")
        reason += (f"; journey: {shares[dom] * 100:.0f}% of the "
                   f"remaining latency is the {dom} stage")
    return (best, used, reason)


# -- knob registry -----------------------------------------------------------


@dataclass(frozen=True)
class Knob:
    """One standing chip-decidable knob: default, where it lands, the
    evidence consulted, and the pure decision rule."""

    default: Any
    kind: str                       # str | int | float | bool
    target: str                     # the config field the value lands on
    rule: Callable[[EvidenceTable, Dict[str, Any]], Optional[tuple]]
    evidence: Tuple[str, ...] = ()  # evidence key prefixes consulted
    doc: str = ""
    choices: Tuple[str, ...] = ()   # for kind=str: valid values


# The registry — governing key set for the ``autotune-knob`` vet group.
# Every key is claimed by exactly one consumer-side TUNED_FIELDS
# literal (gossip/plane.py, agent/agent.py, state/device_store.py);
# tools/vet/table_drift.py holds the union equal to this key set.
KNOBS: Dict[str, Knob] = {
    "dissem": Knob(
        default="swar", kind="str", choices=DISSEM_CHOICES,
        target="PlaneConfig.dissem", rule=_rule_dissem,
        evidence=("bench.rps.",),
        doc="Dissemination merge strategy for the kernel round."),
    "fused_nb": Knob(
        default=1, kind="int", target="PlaneConfig.fused_nb",
        rule=_rule_fused_nb, evidence=("bench.fused_nb.",),
        doc="Column-block count for the fused Pallas kernel's grid."),
    "hot_slots": Knob(
        default=0, kind="int", target="PlaneConfig.hot_slots",
        rule=_rule_hot_slots, evidence=("bench.rps.",),
        doc="Active-rumor top-k short-circuit in the dissemination "
            "sweep (0 = full sweep)."),
    "shard_devices": Knob(
        default=1, kind="int", target="PlaneConfig.shard_devices",
        rule=_rule_shard_devices, evidence=("bench.rps.",),
        doc="Devices the SWIM round is shard_map'd over."),
    "unroll": Knob(
        default=4, kind="int", target="PlaneConfig.unroll",
        rule=_rule_unroll, evidence=("bench.unroll.",),
        doc="Kernel rounds fused per scan iteration."),
    "flight_drain_every": Knob(
        default=16, kind="int", target="PlaneConfig.flight_drain_every",
        rule=_rule_flight_drain_every,
        evidence=("bench.rps.", "fuse.journey_stage_share.drain."),
        doc="Dispatches between flight-ring host drains."),
    "http_workers": Knob(
        default=1, kind="int", target="AgentConfig.http_workers",
        rule=_rule_http_workers, evidence=("serve.",),
        doc="Serving-plane HTTP worker processes."),
    "device_store": Knob(
        default=False, kind="bool", target="AgentConfig.device_store",
        rule=_rule_device_store, evidence=("fingerprint.",),
        doc="Device-resident state store (batched FSM apply + device "
            "watch matching)."),
    "watch_device_min": Knob(
        default=DEFAULT_WATCH_DEVICE_MIN, kind="int",
        target="DeviceStoreBridge watch matcher floor (CPU)",
        rule=_rule_watch_device_min, evidence=("watch.",),
        doc="Standing-watch count where the device matcher beats the "
            "host radix walk on CPU."),
    "reconcile_batch_max": Knob(
        default=64, kind="int", target="AgentConfig.reconcile_batch_max",
        rule=_rule_reconcile_batch_max, evidence=("fuse.",),
        doc="Catalog writes folded into one BATCH raft envelope per "
            "reconcile flush (agent/reconcile.py); cadence coupling "
            "rides flight_drain_every."),
    "lease_timeout_floor_s": Knob(
        default=0.0, kind="float",
        target="RaftConfig.lease_timeout (when not overridden)",
        rule=_rule_lease_timeout_floor, evidence=("chaos.",),
        doc="Lease-timeout floor vs the chaos detectability verdicts "
            "(0 = auto window; -1 = lease reads disabled)."),
}


def _valid(knob: Knob, value: Any) -> bool:
    """A persisted verdict is operator input from disk: type- and
    domain-check before a boot applies it (a corrupted file must
    degrade to defaults, not crash SwimParams validation)."""
    if knob.kind == "str":
        return isinstance(value, str) and (
            not knob.choices or value in knob.choices)
    if knob.kind == "bool":
        return isinstance(value, bool)
    if knob.kind == "int":
        return isinstance(value, int) and not isinstance(value, bool)
    if knob.kind == "float":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    return False


# -- fingerprint + persistence -----------------------------------------------


def fingerprint(platform: Optional[str] = None,
                device_count: Optional[int] = None) -> Dict[str, Any]:
    """Backend identity a verdict is scoped to: platform x topology x
    jax version.  Imports jax only when the caller did not supply the
    platform/topology (the offline CLI passes both to stay chip-free)."""
    from consul_tpu.obs import devstats
    if platform is None or device_count is None:
        import jax
        platform = platform or jax.default_backend()
        if device_count is None:
            device_count = jax.device_count()
    return {"platform": str(platform), "device_count": int(device_count),
            "jax": devstats.jax_version()}


def cache_dir() -> str:
    """The XLA compile-cache directory the verdict lives next to (same
    resolution as gossip/plane.py start())."""
    return os.environ.get(
        "CONSUL_TPU_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "consul_tpu_jax_cache"))


def verdict_dir() -> str:
    return os.environ.get("CONSUL_TPU_AUTOTUNE_DIR",
                          os.path.join(cache_dir(), "autotune"))


def verdict_path(platform: str) -> str:
    return os.path.join(verdict_dir(), f"verdict-{platform}.json")


def enabled() -> bool:
    return os.environ.get("CONSUL_TPU_AUTOTUNE", "1") != "0"


def _round_floats(value: Any) -> Any:
    if isinstance(value, float):
        return round(value, 6)
    return value


def settle(rows: Sequence[Evidence], fp: Dict[str, Any]) -> Dict[str, Any]:
    """Evidence + fingerprint -> verdict dict.  Pure and deterministic:
    identical inputs produce identical output (no wall-clock reads —
    freshness is judged against the evidence epoch)."""
    table = EvidenceTable(rows, fp.get("platform", ""))
    knobs: Dict[str, Any] = {}
    for name in sorted(KNOBS):
        knob = KNOBS[name]
        try:
            got = knob.rule(table, fp)
        except Exception:  # noqa: E02 — one bad rule must not void the rest
            got = None
        if got is None:
            knobs[name] = {"value": _round_floats(knob.default),
                           "source": "default", "evidence": [],
                           "reason": "no admissible evidence"}
        else:
            value, used, reason = got
            knobs[name] = {"value": _round_floats(value),
                           "source": "evidence",
                           "evidence": sorted(used), "reason": reason}
    return {
        "format": VERDICT_FORMAT,
        "fingerprint": dict(fp),
        "evidence_epoch_unix": round(table.epoch, 3),
        "evidence_rows": len(table.rows),
        "rejected_rows": sorted(
            f"{r.key} [{why}]" for r, why in table.rejected),
        "knobs": knobs,
    }


def verdict_bytes(verdict: Dict[str, Any]) -> bytes:
    """Canonical serialization — ``make tune-check`` byte-compares two
    independent settles of the same artifacts."""
    return (json.dumps(verdict, indent=1, sort_keys=True) + "\n").encode()


def save_verdict(verdict: Dict[str, Any],
                 path: Optional[str] = None) -> Optional[str]:
    path = path or verdict_path(verdict["fingerprint"]["platform"])
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(verdict_bytes(verdict))
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def load_verdict(platform: str,
                 path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    payload = _read_json(path or verdict_path(platform))
    if not isinstance(payload, dict) \
            or payload.get("format") != VERDICT_FORMAT \
            or not isinstance(payload.get("knobs"), dict):
        return None
    return payload


# -- boot-time resolution ----------------------------------------------------

# Per-process count of fingerprint-change re-settles (the
# consul_autotune_resettles_total counter).
_RESETTLES = 0


def resettles() -> int:
    return _RESETTLES


def _resettle(fp: Dict[str, Any], root: str) -> Optional[Dict[str, Any]]:
    """The persisted verdict no longer matches this backend: settle a
    fresh one from whatever artifacts this checkout holds and persist
    it (best-effort — an unwritable cache dir still yields a usable
    in-memory verdict)."""
    global _RESETTLES
    _RESETTLES += 1
    verdict = settle(gather_evidence(root), fp)
    save_verdict(verdict)
    return verdict


@dataclass
class Resolution:
    """One boot's knob resolution: per-knob rows + the metadata the
    operator surfaces (/v1/operator/autotune, prom families) report."""

    rows: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    meta: Dict[str, Any] = field(default_factory=dict)

    def value(self, name: str) -> Any:
        return self.rows[name]["value"]

    def wire(self) -> Dict[str, Any]:
        return {"knobs": dict(self.rows), **self.meta,
                "resettles": resettles()}


def resolve(names: Sequence[str], explicit: Dict[str, Any],
            platform: Optional[str] = None,
            device_count: Optional[int] = None,
            root: str = REPO_ROOT) -> Resolution:
    """Strict resolution order per knob: explicit flag > persisted
    verdict > registry default.  ``explicit`` maps knob name -> value
    for knobs the operator actually set (absent/None = unset).  A
    verdict whose fingerprint no longer matches this backend is
    re-settled from the repo artifacts and re-persisted."""
    fp = fingerprint(platform, device_count)
    verdict = None
    vpath = verdict_path(fp["platform"])
    if enabled():
        verdict = load_verdict(fp["platform"])
        if verdict is not None and verdict.get("fingerprint") != fp:
            verdict = _resettle(fp, root)
    res = Resolution(meta={
        "fingerprint": fp,
        "verdict_path": vpath,
        "verdict_found": verdict is not None,
        "autotune_enabled": enabled(),
        "evidence_epoch_unix": (verdict or {}).get(
            "evidence_epoch_unix", 0.0),
    })
    vknobs = (verdict or {}).get("knobs", {})
    for name in names:
        knob = KNOBS[name]
        if explicit.get(name) is not None:
            res.rows[name] = {
                "value": explicit[name], "source": "flag",
                "evidence": [], "reason": "explicit configuration"}
            continue
        vk = vknobs.get(name)
        if isinstance(vk, dict) and _valid(knob, vk.get("value")):
            res.rows[name] = {
                "value": vk["value"],
                # A verdict row that merely restates the registry
                # default carries no evidence — report it as such.
                "source": ("verdict" if vk.get("source") == "evidence"
                           else "default"),
                "evidence": list(vk.get("evidence") or []),
                "reason": str(vk.get("reason", ""))}
        else:
            res.rows[name] = {
                "value": knob.default, "source": "default", "evidence": [],
                "reason": ("autotune disabled" if not enabled()
                           else "no verdict for this knob")}
    return res


def resolved_value(name: str, default: Any = None,
                   platform: Optional[str] = None,
                   device_count: Optional[int] = None) -> Any:
    """One-knob convenience for leaf consumers (the device-store
    bridge): verdict value when present and valid, else ``default``
    (falling back to the registry default when None)."""
    res = resolve([name], {}, platform=platform, device_count=device_count)
    row = res.rows[name]
    if row["source"] in ("verdict",):
        return row["value"]
    return KNOBS[name].default if default is None else default


# -- observability -----------------------------------------------------------


def prom_families(wire: Dict[str, Any], now: float,
                  ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """``consul_autotune_*`` families from a merged wire payload:
    (labeled_gauges, labeled_counters) in obs/prom.py family shape."""
    rows = wire.get("knobs") or {}
    info_rows, value_rows = [], []
    for name in sorted(rows):
        row = rows[name]
        info_rows.append((
            {"knob": name, "value": str(row.get("value")),
             "source": str(row.get("source", "default"))}, 1.0))
        value = row.get("value")
        if isinstance(value, bool):
            value_rows.append(({"knob": name}, 1.0 if value else 0.0))
        elif isinstance(value, (int, float)):
            value_rows.append(({"knob": name}, float(value)))
    epoch = float(wire.get("evidence_epoch_unix") or 0.0)
    age = (now - epoch) if epoch > 0 else -1.0
    gauges = [
        {"name": "consul_autotune_knob_info",
         "help": "Resolved autotune knobs: value + resolution source "
                 "(flag | verdict | default).",
         "rows": info_rows or [({"knob": "none", "value": "",
                                 "source": "default"}, 0.0)]},
        {"name": "consul_autotune_knob_value",
         "help": "Resolved numeric knob values (bool as 0/1; "
                 "string-valued knobs appear only in knob_info).",
         "rows": value_rows or [({"knob": "none"}, 0.0)]},
        {"name": "consul_autotune_evidence_age_seconds",
         "help": "Age of the newest evidence behind the persisted "
                 "verdict (-1 = no evidence-backed verdict).",
         "rows": [({}, round(age, 3))]},
    ]
    counters = [
        {"name": "consul_autotune_resettles_total",
         "help": "Fingerprint-change re-settles since process start.",
         "rows": [({}, float(wire.get("resettles", 0)))]},
    ]
    return gauges, counters
