"""Framework version.

Mirrors the role of the reference's ``version.go:8`` (Consul v0.5.2): a
single place that names the release and the protocol versions spoken on
the wire.  Protocol versioning follows the reference's scheme
(``consul/config.go:31-37``): a [min, max] range advertised in gossip
tags so mixed-version clusters can negotiate.
"""

VERSION = "0.1.0"

# Protocol versions (analogue of consul.ProtocolVersionMin/Max).
PROTOCOL_VERSION_MIN = 1
PROTOCOL_VERSION_MAX = 2
PROTOCOL_VERSION = PROTOCOL_VERSION_MAX

# Consul-protocol -> gossip-wire-protocol map (the reference masks serf
# protocol versions behind its own numbering, consul/config.go:26-37:
# {1: 4, 2: 4, 3: 5}).  Both of our protocol versions speak gossip wire
# version 1 — the map exists so a future wire change can ride a
# protocol bump the same way.
PROTOCOL_VERSION_MAP = {1: 1, 2: 1}


def check_protocol_version(v: int) -> None:
    """consul.Config.CheckVersion (consul/config.go:208-217)."""
    if v < PROTOCOL_VERSION_MIN:
        raise ValueError(
            f"Protocol version '{v}' too low. Must be in range: "
            f"[{PROTOCOL_VERSION_MIN}, {PROTOCOL_VERSION_MAX}]")
    if v > PROTOCOL_VERSION_MAX:
        raise ValueError(
            f"Protocol version '{v}' too high. Must be in range: "
            f"[{PROTOCOL_VERSION_MIN}, {PROTOCOL_VERSION_MAX}]")
