"""Framework version.

Mirrors the role of the reference's ``version.go:8`` (Consul v0.5.2): a
single place that names the release and the protocol versions spoken on
the wire.  Protocol versioning follows the reference's scheme
(``consul/config.go:31-37``): a [min, max] range advertised in gossip
tags so mixed-version clusters can negotiate.
"""

VERSION = "0.1.0"

# Protocol versions (analogue of consul.ProtocolVersionMin/Max).
PROTOCOL_VERSION_MIN = 1
PROTOCOL_VERSION_MAX = 2
PROTOCOL_VERSION = PROTOCOL_VERSION_MAX
