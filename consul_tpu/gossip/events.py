"""User-event dissemination kernel: Serf's lamport-clocked broadcast
queue as batched array ops.

Parity target: Serf's user-event layer as consumed by Consul
(``consul/serf.go`` user-event handling; behavior contract at
``website/source/docs/internals/gossip.html.markdown`` §"gossip" and
the Serf event docs): events are flooded via the same gossip fanout as
membership rumors, stamped with a cluster-wide Lamport time, buffered
for dedup, and retransmitted with the standard
``retransmit_mult * log(n)`` budget.

Kernel layout: E concurrent event slots over N nodes.

    has[e, i]  (uint8)  bits 7: seen   bits 3-0: age (rounds since seen)

A node that has seen event ``e`` gossips it to ``fanout`` peers per
round while its age is within the spread budget — the identical
inverse-permutation gather machinery as the membership kernel
(kernel.py), so both piggyback on one communication pattern.  Lamport
times live in ``ltime[e]`` (events) and ``node_ltime[i]`` (per-node
clocks): a node receiving an event witnesses its ltime, advancing the
local clock to ``max(local, event)+1`` — Serf's lamport rules.

Coverage statistics (rounds to 50%/99%/100%) are what the
cross-validation tier compares against the discrete-event epidemic
model (BASELINE config #3: "event convergence statistics match Serf").
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.gossip.params import SwimParams
from consul_tpu.gossip.kernel import alloc_free_slots, gossip_offsets

_SEEN = 0x80
_AGE_MASK = 0x0F


class EventState(NamedTuple):
    round: jnp.ndarray       # i32 scalar
    has: jnp.ndarray         # u8 [E, N] seen-bit + age
    slot_used: jnp.ndarray   # bool [E]
    ltime: jnp.ndarray       # i32 [E] lamport time of each event
    origin: jnp.ndarray      # i32 [E] firing node
    start_round: jnp.ndarray  # i32 [E]
    node_ltime: jnp.ndarray  # i32 [N] per-node lamport clock
    n_seen: jnp.ndarray      # i32 [E] cumulative deliveries (survives GC
                             #   until the slot is reused — the convergence
                             #   statistic of BASELINE config #3)
    drops: jnp.ndarray       # i32 — fires lost to full slots


def init_events(p: SwimParams, slots: int = 64) -> EventState:
    E, N = slots, p.n
    return EventState(
        round=jnp.int32(0),
        has=jnp.zeros((E, N), jnp.uint8),
        slot_used=jnp.zeros((E,), bool),
        ltime=jnp.zeros((E,), jnp.int32),
        origin=jnp.full((E,), -1, jnp.int32),
        start_round=jnp.zeros((E,), jnp.int32),
        node_ltime=jnp.zeros((N,), jnp.int32),
        n_seen=jnp.zeros((E,), jnp.int32),
        drops=jnp.int32(0),
    )


def fire_events(state: EventState, nodes: jnp.ndarray) -> EventState:
    """Originate one event per entry of ``nodes`` (int32 array of firing
    node ids; -1 entries are ignored).  Each takes a free slot; overflow
    counts into ``drops``.  Lamport: fire = local clock + 1 (Serf
    UserEvent stamps the next time)."""
    E = state.has.shape[0]
    want = nodes >= 0
    can, _slot_for, sidx = alloc_free_slots(~state.slot_used, want)
    node_c = jnp.clip(nodes, 0, state.node_ltime.shape[0] - 1)

    fire_lt = state.node_ltime[node_c] + 1
    node_ltime = state.node_ltime.at[
        jnp.where(can, node_c, state.node_ltime.shape[0])].set(
        fire_lt, mode="drop")

    slot_used = state.slot_used.at[sidx].set(True, mode="drop")
    ltime = state.ltime.at[sidx].set(fire_lt, mode="drop")
    origin = state.origin.at[sidx].set(nodes, mode="drop")
    start_round = state.start_round.at[sidx].set(state.round, mode="drop")
    has = state.has.at[sidx, node_c].set(jnp.uint8(_SEEN), mode="drop")
    n_seen = state.n_seen.at[sidx].set(1, mode="drop")  # the origin has it
    drops = state.drops + jnp.sum((want & ~can).astype(jnp.int32))
    return state._replace(has=has, slot_used=slot_used, ltime=ltime,
                          origin=origin, start_round=start_round,
                          node_ltime=node_ltime, n_seen=n_seen, drops=drops)


@functools.partial(jax.jit, static_argnames=("p",))
def event_round(state: EventState, base_key: jax.Array, alive: jnp.ndarray,
                p: SwimParams) -> EventState:
    """One gossip round of event flooding."""
    rnd = state.round
    key = jax.random.fold_in(jax.random.fold_in(base_key, 7), rnd)
    N = p.n

    # Gossip on PRE-tick ages (a copy received last round, age 0, gets
    # its first send this round even with a 1-round budget); ages tick
    # when the new state is assembled below.
    cur = state.has
    seen = (cur & _SEEN) > 0

    # fanout deliveries via circulant rolls (the membership kernel's
    # communication pattern — see kernel.gossip_offsets on why rolls
    # beat permutation gathers ~by the whole kernel's speed on TPU)
    rx_ok = alive
    new_seen = jnp.zeros_like(seen)
    offs = gossip_offsets(key, N, p.fanout)
    for f in range(p.fanout):
        o = offs[f]
        src_ok = jnp.roll(alive, o)
        hin = jnp.roll(cur, o, axis=1)
        active = (src_ok[None, :] & ((hin & _SEEN) > 0)
                  & ((hin & _AGE_MASK) < p.spread_budget_rounds))
        new_seen = new_seen | (active & rx_ok[None, :])

    # push/pull anti-entropy: full-state sync with one partner, spread
    # budget ignored (this recovers events that aged out under loss)
    if p.pushpull_every:
        def _pp(ns):
            kpp = jax.random.fold_in(key, 9)
            o = jax.random.randint(kpp, (), 1, N, dtype=jnp.int32)
            for shift in (o, -o):
                ok = rx_ok & jnp.roll(alive, shift)
                hin = jnp.roll(cur, shift, axis=1)
                ns = ns | (((hin & _SEEN) > 0) & ok[None, :])
            return ns

        new_seen = jax.lax.cond(
            rnd % p.pushpull_every == p.pushpull_every - 1,
            _pp, lambda ns: ns, new_seen)

    fresh = new_seen & ~seen
    age = cur & _AGE_MASK
    aged = jnp.where(seen,
                     jnp.uint8(_SEEN)
                     | jnp.minimum(age + 1, _AGE_MASK).astype(jnp.uint8),
                     cur)
    has = jnp.where(fresh, jnp.uint8(_SEEN), aged)
    n_seen = state.n_seen + jnp.sum(fresh, axis=1, dtype=jnp.int32)  # noqa: O01 — monotone mod 2**32 (SwimState wrap convention, gossip/kernel.py); consumers take i32 deltas

    # lamport witness: clock = max(clock, max ltime of newly seen events)+1
    # (Serf witnessedClock). One max over slots is enough per round.
    wit = jnp.max(jnp.where(fresh, state.ltime[:, None], 0), axis=0)
    node_ltime = jnp.where(wit > 0,
                           jnp.maximum(state.node_ltime, wit) + 1,
                           state.node_ltime)

    # slot GC: recycle after the event TTL (flood window + push/pull
    # recovery cycles) — Serf's recent-event buffer rotating out.
    done = state.slot_used & (rnd - state.start_round > p.event_ttl_rounds)
    has = jnp.where(done[:, None], jnp.uint8(0), has)
    slot_used = state.slot_used & ~done
    origin = jnp.where(done, -1, state.origin)

    return state._replace(round=rnd + 1, has=has, slot_used=slot_used,
                          origin=origin, node_ltime=node_ltime,
                          n_seen=n_seen)


def coverage(state: EventState, alive: jnp.ndarray) -> jnp.ndarray:
    """Fraction of alive nodes that have seen each event slot [E]."""
    seen = ((state.has & _SEEN) > 0) & alive[None, :]
    n_alive = jnp.maximum(jnp.sum(alive), 1)
    return jnp.sum(seen, axis=1) / n_alive


@functools.partial(jax.jit, static_argnames=("p", "steps"))
def run_event_rounds(state: EventState, base_key: jax.Array,
                     alive: jnp.ndarray, p: SwimParams, steps: int):
    """Scan; traces per-round coverage [T, E] for convergence curves."""

    def body(st, _):
        st = event_round(st, base_key, alive, p)
        return st, coverage(st, alive)

    return jax.lax.scan(body, state, None, length=steps)
