"""Cross-validation core: TPU kernel vs discrete-event SWIM oracle.

Shared by the artifact generator (``tools/crossval_report.py`` →
``CROSSVAL.json``) and the in-suite regression tier
(``tests/test_gossip_crossval.py``), so the suite gates on the SAME
statistics the published artifact reports — the round-3 lesson was that
evidence living only in an offline tool run lets regressions (and
sample-starved percentiles) ship unnoticed.

Definitions:
  latency       = dead_declared_round - fail_round (both models)
  relative_error = |kernel - refmodel| / refmodel, per statistic
  completeness  = detected events / injected failures, per model
"""

from __future__ import annotations

import time

import numpy as np


def kernel_event_latencies(p, fail_at: dict, steps: int, seed: int):
    """Per-event detection latencies from the kernel's round trace.

    A victim's episode slot records its verdict round in
    ``slot_dead_round``; latency = dead_round - fail_round (the same
    definition ``RefModel.detection_latencies`` uses).  Returns
    ``(latencies, n_false_dead, n_refuted, drops)``.
    """
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (NEVER, PHASE_DEAD, init_state,
                                          run_rounds)

    fail = np.full(p.n, NEVER, np.int32)
    for v, t in fail_at.items():
        fail[v] = t
    st, trace = run_rounds(init_state(p), jax.random.key(seed),
                           jnp.asarray(fail), p, steps, trace=True)
    slot_node = np.asarray(trace.slot_node)        # [T, S]
    slot_dead = np.asarray(trace.slot_dead_round)  # [T, S]
    slot_phase = np.asarray(trace.slot_phase)      # [T, S]
    lats = []
    for v, t_fail in fail_at.items():
        # Only true detections: a lossy run can falsely declare a victim
        # dead BEFORE its fail round — the refmodel books those under
        # n_false_dead, not detection latency, so we must too.  The
        # verdict round is shared with refutes (slot_dead_round records
        # either verdict), so require the DEAD phase.
        mask = ((slot_node == v) & (slot_dead >= t_fail)
                & (slot_phase == PHASE_DEAD))
        if mask.any():
            lats.append(int(slot_dead[mask].min()) - t_fail)
    return lats, int(st.n_false_dead), int(st.n_refuted), int(st.drops)


def refmodel_event_latencies(p, fail_at: dict, steps: int, seed: int):
    from consul_tpu.gossip.refmodel import RefModel
    m = RefModel(p, dict(fail_at), seed=seed)
    m.run(steps)
    return m.detection_latencies(), m.n_false_dead, m.n_refuted


def loss_sized_slots(n: int, loss: float, base: int = 64) -> int:
    """Slot provisioning for a lossy regime.

    Loss manufactures spurious suspicion episodes; each holds a slot
    from initiation until the refute verdict's dissemination window
    closes.  Expected concurrent episodes ≈ (spurious initiations per
    round) × (hold rounds); under-provisioning surfaces as ``drops``
    and detection gaps (round-3 CROSSVAL config 3: 64 slots vs ~250
    needed → 2/16 detections).  This mirrors real provisioning: the
    S×N belief matrix is sized for the operating loss regime, and the
    ``drops`` counter is the saturation alarm."""
    from consul_tpu.gossip.params import SwimParams
    p = SwimParams(n=n, loss_rate=loss)
    # P(an alive target's probe goes spurious): direct fails AND no
    # indirect helper rescues.
    p_no_rescue = p.p_indirect_fail_alive ** p.indirect_k if p.indirect_k else 1.0
    p_spur = p.p_direct_fail_alive * p_no_rescue
    per_round = (n / p.probe_every) * p_spur
    hold = 4 + 2 * p.spread_budget_rounds + 8  # refute latency + verdict window
    need = int(per_round * hold * 1.5)  # chained re-arms margin
    return max(base, 1 << (need - 1).bit_length()) if need else base


def run_config(n: int, n_victims: int, seeds: int, loss: float = 0.0,
               slots: int | None = None) -> dict:
    """One matched kernel-vs-oracle config; returns the report row."""
    from consul_tpu.gossip.params import SwimParams
    if slots is None:
        slots = loss_sized_slots(n, loss)
    p = SwimParams(n=n, slots=slots, probe_every=5, loss_rate=loss)
    first_fail = 30
    spacing = max(5, p.suspicion_min_rounds // 4)
    fail_at = {(n // (n_victims + 1)) * (i + 1): first_fail + i * spacing
               for i in range(n_victims)}
    steps = (first_fail + n_victims * spacing
             + p.slot_ttl_rounds + 8 * p.probe_every)

    k_lats, r_lats = [], []
    k_fp = r_fp = k_ref = r_ref = k_drops = 0
    t0 = time.time()
    for s in range(seeds):
        kl, kf, kr, kd = kernel_event_latencies(p, fail_at, steps, seed=s)
        k_lats += kl
        k_fp += kf
        k_ref += kr
        k_drops += kd
    t_kernel = time.time() - t0
    t0 = time.time()
    for s in range(seeds):
        rl, rf, rr = refmodel_event_latencies(p, fail_at, steps,
                                              seed=1000 + s)
        r_lats += rl
        r_fp += rf
        r_ref += rr
    t_ref = time.time() - t0

    k = np.asarray(k_lats, float)
    r = np.asarray(r_lats, float)

    def pct(a, q):
        return float(np.percentile(a, q)) if len(a) else None

    def rel(kv, rv):
        if kv is None or rv is None or not rv:
            return None
        return round(abs(kv - rv) / rv, 4)

    expected = n_victims * seeds
    return {
        "n": n,
        "loss_rate": loss,
        "slots": slots,
        "victims_per_run": n_victims,
        "seeds": seeds,
        "samples": {"kernel": len(k), "refmodel": len(r)},
        "expected_events": expected,
        # Detection completeness: fraction of injected failures whose
        # dead verdict was declared inside the window.  First-class
        # because round 3 shipped 2/16 here without anyone noticing —
        # percentiles over a starved sample set are meaningless.
        "completeness": {
            "kernel": round(len(k) / expected, 4) if expected else None,
            "refmodel": round(len(r) / expected, 4) if expected else None,
        },
        # Suspicion initiations lost to full slots (saturation alarm for
        # the S sizing above; structurally 0 in the refmodel).
        "kernel_slot_drops": k_drops,
        "detection_latency_rounds": {
            "kernel": {"mean": round(float(k.mean()), 2) if len(k) else None,
                       "p50": pct(k, 50), "p99": pct(k, 99)},
            "refmodel": {"mean": round(float(r.mean()), 2) if len(r) else None,
                         "p50": pct(r, 50), "p99": pct(r, 99)},
        },
        "relative_error": {
            "mean": rel(float(k.mean()) if len(k) else None,
                        float(r.mean()) if len(r) else None),
            "p50": rel(pct(k, 50), pct(r, 50)),
            "p99": rel(pct(k, 99), pct(r, 99)),
        },
        "false_dead": {"kernel": k_fp, "refmodel": r_fp},
        "refutes": {"kernel": k_ref, "refmodel": r_ref},
        "lifeguard_envelope_rounds": [p.suspicion_min_rounds,
                                      p.suspicion_max_rounds],
        "wall_s": {"kernel": round(t_kernel, 1), "refmodel": round(t_ref, 1)},
    }
