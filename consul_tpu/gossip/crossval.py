"""Cross-validation core: TPU kernel vs discrete-event SWIM oracle.

Shared by the artifact generator (``tools/crossval_report.py`` →
``CROSSVAL.json``) and the in-suite regression tier
(``tests/test_gossip_crossval.py``), so the suite gates on the SAME
statistics the published artifact reports — the round-3 lesson was that
evidence living only in an offline tool run lets regressions (and
sample-starved percentiles) ship unnoticed.

Definitions:
  latency       = dead_declared_round - fail_round (both models)
  relative_error = |kernel - refmodel| / refmodel, per statistic
  completeness  = detected events / injected failures, per model
"""

from __future__ import annotations

import time

import numpy as np


def kernel_event_latencies(p, fail_at: dict, steps: int, seed: int,
                           ndev: int = 0):
    """Per-event detection latencies from the kernel's round trace.

    A victim's episode slot records its verdict round in
    ``slot_dead_round``; latency = dead_round - fail_round (the same
    definition ``RefModel.detection_latencies`` uses).  Returns
    ``(latencies, n_false_dead, n_refuted, drops)``.

    ``ndev > 1`` runs the ICI-sharded kernel instead — bit-identical
    dynamics (tests/test_shard_map_parity.py), so the oracle gates
    apply to the sharded lowering unchanged; it lets the crossval tier
    exercise the production multi-device path end-to-end.
    """
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (NEVER, PHASE_DEAD, init_state,
                                          run_rounds, run_rounds_sharded,
                                          shard_state)

    fail = np.full(p.n, NEVER, np.int32)
    for v, t in fail_at.items():
        fail[v] = t
    if ndev > 1:
        st, trace = run_rounds_sharded(
            shard_state(init_state(p), ndev), jax.random.key(seed),
            jnp.asarray(fail), p, steps, trace=True, ndev=ndev)
    else:
        st, trace = run_rounds(init_state(p), jax.random.key(seed),
                               jnp.asarray(fail), p, steps, trace=True)
    slot_node = np.asarray(trace.slot_node)        # [T, S]
    slot_dead = np.asarray(trace.slot_dead_round)  # [T, S]
    slot_phase = np.asarray(trace.slot_phase)      # [T, S]
    lats = []
    for v, t_fail in fail_at.items():
        # Only true detections: a lossy run can falsely declare a victim
        # dead BEFORE its fail round — the refmodel books those under
        # n_false_dead, not detection latency, so we must too.  The
        # verdict round is shared with refutes (slot_dead_round records
        # either verdict), so require the DEAD phase.
        mask = ((slot_node == v) & (slot_dead >= t_fail)
                & (slot_phase == PHASE_DEAD))
        if mask.any():
            lats.append(int(slot_dead[mask].min()) - t_fail)
    return lats, int(st.n_false_dead), int(st.n_refuted), int(st.drops)


def refmodel_event_latencies(p, fail_at: dict, steps: int, seed: int):
    from consul_tpu.gossip.refmodel import RefModel
    m = RefModel(p, dict(fail_at), seed=seed)
    m.run(steps)
    return m.detection_latencies(), m.n_false_dead, m.n_refuted


def loss_sized_slots(n: int, loss: float, base: int = 64) -> int:
    """Slot provisioning for a lossy regime.

    Loss manufactures spurious suspicion episodes; each holds a slot
    from initiation until the refute verdict's dissemination window
    closes.  Expected concurrent episodes ≈ (spurious initiations per
    round) × (hold rounds); under-provisioning surfaces as ``drops``
    and detection gaps (round-3 CROSSVAL config 3: 64 slots vs ~250
    needed → 2/16 detections).  This mirrors real provisioning: the
    S×N belief matrix is sized for the operating loss regime, and the
    ``drops`` counter is the saturation alarm."""
    from consul_tpu.gossip.params import SwimParams
    p = SwimParams(n=n, loss_rate=loss)
    # P(an alive target's probe goes spurious): direct fails AND no
    # indirect helper rescues.
    p_no_rescue = p.p_indirect_fail_alive ** p.indirect_k if p.indirect_k else 1.0
    p_spur = p.p_direct_fail_alive * p_no_rescue
    per_round = (n / p.probe_every) * p_spur
    hold = 4 + 2 * p.spread_budget_rounds + 8  # refute latency + verdict window
    need = int(per_round * hold * 1.5)  # chained re-arms margin
    return max(base, 1 << (need - 1).bit_length()) if need else base


def run_config(n: int, n_victims: int, seeds: int, loss: float = 0.0,
               slots: int | None = None, pushpull: bool = False,
               oracle: bool = True, ndev: int = 0,
               dissem: str = "swar") -> dict:
    """One matched kernel-vs-oracle config; returns the report row.

    ``pushpull`` arms anti-entropy in BOTH models (memberlist
    PushPullInterval, 150 rounds = 30s LAN).  ``oracle=False`` skips
    the discrete-event model and gates on the analytic Lifeguard
    envelope only — the pure-Python oracle is tractable to a few
    thousand nodes, so the 100k BASELINE row (whose published
    criterion IS "p99 within Lifeguard bounds") runs kernel-only,
    with the same config shape oracle-validated at 1k/10k.
    ``dissem`` selects the kernel's dissemination lowering
    (params.SwimParams.dissem) — the oracle never sees it, so running
    the same config at two strategies is an end-to-end statistical
    parity check on top of the bit-parity tier."""
    from consul_tpu.gossip.params import SwimParams
    if slots is None:
        slots = loss_sized_slots(n, loss)
    p = SwimParams(n=n, slots=slots, probe_every=5, loss_rate=loss,
                   pushpull_every=150 if pushpull else 0, dissem=dissem)
    first_fail = 30
    spacing = max(5, p.suspicion_min_rounds // 4)
    fail_at = {(n // (n_victims + 1)) * (i + 1): first_fail + i * spacing
               for i in range(n_victims)}
    steps = (first_fail + n_victims * spacing
             + p.slot_ttl_rounds + 8 * p.probe_every)

    k_lats, r_lats = [], []
    k_fp = r_fp = k_ref = r_ref = k_drops = 0
    t0 = time.time()
    for s in range(seeds):
        kl, kf, kr, kd = kernel_event_latencies(p, fail_at, steps, seed=s,
                                                ndev=ndev)
        k_lats += kl
        k_fp += kf
        k_ref += kr
        k_drops += kd
    t_kernel = time.time() - t0
    t0 = time.time()
    for s in range(seeds if oracle else 0):
        rl, rf, rr = refmodel_event_latencies(p, fail_at, steps,
                                              seed=1000 + s)
        r_lats += rl
        r_fp += rf
        r_ref += rr
    t_ref = time.time() - t0

    k = np.asarray(k_lats, float)
    r = np.asarray(r_lats, float)

    def pct(a, q):
        return float(np.percentile(a, q)) if len(a) else None

    def rel(kv, rv):
        if kv is None or rv is None or not rv:
            return None
        return round(abs(kv - rv) / rv, 4)

    expected = n_victims * seeds
    return {
        "n": n,
        "loss_rate": loss,
        "slots": slots,
        "dissem": dissem,
        "pushpull_every": p.pushpull_every,
        # A skipped oracle must never read as an oracle that detected
        # nothing: its stats are None and the row says why.
        "oracle": oracle if oracle else "skipped (pure-Python oracle "
                  "intractable at this n; envelope gate only)",
        "victims_per_run": n_victims,
        "seeds": seeds,
        "samples": {"kernel": len(k),
                    "refmodel": len(r) if oracle else None},
        "expected_events": expected,
        # Detection completeness: fraction of injected failures whose
        # dead verdict was declared inside the window.  First-class
        # because round 3 shipped 2/16 here without anyone noticing —
        # percentiles over a starved sample set are meaningless.
        "completeness": {
            "kernel": round(len(k) / expected, 4) if expected else None,
            "refmodel": (round(len(r) / expected, 4)
                         if oracle and expected else None),
        },
        # Suspicion initiations lost to full slots (saturation alarm for
        # the S sizing above; structurally 0 in the refmodel).
        "kernel_slot_drops": k_drops,
        "detection_latency_rounds": {
            "kernel": {"mean": round(float(k.mean()), 2) if len(k) else None,
                       "p50": pct(k, 50), "p99": pct(k, 99)},
            "refmodel": {"mean": round(float(r.mean()), 2) if len(r) else None,
                         "p50": pct(r, 50), "p99": pct(r, 99)},
        },
        "relative_error": {
            "mean": rel(float(k.mean()) if len(k) else None,
                        float(r.mean()) if len(r) else None),
            "p50": rel(pct(k, 50), pct(r, 50)),
            "p99": rel(pct(k, 99), pct(r, 99)),
        },
        "false_dead": {"kernel": k_fp, "refmodel": r_fp},
        "refutes": {"kernel": k_ref, "refmodel": r_ref},
        "lifeguard_envelope_rounds": [p.suspicion_min_rounds,
                                      p.suspicion_max_rounds],
        "wall_s": {"kernel": round(t_kernel, 1), "refmodel": round(t_ref, 1)},
    }


# -- nemesis scenarios (gossip/nemesis.py: correlated faults; the
# oracle models the same injection schedule) --------------------------------


def _flap_down_windows(nem) -> list:
    """[(down_start, down_end)] for a flapping schedule — the rounds a
    flap node is actually dead; detection events are attributed to the
    window they fired in (both models use the window start as the
    fail round)."""
    out = []
    td = nem.start + nem.flap_up
    while td < nem.stop:
        out.append((td, min(td + nem.flap_period - nem.flap_up, nem.stop)))
        td += nem.flap_period
    return out


def kernel_nemesis_stats(p, sc, steps: int, seed: int, ndev: int = 0):
    """One kernel run under a nemesis scenario.  Returns
    ``(latencies, n_false_dead, n_refuted, drops, member_frac_end)``.

    Latencies cover static kills (``sc.fail_round``) and, for flapping
    scenarios, the FIRST dead verdict per flap node attributed to its
    down-phase window — the same one-event-per-subject definition the
    refmodel's ``dead_declared`` guard enforces."""
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (PHASE_DEAD, init_nem_state,
                                          init_state, run_rounds,
                                          run_rounds_sharded, shard_state)

    nem = sc.nem
    active = (nem.has_partition or nem.has_flap or nem.has_degraded
              or nem.heal_rejoin)
    kw = dict(
        trace=True,
        join_round=(jnp.asarray(sc.join_round)
                    if sc.join_round is not None else None),
        nem=nem if active else None,
        nem_state=(init_nem_state(p.n)
                   if active and nem.needs_state else None),
    )
    fail = jnp.asarray(sc.fail_round)
    if ndev > 1:
        out, trace = run_rounds_sharded(
            shard_state(init_state(p), ndev), jax.random.key(seed),
            fail, p, steps, ndev=ndev, **kw)
    else:
        out, trace = run_rounds(init_state(p), jax.random.key(seed),
                                fail, p, steps, **kw)
    # the carry is (state[, hist][, nem_state]) when extras are
    # threaded; SwimState is itself a tuple, so sniff the field
    st = out if hasattr(out, "member") else out[0]
    slot_node = np.asarray(trace.slot_node)
    slot_dead = np.asarray(trace.slot_dead_round)
    slot_phase = np.asarray(trace.slot_phase)
    lats = []
    for v in np.nonzero(sc.killed)[0]:
        t_fail = int(sc.fail_round[v])
        mask = ((slot_node == v) & (slot_dead >= t_fail)
                & (slot_phase == PHASE_DEAD))
        if mask.any():
            lats.append(int(slot_dead[mask].min()) - t_fail)
    if nem.has_flap:
        wins = _flap_down_windows(nem)
        for v in range(nem.flap_lo, min(nem.flap_hi, p.n)):
            for td, te in wins:
                mask = ((slot_node == v) & (slot_phase == PHASE_DEAD)
                        & (slot_dead >= td) & (slot_dead < te))
                if mask.any():
                    lats.append(int(slot_dead[mask].min()) - td)
                    break
    member_frac = float(np.asarray(st.member).mean())
    return (lats, int(st.n_false_dead), int(st.n_refuted), int(st.drops),
            member_frac)


def run_nemesis_config(name: str, n: int, seeds: int, ndev: int = 0,
                       slots: int | None = None,
                       steps: int | None = None) -> dict:
    """One nemesis scenario, kernel vs oracle — both models inject the
    SAME schedule (``nemesis.build``).  Returns the report row (same
    statistics families as ``run_config`` plus the scenario label and
    end-state membership recovery).

    Slot sizing: a partition manufactures up to n/2 concurrent
    cross-side suspicion episodes (every far-side node at once), so the
    default provisions ``max(64, n)`` — the iid ``loss_sized_slots``
    estimate badly under-provisions correlated regimes."""
    from consul_tpu.gossip import nemesis
    from consul_tpu.gossip.params import SwimParams
    from consul_tpu.gossip.refmodel import RefModel

    sc = nemesis.build(name, n)
    nem = sc.nem
    if slots is None:
        slots = max(64, 1 << (n - 1).bit_length())
    if steps is None:
        steps = sc.steps
    p = SwimParams(n=n, slots=slots, probe_every=5)
    fail_at = {int(v): int(sc.fail_round[v])
               for v in np.nonzero(sc.killed)[0]}
    expected = (len(fail_at)
                + (nem.flap_hi - nem.flap_lo if nem.has_flap else 0)) * seeds

    k_lats, r_lats = [], []
    k_fp = r_fp = k_ref = r_ref = k_drops = 0
    k_mem, r_mem = [], []
    t0 = time.time()
    for s in range(seeds):
        kl, kf, kr, kd, km = kernel_nemesis_stats(p, sc, steps, seed=s,
                                                  ndev=ndev)
        k_lats += kl
        k_fp += kf
        k_ref += kr
        k_drops += kd
        k_mem.append(km)
    t_kernel = time.time() - t0
    t0 = time.time()
    for s in range(seeds):
        m = RefModel(p, dict(fail_at), seed=1000 + s, nemesis=nem)
        m.run(steps)
        r_lats += m.detection_latencies()
        r_fp += m.n_false_dead
        r_ref += m.n_refuted
        alive = [i for i in range(n) if m._alive_truth(i)]
        r_mem.append(float(np.mean([m._member_count(i) / (n - 1)
                                    for i in alive])) if alive else 0.0)
    t_ref = time.time() - t0

    k = np.asarray(k_lats, float)
    r = np.asarray(r_lats, float)

    def pct(a, q):
        return float(np.percentile(a, q)) if len(a) else None

    def rel(kv, rv):
        if kv is None or rv is None or not rv:
            return None
        return round(abs(kv - rv) / rv, 4)

    return {
        "scenario": name,
        "description": sc.description,
        "n": n,
        "slots": slots,
        "seeds": seeds,
        "steps": steps,
        "samples": {"kernel": len(k), "refmodel": len(r)},
        "expected_events": expected,
        "completeness": {
            "kernel": round(len(k) / expected, 4) if expected else None,
            "refmodel": round(len(r) / expected, 4) if expected else None,
        },
        "kernel_slot_drops": k_drops,
        "detection_latency_rounds": {
            "kernel": {"mean": round(float(k.mean()), 2) if len(k) else None,
                       "p50": pct(k, 50), "p99": pct(k, 99)},
            "refmodel": {"mean": round(float(r.mean()), 2) if len(r) else None,
                         "p50": pct(r, 50), "p99": pct(r, 99)},
        },
        "relative_error": {
            "mean": rel(float(k.mean()) if len(k) else None,
                        float(r.mean()) if len(r) else None),
            "p50": rel(pct(k, 50), pct(r, 50)),
            "p99": rel(pct(k, 99), pct(r, 99)),
        },
        "false_dead": {"kernel": k_fp, "refmodel": r_fp},
        "refutes": {"kernel": k_ref, "refmodel": r_ref},
        # End-state membership recovery: after a heal/flap window closes
        # the membership view must converge back (>= 0.95 gates).
        "member_frac_end": {
            "kernel": round(float(np.mean(k_mem)), 4),
            "refmodel": round(float(np.mean(r_mem)), 4),
        },
        "lifeguard_envelope_rounds": [p.suspicion_min_rounds,
                                      p.suspicion_max_rounds],
        "wall_s": {"kernel": round(t_kernel, 1), "refmodel": round(t_ref, 1)},
    }


# -- join churn (gossip.html.markdown:10-43: joins propagate as
# gossiped alive messages; consumed by consul/leader.go:354-421) ------------


def run_join_config(n: int, n_joiners: int, n_victims: int, seeds: int,
                    loss: float = 0.0) -> dict:
    """Concurrent joins + failures, kernel vs oracle.

    Two statistics families, matched definitions in both models:
      - detection: latency percentiles + completeness for the victims,
        with join churn running concurrently (the same gates as the
        static-membership configs);
      - join propagation: rounds from a node's join until 95% of the
        eventual membership holds its alive@inc announcement (kernel:
        ``n_heard_alive`` on the JOIN slot; oracle: the incremental
        join-knowers set).  The 95%-of-(n - victims) target is shared;
        the small asymmetry (the oracle's knower set is monotone and
        may count observers that later die; the kernel counts current
        members only) biases both toward the same side well under the
        gate."""
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (NEVER, PHASE_DEAD, PHASE_JOIN,
                                          init_state, run_rounds)
    from consul_tpu.gossip.params import SwimParams
    from consul_tpu.gossip.refmodel import RefModel

    slots = max(64, loss_sized_slots(n, loss))
    p = SwimParams(n=n, slots=slots, probe_every=5, loss_rate=loss)
    spacing = max(5, p.suspicion_min_rounds // 4)
    # Joiners are the top ids (they start outside the pool); victims are
    # spread through the standing membership; the windows interleave.
    joiners = [n - 1 - i for i in range(n_joiners)]
    join_at = {j: 20 + i * spacing for i, j in enumerate(joiners)}
    victims = [(n // (n_victims + 1)) * (i + 1) for i in range(n_victims)]
    fail_at = {v: 30 + i * spacing for i, v in enumerate(victims)}
    steps = (max(max(join_at.values()), max(fail_at.values()))
             + p.slot_ttl_rounds + 8 * p.probe_every)
    target = 0.95 * (n - n_victims)

    fail = np.full(n, NEVER, np.int32)
    for v, t in fail_at.items():
        fail[v] = t
    join = np.full(n, NEVER, np.int32)
    for j, t in join_at.items():
        join[j] = t

    k_lats, r_lats, k_join, r_join = [], [], [], []
    k_fp = r_fp = k_drops = 0
    t0 = time.time()
    for s in range(seeds):
        st = init_state(p)._replace(member=jnp.asarray(join == NEVER))
        st, trace = run_rounds(st, jax.random.key(s), jnp.asarray(fail), p,
                               steps, trace=True,
                               join_round=jnp.asarray(join))
        slot_node = np.asarray(trace.slot_node)
        slot_dead = np.asarray(trace.slot_dead_round)
        slot_phase = np.asarray(trace.slot_phase)
        heard_alive = np.asarray(trace.n_heard_alive)
        for v, t_fail in fail_at.items():
            mask = ((slot_node == v) & (slot_dead >= t_fail)
                    & (slot_phase == PHASE_DEAD))
            if mask.any():
                k_lats.append(int(slot_dead[mask].min()) - t_fail)
        for j, t_join in join_at.items():
            jm = (slot_node == j) & (slot_phase == PHASE_JOIN)
            curve = np.where(jm, heard_alive, 0).max(axis=1)
            hit = np.nonzero(curve >= target)[0]
            if hit.size:
                k_join.append(int(hit[0]) + 1 - t_join)
        k_fp += int(st.n_false_dead)
        k_drops += int(st.drops)
    t_kernel = time.time() - t0
    t0 = time.time()
    for s in range(seeds):
        m = RefModel(p, dict(fail_at), seed=1000 + s,
                     join_tick=dict(join_at))
        m.run(steps)
        r_lats += m.detection_latencies()
        r_fp += m.n_false_dead
        for j, t_join in join_at.items():
            hits = [t for t, c in m.join_curve[j] if c >= target]
            if hits:
                r_join.append(hits[0] + 1 - t_join)
    t_ref = time.time() - t0

    k = np.asarray(k_lats, float)
    r = np.asarray(r_lats, float)

    def pct(a, q):
        return float(np.percentile(a, q)) if len(a) else None

    def rel(kv, rv):
        if kv is None or rv is None or not rv:
            return None
        return round(abs(kv - rv) / rv, 4)

    def m_(a):
        return round(float(np.mean(a)), 2) if len(a) else None

    expected = n_victims * seeds
    expected_joins = n_joiners * seeds
    return {
        "n": n,
        "loss_rate": loss,
        "slots": slots,
        "joiners_per_run": n_joiners,
        "victims_per_run": n_victims,
        "seeds": seeds,
        "completeness": {
            "kernel": round(len(k) / expected, 4) if expected else None,
            "refmodel": round(len(r) / expected, 4) if expected else None,
        },
        "kernel_slot_drops": k_drops,
        "detection_latency_rounds": {
            "kernel": {"mean": m_(k), "p50": pct(k, 50), "p99": pct(k, 99)},
            "refmodel": {"mean": m_(r), "p50": pct(r, 50), "p99": pct(r, 99)},
        },
        "relative_error": {
            "mean": rel(m_(k), m_(r)),
            "p50": rel(pct(k, 50), pct(r, 50)),
            "p99": rel(pct(k, 99), pct(r, 99)),
        },
        "false_dead": {"kernel": k_fp, "refmodel": r_fp},
        "join_spread_rounds_to_95pct": {
            "kernel": m_(k_join), "refmodel": m_(r_join),
            "relative_error": rel(m_(k_join), m_(r_join)),
            "completed": {"kernel": len(k_join), "refmodel": len(r_join),
                          "expected": expected_joins},
        },
        "wall_s": {"kernel": round(t_kernel, 1), "refmodel": round(t_ref, 1)},
    }


# -- event convergence (BASELINE config #3: "event convergence
# statistics match Serf") ---------------------------------------------------


def event_oracle_curve(n: int, fanout: int, budget: int, steps: int,
                       seed: int) -> np.ndarray:
    """Per-node discrete-event flood with stock-gossip semantics: every
    node that has the event pushes it to ``fanout`` UNIFORM random
    peers per round while its copy's age is within the transmit budget
    (iid targets — the behavior the kernel approximates with per-round
    circulant shifts).  Returns the coverage fraction per round [T]."""
    rng = np.random.default_rng(seed)
    receipt = np.full(n, -1, np.int64)
    receipt[rng.integers(n)] = 0  # origin fired before round 1
    out = np.empty(steps, np.float64)
    for t in range(1, steps + 1):
        senders = np.nonzero((receipt >= 0) & (t - 1 - receipt < budget))[0]
        if senders.size:
            tgt = rng.integers(0, n - 1, size=(senders.size, fanout))
            # shift to skip self (uniform over the other n-1 nodes)
            tgt = tgt + (tgt >= senders[:, None])
            fresh = tgt[receipt[tgt] < 0]
            receipt[fresh] = t
        out[t - 1] = np.count_nonzero(receipt >= 0) / n
    return out


def kernel_event_curve(p, steps: int, seed: int) -> np.ndarray:
    """Coverage curve [T] of one kernel-flooded event (slot 0)."""
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.events import (fire_events, init_events,
                                          run_event_rounds)

    st = init_events(p, slots=4)
    origin = int(jax.random.randint(jax.random.key(seed ^ 0x5EED), (),
                                    0, p.n))
    st = fire_events(st, jnp.asarray([origin], jnp.int32))
    alive = jnp.ones((p.n,), bool)
    _, cov = run_event_rounds(st, jax.random.key(seed), alive, p, steps)
    return np.asarray(cov)[:, 0]


def _rounds_to(curve: np.ndarray, frac: float) -> float:
    hit = np.nonzero(curve >= frac)[0]
    return float(hit[0] + 1) if hit.size else float("inf")


def run_event_config(n: int, seeds: int) -> dict:
    """Event-convergence comparison: kernel circulant flood vs the
    iid-target oracle.  Statistics: rounds to 50% / 99% coverage."""
    from consul_tpu.gossip.params import SwimParams
    p = SwimParams(n=n, slots=4, pushpull_every=0)
    budget = p.spread_budget_rounds
    # Flood completes in O(log_fanout n) + budget tail; 8x margin.
    steps = int(8 * (np.log(max(n, 2)) / np.log(p.fanout + 1) + budget))

    t0 = time.time()
    k50, k99, r50, r99 = [], [], [], []
    for s in range(seeds):
        kc = kernel_event_curve(p, steps, seed=s)
        k50.append(_rounds_to(kc, 0.5))
        k99.append(_rounds_to(kc, 0.99))
    t_kernel = time.time() - t0
    t0 = time.time()
    for s in range(seeds):
        oc = event_oracle_curve(n, p.fanout, budget, steps, seed=1000 + s)
        r50.append(_rounds_to(oc, 0.5))
        r99.append(_rounds_to(oc, 0.99))
    t_ref = time.time() - t0

    def m(a):
        a = [x for x in a if np.isfinite(x)]
        return round(float(np.mean(a)), 2) if a else None

    def rel(kv, rv):
        if kv is None or rv is None or not rv:
            return None
        return round(abs(kv - rv) / rv, 4)

    out = {
        "n": n,
        "seeds": seeds,
        "fanout": p.fanout,
        "transmit_budget_rounds": budget,
        "completed": {"kernel": int(np.sum(np.isfinite(k99))),
                      "oracle": int(np.sum(np.isfinite(r99)))},
        "rounds_to_50pct": {"kernel": m(k50), "oracle": m(r50),
                            "relative_error": rel(m(k50), m(r50))},
        "rounds_to_99pct": {"kernel": m(k99), "oracle": m(r99),
                            "relative_error": rel(m(k99), m(r99))},
        "wall_s": {"kernel": round(t_kernel, 1),
                   "oracle": round(t_ref, 1)},
    }
    return out
