"""The SWIM round kernel: failure detection + dissemination as batched array ops.

Re-design of the reference's gossip substrate (memberlist SWIM + Serf
dissemination; behavior contract at
``website/source/docs/internals/gossip.html.markdown:10-43``, consumed by
Consul at ``consul/server.go:257-273`` / ``consul/config.go:266-272``)
as a single jit-compiled synchronous-rounds step.

**State compression.**  A faithful N-node cluster has N distinct views —
an N×N belief matrix, hopeless at 1M nodes.  SWIM's structure makes the
compression exact enough for its statistics: all information about a
subject node travels as a small set of totally-ordered messages
(suspect@inc < dead < alive@inc+1 within one suspicion episode), so an
observer's belief about a subject is just "the highest message it has
heard, and when".  At any instant only nodes with an in-flight rumor
need tracking.  We therefore keep an S×N matrix over "subject slots":

    heard[s, i]  (uint8):  bits 7-6  msg   (0 none, 1 suspect, 2 dead, 3 refute)
                           bits 5-4  conf  (independent suspicion confirmations, Lifeguard)
                           bits 3-0  age   (rounds since this node heard the msg)

The bit layout makes "merge = numeric max" give message priority
ordering for scatter-marking; the gossip merge itself uses explicit
logic.  Slots are allocated when a probe failure starts a suspicion
episode, recycled after the episode resolves (dead / refuted) and its
verdict has disseminated; overflow is *counted* (``drops``), never
silent.

**Communication as rolls.**  Each round every node pushes its active
rumors to ``fanout`` peers.  The round's communication graph is
``fanout`` random circulant shifts redrawn per round (node ``i`` pushes
to ``i + o_f``), so the senders into node ``d`` are ``d - o_f`` —
delivery is ``fanout`` contiguous rolls along the observer axis, which
move at memory bandwidth where an arbitrary-permutation gather pays
~6.5ns per random index on TPU (see ``gossip_offsets``).

**Timers.**  One round = one gossip interval; each node probes once
every ``probe_every`` rounds, staggered in contiguous id blocks so a
fixed 1/probe_every of the cluster probes per round (the refmodel
staggers per-node probe phases the same way — memberlist probe timers
have random phase).  Suspicion timeouts follow Lifeguard
(params.timeout_table): all observers time from the episode start
(slot_start) — the first suspector's timer governs first-detection in
both models, so detection-time statistics are preserved (validated in
tests against the discrete-event reference model).

Known approximations vs stock memberlist: exactly-``fanout`` in-degree
per round with round-shared circulant shifts (targets correlated across
nodes within a round; each node's target sequence over rounds uniform)
instead of per-node Poisson(fanout) push; uniform
random probe targets instead of shuffled round-robin sweeps;
episode-start-based suspicion timers; confirmation counts capped at 3
and approximated by receipt rounds rather than distinct-origin tracking;
refutation is globally instantaneous (a refute cancels every observer's
pending dead declaration in the same round, rather than racing its
propagation against each observer's local timer — biases false-positive
counts low vs event-driven memberlist).
Each is quantified against the discrete-event reference model
(gossip/refmodel.py) by the cross-validation test tier.

**ICI sharding.**  ``run_rounds_sharded``/``swim_round_sharded`` run the
same round ``shard_map``-partitioned along the observer axis N: only the
``heard [S, N]`` belief matrix is sharded; every other register is
replicated and the few heard-derived quantities are ``psum``-merged (the
per-column contributions are disjoint, so the merge is exact and the
sharded kernel is bit-identical to the single-device one — the parity
tier asserts it).  See the "ICI sharding" section below for the layout
and the halo-exchange roll.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.gossip.nemesis import NemesisParams
from consul_tpu.gossip.params import SwimParams
from consul_tpu.obs.flight import N_COLS as _FLIGHT_COLS
from consul_tpu.obs.hist import LATENCY_BUCKETS as _HIST_LAT
from consul_tpu.obs.hist import SPREAD_BUCKETS as _HIST_SPREAD

MSG_NONE = 0
MSG_SUSPECT = 1
MSG_DEAD = 2
MSG_REFUTE = 3   # alive@inc: refutations AND join announcements

PHASE_FREE = 0
PHASE_SUSPECT = 1
PHASE_DEAD = 2
PHASE_REFUTED = 3
PHASE_JOIN = 4   # alive@inc dissemination for a node joining the pool

NEVER = np.int32(2**31 - 1)  # fail_round value for "never fails"

_MSG_SHIFT = 6
_CONF_SHIFT = 4
_CONF_MASK = 0x3
_AGE_MASK = 0xF


def _enc(msg: int, conf: int = 0, age: int = 0) -> int:
    return (msg << _MSG_SHIFT) | (conf << _CONF_SHIFT) | age


class SwimState(NamedTuple):
    """One LAN pool's protocol state. All arrays live in HBM."""

    round: jnp.ndarray          # i32 scalar — current gossip round
    heard: jnp.ndarray          # u8  [S, N] — per-(slot, observer) belief
    slot_node: jnp.ndarray      # i32 [S] — subject node id, -1 = free
    slot_phase: jnp.ndarray     # i32 [S] — PHASE_*
    slot_inc: jnp.ndarray       # i32 [S] — incarnation the episode speaks at:
                                #   suspicion slots record the inc under suspicion
                                #   (ordering within an episode is positional —
                                #   suspect < dead < refute — so the guard is
                                #   implicit); JOIN slots record the alive@inc the
                                #   join announces (bumped on every (re)join)
    slot_start: jnp.ndarray     # i32 [S] — round the episode began
    slot_nsusp: jnp.ndarray     # i32 [S] — independent suspicion initiators
    slot_dead_round: jnp.ndarray  # i32 [S] — round the episode's verdict was
                                #   declared (dead by timer, or refute), -1
                                #   while still in suspicion
    slot_of_node: jnp.ndarray   # i32 [N] — node -> slot, -1 = none
    incarnation: jnp.ndarray    # i32 [N] — per-node incarnation counter
    member: jnp.ndarray         # bool [N] — current cluster membership
    # Wrap convention for the i32 stat counters below (and HistBank):
    # they are monotone accumulators mod 2**32.  JAX defaults to 32-bit
    # integers and this repo never enables x64 (doing so would flip
    # every default dtype and break the bit-parity suite; jnp.int64
    # silently truncates back to int32 under the default config), so at
    # the paper's 1M-node/10k-rounds-per-second scale they WILL wrap on
    # long runs.  That is safe for every consumer: deltas taken in
    # int32/uint32 arithmetic (RoundTrace's `new - old` in swim_round,
    # HistRecorder's modular drain) stay exact across a wrap as long as
    # one drain interval accumulates < 2**31 — hours at paper scale vs
    # a sub-second drain cadence.  Absolute host-side reads are only
    # used by short-horizon tests/benches.  Flagged by vet O01; each
    # accumulation site carries a justified noqa.
    drops: jnp.ndarray          # i32 — suspicion initiations lost to full slots
    n_detected: jnp.ndarray     # i32 — true failures detected (at slot GC)
    sum_detect_rounds: jnp.ndarray  # i32 — sum of (dead_round - fail_round)
    n_false_dead: jnp.ndarray   # i32 — alive nodes declared dead
    n_refuted: jnp.ndarray      # i32 — episodes ended by refutation


def init_state(p: SwimParams) -> SwimState:
    S, N = p.slots, p.n
    return SwimState(
        round=jnp.int32(0),
        heard=jnp.zeros((S, N), jnp.uint8),
        slot_node=jnp.full((S,), -1, jnp.int32),
        slot_phase=jnp.zeros((S,), jnp.int32),
        slot_inc=jnp.zeros((S,), jnp.int32),
        slot_start=jnp.zeros((S,), jnp.int32),
        slot_nsusp=jnp.zeros((S,), jnp.int32),
        slot_dead_round=jnp.full((S,), -1, jnp.int32),
        slot_of_node=jnp.full((N,), -1, jnp.int32),
        incarnation=jnp.zeros((N,), jnp.int32),
        member=jnp.ones((N,), bool),
        drops=jnp.int32(0),
        n_detected=jnp.int32(0),
        sum_detect_rounds=jnp.int32(0),
        n_false_dead=jnp.int32(0),
        n_refuted=jnp.int32(0),
    )


class FlightRing(NamedTuple):
    """On-device flight-recorder ring: one i32 row of per-round counters
    (column layout = ``obs.flight.FLIGHT_COLS``) written per round at
    ``cursor % R`` INSIDE the scan body — the host drains it in
    amortized batches (gossip/plane.py), never per round."""

    rows: jnp.ndarray    # i32 [R, N_COLS]
    cursor: jnp.ndarray  # i32 scalar — total rows ever written


def init_flight(ring_rounds: int = 256) -> FlightRing:
    return FlightRing(rows=jnp.zeros((ring_rounds, _FLIGHT_COLS), jnp.int32),
                      cursor=jnp.int32(0))


class HistBank(NamedTuple):
    """On-device detection-latency observatory: cumulative fixed-bucket
    integer histograms accumulated INSIDE the scan body (bucket layouts
    documented in ``obs.hist``).  The latency banks are one round per
    bucket with a top overflow bucket — the host reconstructs the exact
    observation multiset below the overflow; the spread bank is
    log2-bucketed via integer bit_length (no float ops, so sharded and
    unsharded banks stay bit-identical)."""

    detect: jnp.ndarray  # i32 [LATENCY_BUCKETS] — fail_round -> dead verdict
    dwell: jnp.ndarray   # i32 [LATENCY_BUCKETS] — episode start -> verdict
    refute: jnp.ndarray  # i32 [LATENCY_BUCKETS] — episode start -> refute
    spread: jnp.ndarray  # i32 [SPREAD_BUCKETS] — verdict holders at slot GC


def init_hist() -> HistBank:
    return HistBank(detect=jnp.zeros((_HIST_LAT,), jnp.int32),
                    dwell=jnp.zeros((_HIST_LAT,), jnp.int32),
                    refute=jnp.zeros((_HIST_LAT,), jnp.int32),
                    spread=jnp.zeros((_HIST_SPREAD,), jnp.int32))


def _hist_add(bank: jnp.ndarray, mask: jnp.ndarray,
              val: jnp.ndarray) -> jnp.ndarray:
    """Scatter masked observations into a bank: value clipped into the
    top (overflow) bucket, unmasked lanes dropped out of range."""
    B = bank.shape[0]
    # noqa-justification: banks follow the SwimState wrap convention —
    # HistRecorder drains them with modular uint32 deltas, so a wrap
    # between drains is absorbed exactly.
    return bank.at[jnp.where(mask, jnp.clip(val, 0, B - 1), B)].add(  # noqa: O01 — wrap-aware host drain (obs/hist.py)
        1, mode="drop")


class NemState(NamedTuple):
    """Per-node Lifeguard local-health registers, threaded through the
    scan carry (like HistBank) when a nemesis scenario needs them
    (``NemesisParams.needs_state``).  Replicated under sharding — every
    update derives from replicated B-space probe lanes or psum-merged
    refute bits, so the sharded and single-device copies stay
    bit-identical (tests/test_shard_map_parity.py)."""

    lhm: jnp.ndarray     # i32 [N] — local-health multiplier, [0, lhm_max]
    streak: jnp.ndarray  # i32 [N] — consecutive direct-probe misses,
                         #   clamped at lhm_max + 1 (only the > compare
                         #   is read, and the clamp bounds the counter)


def init_nem_state(n: int) -> NemState:
    return NemState(lhm=jnp.zeros((n,), jnp.int32),
                    streak=jnp.zeros((n,), jnp.int32))


def _nem_group(nem: NemesisParams, n: int) -> jnp.ndarray:
    """Partition group bit per node, [n] i32 — derived inside the jit
    from statics only; bit-for-bit nemesis.group_of (the hash uses
    uint32 wraparound, identical in numpy and jnp)."""
    if nem.part_kind == "hash":
        ids = jnp.arange(n, dtype=jnp.uint32)
        return ((ids * jnp.uint32(2654435761)) >> 31).astype(jnp.int32)
    return (jnp.arange(n, dtype=jnp.int32) >= (n // 2)).astype(jnp.int32)


def _nem_in_window(nem: NemesisParams, rnd) -> jnp.ndarray:
    return (rnd >= nem.start) & (rnd < nem.stop)


def _nem_schedule(nem: NemesisParams, rnd, fail_round, join_round):
    """Apply the round's injection schedule to the ground-truth inputs
    (the kills half of the catalog; the loss half lives in the probe
    and dissemination phases).  Pure function of replicated [N] arrays
    and statics — shard-safe by construction.

    - flapping: the down phase overrides ``fail_round`` to "failed
      now"; the up phase re-arms ``join_round`` so the node rejoins via
      the ordinary join tick (incarnation bump + alive@inc flood).
    - heal_rejoin: after the window closes every node is join-pending —
      members are ignored by the join tick's ``~member`` gate, so only
      falsely-declared-dead nodes actually rejoin."""
    if nem.has_flap:
        n = fail_round.shape[0]
        ids = jnp.arange(n, dtype=jnp.int32)
        flap = (ids >= nem.flap_lo) & (ids < nem.flap_hi)
        down_phase = ((rnd - nem.start) % nem.flap_period) >= nem.flap_up
        down = flap & _nem_in_window(nem, rnd) & down_phase
        fail_round = jnp.where(down, jnp.minimum(fail_round, rnd),
                               fail_round)
        join_round = jnp.where(flap & ~down,
                               jnp.minimum(join_round, rnd), join_round)
    if nem.heal_rejoin:
        join_round = jnp.minimum(join_round, jnp.int32(nem.stop))
    return fail_round, join_round


_AGE_FRESH = 0xF  # sentinel: written by this round's probe marks, pre-aging


def _age_tick(heard: jnp.ndarray) -> jnp.ndarray:
    """Advance every in-flight rumor's age by one round.

    Runs AFTER the probe tick (so the whole age+gossip+timers tail can
    be skipped when no episode is active): a mark the probe just wrote
    carries the ``_AGE_FRESH`` sentinel and ages to 0 here — i.e. it is
    brand new this round — while real ages saturate at 14."""
    msg = heard >> _MSG_SHIFT
    age = heard & _AGE_MASK
    new_age = jnp.where(age == _AGE_FRESH, jnp.uint8(0),
                        jnp.minimum(age + 1, jnp.uint8(_AGE_MASK - 1)))
    aged = (heard & ~jnp.uint8(_AGE_MASK)) | new_age.astype(jnp.uint8)
    return jnp.where(msg > 0, aged, heard)


def alloc_free_slots(free: jnp.ndarray, want: jnp.ndarray):
    """Rank the True entries of ``want`` onto the free slots of ``free``
    in ascending slot order — the shared compaction behind suspicion
    slots (probe tick), JOIN slots, and event slots (events.fire_events).
    Returns ``(can, slot_ids, sidx)``: ``can`` marks served entries,
    ``slot_ids`` their slots, and ``sidx`` equals the slot id for served
    entries and ``len(free)`` (out-of-range, for ``mode='drop'``
    scatters) otherwise."""
    S = free.shape[0]
    free_order = jnp.argsort(jnp.where(free, 0, 1),
                             stable=True).astype(jnp.int32)
    n_free = jnp.sum(free)
    rank = jnp.cumsum(want.astype(jnp.int32)) - 1
    can = want & (rank < n_free)
    slot_ids = free_order[jnp.clip(rank, 0, S - 1)]
    sidx = jnp.where(can, slot_ids, S)
    return can, slot_ids, sidx


# ---------------------------------------------------------------------------
# ICI sharding (shard_map along the observer axis N)
#
# Layout: ONLY the [S, N] belief matrix is sharded (P devices, L = N/P
# contiguous observer columns per shard).  Everything else — the S-space
# slot registers, the [N] per-node registers (slot_of_node, incarnation,
# member), mf, the PRNG key, and the scalar counters — is REPLICATED:
# every write to those derives from replicated inputs plus the few
# heard-derived quantities below, which are psum-combined.  Each observer
# column is owned by exactly one shard, so the psum contributions are
# disjoint integers — the merge is exact and the sharded round is
# bit-identical to the single-device one (tests/test_shard_map_parity.py).
#
# Communication per round: each circulant delivery ``roll(packed, o)``
# becomes a shard-local roll plus a log2(P)-hop ppermute halo exchange
# (_roll_sharded); the probe tick's contiguous prober-block window is
# read with zero-padded local slices + one psum (_win_read) and written
# back shard-locally (_win_write); _finish_round psums the subjects'
# own-belief bytes and the per-slot timer-fired bits.  All lax.cond
# predicates (any_join, n_active, push/pull cadence) are replicated, so
# every shard takes the same branch and the collective schedules line up
# (check_rep=False — replication is by construction, not inferred).
# ---------------------------------------------------------------------------

_SHARD_AXIS = "ici"


class _ShardCtx(NamedTuple):
    """Static sharding context threaded through the round phases.
    ``None`` everywhere means the unchanged single-device lowering."""

    ndev: int   # devices along the observer axis
    L: int      # observer columns per shard (N // ndev)


def _sc_base(sc: _ShardCtx) -> jnp.ndarray:
    """This shard's first global observer column (traced)."""
    return jax.lax.axis_index(_SHARD_AXIS).astype(jnp.int32) * sc.L


def _sloc(sc: _ShardCtx, v: jnp.ndarray) -> jnp.ndarray:
    """Local [L] slice of a replicated [N] per-node vector."""
    return jax.lax.dynamic_slice(v, (_sc_base(sc),), (sc.L,))


def _sloc_roll(sc: _ShardCtx, v: jnp.ndarray, o) -> jnp.ndarray:
    """Local [L] slice of ``jnp.roll(v, o)`` for a replicated [N]
    vector — a dynamic slice of the doubled vector, never a gather."""
    n = v.shape[0]
    v2 = jnp.concatenate([v, v])
    return jax.lax.dynamic_slice(v2, ((_sc_base(sc) - o) % n,), (sc.L,))


def _roll_sharded(sc: _ShardCtx, x: jnp.ndarray, o) -> jnp.ndarray:
    """Global ``jnp.roll(x, o, axis=-1)`` of an observer-sharded array.

    The traced global shift decomposes into a shard-local roll by
    ``o mod L`` plus a whole-shard rotation by ``o // L`` — done as a
    binary-decomposed chain of log2(P) *conditional* ppermutes (the
    condition selects results, never collectives, so the ppermute
    schedule is static and identical on every shard) — plus one
    neighbor exchange supplying the ``o mod L`` halo columns that
    crossed the shard boundary."""
    L, ndev = sc.L, sc.ndev
    o = o % (L * ndev)
    q, r = o // L, o % L
    y = jnp.roll(x, r, axis=-1)
    step = 1
    while step < ndev:
        perm = [(i, (i + step) % ndev) for i in range(ndev)]
        shifted = jax.lax.ppermute(y, _SHARD_AXIS, perm)
        y = jnp.where((q // step) % 2 == 1, shifted, y)
        step *= 2
    nxt = jax.lax.ppermute(y, _SHARD_AXIS,
                           [(i, (i + 1) % ndev) for i in range(ndev)])
    return jnp.where(jnp.arange(L) < r, nxt, y)


def _win_read(sc: _ShardCtx, h: jnp.ndarray, blk, B: int) -> jnp.ndarray:
    """Replicated [S, B] window ``heard[:, blk:blk+B]`` of the sharded
    matrix (the window never wraps: blk = (rnd % probe_every) * B with
    N = B * probe_every, enforced by _check_shardable).  Each shard
    slices its overlap out of a zero-padded copy and the psum merges
    the disjoint contributions exactly.  The explicit clip is
    load-bearing: dynamic_slice normalizes NEGATIVE starts numpy-style
    (adding the dim size) *before* clamping, which would alias an
    empty-overlap shard's slice back onto real data."""
    S = h.shape[0]
    z = jnp.zeros((S, B), h.dtype)
    Z = jnp.concatenate([z, h, z], axis=1)
    start = jnp.clip(B + blk - _sc_base(sc), 0, B + sc.L)
    part = jax.lax.dynamic_slice(Z, (jnp.int32(0), start), (S, B))
    return jax.lax.psum(part.astype(jnp.int32), _SHARD_AXIS).astype(h.dtype)


def _win_write(sc: _ShardCtx, h: jnp.ndarray, win: jnp.ndarray, blk,
               B: int) -> jnp.ndarray:
    """Write a replicated [S, B] window into cols [blk, blk+B) of the
    sharded matrix: each shard overwrites exactly the columns it owns.
    No collective; same clip caveat as _win_read."""
    S = h.shape[0]
    zl = jnp.zeros((S, sc.L), win.dtype)
    Zw = jnp.concatenate([zl, win, zl], axis=1)
    base = _sc_base(sc)
    start = jnp.clip(sc.L + base - blk, 0, B + sc.L)
    part = jax.lax.dynamic_slice(Zw, (jnp.int32(0), start), (S, sc.L))
    g = base + jnp.arange(sc.L, dtype=jnp.int32)
    inw = (g >= blk) & (g < blk + B)
    return jnp.where(inw[None, :], part, h)


def _join_tick(p: SwimParams, rnd, carry, join_round, fail_round, sc=None):
    """Activate pending joins on-device (memberlist: a join IS an
    alive@inc message gossiped like any rumor — behavior contract
    ``website/source/docs/internals/gossip.html.markdown:10-43``,
    consumed by the leader's join path ``consul/leader.go:354-421``).

    A node with ``join_round[i] <= rnd`` that is not yet a member (and
    is not already dead by ground truth) is PENDING: when it wins a
    rumor slot, membership flips, the incarnation bumps (alive@inc
    supersedes any prior suspect/dead at the old inc — memberlist
    aliveNode), any stale episode about the id is cleared, and the
    PHASE_JOIN slot's alive rumor (MSG_REFUTE — the same message class
    a refutation floods) disseminates through the ordinary gossip path.

    Join bursts are a retry queue, not a loss: at most one join per
    segmented-min segment wins a slot per round; the rest stay pending
    and retry next round (a join without its announcement would be a
    member nobody can learn about — memberlist never loses the alive
    message, it queues it).  The deferral is observable in the trace
    as slot_start - join_round lag."""
    (heard, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
     slot_dead_round, slot_of_node, incarnation, member, drops) = carry
    N, S = p.n, p.slots

    pending = (join_round <= rnd) & ~member & (fail_round > rnd)

    # JOIN-slot allocation: segmented-min compaction, the probe tick's
    # trick — O(N) work, no sort, no N-scatter.
    masked = jnp.where(pending, jnp.arange(N, dtype=jnp.int32), N)
    kk = min(S, N)
    GB = -(-N // kk)
    pad = kk * GB - N
    masked_p = (jnp.concatenate([masked, jnp.full((pad,), N, jnp.int32)])
                if pad else masked)
    cand = jnp.min(masked_p.reshape(kk, GB), axis=1)
    in_dom = cand < N
    can_k, slot_k, sidx = alloc_free_slots(slot_node < 0, in_dom)
    cand_c = jnp.clip(cand, 0, N - 1)

    # Winners in N-space: these ids join THIS round.
    joining = jnp.zeros((N,), bool).at[
        jnp.where(can_k, cand_c, N)].set(True, mode="drop")
    incarnation = incarnation + joining.astype(jnp.int32)
    member = member | joining

    # Clear any stale episode about a rejoining winner (e.g. a dead
    # verdict whose slot has not yet been GC'd).
    node_c0 = jnp.clip(slot_node, 0, N - 1)
    stale = (slot_node >= 0) & joining[node_c0]
    heard = jnp.where(stale[:, None], jnp.uint8(0), heard)
    slot_of_node = slot_of_node.at[jnp.where(stale, node_c0, N)].set(
        -1, mode="drop")
    slot_node = jnp.where(stale, -1, slot_node)
    slot_phase = jnp.where(stale, PHASE_FREE, slot_phase)
    slot_dead_round = jnp.where(stale, -1, slot_dead_round)

    slot_node = slot_node.at[sidx].set(cand_c, mode="drop")
    slot_phase = slot_phase.at[sidx].set(PHASE_JOIN, mode="drop")
    slot_inc = slot_inc.at[sidx].set(incarnation[cand_c], mode="drop")
    slot_start = slot_start.at[sidx].set(rnd, mode="drop")
    slot_nsusp = slot_nsusp.at[sidx].set(0, mode="drop")
    # The join IS the episode's verdict: the slot lives only for the
    # alive rumor's dissemination window (verdict-done GC).
    slot_dead_round = slot_dead_round.at[sidx].set(rnd, mode="drop")
    slot_of_node = slot_of_node.at[jnp.where(can_k, cand_c, N)].set(
        slot_k, mode="drop")
    # The joiner seeds its own announcement flood.  Sharded: the seed
    # column belongs to exactly one shard — the others drop the write.
    if sc is None:
        heard = heard.at[sidx, cand_c].set(
            jnp.uint8(_enc(MSG_REFUTE, age=_AGE_FRESH)), mode="drop")
    else:
        base = _sc_base(sc)
        owned = (cand_c >= base) & (cand_c < base + sc.L)
        heard = heard.at[jnp.where(owned, sidx, S),
                         jnp.clip(cand_c - base, 0, sc.L - 1)].set(
            jnp.uint8(_enc(MSG_REFUTE, age=_AGE_FRESH)), mode="drop")

    return (heard, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
            slot_dead_round, slot_of_node, incarnation, member, drops)


def _block_size(p: SwimParams) -> int:
    """Probers per round under staggering: each node probes once per
    ``probe_every`` rounds, spread across rounds in contiguous id
    blocks (the refmodel staggers per-node probe phases the same way,
    refmodel.py probe_offset)."""
    return max(1, -(-p.n // p.probe_every))


def _probe_tick(p: SwimParams, rnd, keys, mf, state_tuple, sc=None,
                nem=None, nem_state=None):
    """One round's probe slice: direct probe -> k indirect probes ->
    suspicion initiation for this round's prober block (reference
    per-node behavior: memberlist probe cycle as configured at
    consul/config.go:266-272, with per-node stagger).

    ``nem``/``nem_state`` (Python-level statics, None = compiled out,
    bit-identical to the baseline): a nemesis schedule adds cross-group
    drop legs to the probe round-trips, spurious reply drops for
    degraded observers, and — when ``nem_state`` is threaded — the
    Lifeguard local-health-multiplier dynamics that suppress a degraded
    observer's false suspicions.  Returns ``(carry, probe_stats)``, or
    ``(carry, probe_stats, nem_state)`` when ``nem_state`` is threaded.

    ``mf`` packs membership and ground truth into one readable i32:
    ``member ? fail_round : -1`` — so ``mf[x] > rnd`` is alive-member
    and ``mf[x] >= 0`` is member, one read instead of two.

    Targets and helpers are circulant like the gossip graph
    (``tgt = pid + o`` with fresh per-round offsets): each prober's
    target sequence over cycles is uniform, and within one round the
    prober block sweeps a contiguous shifted block — closer to
    memberlist's shuffled round-robin sweep than iid uniform draws, and
    every membership lookup becomes a slice of a rolled array instead
    of a ~6.5ns/index random gather (tools/profile_kernel.py).  Helper
    collision with the target has probability k/N — negligible,
    accepted."""
    (heard, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
     slot_dead_round, slot_of_node, incarnation, member, drops) = state_tuple
    k_t, k_dl, _k_h, k_hl = keys
    N, S = p.n, p.slots
    B = _block_size(p)

    # This round's probers: block (rnd % probe_every); ids >= N are
    # padding lanes on the final block and initiate nothing.
    blk = (rnd % p.probe_every) * B
    pid = blk + jnp.arange(B, dtype=jnp.int32)
    pid_c = jnp.minimum(pid, N - 1)
    pvalid = pid < N

    # mf doubled once: every shifted-block read below is a dynamic
    # slice of it (wrap-around included), never a random gather.
    mf2 = jnp.concatenate([mf, mf])

    def _mf_block(offset):
        return jax.lax.dynamic_slice(mf2, ((blk + offset) % N,), (B,))

    # Direct-probe target: pid + o_t.  Offsets in [1, N-1]: 0 would be
    # a self-probe.
    offs = jax.random.randint(k_t, (1 + p.indirect_k,), 1, N, jnp.int32)
    tgt = (pid_c + offs[0]) % N
    prober_ok = pvalid & (jax.lax.dynamic_slice(mf2, (blk,), (B,)) > rnd)
    mf_t = _mf_block(offs[0])
    tgt_member = mf_t >= 0
    tgt_alive = mf_t > rnd

    # -- nemesis probe legs (statics; compiled out when nem is None).
    # All draws are B-space off the previously-unused _k_h probe key —
    # replicated under sharding, and the baseline key schedule (k_t,
    # k_dl, k_hl, k_gossip, ...) is untouched either way.
    dir_nem_drop = jnp.zeros((B,), bool)
    degraded = jnp.zeros((B,), bool)
    if nem is not None and (nem.has_partition or nem.has_degraded):
        k_np, k_no, k_nip, k_nio = jax.random.split(_k_h, 4)
        in_win = _nem_in_window(nem, rnd)
        if nem.has_partition:
            grp = _nem_group(nem, N)
            grp2 = jnp.concatenate([grp, grp])

            def _grp_block(offset):
                return jax.lax.dynamic_slice(grp2, ((blk + offset) % N,),
                                             (B,))

            g_p = jax.lax.dynamic_slice(grp2, (blk,), (B,))
            g_t = _grp_block(offs[0])
            cross_t = g_p != g_t
            # A probe round-trip crosses both directions once, so the
            # drop probability is direction-independent (nemesis.py).
            p_rt = nem.p_roundtrip
            u_np = jax.random.uniform(k_np, (B,))
            dir_nem_drop = in_win & cross_t & (u_np < p_rt)
        if nem.has_degraded:
            degraded = (in_win & (pid >= nem.obs_lo) & (pid < nem.obs_hi))
            u_no = jax.random.uniform(k_no, (B,))
            dir_nem_drop = dir_nem_drop | (degraded
                                           & (u_no < nem.p_obs_miss))

    u = jax.random.uniform(k_dl, (B,))
    direct_fail = tgt_member & (~tgt_alive | (u < p.p_direct_fail_alive)
                                | dir_nem_drop)

    if p.indirect_k:
        hu = jax.random.uniform(k_hl, (B, p.indirect_k))
        helper_alive = jnp.stack(
            [_mf_block(offs[1 + j]) > rnd for j in range(p.indirect_k)], axis=1)
        ind_ok = (helper_alive
                  & tgt_alive[:, None] & tgt_member[:, None]
                  & (hu >= p.p_indirect_fail_alive))
        if nem is not None and nem.has_partition:
            # Indirect legs: prober<->helper and helper<->target are
            # each a cross-or-not round trip; one draw per helper at
            # the combined drop probability (distributionally identical
            # to independent per-leg draws — the refmodel mirrors the
            # same combination).
            g_h = jnp.stack([_grp_block(offs[1 + j])
                             for j in range(p.indirect_k)], axis=1)
            n_cross = ((g_p[:, None] != g_h).astype(jnp.int32)
                       + (g_h != g_t[:, None]).astype(jnp.int32))
            p_rt1 = nem.p_roundtrip
            p_rt2 = 1.0 - (1.0 - p_rt1) * (1.0 - p_rt1)
            p_ind = jnp.where(n_cross == 0, 0.0,
                              jnp.where(n_cross == 1, p_rt1, p_rt2))
            hu_p = jax.random.uniform(k_nip, (B, p.indirect_k))
            ind_ok = ind_ok & ~(in_win & (hu_p < p_ind))
        if nem is not None and nem.has_degraded:
            # A degraded prober also mishandles replies relayed back by
            # its helpers — Lifeguard's slow-observer case.
            hu_o = jax.random.uniform(k_nio, (B, p.indirect_k))
            ind_ok = ind_ok & ~(degraded[:, None]
                                & (hu_o < nem.p_obs_miss))
        rescued = jnp.any(ind_ok, axis=1)
    else:
        rescued = jnp.zeros((B,), bool)
    init = prober_ok & direct_fail & ~rescued

    # Don't re-suspect a target this prober already believes dead.
    # ``aligned`` (N = probe_every * B, true for every power-of-ten-ish
    # production size and the crossval configs): prober columns are one
    # contiguous block, so per-prober belief reads/writes are a dynamic
    # slice + one-hot row select instead of ~6.5ns/index 2D gathers.
    aligned = (N == B * p.probe_every)
    srow = jnp.arange(S, dtype=jnp.int32)

    def _row_pick(hblk, rows):
        sel = srow[:, None] == rows[None, :]
        return jnp.max(jnp.where(sel, hblk, jnp.uint8(0)), axis=0)

    s2 = jnp.concatenate([slot_of_node, slot_of_node])
    s_t = jax.lax.dynamic_slice(s2, ((blk + offs[0]) % N,), (B,))
    if sc is not None:
        # Sharded (requires aligned — _check_shardable): one psum
        # replicates the window; it is reused below for the post-rearm
        # read (only the rearm clear touches heard in between, and
        # rearm is replicated — the local recompute is exact).
        hblk_pre = _win_read(sc, heard, blk, B)
        cur = _row_pick(hblk_pre, jnp.clip(s_t, 0, S - 1))
    elif aligned:
        cur = _row_pick(jax.lax.dynamic_slice(heard, (0, blk), (S, B)),
                        jnp.clip(s_t, 0, S - 1))
    else:
        cur = heard[jnp.clip(s_t, 0, S - 1), pid_c]
    init = init & ~((s_t >= 0) & ((cur >> _MSG_SHIFT) == MSG_DEAD))

    # -- Lifeguard local-health multiplier (static; compiled out unless
    # the scenario threads NemState).  A prober only initiates suspicion
    # after more consecutive direct misses than its current LHM — with
    # LHM 0 the gate is `streak >= 1`, true for every miss, so the
    # baseline dynamics are bit-identical.  LHM rises on NACK-style
    # evidence (direct miss while helpers vouch for the target: the
    # observer, not the target, is the problem) and on being refuted
    # (_finish_round); it falls on clean probe success.  All lanes are
    # replicated B-space values, so the scatters below are shard-exact.
    if nem is not None and nem_state is not None:
        lhm, streak = nem_state
        lhm2 = jnp.concatenate([lhm, lhm])
        streak2 = jnp.concatenate([streak, streak])
        lhm_b = jax.lax.dynamic_slice(lhm2, (blk,), (B,))
        streak_b = jax.lax.dynamic_slice(streak2, (blk,), (B,))
        miss = prober_ok & tgt_member & direct_fail
        streak_new = jnp.where(
            miss, jnp.minimum(streak_b + 1, nem.lhm_max + 1), 0)
        init = init & (streak_new > lhm_b)
        lhm_up = miss & rescued
        lhm_dn = prober_ok & tgt_member & ~direct_fail
        lhm_new = jnp.clip(lhm_b + lhm_up.astype(jnp.int32)
                           - lhm_dn.astype(jnp.int32), 0, nem.lhm_max)
        widx = jnp.where(pvalid, pid, N)
        lhm = lhm.at[widx].set(lhm_new, mode="drop")
        streak = streak.at[widx].set(streak_new, mode="drop")
        nem_state = NemState(lhm=lhm, streak=streak)

    # All slot bookkeeping below runs in B-space (this round's probers)
    # and S-space — never N-space.  The previous formulation scattered
    # per-target counts into an N-vector and ranked it with top_k(N);
    # at 1M nodes those two ops dominated the whole probe tick
    # (~25 ms/round on a v5e — see tools/profile_kernel.py).

    node_c = jnp.clip(slot_node, 0, N - 1)
    valid = slot_node >= 0

    # Circulant targets are DISTINCT within a round (tgt = pid + o over
    # distinct pids), so a slot's subject has at most one initiator this
    # round: its would-be prober is i = (subject - blk - o) mod N, an
    # S-sized lookup into ``init`` — no S×B compare, no N-scatter.
    init_i = init.astype(jnp.int32)
    i_s = (node_c - blk - offs[0]) % N
    in_blk = valid & (i_s < B)
    add_here = jnp.where(in_blk, init_i[jnp.minimum(i_s, B - 1)], 0)
    slot_want = add_here > 0

    # Existing suspect episodes absorb new initiators.
    slot_nsusp = jnp.where((slot_phase == PHASE_SUSPECT) & slot_want,
                           slot_nsusp + add_here, slot_nsusp)

    # A refuted (or freshly-joined) episode whose subject fails probes
    # re-arms as a suspicion at the bumped incarnation (memberlist:
    # suspect at inc >= alive inc supersedes the alive).
    rearm = (((slot_phase == PHASE_REFUTED) | (slot_phase == PHASE_JOIN))
             & slot_want)
    slot_phase = jnp.where(rearm, PHASE_SUSPECT, slot_phase)
    slot_inc = jnp.where(rearm, incarnation[node_c], slot_inc)
    slot_start = jnp.where(rearm, rnd, slot_start)
    slot_nsusp = jnp.where(rearm, add_here, slot_nsusp)
    slot_dead_round = jnp.where(rearm, -1, slot_dead_round)
    heard = jnp.where(rearm[:, None], jnp.uint8(0), heard)

    # Allocate fresh slots: needy targets (distinct by construction)
    # are compacted to kk candidates with a segmented min — one winner
    # per contiguous prober segment, O(B) work (a top_k/sort of the
    # 200k-prober block costs several ms on the VPU).  A second needer
    # in the same segment waits for the subject's next probe cycle —
    # the same deferral as losing the slot race, counted in ``drops``.
    need_b = init & (s_t < 0) & (mf_t >= 0)
    masked = jnp.where(need_b, tgt, N)
    kk = min(S, N, B)
    GB = -(-B // kk)
    pad_b = kk * GB - B
    masked_p = (jnp.concatenate([masked, jnp.full((pad_b,), N, jnp.int32)])
                if pad_b else masked)
    cand = jnp.min(masked_p.reshape(kk, GB), axis=1)
    in_dom = cand < N
    can_k, slot_k, sidx = alloc_free_slots(~valid, in_dom)
    cand_c = jnp.clip(cand, 0, N - 1)
    slot_node = slot_node.at[sidx].set(cand_c, mode="drop")
    slot_phase = slot_phase.at[sidx].set(PHASE_SUSPECT, mode="drop")
    slot_inc = slot_inc.at[sidx].set(incarnation[cand_c], mode="drop")
    slot_start = slot_start.at[sidx].set(rnd, mode="drop")
    # Exactly one initiator per distinct target this round.
    slot_nsusp = slot_nsusp.at[sidx].set(1, mode="drop")
    slot_dead_round = slot_dead_round.at[sidx].set(-1, mode="drop")
    slot_of_node = slot_of_node.at[jnp.where(can_k, cand_c, N)].set(
        slot_k, mode="drop")
    # Drop accounting: needy targets that found no free slot this round
    # (they re-initiate on a later probe cycle while the subject keeps
    # failing probes; the counter measures slot pressure).
    n_need = jnp.sum(need_b.astype(jnp.int32))
    served = jnp.sum(can_k.astype(jnp.int32))
    drops = drops + (n_need - served)  # noqa: O01 — monotone mod 2**32 (SwimState wrap convention); consumers take i32 deltas

    # Initiators record their own suspicion with a *fresh* age so the
    # rumor re-enters circulation (memberlist re-enqueues the suspect
    # broadcast on every independent suspicion — this is what carries
    # confirmations outward and shrinks the Lifeguard timeout).
    s2b = jnp.concatenate([slot_of_node, slot_of_node])
    s_t2 = jax.lax.dynamic_slice(s2b, ((blk + offs[0]) % N,), (B,))
    rows2 = jnp.clip(s_t2, 0, S - 1)
    if sc is not None:
        # Post-rearm window, recomputed from the pre-rearm psum (saves
        # a collective; exact — see above).  Write-back is shard-local.
        hblk = jnp.where(rearm[:, None], jnp.uint8(0), hblk_pre)
        cur2 = _row_pick(hblk, rows2)
        mark_ok = init & (s_t2 >= 0) & ((cur2 >> _MSG_SHIFT) <= MSG_SUSPECT)
        fresh = (jnp.uint8(_enc(MSG_SUSPECT, age=_AGE_FRESH))
                 | (cur2 & jnp.uint8(_CONF_MASK << _CONF_SHIFT)))
        sel = (srow[:, None] == rows2[None, :]) & mark_ok[None, :]
        heard = _win_write(sc, heard, jnp.where(sel, fresh[None, :], hblk),
                           blk, B)
    elif aligned:
        hblk = jax.lax.dynamic_slice(heard, (0, blk), (S, B))
        cur2 = _row_pick(hblk, rows2)
        mark_ok = init & (s_t2 >= 0) & ((cur2 >> _MSG_SHIFT) <= MSG_SUSPECT)
        fresh = (jnp.uint8(_enc(MSG_SUSPECT, age=_AGE_FRESH))
                 | (cur2 & jnp.uint8(_CONF_MASK << _CONF_SHIFT)))
        sel = (srow[:, None] == rows2[None, :]) & mark_ok[None, :]
        heard = jax.lax.dynamic_update_slice(
            heard, jnp.where(sel, fresh[None, :], hblk), (0, blk))
    else:
        cur2 = heard[rows2, pid_c]
        mark_ok = init & (s_t2 >= 0) & ((cur2 >> _MSG_SHIFT) <= MSG_SUSPECT)
        fresh = (jnp.uint8(_enc(MSG_SUSPECT, age=_AGE_FRESH))
                 | (cur2 & jnp.uint8(_CONF_MASK << _CONF_SHIFT)))
        heard = heard.at[jnp.where(mark_ok, s_t2, S), pid_c].set(
            fresh, mode="drop")

    # Flight-recorder observables (all B-space reductions, bytes each;
    # XLA dead-code-eliminates them when the caller drops the tuple —
    # collect=False rounds pay nothing).
    probe_stats = (
        jnp.sum(prober_ok.astype(jnp.int32)),                 # probes fired
        jnp.sum((prober_ok & direct_fail).astype(jnp.int32)),  # acks missed
        jnp.sum((prober_ok & direct_fail                       # indirect
                 & tgt_member).astype(jnp.int32)),             #   escalations
        jnp.sum(init.astype(jnp.int32)),                       # suspicions
    )
    out_carry = (heard, slot_node, slot_phase, slot_inc, slot_start,
                 slot_nsusp, slot_dead_round, slot_of_node, incarnation,
                 member, drops)
    if nem_state is not None:
        return out_carry, probe_stats, nem_state
    return out_carry, probe_stats


@functools.partial(jax.jit, static_argnames=("p",),
                   donate_argnames=("state",))
def swim_round(state: SwimState, base_key: jax.Array, fail_round: jnp.ndarray,
               p: SwimParams,
               join_round: jnp.ndarray | None = None) -> SwimState:
    """Advance the pool by one gossip round.

    ``state`` is DONATED: the 64 MB-at-1M ``heard`` matrix is updated
    in place instead of copied per dispatch.  Callers must rebind
    (``state = swim_round(state, ...)``) and never reuse the argument.

    ``join_round`` (optional, [N] i32, NEVER = present from start):
    nodes whose entry equals the current round join the pool this round
    — see ``_join_tick``.  ``None`` compiles the join machinery out
    entirely (the bench regimes and static-membership sims pay zero)."""
    return _swim_round_impl(state, base_key, fail_round, p, join_round,
                            collect=False)[0]


def swim_round_hist(state: SwimState, base_key: jax.Array,
                    fail_round: jnp.ndarray, p: SwimParams, hist: HistBank,
                    join_round: jnp.ndarray | None = None):
    """One round threading the observatory banks: ``(state, hist)``.

    NOT jitted — composes inside outer jits (multidc_round's per-DC
    loop) exactly like ``sharded_round_callable``; jit'd callers own
    donation."""
    out = _swim_round_impl(state, base_key, fail_round, p, join_round,
                           collect=False, hist=hist)
    return out[0], out[2]


def _swim_round_impl(state: SwimState, base_key: jax.Array,
                     fail_round: jnp.ndarray, p: SwimParams,
                     join_round: jnp.ndarray | None, collect: bool,
                     sc: _ShardCtx | None = None,
                     hist: HistBank | None = None,
                     nem: NemesisParams | None = None,
                     nem_state: NemState | None = None):
    """One round + (optionally) its flight-recorder row + histograms.

    ``collect`` is a PYTHON-level static: False compiles exactly the
    old round (the stats tuple is dropped and DCE'd — bit-identical
    states, zero cost); True additionally returns one i32[N_COLS] row
    of per-round counters (column layout = obs.flight.FLIGHT_COLS).
    The only S×N-sized extra work is the dissemination-bytes
    reduction, and it sits behind the same ``n_active > 0`` cond as
    the round tail — a quiescent (healthy) round never touches the
    belief matrix for it.

    ``hist`` (optional HistBank, also Python-level static): thread the
    observatory banks through the round — _finish_round accumulates at
    the verdict/GC sites, a quiescent round passes them through
    untouched (no episodes -> nothing to observe).

    ``nem`` (optional NemesisParams, static): apply a nemesis injection
    schedule — kill/flap/heal rewrites of the ground-truth inputs here,
    cross-partition drop legs in the probe/gossip/push-pull phases, and
    (with ``nem_state``) the Lifeguard LHM dynamics.  ``None`` compiles
    every injection point out — bit-identical to the baseline round.

    Returns ``(state, row, hist, nem_state)``; legs are None when
    compiled out."""
    rnd = state.round
    key = jax.random.fold_in(base_key, rnd)
    k_probe = jax.random.split(jax.random.fold_in(key, 1), 4)
    k_gossip = jax.random.fold_in(key, 2)

    N, S = p.n, p.slots
    if nem is not None:
        # The kills half of the schedule: flap square waves and the
        # post-heal rejoin rewrite fail_round/join_round before any
        # phase reads them.
        if nem.needs_join and join_round is None:
            raise ValueError(
                f"nemesis scenario {nem.scenario!r} rewrites join_round; "
                "pass a join_round array (all-NEVER works)")
        fail_round, join_round = _nem_schedule(nem, rnd, fail_round,
                                               join_round)
    alive = fail_round > rnd

    carry = (state.heard, state.slot_node, state.slot_phase, state.slot_inc,
             state.slot_start, state.slot_nsusp, state.slot_dead_round,
             state.slot_of_node, state.incarnation, state.member, state.drops)

    # -- 0. join tick: admit pending joiners (alive@inc rumors).
    # One N-compare guards the cond; no joins pending -> no work.
    if join_round is not None:
        any_join = jnp.any((join_round <= rnd) & ~state.member
                           & (fail_round > rnd))
        carry = jax.lax.cond(
            any_join,
            lambda c: _join_tick(p, rnd, c, join_round, fail_round, sc),
            lambda c: c, carry)

    member_now = carry[9]
    # Packed per-node status: member ? fail_round : -1.  One gather
    # answers both "is x a member" (>= 0) and "is x an alive member"
    # (> rnd) — the kernel's most common random reads.
    mf = jnp.where(member_now, fail_round, -1)

    # -- 1. probe tick (staggered: block rnd % probe_every probes).  Runs
    # FIRST, on the un-aged matrix: its decisions read only msg/conf
    # bits, and its fresh marks carry the _AGE_FRESH sentinel that the
    # tail's age tick turns into age 0 --------------------------------
    if nem is not None and nem_state is not None:
        carry, probe_stats, nem_state = _probe_tick(
            p, rnd, k_probe, mf, carry, sc, nem, nem_state)
    else:
        carry, probe_stats = _probe_tick(p, rnd, k_probe, mf, carry, sc,
                                         nem)
    (heard, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
     slot_dead_round, slot_of_node, incarnation, member, drops) = carry

    rx_ok = alive & member
    # Lifeguard confirmations cap: the number of other independent
    # suspectors.  The same cap clamps the timer lookup in the finish
    # phase — keep them identical.
    conf_cap = jnp.minimum(p.max_confirmations,
                           jnp.maximum(slot_nsusp - 1, 0))

    def _maybe_pushpull(h, sub_rx_ok):
        # -- 3b. push/pull anti-entropy (memberlist PushPullInterval):
        # full belief exchange with one random partner, bidirectional,
        # ignoring the per-message spread budget — this is what recovers
        # rumors that aged out before reaching everyone (e.g. under
        # packet loss) ---------------------------------------------------
        if not p.pushpull_every:
            return h

        def _pushpull(h):
            kpp = jax.random.fold_in(key, 3)
            # One circulant pairing: i dials i + o.  Merging both
            # directions (+o and -o rolls) makes each pair's exchange
            # symmetric, as memberlist's push/pull TCP sync is.
            o = jax.random.randint(kpp, (), 1, N, dtype=jnp.int32)
            rxl = sub_rx_ok if sc is None else _sloc(sc, sub_rx_ok)
            for j, shift in enumerate((o, -o)):
                mfl = (jnp.roll(mf, shift) if sc is None
                       else _sloc_roll(sc, mf, shift))
                ok = rxl & (mfl > rnd)
                if nem is not None and nem.has_partition:
                    # Cross-group sync legs drop at the sender-group
                    # edge probability.  Full-[N] draws off a replicated
                    # key, sliced per shard — bit-parity preserved.
                    grp = _nem_group(nem, N)
                    g_src = (jnp.roll(grp, shift) if sc is None
                             else _sloc_roll(sc, grp, shift))
                    g_dst = grp if sc is None else _sloc(sc, grp)
                    p_edge = jnp.where(g_src == 0, nem.p_ab, nem.p_ba)
                    dv_full = jax.random.uniform(
                        jax.random.fold_in(jax.random.fold_in(key, 5), j),
                        (N,))
                    dv = dv_full if sc is None else _sloc(sc, dv_full)
                    drop = (_nem_in_window(nem, rnd) & (g_src != g_dst)
                            & (dv < p_edge))
                    ok = ok & ~drop
                hin = (jnp.roll(h, shift, axis=1) if sc is None
                       else _roll_sharded(sc, h, shift))
                upgraded = (((hin >> _MSG_SHIFT) > (h >> _MSG_SHIFT))
                            & ok[None, :])
                h = jnp.where(upgraded, hin, h)
            return h

        return jax.lax.cond(rnd % p.pushpull_every == p.pushpull_every - 1,
                            _pushpull, lambda h: h, h)

    # The loss half of the nemesis schedule needs per-leg drop draws in
    # the dissemination phase; key 4 is reserved for it (5 = push/pull).
    k_nem = (jax.random.fold_in(key, 4)
             if nem is not None and nem.has_partition else None)
    has_ns = nem_state is not None

    def _tail_unpack(op):
        if hist is None and not has_ns:
            return op, None, None
        parts = list(op)
        heard = parts.pop(0)
        hb = parts.pop(0) if hist is not None else None
        nsv = parts.pop(0) if has_ns else None
        return heard, hb, nsv

    def _tail_pack(heard, hb, nsv):
        if hist is None and not has_ns:
            return heard
        return ((heard,) + ((hb,) if hist is not None else ())
                + ((nsv,) if has_ns else ()))

    def _full_tail(op):
        heard, hb, nsv = _tail_unpack(op)
        # -- 2+3. age (fused into the dissemination pack) + gossip push
        # via circulant rolls ---------------------------------------------
        heard = _disseminate(p, rnd, k_gossip, heard, mf, rx_ok, conf_cap,
                             sc, nem, k_nem)
        heard = _maybe_pushpull(heard, rx_ok)
        return _finish_round(p, state, rnd, fail_round, alive, member, heard,
                             None, jnp.arange(S, dtype=jnp.int32), slot_node,
                             slot_phase, slot_inc, slot_start, slot_nsusp,
                             slot_dead_round, slot_of_node, incarnation,
                             drops, conf_cap, rx_ok, sc, hb, nem, nsv)

    def _hot_tail(op):
        heard, hb, nsv = _tail_unpack(op)
        # A handful of live episodes: slice just their belief rows, run
        # the identical age/gossip/timer pipeline on the [H, N] subset,
        # write back.  Inactive rows are all-zero, so excluding them
        # is exact.  top_k over the 0/1 activity vector yields H
        # distinct slot ids (lowest-index ties), padding with inactive
        # slots whose rows are no-ops end to end.
        #
        # Row IO is H per-row dynamic slices/updates with traced starts
        # — NOT a single [H] fancy-index gather: on this TPU a traced-
        # index row gather lowers element-wise (~6.5ns/index ⇒ ~52ms
        # for 8×1M rows — the round-3 hot tier was 10x SLOWER than the
        # full tail it replaced), while dynamic_slice moves each row at
        # memory bandwidth (BENCH_NOTES §1c / axon perf model).
        act = (slot_node >= 0).astype(jnp.int32)
        _, idx = jax.lax.top_k(act, p.hot_slots)
        idx = idx.astype(jnp.int32)
        sub = jnp.concatenate([
            jax.lax.dynamic_slice_in_dim(heard, idx[j], 1, axis=0)
            for j in range(p.hot_slots)], axis=0)
        sub = _disseminate(p, rnd, k_gossip, sub, mf, rx_ok, conf_cap[idx],
                           sc, nem, k_nem)
        sub = _maybe_pushpull(sub, rx_ok)
        return _finish_round(p, state, rnd, fail_round, alive, member, sub,
                             heard, idx, slot_node, slot_phase, slot_inc,
                             slot_start, slot_nsusp, slot_dead_round,
                             slot_of_node, incarnation, drops, conf_cap,
                             rx_ok, sc, hb, nem, nsv)

    def _quiescent_tail(op):
        heard, hb, nsv = _tail_unpack(op)
        # No active episode anywhere: the belief matrix is all-zero and
        # every age/gossip/timer/GC pass is a no-op.  A healthy cluster
        # pays only the probe tick per round.  No episodes -> nothing
        # for the observatory either: the banks pass through untouched.
        st = SwimState(
            round=rnd + 1, heard=heard, slot_node=slot_node,
            slot_phase=slot_phase, slot_inc=slot_inc, slot_start=slot_start,
            slot_nsusp=slot_nsusp, slot_dead_round=slot_dead_round,
            slot_of_node=slot_of_node, incarnation=incarnation, member=member,
            drops=drops, n_detected=state.n_detected,
            sum_detect_rounds=state.sum_detect_rounds,
            n_false_dead=state.n_false_dead, n_refuted=state.n_refuted,
        )
        return _tail_pack(st, hb, nsv)

    n_active = jnp.sum((slot_node >= 0).astype(jnp.int32))

    def _nonquiescent(op):
        if p.hot_slots and S > p.hot_slots:
            return jax.lax.cond(n_active <= p.hot_slots, _hot_tail,
                                _full_tail, op)
        return _full_tail(op)

    out = jax.lax.cond(n_active > 0, _nonquiescent, _quiescent_tail,
                       _tail_pack(heard, hist, nem_state))
    new_state, hist_out, ns_out = _tail_unpack(out)
    if not collect:
        return new_state, None, hist_out, ns_out

    # -- flight row (obs.flight.FLIGHT_COLS order) ------------------------
    # Dissemination bytes: every in-budget rumor entry is pushed to
    # ``fanout`` peers at one belief byte each.  Behind the quiescence
    # cond so the healthy fast path never reads the matrix for it.
    def _tx_bytes(h):
        live = ((h >> _MSG_SHIFT) > 0) & \
            ((h & _AGE_MASK) < p.spread_budget_rounds)
        t = p.fanout * jnp.sum(live.astype(jnp.int32))
        return t if sc is None else jax.lax.psum(t, _SHARD_AXIS)

    tx = jax.lax.cond(n_active > 0, _tx_bytes,
                      lambda h: jnp.int32(0), new_state.heard)
    dead_before = state.n_detected + state.n_false_dead
    dead_after = new_state.n_detected + new_state.n_false_dead
    row = jnp.stack([
        rnd,
        probe_stats[0],                                    # probes
        probe_stats[1],                                    # acks_missed
        probe_stats[2],                                    # indirect_probes
        probe_stats[3],                                    # suspect_new
        new_state.n_refuted - state.n_refuted,             # alive_events
        dead_after - dead_before,                          # dead_events
        jnp.sum((new_state.slot_phase == PHASE_JOIN)
                .astype(jnp.int32)),                       # join_rumors
        jnp.sum((new_state.slot_node >= 0)
                .astype(jnp.int32)),                       # queue_occupancy
        tx,                                                # dissem_bytes
        new_state.drops - state.drops,                     # drops
        jnp.sum(new_state.member.astype(jnp.int32)),       # members
    ]).astype(jnp.int32)
    return new_state, row, hist_out, ns_out


def gossip_offsets(key: jax.Array, n: int, fanout: int) -> jnp.ndarray:
    """``fanout`` nonzero circulant shifts for one round's gossip graph.

    Node ``i`` pushes to ``i + o_f (mod n)`` — the round's communication
    graph is ``fanout`` random circulants, redrawn every round.  vs the
    keyed-permutation graph this keeps in-degree exactly ``fanout`` and
    replaces every delivery gather with a contiguous roll: on this TPU a
    random 1M-index gather costs ~6.5ns/index (~6.5ms) while a roll
    moves the same row at memory bandwidth (tools/profile_kernel.py) —
    the difference is the whole kernel's speed.  The trade: within one
    round every node's targets share the same shifts (targets are
    correlated ACROSS nodes; each node's own target sequence over rounds
    is still uniform).  Single-rumor spread over independent per-round
    shifts is the classic additive sumset process whose coverage curve
    matches uniform push gossip to within the crossval tier's bounds —
    quantified, like every kernel approximation, against the
    discrete-event reference model."""
    # Uniform in [1, n-1]: zero would be a self-loop (memberlist never
    # gossips to self); distinctness across the fanout draws is not
    # enforced (collision probability fanout^2/n, a duplicate edge for
    # one round — the same rumor delivered twice, absorbed by max-merge).
    return jax.random.randint(key, (fanout,), 1, n, dtype=jnp.int32)


# SWAR constants: four u8 belief bytes ride one u32 lane (byte k of
# word g = slot row 4g+k).  All per-byte fields are < 0x80, so the
# borrow-guard comparison trick below is exact.
_LSB = 0x01010101
_B7 = 0x80808080
_AGE4 = 0x0F0F0F0F
_MSG4 = 0x03030303


def _bcast_byte(b):
    """Per-byte 0/1 (at each byte's LSB) -> 0x00/0xFF per byte."""
    return (b << 8) - b  # u32 wrap makes the top byte come out right


def _byte_ge(a, b):
    """Per-byte (a >= b) as a 0x00/0xFF mask; fields must be < 0x80."""
    t = (a | jnp.uint32(_B7)) - b
    return _bcast_byte((t >> 7) & jnp.uint32(_LSB))


def _byte_eq(a, b):
    """Per-byte (a == b) as a 0x00/0xFF mask; fields must be < 0x80.

    NOT the classic ``(x-LSB) & ~x & 0x80..`` zero-byte test — that
    one's per-byte indicators are polluted by borrows propagating past
    a zero byte (it only answers "is there ANY zero byte").  Two
    borrow-free >= comparisons are exact."""
    return _byte_ge(a, b) & _byte_ge(b, a)


def _byte_sel(mask, a, b):
    """Per-byte select: mask bytes are 0x00/0xFF."""
    return (a & mask) | (b & ~mask)


def _disseminate(p: SwimParams, rnd, k_gossip, heard, mf, rx_ok,
                 conf_cap, sc=None, nem=None, k_nem=None) -> jnp.ndarray:
    """One round of rumor push: ``fanout`` circulant-shift deliveries,
    merged per destination with message-priority + Lifeguard
    confirmation counting.  Dispatches on ``p.dissem`` (static): all
    four strategies are bit-identical (tested); the switch exists for
    on-chip A/Bs and a one-line fallback.

    ``nem``/``k_nem`` (static / replicated key): a partitioned nemesis
    schedule drops each cross-group delivery leg at the sender-group
    edge probability — per-leg full-[N] draws off ``k_nem`` (replicated,
    shard-sliced, so sharded and single-device rounds stay
    bit-identical)."""
    if p.dissem == "planes":
        return _disseminate_planes(p, rnd, k_gossip, heard, mf, rx_ok,
                                   conf_cap, sc, nem, k_nem)
    if p.dissem == "fused":
        from consul_tpu.gossip.fused import fused_disseminate
        return fused_disseminate(p, rnd, k_gossip, heard, mf, rx_ok,
                                 conf_cap, sc, nem, k_nem)
    if p.dissem == "prefused":
        return _disseminate_swar(p, rnd, k_gossip, heard, mf, rx_ok,
                                 conf_cap, sc, nem, k_nem, prefuse=True)
    return _disseminate_swar(p, rnd, k_gossip, heard, mf, rx_ok,
                             conf_cap, sc, nem, k_nem)


def _nem_leg_drop(p: SwimParams, nem, k_nem, rnd, f, o, sc):
    """Per-destination drop mask for gossip leg ``f`` (shift ``o``):
    the sender into destination d is d - o, so the sender group is the
    rolled group vector; cross-group lanes drop at the sender-group
    edge probability inside the fault window.  Returns a local-[L]
    (or [N]) bool mask."""
    N = p.n
    grp = _nem_group(nem, N)
    g_src = jnp.roll(grp, o) if sc is None else _sloc_roll(sc, grp, o)
    g_dst = grp if sc is None else _sloc(sc, grp)
    p_edge = jnp.where(g_src == 0, nem.p_ab, nem.p_ba)
    dv_full = jax.random.uniform(jax.random.fold_in(k_nem, f), (N,))
    dv = dv_full if sc is None else _sloc(sc, dv_full)
    return _nem_in_window(nem, rnd) & (g_src != g_dst) & (dv < p_edge)


def _swar_age_field(packed):
    """The aged AGE field alone (no recombination into the word): fresh
    probe marks (the per-byte ``_AGE_FRESH`` sentinel) become age 0,
    real ages saturate at 14, message-free bytes keep their raw age.
    ``inc`` stays byte-isolated: age <= 0xF so age+1 never carries
    across a byte lane."""
    age = packed & jnp.uint32(_AGE4)
    has_msg = ~_byte_eq(packed >> _MSG_SHIFT & jnp.uint32(_MSG4),
                        jnp.uint32(0))
    fresh = _byte_eq(age, jnp.uint32(_AGE4))  # == _AGE_FRESH per byte
    inc = age + jnp.uint32(_LSB)
    sat = _byte_ge(inc, jnp.uint32((_AGE_MASK - 1) * _LSB))
    aged = _byte_sel(fresh, jnp.uint32(0),
                     _byte_sel(sat, jnp.uint32((_AGE_MASK - 1) * _LSB), inc))
    return _byte_sel(has_msg, aged, age)


def _swar_age(packed):
    """The age tick as SWAR on packed u32 words (see ``_age_tick`` for
    the semantics): fresh probe marks (the per-byte ``_AGE_FRESH``
    sentinel) become age 0, real ages saturate at 14, message-free
    bytes are untouched.  ``inc`` stays byte-isolated: age <= 0xF so
    age+1 never carries across a byte lane.  (Kept as a whole-word
    select rather than ``_swar_age_field`` splicing — algebraically
    identical, but this op shape is the one XLA:CPU fuses without an
    extra materialization, measured via ``cost_analysis``.)"""
    age = packed & jnp.uint32(_AGE4)
    has_msg = ~_byte_eq(packed >> _MSG_SHIFT & jnp.uint32(_MSG4),
                        jnp.uint32(0))
    fresh = _byte_eq(age, jnp.uint32(_AGE4))  # == _AGE_FRESH per byte
    inc = age + jnp.uint32(_LSB)
    sat = _byte_ge(inc, jnp.uint32((_AGE_MASK - 1) * _LSB))
    aged = _byte_sel(fresh, jnp.uint32(0),
                     _byte_sel(sat, jnp.uint32((_AGE_MASK - 1) * _LSB), inc))
    return _byte_sel(has_msg, (packed & ~jnp.uint32(_AGE4)) | aged, packed)


def _disseminate_swar(p: SwimParams, rnd, k_gossip, heard, mf, rx_ok,
                      conf_cap, sc=None, nem=None, k_nem=None,
                      prefuse: bool = False) -> jnp.ndarray:
    """The belief matrix moves as u32 words holding FOUR slot-rows per
    element; the whole merge is SWAR on those words — one fused
    elementwise pass that reads the current matrix and the ``fanout``
    rolled copies once each, instead of the per-byte-plane loop that
    produces four separate [S4, N] outputs (each re-reading every
    pin).  IO per round drops from ~12 pin reads + 4 plane read/writes
    to fanout+1 reads + 1 write.

    ``prefuse`` (static; ``p.dissem == "prefused"``): commute the age
    tick across the circulant rolls.  Aging is elementwise and a roll
    is a permutation, so ``roll(age(x)) == age(roll(x))`` exactly —
    instead of materializing an aged copy of the whole packed matrix
    before the pin reads (a full [S,N] read+write the multi-consumer
    boundary forces on XLA), the deferred tick folds into each leg's
    actual use: the pins' budget test becomes a shifted-threshold
    compare on raw ages (see the in-loop comment — no per-pin age
    pass at all), and the current-value leg computes only the aged
    AGE field.  Bit-identical by the commutation; one fewer dense
    pass by construction, and near-zero redundant flops."""
    S, N = heard.shape
    S4 = -(-S // 4)
    pad = 4 * S4 - S
    h_rows = (jnp.concatenate(
        [heard, jnp.zeros((pad, N), jnp.uint8)]) if pad else heard)
    planes = h_rows.reshape(S4, 4, N).astype(jnp.uint32)
    packed = (planes[:, 0] | (planes[:, 1] << 8)
              | (planes[:, 2] << 16) | (planes[:, 3] << 24))

    # Age tick, fused into the packed chain (the standalone u8 pass
    # costs a full read+write of the matrix).  The prefused strategy
    # defers this into the per-leg chains below instead.
    if not prefuse:
        packed = _swar_age(packed)

    # Offsets are drawn over the GLOBAL observer count: under sharding
    # the local width is N/ndev but the circulant graph spans the pool.
    offs = gossip_offsets(k_gossip, p.n, p.fanout)
    budget_b = jnp.uint32(p.spread_budget_rounds * _LSB)
    rx_l = rx_ok if sc is None else _sloc(sc, rx_ok)
    rx = jnp.where(rx_l, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))[None, :]

    in_msg = jnp.zeros((S4, N), jnp.uint32)
    n_sus = jnp.zeros((S4, N), jnp.uint32)
    for f in range(p.fanout):
        # Sender into d is d - o_f: delivery = roll by +o_f (contiguous;
        # sharded: local roll + ppermute halo exchange, and the rolled
        # replicated mf is a local slice of its doubled copy).
        o = offs[f]
        mf_r = jnp.roll(mf, o) if sc is None else _sloc_roll(sc, mf, o)
        src_live = mf_r > rnd
        if nem is not None and nem.has_partition:
            src_live = src_live & ~_nem_leg_drop(p, nem, k_nem, rnd, f, o,
                                                 sc)
        src = jnp.where(src_live,
                        jnp.uint32(0xFFFFFFFF), jnp.uint32(0))[None, :]
        pin = (jnp.roll(packed, o, axis=1) if sc is None
               else _roll_sharded(sc, packed, o))
        if prefuse:
            # The pin leg consumes the aged pin ONLY through (a) its
            # msg bits — which aging never touches — and (b) this
            # budget test, so the deferred age tick folds into the
            # compare instead of running per pin:
            #   aged_age >= b  ⟺  raw_age ∈ [b-1, 14], fresh exempt
            # (aged = fresh ? 0 : min(age+1, 14), and b is clamped to
            # [1, 14] by SwimParams.spread_budget_rounds, so no edge
            # branches).  Message-free bytes disagree with the aged
            # compare at raw_age ∈ {b-1, 0xF}, but their msg bits are
            # 0 so ``m`` is 0 either way — bit-exact.
            a = pin & jnp.uint32(_AGE4)
            dead = (_byte_ge(a, jnp.uint32(
                (p.spread_budget_rounds - 1) * _LSB))
                    & ~_byte_eq(a, jnp.uint32(_AGE4)))
            live = ~dead & src
        else:
            live = ~_byte_ge(pin & jnp.uint32(_AGE4), budget_b) & src
        m = (pin >> _MSG_SHIFT) & jnp.uint32(_MSG4) & live
        in_msg = _byte_sel(_byte_ge(m, in_msg), m, in_msg)
        n_sus = n_sus + ((_byte_eq(m, jnp.uint32(MSG_SUSPECT * _LSB))
                          >> 7) & jnp.uint32(_LSB))

    cap_b = (jnp.concatenate([conf_cap, jnp.zeros((pad,), jnp.int32)])
             if pad else conf_cap).astype(jnp.uint32).reshape(S4, 4)
    cap_packed = (cap_b[:, 0] | (cap_b[:, 1] << 8)
                  | (cap_b[:, 2] << 16) | (cap_b[:, 3] << 24))[:, None]

    # The current-value leg needs the aged AGE field (it lands in
    # ``out_age``), but msg/conf bits are age-invariant — under prefuse
    # compute just the field instead of rebuilding the whole word.
    cur = packed
    cur_msg = (cur >> _MSG_SHIFT) & jnp.uint32(_MSG4)
    age_c = _swar_age_field(packed) if prefuse else cur & jnp.uint32(_AGE4)
    conf = (cur >> _CONF_SHIFT) & jnp.uint32(_MSG4)
    upgraded = ~_byte_ge(cur_msg, in_msg) & rx
    sus_b = jnp.uint32(MSG_SUSPECT * _LSB)
    bump = _byte_eq(cur_msg, sus_b) & _byte_eq(in_msg, sus_b) & rx
    conf_sum = conf + n_sus  # per-byte <= 6: no cross-byte carry
    capped = _byte_sel(_byte_ge(cap_packed, conf_sum), conf_sum, cap_packed)
    conf_new = _byte_sel(bump, capped, conf)
    # A suspicion heard at a HIGHER confirmation count is a new message
    # in memberlist (suspect-from-origin-X re-enqueues with its own
    # retransmit budget — refmodel.py:197-201): model the re-broadcast
    # by refreshing the entry's spread window whenever the local count
    # rises.  Bounded: conf can rise at most max_confirmations times
    # per observer per episode.  Without this, confirmations trickle
    # instead of flooding and the Lifeguard timeout decays late —
    # measured as a 61% p99 detection-latency error at 10k nodes
    # (CROSSVAL.json history).
    conf_rose = ~_byte_ge(conf, conf_new)
    out_msg = _byte_sel(upgraded, in_msg, cur_msg)
    out_age = _byte_sel(upgraded | conf_rose, jnp.uint32(0), age_c)
    out_conf = _byte_sel(upgraded, jnp.uint32(0), conf_new)
    out = (out_msg << _MSG_SHIFT) | (out_conf << _CONF_SHIFT) | out_age

    planes_out = jnp.stack([(out >> (8 * k)) & jnp.uint32(0xFF)
                            for k in range(4)], axis=1)
    return planes_out.reshape(4 * S4, N)[:S].astype(jnp.uint8)



def _disseminate_planes(p: SwimParams, rnd, k_gossip, heard, mf, rx_ok,
                        conf_cap, sc=None, nem=None,
                        k_nem=None) -> jnp.ndarray:
    """The round-3 strategy (kept for A/B + fallback, see
    ``_disseminate``): merge logic runs per byte-plane on native
    u32 lanes, producing four [S4, N] plane outputs.  Measured
    155-166 rounds/s at 1M/64-slot churn on the v5e."""
    S, N = heard.shape
    S4 = -(-S // 4)
    pad = 4 * S4 - S
    h_rows = (jnp.concatenate(
        [heard, jnp.zeros((pad, N), jnp.uint8)]) if pad else heard)
    planes = h_rows.reshape(S4, 4, N).astype(jnp.uint32)
    # Age tick, fused into the packing chain on u32 lanes (the
    # standalone u8 pass costs a full read+write of the matrix): fresh
    # probe marks (_AGE_FRESH sentinel) become age 0, real ages
    # saturate at 14.  See _age_tick for the semantics.
    msg = planes >> _MSG_SHIFT
    age = planes & _AGE_MASK
    new_age = jnp.where(age == _AGE_FRESH, jnp.uint32(0),
                        jnp.minimum(age + 1, jnp.uint32(_AGE_MASK - 1)))
    planes = jnp.where(msg > 0,
                       (planes & ~jnp.uint32(_AGE_MASK)) | new_age, planes)
    packed = (planes[:, 0] | (planes[:, 1] << 8)
              | (planes[:, 2] << 16) | (planes[:, 3] << 24))

    # Offsets over the GLOBAL observer count (see _disseminate_swar).
    offs = gossip_offsets(k_gossip, p.n, p.fanout)
    budget = jnp.uint32(p.spread_budget_rounds)
    rx_l = rx_ok if sc is None else _sloc(sc, rx_ok)
    pins = []
    for f in range(p.fanout):
        # Sender into d is d - o_f: delivery = roll by +o_f (contiguous).
        o = offs[f]
        src_ok = (jnp.roll(mf, o) if sc is None
                  else _sloc_roll(sc, mf, o)) > rnd
        if nem is not None and nem.has_partition:
            src_ok = src_ok & ~_nem_leg_drop(p, nem, k_nem, rnd, f, o, sc)
        pins.append(((jnp.roll(packed, o, axis=1) if sc is None
                      else _roll_sharded(sc, packed, o)), src_ok))

    cap4 = (jnp.concatenate([conf_cap, jnp.zeros((pad,), jnp.int32)])
            if pad else conf_cap).reshape(S4, 4).astype(jnp.uint32)

    out_planes = []
    for k in range(4):
        in_msg = jnp.zeros((S4, N), jnp.uint32)
        n_sus_in = jnp.zeros((S4, N), jnp.uint32)
        for pin, src_ok in pins:
            bk = (pin >> (8 * k)) & jnp.uint32(0xFF)
            active = src_ok[None, :] & ((bk & _AGE_MASK) < budget)
            m = jnp.where(active, bk >> _MSG_SHIFT, jnp.uint32(0))
            in_msg = jnp.maximum(in_msg, m)
            n_sus_in = n_sus_in + (m == MSG_SUSPECT).astype(jnp.uint32)

        cur = planes[:, k]                        # [S4, N] u32 bytes
        cur_msg = cur >> _MSG_SHIFT
        age = cur & _AGE_MASK
        conf = (cur >> _CONF_SHIFT) & _CONF_MASK
        upgraded = (in_msg > cur_msg) & rx_l[None, :]
        bump = ((cur_msg == MSG_SUSPECT) & (in_msg == MSG_SUSPECT)
                & rx_l[None, :])
        conf_new = jnp.where(bump,
                             jnp.minimum(conf + n_sus_in, cap4[:, k][:, None]),
                             conf)
        # A suspicion heard at a HIGHER confirmation count is a new
        # message in memberlist (suspect-from-origin-X re-enqueues with
        # its own retransmit budget — refmodel.py:197-201): model the
        # re-broadcast by refreshing the entry's spread window whenever
        # the local count rises.  Bounded: conf can rise at most
        # max_confirmations times per observer per episode.  Without
        # this, confirmations trickle instead of flooding and the
        # Lifeguard timeout decays late — measured as a 61% p99
        # detection-latency error at 10k nodes (CROSSVAL.json history).
        conf_rose = conf_new > conf
        out_msg = jnp.where(upgraded, in_msg, cur_msg)
        out_age = jnp.where(upgraded | conf_rose, jnp.uint32(0), age)
        out_conf = jnp.where(upgraded, jnp.uint32(0), conf_new)
        out_planes.append(
            (out_msg << _MSG_SHIFT) | (out_conf << _CONF_SHIFT) | out_age)

    return jnp.stack(out_planes, axis=1).reshape(4 * S4, N)[:S].astype(jnp.uint8)

def _finish_round(p: SwimParams, state: SwimState, rnd, fail_round, alive,
                  member, heard_sub, full_heard, idx, slot_node, slot_phase,
                  slot_inc, slot_start, slot_nsusp, slot_dead_round,
                  slot_of_node, incarnation, drops, conf_cap,
                  rx_ok, sc=None, hist=None, nem=None, nem_state=None):
    """Refutation, suspicion-timer firing, episode GC, stats.

    Operates on ``heard_sub`` — the belief rows of the slots listed in
    ``idx`` ([H] distinct slot ids; inactive padding entries are
    no-ops).  The full path passes ``idx = arange(S)`` with
    ``full_heard=None`` (the subset IS the matrix); the hot path passes
    the gathered active rows and scatters them back.

    ``hist`` (optional HistBank, a Python-level static like the flight
    ``collect`` flag): accumulate the observatory histograms at the
    verdict/GC sites; ``None`` compiles them out entirely.

    ``nem``/``nem_state`` (statics): with LHM threaded, a subject that
    had to refute a suspicion about itself just learned it answers
    probes too slowly — its own LHM rises (Lifeguard increments the
    local health multiplier on self-refutation, alongside the probe
    tick's missed-ack/NACK signals).  Returns the state packed with
    whichever of hist/nem_state are threaded (matching the round
    tails' ``_tail_pack`` order: state[, hist][, nem_state])."""
    N, S = p.n, p.slots
    H = idx.shape[0]
    is_full = full_heard is None

    # Per-slot registers viewed through idx.
    sl_node = slot_node[idx]
    sl_phase = slot_phase[idx]
    sl_start = slot_start[idx]
    sl_dead_round = slot_dead_round[idx]
    cc = conf_cap[idx]

    # -- 4. refutation: a live subject that hears of its own suspicion
    # bumps its incarnation and spreads alive@inc+1 (Serf/memberlist
    # refutation; Lifeguard's false-positive escape hatch) ---------------
    hrows = jnp.arange(H, dtype=jnp.int32)
    node_c = jnp.clip(sl_node, 0, N - 1)
    n_refuted = state.n_refuted
    refute_now = jnp.zeros((H,), bool)
    if p.refute:
        if sc is None:
            own_msg = heard_sub[hrows, node_c] >> _MSG_SHIFT
        else:
            # Each subject's own-belief byte lives on exactly one shard:
            # mask local ownership, psum the disjoint contributions.
            base = _sc_base(sc)
            owned = (node_c >= base) & (node_c < base + sc.L)
            loc = jnp.clip(node_c - base, 0, sc.L - 1)
            own_msg = jax.lax.psum(
                jnp.where(owned, heard_sub[hrows, loc].astype(jnp.int32), 0),
                _SHARD_AXIS) >> _MSG_SHIFT
        refutable = (sl_phase == PHASE_SUSPECT) | (sl_phase == PHASE_DEAD)
        refute_now = (refutable & (sl_node >= 0) & alive[node_c]
                      & member[node_c]
                      & ((own_msg == MSG_SUSPECT) | (own_msg == MSG_DEAD)))
        incarnation = incarnation.at[jnp.where(refute_now, node_c, N)].add(1, mode="drop")  # noqa: O01 — indices are distinct node ids: <=1 bump/node/round, and each needs a prior suspicion
        sl_phase = jnp.where(refute_now, PHASE_REFUTED, sl_phase)
        # The refute IS the episode's verdict: record its round so GC can
        # recycle the slot as soon as the verdict has disseminated (a
        # dead-then-refuted slot's dead round is superseded — the refute
        # is the message that still needs spreading).
        sl_dead_round = jnp.where(refute_now, rnd, sl_dead_round)
        refute_val = jnp.where(refute_now, jnp.uint8(_enc(MSG_REFUTE)),
                               jnp.uint8(0))
        if sc is None:
            heard_sub = heard_sub.at[hrows, node_c].max(refute_val)
        else:
            heard_sub = heard_sub.at[hrows, jnp.where(owned, loc, sc.L)].max(
                refute_val, mode="drop")
        n_refuted = n_refuted + jnp.sum(refute_now.astype(jnp.int32))  # noqa: O01 — monotone mod 2**32 (SwimState wrap convention)

    if nem is not None and nem_state is not None:
        # Lifeguard: self-refutation bumps the refuter's own LHM (it
        # answered a suspicion too slowly to prevent it).  refute_now is
        # replicated (psum-merged own_msg under sharding) and slot
        # subjects are distinct node ids, so the scatter is shard-exact
        # and collision-free; the min clamps keep the register bounded.
        lhm_r, streak_r = nem_state
        lhm_r = jnp.minimum(
            lhm_r.at[jnp.where(refute_now, node_c, N)].add(1, mode="drop"),  # noqa: O01 — clamped to nem.lhm_max every round: carry-in <= lhm_max, +1/slot, min() bounds it
            nem.lhm_max)
        nem_state = NemState(lhm=lhm_r, streak=streak_r)

    # -- 5. suspicion timers fire -> dead declared ------------------------
    tbl = jnp.asarray(p.timeout_table())
    c_eff = jnp.minimum(((heard_sub >> _CONF_SHIFT) & _CONF_MASK).astype(jnp.int32),
                        cc[:, None])
    elapsed = rnd - sl_start
    rx_l = rx_ok if sc is None else _sloc(sc, rx_ok)
    fire = ((sl_phase == PHASE_SUSPECT)[:, None]
            & ((heard_sub >> _MSG_SHIFT) == MSG_SUSPECT)
            & rx_l[None, :]
            & (elapsed[:, None] >= tbl[c_eff]))
    slot_fired = jnp.any(fire, axis=1)
    if sc is not None:
        # Any observer on any shard fires the slot's timer.
        slot_fired = jax.lax.psum(slot_fired.astype(jnp.int32),
                                  _SHARD_AXIS) > 0
    new_dead = slot_fired & (sl_dead_round < 0)
    sl_phase = jnp.where(slot_fired, PHASE_DEAD, sl_phase)
    sl_dead_round = jnp.where(new_dead, rnd, sl_dead_round)
    heard_sub = jnp.where(fire, jnp.uint8(_enc(MSG_DEAD)), heard_sub)

    # Detection stats are recorded at declaration time.
    truly_dead = fail_round[node_c] <= rnd
    n_detected = state.n_detected + jnp.sum((new_dead & truly_dead).astype(jnp.int32))  # noqa: O01 — monotone mod 2**32 (SwimState wrap convention)
    sum_detect_rounds = state.sum_detect_rounds + jnp.sum(  # noqa: O01 — monotone mod 2**32 (SwimState wrap convention)
        jnp.where(new_dead & truly_dead, rnd - fail_round[node_c], 0))
    n_false_dead = state.n_false_dead + jnp.sum((new_dead & ~truly_dead).astype(jnp.int32))  # noqa: O01 — monotone mod 2**32 (SwimState wrap convention)

    # -- 6. episode GC: recycle slots, apply verdicts ---------------------
    # A slot whose verdict is in (dead by timer, or refuted) only needs
    # to outlive that verdict's dissemination (two spread budgets, like
    # the slot-TTL tail), not the worst-case zero-confirmation suspicion
    # timeout.  This is scarcity relief, not a semantics change
    # (memberlist has no slot scarcity at all; a recycled-slot subject
    # that still fails probes re-enters suspicion at the next cycle).
    # Fast-recycling REFUTED slots matters most: under heavy loss the
    # spurious-suspicion rate is high (25% loss: ~0.03*N new refuted
    # episodes per round), and holding each for the full slot TTL
    # starved every slot — 87% of true failures went undetected in the
    # round-3 crossval loss config (CROSSVAL.json config 3: 2/16).
    # JOIN slots carry their verdict from birth (slot_dead_round = the
    # join round): they recycle on the same dissemination window.
    verdict_done = ((((sl_phase == PHASE_DEAD) | (sl_phase == PHASE_REFUTED)
                      | (sl_phase == PHASE_JOIN))
                     & (sl_dead_round >= 0))
                    & (rnd - sl_dead_round > 2 * p.spread_budget_rounds + 8))
    expired = ((sl_phase > PHASE_FREE)
               & ((rnd - sl_start > p.slot_ttl_rounds) | verdict_done))
    is_dead = expired & (sl_phase == PHASE_DEAD)

    # -- observatory histograms (hist is a Python-level static; None
    # compiles this block out — bit-identical dynamics either way).
    # Latencies are recorded at verdict time, spread at slot GC, all
    # from replicated/psum-merged inputs, so the sharded and unsharded
    # banks are bit-identical (tests/test_shard_map_parity.py).
    if hist is not None:
        # Dissemination spread: members still holding the episode's
        # verdict message when its slot is recycled.  Must read
        # heard_sub/member BEFORE the GC wipe below.
        verdict_msg = jnp.where(sl_phase == PHASE_DEAD, MSG_DEAD, MSG_REFUTE)
        mem_l = member if sc is None else _sloc(sc, member)
        hold = (((heard_sub >> _MSG_SHIFT).astype(jnp.int32)
                 == verdict_msg[:, None]) & mem_l[None, :])
        n_hold = jnp.sum(hold, axis=1, dtype=jnp.int32)
        if sc is not None:
            n_hold = jax.lax.psum(n_hold, _SHARD_AXIS)
        # Integer log2 bucket = bit_length via shift-and-count (no
        # float ops — exactness under sharding).
        blen = jnp.sum((n_hold[:, None]
                        >> jnp.arange(31, dtype=jnp.int32)) > 0,
                       axis=1, dtype=jnp.int32)
        hist = HistBank(
            detect=_hist_add(hist.detect, new_dead & truly_dead,
                             rnd - fail_round[node_c]),
            dwell=_hist_add(hist.dwell, new_dead | refute_now,
                            rnd - sl_start),
            refute=_hist_add(hist.refute, refute_now, rnd - sl_start),
            spread=_hist_add(hist.spread, expired & (sl_dead_round >= 0),
                             blen),
        )

    member = member.at[jnp.where(is_dead, node_c, N)].set(False, mode="drop")
    slot_of_node = slot_of_node.at[jnp.where(expired, node_c, N)].set(-1, mode="drop")
    heard_sub = jnp.where(expired[:, None], jnp.uint8(0), heard_sub)
    sl_node = jnp.where(expired, -1, sl_node)
    sl_phase = jnp.where(expired, PHASE_FREE, sl_phase)
    sl_dead_round = jnp.where(expired, -1, sl_dead_round)

    if is_full:
        heard = heard_sub
        slot_node_o, slot_phase_o = sl_node, sl_phase
        slot_dead_o = sl_dead_round
    else:
        # Write the subset rows back as H per-row dynamic updates with
        # traced starts: each moves one row at memory bandwidth and the
        # untouched S-H rows are never rewritten.  (A scatter of [H, N]
        # updates lowers element-wise on this TPU — ~6.5ns/element,
        # 50ms for 8 rows at 1M — and the previous inverse-map select
        # re-wrote the whole S×N matrix to change H rows.)
        heard = full_heard
        for j in range(H):
            heard = jax.lax.dynamic_update_slice(
                heard, jax.lax.dynamic_slice_in_dim(heard_sub, j, 1, axis=0),
                (idx[j], jnp.int32(0)))
        slot_node_o = slot_node.at[idx].set(sl_node)
        slot_phase_o = slot_phase.at[idx].set(sl_phase)
        slot_dead_o = slot_dead_round.at[idx].set(sl_dead_round)

    st = SwimState(
        round=rnd + 1,
        heard=heard,
        slot_node=slot_node_o,
        slot_phase=slot_phase_o,
        slot_inc=slot_inc,
        slot_start=slot_start,
        slot_nsusp=slot_nsusp,
        slot_dead_round=slot_dead_o,
        slot_of_node=slot_of_node,
        incarnation=incarnation,
        member=member,
        drops=drops,
        n_detected=n_detected,
        sum_detect_rounds=sum_detect_rounds,
        n_false_dead=n_false_dead,
        n_refuted=n_refuted,
    )
    out = ((st,) + ((hist,) if hist is not None else ())
           + ((nem_state,) if nem_state is not None else ()))
    return out[0] if len(out) == 1 else out


class RoundTrace(NamedTuple):
    """Per-round observables emitted by run_rounds (small: O(S))."""

    slot_node: jnp.ndarray       # [T, S]
    slot_phase: jnp.ndarray      # [T, S]
    slot_start: jnp.ndarray      # [T, S]
    slot_dead_round: jnp.ndarray  # [T, S]
    n_heard_dead: jnp.ndarray    # [T, S] — members that hold the dead verdict
    n_heard_alive: jnp.ndarray   # [T, S] — members that hold the alive@inc
                                 #   rumor (join announcements / refutes)


@functools.partial(jax.jit,
                   static_argnames=("p", "steps", "trace", "unroll", "nem"),
                   donate_argnames=("state", "flight", "hist", "nem_state"))
def run_rounds(state: SwimState, base_key: jax.Array, fail_round: jnp.ndarray,
               p: SwimParams, steps: int, trace: bool = False,
               unroll: int = 4, join_round: jnp.ndarray | None = None,
               flight: FlightRing | None = None,
               hist: HistBank | None = None,
               nem: NemesisParams | None = None,
               nem_state: NemState | None = None):
    """Scan ``steps`` rounds.  With ``trace``, also return per-round slot
    snapshots for detection-curve analysis (adds one S×N reduction/round).
    ``unroll`` fuses that many rounds per scan iteration — amortizes
    per-iteration dispatch/sync on backends where that dominates.

    ``state``, ``flight``, ``hist`` and ``nem_state`` are DONATED: the
    belief matrix, the ring and the banks are updated in place instead
    of copied per dispatch (64 MB per copy at 1M nodes).  Callers must
    rebind all and never reuse the passed-in arrays afterwards.

    ``flight`` (optional FlightRing): record one flight-recorder row
    per round into the on-device ring at ``cursor % R`` — no host
    transfer here; the caller drains the ring whenever it likes
    (gossip/plane.py amortizes over >= 64 rounds).

    ``hist`` (optional HistBank): accumulate the detection-latency
    observatory histograms in HBM (obs/hist.py bucket layouts), drained
    on the same cadence.

    ``nem`` (optional NemesisParams, STATIC — part of the jit cache
    key): run every round under a nemesis injection schedule
    (gossip/nemesis.py).  A scenario with ``needs_state`` additionally
    threads ``nem_state`` (kernel.NemState) through the carry for the
    Lifeguard LHM dynamics.  Each optional extends the scan carry and
    the first return value in order: ``state``[, ``flight``][,
    ``hist``][, ``nem_state``]; ``None`` compiles the machinery out
    entirely."""
    if nem is not None and nem.needs_state and nem_state is None:
        raise ValueError(
            f"nemesis scenario {nem.scenario!r} needs NemState; pass "
            "nem_state=init_nem_state(p.n)")
    return _run_rounds_impl(state, base_key, fail_round, p, steps, trace,
                            unroll, join_round, flight, None, hist, nem,
                            nem_state)


def _run_rounds_impl(state, base_key, fail_round, p, steps, trace, unroll,
                     join_round, flight, sc, hist=None, nem=None,
                     nem_state=None):
    has_fl = flight is not None
    has_hb = hist is not None
    has_ns = nem_state is not None

    def body(carry, _):
        if has_fl or has_hb or has_ns:
            parts = list(carry)
            st = parts.pop(0)
            fl = parts.pop(0) if has_fl else None
            hb = parts.pop(0) if has_hb else None
            ns = parts.pop(0) if has_ns else None
        else:
            st, fl, hb, ns = carry, None, None, None
        st, row, hb, ns = _swim_round_impl(st, base_key, fail_round, p,
                                           join_round, collect=has_fl, sc=sc,
                                           hist=hb, nem=nem, nem_state=ns)
        if has_fl:
            R = fl.rows.shape[0]
            fl = FlightRing(
                rows=jax.lax.dynamic_update_slice(
                    fl.rows, row[None, :], (fl.cursor % R, jnp.int32(0))),
                cursor=fl.cursor + 1)
        if trace:
            msg = st.heard >> _MSG_SHIFT
            mem = (st.member if sc is None else _sloc(sc, st.member))[None, :]
            n_heard_dead = jnp.sum((msg == MSG_DEAD) & mem,
                                   axis=1, dtype=jnp.int32)
            n_heard_alive = jnp.sum((msg == MSG_REFUTE) & mem,
                                    axis=1, dtype=jnp.int32)
            if sc is not None:
                n_heard_dead = jax.lax.psum(n_heard_dead, _SHARD_AXIS)
                n_heard_alive = jax.lax.psum(n_heard_alive, _SHARD_AXIS)
            y = RoundTrace(st.slot_node, st.slot_phase, st.slot_start,
                           st.slot_dead_round, n_heard_dead, n_heard_alive)
        else:
            y = None
        out = ((st,) + ((fl,) if has_fl else ()) + ((hb,) if has_hb else ())
               + ((ns,) if has_ns else ()))
        return (out if len(out) > 1 else st), y

    init = ((state,) + ((flight,) if has_fl else ())
            + ((hist,) if has_hb else ())
            + ((nem_state,) if has_ns else ()))
    if len(init) == 1:
        init = state
    return jax.lax.scan(body, init, None, length=steps,
                        unroll=min(unroll, max(steps, 1)))


# ---------------------------------------------------------------------------
# Public sharded entry points (see the "ICI sharding" section above for
# the layout).  Factories are lru_cached per (params, topology) exactly
# like jit caches per static args.
# ---------------------------------------------------------------------------

def _check_shardable(p: SwimParams, ndev: int) -> None:
    """Static alignment constraints of the sharded lowering.

    ``n`` must split evenly over the devices (contiguous observer
    columns per shard) and over ``probe_every`` (the probe tick's
    prober block must be the aligned contiguous-window case — the
    unaligned gather fallback has no sharded lowering).  In short:
    n divisible by device_count and by probe_every."""
    if ndev < 1:
        raise ValueError(f"ndev must be >= 1, got {ndev}")
    if p.n % ndev:
        raise ValueError(
            f"sharded kernel needs n % ndev == 0 (n={p.n}, ndev={ndev})")
    if p.n % p.probe_every:
        raise ValueError(
            f"sharded kernel needs n % probe_every == 0 (aligned prober "
            f"blocks; n={p.n}, probe_every={p.probe_every})")


def _default_ndev() -> int:
    return len(jax.devices())


@functools.lru_cache(maxsize=None)
def _shard_mesh(ndev: int):
    return jax.sharding.Mesh(np.array(jax.devices()[:ndev]), (_SHARD_AXIS,))


def _state_spec():
    Ps = jax.sharding.PartitionSpec
    return SwimState(**{f: (Ps(None, _SHARD_AXIS) if f == "heard" else Ps())
                        for f in SwimState._fields})


def shard_state(state: SwimState, ndev: int | None = None) -> SwimState:
    """Place a SwimState on the device mesh: ``heard`` column-sharded
    along the observer axis, every other register replicated.  Call
    once before a sharded run loop so dispatches don't re-lay-out the
    belief matrix every call."""
    ndev = ndev or _default_ndev()
    mesh = _shard_mesh(ndev)
    sh = jax.tree.map(lambda spec: jax.sharding.NamedSharding(mesh, spec),
                      _state_spec())
    return jax.device_put(state, sh)


@functools.lru_cache(maxsize=None)
def sharded_round_callable(p: SwimParams, ndev: int, has_join: bool = False,
                           has_hist: bool = False,
                           nem: NemesisParams | None = None,
                           has_nem_state: bool = False):
    """The shard_map-wrapped single round, NOT jitted: composes inside
    outer jits (multidc_round's per-DC loop) or under the donating jit
    of ``swim_round_sharded``.  Signature: (state, base_key, fail_round
    [, join_round][, hist][, nem_state]) -> state packed with whichever
    of hist/nem_state are threaded (the banks and the LHM registers are
    replicated — every increment derives from replicated or psum-merged
    values)."""
    from jax.experimental.shard_map import shard_map
    _check_shardable(p, ndev)
    mesh = _shard_mesh(ndev)
    sc = _ShardCtx(ndev, p.n // ndev)
    Ps = jax.sharding.PartitionSpec
    st = _state_spec()
    hb = HistBank(*([Ps()] * len(HistBank._fields)))
    ns = NemState(*([Ps()] * len(NemState._fields)))
    in_specs = ((st, Ps(), Ps()) + ((Ps(),) if has_join else ())
                + ((hb,) if has_hist else ())
                + ((ns,) if has_nem_state else ()))
    out_specs = ((st,) + ((hb,) if has_hist else ())
                 + ((ns,) if has_nem_state else ()))
    if len(out_specs) == 1:
        out_specs = st

    def _round(state, base_key, fail_round, *rest):
        i = 0
        join_round = hist = nem_state = None
        if has_join:
            join_round = rest[i]
            i += 1
        if has_hist:
            hist = rest[i]
            i += 1
        if has_nem_state:
            nem_state = rest[i]
        out = _swim_round_impl(state, base_key, fail_round, p, join_round,
                               collect=False, sc=sc, hist=hist, nem=nem,
                               nem_state=nem_state)
        packed = ((out[0],) + ((out[2],) if has_hist else ())
                  + ((out[3],) if has_nem_state else ()))
        return packed[0] if len(packed) == 1 else packed

    return shard_map(_round, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


@functools.lru_cache(maxsize=None)
def _swim_round_sharded_jit(p: SwimParams, ndev: int, has_join: bool):
    return jax.jit(sharded_round_callable(p, ndev, has_join),
                   donate_argnums=(0,))


def swim_round_sharded(state: SwimState, base_key: jax.Array,
                       fail_round: jnp.ndarray, p: SwimParams,
                       join_round: jnp.ndarray | None = None,
                       ndev: int | None = None) -> SwimState:
    """``swim_round`` sharded across ``ndev`` devices — bit-identical
    output, ``state`` donated.  See _check_shardable for the alignment
    constraints."""
    ndev = ndev or _default_ndev()
    fn = _swim_round_sharded_jit(p, ndev, join_round is not None)
    args = (state, base_key, fail_round) + (
        (join_round,) if join_round is not None else ())
    return fn(*args)


@functools.lru_cache(maxsize=None)
def _run_rounds_sharded_jit(p: SwimParams, ndev: int, steps: int,
                            trace: bool, unroll: int, has_join: bool,
                            has_flight: bool, has_hist: bool,
                            nem: NemesisParams | None = None,
                            has_nem_state: bool = False):
    from jax.experimental.shard_map import shard_map
    _check_shardable(p, ndev)
    mesh = _shard_mesh(ndev)
    sc = _ShardCtx(ndev, p.n // ndev)
    Ps = jax.sharding.PartitionSpec
    st = _state_spec()
    fl = FlightRing(rows=Ps(), cursor=Ps())
    hb = HistBank(*([Ps()] * len(HistBank._fields)))
    ns = NemState(*([Ps()] * len(NemState._fields)))
    in_specs = ((st, Ps(), Ps())
                + ((Ps(),) if has_join else ())
                + ((fl,) if has_flight else ())
                + ((hb,) if has_hist else ())
                + ((ns,) if has_nem_state else ()))
    carry_spec = ((st,) + ((fl,) if has_flight else ())
                  + ((hb,) if has_hist else ())
                  + ((ns,) if has_nem_state else ()))
    if len(carry_spec) == 1:
        carry_spec = st
    tr = RoundTrace(*([Ps()] * len(RoundTrace._fields)))
    out_specs = (carry_spec, tr) if trace else carry_spec

    def _run(state, base_key, fail_round, *rest):
        i = 0
        join_round = flight = hist = nem_state = None
        if has_join:
            join_round = rest[i]
            i += 1
        if has_flight:
            flight = rest[i]
            i += 1
        if has_hist:
            hist = rest[i]
            i += 1
        if has_nem_state:
            nem_state = rest[i]
        carry, ys = _run_rounds_impl(state, base_key, fail_round, p, steps,
                                     trace, unroll, join_round, flight, sc,
                                     hist, nem, nem_state)
        return (carry, ys) if trace else carry

    donate = (0,)
    if has_flight:
        donate += (3 + int(has_join),)
    if has_hist:
        donate += (3 + int(has_join) + int(has_flight),)
    if has_nem_state:
        donate += (3 + int(has_join) + int(has_flight) + int(has_hist),)
    return jax.jit(shard_map(_run, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False),
                   donate_argnums=donate)


def run_rounds_sharded(state: SwimState, base_key: jax.Array,
                       fail_round: jnp.ndarray, p: SwimParams, steps: int,
                       trace: bool = False, unroll: int = 4,
                       join_round: jnp.ndarray | None = None,
                       flight: FlightRing | None = None,
                       hist: HistBank | None = None,
                       nem: NemesisParams | None = None,
                       nem_state: NemState | None = None,
                       ndev: int | None = None):
    """``run_rounds`` sharded across ``ndev`` devices (default: all
    local devices) — same contract and bit-identical results; ``state``,
    ``flight``, ``hist`` and ``nem_state`` donated.  Compute and HBM
    traffic for the belief matrix drop by ``ndev``; the circulant
    deliveries pay a log2(ndev) ppermute halo exchange instead.
    Constraints: n divisible by ndev and by probe_every
    (_check_shardable)."""
    if nem is not None and nem.needs_state and nem_state is None:
        raise ValueError(
            f"nemesis scenario {nem.scenario!r} needs NemState; pass "
            "nem_state=init_nem_state(p.n)")
    ndev = ndev or _default_ndev()
    fn = _run_rounds_sharded_jit(p, ndev, steps, trace, unroll,
                                 join_round is not None, flight is not None,
                                 hist is not None, nem,
                                 nem_state is not None)
    args = [state, base_key, fail_round]
    if join_round is not None:
        args.append(join_round)
    if flight is not None:
        args.append(flight)
    if hist is not None:
        args.append(hist)
    if nem_state is not None:
        args.append(nem_state)
    out = fn(*args)
    return out if trace else (out, None)
