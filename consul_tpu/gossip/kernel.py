"""The SWIM round kernel: failure detection + dissemination as batched array ops.

Re-design of the reference's gossip substrate (memberlist SWIM + Serf
dissemination; behavior contract at
``website/source/docs/internals/gossip.html.markdown:10-43``, consumed by
Consul at ``consul/server.go:257-273`` / ``consul/config.go:266-272``)
as a single jit-compiled synchronous-rounds step.

**State compression.**  A faithful N-node cluster has N distinct views —
an N×N belief matrix, hopeless at 1M nodes.  SWIM's structure makes the
compression exact enough for its statistics: all information about a
subject node travels as a small set of totally-ordered messages
(suspect@inc < dead < alive@inc+1 within one suspicion episode), so an
observer's belief about a subject is just "the highest message it has
heard, and when".  At any instant only nodes with an in-flight rumor
need tracking.  We therefore keep an S×N matrix over "subject slots":

    heard[s, i]  (uint8):  bits 7-6  msg   (0 none, 1 suspect, 2 dead, 3 refute)
                           bits 5-4  conf  (independent suspicion confirmations, Lifeguard)
                           bits 3-0  age   (rounds since this node heard the msg)

The bit layout makes "merge = numeric max" give message priority
ordering for scatter-marking; the gossip merge itself uses explicit
logic.  Slots are allocated when a probe failure starts a suspicion
episode, recycled after the episode resolves (dead / refuted) and its
verdict has disseminated; overflow is *counted* (``drops``), never
silent.

**Communication as gathers.**  Each round every node pushes its active
rumors to ``fanout`` peers.  The round's communication graph is
``fanout`` keyed Feistel permutations (consul_tpu.ops.feistel), so the
senders into node d are ``perm_f^{-1}(d)`` — delivery is ``fanout``
vectorized gathers along the observer axis, no sort/scatter.

**Timers.**  One round = one gossip interval; each node probes once
every ``probe_every`` rounds, staggered in contiguous id blocks so a
fixed 1/probe_every of the cluster probes per round (the refmodel
staggers per-node probe phases the same way — memberlist probe timers
have random phase).  Suspicion timeouts follow Lifeguard
(params.timeout_table): all observers time from the episode start
(slot_start) — the first suspector's timer governs first-detection in
both models, so detection-time statistics are preserved (validated in
tests against the discrete-event reference model).

Known approximations vs stock memberlist: exactly-``fanout`` in-degree
per round (permutation gossip) instead of Poisson(fanout); uniform
random probe targets instead of shuffled round-robin sweeps;
episode-start-based suspicion timers; confirmation counts capped at 3
and approximated by receipt rounds rather than distinct-origin tracking;
refutation is globally instantaneous (a refute cancels every observer's
pending dead declaration in the same round, rather than racing its
propagation against each observer's local timer — biases false-positive
counts low vs event-driven memberlist).
Each is quantified against the discrete-event reference model
(gossip/refmodel.py) by the cross-validation test tier.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.gossip.params import SwimParams
from consul_tpu.ops.feistel import (
    gossip_partners, gossip_sources, random_targets)

MSG_NONE = 0
MSG_SUSPECT = 1
MSG_DEAD = 2
MSG_REFUTE = 3

PHASE_FREE = 0
PHASE_SUSPECT = 1
PHASE_DEAD = 2
PHASE_REFUTED = 3

NEVER = np.int32(2**31 - 1)  # fail_round value for "never fails"

_MSG_SHIFT = 6
_CONF_SHIFT = 4
_CONF_MASK = 0x3
_AGE_MASK = 0xF


def _enc(msg: int, conf: int = 0, age: int = 0) -> int:
    return (msg << _MSG_SHIFT) | (conf << _CONF_SHIFT) | age


class SwimState(NamedTuple):
    """One LAN pool's protocol state. All arrays live in HBM."""

    round: jnp.ndarray          # i32 scalar — current gossip round
    heard: jnp.ndarray          # u8  [S, N] — per-(slot, observer) belief
    slot_node: jnp.ndarray      # i32 [S] — subject node id, -1 = free
    slot_phase: jnp.ndarray     # i32 [S] — PHASE_*
    slot_inc: jnp.ndarray       # i32 [S] — incarnation under suspicion (diagnostic
                                #   only for now: message ordering within an episode
                                #   is positional — suspect < dead < refute — so the
                                #   incarnation guard is implicit; joins/rejoins will
                                #   consume this field when they land)
    slot_start: jnp.ndarray     # i32 [S] — round the episode began
    slot_nsusp: jnp.ndarray     # i32 [S] — independent suspicion initiators
    slot_dead_round: jnp.ndarray  # i32 [S] — round dead was declared, -1
    slot_of_node: jnp.ndarray   # i32 [N] — node -> slot, -1 = none
    incarnation: jnp.ndarray    # i32 [N] — per-node incarnation counter
    member: jnp.ndarray         # bool [N] — current cluster membership
    drops: jnp.ndarray          # i32 — suspicion initiations lost to full slots
    n_detected: jnp.ndarray     # i32 — true failures detected (at slot GC)
    sum_detect_rounds: jnp.ndarray  # i32 — sum of (dead_round - fail_round)
    n_false_dead: jnp.ndarray   # i32 — alive nodes declared dead
    n_refuted: jnp.ndarray      # i32 — episodes ended by refutation


def init_state(p: SwimParams) -> SwimState:
    S, N = p.slots, p.n
    return SwimState(
        round=jnp.int32(0),
        heard=jnp.zeros((S, N), jnp.uint8),
        slot_node=jnp.full((S,), -1, jnp.int32),
        slot_phase=jnp.zeros((S,), jnp.int32),
        slot_inc=jnp.zeros((S,), jnp.int32),
        slot_start=jnp.zeros((S,), jnp.int32),
        slot_nsusp=jnp.zeros((S,), jnp.int32),
        slot_dead_round=jnp.full((S,), -1, jnp.int32),
        slot_of_node=jnp.full((N,), -1, jnp.int32),
        incarnation=jnp.zeros((N,), jnp.int32),
        member=jnp.ones((N,), bool),
        drops=jnp.int32(0),
        n_detected=jnp.int32(0),
        sum_detect_rounds=jnp.int32(0),
        n_false_dead=jnp.int32(0),
        n_refuted=jnp.int32(0),
    )


def _age_tick(heard: jnp.ndarray) -> jnp.ndarray:
    msg = heard >> _MSG_SHIFT
    age = heard & _AGE_MASK
    aged = (heard & ~jnp.uint8(_AGE_MASK)) | jnp.minimum(age + 1, _AGE_MASK).astype(jnp.uint8)
    return jnp.where(msg > 0, aged, heard)


def _block_size(p: SwimParams) -> int:
    """Probers per round under staggering: each node probes once per
    ``probe_every`` rounds, spread across rounds in contiguous id
    blocks (the refmodel staggers per-node probe phases the same way,
    refmodel.py probe_offset)."""
    return max(1, -(-p.n // p.probe_every))


def _probe_tick(p: SwimParams, rnd, keys, mf, state_tuple):
    """One round's probe slice: direct probe -> k indirect probes ->
    suspicion initiation for this round's prober block (reference
    per-node behavior: memberlist probe cycle as configured at
    consul/config.go:266-272, with per-node stagger).

    ``mf`` packs membership and ground truth into one gatherable i32:
    ``member ? fail_round : -1`` — so ``mf[x] > rnd`` is alive-member
    and ``mf[x] >= 0`` is member, one gather instead of two.

    Helpers are sampled uniformly excluding the prober (collision with
    the target has probability k/N — negligible, accepted)."""
    (heard, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
     slot_dead_round, slot_of_node, incarnation, member, drops) = state_tuple
    k_t, k_dl, k_h, k_hl = keys
    N, S = p.n, p.slots
    B = _block_size(p)

    # This round's probers: block (rnd % probe_every); ids >= N are
    # padding lanes on the final block and initiate nothing.
    pid = (rnd % p.probe_every) * B + jnp.arange(B, dtype=jnp.int32)
    pid_c = jnp.minimum(pid, N - 1)
    pvalid = pid < N

    tgt = random_targets(k_t, N, (B,), ids=pid_c)
    prober_ok = pvalid & (mf[pid_c] > rnd)
    mf_t = mf[tgt]
    tgt_member = mf_t >= 0
    tgt_alive = mf_t > rnd

    u = jax.random.uniform(k_dl, (B,))
    direct_fail = tgt_member & (~tgt_alive | (u < p.p_direct_fail_alive))

    helpers = random_targets(k_h, N, (B, p.indirect_k), ids=pid_c)
    hu = jax.random.uniform(k_hl, (B, p.indirect_k))
    ind_ok = ((mf[helpers] > rnd)
              & tgt_alive[:, None] & tgt_member[:, None]
              & (hu >= p.p_indirect_fail_alive))
    init = prober_ok & direct_fail & ~jnp.any(ind_ok, axis=1)

    # Don't re-suspect a target this prober already believes dead.
    s_t = slot_of_node[tgt]
    cur = heard[jnp.clip(s_t, 0, S - 1), pid_c]
    init = init & ~((s_t >= 0) & ((cur >> _MSG_SHIFT) == MSG_DEAD))

    # Aggregate per target.
    nsusp_add = jnp.zeros((N,), jnp.int32).at[tgt].add(init.astype(jnp.int32))
    want = nsusp_add > 0

    node_c = jnp.clip(slot_node, 0, N - 1)
    valid = slot_node >= 0
    slot_want = valid & want[node_c]
    add_here = jnp.where(valid, nsusp_add[node_c], 0)

    # Existing suspect episodes absorb new initiators.
    slot_nsusp = jnp.where((slot_phase == PHASE_SUSPECT) & slot_want,
                           slot_nsusp + add_here, slot_nsusp)

    # A refuted episode whose subject fails probes again re-arms at the
    # bumped incarnation (memberlist: suspect at inc >= alive inc).
    rearm = (slot_phase == PHASE_REFUTED) & slot_want
    slot_phase = jnp.where(rearm, PHASE_SUSPECT, slot_phase)
    slot_inc = jnp.where(rearm, incarnation[node_c], slot_inc)
    slot_start = jnp.where(rearm, rnd, slot_start)
    slot_nsusp = jnp.where(rearm, add_here, slot_nsusp)
    slot_dead_round = jnp.where(rearm, -1, slot_dead_round)
    heard = jnp.where(rearm[:, None], jnp.uint8(0), heard)

    # Allocate fresh slots: k-th needer (by node id) takes the k-th free
    # slot.  top_k over the need mask replaces a full-N cumsum ranking —
    # at most S needers can be served anyway (ties in top_k resolve to
    # the lowest index, preserving the by-id order).
    need = want & (slot_of_node < 0) & member
    free = ~valid
    free_order = jnp.argsort(jnp.where(free, 0, 1), stable=True).astype(jnp.int32)
    n_free = jnp.sum(free)
    kk = min(S, N)  # a tiny pool (e.g. a WAN bridge) has fewer nodes than slots
    vals, cand = jax.lax.top_k(need.astype(jnp.int32), kk)
    krank = jnp.arange(kk, dtype=jnp.int32)
    can_k = (vals > 0) & (krank < n_free)
    slot_k = free_order[krank]
    sidx = jnp.where(can_k, slot_k, S)  # S = out of range -> dropped
    slot_node = slot_node.at[sidx].set(cand, mode="drop")
    slot_phase = slot_phase.at[sidx].set(PHASE_SUSPECT, mode="drop")
    slot_inc = slot_inc.at[sidx].set(incarnation[cand], mode="drop")
    slot_start = slot_start.at[sidx].set(rnd, mode="drop")
    slot_nsusp = slot_nsusp.at[sidx].set(nsusp_add[cand], mode="drop")
    slot_dead_round = slot_dead_round.at[sidx].set(-1, mode="drop")
    slot_of_node = slot_of_node.at[jnp.where(can_k, cand, N)].set(
        slot_k, mode="drop")
    drops = drops + jnp.sum(need.astype(jnp.int32)) - jnp.sum(can_k.astype(jnp.int32))

    # Initiators record their own suspicion with a *fresh* age so the
    # rumor re-enters circulation (memberlist re-enqueues the suspect
    # broadcast on every independent suspicion — this is what carries
    # confirmations outward and shrinks the Lifeguard timeout).
    s_t2 = slot_of_node[tgt]
    cur2 = heard[jnp.clip(s_t2, 0, S - 1), pid_c]
    mark_ok = init & (s_t2 >= 0) & ((cur2 >> _MSG_SHIFT) <= MSG_SUSPECT)
    fresh = (jnp.uint8(_enc(MSG_SUSPECT)) | (cur2 & jnp.uint8(_CONF_MASK << _CONF_SHIFT)))
    heard = heard.at[jnp.where(mark_ok, s_t2, S), pid_c].set(fresh, mode="drop")

    return (heard, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
            slot_dead_round, slot_of_node, incarnation, member, drops)


@functools.partial(jax.jit, static_argnames=("p",))
def swim_round(state: SwimState, base_key: jax.Array, fail_round: jnp.ndarray,
               p: SwimParams) -> SwimState:
    """Advance the pool by one gossip round."""
    rnd = state.round
    key = jax.random.fold_in(base_key, rnd)
    k_probe = jax.random.split(jax.random.fold_in(key, 1), 4)
    k_gossip = jax.random.fold_in(key, 2)

    N, S = p.n, p.slots
    alive = fail_round > rnd
    # Packed per-node status: member ? fail_round : -1.  One gather
    # answers both "is x a member" (>= 0) and "is x an alive member"
    # (> rnd) — the kernel's most common random reads.
    mf = jnp.where(state.member, fail_round, -1)

    # -- 1. age every in-flight rumor ------------------------------------
    heard = _age_tick(state.heard)

    # -- 2. probe tick (staggered: block rnd % probe_every probes) --------
    carry = (heard, state.slot_node, state.slot_phase, state.slot_inc,
             state.slot_start, state.slot_nsusp, state.slot_dead_round,
             state.slot_of_node, state.incarnation, state.member, state.drops)
    carry = _probe_tick(p, rnd, k_probe, mf, carry)
    (heard, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
     slot_dead_round, slot_of_node, incarnation, member, drops) = carry

    # -- 3. gossip dissemination (push via inverse-permutation gathers) ---
    cur_msg = (heard >> _MSG_SHIFT).astype(jnp.uint8)
    rx_ok = alive & member
    in_msg = jnp.zeros_like(cur_msg)
    n_sus_in = jnp.zeros(heard.shape, jnp.uint8)
    srcs_all = gossip_sources(k_gossip, N, p.fanout)
    ids_n = jnp.arange(N, dtype=jnp.int32)
    for f in range(p.fanout):
        srcs = srcs_all[f]
        # Permutation fixed points would deliver a node's own rumor back to
        # it (and count as a Lifeguard confirmation); memberlist never
        # gossips to self.
        src_ok = (mf[srcs] > rnd) & (srcs != ids_n)
        hin = heard[:, srcs]
        active = src_ok[None, :] & ((hin & _AGE_MASK) < p.spread_budget_rounds)
        m = jnp.where(active, (hin >> _MSG_SHIFT).astype(jnp.uint8), jnp.uint8(0))
        in_msg = jnp.maximum(in_msg, m)
        n_sus_in = n_sus_in + (m == MSG_SUSPECT).astype(jnp.uint8)

    age = heard & _AGE_MASK
    conf = ((heard >> _CONF_SHIFT) & _CONF_MASK).astype(jnp.int32)
    upgraded = (in_msg > cur_msg) & rx_ok[None, :]
    # Lifeguard confirmations: extra suspect receipts while already
    # suspecting, capped by the number of other independent suspectors.
    # The same cap clamps the timer lookup below — keep them identical.
    conf_cap = jnp.minimum(p.max_confirmations,
                           jnp.maximum(slot_nsusp - 1, 0))[:, None]
    bump = (cur_msg == MSG_SUSPECT) & (in_msg == MSG_SUSPECT) & rx_ok[None, :]
    conf = jnp.where(bump, jnp.minimum(conf + n_sus_in.astype(jnp.int32), conf_cap), conf)

    out_msg = jnp.where(upgraded, in_msg, cur_msg)
    out_age = jnp.where(upgraded, jnp.uint8(0), age.astype(jnp.uint8))
    out_conf = jnp.where(upgraded, 0, conf).astype(jnp.uint8)
    heard = ((out_msg << _MSG_SHIFT) | (out_conf << _CONF_SHIFT) | out_age).astype(jnp.uint8)

    # -- 3b. push/pull anti-entropy (memberlist PushPullInterval): full
    # belief exchange with one random partner, bidirectional, ignoring
    # the per-message spread budget — this is what recovers rumors that
    # aged out before reaching everyone (e.g. under packet loss) --------
    if p.pushpull_every:
        def _pushpull(h):
            kpp = jax.random.fold_in(key, 3)
            # fwd = who dials me under the permutation; rev = whom I dial.
            # Doing both directions makes each pair's exchange symmetric.
            fwd, rev = gossip_partners(kpp, N)
            for partner in (fwd, rev):
                ok = rx_ok & (mf[partner] > rnd) & (partner != ids_n)
                hin = h[:, partner]
                upgraded = ((hin >> _MSG_SHIFT) > (h >> _MSG_SHIFT)) & ok[None, :]
                h = jnp.where(upgraded, hin, h)
            return h

        heard = jax.lax.cond(rnd % p.pushpull_every == p.pushpull_every - 1,
                             _pushpull, lambda h: h, heard)

    # -- 4. refutation: a live subject that hears of its own suspicion
    # bumps its incarnation and spreads alive@inc+1 (Serf/memberlist
    # refutation; Lifeguard's false-positive escape hatch) ---------------
    srows = jnp.arange(S, dtype=jnp.int32)
    node_c = jnp.clip(slot_node, 0, N - 1)
    n_refuted = state.n_refuted
    if p.refute:
        own_msg = heard[srows, node_c] >> _MSG_SHIFT
        refutable = (slot_phase == PHASE_SUSPECT) | (slot_phase == PHASE_DEAD)
        refute_now = (refutable & (slot_node >= 0) & alive[node_c]
                      & member[node_c]
                      & ((own_msg == MSG_SUSPECT) | (own_msg == MSG_DEAD)))
        incarnation = incarnation.at[jnp.where(refute_now, node_c, N)].add(1, mode="drop")
        slot_phase = jnp.where(refute_now, PHASE_REFUTED, slot_phase)
        heard = heard.at[srows, node_c].max(
            jnp.where(refute_now, jnp.uint8(_enc(MSG_REFUTE)), jnp.uint8(0)))
        n_refuted = n_refuted + jnp.sum(refute_now.astype(jnp.int32))

    # -- 5. suspicion timers fire -> dead declared ------------------------
    tbl = jnp.asarray(p.timeout_table())
    c_eff = jnp.minimum(((heard >> _CONF_SHIFT) & _CONF_MASK).astype(jnp.int32),
                        conf_cap)
    elapsed = rnd - slot_start
    fire = ((slot_phase == PHASE_SUSPECT)[:, None]
            & ((heard >> _MSG_SHIFT) == MSG_SUSPECT)
            & rx_ok[None, :]
            & (elapsed[:, None] >= tbl[c_eff]))
    slot_fired = jnp.any(fire, axis=1)
    new_dead = slot_fired & (slot_dead_round < 0)
    slot_phase = jnp.where(slot_fired, PHASE_DEAD, slot_phase)
    slot_dead_round = jnp.where(new_dead, rnd, slot_dead_round)
    heard = jnp.where(fire, jnp.uint8(_enc(MSG_DEAD)), heard)

    # Detection stats are recorded at declaration time.
    truly_dead = fail_round[node_c] <= rnd
    n_detected = state.n_detected + jnp.sum((new_dead & truly_dead).astype(jnp.int32))
    sum_detect_rounds = state.sum_detect_rounds + jnp.sum(
        jnp.where(new_dead & truly_dead, rnd - fail_round[node_c], 0))
    n_false_dead = state.n_false_dead + jnp.sum((new_dead & ~truly_dead).astype(jnp.int32))

    # -- 6. episode GC: recycle slots, apply verdicts ---------------------
    expired = (slot_phase > PHASE_FREE) & (rnd - slot_start > p.slot_ttl_rounds)
    is_dead = expired & (slot_phase == PHASE_DEAD)
    member = member.at[jnp.where(is_dead, node_c, N)].set(False, mode="drop")
    slot_of_node = slot_of_node.at[jnp.where(expired, node_c, N)].set(-1, mode="drop")
    heard = jnp.where(expired[:, None], jnp.uint8(0), heard)
    slot_node = jnp.where(expired, -1, slot_node)
    slot_phase = jnp.where(expired, PHASE_FREE, slot_phase)
    slot_dead_round = jnp.where(expired, -1, slot_dead_round)

    return SwimState(
        round=rnd + 1,
        heard=heard,
        slot_node=slot_node,
        slot_phase=slot_phase,
        slot_inc=slot_inc,
        slot_start=slot_start,
        slot_nsusp=slot_nsusp,
        slot_dead_round=slot_dead_round,
        slot_of_node=slot_of_node,
        incarnation=incarnation,
        member=member,
        drops=drops,
        n_detected=n_detected,
        sum_detect_rounds=sum_detect_rounds,
        n_false_dead=n_false_dead,
        n_refuted=n_refuted,
    )


class RoundTrace(NamedTuple):
    """Per-round observables emitted by run_rounds (small: O(S))."""

    slot_node: jnp.ndarray       # [T, S]
    slot_phase: jnp.ndarray      # [T, S]
    slot_start: jnp.ndarray      # [T, S]
    slot_dead_round: jnp.ndarray  # [T, S]
    n_heard_dead: jnp.ndarray    # [T, S] — members that hold the dead verdict


@functools.partial(jax.jit, static_argnames=("p", "steps", "trace", "unroll"))
def run_rounds(state: SwimState, base_key: jax.Array, fail_round: jnp.ndarray,
               p: SwimParams, steps: int, trace: bool = False,
               unroll: int = 4):
    """Scan ``steps`` rounds.  With ``trace``, also return per-round slot
    snapshots for detection-curve analysis (adds one S×N reduction/round).
    ``unroll`` fuses that many rounds per scan iteration — amortizes
    per-iteration dispatch/sync on backends where that dominates."""

    def body(st, _):
        st = swim_round(st, base_key, fail_round, p)
        if trace:
            n_heard_dead = jnp.sum(
                (((st.heard >> _MSG_SHIFT) == MSG_DEAD) & st.member[None, :]),
                axis=1, dtype=jnp.int32)
            y = RoundTrace(st.slot_node, st.slot_phase, st.slot_start,
                           st.slot_dead_round, n_heard_dead)
        else:
            y = None
        return st, y

    return jax.lax.scan(body, state, None, length=steps,
                        unroll=min(unroll, max(steps, 1)))
