"""Multi-datacenter gossip: per-DC LAN pools + one cross-DC WAN pool.

Parity target: Consul's two-pool topology (``consul/server.go:257-273``:
every node is in its DC's LAN pool; servers additionally join a global
WAN pool with coarser timers, ``consul/config.go:266-272``) and Serf
event propagation across DCs through the WAN members.

Kernel composition (BASELINE config #5, the 1M-node shape):

- ``D`` LAN pools of ``n_lan`` nodes each — one :class:`SwimState`
  with a leading DC axis, advanced by ``jax.vmap`` of the single-pool
  round (per-DC PRNG keys).  With ``lan_devices > 1`` each DC's round
  runs through the shard_map'd kernel (``kernel.sharded_round_callable``):
  LAN traffic stays inside a shard group (ICI) and only the small WAN
  pool crosses slice boundaries (DCN) — the same locality the
  reference gets from LAN-vs-WAN gossip profiles.
- One WAN pool of ``D * n_servers`` nodes (server ``j`` of DC ``d`` is
  WAN id ``d * n_servers + j``) with the WAN timing profile.
- Events: each DC floods its LAN event pool; every round, server
  nodes bridge LAN<->WAN (an event any server has seen enters the WAN
  pool, and an event any WAN member of DC ``d`` carries enters ``d``'s
  LAN pool at that server) — Consul's actual cross-DC event path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from consul_tpu.gossip.events import (
    EventState, _SEEN, event_round, init_events)
from consul_tpu.gossip.kernel import (
    HistBank, SwimState, init_hist, init_state, sharded_round_callable,
    swim_round, swim_round_hist)
from consul_tpu.gossip.params import SwimParams, lan_profile, wan_profile


class MultiDCParams(NamedTuple):
    n_dcs: int
    n_lan: int          # nodes per DC
    n_servers: int      # servers per DC (3-5 in the reference posture)
    event_slots: int
    lan: SwimParams
    wan: SwimParams
    # Devices each DC's LAN round is shard_map'd over (observer axis;
    # kernel.sharded_round_callable).  0/1 = single-device LAN rounds.
    # Requires n_lan % (lan_devices * lan.probe_every) alignment.
    lan_devices: int = 0


def make_params(n_dcs: int, n_lan: int, n_servers: int = 3,
                event_slots: int = 32, lan_devices: int = 0,
                **kw) -> MultiDCParams:
    return MultiDCParams(
        n_dcs=n_dcs, n_lan=n_lan, n_servers=n_servers,
        event_slots=event_slots,
        lan=lan_profile(n_lan, **kw),
        wan=wan_profile(n_dcs * n_servers),
        lan_devices=lan_devices,
    )


class MultiDCState(NamedTuple):
    lan: SwimState          # leading axis D on every array
    lan_events: EventState  # leading axis D
    wan: SwimState
    wan_events: EventState


def init_multidc(p: MultiDCParams) -> MultiDCState:
    lan = jax.vmap(lambda _: init_state(p.lan))(jnp.arange(p.n_dcs))
    lan_events = jax.vmap(lambda _: init_events(p.lan, p.event_slots))(
        jnp.arange(p.n_dcs))
    return MultiDCState(
        lan=lan,
        lan_events=lan_events,
        wan=init_state(p.wan),
        wan_events=init_events(p.wan, p.event_slots),
    )


def init_multidc_hist(p: MultiDCParams) -> HistBank:
    """Per-DC observatory banks: one HistBank with a leading D axis."""
    return jax.vmap(lambda _: init_hist())(jnp.arange(p.n_dcs))


def _merge_seen(dst: jnp.ndarray, src_seen: jnp.ndarray) -> jnp.ndarray:
    """Set the seen-bit (age 0) where src has seen and dst hasn't."""
    newly = src_seen & ((dst & _SEEN) == 0)
    return jnp.where(newly, jnp.uint8(_SEEN), dst)


@functools.partial(jax.jit, static_argnames=("p",))
def multidc_round(state: MultiDCState, base_key: jax.Array,
                  lan_fail: jnp.ndarray, wan_fail: jnp.ndarray,
                  p: MultiDCParams, lan_hist: HistBank | None = None):
    """One LAN gossip interval across every pool.

    ``lan_fail``: [D, n_lan] per-pool fail rounds; ``wan_fail``:
    [D*n_servers].  The WAN pool ticks every round too — its *protocol*
    is slower via its own probe_every/suspicion params (its rounds are
    LAN-interval sized; wan_profile's probe_every scales accordingly).

    ``lan_hist`` (optional, ``init_multidc_hist``): thread per-DC
    observatory banks through each DC's LAN round; returns
    ``(state, lan_hist)`` instead of the bare state.
    """
    D, s = p.n_dcs, p.n_servers
    keys = jax.random.split(jax.random.fold_in(base_key, 11), D)

    # -- LAN pools: membership + events, one static unroll per DC --------
    # NOT vmapped: under vmap the kernel's circulant rolls and
    # block slices (traced shifts, batched) lower to random-index
    # gathers — measured ~100x slower at 4x250k than the same work
    # unbatched (tools/profile_kernel.py findings; the gather costs
    # ~6.5ns/index on this TPU).  D is small and static, so a Python
    # loop compiles D copies that keep the roll/slice lowering.
    def _per_dc(tree, d):
        return jax.tree.map(lambda x: x[d], tree)

    # DC x shard composition: with lan_devices > 1 each DC's round is
    # the shard_map-wrapped kernel (observer axis split across ICI,
    # kernel.py "ICI sharding"); the D-loop stays a static unroll, so
    # the per-DC collectives schedule back-to-back on the same ring.
    has_hist = lan_hist is not None
    if p.lan_devices > 1:
        _lan_round = sharded_round_callable(p.lan, p.lan_devices,
                                            has_hist=has_hist)
    elif has_hist:
        _lan_round = lambda st, k, f, hb: swim_round_hist(st, k, f, p.lan, hb)
    else:
        _lan_round = functools.partial(swim_round, p=p.lan)
    if has_hist:
        pairs = [
            _lan_round(_per_dc(state.lan, d), keys[d], lan_fail[d],
                       _per_dc(lan_hist, d))
            for d in range(D)
        ]
        lan_list = [st for st, _ in pairs]
        lan_hist = jax.tree.map(lambda *xs: jnp.stack(xs),
                                *[hb for _, hb in pairs])
    else:
        lan_list = [
            _lan_round(_per_dc(state.lan, d), keys[d], lan_fail[d])
            for d in range(D)
        ]
    lan = jax.tree.map(lambda *xs: jnp.stack(xs), *lan_list)
    lan_alive = (lan_fail > state.lan_events.round[:, None])
    lan_ev_list = [
        event_round(_per_dc(state.lan_events, d), keys[d], lan_alive[d], p.lan)
        for d in range(D)
    ]
    lan_events = jax.tree.map(lambda *xs: jnp.stack(xs), *lan_ev_list)

    # -- WAN pool ---------------------------------------------------------
    wan_key = jax.random.fold_in(base_key, 13)
    wan = swim_round(state.wan, wan_key, wan_fail, p.wan)
    wan_alive = wan_fail > state.wan_events.round
    wan_events = event_round(state.wan_events, wan_key, wan_alive, p.wan)

    # -- event bridge at the servers (serf WAN user-event relay) ---------
    # Slot ids are GLOBAL: fire_in_dc allocates a slot free in every
    # pool and stamps ltime/origin/start_round everywhere up front, so
    # the bridge only merges seen-bits — metadata (Lamport time, GC
    # clock) already exists on the receiving side, and per-pool GC
    # (which cleared has+slot_used inside event_round above) is never
    # overridden from stale pre-round state.
    E = p.event_slots
    # LAN server view: [D, E, s] -> [E, D*s]
    lan_srv_seen = ((lan_events.has[:, :, :s] & _SEEN) > 0)
    lan_srv_flat = jnp.transpose(lan_srv_seen, (1, 0, 2)).reshape(E, D * s)
    wan_live = wan_events.slot_used[:, None]
    wan_has = _merge_seen(wan_events.has, lan_srv_flat & wan_live)

    wan_seen = ((wan_has & _SEEN) > 0)
    wan_by_dc = jnp.transpose(wan_seen.reshape(E, D, s), (1, 0, 2))  # [D, E, s]
    lan_live = lan_events.slot_used[:, :, None]
    lan_srv = lan_events.has[:, :, :s]
    lan_srv = jax.vmap(_merge_seen)(lan_srv, wan_by_dc & lan_live)
    lan_has = lan_events.has.at[:, :, :s].set(lan_srv)

    lan_events = lan_events._replace(has=lan_has)
    wan_events = wan_events._replace(has=wan_has)

    out = MultiDCState(lan=lan, lan_events=lan_events,
                       wan=wan, wan_events=wan_events)
    return (out, lan_hist) if has_hist else out


def fire_in_dc(state: MultiDCState, dc: int, node: int,
               p: MultiDCParams) -> MultiDCState:
    """Originate one user event at (dc, node).

    Allocates a slot that is free in EVERY pool (slot ids are global
    across DCs — two concurrently-live events must never share an
    index, or the seen-bit bridge would conflate them) and stamps the
    slot metadata in every pool so late bridge deliveries carry the
    right Lamport time and GC clock."""
    le, we = state.lan_events, state.wan_events
    free = ~(jnp.any(le.slot_used, axis=0) | we.slot_used)
    if not bool(jnp.any(free)):
        le = le._replace(drops=le.drops + 1)
        return state._replace(lan_events=le)
    slot = int(jnp.argmax(free))

    fire_lt = int(le.node_ltime[dc, node]) + 1
    lan_events = le._replace(
        has=le.has.at[dc, slot, node].set(jnp.uint8(_SEEN)),
        slot_used=le.slot_used.at[:, slot].set(True),
        ltime=le.ltime.at[:, slot].set(fire_lt),
        origin=le.origin.at[:, slot].set(-1).at[dc, slot].set(node),
        start_round=le.start_round.at[:, slot].set(le.round[:]),
        node_ltime=le.node_ltime.at[dc, node].set(fire_lt),
        n_seen=le.n_seen.at[:, slot].set(0).at[dc, slot].set(1),
    )
    wan_events = we._replace(
        slot_used=we.slot_used.at[slot].set(True),
        ltime=we.ltime.at[slot].set(fire_lt),
        origin=we.origin.at[slot].set(-1),
        start_round=we.start_round.at[slot].set(we.round),
        n_seen=we.n_seen.at[slot].set(0),
    )
    return state._replace(lan_events=lan_events, wan_events=wan_events)


def event_coverage(state: MultiDCState) -> jnp.ndarray:
    """[D, E] fraction of each DC's nodes holding each event."""
    seen = (state.lan_events.has & _SEEN) > 0
    return jnp.mean(seen.astype(jnp.float32), axis=2)


@functools.partial(jax.jit, static_argnames=("p", "steps"))
def run_multidc_rounds(state: MultiDCState, base_key: jax.Array,
                       lan_fail: jnp.ndarray, wan_fail: jnp.ndarray,
                       p: MultiDCParams, steps: int,
                       lan_hist: HistBank | None = None
                       ) -> Tuple[MultiDCState, jnp.ndarray]:
    """Scan ``steps`` rounds; traces per-round [D, E] event coverage.

    With ``lan_hist`` the carry (and first return value) is
    ``(state, lan_hist)`` — per-DC observatory banks accumulated
    through every LAN round."""
    has_hist = lan_hist is not None

    def body(carry, _):
        if has_hist:
            st, hb = carry
            st, hb = multidc_round(st, base_key, lan_fail, wan_fail, p, hb)
        else:
            st = multidc_round(carry, base_key, lan_fail, wan_fail, p)
        seen = (st.lan_events.has & _SEEN) > 0
        cov = jnp.mean(seen.astype(jnp.float32), axis=2)
        return ((st, hb) if has_hist else st), cov

    init = (state, lan_hist) if has_hist else state
    return jax.lax.scan(body, init, None, length=steps)
