"""Gossip plane: SWIM failure detection + epidemic dissemination on TPU.

This package is the TPU-native re-design of the reference's L0/L1 layers
(memberlist SWIM + Serf, SURVEY.md §1): instead of per-node goroutines
and timers, the membership protocol for N nodes executes as one
jit-compiled, batched message-passing round step over HBM-resident
arrays (``kernel.py``).  The same kernel is both the membership engine
behind the agent and a million-node simulator cross-validated against a
discrete-event reference model of memberlist semantics (``refmodel.py``).
"""

from consul_tpu.gossip.params import SwimParams, lan_profile, wan_profile  # noqa: F401
from consul_tpu.gossip.kernel import SwimState, init_state, swim_round, run_rounds  # noqa: F401
from consul_tpu.gossip.events import (  # noqa: F401
    EventState, coverage, event_round, fire_events, init_events,
    run_event_rounds)
from consul_tpu.gossip.multidc import (  # noqa: F401
    MultiDCParams, MultiDCState, event_coverage, fire_in_dc, init_multidc,
    make_params, multidc_round, run_multidc_rounds)
