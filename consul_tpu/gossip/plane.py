"""The TPU gossip plane daemon: the kernel as a real membership backend.

This is the graft SURVEY.md §7 and BASELINE.json describe — the point
where the framework's two planes become one system.  A cluster of real
agents configured with ``gossip_backend=tpu`` delegates its LAN
membership substrate (the memberlist role, reference boundary
``consul/server.go:284-325`` → serf → memberlist) to this daemon:

- **Membership state lives in the kernel arrays.**  Every registered
  agent is a node id in the SWIM kernel's universe
  (:mod:`consul_tpu.gossip.kernel`): its probe outcomes, suspicion
  episode, Lifeguard timeout decay, dissemination, refutation, and the
  final dead verdict all execute on-device in the jit round step —
  optionally alongside millions of simulated nodes in the same arrays
  (``sim_nodes``; the hybrid BASELINE config-#5 posture).
- **The physical liveness signal is the bridge heartbeat.**  In stock
  memberlist the raw signal is "probe packet unanswered"; here it is
  "agent's heartbeat lapsed on the bridge socket" (the agent side runs
  a native C++ heartbeat thread — ``native/gbridge.cpp`` — so a busy
  Python event loop cannot starve its own liveness).  A lapsed agent
  starts failing kernel probes; everything above that signal — the
  suspicion state machine, confirmation-driven timeout decay, verdict
  dissemination, refutation on resumed heartbeats — is kernel dynamics,
  not host code.
- **Events flow out the serf boundary.**  Membership transitions
  (join/failed/leave) stream to every connected agent, which raises
  them through the same ``on_event`` channel the asyncio backend uses
  (→ server routing tables, leader reconcile → serfHealth, exactly as
  ``consul/serf.go:90-110`` feeds ``consul/leader.go``).

Wire protocol (shared with the C++ bridge): 4-byte big-endian length +
msgpack map.  Client→plane: register / hb / leave / force-leave /
event / members.  Plane→client: welcome snapshot, pushed membership
events, pushed user events.

One plane serves one LAN pool (one DC).  The WAN pool — tiny,
servers-only — stays on the asyncio backend; cross-DC remains the
reference's two-pool topology.

Security posture — TRUSTED NETWORK ASSUMED for non-loopback binds.
The bridge protocol is plaintext msgpack: an armed keyring
(``encrypt_keys``) gates *admission* (registration requires an HMAC
proof, see :func:`registration_proof`) but does NOT encrypt the
stream — membership events, user-event payloads, and stats frames are
readable, and frames after registration are not individually
authenticated, by any on-path observer.  Binding to anything other
than 127.0.0.1 / a mode-0600 unix socket therefore assumes the
network segment is trusted (the same posture as memberlist with
gossip verification but no transport encryption).  Deployments that
cannot assume this must front the plane port with their own transport
security (e.g. a local sidecar or an ipsec/wireguard segment).

The registration replay cache (``_seen_nonces``) is IN-MEMORY ONLY:
a plane restart forgets seen (ts, nonce) pairs, so a captured
register frame can be replayed against the restarted plane for up to
``auth_skew_s`` after its original timestamp.  The window is small
(default 30s) and the frame only re-registers the same node identity,
but operators rotating keys after a suspected capture should restart
the plane LAST, after the old key is removed everywhere.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import os
import struct
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

import msgpack
import numpy as np

from consul_tpu.obs import journey as _journey

EV_JOIN = "member-join"
EV_LEAVE = "member-leave"
EV_FAILED = "member-failed"
EV_UPDATE = "member-update"
EV_USER = "user"

# Fixed rounds per kernel dispatch: one compiled variant, wall-clock
# catch-up runs several dispatches.
STEPS_PER_TICK = 4

# Drain the on-device flight ring every this many dispatches.  At
# STEPS_PER_TICK=4 this is 64 kernel rounds per host transfer — the
# recorder adds ZERO per-round (and zero per-dispatch) host syncs.
# Must stay <= the ring length / STEPS_PER_TICK or rows overflow
# (overflow is counted, not silent — obs.flight tracks it).
FLIGHT_DRAIN_EVERY = 16


@dataclass
class PlaneConfig:
    bind_addr: str = "127.0.0.1"
    bind_port: int = 8310          # the plane's rendezvous port
    unix_path: str = ""            # serve on a unix socket instead
    capacity: int = 1024           # real-agent universe size (node ids)
    sim_nodes: int = 0             # extra simulated nodes sharing the arrays
    gossip_interval_s: float = 0.2  # kernel round length in wall time
    probe_every: int = 5
    suspicion_mult: float = 4.0
    # heartbeat lapse after which an agent starts failing kernel probes
    # (the "probe packet unanswered" signal); the DEFAULT heartbeat
    # period the plane hands to clients is lapse/3.
    hb_lapse_s: float = 2.0
    slots: int = 64
    # Gossip keyring (base64 keys, same format as the agents' encrypt
    # key).  Non-empty => registration requires an HMAC proof derived
    # from an installed key (registration_proof) — the plane-side
    # counterpart of serf enforcing the keyring on the gossip fabric:
    # without it any process that can reach the plane port could
    # register nodes, inject events, or force-leave members.
    encrypt_keys: List[str] = field(default_factory=list)
    auth_skew_s: float = 30.0      # accepted |now - auth_ts| window
    # Left-name tombstone window: a "left" PlaneNode stays listed (serf
    # tombstone parity) until reaped — without a reap, node-name churn
    # grows the member list and welcome snapshots without bound.
    # Matches serf's TombstoneTimeout default (24h).
    tombstone_timeout_s: float = 24 * 3600.0
    # Concurrent user-event slots in the dissemination kernel
    # (gossip/events.py): fired events flood the SAME gossip substrate
    # as membership — real agents and the sim swarm share the flood —
    # instead of a host-side TCP fanout.
    event_slots: int = 64
    # Devices the SWIM round is shard_map'd over (kernel.py "ICI
    # sharding").  1 = single-device; >1 = explicit (start() raises if
    # the universe size is not divisible by shard_devices and
    # probe_every); 0 = all local devices when the alignment
    # constraints hold, else fall back to single-device; -1 = resolve
    # through the persisted autotune verdict (obs/tuner.py), with a
    # misaligned verdict degrading to single-device instead of raising.
    shard_devices: int = -1
    # Detection-latency SLO objective in kernel rounds (obs/slo.py).
    # 0 = auto: the params' worst-case Lifeguard suspicion window plus
    # one probe-selection period (the latest round a clean detection
    # can land when nothing goes wrong).
    slo_objective_rounds: int = 0
    slo_attainment_target: float = 0.99
    # Nemesis scenario to run the kernel under (gossip/nemesis.py
    # catalog name; "" = none).  The scenario's injection schedule —
    # partition/asymmetric-loss edge drops, flapping, degraded
    # observers — applies to every dispatch, its scheduled kills merge
    # into the heartbeat-driven fail rounds, and every drained
    # histogram delta is attributed to the scenario label, giving the
    # SLO observatory a per-failure-mode breakdown (/v1/agent/slo
    # ``scenarios``, scenario-labeled Prometheus histograms).
    nemesis: str = ""
    # Autotuned kernel knobs (obs/tuner.py).  Each field below defaults
    # to an AUTO sentinel: left there, the value resolves through the
    # persisted per-platform autotune verdict at start() (explicit
    # config value > verdict > registry default); any other value is an
    # explicit operator setting and wins over the verdict.  TUNED_FIELDS
    # below is the consumer-side claim for the autotune-knob vet group.
    #
    # Dissemination merge strategy for the kernel round
    # (params.SwimParams.dissem: swar | planes | prefused | fused —
    # all bit-identical; see gossip/params.py).  "" = auto.
    dissem: str = ""
    # Active-rumor top-k short-circuit (params.SwimParams.hot_slots;
    # 0 = full sweep).  -1 = auto.
    hot_slots: int = -1
    # Fused-kernel column-block count (params.SwimParams.fused_nb,
    # min 1).  0 = auto.
    fused_nb: int = 0
    # Kernel rounds fused per scan iteration (kernel.run_rounds unroll,
    # min 1).  0 = auto.
    unroll: int = 0
    # Dispatches between flight-ring host drains (min 1).  0 = auto.
    flight_drain_every: int = 0


# PlaneConfig knobs resolved through the autotune verdict — the
# plane's consumer-side claim for the ``autotune-knob`` vet group
# (tools/vet/table_drift.py): the union of every TUNED_FIELDS literal
# must equal the obs/tuner.py KNOBS key set.
TUNED_FIELDS = ("dissem", "hot_slots", "fused_nb", "shard_devices",
                "unroll", "flight_drain_every")

# The per-field AUTO sentinel (the dataclass default): any other value
# is an explicit operator setting and skips the verdict.
_TUNED_AUTO = {"dissem": "", "hot_slots": -1, "fused_nb": 0,
               "shard_devices": -1, "unroll": 0, "flight_drain_every": 0}


@dataclass
class PlaneNode:
    """Host-side metadata for one registered node id."""

    id: int
    name: str
    addr: str = ""
    port: int = 0
    tags: Dict[str, str] = field(default_factory=dict)
    last_hb: float = 0.0
    writer: Optional[asyncio.StreamWriter] = None
    # lifecycle the AGENTS should believe (derived from kernel verdicts)
    status: str = "alive"          # alive | failed | left
    left_at: float = 0.0           # monotonic time the node went "left"


def registration_proof(key_b64: str, name: str, addr: str, port: int,
                       ts: int, nonce: bytes,
                       tags: Optional[Dict[str, str]] = None) -> bytes:
    """HMAC proof binding a registration to the gossip keyring.

    Shared by the plane (verify) and TpuSerfPool (prove): the agents'
    ``encrypt`` gossip key doubles as the plane admission secret, so
    the security posture does not silently downgrade when
    ``gossip_backend=tpu`` replaces the encrypted serf fabric
    (reference: serf rejects plaintext when a keyring is armed).
    The MAC covers every register field — including tags, which carry
    role/dc routing decisions — so no field is forgeable.  The fields
    are msgpack-canonicalized (length-prefixed), never joined with
    in-band delimiters: two different registrations can never serialize
    to the same MAC input."""
    msg = msgpack.packb(
        ["consul-tpu-plane-register", name, addr, int(port), int(ts),
         nonce, sorted((tags or {}).items())], use_bin_type=True)
    return hmac.new(base64.b64decode(key_b64), msg,
                    hashlib.sha256).digest()


class GossipPlane:
    """The daemon: kernel session + bridge server + event fanout."""

    def __init__(self, config: Optional[PlaneConfig] = None) -> None:
        self.config = config or PlaneConfig()
        self._seen_nonces: Dict[tuple, float] = {}  # (ts, nonce) -> expiry
        self._nodes_by_name: Dict[str, PlaneNode] = {}
        self._nodes_by_id: Dict[int, PlaneNode] = {}
        self._free_ids: List[int] = []
        self._declared_dead: Set[int] = set()
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()   # every live bridge connection's writer
        self._tick_task: Optional[asyncio.Task] = None
        self._started = False
        # kernel session state, created in start() (jax import deferred)
        self._p = None
        self._state = None
        self._key = None
        self._fail: Optional[np.ndarray] = None
        self._rounds_done = 0
        self._t0 = 0.0
        self._ndev = 1       # resolved in start() (config.shard_devices)
        self._run = None     # bound round-runner (sharded or not)
        # Autotune resolution (obs/tuner.py), bound in start(); the
        # pre-start defaults keep operator queries and stop() safe.
        self._autotune = None
        self._unroll = 4
        self._drain_every = FLIGHT_DRAIN_EVERY
        # Events-kernel session: fires queue between dispatches; slot
        # metadata (payloads never enter device arrays) + delivery
        # bookkeeping live host-side, keyed by (slot, start_round).
        self._ev_state = None
        self._fire_queue: List[tuple] = []   # (origin_id, meta dict)
        self._ev_meta: Dict[tuple, Dict[str, Any]] = {}
        # Kernel flight recorder: on-device ring written inside the jit
        # step, drained host-side every FLIGHT_DRAIN_EVERY dispatches.
        self._flight = None                  # FlightRing (device)
        self._flight_recorder = None         # obs.flight.FlightRecorder
        self._dispatches_since_drain = 0
        # Structured membership-event batch (PR 18): detect/refute/join
        # verdicts accumulate per drain cadence with the node-id →
        # catalog identity resolved ONCE at queue time via the
        # admission table (_member_wire snapshots name/addr/tags/state),
        # then ship as one ``evbatch`` frame instead of per-event host
        # dicts.  Own counter, not _dispatches_since_drain: the flight
        # drain early-returns on flightless planes and must not gate
        # event delivery.
        self._pending_events: List[Dict[str, Any]] = []
        self._dispatches_since_event_flush = 0
        # Journey ledger: the round-start stamp of the dispatch whose
        # verdicts are being queued (detect stage = device round to
        # host-visible verdict).  0.0 while no dispatch is in flight.
        self._journey_round0 = 0.0
        # Detection-latency observatory: on-device histogram banks
        # accumulated inside the same jit step, drained on the flight
        # cadence into the host recorder + SLO burn-rate tracker.
        self._hist = None                    # kernel.HistBank (device)
        self._hist_recorder = None           # obs.hist.HistRecorder
        self._slo = None                     # obs.slo.SloTracker
        self._slo_board = None               # obs.slo.SloBoard (nemesis)
        self._nem = None                     # nemesis.NemesisParams
        self._nem_state = None               # kernel.NemState (device)
        self._nem_fail = None                # scheduled kills (np i32 [n])
        # Device/kernel observatory (obs/devstats.py): dispatch-latency
        # hists, rounds/s EWMA, HBM occupancy, compile + roofline
        # telemetry.  None when CONSUL_TPU_DEV_OBS=0 — every hot-path
        # hook is then a single attribute-is-None test.
        self._dev = None                     # devstats.DevStats
        self._cache_dir = ""                 # persistent compile cache

    # -- universe ----------------------------------------------------------

    @property
    def n_universe(self) -> int:
        return self.config.capacity + self.config.sim_nodes

    def _alloc_id(self) -> Optional[int]:
        if self._free_ids:
            return self._free_ids.pop()
        return None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        import jax

        from consul_tpu.gossip.kernel import NEVER, init_state
        from consul_tpu.gossip.params import SwimParams

        # Persistent compilation cache: the dispatch shape compiles in
        # seconds-to-minutes; across restarts the plane should pay that
        # once per (params, jaxlib), not once per boot (same wiring as
        # bench.py _setup_jax; best-effort — older jaxlibs lack it).
        cache_dir = os.environ.get(
            "CONSUL_TPU_COMPILE_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "consul_tpu_jax_cache"))
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            self._cache_dir = cache_dir
        except Exception:  # noqa: E02 — cache is an optimization only
            self._cache_dir = ""

        c = self.config
        n = self.n_universe
        # Resolve the autotuned knobs before any kernel object exists:
        # explicit config value > persisted per-platform verdict >
        # registry default (obs/tuner.py).  The resolution rows are
        # served on the ``autotune`` bridge frame for the agent's
        # operator route and prom families.
        from consul_tpu.obs import tuner
        explicit = {f: getattr(c, f) for f in TUNED_FIELDS
                    if getattr(c, f) != _TUNED_AUTO[f]}
        self._autotune = tuner.resolve(
            list(TUNED_FIELDS), explicit,
            platform=jax.default_backend(),
            device_count=len(jax.devices()))
        knob = self._autotune.value
        self._p = SwimParams(
            n=n, slots=c.slots, probe_every=c.probe_every,
            suspicion_mult=c.suspicion_mult,
            gossip_interval_s=c.gossip_interval_s,
            dissem=knob("dissem"), hot_slots=int(knob("hot_slots")),
            fused_nb=int(knob("fused_nb")))
        self._unroll = max(1, int(knob("unroll")))
        self._drain_every = max(1, int(knob("flight_drain_every")))
        self._state = init_state(self._p)
        # Only registered agents (and live sim nodes) are members; start
        # with an empty membership and admit on register.
        self._state = self._state._replace(
            member=self._state.member.at[:].set(False))
        if c.sim_nodes:
            # Simulated nodes occupy ids [capacity, capacity+sim); they
            # are members that never fail (load/dissemination substrate).
            self._state = self._state._replace(
                member=self._state.member.at[c.capacity:].set(True))
        self._key = jax.random.PRNGKey(int.from_bytes(os.urandom(4), "big"))
        self._fail = np.full((n,), int(NEVER), np.int32)
        # Joins are kernel dynamics too: registration sets the id's
        # join_round and the kernel admits it on-device (alive@inc
        # rumor, kernel._join_tick); EV_JOIN broadcasts only once the
        # kernel's membership flip is visible (_pending_join).
        self._join = np.full((n,), int(NEVER), np.int32)
        self._pending_join: Dict[int, PlaneNode] = {}
        self._free_ids = list(range(c.capacity - 1, -1, -1))
        # Vectorized lapse bookkeeping (O(capacity) numpy per tick, not
        # an O(capacity) Python loop): heartbeat times + lifecycle masks
        # indexed by node id.
        self._hb_at = np.zeros((c.capacity,), np.float64)
        self._eligible = np.zeros((c.capacity,), bool)  # registered, not left
        self._alive_mask = np.zeros((c.capacity,), bool)  # status == alive
        # Pre-compile the dispatch shape before serving: the first jit
        # compile takes seconds-to-minutes and must not stall the event
        # loop (a stalled plane cannot ingest heartbeats, which would
        # read as every agent lapsing at once).
        import jax.numpy as jnp

        from consul_tpu.gossip.events import init_events, run_event_rounds
        from consul_tpu.gossip.kernel import (
            _check_shardable, init_flight, init_hist, init_nem_state,
            run_rounds, run_rounds_sharded, shard_state)
        from consul_tpu.obs.flight import FlightRecorder
        from consul_tpu.obs.hist import HistRecorder
        from consul_tpu.obs.slo import SloBoard, SloTracker
        self._ev_state = init_events(self._p, slots=c.event_slots)
        # Nemesis injection (config docstring): the schedule is a jit
        # static, the scenario's static kills merge into the dispatch
        # fail rounds, and LHM scenarios thread NemState through the
        # donated carry.
        self._nem = None
        self._nem_state = None
        self._nem_fail = None
        if c.nemesis:
            from consul_tpu.gossip.nemesis import build as build_nemesis
            sc = build_nemesis(c.nemesis, n)
            self._nem = sc.nem
            self._nem_fail = (np.asarray(sc.fail_round)
                              if bool(sc.killed.any()) else None)
            if sc.nem.needs_state:
                self._nem_state = init_nem_state(n)
        # Resolve the device count for the sharded round (config
        # docstring: 1 = off, >1 = explicit/strict, 0 = all devices
        # when the alignment constraints hold, -1 = verdict).
        ndev = c.shard_devices
        tuned_shard = ndev < 0
        if tuned_shard:
            ndev = int(knob("shard_devices"))
        if ndev == 0:
            ndev = len(jax.devices())
            if n % ndev or n % self._p.probe_every:
                ndev = 1
        if ndev > 1:
            if tuned_shard:
                # A verdict settled on another topology must not brick
                # the boot: misaligned => degrade to single-device.
                try:
                    if ndev > len(jax.devices()):
                        raise ValueError("fewer devices than verdict")
                    _check_shardable(self._p, ndev)
                except ValueError:
                    ndev = 1
            else:
                _check_shardable(self._p, ndev)  # raises, constraint
        if ndev > 1:
            self._state = shard_state(self._state, ndev)
        self._ndev = ndev
        if ndev > 1:
            def _run(state, key, fail, steps, join_round, flight, hist,
                     nem_state=None):
                return run_rounds_sharded(
                    state, key, fail, self._p, steps=steps, trace=True,
                    join_round=join_round, flight=flight, hist=hist,
                    nem=self._nem, nem_state=nem_state, ndev=self._ndev,
                    unroll=self._unroll)
        else:
            def _run(state, key, fail, steps, join_round, flight, hist,
                     nem_state=None):
                return run_rounds(
                    state, key, fail, self._p, steps=steps, trace=True,
                    join_round=join_round, flight=flight, hist=hist,
                    nem=self._nem, nem_state=nem_state,
                    unroll=self._unroll)
        self._run = _run
        # Flight ring sized so a full drain interval fits with headroom
        # (bounded-burst catch-up can run up to max_burst extra
        # dispatches before the drain counter trips).
        self._flight = init_flight(
            ring_rounds=4 * self._drain_every * STEPS_PER_TICK)
        self._flight_recorder = FlightRecorder()
        self._dispatches_since_drain = 0
        self._pending_events = []
        self._dispatches_since_event_flush = 0
        # Observatory banks ride the same dispatch: cumulative on-device
        # histograms drained on the flight cadence, feeding the live SLO.
        self._hist = init_hist()
        self._hist_recorder = HistRecorder()
        objective = c.slo_objective_rounds or (
            self._p.suspicion_max_rounds + self._p.probe_every)
        self._slo = SloTracker(objective,
                               attainment_target=c.slo_attainment_target)
        self._slo_board = SloBoard(
            objective, attainment_target=c.slo_attainment_target)
        # Device/kernel observatory (obs/devstats.py): created here so
        # the warmup compiles below are its first compile-telemetry
        # samples; compiled out to a None attribute when disabled.
        from consul_tpu.obs import devstats
        self._dev = devstats.DevStats() if devstats.enabled() else None
        if self._dev is not None:
            self._dev.set_session(slots=c.slots, n=n,
                                  steps_per_dispatch=STEPS_PER_TICK,
                                  ndev=ndev, dissem=self._p.dissem)
        # run_rounds donates state+flight+hist (+nem_state): warm up on
        # copies so the session arrays survive the throwaway compile
        # dispatch.  The wall time around each warmup is the compile
        # telemetry; persistent-cache hit/miss is read off the cache
        # dir's entry count (a hit persists nothing new).
        cache_before = devstats.cache_entries(self._cache_dir)
        t_compile = time.monotonic()
        jax.block_until_ready(self._run(
            jax.tree.map(jnp.copy, self._state), self._key,
            jnp.asarray(self._fail), STEPS_PER_TICK,
            jnp.asarray(self._join),
            jax.tree.map(jnp.copy, self._flight),
            jax.tree.map(jnp.copy, self._hist),
            (jax.tree.map(jnp.copy, self._nem_state)
             if self._nem_state is not None else None))[0])
        if self._dev is not None:
            after = devstats.cache_entries(self._cache_dir)
            hit = (None if cache_before is None or after is None
                   else after == cache_before)
            self._dev.note_compile("plane_dispatch",
                                   time.monotonic() - t_compile,
                                   cache_hit=hit)
            cache_before = after
        t_compile = time.monotonic()
        jax.block_until_ready(run_event_rounds(
            self._ev_state, self._key, self._state.member, self._p,
            steps=STEPS_PER_TICK)[0])
        if self._dev is not None:
            after = devstats.cache_entries(self._cache_dir)
            hit = (None if cache_before is None or after is None
                   else after == cache_before)
            self._dev.note_compile("event_dispatch",
                                   time.monotonic() - t_compile,
                                   cache_hit=hit)
            # Lowered cost_analysis of the dispatch shape: FLOPs +
            # bytes-accessed estimates feed the derived roofline gauge.
            # Lowering only traces (no second compile; the inner jits'
            # donation is inlined away — the profile_kernel pattern);
            # best-effort across backends.
            try:
                lowered = jax.jit(
                    lambda st, k, f, j, fl, h, ns: self._run(
                        st, k, f, STEPS_PER_TICK, j, fl, h, ns)[0]
                ).lower(self._state, self._key, jnp.asarray(self._fail),
                        jnp.asarray(self._join), self._flight,
                        self._hist, self._nem_state)
                self._dev.note_cost("plane_dispatch",
                                    lowered.cost_analysis(),
                                    steps=STEPS_PER_TICK)
            except Exception:  # noqa: E02 — estimates only, never fatal
                pass
            self._dev.sample_devices()
        self._rounds_done = 0
        self._t0 = time.monotonic()

        if c.unix_path:
            try:
                os.unlink(c.unix_path)
            except FileNotFoundError:
                pass
            self._server = await asyncio.start_unix_server(
                self._serve, c.unix_path)
        else:
            self._server = await asyncio.start_server(
                self._serve, c.bind_addr, c.bind_port)
        self._tick_task = asyncio.get_event_loop().create_task(self._ticker())
        self._started = True

    @property
    def local_addr(self) -> tuple:
        socks = self._server.sockets if self._server else []
        return socks[0].getsockname()[:2] if socks else ("", 0)

    async def stop(self) -> None:
        self._started = False
        if self._tick_task is not None:
            self._tick_task.cancel()
            try:
                await self._tick_task
            except asyncio.CancelledError:
                pass  # we just cancelled it
            except Exception:  # noqa: E02 — tick's own failure; shutting down
                pass
        # Close every live connection BEFORE wait_closed(): since
        # Python 3.12.1 Server.wait_closed() waits for active handlers,
        # and agents' native heartbeat threads keep their sockets open
        # indefinitely — stop() would hang forever otherwise.
        for writer in list(self._conns):
            try:
                writer.close()
            except Exception:  # noqa: E02 — best-effort close at teardown
                pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- kernel session ----------------------------------------------------

    def _due_rounds(self) -> int:
        elapsed = time.monotonic() - self._t0
        return int(elapsed / self.config.gossip_interval_s) - self._rounds_done

    async def _ticker(self) -> None:
        """Map wall time onto kernel rounds: every gossip interval one
        round is due; catch-up runs whole STEPS_PER_TICK dispatches.

        Catch-up is BOUNDED: if the backend cannot sustain the
        configured round rate (slow CPU kernel, transient recompile),
        an unbounded drain would monopolize the event loop, starve the
        heartbeat readers, and mass-declare the cluster dead.  After
        the burst limit the round clock is re-based — the protocol runs
        slower than configured, which SWIM tolerates; a frozen plane it
        does not."""
        interval = self.config.gossip_interval_s
        max_burst = 4  # dispatches per wake before yielding/re-basing
        while True:
            await asyncio.sleep(interval * STEPS_PER_TICK / 2)
            try:
                self._mark_lapsed()
                self._reap_tombstones()
                burst = 0
                while self._due_rounds() >= STEPS_PER_TICK:
                    self._dispatch()
                    burst += 1
                    if burst >= max_burst:
                        if self._due_rounds() >= STEPS_PER_TICK:
                            # Hopelessly behind: drop the backlog.
                            self._t0 = (time.monotonic()
                                        - self._rounds_done * interval)
                        break
            except asyncio.CancelledError:
                raise
            except Exception as e:  # keep the plane alive; surface once
                import sys
                print(f"[gossip-plane] tick error: {e!r}", file=sys.stderr)
                await asyncio.sleep(interval * 4)

    def _mark_lapsed(self) -> None:
        """Heartbeat lapse -> the node starts failing kernel probes (the
        physical probe-loss signal); resumed heartbeat -> it answers
        again (the kernel's refutation path takes it from there).
        Pure numpy over the id-indexed arrays: stays cheap at hundreds
        of live agents and tens-of-thousands capacity."""
        now = time.monotonic()
        rnd = self._rounds_done
        from consul_tpu.gossip.kernel import NEVER
        cap = self.config.capacity
        real = self._fail[:cap]
        lapsed = (now - self._hb_at) > self.config.hb_lapse_s
        failing = real != int(NEVER)
        real[self._eligible & lapsed & ~failing] = rnd
        # back before any verdict: stop failing probes; an active
        # suspicion episode resolves by on-device refutation
        # (incarnation bump)
        real[self._eligible & self._alive_mask & ~lapsed & failing] = \
            int(NEVER)

    def _reap_tombstones(self) -> None:
        """Drop "left" names whose tombstone window expired (serf's
        tombstone reap): without this, node-name churn grows the member
        list and every welcome snapshot without bound.  Also release
        registrations that died MID-JOIN: a node whose heartbeats
        lapsed before the kernel ever admitted it was never announced
        to anyone — it simply ceases (otherwise its id leaks and
        welcome snapshots list a ghost forever)."""
        now = time.monotonic()
        cutoff = now - self.config.tombstone_timeout_s
        for name in [n for n, node in self._nodes_by_name.items()
                     if node.status == "left" and node.id < 0
                     and node.left_at < cutoff]:
            del self._nodes_by_name[name]
        from consul_tpu.gossip.kernel import NEVER
        ghost_cutoff = now - max(10 * self.config.hb_lapse_s, 5.0)
        for node in [n for n in self._nodes_by_id.values()
                     if n.status == "joining"
                     and self._hb_at[n.id] < ghost_cutoff]:
            i = node.id
            self._eligible[i] = False
            self._alive_mask[i] = False
            self._join[i] = int(NEVER)
            self._fail[i] = int(NEVER)
            self._pending_join.pop(i, None)
            self._nodes_by_id.pop(i, None)
            self._nodes_by_name.pop(node.name, None)
            self._free_ids.append(i)
            node.id = -1
            # Kill any still-open session: a revenant whose heartbeats
            # resume AFTER the reap must re-register through the redial
            # path (fresh id, fresh welcome) — the hb handler cannot
            # re-admit an id-less node, and a zombie that believes it
            # is a member while the plane no longer lists it is worse
            # than a reconnect.
            if node.writer is not None:
                try:
                    node.writer.close()
                except Exception:  # noqa: E02 — best-effort close
                    pass
                node.writer = None

    def _dispatch(self) -> None:
        """Advance the kernel by STEPS_PER_TICK rounds and fan out the
        membership transitions the verdicts imply."""
        import jax.numpy as jnp

        from consul_tpu.gossip.kernel import PHASE_DEAD

        dev = self._dev
        t_disp = time.monotonic() if dev is not None else 0.0
        # The journey's detect stage anchors on the same round-start
        # stamp; take one when the device recorder didn't already.
        self._journey_round0 = (
            t_disp if t_disp else
            (time.monotonic() if _journey.journey is not None else 0.0))
        fail = self._fail
        if self._nem_fail is not None:
            # Scenario-scheduled kills (absolute kernel rounds) override
            # live heartbeats — an injected fault IS the node failing.
            fail = np.minimum(fail, self._nem_fail)
        out, trace = self._run(
            self._state, self._key, jnp.asarray(fail),
            STEPS_PER_TICK, jnp.asarray(self._join), self._flight,
            self._hist, self._nem_state)
        if self._nem_state is not None:
            state, self._flight, self._hist, self._nem_state = out
        else:
            state, self._flight, self._hist = out
        self._state = state
        self._rounds_done += STEPS_PER_TICK
        # Amortized drain: one host transfer per resolved drain cadence
        # (default FLIGHT_DRAIN_EVERY dispatches, >= 64 rounds), never
        # per round.
        self._dispatches_since_drain += 1
        if self._dispatches_since_drain >= self._drain_every:
            self._drain_flight()

        # Joins the kernel admitted this dispatch: the EV_JOIN the
        # agents see is the kernel's membership flip, not host-side
        # bookkeeping (robust to JOIN-slot overflow — the flip is the
        # ground truth; the rumor slot only drives dissemination).
        if self._pending_join:
            mem = np.asarray(state.member)
            for i, node in list(self._pending_join.items()):
                if node.status != "joining":   # evicted while pending
                    self._pending_join.pop(i, None)
                elif mem[i]:
                    self._pending_join.pop(i, None)
                    node.status = "alive"
                    self._queue_member_event(EV_JOIN, node)

        # Dead verdicts declared during this dispatch (trace carries the
        # per-round slot registers: subject + phase).
        slot_node = np.asarray(trace.slot_node)    # [T, S]
        slot_phase = np.asarray(trace.slot_phase)  # [T, S]
        if dev is not None:
            # The trace fetch above forced the device work, so this is
            # the dispatch's true host-visible latency.
            dev.note_dispatch(
                "sharded_round" if self._ndev > 1 else "round_step",
                (time.monotonic() - t_disp) * 1e3, STEPS_PER_TICK)
        dead_mask = (slot_phase == PHASE_DEAD) & (slot_node >= 0)
        for sid in np.unique(slot_node[dead_mask]):
            node = self._nodes_by_id.get(int(sid))
            if node is None or node.id in self._declared_dead:
                continue
            if node.status != "alive":
                continue
            self._declared_dead.add(node.id)
            node.status = "failed"
            self._alive_mask[node.id] = False
            self._queue_member_event(EV_FAILED, node)

        # Ship the cadence's structured batch: the counter only runs
        # while events are queued, so the first event of a quiet period
        # waits at most one drain cadence, and a steady trickle still
        # coalesces a full cadence's worth per frame.
        if not self._pending_events:
            self._dispatches_since_event_flush = 0
        else:
            self._dispatches_since_event_flush += 1
            if self._dispatches_since_event_flush >= self._drain_every:
                self._flush_member_events()

        self._dispatch_events()

    def _dispatch_events(self) -> None:
        """User events ride the dissemination kernel: queued fires enter
        the [E, N] flood — the lamport stamp, the flood dynamics, and
        the convergence observable are kernel state (reference:
        EventFire → serf UserEvent → gossip broadcast,
        consul/internal_endpoint.go:87).

        Registered agents are SEEDED into the flood and notified over
        TCP with the kernel's ltime: every real agent "knows" the event
        the moment it is stamped (host fanout is the low-latency
        notification; serf's UDP delivery to a handful of live agents
        is similarly instant at these scales).  The roll-based flood
        then carries it across the hybrid universe — the sim swarm's
        convergence is the kernel-measured statistic.  (Per-column
        delivery to agents is NOT used: circulant shifts over a
        sparsely-registered id space hit the few live member ids too
        rarely before the spread budget closes — the dense-membership
        approximation the rolls rely on, documented in
        kernel.gossip_offsets, does not hold for the agent subset.)"""
        import jax.numpy as jnp

        from consul_tpu.gossip.events import _SEEN, fire_events, \
            run_event_rounds

        if not self._fire_queue and not self._ev_meta:
            # No live event anywhere: skip the whole event dispatch
            # (the kernel's event clock lags while idle — every TTL
            # comparison is relative to it, so lagging is free, and a
            # quiescent plane pays nothing for the events tier).
            return
        ev = self._ev_state
        if self._fire_queue:
            fires, self._fire_queue = self._fire_queue, []
            before_used = np.asarray(ev.slot_used)
            fire_round = int(ev.round)
            ev = fire_events(ev, jnp.asarray([f[0] for f in fires],
                                             jnp.int32))
            # fire_events hands free slots out in ascending index order,
            # one per fire — recover the mapping to attach host metadata
            # (name/payload never enter device arrays).
            free_list = [s for s in range(before_used.shape[0])
                         if not before_used[s]]
            ltimes = np.asarray(ev.ltime)
            live = [n for n in self._nodes_by_id.values()
                    if n.id >= 0 and n.status in ("alive", "joining")]
            seed_ids = jnp.asarray([n.id for n in live] or [0], jnp.int32)
            for k, (_oid, meta) in enumerate(fires):
                if k >= len(free_list):
                    # dropped, counted in ev.drops — overflow is never
                    # silent (same posture as the membership slots)
                    continue
                s = free_list[k]
                meta = dict(meta, ltime=int(ltimes[s]))
                self._ev_meta[(s, fire_round)] = meta
                if live:
                    # Seeding = witnessing: the seeded nodes' lamport
                    # clocks advance by the kernel's witness rule
                    # (max(clock, event)+1) so a later fire from any
                    # agent is stamped AFTER this event.
                    nl = ev.node_ltime
                    ev = ev._replace(
                        has=ev.has.at[s, seed_ids].set(jnp.uint8(_SEEN)),
                        n_seen=ev.n_seen.at[s].set(len(live)),
                        node_ltime=nl.at[seed_ids].set(
                            jnp.maximum(nl[seed_ids], ev.ltime[s]) + 1))
                for node in live:
                    if node.writer is not None:
                        self._send(node.writer, {
                            "t": "user", "name": meta["name"],
                            "payload": meta["payload"],
                            "ltime": meta["ltime"], "from": meta["from"],
                            "coalesce": meta["coalesce"]})

        ev, _cov = run_event_rounds(ev, self._key, self._state.member,
                                    self._p, steps=STEPS_PER_TICK)
        self._ev_state = ev
        # GC host metadata for slots whose flood window closed.
        if self._ev_meta:
            used = np.asarray(ev.slot_used)
            startr = np.asarray(ev.start_round)
            for (s, sr) in list(self._ev_meta):
                if not used[s] or int(startr[s]) != sr:
                    self._ev_meta.pop((s, sr), None)

    def _drain_flight(self) -> None:
        """Pull the on-device flight ring to the host recorder.  One
        device->host transfer for the whole batch; called every
        FLIGHT_DRAIN_EVERY dispatches and on-demand for a ``flight``
        bridge query."""
        if self._flight is None or self._flight_recorder is None:
            return
        self._dispatches_since_drain = 0
        dev = self._dev
        t_drain = time.monotonic() if dev is not None else 0.0
        cursor = int(self._flight.cursor)
        if cursor == self._flight_recorder.last_cursor:
            return  # nothing new since the last drain (banks idle too)
        self._flight_recorder.ingest(
            np.asarray(self._flight.rows), cursor)
        self._drain_hist()
        if dev is not None:
            dev.note_drain((time.monotonic() - t_drain) * 1e3)
            # Heavier device sampling (HBM stats + live-buffer census)
            # rides this cadence, never the per-dispatch path.
            dev.sample_devices()

    def _drain_hist(self) -> None:
        """Pull the on-device histogram banks to the host recorder and
        feed the detect delta to the SLO tracker.  Rides the flight
        drain cadence; also called on-demand for an ``slo`` query."""
        if self._hist is None or self._hist_recorder is None:
            return
        scenario = self._nem.scenario if self._nem is not None else None
        deltas = self._hist_recorder.ingest(
            {f: np.asarray(getattr(self._hist, f))
             for f in self._hist._fields},
            scenario=scenario)
        if "detect" in deltas:
            if self._slo is not None:
                self._slo.observe(deltas["detect"])
            if scenario and self._slo_board is not None:
                self._slo_board.observe(scenario, deltas["detect"])

    def event_coverage(self) -> Dict[int, float]:
        """Live event slots -> fraction of members holding the event
        (the convergence observable, incl. the sim swarm)."""
        from consul_tpu.gossip.events import coverage
        cov = np.asarray(coverage(self._ev_state, self._state.member))
        used = np.asarray(self._ev_state.slot_used)
        return {int(s): float(cov[s]) for s in np.nonzero(used)[0]}

    # -- registration / membership ops ------------------------------------

    def _admit(self, node: PlaneNode) -> None:
        """(Re)admission is a kernel join: the host only releases the id
        (clears membership + any stale episode — control-plane surgery
        between dispatches) and stamps ``join_round``; the kernel's
        join tick performs the membership flip, the incarnation bump,
        and the alive@inc dissemination on-device, and EV_JOIN is
        broadcast when that flip lands (_dispatch)."""
        from consul_tpu.gossip.kernel import NEVER
        i = node.id
        self._fail[i] = int(NEVER)
        st = self._state
        member = st.member.at[i].set(False)
        slot = int(st.slot_of_node[i])
        if slot >= 0:
            st = st._replace(
                heard=st.heard.at[slot, :].set(0),
                slot_node=st.slot_node.at[slot].set(-1),
                slot_phase=st.slot_phase.at[slot].set(0),
                slot_dead_round=st.slot_dead_round.at[slot].set(-1),
                slot_of_node=st.slot_of_node.at[i].set(-1),
            )
        self._state = st._replace(member=member)
        self._join[i] = self._rounds_done  # next dispatch's first round
        self._declared_dead.discard(i)
        node.status = "joining"
        self._pending_join[i] = node
        node.last_hb = time.monotonic()
        self._hb_at[i] = node.last_hb
        self._eligible[i] = True
        self._alive_mask[i] = True

    def _evict(self, node: PlaneNode, status: str) -> None:
        from consul_tpu.gossip.kernel import NEVER
        i = node.id
        if i < 0:
            return  # already evicted (duplicate leave frame): -1 would
                    # otherwise index the HIGHEST id's lifecycle entries
        self._eligible[i] = False
        self._alive_mask[i] = False
        self._join[i] = int(NEVER)
        self._pending_join.pop(i, None)
        st = self._state
        st = st._replace(member=st.member.at[i].set(False))
        slot = int(st.slot_of_node[i])
        if slot >= 0:
            st = st._replace(
                heard=st.heard.at[slot, :].set(0),
                slot_node=st.slot_node.at[slot].set(-1),
                slot_phase=st.slot_phase.at[slot].set(0),
                slot_dead_round=st.slot_dead_round.at[slot].set(-1),
                slot_of_node=st.slot_of_node.at[i].set(-1),
            )
        self._state = st
        node.status = status
        if status == "left":
            # A left node's id goes back to the pool (name-churn must
            # not exhaust capacity); the PlaneNode stays listed as
            # "left" for members-output parity with serf's tombstone
            # window, and re-registers through the id-less path.
            self._declared_dead.discard(i)
            self._nodes_by_id.pop(i, None)
            self._free_ids.append(i)
            node.id = -1
            node.left_at = time.monotonic()

    def members_wire(self) -> List[Dict[str, Any]]:
        return [self._member_wire(n) for n in self._nodes_by_name.values()]

    def _stats_wire(self) -> Dict[str, Any]:
        by = {"alive": 0, "failed": 0, "left": 0, "joining": 0}
        for node in self._nodes_by_name.values():
            by[node.status] = by.get(node.status, 0) + 1
        st = self._state
        return {
            "t": "stats", "round": self._rounds_done,
            "capacity": self.config.capacity,
            "sim_nodes": self.config.sim_nodes,
            "members": by,
            "pending_joins": len(self._pending_join),
            "event_slots_live": len(self._ev_meta),
            # on-demand device sync: these force a fetch, which is fine
            # for an operator query
            "kernel": {"drops": int(st.drops),
                       "n_detected": int(st.n_detected),
                       "n_false_dead": int(st.n_false_dead),
                       "n_refuted": int(st.n_refuted)},
        }

    def _slo_wire(self) -> Dict[str, Any]:
        """/v1/agent/slo payload: SLO burn-rate snapshot + exact latency
        percentiles + cumulative histogram families.  Drains the device
        banks first (on-demand sync — fine for an operator query)."""
        self._drain_hist()
        out: Dict[str, Any] = {"t": "slo"}
        if self._nem is not None:
            out["scenario"] = self._nem.scenario
        if self._slo is not None:
            out["slo"] = self._slo.snapshot()
        if self._hist_recorder is not None:
            out["latency"] = self._hist_recorder.summary()
            out["hists"] = self._hist_recorder.families()
            # Per-scenario breakdown: one burn-rate + percentile row per
            # nemesis scenario that has attributed detections.
            board = (self._slo_board.snapshot()
                     if self._slo_board is not None else {})
            scns = self._hist_recorder.scenarios()
            if scns:
                out["scenarios"] = {
                    scn: {"slo": board.get(scn),
                          "latency": self._hist_recorder.summary(scn)}
                    for scn in scns}
        return out

    def _device_wire(self) -> Dict[str, Any]:
        """/v1/agent/device payload: the device/kernel observatory's
        dispatch hists, rounds/s EWMA, per-device HBM + live-buffer
        rows, compile + roofline telemetry — plus the ready-to-render
        Prometheus families the agent splices into its scrape.  A
        disabled observatory reports just that (the JSON twin of the
        compiled-out hooks)."""
        out: Dict[str, Any] = {"t": "device",
                               "enabled": self._dev is not None}
        if self._dev is not None:
            self._dev.sample_devices()
            out.update(self._dev.wire())
            hists, gauges, counters = self._dev.prom_families()
            out["families"] = {"histograms": hists, "gauges": gauges,
                               "counters": counters}
        return out

    def _autotune_wire(self) -> Dict[str, Any]:
        """``autotune`` bridge frame: the knob resolution this plane
        booted with (obs/tuner.py Resolution.wire — per-knob value,
        source, evidence keys, reason + verdict metadata)."""
        out: Dict[str, Any] = {"t": "autotune"}
        if self._autotune is not None:
            out.update(self._autotune.wire())
        return out

    def _profile_wire(self, steps: int, phases: bool = False
                      ) -> Dict[str, Any]:
        """On-demand device profiling: run ``steps`` kernel rounds on
        COPIES of the session arrays (the dispatch donates its inputs)
        under ``jax.profiler.trace`` to a fresh temp dir, optionally
        followed by per-phase timings through the shared harness
        (tools/profile_kernel).  Synchronous by design — an operator
        query against the already-compiled dispatch shape, bounded so
        it cannot recompile or run away."""
        import tempfile

        payload: Dict[str, Any] = {"t": "profile"}
        try:
            import jax
            import jax.numpy as jnp

            steps = max(STEPS_PER_TICK,
                        min(int(steps), 64 * STEPS_PER_TICK))
            ndisp = -(-steps // STEPS_PER_TICK)
            fail = jnp.asarray(self._fail)
            join = jnp.asarray(self._join)

            def _one_dispatch():
                out = self._run(
                    jax.tree.map(jnp.copy, self._state), self._key, fail,
                    STEPS_PER_TICK, join,
                    jax.tree.map(jnp.copy, self._flight),
                    jax.tree.map(jnp.copy, self._hist),
                    (jax.tree.map(jnp.copy, self._nem_state)
                     if self._nem_state is not None else None))
                return out[0][0]

            trace_dir = tempfile.mkdtemp(prefix="consul-tpu-profile-")
            t0 = time.perf_counter()
            with jax.profiler.trace(trace_dir):
                for _ in range(ndisp):
                    jax.block_until_ready(_one_dispatch())
            wall = time.perf_counter() - t0
            payload.update(
                trace_dir=trace_dir, rounds=ndisp * STEPS_PER_TICK,
                dispatches=ndisp, wall_s=wall,
                round_ms=wall * 1e3 / (ndisp * STEPS_PER_TICK))
            # The same roofline-utilization derivation the devstats
            # observatory and bench.py report (obs/devstats.py) —
            # profiling paths must agree on one figure.
            from consul_tpu.obs import devstats
            util = devstats.roofline_utilization(
                devstats.dense_bytes_per_round(self._p.slots, self._p.n),
                1000.0 / payload["round_ms"])
            if util is not None:
                payload["roofline_utilization"] = round(util, 6)
            if phases:
                payload["phases_ms"] = self._profile_phases()
        except Exception as e:  # noqa: E02 — profiling must never kill the plane
            payload["error"] = f"{type(e).__name__}: {e}"
        return payload

    def _profile_phases(self) -> Dict[str, float]:
        """Per-phase timings (ms) via tools/profile_kernel's harness.
        Single-device sessions only — the standalone phase callables
        take unsharded arrays; a sharded session reports just the
        profiler capture."""
        if self._ndev > 1:
            return {}
        import jax
        import jax.numpy as jnp

        from consul_tpu.gossip.kernel import (
            _age_tick, _disseminate, _probe_tick)
        from tools.profile_kernel import make_timed, timed

        p, st, key = self._p, self._state, self._key
        fail = jnp.asarray(self._fail)
        mf = jnp.where(st.member, fail, -1)
        rx = (fail > st.round) & st.member
        cc = jnp.minimum(p.max_confirmations,
                         jnp.maximum(st.slot_nsusp - 1, 0))

        def f_probe(s, mf_):
            keys = jax.random.split(key, 4)
            carry = (s.heard, s.slot_node, s.slot_phase, s.slot_inc,
                     s.slot_start, s.slot_nsusp, s.slot_dead_round,
                     s.slot_of_node, s.incarnation, s.member, s.drops)
            return _probe_tick(p, s.round, keys, mf_, carry)[0]

        # Label parity with tools/profile_kernel: the swar-family
        # strategies age INSIDE dissemination, so their row is the
        # merged age+gossip phase and the standalone age row is marked
        # as such; planes really dispatches both.
        dis_key = ("disseminate" if p.dissem == "planes"
                   else "age_gossip_merge")
        out = {
            "age_tick_standalone": timed(make_timed(_age_tick), st.heard,
                                         iters=4, warmup=1),
            "probe_tick": timed(make_timed(f_probe), st, mf,
                                iters=4, warmup=1),
            dis_key: timed(
                make_timed(lambda h, m_, c_: _disseminate(
                    p, st.round, key, h, m_, rx, c_)),
                st.heard, mf, cc, iters=4, warmup=1),
        }
        return {k: v * 1e3 for k, v in out.items()}

    # -- bridge server -----------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        me: Optional[PlaneNode] = None
        if not self._started:
            # Accepted in the closing window: stop() snapshotted _conns
            # before this task ran — bail so wait_closed() can finish.
            try:
                writer.close()
            except Exception:  # noqa: E02 — best-effort close
                pass
            return
        self._conns.add(writer)
        try:
            while True:
                hdr = await reader.readexactly(4)
                (ln,) = struct.unpack(">I", hdr)
                if ln > 1 << 20:
                    break
                m = msgpack.unpackb(await reader.readexactly(ln), raw=False)
                t = m.get("t")
                if t == "register":
                    me, refuse = self._register(m, writer)
                    if me is None:
                        self._send(writer, {"t": "err", "error": refuse})
                        break
                elif me is None:
                    continue
                elif t == "hb":
                    me.last_hb = time.monotonic()
                    if me.id >= 0:
                        self._hb_at[me.id] = me.last_hb
                    if me.status == "failed":
                        # heartbeats resumed after a dead verdict: the
                        # node rejoins at a fresh incarnation (serf
                        # failed->rejoin choreography); EV_JOIN fires
                        # when the kernel's membership flip lands
                        self._admit(me)
                elif t == "leave":
                    self._evict(me, "left")
                    self._broadcast_member_event(EV_LEAVE, me)
                elif t == "force-leave":
                    tgt = self._nodes_by_name.get(m.get("node", ""))
                    if tgt is not None and tgt.status == "failed":
                        self._evict(tgt, "left")
                        self._broadcast_member_event(EV_LEAVE, tgt)
                elif t == "tags":
                    me.tags = dict(m.get("tags") or {})
                    self._broadcast_member_event(EV_UPDATE, me)
                elif t == "event":
                    # Enters the dissemination kernel at the next
                    # dispatch: lamport stamp, flood, and delivery
                    # timing are kernel dynamics (_dispatch_events).
                    if me.id >= 0:
                        self._fire_queue.append((me.id, {
                            "name": m.get("name", ""),
                            "payload": m.get("payload", b""),
                            "coalesce": m.get("coalesce", True),
                            "from": me.name}))
                elif t == "members":
                    self._send(writer, {"t": "members",
                                        "members": self.members_wire()})
                elif t == "stats":
                    # serf.Stats() role for the plane: kernel session
                    # counters on demand (registered connections only —
                    # an armed keyring must gate observability too).
                    self._send(writer, self._stats_wire())
                elif t == "flight":
                    # Flight-recorder query: drain whatever the kernel
                    # has written since the last amortized drain, then
                    # serve the host-side timeline (same keyring gate
                    # as stats).
                    self._drain_flight()
                    payload = {"t": "flight"}
                    if self._flight_recorder is not None:
                        payload.update(self._flight_recorder.wire(
                            limit=int(m.get("limit", 256) or 256)))
                    self._send(writer, payload)
                elif t == "slo":
                    # Detection-latency SLO observatory: burn rate,
                    # exact percentiles, cumulative histogram families
                    # (same keyring gate as stats).
                    self._drain_flight()
                    self._send(writer, self._slo_wire())
                elif t == "device":
                    # Device/kernel observatory query (obs/devstats.py):
                    # dispatch hists, HBM rows, compile + roofline
                    # telemetry (same keyring gate as stats).
                    self._send(writer, self._device_wire())
                elif t == "autotune":
                    # Autotune observatory query (obs/tuner.py): the
                    # knob resolution this plane booted with (same
                    # keyring gate as stats).
                    self._send(writer, self._autotune_wire())
                elif t == "profile":
                    # On-demand device profiling of K kernel rounds.
                    # Blocks this connection's loop while capturing —
                    # an explicit, bounded operator action.
                    self._send(writer, self._profile_wire(
                        int(m.get("steps", 8 * STEPS_PER_TICK)
                            or 8 * STEPS_PER_TICK),
                        phases=bool(m.get("phases", False))))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            # Socket loss is NOT a leave: the kernel's failure detector
            # owns that verdict (heartbeats just stop arriving).
            self._conns.discard(writer)
            if me is not None and me.writer is writer:
                me.writer = None
            try:
                writer.close()
            except Exception:  # noqa: E02 — best-effort close
                pass

    def _verify_auth(self, m: Dict[str, Any]) -> bool:
        """Registration proof check against every installed key (key
        rotation: agents may still prove with a non-primary key).
        Never raises — malformed auth fields are a refusal, not a
        handler crash — and a (ts, nonce) pair is single-use within
        the skew window (replay of a captured register frame fails).
        The nonce cache is in-memory only: a plane restart reopens a
        replay window of up to ``auth_skew_s`` (module docstring)."""
        try:
            ts = int(m.get("auth_ts", 0) or 0)
            nonce = bytes(m.get("auth_nonce", b"") or b"")
            mac = bytes(m.get("auth", b"") or b"")
            now = time.time()
            if abs(now - ts) > self.config.auth_skew_s:
                return False
            seen = self._seen_nonces
            for k in [k for k, exp in seen.items() if exp < now]:
                del seen[k]
            if (ts, nonce) in seen:
                return False
            for key in self.config.encrypt_keys:
                try:
                    want = registration_proof(
                        key, m.get("name", ""), m.get("addr", ""),
                        int(m.get("port", 0) or 0), ts, nonce,
                        m.get("tags") or {})
                except Exception:
                    continue  # one bad key must not mask the others
                if hmac.compare_digest(want, mac):
                    seen[(ts, nonce)] = now + 2 * self.config.auth_skew_s
                    return True
        except Exception:
            return False
        return False

    def _register(self, m: Dict[str, Any], writer: asyncio.StreamWriter
                  ) -> tuple[Optional[PlaneNode], str]:
        if self.config.encrypt_keys and not self._verify_auth(m):
            return None, "authentication failed (keyring proof required)"
        name = m.get("name", "")
        node = self._nodes_by_name.get(name)
        if node is not None and node.status == "alive" \
                and node.writer is not None and node.writer is not writer \
                and (time.monotonic() - node.last_hb) <= self.config.hb_lapse_s:
            # Name conflict with a LIVE registration: refuse, as
            # memberlist's name-conflict delegate does.  A dead/lapsed
            # holder is a restart and may re-register.
            return None, "name taken by a live node"
        if node is None or node.id < 0:
            nid = self._alloc_id()
            if nid is None:
                return None, "plane full"
            if node is None:
                node = PlaneNode(id=nid, name=name)
                self._nodes_by_name[name] = node
            else:  # a previously-left name re-registering
                node.id = nid
            self._nodes_by_id[nid] = node
        node.addr = m.get("addr", "")
        node.port = int(m.get("port", 0) or 0)
        node.tags = dict(m.get("tags") or {})
        node.writer = writer
        self._admit(node)
        self._send(writer, {
            "t": "welcome", "id": node.id, "round": self._rounds_done,
            "hb_interval_s": self.config.hb_lapse_s / 3.0,
            "members": self.members_wire()})
        # EV_JOIN broadcasts from _dispatch once the kernel admits the id.
        return node, ""

    def _member_wire(self, node: PlaneNode) -> Dict[str, Any]:
        # "joining" (registered, kernel flip pending <1 tick) reads as
        # alive on the wire — serf members show a joiner immediately.
        return {"name": node.name, "addr": node.addr, "port": node.port,
                "tags": node.tags,
                "state": ("dead" if node.status == "failed" else
                          "left" if node.status == "left" else "alive")}

    def _queue_member_event(self, kind: str, node: PlaneNode) -> None:
        """Accumulate one kernel-verdict transition into the cadence's
        structured batch.  Identity is resolved NOW (the admission
        table may reuse the id before the flush), so a detect queued
        before a same-cadence refute keeps its own snapshot."""
        ev: Dict[str, Any] = {"kind": kind, "node": self._member_wire(node)}
        jy = _journey.journey
        if jy is not None:
            now = time.monotonic()
            detect_ms = ((now - self._journey_round0) * 1000.0
                         if self._journey_round0 else -1.0)
            jy.stage_observe("detect", detect_ms)
            # Stamp carriage for downstream stages: [t_detect, t_flush,
            # detect_ms] — monotonic floats, in-process comparisons only
            # (the decode hook drops cross-process deltas).
            ev["jt"] = [now, 0.0, round(detect_ms, 3)]
        self._pending_events.append(ev)

    def _flush_member_events(self) -> None:
        """Ship the queued transitions as one ``evbatch`` frame — one
        msgpack encode + one write per connection for the whole
        cadence, the wire half of the fused detect→catalog pipeline."""
        self._dispatches_since_event_flush = 0
        if not self._pending_events:
            return
        events, self._pending_events = self._pending_events, []
        jy = _journey.journey
        if jy is not None:
            now = time.monotonic()
            for ev in events:
                jt = ev.get("jt")
                if jt:
                    jy.stage_observe("drain", (now - jt[0]) * 1000.0)
                    jt[1] = now
        self._broadcast({"t": "evbatch", "events": events})

    def _broadcast_member_event(self, kind: str, node: PlaneNode) -> None:
        # Host-driven transitions (leave/force-leave/tags) broadcast
        # immediately; the queued batch flushes FIRST so an agent never
        # sees a leave before the failure that preceded it.
        self._flush_member_events()
        self._broadcast({"t": "ev", "kind": kind,
                         "node": self._member_wire(node)})

    def _broadcast(self, payload: Dict[str, Any]) -> None:
        for node in self._nodes_by_id.values():
            if node.writer is not None:
                self._send(node.writer, payload)

    @staticmethod
    def _send(writer: asyncio.StreamWriter, payload: Dict[str, Any]) -> None:
        try:
            raw = msgpack.packb(payload, use_bin_type=True)
            writer.write(struct.pack(">I", len(raw)) + raw)
        except Exception:  # noqa: E02 — dying peer socket; reaper collects it
            pass


async def run_plane(config: PlaneConfig) -> GossipPlane:
    plane = GossipPlane(config)
    await plane.start()
    return plane
