"""Discrete-event reference model of SWIM/Lifeguard membership semantics.

This is the cross-validation oracle for the TPU kernel (BASELINE.md
config 2): a clean-room, per-node implementation of the protocol the
reference consumes through memberlist/Serf (behavior contract:
``website/source/docs/internals/gossip.html.markdown``; SWIM paper;
Lifeguard, PAPERS.md #1).  Unlike the kernel it keeps *faithful*
per-node state — shuffled round-robin probe lists, Poisson gossip
in-degree (independent uniform targets), per-node suspicion timers
started at local hearing time, distinct-origin confirmation sets, and
per-message retransmit budgets — so the kernel's batched approximations
can be quantified against it.

Time advances in gossip ticks (same granularity as the kernel's rounds)
so distributions are directly comparable.  It is event-sparse: beliefs
are stored only for subjects that deviate from "alive@0", which keeps
pure-Python simulation tractable to a few thousand nodes.
"""

from __future__ import annotations

import dataclasses
import random
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from consul_tpu.gossip.nemesis import NemesisParams, group_of
from consul_tpu.gossip.params import SwimParams

ALIVE, SUSPECT, DEAD = 0, 1, 2


@dataclasses.dataclass
class Message:
    kind: int          # SUSPECT / DEAD / ALIVE(refute) — ALIVE encoded as 3
    subject: int
    inc: int
    origin: int        # original suspector/declarer (drives Lifeguard distinctness)


REFUTE = 3


@dataclasses.dataclass
class Belief:
    status: int = ALIVE
    inc: int = 0
    heard_tick: int = 0
    confirmers: Optional[Set[int]] = None  # distinct suspicion origins seen


class Broadcast:
    __slots__ = ("msg", "remaining", "born")

    def __init__(self, msg: Message, remaining: int, born: int = -1):
        self.msg = msg
        self.remaining = remaining
        # Tick the broadcast was enqueued: it may not be FORWARDED
        # within the same tick (one gossip hop per tick — the same
        # synchronous-rounds convention the kernel and the event oracle
        # use; without this, shuffled intra-tick processing lets a
        # rumor chain multiple hops per tick and flood measurably
        # faster than either other model).  Beliefs and timers still
        # update at receipt — only re-forwarding waits.
        self.born = born


@dataclasses.dataclass
class DetectionEvent:
    subject: int
    fail_tick: int
    first_suspect_tick: int
    dead_tick: int


class RefModel:
    """Per-node discrete-event SWIM simulation."""

    def __init__(self, p: SwimParams, fail_tick: Dict[int, int], seed: int = 0,
                 join_tick: Optional[Dict[int, int]] = None,
                 nemesis: Optional[NemesisParams] = None):
        self.p = p
        self.n = p.n
        self.rng = random.Random(seed)
        self.fail_tick = dict(fail_tick)
        # Nemesis schedule (gossip/nemesis.py): the oracle models the
        # SAME correlated faults the kernel injects — partition /
        # asymmetric-loss edge drops, flapping truth overrides with
        # rejoin-on-up-edge, heal rejoin, degraded-observer reply drops
        # and the Lifeguard local-health multiplier.
        self.nemesis = nemesis
        self._nem_group = (group_of(nemesis, self.n)
                           if nemesis is not None and nemesis.has_partition
                           else None)
        # Lifeguard LHM registers (kernel.NemState rule, per prober):
        # suspicion initiation gates on streak > lhm; +1 on NACK-style
        # evidence (direct miss while a helper vouches) and on being
        # refuted, -1 on clean probe success.
        self._lhm = [0] * self.n
        self._lhm_streak = [0] * self.n
        # Joins (memberlist: a join is a TCP state sync with one contact
        # node followed by a gossiped alive@inc broadcast —
        # gossip.html.markdown:10-43): nodes with a join_tick do not
        # exist in anyone's view (or act) until that tick.
        self.join_tick = dict(join_tick or {})
        self.tick = 0
        # Per-node protocol state (sparse: only deviations from alive@0).
        self.beliefs: List[Dict[int, Belief]] = [dict() for _ in range(self.n)]
        self.queues: List[List[Broadcast]] = [[] for _ in range(self.n)]
        self.incarnation = [0] * self.n
        # Membership views are stored SPARSELY as per-node exclusion
        # sets (nodes believed dead): everyone starts believing everyone
        # is a member, and a dense per-node member set would be O(n²)
        # memory — ~13 GB at n=10k, which made large oracle runs swap.
        self.not_member: List[Set[int]] = [set() for _ in range(self.n)]
        # Round-robin probe lists (memberlist: shuffled sweep, reshuffle
        # at end).  Lazy + int32-packed: eager Python lists were the
        # other O(n²) memory sink (~4 GB at n=10k).
        self.probe_list: List[Optional[np.ndarray]] = [None] * self.n
        self.probe_pos = [0] * self.n
        self.probe_offset = [self.rng.randrange(p.probe_every) for _ in range(self.n)]
        self.pushpull_offset = ([self.rng.randrange(p.pushpull_every)
                                 for _ in range(self.n)]
                                if p.pushpull_every else [])
        # Suspicion timers: (observer, subject) -> deadline handled lazily.
        self.first_suspect: Dict[int, int] = {}
        self.dead_declared: Dict[int, int] = {}
        self.events: List[DetectionEvent] = []
        self.n_refuted = 0
        self.n_false_dead = 0
        self.dissemination: Dict[int, List[Tuple[int, int]]] = defaultdict(list)
        # Incremental dissemination bookkeeping: observers currently
        # holding the dead verdict per subject.  Replaces an O(n) scan
        # per dead subject per tick, which dominated 10k-node oracle
        # runs in the cross-validation harness.
        self._dead_knowers: Dict[int, Set[int]] = defaultdict(set)
        # Join-propagation bookkeeping: who has learned of each joiner.
        self._join_knowers: Dict[int, Set[int]] = defaultdict(set)
        self.join_curve: Dict[int, List[Tuple[int, int]]] = defaultdict(list)
        for j in self.join_tick:
            for i in range(self.n):
                if i != j:
                    self.not_member[i].add(j)
        # Same Lifeguard decay the kernel uses — one source of truth.
        self._timeouts = p.timeout_table()

    # -- helpers ----------------------------------------------------------

    def _shuffled(self, i: int) -> np.ndarray:
        """Fresh shuffled probe ring for node i: current members only,
        int32-packed (memberlist reshuffles its node ring per sweep)."""
        rng = np.random.default_rng(self.rng.getrandbits(64))
        perm = rng.permutation(self.n).astype(np.int32)
        drop = self.not_member[i] | {i}
        if drop:
            mask = np.ones(self.n, bool)
            mask[list(drop)] = False
            perm = perm[mask[perm]]
        return perm

    def _is_member(self, i: int, x: int) -> bool:
        return x != i and x not in self.not_member[i]

    def _member_count(self, i: int) -> int:
        return self.n - 1 - len(self.not_member[i])

    def _sample_members(self, i: int, k: int,
                        exclude: Tuple[int, ...] = ()) -> List[int]:
        """k distinct members of i's view (rejection sampling — the
        exclusion set is tiny relative to n, so acceptance is high).
        Falls back to an explicit scan for tiny viable sets."""
        viable = self._member_count(i) - sum(
            1 for e in set(exclude) if self._is_member(i, e))
        k = min(k, max(0, viable))
        if k <= 0:
            return []
        out: List[int] = []
        seen = set(exclude)
        seen.add(i)
        attempts = 0
        while len(out) < k and attempts < 20 * (k + 1):
            attempts += 1
            x = self.rng.randrange(self.n)
            if x in seen or x in self.not_member[i]:
                continue
            seen.add(x)
            out.append(x)
        if len(out) < k:  # dense fallback (view almost empty)
            pool = [x for x in range(self.n)
                    if x not in seen and x not in self.not_member[i]]
            self.rng.shuffle(pool)
            out.extend(pool[: k - len(out)])
        return out

    def _alive_truth(self, i: int) -> bool:
        return (self.fail_tick.get(i, 1 << 60) > self.tick
                and self._joined(i) and not self._flap_down(i))

    # -- nemesis fault injection (mirrors kernel._nem_* derivations) ------

    def _nem_window(self, t: Optional[int] = None) -> bool:
        nem = self.nemesis
        if nem is None:
            return False
        t = self.tick if t is None else t
        return nem.start <= t < nem.stop

    def _flap_down(self, i: int, t: Optional[int] = None) -> bool:
        """Square-wave truth override: up ``flap_up`` rounds, then down
        for the rest of the period, inside the fault window."""
        nem = self.nemesis
        if nem is None or not nem.has_flap:
            return False
        if not (nem.flap_lo <= i < nem.flap_hi):
            return False
        t = self.tick if t is None else t
        if not (nem.start <= t < nem.stop):
            return False
        return ((t - nem.start) % nem.flap_period) >= nem.flap_up

    def _edge_lost(self, src: int, dst: int) -> bool:
        """One directed message leg crossing the partition: dropped with
        the source group's edge probability."""
        nem = self.nemesis
        if self._nem_group is None or not self._nem_window():
            return False
        gs = int(self._nem_group[src])
        if gs == int(self._nem_group[dst]):
            return False
        pe = nem.p_ab if gs == 0 else nem.p_ba
        return pe > 0 and self.rng.random() < pe

    def _truth_fail_tick(self, subject: int) -> int:
        """Tick the subject ACTUALLY went down — its scheduled fail
        tick, or the start of its current flap down-phase (flap victims
        have no ``fail_tick`` entry)."""
        ft = self.fail_tick.get(subject)
        if ft is not None and ft <= self.tick:
            return ft
        nem = self.nemesis
        if nem is not None and self._flap_down(subject):
            rel = (self.tick - nem.start) % nem.flap_period
            return self.tick - (rel - nem.flap_up)
        return self.tick

    def _obs_miss(self, i: int) -> bool:
        """Degraded observer: prober ``i`` drops a reply it DID receive
        (the observer is slow, not the target)."""
        nem = self.nemesis
        return (nem is not None and nem.has_degraded and self._nem_window()
                and nem.obs_lo <= i < nem.obs_hi
                and self.rng.random() < nem.p_obs_miss)

    def _joined(self, i: int) -> bool:
        return self.join_tick.get(i, -(1 << 60)) <= self.tick

    def _lost(self) -> bool:
        return self.rng.random() < self.p.loss_rate

    def _belief(self, i: int, subject: int) -> Belief:
        b = self.beliefs[i].get(subject)
        if b is None:
            b = Belief(inc=0)
            self.beliefs[i][subject] = b
        return b

    def _transmit_limit(self) -> int:
        return self.p.transmit_limit

    def _enqueue(self, i: int, msg: Message, originated: bool = False) -> None:
        """``originated``: the node CREATED this message during its own
        probe/join phase — it rides the node's own gossip burst this
        same tick (the kernel's fresh-mark behavior).  Messages enqueued
        while HANDLING received gossip forward from the next tick."""
        # memberlist queue invalidates older broadcasts about the same subject
        self.queues[i] = [b for b in self.queues[i] if b.msg.subject != msg.subject]
        self.queues[i].append(Broadcast(msg, self._transmit_limit(),
                                        born=-1 if originated else self.tick))

    def _suspicion_timeout(self, nconf: int) -> int:
        return int(self._timeouts[min(nconf, self.p.max_confirmations)])

    # -- message handling (SWIM semantics) --------------------------------

    def _handle(self, i: int, msg: Message) -> None:
        if not self._alive_truth(i):
            return
        subject = msg.subject
        if subject == i:
            # About me: refute suspicion/death (alive with bumped incarnation).
            if msg.kind in (SUSPECT, DEAD) and self.p.refute and msg.inc >= self.incarnation[i]:
                self.incarnation[i] = msg.inc + 1
                self.n_refuted += 1
                if self.nemesis is not None and self.nemesis.lhm_max > 0:
                    # Lifeguard: being refuted is evidence the LOCAL
                    # node is degraded — raise its multiplier.
                    self._lhm[i] = min(self._lhm[i] + 1,
                                       self.nemesis.lhm_max)
                self._enqueue(i, Message(REFUTE, i, self.incarnation[i], i))
            return
        b = self._belief(i, subject)
        if msg.kind == SUSPECT:
            if b.status == DEAD or msg.inc < b.inc:
                return
            if b.status == SUSPECT and msg.inc == b.inc:
                if b.confirmers is not None and msg.origin not in b.confirmers:
                    b.confirmers.add(msg.origin)
                    self._enqueue(i, msg)
                return
            b.status, b.inc, b.heard_tick = SUSPECT, msg.inc, self.tick
            b.confirmers = {msg.origin}
            self.first_suspect.setdefault(subject, self.tick)
            self._enqueue(i, msg)
        elif msg.kind == DEAD:
            if b.status == DEAD or msg.inc < b.inc:
                return
            b.status, b.inc, b.heard_tick = DEAD, msg.inc, self.tick
            self.not_member[i].add(subject)
            self._dead_knowers[subject].add(i)
            self._enqueue(i, msg)
        elif msg.kind == REFUTE:
            if msg.inc <= b.inc and b.status != ALIVE:
                return
            if msg.inc > b.inc:
                b.status, b.inc, b.heard_tick = ALIVE, msg.inc, self.tick
                b.confirmers = None
                # Faithfulness fix (was a latent oracle bug): memberlist's
                # aliveNode at a newer incarnation RE-ADMITS the subject to
                # the membership view; the old dense-set code left a
                # refuted node permanently excluded from members[i].
                readmitted = subject in self.not_member[i]
                self.not_member[i].discard(subject)
                self._dead_knowers[subject].discard(i)
                if subject in self.join_tick:
                    first = i not in self._join_knowers[subject]
                    self._join_knowers[subject].add(i)
                    # memberlist aliveNode splices a NEW member into the
                    # probe ring at a random offset immediately (it
                    # would otherwise wait a full sweep for reshuffle).
                    ring = self.probe_list[i]
                    if first and readmitted and ring is not None:
                        pos = self.rng.randrange(len(ring) + 1)
                        self.probe_list[i] = np.insert(
                            ring, pos, np.int32(subject))
                self._enqueue(i, msg)

    def _declare_dead(self, i: int, subject: int, b: Belief) -> None:
        b.status = DEAD
        self.not_member[i].add(subject)
        self._dead_knowers[subject].add(i)
        if subject not in self.dead_declared:
            self.dead_declared[subject] = self.tick
            truly = not self._alive_truth(subject)
            if truly:
                self.events.append(DetectionEvent(
                    subject, self._truth_fail_tick(subject),
                    self.first_suspect.get(subject, self.tick), self.tick))
            else:
                self.n_false_dead += 1
        self._enqueue(i, Message(DEAD, subject, b.inc, i))

    # -- per-tick phases --------------------------------------------------

    def _probe(self, i: int) -> None:
        if self._member_count(i) <= 0:
            return
        # next round-robin target still believed a member
        ring = self.probe_list[i]
        if ring is None:
            ring = self.probe_list[i] = self._shuffled(i)
        for _ in range(len(ring) + 1):
            if self.probe_pos[i] >= len(ring):
                ring = self.probe_list[i] = self._shuffled(i)
                self.probe_pos[i] = 0
                if len(ring) == 0:
                    return
            t = int(ring[self.probe_pos[i]])
            self.probe_pos[i] += 1
            if self._is_member(i, t):
                break
        else:
            return
        target_up = self._alive_truth(t)
        # Direct probe: request i->t, ack t->i — two iid loss draws plus
        # one partition draw per direction plus the degraded-observer
        # chance of dropping the ack after receipt.
        direct_ok = (target_up and not self._lost() and not self._lost()
                     and not self._edge_lost(i, t)
                     and not self._edge_lost(t, i)
                     and not self._obs_miss(i))
        ok = direct_ok
        rescued = False
        if not ok:
            helpers = self._sample_members(i, self.p.indirect_k, exclude=(t,))
            for h in helpers:
                if not self._alive_truth(h):
                    continue
                # Four legs: i->h, h->t, t->h, h->i — each crosses the
                # partition independently; the final reply can still be
                # dropped by a degraded prober.
                if (target_up and not any(self._lost() for _ in range(4))
                        and not self._edge_lost(i, h)
                        and not self._edge_lost(h, t)
                        and not self._edge_lost(t, h)
                        and not self._edge_lost(h, i)
                        and not self._obs_miss(i)):
                    ok = rescued = True
                    break
        nem = self.nemesis
        if nem is not None and nem.lhm_max > 0:
            # Lifeguard local-health multiplier — the kernel NemState
            # rule verbatim: gate on the OLD multiplier, then update.
            miss = not direct_ok
            streak = (min(self._lhm_streak[i] + 1, nem.lhm_max + 1)
                      if miss else 0)
            gate = streak > self._lhm[i]
            self._lhm[i] = min(max(
                self._lhm[i] + (1 if (miss and rescued) else 0)
                - (0 if miss else 1), 0), nem.lhm_max)
            self._lhm_streak[i] = streak
            if not ok and not gate:
                return  # LHM suppresses this round's suspicion
        if not ok:
            b = self._belief(i, t)
            if b.status == ALIVE:
                inc = max(b.inc, 0)
                b.status, b.inc, b.heard_tick = SUSPECT, inc, self.tick
                b.confirmers = {i}  # creator seed; not a confirmation
                self.first_suspect.setdefault(t, self.tick)
                self._enqueue(i, Message(SUSPECT, t, inc, i),
                              originated=True)
            elif b.status == SUSPECT:
                # memberlist suspectNode on an existing suspicion: the local
                # failed probe is an independent confirmation, re-gossiped.
                if b.confirmers is not None and i not in b.confirmers:
                    b.confirmers.add(i)
                    self._enqueue(i, Message(SUSPECT, t, b.inc, i),
                                  originated=True)

    def _gossip(self, i: int) -> None:
        if not self.queues[i] or self._member_count(i) <= 0:
            return
        targets = self._sample_members(i, self.p.fanout)
        for b in list(self.queues[i]):
            if b.born == self.tick:
                continue  # one hop per tick: forwarded from next tick on
            for t in targets:
                if b.remaining <= 0:
                    break
                b.remaining -= 1
                if (self._alive_truth(t) and not self._lost()
                        and not self._edge_lost(i, t)):
                    self._handle(t, b.msg)
        self.queues[i] = [b for b in self.queues[i] if b.remaining > 0]

    def _pushpull(self, i: int) -> None:
        """memberlist PushPullInterval: full bidirectional state sync
        with one random member over TCP (pushPullNode →
        mergeRemoteState).  Each deviating belief merges through the
        ordinary message semantics — this is what recovers rumors whose
        retransmit budget expired before reaching everyone."""
        partners = self._sample_members(i, 1)
        if not partners:
            return
        j = partners[0]
        if not self._alive_truth(j):
            return  # TCP dial to a dead node fails
        if self._edge_lost(i, j) or self._edge_lost(j, i):
            return  # TCP sync crossing the partition fails
        kind_of = {SUSPECT: SUSPECT, DEAD: DEAD, ALIVE: REFUTE}
        for a, b in ((i, j), (j, i)):
            for subject, bel in list(self.beliefs[b].items()):
                if bel.status == ALIVE and bel.inc == 0:
                    continue  # no information beyond the default
                self._handle(a, Message(kind_of[bel.status], subject,
                                        bel.inc, b))

    def _timers(self, i: int) -> None:
        for subject, b in list(self.beliefs[i].items()):
            if b.status != SUSPECT:
                continue
            # memberlist seeds the suspicion with its creator, which does not
            # count as a confirmation; n = distinct origins seen since.
            nconf = min(self.p.max_confirmations, max(0, len(b.confirmers or ()) - 1))
            if self.tick - b.heard_tick >= self._suspicion_timeout(nconf):
                self._declare_dead(i, subject, b)

    def _do_join(self, j: int) -> None:
        """Node ``j`` joins: state sync with one live contact (the TCP
        push/pull leg of memberlist Join), then an alive@inc broadcast
        floods through gossip (the same REFUTE message class)."""
        self.incarnation[j] = max(1, self.incarnation[j] + 1)
        contacts = [x for x in range(self.n)
                    if x != j and self._alive_truth(x)
                    and not self._edge_lost(j, x)
                    and not self._edge_lost(x, j)]
        if contacts:
            c = self.rng.choice(contacts)
            # joiner adopts the contact's membership view...
            self.not_member[j] = set(self.not_member[c]) - {j}
            # ...and appears in the contact's view over the same sync
            self.not_member[c].discard(j)
            self._join_knowers[j].add(c)
        self.probe_list[j] = None  # fresh ring over the synced view
        self.probe_pos[j] = 0
        self._join_knowers[j].add(j)
        self._enqueue(j, Message(REFUTE, j, self.incarnation[j], j),
                      originated=True)

    def step(self) -> None:
        t = self.tick
        nem = self.nemesis
        if nem is not None and nem.has_flap:
            # Flap up edge: the node restarts — incarnation bump +
            # alive@inc flood through the ordinary join path (the
            # kernel re-arms join_round to the same effect).
            for i in range(nem.flap_lo, min(nem.flap_hi, self.n)):
                if (self._flap_down(i, t - 1) and not self._flap_down(i, t)
                        and self.fail_tick.get(i, 1 << 60) > t
                        and self._joined(i)):
                    self._do_join(i)
        if nem is not None and nem.heal_rejoin and t == nem.stop:
            # Partition heal: every node falsely declared dead rejoins
            # (kernel: join_round = min(join_round, stop)).
            for j in range(self.n):
                if self._alive_truth(j) and (j in self.dead_declared
                                             or self._dead_knowers.get(j)):
                    self._do_join(j)
        for j, jt in self.join_tick.items():
            if jt == t and self.fail_tick.get(j, 1 << 60) > t:
                self._do_join(j)
        for i in range(self.n):
            if not self._alive_truth(i):
                continue
            if (t + self.probe_offset[i]) % self.p.probe_every == 0:
                self._probe(i)
            if self.p.pushpull_every and \
                    (t + self.pushpull_offset[i]) % self.p.pushpull_every == 0:
                self._pushpull(i)
        order = list(range(self.n))
        self.rng.shuffle(order)
        for i in order:
            if self._alive_truth(i):
                self._gossip(i)
        for i in range(self.n):
            if self._alive_truth(i):
                self._timers(i)
        # dissemination curve for failed subjects (incremental count;
        # includes observers that themselves die later — the curve is
        # monotone either way and its consumers check the peak)
        for subject in self.dead_declared:
            self.dissemination[subject].append(
                (t, len(self._dead_knowers[subject])))
        for j, jt in self.join_tick.items():
            if jt <= t:
                self.join_curve[j].append((t, len(self._join_knowers[j])))
        self.tick += 1

    def run(self, ticks: int) -> None:
        for _ in range(ticks):
            self.step()

    # -- summary ----------------------------------------------------------

    def detection_latencies(self) -> List[int]:
        return [e.dead_tick - e.fail_tick for e in self.events]
