"""Discrete-event reference model of SWIM/Lifeguard membership semantics.

This is the cross-validation oracle for the TPU kernel (BASELINE.md
config 2): a clean-room, per-node implementation of the protocol the
reference consumes through memberlist/Serf (behavior contract:
``website/source/docs/internals/gossip.html.markdown``; SWIM paper;
Lifeguard, PAPERS.md #1).  Unlike the kernel it keeps *faithful*
per-node state — shuffled round-robin probe lists, Poisson gossip
in-degree (independent uniform targets), per-node suspicion timers
started at local hearing time, distinct-origin confirmation sets, and
per-message retransmit budgets — so the kernel's batched approximations
can be quantified against it.

Time advances in gossip ticks (same granularity as the kernel's rounds)
so distributions are directly comparable.  It is event-sparse: beliefs
are stored only for subjects that deviate from "alive@0", which keeps
pure-Python simulation tractable to a few thousand nodes.
"""

from __future__ import annotations

import dataclasses
import random
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from consul_tpu.gossip.params import SwimParams

ALIVE, SUSPECT, DEAD = 0, 1, 2


@dataclasses.dataclass
class Message:
    kind: int          # SUSPECT / DEAD / ALIVE(refute) — ALIVE encoded as 3
    subject: int
    inc: int
    origin: int        # original suspector/declarer (drives Lifeguard distinctness)


REFUTE = 3


@dataclasses.dataclass
class Belief:
    status: int = ALIVE
    inc: int = 0
    heard_tick: int = 0
    confirmers: Optional[Set[int]] = None  # distinct suspicion origins seen


class Broadcast:
    __slots__ = ("msg", "remaining")

    def __init__(self, msg: Message, remaining: int):
        self.msg = msg
        self.remaining = remaining


@dataclasses.dataclass
class DetectionEvent:
    subject: int
    fail_tick: int
    first_suspect_tick: int
    dead_tick: int


class RefModel:
    """Per-node discrete-event SWIM simulation."""

    def __init__(self, p: SwimParams, fail_tick: Dict[int, int], seed: int = 0):
        self.p = p
        self.n = p.n
        self.rng = random.Random(seed)
        self.fail_tick = dict(fail_tick)
        self.tick = 0
        # Per-node protocol state (sparse: only deviations from alive@0).
        self.beliefs: List[Dict[int, Belief]] = [dict() for _ in range(self.n)]
        self.queues: List[List[Broadcast]] = [[] for _ in range(self.n)]
        self.incarnation = [0] * self.n
        self.members: List[Set[int]] = [set(range(self.n)) - {i} for i in range(self.n)]
        # Round-robin probe lists (memberlist: shuffled sweep, reshuffle at end).
        self.probe_list: List[List[int]] = [self._shuffled(i) for i in range(self.n)]
        self.probe_pos = [0] * self.n
        self.probe_offset = [self.rng.randrange(p.probe_every) for _ in range(self.n)]
        # Suspicion timers: (observer, subject) -> deadline handled lazily.
        self.first_suspect: Dict[int, int] = {}
        self.dead_declared: Dict[int, int] = {}
        self.events: List[DetectionEvent] = []
        self.n_refuted = 0
        self.n_false_dead = 0
        self.dissemination: Dict[int, List[Tuple[int, int]]] = defaultdict(list)
        # Same Lifeguard decay the kernel uses — one source of truth.
        self._timeouts = p.timeout_table()

    # -- helpers ----------------------------------------------------------

    def _shuffled(self, i: int) -> List[int]:
        lst = [x for x in range(self.n) if x != i]
        self.rng.shuffle(lst)
        return lst

    def _alive_truth(self, i: int) -> bool:
        return self.fail_tick.get(i, 1 << 60) > self.tick

    def _lost(self) -> bool:
        return self.rng.random() < self.p.loss_rate

    def _belief(self, i: int, subject: int) -> Belief:
        b = self.beliefs[i].get(subject)
        if b is None:
            b = Belief(inc=0)
            self.beliefs[i][subject] = b
        return b

    def _transmit_limit(self) -> int:
        return self.p.transmit_limit

    def _enqueue(self, i: int, msg: Message) -> None:
        # memberlist queue invalidates older broadcasts about the same subject
        self.queues[i] = [b for b in self.queues[i] if b.msg.subject != msg.subject]
        self.queues[i].append(Broadcast(msg, self._transmit_limit()))

    def _suspicion_timeout(self, nconf: int) -> int:
        return int(self._timeouts[min(nconf, self.p.max_confirmations)])

    # -- message handling (SWIM semantics) --------------------------------

    def _handle(self, i: int, msg: Message) -> None:
        if not self._alive_truth(i):
            return
        subject = msg.subject
        if subject == i:
            # About me: refute suspicion/death (alive with bumped incarnation).
            if msg.kind in (SUSPECT, DEAD) and self.p.refute and msg.inc >= self.incarnation[i]:
                self.incarnation[i] = msg.inc + 1
                self.n_refuted += 1
                self._enqueue(i, Message(REFUTE, i, self.incarnation[i], i))
            return
        b = self._belief(i, subject)
        if msg.kind == SUSPECT:
            if b.status == DEAD or msg.inc < b.inc:
                return
            if b.status == SUSPECT and msg.inc == b.inc:
                if b.confirmers is not None and msg.origin not in b.confirmers:
                    b.confirmers.add(msg.origin)
                    self._enqueue(i, msg)
                return
            b.status, b.inc, b.heard_tick = SUSPECT, msg.inc, self.tick
            b.confirmers = {msg.origin}
            self.first_suspect.setdefault(subject, self.tick)
            self._enqueue(i, msg)
        elif msg.kind == DEAD:
            if b.status == DEAD or msg.inc < b.inc:
                return
            b.status, b.inc, b.heard_tick = DEAD, msg.inc, self.tick
            self.members[i].discard(subject)
            self._enqueue(i, msg)
        elif msg.kind == REFUTE:
            if msg.inc <= b.inc and b.status != ALIVE:
                return
            if msg.inc > b.inc:
                b.status, b.inc, b.heard_tick = ALIVE, msg.inc, self.tick
                b.confirmers = None
                self._enqueue(i, msg)

    def _declare_dead(self, i: int, subject: int, b: Belief) -> None:
        b.status = DEAD
        self.members[i].discard(subject)
        if subject not in self.dead_declared:
            self.dead_declared[subject] = self.tick
            truly = not self._alive_truth(subject)
            if truly:
                self.events.append(DetectionEvent(
                    subject, self.fail_tick[subject],
                    self.first_suspect.get(subject, self.tick), self.tick))
            else:
                self.n_false_dead += 1
        self._enqueue(i, Message(DEAD, subject, b.inc, i))

    # -- per-tick phases --------------------------------------------------

    def _probe(self, i: int) -> None:
        if not self.members[i]:
            return
        # next round-robin target still believed a member
        for _ in range(len(self.probe_list[i]) + 1):
            if self.probe_pos[i] >= len(self.probe_list[i]):
                self.probe_list[i] = self._shuffled(i)
                self.probe_list[i] = [t for t in self.probe_list[i] if t in self.members[i]]
                self.probe_pos[i] = 0
                if not self.probe_list[i]:
                    return
            t = self.probe_list[i][self.probe_pos[i]]
            self.probe_pos[i] += 1
            if t in self.members[i]:
                break
        else:
            return
        target_up = self._alive_truth(t)
        ok = target_up and not self._lost() and not self._lost()
        if not ok:
            helpers = self.rng.sample(sorted(self.members[i] - {t}),
                                      min(self.p.indirect_k, max(0, len(self.members[i]) - 1)))
            for h in helpers:
                if not self._alive_truth(h):
                    continue
                if target_up and not any(self._lost() for _ in range(4)):
                    ok = True
                    break
        if not ok:
            b = self._belief(i, t)
            if b.status == ALIVE:
                inc = max(b.inc, 0)
                b.status, b.inc, b.heard_tick = SUSPECT, inc, self.tick
                b.confirmers = {i}  # creator seed; not a confirmation
                self.first_suspect.setdefault(t, self.tick)
                self._enqueue(i, Message(SUSPECT, t, inc, i))
            elif b.status == SUSPECT:
                # memberlist suspectNode on an existing suspicion: the local
                # failed probe is an independent confirmation, re-gossiped.
                if b.confirmers is not None and i not in b.confirmers:
                    b.confirmers.add(i)
                    self._enqueue(i, Message(SUSPECT, t, b.inc, i))

    def _gossip(self, i: int) -> None:
        if not self.queues[i] or not self.members[i]:
            return
        k = min(self.p.fanout, len(self.members[i]))
        targets = self.rng.sample(sorted(self.members[i]), k)
        for b in list(self.queues[i]):
            for t in targets:
                if b.remaining <= 0:
                    break
                b.remaining -= 1
                if self._alive_truth(t) and not self._lost():
                    self._handle(t, b.msg)
        self.queues[i] = [b for b in self.queues[i] if b.remaining > 0]

    def _timers(self, i: int) -> None:
        for subject, b in list(self.beliefs[i].items()):
            if b.status != SUSPECT:
                continue
            # memberlist seeds the suspicion with its creator, which does not
            # count as a confirmation; n = distinct origins seen since.
            nconf = min(self.p.max_confirmations, max(0, len(b.confirmers or ()) - 1))
            if self.tick - b.heard_tick >= self._suspicion_timeout(nconf):
                self._declare_dead(i, subject, b)

    def step(self) -> None:
        t = self.tick
        for i in range(self.n):
            if not self._alive_truth(i):
                continue
            if (t + self.probe_offset[i]) % self.p.probe_every == 0:
                self._probe(i)
        order = list(range(self.n))
        self.rng.shuffle(order)
        for i in order:
            if self._alive_truth(i):
                self._gossip(i)
        for i in range(self.n):
            if self._alive_truth(i):
                self._timers(i)
        # dissemination curve for failed subjects
        for subject in self.dead_declared:
            knows = sum(1 for i in range(self.n)
                        if self._alive_truth(i)
                        and self.beliefs[i].get(subject) is not None
                        and self.beliefs[i][subject].status == DEAD)
            self.dissemination[subject].append((t, knows))
        self.tick += 1

    def run(self, ticks: int) -> None:
        for _ in range(ticks):
            self.step()

    # -- summary ----------------------------------------------------------

    def detection_latencies(self) -> List[int]:
        return [e.dead_tick - e.fail_tick for e in self.events]
