"""SWIM/Lifeguard protocol parameters, expressed in gossip rounds.

The reference's timing contract comes from memberlist's LAN/WAN profiles
as consumed by Consul (``consul/config.go:266-272``; tuned-down test
values visible at ``consul/server_test.go:50-62``): probe interval 1s,
gossip interval 200ms, suspicion multiplier 4-6, retransmit multiplier 4,
k=3 indirect probes, gossip fanout 3.  Our kernel is synchronous-rounds:
**one round = one gossip interval** (the finest protocol tick), and
probes fire every ``probe_every`` rounds (5 for the LAN profile).  All
timeouts are converted to rounds here, once, statically — the kernel
itself never sees wall-clock time.  Mapping rounds back to seconds for
cross-validation is ``round * gossip_interval_s``.

Lifeguard (PAPERS.md #1, arxiv 1707.00788): the suspicion timeout starts
at ``max = suspicion_max_mult * min`` and shrinks toward
``min = suspicion_mult * log10(n) * probe interval`` as independent
confirmations arrive, following the paper's logarithmic decay.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class SwimParams:
    """Static protocol config; hashable so it can be a jit static arg."""

    n: int  # number of node ids in the membership universe
    slots: int = 32  # concurrent rumor slots (S); overflow is counted, not silent
    fanout: int = 3  # gossip targets per node per round (memberlist GossipNodes)
    indirect_k: int = 3  # indirect probe helpers (memberlist IndirectChecks)
    probe_every: int = 5  # gossip rounds per probe tick (1s probe / 200ms gossip)
    suspicion_mult: float = 4.0  # memberlist SuspicionMult
    suspicion_max_mult: float = 6.0  # Lifeguard SuspicionMaxTimeoutMult
    max_confirmations: int = 3  # confirmations that drive timeout to min
    retransmit_mult: float = 4.0  # memberlist RetransmitMult
    loss_rate: float = 0.0  # iid packet-loss probability per message
    gossip_interval_s: float = 0.2  # for round<->seconds conversion only
    refute: bool = True  # alive subjects refute suspicion (incarnation bump)
    # Push/pull anti-entropy cadence in rounds; 0 disables.  memberlist
    # default: 30s LAN = 150 rounds, 60s WAN = 120 rounds (PushPullInterval,
    # selected by the reference via the LAN/WAN profiles).
    pushpull_every: int = 0
    # Hot-tier width: rounds with <= this many live episodes process
    # only the sliced subset of belief rows (kernel._hot_tail).
    # 0 disables the tier (two-way cond: quiescent / full).  Field
    # default stays OFF (WAN and bare SwimParams); lan_profile defaults
    # it to 8 now that the round-4 dynamic-slice rework landed.
    # History: the round-3 tier (traced-
    # index row GATHERS, ~6.5ns/element) measured ~10x slower than the
    # full tail (15.7 vs 155 r/s at 1M, 10ppm churn); the round-4
    # rework moves rows with per-row dynamic slices at memory
    # bandwidth instead (profile_kernel.py realistic_churn_* entries
    # are the decision gate).
    hot_slots: int = 0
    # Dissemination merge strategy (all four are bit-identical; the
    # switch exists so an on-chip A/B is one flag and a surprise
    # regression on the real lowering is a one-line revert):
    #   "swar"     - single SWAR pass over packed u32 words (round-4
    #                rewrite, ~2.3x less IO by counting; the default).
    #   "planes"   - the round-3 per-byte-plane loop (measured 155-166
    #                r/s at 1M/64-slot churn).
    #   "prefused" - SWAR with the age tick commuted across the
    #                circulant rolls (age is elementwise, roll is a
    #                permutation, so age(roll(x)) == roll(age(x))):
    #                no aged copy of the packed matrix is materialized
    #                before the pin reads — one fewer full [S,N]
    #                read+write per dense round (round 12).
    #   "fused"    - Pallas one-pass kernel (gossip/fused.py): rolls,
    #                merge, and aging in one traversal of the belief
    #                matrix; interpret-mode on CPU, Mosaic on TPU.
    dissem: str = "swar"
    # Column-block count for the fused Pallas kernel's grid (dissem=
    # "fused" only): the observer axis splits into this many
    # ``n/fused_nb``-wide blocks, each read/written once per round.
    # 1 = whole-row blocks (rolls become pure VMEM compute; the right
    # shape whenever S rows fit VMEM).  Must divide ``n``; the slow
    # parity tests sweep it.
    fused_nb: int = 1

    def __post_init__(self) -> None:
        if self.dissem not in ("swar", "planes", "prefused", "fused"):
            raise ValueError(
                f"dissem must be swar|planes|prefused|fused, got "
                f"{self.dissem!r}")
        if self.fused_nb < 1:
            raise ValueError(f"fused_nb must be >= 1, got {self.fused_nb}")

    # ---- derived, all static ----

    @property
    def dissem_swar(self) -> bool:
        """Back-compat view of the pre-round-12 two-way A/B flag."""
        return self.dissem != "planes"

    @property
    def log_n(self) -> float:
        return max(1.0, math.log10(max(self.n, 1)))

    @property
    def suspicion_min_rounds(self) -> int:
        return max(1, math.ceil(self.suspicion_mult * self.log_n * self.probe_every))

    @property
    def suspicion_max_rounds(self) -> int:
        return max(
            self.suspicion_min_rounds,
            math.ceil(self.suspicion_max_mult * self.suspicion_mult * self.log_n * self.probe_every),
        )

    def timeout_table(self) -> np.ndarray:
        """Suspicion timeout (rounds) per confirmation count 0..max_confirmations.

        Lifeguard decay: timeout(c) = max - (max-min) * log(c+1)/log(k+1).
        """
        lo, hi = self.suspicion_min_rounds, self.suspicion_max_rounds
        k = self.max_confirmations
        out = []
        for c in range(k + 1):
            frac = math.log(c + 1) / math.log(k + 1) if k > 0 else 1.0
            out.append(int(max(lo, math.ceil(hi - (hi - lo) * frac))))
        return np.asarray(out, dtype=np.int32)

    @property
    def transmit_limit(self) -> int:
        """Total piggyback transmissions per node per message (memberlist
        retransmit limit: RetransmitMult * ceil(log10(n+1)))."""
        return max(1, int(self.retransmit_mult * math.ceil(math.log10(self.n + 1))))

    @property
    def spread_budget_rounds(self) -> int:
        """Rounds a node keeps gossiping a message: limit / fanout, i.e. a
        node spends ``fanout`` transmissions per round.  Capped at 14 to
        fit the 4-bit age field with its 0xF fresh-mark sentinel
        (kernel._AGE_FRESH; only reached at astronomically large n)."""
        return min(14, max(1, math.ceil(self.transmit_limit / self.fanout)))

    @property
    def event_ttl_rounds(self) -> int:
        """Rounds an event slot stays allocated after firing: the flood
        window plus — when push/pull is enabled — enough anti-entropy
        cycles for pairwise exchange to double coverage to full
        (log2(n) syncs), mirroring Serf's recent-event buffer whose
        entries outlive their broadcast budget for exactly this reason."""
        ttl = self.spread_budget_rounds + 8
        if self.pushpull_every:
            ttl += self.pushpull_every * math.ceil(math.log2(self.n + 1))
        return ttl

    @property
    def slot_ttl_rounds(self) -> int:
        """Rounds before a rumor slot is recycled: worst-case suspicion
        timer plus two full dissemination sweeps of the final verdict."""
        return self.suspicion_max_rounds + 2 * self.spread_budget_rounds + 8

    @property
    def p_direct_fail_alive(self) -> float:
        """P(direct probe of an alive target fails) = probe or ack lost."""
        q = 1.0 - self.loss_rate
        return 1.0 - q * q

    @property
    def p_indirect_fail_alive(self) -> float:
        """P(one indirect relay of an alive target fails) — four legs."""
        q = 1.0 - self.loss_rate
        return 1.0 - q ** 4


# Ready-made profiles mirroring memberlist's LAN and WAN defaults.
def lan_profile(n: int, **kw) -> SwimParams:
    kw.setdefault("pushpull_every", 150)  # 30s / 200ms gossip
    # Hot tier on by default: the few most-recently-touched rumor slots
    # take the cheap narrow tail (kernel._hot_tail) while the full S-wide
    # tail runs only when episodes overflow it.  Bit-identical to the
    # full tail (tests/test_shard_map_parity.py::test_hot_default_parity).
    kw.setdefault("hot_slots", 8)
    return SwimParams(n=n, probe_every=5, suspicion_mult=4.0, retransmit_mult=4.0,
                      fanout=3, gossip_interval_s=0.2, **kw)


def wan_profile(n: int, **kw) -> SwimParams:
    """memberlist DefaultWANConfig: probe 5s / gossip 500ms, wider timers
    (selected by the reference at consul/config.go:268)."""
    kw.setdefault("pushpull_every", 120)  # 60s / 500ms gossip
    return SwimParams(n=n, probe_every=10, suspicion_mult=6.0, retransmit_mult=4.0,
                      fanout=4, gossip_interval_s=0.5, **kw)
