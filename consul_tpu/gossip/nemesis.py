"""Nemesis scenario catalog: correlated-fault injection for the SWIM kernel.

Real fleets do not fail iid — racks die together, networks bisect,
nodes flap, observers get slow.  This module is the catalog of those
adversarial scenarios, expressed as **pure injection schedules**: a
``NemesisParams`` carries only static scalars (id ranges, a hash bit,
loss probabilities, a round window), and every mask the kernel needs is
derived *inside* the jit from ``jnp.arange`` — no new traced arrays, no
in_spec churn, and the schedule hashes as a jit static argument.

Fault axes (composable; each gated by its own static flag):

- **Correlated kills** — contiguous id blocks (a rack) or hashed id
  subsets (a zone striped across racks) fail at one round.  These need
  no kernel support at all: they are ``fail_round`` constructions, and
  the scenario label is attributed host-side.
- **Partitions / asymmetric loss** — the gossip graph is bisected into
  two groups (contiguous halves or a multiplicative-hash bit) and every
  cross-group message legs through an extra Bernoulli drop:
  ``p_ab`` on A->B edges, ``p_ba`` on B->A.  ``p_ab = p_ba = 1.0`` is a
  full bisection; ``p_ba = 0`` with ``p_ab > 0`` is asymmetric loss
  (acks die, probes arrive).  Applies to gossip legs, push/pull, and
  probe round-trips (a direct probe crosses both directions, so its
  drop probability is ``1-(1-p_ab)(1-p_ba)`` regardless of direction).
- **Flapping** — an id range oscillates down/up on a square wave inside
  the window; the down phase is a ``fail_round`` override, the up phase
  re-arms ``join_round`` so the node rejoins through the ordinary join
  tick (incarnation bump, alive@inc flood) exactly like a memberlist
  restart.
- **Heal rejoin** — after a partition heals (``stop``), nodes that were
  falsely declared dead rejoin via ``join_round = min(join_round,
  stop)`` — dissemination of the recovery rides the existing join path.
- **Degraded observers (Lifeguard LHM)** — probers in an id range drop
  acks/indirect replies they *did* receive with ``p_obs_miss`` (the
  observer is slow, not the target).  The kernel pairs this with a
  local-health multiplier (``kernel.NemState``): LHM rises on
  NACK-style evidence (direct miss while helpers vouch for the target)
  and on being refuted, falls on clean probe success, and a suspicion
  only starts after ``streak > LHM`` consecutive misses — Lifeguard's
  false-positive suppression for degraded observers (PAPERS.md
  #lifeguard), absent from the kernel until now.

This module deliberately imports only numpy: the refmodel oracle and
the agent process consume it without a jax context.  The kernel-side
mask derivation lives in gossip/kernel.py and mirrors ``group_of``
bit-for-bit (the multiplicative hash uses only uint32 wraparound).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

NEVER = np.int32(2**31 - 1)  # matches kernel.NEVER (no import cycle)

# Knuth's multiplicative hash; the group bit is the top bit of the
# 32-bit product.  uint32 wraparound only — numpy and jnp agree exactly.
HASH_MULT = 2654435761


def hash_group(ids) -> np.ndarray:
    """Hash-partition group bit (0/1) per node id — numpy mirror of the
    kernel's in-jit derivation (kernel._nem_group)."""
    prod = (np.asarray(ids, dtype=np.uint64) * np.uint64(HASH_MULT)) \
        & np.uint64(0xFFFFFFFF)
    return (prod >> np.uint64(31)).astype(np.int32)


@dataclass(frozen=True)
class NemesisParams:
    """One scenario's injection schedule.  Hashable scalars ONLY — this
    is a jit static argument (kernel.run_rounds ``static_argnames``);
    adding an array field would silently retrace per call."""

    scenario: str = ""        # label for the observatory dimension
    start: int = 0            # fault window [start, stop) in rounds
    stop: int = int(NEVER)

    # -- partition / asymmetric loss ------------------------------------
    part_kind: str = "none"   # "none" | "contig" | "hash"
    p_ab: float = 0.0         # drop prob on group-0 -> group-1 edges
    p_ba: float = 0.0         # drop prob on group-1 -> group-0 edges
    heal_rejoin: bool = False  # re-arm join_round at ``stop``

    # -- flapping --------------------------------------------------------
    flap_lo: int = 0          # flapping id range [flap_lo, flap_hi)
    flap_hi: int = 0
    flap_period: int = 0      # square wave: up flap_up rounds, then down
    flap_up: int = 0

    # -- degraded observers / Lifeguard LHM ------------------------------
    obs_lo: int = 0           # degraded prober id range [obs_lo, obs_hi)
    obs_hi: int = 0
    p_obs_miss: float = 0.0   # P(degraded prober drops a reply it got)
    lhm_max: int = 0          # local-health multiplier ceiling; 0 = LHM off

    @property
    def has_partition(self) -> bool:
        return self.part_kind != "none" and (self.p_ab > 0 or self.p_ba > 0)

    @property
    def has_flap(self) -> bool:
        return self.flap_hi > self.flap_lo and self.flap_period > 0

    @property
    def has_degraded(self) -> bool:
        return self.obs_hi > self.obs_lo and self.p_obs_miss > 0

    @property
    def needs_state(self) -> bool:
        """True when the scenario threads kernel.NemState (LHM/streak)
        through the scan carry."""
        return self.lhm_max > 0

    @property
    def needs_join(self) -> bool:
        """True when the schedule rewrites join_round — callers must
        pass a join_round array (all-NEVER works)."""
        return self.has_flap or self.heal_rejoin

    @property
    def p_roundtrip(self) -> float:
        """Cross-group round-trip drop probability: any request/reply
        pair crosses both directions once."""
        return 1.0 - (1.0 - self.p_ab) * (1.0 - self.p_ba)


def group_of(nem: NemesisParams, n: int) -> np.ndarray:
    """Partition group bit (0/1) per node id, [n] int32."""
    ids = np.arange(n)
    if nem.part_kind == "hash":
        return hash_group(ids)
    return (ids >= n // 2).astype(np.int32)


@dataclass
class Scenario:
    """A fully-instantiated scenario at cluster size ``n``: the static
    schedule plus its ground-truth arrays and a suggested horizon."""

    name: str
    nem: NemesisParams
    fail_round: np.ndarray               # i32 [n] ground-truth kills
    join_round: Optional[np.ndarray]     # i32 [n] or None (no join path)
    steps: int                           # suggested simulation horizon
    description: str

    @property
    def killed(self) -> np.ndarray:
        return self.fail_round < NEVER


def _base(n: int) -> np.ndarray:
    return np.full((n,), NEVER, dtype=np.int32)


def _block_kill(n: int) -> Scenario:
    fail = _base(n)
    lo, hi = n // 8, n // 4
    fail[lo:hi] = 30
    return Scenario(
        name="block_kill",
        nem=NemesisParams(scenario="block_kill"),
        fail_round=fail, join_round=None, steps=400,
        description=(f"Rack kill: contiguous ids [{lo}, {hi}) all fail at "
                     f"round 30 — correlated loss of n/8 members at once."))


def _zone_kill(n: int) -> Scenario:
    fail = _base(n)
    ids = np.arange(n)
    victims = (hash_group(ids) == 1) & (ids % 8 == 0)
    fail[victims] = 30
    return Scenario(
        name="zone_kill",
        nem=NemesisParams(scenario="zone_kill"),
        fail_round=fail, join_round=None, steps=400,
        description=("Zone kill: a hashed ~n/16 subset striped across the "
                     "id space fails at round 30."))


def _partition_heal(n: int) -> Scenario:
    nem = NemesisParams(scenario="partition_heal", start=40, stop=160,
                        part_kind="contig", p_ab=1.0, p_ba=1.0,
                        heal_rejoin=True)
    return Scenario(
        name="partition_heal",
        nem=nem, fail_round=_base(n), join_round=_base(n), steps=400,
        description=("Full bisection rounds [40, 160): no message crosses "
                     "the halves; both sides declare the other dead, then "
                     "the heal re-arms join_round and membership recovers."))


def _asym_loss(n: int) -> Scenario:
    fail = _base(n)
    ids = np.arange(n)
    fail[ids % 37 == 5] = 40
    nem = NemesisParams(scenario="asym_loss", start=20,
                        part_kind="hash", p_ab=0.6, p_ba=0.0)
    return Scenario(
        name="asym_loss",
        nem=nem, fail_round=fail, join_round=None, steps=400,
        description=("Asymmetric loss from round 20 on: hashed group-0 -> "
                     "group-1 edges drop 60% (replies die, requests "
                     "arrive), plus scattered true kills at round 40."))


def _flapping(n: int) -> Scenario:
    hi = max(2, n // 64)
    # Down phases must outlast the Lifeguard suspicion timeout
    # (~50-290 rounds at oracle scale, params.timeout_table) or no
    # verdict ever fires and the scenario measures nothing: 60 up / 80
    # down gives two full detect->rejoin cycles inside the window.
    nem = NemesisParams(scenario="flapping", start=30, stop=310,
                        flap_lo=0, flap_hi=hi, flap_period=140, flap_up=60)
    return Scenario(
        name="flapping",
        nem=nem, fail_round=_base(n), join_round=_base(n), steps=420,
        description=(f"Flapping: ids [0, {hi}) oscillate 60 rounds up / 80 "
                     "down through rounds [30, 310), rejoining through the "
                     "join tick (incarnation bump) on every up edge."))


def _degraded_observer(n: int) -> Scenario:
    fail = _base(n)
    ids = np.arange(n)
    fail[ids % 29 == 7] = 30
    nem = NemesisParams(scenario="degraded_observer",
                        obs_lo=0, obs_hi=max(1, n // 4),
                        p_obs_miss=0.3, lhm_max=3)
    return Scenario(
        name="degraded_observer",
        nem=nem, fail_round=fail, join_round=None, steps=400,
        description=("Slow observers: probers in [0, n/4) drop 30% of the "
                     "replies they receive; the Lifeguard local-health "
                     "multiplier suppresses their false suspicions while "
                     "true kills at round 30 must still be detected."))


CATALOG: Dict[str, Callable[[int], Scenario]] = {
    "block_kill": _block_kill,
    "zone_kill": _zone_kill,
    "partition_heal": _partition_heal,
    "asym_loss": _asym_loss,
    "flapping": _flapping,
    "degraded_observer": _degraded_observer,
}


def names() -> List[str]:
    return list(CATALOG)


def build(name: str, n: int) -> Scenario:
    """Instantiate a catalog scenario at cluster size ``n``."""
    try:
        factory = CATALOG[name]
    except KeyError:
        raise ValueError(
            f"unknown nemesis scenario {name!r}; have {sorted(CATALOG)}"
        ) from None
    return factory(n)
