"""One-pass Pallas dissemination: age + circulant gossip + SWAR-merge
in a single traversal of the belief matrix.

BENCH_NOTES §1c prices the dense round at ~5 full [S, N] passes (1
read + 3 shifted reads + 1 write at the chip's ~185 GB/s effective
bandwidth) and attributes the remaining headroom to XLA
materialization boundaries between the age / gossip / merge stages.
This module is the direct attack (ROADMAP item 2): a ``pallas_call``
whose grid walks the observer axis in column blocks, reading each
block of ``heard`` once, computing every rolled pin delivery *in
VMEM*, and writing each output block once — the matrix crosses HBM
twice per round instead of five times.

**Static-offset block windows.** The circulant shifts are traced
per-round scalars, and an earlier attempt to express the shifted
reads as arbitrary-offset ``make_async_copy`` DMAs was rejected by
Mosaic.  The restructuring that sidesteps it: a shift ``o``
decomposes into a block part ``q = o // Bn`` and a residue
``r = o % Bn``, so output block ``j`` of the rolled matrix is fully
covered by input blocks ``(j - q - 1) % nb`` and ``(j - q) % nb``.
Block indices are data-dependent but *block-granular* — exactly what
``pltpu.PrefetchScalarGridSpec`` exists for: the ``(q, r)`` pairs ride
a scalar-prefetch operand, the ``BlockSpec`` index maps read ``q``
to pick the two windows, and the kernel body splices the residue with
one in-VMEM ``dynamic_slice``.  No arbitrary-shift DMA anywhere.

**Bit-exactness.** The merge body is the per-byte meaning of the SWAR
word ops in ``kernel._disseminate_swar`` (every compared field is
< 0x80, so ``_byte_ge``/``_byte_eq``/``_byte_sel`` are exact per-byte
``>=``/``==``/``where``), and aging commutes with the rolls (it is
elementwise; a roll is a permutation), so applying ``_age_tick``'s
semantics to each rolled pin equals rolling the aged matrix.  Parity
with ``_disseminate_swar`` is pinned bit-for-bit by
``tests/test_fused_parity.py`` across healthy/churn/loss/pushpull/
hot-tier/sharded rounds.

**Where it runs.** Hardware is currently unreachable, so every path
here must execute on this box: the kernel runs under
``interpret=True`` whenever the backend is not a TPU (CPU CI, the
8-device virtual mesh) and compiles via Mosaic on a real chip — §5c's
next chip session flips nothing but the backend.

**Sharded composition.** Under ``shard_map`` the rolled pins cross
shard boundaries, which is the existing halo hop's job
(``kernel._roll_sharded``: local roll + log2(P) conditional ppermutes
+ one neighbor exchange) — a Pallas grid cannot issue collectives
mid-kernel.  The sharded leg therefore pre-rolls the pins in XLA and
fuses everything after the halo (aging, budget mask, priority merge,
confirmation count) in one elementwise Pallas pass over the local
block.  Single-device keeps the full one-pass structure.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from consul_tpu.gossip.kernel import (_AGE_FRESH, _AGE_MASK, _CONF_MASK,
                                      _CONF_SHIFT, _MSG_SHIFT, _nem_leg_drop,
                                      _roll_sharded, _sloc, _sloc_roll,
                                      MSG_SUSPECT, gossip_offsets)
from consul_tpu.gossip.params import SwimParams
from consul_tpu.ops.divisibility import require_divisible


@functools.lru_cache(maxsize=1)
def _interpret() -> bool:
    """Pallas interpret mode everywhere except a real TPU backend (the
    CPU mesh runs the same kernel body through the reference
    interpreter — bit-identical, just not fast)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _age_u8(x):
    """``_age_tick`` semantics on int32 lanes each holding one belief
    byte: fresh probe marks (``_AGE_FRESH``) become age 0, real ages
    saturate at ``_AGE_MASK - 1``, message-free bytes are untouched."""
    age = x & _AGE_MASK
    new_age = jnp.where(age == _AGE_FRESH, 0,
                        jnp.minimum(age + 1, _AGE_MASK - 1))
    return jnp.where((x >> _MSG_SHIFT) > 0, (x & ~_AGE_MASK) | new_age, x)


def _merge(p: SwimParams, cur, pins, srcs, rx, cap):
    """Priority-max merge + Lifeguard confirmation counting on int32
    lanes — the per-byte meaning of the SWAR block in
    ``_disseminate_swar`` (each comment there applies here verbatim).
    ``cur``/``pins`` are ALREADY aged; ``srcs``/``rx`` are 0/1 masks;
    ``cap`` broadcasts per slot row."""
    budget = p.spread_budget_rounds
    in_msg = jnp.zeros_like(cur)
    n_sus = jnp.zeros_like(cur)
    for pin, src in zip(pins, srcs):
        live = ((pin & _AGE_MASK) < budget) & (src > 0)
        m = jnp.where(live, pin >> _MSG_SHIFT, 0)
        in_msg = jnp.maximum(in_msg, m)
        n_sus = n_sus + (m == MSG_SUSPECT).astype(jnp.int32)
    rxm = rx > 0
    cur_msg = cur >> _MSG_SHIFT
    age_c = cur & _AGE_MASK
    conf = (cur >> _CONF_SHIFT) & _CONF_MASK
    upgraded = (in_msg > cur_msg) & rxm
    bump = (cur_msg == MSG_SUSPECT) & (in_msg == MSG_SUSPECT) & rxm
    # conf + n_sus <= 6: no overflow anywhere near the int32 lane.
    conf_new = jnp.where(bump, jnp.minimum(conf + n_sus, cap), conf)
    # Rising confirmation count refreshes the spread window (memberlist
    # re-enqueue semantics — the long comment in _disseminate_swar).
    conf_rose = conf_new > conf
    out_msg = jnp.where(upgraded, in_msg, cur_msg)
    out_age = jnp.where(upgraded | conf_rose, 0, age_c)
    out_conf = jnp.where(upgraded, 0, conf_new)
    return (out_msg << _MSG_SHIFT) | (out_conf << _CONF_SHIFT) | out_age


def _src_masks(p: SwimParams, rnd, offs, mf, sc, nem, k_nem):
    """[fanout, L] uint8 sender-liveness masks, one per gossip leg —
    O(N) vectors built in XLA (they are three orders of magnitude
    smaller than the belief matrix; fusing them into the Pallas pass
    would buy nothing and cost the nemesis composition)."""
    rows = []
    for f in range(p.fanout):
        o = offs[f]
        mf_r = jnp.roll(mf, o) if sc is None else _sloc_roll(sc, mf, o)
        src_live = mf_r > rnd
        if nem is not None and nem.has_partition:
            src_live = src_live & ~_nem_leg_drop(p, nem, k_nem, rnd, f, o,
                                                 sc)
        rows.append(src_live)
    return jnp.stack(rows).astype(jnp.uint8)


# -- single-device: the one-pass block-window kernel ----------------------

def _fused_single(p: SwimParams, heard, offs, src, rx, cap) -> jnp.ndarray:
    S, N = heard.shape
    nb = p.fused_nb
    # The shared contract (ops/divisibility.py): the vet P01 pass
    # treats this exact call as the guard for the N // nb block width.
    require_divisible(N, nb, what="n", by="fused_nb")
    Bn = N // nb
    fanout = p.fanout

    def kern(qr_ref, cur_ref, *rest):
        ab = rest[:2 * fanout]
        src_ref, rx_ref, cap_ref, out_ref = rest[2 * fanout:]
        cur = _age_u8(cur_ref[...].astype(jnp.int32))
        pins, srcs = [], []
        for f in range(fanout):
            # Window splice: blocks A|B side by side, the pin block
            # starts r columns before the A/B seam (module docstring).
            r = qr_ref[fanout + f]
            pair = jnp.concatenate(
                [ab[2 * f][...], ab[2 * f + 1][...]],
                axis=1).astype(jnp.int32)
            pin = jax.lax.dynamic_slice(pair, (0, Bn - r), (S, Bn))
            pins.append(_age_u8(pin))
            srcs.append(src_ref[f, :][None, :].astype(jnp.int32))
        out = _merge(p, cur, pins, srcs,
                     rx_ref[...].astype(jnp.int32),
                     cap_ref[...].astype(jnp.int32))
        out_ref[...] = out.astype(jnp.uint8)

    in_specs = [pl.BlockSpec((S, Bn), lambda j, qr: (0, j))]
    for f in range(fanout):
        in_specs.append(pl.BlockSpec(
            (S, Bn), lambda j, qr, f=f: (0, (j - qr[f] - 1) % nb)))
        in_specs.append(pl.BlockSpec(
            (S, Bn), lambda j, qr, f=f: (0, (j - qr[f]) % nb)))
    in_specs += [
        pl.BlockSpec((fanout, Bn), lambda j, qr: (0, j)),
        pl.BlockSpec((1, Bn), lambda j, qr: (0, j)),
        pl.BlockSpec((S, 1), lambda j, qr: (0, 0)),
    ]
    qr = jnp.concatenate([offs // Bn, offs % Bn]).astype(jnp.int32)
    operands = [heard] + [heard] * (2 * fanout) + [
        src, rx[None, :], cap.astype(jnp.int32)[:, None]]
    return pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((S, Bn), lambda j, qr: (0, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((S, N), jnp.uint8),
        interpret=_interpret(),
    )(qr, *operands)


# -- sharded: halo-hop pins in XLA, everything after fused ----------------

def _fused_sharded(p: SwimParams, heard, offs, src, rx, cap,
                   sc) -> jnp.ndarray:
    S, L = heard.shape
    fanout = p.fanout
    pins = jnp.stack([_roll_sharded(sc, heard, offs[f])
                      for f in range(fanout)])

    def kern(cur_ref, pins_ref, src_ref, rx_ref, cap_ref, out_ref):
        cur = _age_u8(cur_ref[...].astype(jnp.int32))
        ps = [_age_u8(pins_ref[f].astype(jnp.int32))
              for f in range(fanout)]
        srcs = [src_ref[f, :][None, :].astype(jnp.int32)
                for f in range(fanout)]
        out = _merge(p, cur, ps, srcs,
                     rx_ref[...].astype(jnp.int32),
                     cap_ref[...].astype(jnp.int32))
        out_ref[...] = out.astype(jnp.uint8)

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((S, L), jnp.uint8),
        interpret=_interpret(),
    )(heard, pins, src, rx[None, :], cap.astype(jnp.int32)[:, None])


def fused_disseminate(p: SwimParams, rnd, k_gossip, heard, mf, rx_ok,
                      conf_cap, sc=None, nem=None,
                      k_nem=None) -> jnp.ndarray:
    """Drop-in for ``kernel._disseminate_swar`` behind
    ``SwimParams.dissem == "fused"`` — same signature, bit-identical
    output (module docstring)."""
    offs = gossip_offsets(k_gossip, p.n, p.fanout)
    src = _src_masks(p, rnd, offs, mf, sc, nem, k_nem)
    rx_l = rx_ok if sc is None else _sloc(sc, rx_ok)
    rx = rx_l.astype(jnp.uint8)
    if sc is None:
        return _fused_single(p, heard, offs, src, rx, conf_cap)
    return _fused_sharded(p, heard, offs, src, rx, conf_cap, sc)
