"""Server core: FSM ownership, apply path, endpoint registry.

Parity target: ``consul/server.go`` + ``consul/rpc.go`` in the
reference.  This slice implements the single-node ("bootstrap") shape:
``raft_apply`` goes straight through the FSM with a monotonically
increasing index, exercising the same typed-entry codec the replicated
path uses (consul/rpc.go:280-297 encodes MessageType + msgpack body);
the Raft engine (consensus/raft.py) slots in behind ``raft_apply``
without endpoint changes.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from consul_tpu.consensus.fsm import ConsulFSM
from consul_tpu.state.tombstone_gc import TombstoneGC
from consul_tpu.structs import codec
from consul_tpu.structs.structs import MessageType

MAX_RAFT_ENTRY_WARN = 1024 * 1024  # 1MB soft cap (consul/rpc.go:42-44)


@dataclass
class ServerConfig:
    node_name: str = "node1"
    datacenter: str = "dc1"
    domain: str = "consul."
    bootstrap: bool = True
    # Protocol timing (test configs compress these, consul/server_test.go:50-69)
    reconcile_interval: float = 60.0
    tombstone_ttl: float = 15 * 60.0
    tombstone_granularity: float = 30.0
    session_ttl_min: float = 10.0
    extra: Dict[str, Any] = field(default_factory=dict)


class Server:
    """In-process server node.  Owns the FSM/state store and the write
    path; endpoint objects hang off it (consul/server.go:414-431)."""

    def __init__(self, config: Optional[ServerConfig] = None) -> None:
        self.config = config or ServerConfig()
        self.gc = TombstoneGC(self.config.tombstone_ttl,
                              self.config.tombstone_granularity)
        self.fsm = ConsulFSM(gc_hint=lambda idx: self.gc.hint(idx, time.monotonic()))
        self._raft_index = 0
        self._leader = True  # single-node bootstrap; Raft flips this later
        self.start_time = time.monotonic()
        # Endpoint registry (server.go:414-431 registers the 7 services).
        from consul_tpu.server.endpoints import (
            Catalog, Health, Internal, KVS, SessionEndpoint, Status)
        self.status = Status(self)
        self.catalog = Catalog(self)
        self.health = Health(self)
        self.kvs = KVS(self)
        self.session = SessionEndpoint(self)
        self.internal = Internal(self)
        self._endpoints = {
            "Status": self.status, "Catalog": self.catalog, "Health": self.health,
            "KVS": self.kvs, "Session": self.session, "Internal": self.internal,
        }

    @property
    def store(self):
        return self.fsm.store

    def is_leader(self) -> bool:
        return self._leader

    def leader_addr(self) -> str:
        return self.config.node_name if self._leader else ""

    def raft_last_index(self) -> int:
        return self._raft_index

    async def raft_apply(self, msg_type: MessageType, req: Any) -> Any:
        """Apply a write through the consensus path (consul/rpc.go:280-297).

        Single-node: encode (same framing the wire uses), bump the index,
        apply.  The encode/decode round-trip is deliberate — it keeps the
        FSM honest about operating on decoded wire payloads only.
        """
        buf = codec.encode(int(msg_type), req)
        if len(buf) > MAX_RAFT_ENTRY_WARN:
            # Reference warns and proceeds (rpc.go:42-44).
            pass
        if not self._leader:
            raise NotLeaderError("Not the leader")
        self._raft_index += 1
        result = self.fsm.apply(self._raft_index, buf)
        # Yield so watch waiters scheduled by notify() can run promptly.
        await asyncio.sleep(0)
        return result

    async def consistent_read_barrier(self) -> None:
        """VerifyLeader equivalent (consul/rpc.go:413-417): single-node
        leadership is unconditional; Raft supplies a real barrier later."""
        if not self._leader:
            raise NotLeaderError("Not the leader")

    def endpoint(self, name: str):
        return self._endpoints[name]

    def raft_peers(self) -> list:
        return [self.config.node_name]

    def known_datacenters(self) -> list:
        """Sorted DC list (consul/catalog_endpoint.go:97-115); the WAN pool
        populates remote DCs once gossip lands."""
        return [self.config.datacenter]

    async def resolve_token(self, token: str):
        """ACL resolution (consul/acl.go:70-148).  None = ACLs disabled;
        the ACL engine supplies a real resolver."""
        return None

    async def filter_acl_service_nodes(self, token: str, nodes: list) -> list:
        acl = await self.resolve_token(token)
        if acl is None:
            return nodes
        return [n for n in nodes if acl.service_read(n.service_name)]

    def reset_session_timer(self, sid: str, session) -> None:
        """Leader-owned TTL timer (consul/session_ttl.go); armed once the
        session-TTL manager lands."""

    def clear_session_timer(self, sid: str) -> None:
        pass

    async def fire_user_event(self, event) -> None:
        """Broadcast via the gossip plane (consul/internal_endpoint.go
        EventFire); local-only until the event pipeline lands."""

    def stats(self) -> Dict[str, Dict[str, str]]:
        """``consul info`` payload (consul/server.go:709-726)."""
        return {
            "consul": {
                "server": "true",
                "leader": str(self.is_leader()).lower(),
                "bootstrap": str(self.config.bootstrap).lower(),
            },
            "raft": {
                "applied_index": str(self._raft_index),
                "last_log_index": str(self._raft_index),
                "state": "Leader" if self._leader else "Follower",
            },
            "runtime": {
                "uptime_s": str(int(time.monotonic() - self.start_time)),
            },
        }


class NotLeaderError(Exception):
    pass
