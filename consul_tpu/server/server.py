"""Server core: Raft-backed apply path, FSM ownership, endpoint registry.

Parity target: ``consul/server.go`` + ``consul/rpc.go``.  Every write
goes through the local Raft node (``raft_apply``, consul/rpc.go:280-297
— encode MessageType byte + msgpack body, apply, surface FSM errors);
reads come straight off the FSM's state store, optionally behind a
leadership barrier (``consistent_read_barrier`` = VerifyLeader,
consul/rpc.go:413-417).  Leadership changes arm/disarm the leader
duties (session TTLs, tombstone GC — server/leader.py).

Single-node "bootstrap" servers run a one-peer Raft cluster (instant
election); multi-server clusters share a transport — in-process
MemoryTransport under test, the TCP RPC mesh in production.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from consul_tpu.consensus.fsm import ConsulFSM
from consul_tpu.consensus.log import FileLogStore, MemoryLogStore
from consul_tpu.consensus.raft import (
    MemoryTransport, NotLeaderError as RaftNotLeaderError, RaftConfig, RaftNode)
from consul_tpu.consensus.snapshot import FileSnapshotStore, MemorySnapshotStore
from consul_tpu.obs import journey as _journey
from consul_tpu.obs import trace as obs_trace
from consul_tpu.server.leader import LeaderDuties
from consul_tpu.state.tombstone_gc import TombstoneGC
from consul_tpu.structs import codec
from consul_tpu.structs.structs import MessageType

MAX_RAFT_ENTRY_WARN = 1024 * 1024  # 1MB soft cap (consul/rpc.go:42-44)
ENQUEUE_LIMIT = 30.0               # max wait for the apply (rpc.go:45-50)


@dataclass
class ServerConfig:
    node_name: str = "node1"
    datacenter: str = "dc1"
    domain: str = "consul."
    bootstrap: bool = True
    peers: List[str] = field(default_factory=list)  # raft peer ids; [] = self only
    # >0: start as a passive follower with NO raft peers and wait for
    # bootstrap-expect self-assembly (maybeBootstrap, consul/serf.go:185-236)
    # or a leader's AddPeer (joinConsulServer, consul/leader.go:504).
    bootstrap_expect: int = 0
    data_dir: str = ""  # "" = in-memory log/snapshots (dev mode)
    raft: RaftConfig = field(default_factory=RaftConfig)
    # Protocol timing (test configs compress these, consul/server_test.go:50-69)
    reconcile_interval: float = 60.0
    tombstone_ttl: float = 15 * 60.0
    tombstone_granularity: float = 30.0
    session_ttl_min: float = 10.0
    # ACL knobs (consul/config.go ACLDatacenter/ACLTTL/ACLDefaultPolicy/
    # ACLDownPolicy/ACLMasterToken; defaults at config.go:253-256)
    acl_datacenter: str = ""        # "" = ACLs disabled
    acl_ttl: float = 30.0
    acl_default_policy: str = "allow"
    acl_down_policy: str = "extend-cache"
    acl_master_token: str = ""
    # Device-resident state store (PR 11): mirror the KV table into a
    # fixed-capacity device hash table, batch committed entries at the
    # commit→apply boundary, and match watches device-side.  Host stays
    # authoritative; the bridge cross-checks every verdict.
    device_store: bool = False
    device_store_capacity: int = 1 << 16
    # Fault-injection seam (chaos/broker.NodeFaults): threads this
    # node's virtual clock + fsync hooks into the RaftNode.  None in
    # production — every seam then costs one is-None test.
    faults: Any = None
    extra: Dict[str, Any] = field(default_factory=dict)


class Server:
    """One server node.  Owns the Raft node + FSM/state store and the
    write path; endpoint objects hang off it (consul/server.go:414-431)."""

    def __init__(self, config: Optional[ServerConfig] = None,
                 transport: Optional[Any] = None) -> None:
        self.config = config or ServerConfig()
        self.gc = TombstoneGC(self.config.tombstone_ttl,
                              self.config.tombstone_granularity)
        # KV table backend: servers with a data dir run the C++ mmap
        # MVCC store underneath (the LMDB role, state_store.go:15);
        # dev-mode servers use in-process dicts.  Like the reference's
        # temp-dir LMDB, the file is recreated per boot — durability is
        # the raft log's job (state_store.go:190-196).
        kv_factory = None
        if self.config.data_dir:
            from consul_tpu.native import native_available
            if native_available():
                import os as _os

                from consul_tpu.state.kvtable import NativeKVTable
                state_dir = _os.path.join(self.config.data_dir, "state")
                kv_factory = lambda: NativeKVTable(state_dir)  # noqa: E731
        self.fsm = ConsulFSM(
            gc_hint=lambda idx: self.gc.hint(idx, time.monotonic()),
            kv_backend_factory=kv_factory)
        if self.config.device_store:
            # Lazy import: pulls in jax; only paid when the flag is on.
            from consul_tpu.state.device_store import DeviceStoreBridge
            self.fsm.attach_device_store(DeviceStoreBridge(
                capacity=self.config.device_store_capacity))
        self.start_time = time.monotonic()

        if self.config.bootstrap_expect:
            peers: List[str] = []  # passive until assembly/AddPeer
        else:
            peers = self.config.peers or [self.config.node_name]
        if self.config.data_dir:
            import os
            raft_dir = os.path.join(self.config.data_dir, "raft")
            # The on-disk format decides the backend — a toolchain change
            # must NEVER flip a node to an empty log (that would amnesia
            # its term/vote and allow double-voting).  Fresh data dirs
            # prefer the C++ mmap store (the raft-boltdb role; first boot
            # pays a one-time build) and fall back to the Python segment
            # log if the toolchain is absent.  Errors opening an EXISTING
            # store propagate rather than silently starting empty.
            has_native = os.path.exists(os.path.join(raft_dir, "raft.cstore"))
            has_file = os.path.exists(os.path.join(raft_dir, "log.seg"))
            if has_native:
                from consul_tpu.native import NativeLogStore
                log_store = NativeLogStore(raft_dir)
            elif has_file:
                log_store = FileLogStore(raft_dir)
            else:
                from consul_tpu.native import NativeLogStore, native_available
                if native_available():
                    log_store = NativeLogStore(raft_dir)
                else:
                    log_store = FileLogStore(raft_dir)
            snap_store = FileSnapshotStore(os.path.join(self.config.data_dir, "snaps"))
        else:
            log_store, snap_store = MemoryLogStore(), MemorySnapshotStore()
        self.raft = RaftNode(self.config.node_name, peers, self.fsm,
                             transport if transport is not None else MemoryTransport(),
                             self.config.raft, log_store=log_store,
                             snap_store=snap_store,
                             faults=self.config.faults)
        self.leader_duties = LeaderDuties(self)
        self.raft.on_leader_change(self.leader_duties.on_leader_change)
        # User-event delivery targets (the agent registers; the gossip
        # plane will too once cross-node fan-out lands).
        self.event_sinks: List[Any] = []
        # RPC mesh (attach_rpc wires these): pooled client, TCP listener,
        # node->addr routes in this DC, dc->[addrs] for WAN forwarding
        # (the localConsuls/remoteConsuls maps, consul/serf.go:239-275).
        self.pool = None
        self.rpc_server = None
        self.route_table: Dict[str, str] = {}
        self.remote_dcs: Dict[str, List[str]] = {}
        self.keyring = None  # agent-owned gossip keyring
        # Membership plane (wired by the agent): reconcile_ch carries
        # gossip member events to the leader loop (consul/serf.go:90-110);
        # lan_members_fn supplies the pool view for full reconciles
        # (consul/leader.go:242-260).
        self.reconcile_ch: Optional[asyncio.Queue] = None
        self.lan_members_fn: Optional[Any] = None
        self.user_event_broadcaster: Optional[Any] = None
        self._barrier_inflight: Optional[asyncio.Future] = None
        # ReadIndex batching: per-key unfired batch new confirmations
        # may join + the previously-running batch (keys: follower_ri,
        # leader_ri).
        self._confirm_batches: Dict[str, dict] = {}
        self._confirm_prev: Dict[str, asyncio.Future] = {}
        self._confirm_tasks: set = set()  # anchor batch runners vs GC

        # Endpoint registry (server.go:414-431 registers the 7 services).
        from consul_tpu.server.endpoints import (
            ACLEndpoint, Catalog, Health, Internal, KVS, SessionEndpoint, Status)
        from consul_tpu.server.acl import ServerACLResolver
        self.acl_resolver = ServerACLResolver(self)
        self.status = Status(self)
        self.catalog = Catalog(self)
        self.health = Health(self)
        self.kvs = KVS(self)
        self.session = SessionEndpoint(self)
        self.internal = Internal(self)
        self.acl = ACLEndpoint(self)
        self._endpoints = {
            "Status": self.status, "Catalog": self.catalog, "Health": self.health,
            "KVS": self.kvs, "Session": self.session, "Internal": self.internal,
            "ACL": self.acl,
        }

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self.reconcile_ch = asyncio.Queue(maxsize=256)
        self.raft.start()

    def membership_notify(self, kind: str, member: Any) -> None:
        """Non-blocking push of a gossip member event toward the leader
        loop (localMemberEvent's buffered send, consul/serf.go:105-108);
        drops on overflow — the periodic full reconcile repairs."""
        if self.reconcile_ch is None:
            return
        jy = _journey.journey
        if jy is not None:
            now = time.monotonic()
            rec = getattr(member, "_journey", None)
            if rec is None:
                # Direct injection (bench/chaos/tests): the journey
                # starts here — which is the harness's own t0, so the
                # e2e histogram matches the harness measurement.
                member._journey = {"t0": now, "t_enq": now, "stages": {}}
            else:
                enq_ms = (now - rec["prev"]) * 1000.0
                jy.stage_observe("enqueue", enq_ms)
                if enq_ms >= 0.0:
                    rec["stages"]["enqueue"] = round(enq_ms, 3)
                rec["t_enq"] = now
        try:
            self.reconcile_ch.put_nowait((kind, member))
        except asyncio.QueueFull:
            pass

    async def stop(self) -> None:
        self.leader_duties.revoke()
        await self.leader_duties.drain()
        # The coalesced barrier task may still be in flight (its waiters
        # are shielded and can all be gone); cancel and AWAIT it, or the
        # loop closes over a pending task ("Task was destroyed ...").
        fut, self._barrier_inflight = self._barrier_inflight, None
        if fut is not None and not fut.done():
            fut.cancel()
            await asyncio.gather(fut, return_exceptions=True)
        # Same obligation for the confirm-batch runners: they are
        # spawned fire-and-forget, so stop() must cancel and AWAIT
        # them.  Cancellation rides each runner's BaseException
        # handler, which resolves its batch future before re-raising —
        # joiners get an exception, never a hang.
        runners = list(self._confirm_tasks)
        for t in runners:
            t.cancel()
        if runners:
            await asyncio.gather(*runners, return_exceptions=True)
        if self.rpc_server is not None:
            await self.rpc_server.stop()
        if self.pool is not None:
            await self.pool.close()
        await self.raft.shutdown()
        self.fsm.store.close()

    async def wait_for_leader(self, timeout: float = 10.0) -> None:
        """Poll until the cluster has a known leader (WaitForLeader,
        testutil/wait.go:32-43)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.raft.leader_id is not None:
                return
            await asyncio.sleep(0.01)
        raise TimeoutError("no leader elected")

    @property
    def store(self):
        return self.fsm.store

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    def leader_addr(self) -> str:
        return self.raft.leader_id or ""

    def raft_last_index(self) -> int:
        return self.raft.last_applied

    def lease_state(self) -> Dict[str, Any]:
        """Serving-plane lease introspection (Status.lease / the
        /v1/status/lease route): whether consistent reads on this node
        are currently barrier-free, and at which index."""
        valid = self.raft.lease_valid()
        return {
            "leader": self.raft.leader_id or "",
            "is_leader": self.raft.is_leader(),
            "valid": valid,
            "remaining_ms": int(self.raft.lease_remaining() * 1000),
            "read_index": self.raft.commit_index if valid else 0,
            "applied_index": self.raft.last_applied,
        }

    async def raft_apply(self, msg_type: MessageType, req: Any) -> Any:
        """Apply a write through consensus (consul/rpc.go:280-297).
        Non-leaders with a route to the leader forward the encoded entry
        (the forwardLeader hop of rpc.go:204)."""
        from consul_tpu.utils.telemetry import metrics
        metrics.incr_counter(("consul", "raft", "apply"))
        buf = codec.encode(int(msg_type), req)
        if len(buf) > MAX_RAFT_ENTRY_WARN:
            # Reference warns and proceeds (rpc.go:42-44).
            pass
        span = obs_trace.child_span("raft-apply",
                                    tags={"type": msg_type.name.lower()})
        try:
            return await self.raft.apply(buf, timeout=ENQUEUE_LIMIT)
        except RaftNotLeaderError as e:
            if self.pool is not None:
                leader_addr = self.route_table.get(self.raft.leader_id or "")
                if leader_addr:
                    return await self.pool.rpc(leader_addr, "Server.Apply",
                                               {"buf": buf})
            raise NotLeaderError(str(e)) from e
        finally:
            obs_trace.finish_span(span)

    async def raft_apply_batch(self, ops: List[tuple]) -> Any:
        """Apply N writes through consensus as ONE log entry (PR 18):
        a BATCH envelope carrying the encoded sub-entries.  The batched
        reconcile pass pays append→quorum once per drain cadence instead
        of once per transition.  Returns the per-sub result list (error
        strings in failed slots, mirroring raft_apply's FSM-error
        surfacing); the NotLeader forward ships the same envelope bytes,
        so a mid-batch leader change retries the whole batch atomically.
        """
        import msgpack as _msgpack

        from consul_tpu.utils.telemetry import metrics
        metrics.incr_counter(("consul", "raft", "apply"))
        subs = [codec.encode(int(t), req) for t, req in ops]
        buf = bytes([int(MessageType.BATCH)]) + _msgpack.packb(
            subs, use_bin_type=True)
        span = obs_trace.child_span("raft-apply",
                                    tags={"type": "batch", "subs": len(subs)})
        try:
            return await self.raft.apply(buf, timeout=ENQUEUE_LIMIT)
        except RaftNotLeaderError as e:
            if self.pool is not None:
                leader_addr = self.route_table.get(self.raft.leader_id or "")
                if leader_addr:
                    return await self.pool.rpc(leader_addr, "Server.Apply",
                                               {"buf": buf})
            raise NotLeaderError(str(e)) from e
        finally:
            obs_trace.finish_span(span)

    async def raft_apply_raw(self, buf: bytes) -> Any:
        """Leader-side target of the Server.Apply forward."""
        try:
            return await self.raft.apply(buf, timeout=ENQUEUE_LIMIT)
        except RaftNotLeaderError as e:
            raise NotLeaderError(str(e)) from e

    async def consistent_read_barrier(self) -> None:
        """Linearizable-read prologue, follower-capable.

        On the leader: VerifyLeader (consul/rpc.go:413-417) — a barrier
        commit proving current leadership.  On a follower: the ReadIndex
        protocol (Raft §6.4, the etcd follower-read design) — ask the
        leader for a leadership-verified commit index, wait until the
        local FSM has applied through it, then serve the read LOCALLY.
        Where the reference ships every ?consistent request to the
        leader in full, this costs the leader one index round-trip and
        keeps the read (and its blocking-query machinery) on the node
        that received it."""
        span = obs_trace.child_span(
            "read-barrier",
            tags={"role": "leader" if self.raft.is_leader() else "follower"})
        try:
            if self.raft.is_leader() or self.pool is None:
                await self._leader_confirm()
            else:
                await self._follower_confirm()
        except RaftNotLeaderError as e:
            raise NotLeaderError(str(e)) from e
        finally:
            obs_trace.finish_span(span)

    async def _leader_confirm(self) -> int:
        """Coalesced leader barrier; returns the read-safe index
        (everything below the barrier entry is committed under the
        CURRENT term once it lands — Raft §6.4's precondition: a fresh
        leader's commit_index may lag entries its predecessor acked
        until its first own-term commit).  Sharing an IN-FLIGHT barrier
        is safe here: the proof each leader-local read needs is only
        "leadership held at some moment after the read arrived", which
        any post-arrival completion supplies.

        Lease fast path: while the leader holds a quorum-renewed lease
        (raft.lease_valid) no other leader can exist, so leadership is
        already proven — the read serves at commit_index with ZERO
        barrier/ReadIndex RPCs.  Expiry (partition, deposition, slow
        heartbeats) falls back to the coalesced barrier below."""
        from consul_tpu.utils.telemetry import metrics
        idx = self.raft.lease_read_index()
        if idx is not None:
            metrics.incr_counter(("consul", "read", "lease"))
            if self.raft.obs is not None:
                self.raft.obs.lease_observe(
                    self.raft.lease_remaining() * 1000.0,
                    self.raft.current_term)
            await self.raft.wait_applied(idx, timeout=ENQUEUE_LIMIT)
            return idx
        metrics.incr_counter(("consul", "read", "barrier"))
        fut = self._barrier_inflight
        if fut is None or fut.done():
            async def _run():
                return await self.raft.barrier(timeout=ENQUEUE_LIMIT) - 1
            fut = asyncio.ensure_future(_run())
            # Shielded waiters can all abandon this future (timeout,
            # disconnect); retrieve its exception so a failed barrier
            # never logs "exception was never retrieved" at GC.
            fut.add_done_callback(
                lambda f: f.cancelled() or f.exception())
            self._barrier_inflight = fut
        return await asyncio.shield(fut)

    async def _follower_confirm(self) -> None:
        """ReadIndex with BATCHED-not-shared in-flight handling: a read
        may only ride a confirmation whose index sample happens after
        the read arrived — joining one already in flight could reuse an
        index recorded before a write this read must observe was acked.
        Reads therefore join the batch that has not FIRED yet; one
        batch runs at a time, so a 64-way burst still costs one index
        round-trip per batch."""
        await self._confirm_batched("follower_ri", self._ri_follower_runner)

    async def _ri_follower_runner(self):
        out = await self.forward_leader("Server.ReadIndex", {})
        await self.raft.wait_applied(int(out["index"]),
                                     timeout=ENQUEUE_LIMIT)

    async def _ri_leader_runner(self):
        # Lease short-circuit: the runner fires after every joiner in
        # its batch arrived, so commit_index sampled here covers every
        # write acked before any of them — and the live lease proves no
        # other leader could have acked more.  A follower ReadIndex
        # then costs one RPC and no barrier commit at all.
        idx = self.raft.lease_read_index()
        if idx is not None:
            from consul_tpu.utils.telemetry import metrics
            metrics.incr_counter(("consul", "read", "lease"))
            if self.raft.obs is not None:
                self.raft.obs.lease_observe(
                    self.raft.lease_remaining() * 1000.0,
                    self.raft.current_term)
            return idx
        return await self.raft.barrier(timeout=ENQUEUE_LIMIT) - 1

    async def _confirm_batched(self, key: str, runner):
        """Join the unfired confirmation batch for ``key`` (create one
        if none is forming); batches run serially.  The fired flag is
        the linearizability hinge: work for a batch (index sample /
        barrier append) only starts after the batch stops accepting
        joiners, so every joiner's arrival precedes it.

        The shield matters: ``b["fut"]`` is SHARED by every joiner, so
        a cancelled reader awaiting it bare would cancel the batch
        future itself and poison its batchmates (matching
        ``_leader_confirm``'s shield)."""
        b = self._confirm_batches.get(key)
        if b is None or b["fired"] or b["fut"].done():
            # fut done while unfired ⇒ the batch died before its work
            # started (runner cancelled awaiting its predecessor) and
            # the record is a tombstone: joining it would return the
            # canceller's error to every future caller on this key.
            b = self._confirm_batches[key] = {
                "fut": asyncio.get_event_loop().create_future(),
                "fired": False}
            task = asyncio.get_event_loop().create_task(
                self._run_confirm_batch(key, b, runner))
            self._confirm_tasks.add(task)
            task.add_done_callback(self._confirm_tasks.discard)
        return await asyncio.shield(b["fut"])

    async def _run_confirm_batch(self, key: str, b: dict, runner) -> None:
        from consul_tpu.rpc.pool import RPCError
        try:
            prev = self._confirm_prev.get(key)
            if prev is not None and not prev.done():
                try:
                    # Serialize batches; the previous batch's failure —
                    # including cancellation — is its own.  The shield
                    # is load-bearing: ``prev`` is the PREVIOUS batch's
                    # shared future, so awaiting it bare would let a
                    # cancelled runner (server stop) cancel prev itself
                    # and poison the predecessor's joiners.  Catching
                    # BaseException is equally load-bearing: a failed
                    # or cancelled prev must not unwind THIS runner
                    # before it fires, or an unfired batch's joiners
                    # wait forever.
                    await asyncio.shield(prev)
                except BaseException:  # noqa: E02,E03 — see comment above
                    if not prev.done():
                        # prev still pending ⇒ the CancelledError is
                        # OURS (shield kept prev alive): bail through
                        # the outer handler, which resolves b["fut"].
                        raise
            b["fired"] = True   # new arrivals form the next batch
            self._confirm_prev[key] = b["fut"]
            result = await runner()
            if not b["fut"].done():
                b["fut"].set_result(result)
        except BaseException as e:
            # Keep the exported exception contract: a remote not-leader
            # rejection (stringified over the wire) is a NotLeaderError
            # to callers, exactly as the local barrier path raises.
            if isinstance(e, (RPCError, RaftNotLeaderError)) and \
                    "leader" in str(e).lower():
                e = NotLeaderError(str(e))
            if not b["fut"].done():
                b["fut"].set_exception(e)
            if isinstance(e, asyncio.CancelledError):
                raise  # don't swallow task cancellation

    async def leader_read_index(self) -> int:
        """Server.ReadIndex target: leadership-verified read-safe index.
        Leader-only by construction — it goes straight to the local
        barrier (never the follower path), so a deposed node fails its
        one hop loudly instead of forwarding onward and returning a
        stale index, and routes never bounce between nodes that each
        think the other leads.

        BATCHED, not shared: joining a barrier already in flight when
        this RPC arrived could return an index sampled before a write
        the calling follower's read must observe (the share-in-flight
        argument only covers leader-LOCAL reads, where the ack implies
        the leader has applied the write).  The returned index excludes
        the barrier entry itself: the entries below it cover every
        previously-acked write (the barrier's own replication round
        also teaches followers that commit level), while making
        followers wait for the barrier ENTRY to apply stalled a
        heartbeat interval per batch (228/s at p50 279 ms vs 3741/s)."""
        if not self.raft.is_leader():
            raise NotLeaderError("not the leader")
        span = obs_trace.child_span("read-index")
        try:
            return await self._confirm_batched("leader_ri",
                                               self._ri_leader_runner)
        except RaftNotLeaderError as e:
            raise NotLeaderError(str(e)) from e
        finally:
            obs_trace.finish_span(span)

    def endpoint(self, name: str):
        return self._endpoints[name]

    def raft_peers(self) -> list:
        return list(self.raft.peers)

    def known_datacenters(self) -> list:
        """Sorted DC list (consul/catalog_endpoint.go:97-115); remote DCs
        come from the WAN route table."""
        return sorted({self.config.datacenter, *self.remote_dcs})

    # -- RPC mesh (consul/rpc.go + pool.go) --------------------------------

    async def attach_rpc(self, host: str = "127.0.0.1", port: int = 0,
                         tls_incoming=None, tls_outgoing=None) -> tuple:
        """Start the TCP RPC listener + pooled client and rebind the raft
        transport onto it (setupRPC, consul/server.go:246/414-431)."""
        from consul_tpu.rpc.pool import ConnPool, TCPTransport
        from consul_tpu.rpc.server import RPCServer
        self.pool = ConnPool(tls_wrap=tls_outgoing)
        self.rpc_server = RPCServer(self, tls_incoming=tls_incoming)
        await self.rpc_server.start(host, port)
        transport = TCPTransport(self.pool)
        transport.register(self.raft)
        self.raft.transport = transport
        self._tcp_transport = transport
        return self.rpc_server.addr

    def set_route(self, node_id: str, addr: str) -> None:
        self.route_table[node_id] = addr
        if getattr(self, "_tcp_transport", None) is not None:
            self._tcp_transport.set_addr(node_id, addr)

    def set_remote_dc(self, dc: str, addrs: List[str]) -> None:
        self.remote_dcs[dc] = list(addrs)

    async def forward_leader(self, method: str, body: Any) -> Any:
        """forwardLeader (consul/rpc.go:204-222)."""
        if self.pool is None:
            raise NotLeaderError("not the leader and no RPC mesh")
        addr = self.route_table.get(self.raft.leader_id or "")
        if not addr:
            raise NotLeaderError("No cluster leader")
        return await self.pool.rpc(addr, method, body,
                                   timeout=_forward_timeout(body))

    async def forward_dc(self, dc: str, method: str, body: Any) -> Any:
        """forwardDC to a random server there (consul/rpc.go:224-242)."""
        import random
        addrs = self.remote_dcs.get(dc)
        if not addrs or self.pool is None:
            raise ValueError(f"No path to datacenter: {dc}")
        return await self.pool.rpc(random.choice(addrs), method, body, dc=dc,
                                   timeout=_forward_timeout(body))

    async def global_rpc(self, method: str, body: Any) -> list:
        """One request to every known DC in parallel, responses merged
        (globalRPC + CompoundResponse, consul/rpc.go:247-276)."""
        import asyncio as _asyncio
        tasks = {self.config.datacenter:
                 self.rpc_server._dispatch({"Method": method, "Body": body})
                 if self.rpc_server else None}
        results = []
        if tasks[self.config.datacenter] is not None:
            local = await tasks[self.config.datacenter]
            if local.get("Error"):
                raise RuntimeError(local["Error"])
            results.append((self.config.datacenter, local.get("Body")))
        remote = [(dc, _asyncio.ensure_future(self.forward_dc(dc, method, body)))
                  for dc in self.remote_dcs]
        for dc, fut in remote:
            results.append((dc, await fut))
        return results

    async def keyring_operation_local(self, op: str, key: str = "") -> Dict:
        """This DC's slice of a keyring op (internal_endpoint.go:68+)."""
        if self.keyring is None:
            raise ValueError("keyring not configured "
                             "(gossip encryption disabled)")
        return self.keyring.operation(op, key, node=self.config.node_name)

    async def resolve_token(self, token: str):
        """ACL resolution (consul/acl.go:70-148).  None = ACLs disabled."""
        return await self.acl_resolver.resolve(token)

    async def rpc_get_remote_acl_policy(self, token_id: str, etag: str):
        """ACL.GetPolicy to the auth DC (consul/acl.go:104-121)."""
        from consul_tpu.structs.structs import ACLPolicyReply
        auth_dc = self.config.acl_datacenter
        if auth_dc not in self.remote_dcs or self.pool is None:
            raise ConnectionError("no route to ACL datacenter")
        body = {"acl_id": token_id, "etag": etag}
        out = await self.forward_dc(auth_dc, "ACL.GetPolicy", body)
        if out is None:
            return None
        return ACLPolicyReply.from_wire(out)

    async def filter_acl_service_nodes(self, token: str, nodes: list) -> list:
        from consul_tpu.server.acl import filter_service_nodes
        return filter_service_nodes(await self.resolve_token(token), nodes)

    def reset_session_timer(self, sid: str, session) -> None:
        """Leader-owned TTL timer (consul/session_ttl.go)."""
        self.leader_duties.reset_session_timer(sid, session)

    def clear_session_timer(self, sid: str) -> None:
        self.leader_duties.clear_session_timer(sid)

    async def fire_user_event(self, event) -> None:
        """Broadcast a user event (consul/internal_endpoint.go EventFire →
        serf.UserEvent).  A fire naming another datacenter forwards over
        the WAN and floods there (EventFireRequest.Datacenter).  With a
        gossip pool armed, the broadcaster floods the cluster and local
        delivery arrives via the pool's own event loopback; without one,
        deliver straight to the local sinks."""
        dc = getattr(event, "datacenter", "")
        if dc and dc != self.config.datacenter:
            await self.forward_dc(dc, "Internal.EventFire", event.to_wire())
            return
        if self.user_event_broadcaster is not None:
            self.user_event_broadcaster(event)
            return
        for sink in self.event_sinks:
            sink(event)

    def add_event_sink(self, sink) -> None:
        self.event_sinks.append(sink)

    def stats(self) -> Dict[str, Dict[str, str]]:
        """``consul info`` payload (consul/server.go:709-726)."""
        return {
            "consul": {
                "server": "true",
                "leader": str(self.is_leader()).lower(),
                "bootstrap": str(self.config.bootstrap).lower(),
                "known_datacenters": str(len(self.known_datacenters())),
            },
            "raft": self.raft.stats(),
            "runtime": {
                "uptime_s": str(int(time.monotonic() - self.start_time)),
            },
        }


class NotLeaderError(Exception):
    pass


def _forward_timeout(body: Any) -> float:
    """RPC budget for a forwarded request: plain calls get a tight
    timeout; a blocking query gets its own wait budget (max 600s,
    consul/rpc.go:29-41) plus grace for the server-side jitter.
    Options ride either nested under ``opts`` or flat (KeyRequest
    subclasses QueryOptions)."""
    if not isinstance(body, dict):
        return 30.0
    opts = body.get("opts") or body
    if opts.get("min_query_index"):
        wait = float(opts.get("max_query_time") or 300.0)
        return min(wait, 600.0) + 10.0
    return 30.0
