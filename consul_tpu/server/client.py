"""Client agent core: no Raft, no state store — RPC forwarding only.

Parity target: ``consul.Client`` (``consul/client.go:72``).  A client
agent participates in LAN gossip for membership/failure detection and
forwards every catalog/health/KV/session/ACL operation to a server
over the pooled RPC mesh.  Server discovery comes from the LAN pool
(consul/client.go:114-121 → nodeJoin/nodeFail handlers), and request
routing keeps **last-server affinity**: the most recently working
server is preferred until it fails, then another is picked at random
(consul/client.go:333-366).

The class mirrors the slice of :class:`~consul_tpu.server.server.Server`
surface the agent's HTTP/DNS/IPC/anti-entropy layers touch, with each
endpoint replaced by a remote proxy that speaks the same method names
the RPC mesh registers (rpc/server.py handlers), so an ``Agent`` can
hold either one without branching at every call site.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from consul_tpu.structs.structs import (
    ACL, CheckServiceNode, DirEntry, HealthCheck, Node, NodeService,
    QueryMeta, QueryOptions, ServiceNode, Session)


@dataclass
class ClientConfig:
    node_name: str = "node1"
    datacenter: str = "dc1"
    domain: str = "consul."
    extra: Dict[str, Any] = field(default_factory=dict)


class NoServersError(Exception):
    """No known consul servers (client.go "No known Consul servers")."""


def _meta_from_wire(d: Optional[Dict]) -> QueryMeta:
    d = d or {}
    return QueryMeta(index=d.get("index", 0),
                     known_leader=d.get("known_leader", True),
                     last_contact=d.get("last_contact", 0.0))


def _opts_to_wire(opts: QueryOptions) -> Dict:
    return {"token": opts.token, "datacenter": opts.datacenter,
            "min_query_index": opts.min_query_index,
            "max_query_time": opts.max_query_time,
            "allow_stale": opts.allow_stale,
            "require_consistent": opts.require_consistent}


# Blocking-query budget rule shared with the server's forward path.
from consul_tpu.server.server import _forward_timeout as _rpc_timeout  # noqa: E402


class ConsulClient:
    """The consul.Client role: LAN-member edge node that owns only a
    connection pool and a server routing table."""

    def __init__(self, config: Optional[ClientConfig] = None,
                 tls_outgoing=None) -> None:
        self.config = config or ClientConfig()
        self.start_time = time.monotonic()
        self.pool = None
        self._tls_outgoing = tls_outgoing
        # Server routing table, maintained by the agent's LAN event
        # handler exactly as for a server (set_route/route_table.pop).
        self.route_table: Dict[str, str] = {}
        self._preferred: Optional[str] = None  # last-server affinity
        self.keyring = None
        self.event_sinks: List[Any] = []
        self.user_event_broadcaster: Optional[Any] = None
        self.lan_members_fn: Optional[Any] = None
        self.remote_dcs: Dict[str, List[str]] = {}  # unused; IPC parity
        self.reconcile_ch = None

        self.status = _RemoteStatus(self)
        self.catalog = _RemoteCatalog(self)
        self.health = _RemoteHealth(self)
        self.kvs = _RemoteKVS(self)
        self.session = _RemoteSession(self)
        self.acl = _RemoteACL(self)
        self.internal = _RemoteInternal(self)

    # -- lifecycle (Server-compatible surface) ------------------------------

    async def start(self) -> None:
        from consul_tpu.rpc.pool import ConnPool
        self.pool = ConnPool(tls_wrap=self._tls_outgoing)

    async def stop(self) -> None:
        if self.pool is not None:
            await self.pool.close()

    def membership_notify(self, kind: str, member: Any) -> None:
        """Clients have no leader loop; membership events only feed the
        routing table (handled in the agent's LAN event hook)."""

    def is_leader(self) -> bool:
        return False

    @property
    def store(self):
        raise NoServersError(
            "client agents hold no local state store; use the endpoints")

    # -- server selection + RPC (client.go:333-366) -------------------------

    def set_route(self, node_id: str, addr: str) -> None:
        self.route_table[node_id] = addr

    def server_count(self) -> int:
        return len(self.route_table)

    def _pick(self) -> str:
        if self._preferred and self._preferred in self.route_table.values():
            return self._preferred
        if not self.route_table:
            raise NoServersError("No known Consul servers")
        return random.choice(list(self.route_table.values()))

    async def rpc(self, method: str, body: Any) -> Any:
        """One RPC to some server: try the affine server first; on a
        transport failure rotate through the rest before giving up.
        Application errors (RPCError with a server-side message) are
        NOT retried — the server answered."""
        from consul_tpu.rpc.pool import RPCError
        timeout = _rpc_timeout(body)
        last_exc: Optional[Exception] = None
        tried: set = set()
        for _ in range(max(1, len(self.route_table))):
            try:
                addr = self._pick()
            except NoServersError:
                break
            if addr in tried:
                remaining = [a for a in self.route_table.values()
                             if a not in tried]
                if not remaining:
                    break
                addr = random.choice(remaining)
            tried.add(addr)
            try:
                out = await self.pool.rpc(addr, method, body, timeout=timeout)
                self._preferred = addr
                return out
            except RPCError:
                self._preferred = addr  # server is healthy; error is ours
                raise
            except Exception as e:  # transport/mux/timeout: rotate
                last_exc = e
                if self._preferred == addr:
                    self._preferred = None
                continue
        if last_exc is not None:
            raise NoServersError(f"rpc failed on all servers: {last_exc}")
        raise NoServersError("No known Consul servers")

    # -- event plane --------------------------------------------------------

    async def fire_user_event(self, event) -> None:
        """Flood via our own LAN pool when armed (clients gossip too);
        fall back to asking a server (Internal.EventFire)."""
        if self.user_event_broadcaster is not None:
            self.user_event_broadcaster(event)
            return
        await self.rpc("Internal.EventFire", event.to_wire())

    def add_event_sink(self, sink) -> None:
        self.event_sinks.append(sink)

    # -- keyring (fanned out via a server's globalRPC) ----------------------

    async def keyring_operation_local(self, op: str, key: str = "") -> Dict:
        if self.keyring is None:
            raise ValueError("keyring not configured "
                             "(gossip encryption disabled)")
        return self.keyring.operation(op, key, node=self.config.node_name)

    # -- observability ------------------------------------------------------

    def stats(self) -> Dict[str, Dict[str, str]]:
        """``consul info`` payload for a client (consul/client.go Stats)."""
        return {
            "consul": {
                "server": "false",
                "known_servers": str(len(self.route_table)),
            },
            "runtime": {
                "uptime_s": str(int(time.monotonic() - self.start_time)),
            },
        }

    def known_datacenters(self) -> List[str]:
        return [self.config.datacenter]

    def leader_addr(self) -> str:
        return ""

    def raft_peers(self) -> List[str]:
        return []

    async def resolve_token(self, token: str):
        """ACL enforcement happens on the servers for every forwarded
        request; the client does not resolve tokens locally (the
        reference's client has no ACL cache either)."""
        return None


# -- remote endpoint proxies -------------------------------------------------
# Each mirrors the in-process endpoint signatures (server/endpoints.py) and
# speaks the registered RPC method names (rpc/server.py _build_handlers).


class _Remote:
    def __init__(self, client: ConsulClient) -> None:
        self.c = client


class _RemoteStatus(_Remote):
    async def ping(self) -> bool:
        return bool(await self.c.rpc("Status.Ping", {}))

    async def leader(self) -> str:
        return await self.c.rpc("Status.Leader", {})

    async def peers(self) -> List[str]:
        return await self.c.rpc("Status.Peers", {})

    async def lease(self) -> dict:
        # Lease state of whichever server the client is affined to —
        # the client itself holds no raft state.
        return await self.c.rpc("Status.Lease", {})


class _RemoteCatalog(_Remote):
    async def register(self, args) -> None:
        await self.c.rpc("Catalog.Register", args.to_wire())

    async def deregister(self, args) -> None:
        await self.c.rpc("Catalog.Deregister", args.to_wire())

    async def list_datacenters(self) -> List[str]:
        return await self.c.rpc("Catalog.ListDatacenters", {})

    async def list_nodes(self, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Catalog.ListNodes",
                             {"opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [Node.from_wire(n)
                                      for n in r.get("data") or []]

    async def list_services(self, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Catalog.ListServices",
                             {"opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), dict(r.get("data") or {})

    async def service_nodes(self, service: str, opts: QueryOptions,
                            tag: str = "") -> tuple:
        r = await self.c.rpc("Catalog.ServiceNodes",
                             {"service": service, "tag": tag,
                              "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [ServiceNode.from_wire(n)
                                      for n in r.get("data") or []]

    async def node_services(self, node: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Catalog.NodeServices",
                             {"node": node, "opts": _opts_to_wire(opts)})
        data = r.get("data")
        if data is None:
            return _meta_from_wire(r.get("meta")), None
        return _meta_from_wire(r.get("meta")), {
            sid: NodeService.from_wire(s) for sid, s in data.items()}


class _RemoteHealth(_Remote):
    async def checks_in_state(self, state: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Health.ChecksInState",
                             {"state": state, "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [HealthCheck.from_wire(x)
                                      for x in r.get("data") or []]

    async def node_checks(self, node: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Health.NodeChecks",
                             {"node": node, "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [HealthCheck.from_wire(x)
                                      for x in r.get("data") or []]

    async def service_checks(self, service: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Health.ServiceChecks",
                             {"service": service, "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [HealthCheck.from_wire(x)
                                      for x in r.get("data") or []]

    async def service_nodes(self, service: str, opts: QueryOptions,
                            tag: str = "",
                            passing_only: bool = False) -> tuple:
        r = await self.c.rpc("Health.ServiceNodes",
                             {"service": service, "tag": tag,
                              "passing": passing_only,
                              "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [CheckServiceNode.from_wire(x)
                                      for x in r.get("data") or []]


class _RemoteKVS(_Remote):
    async def apply(self, args) -> bool:
        return bool(await self.c.rpc("KVS.Apply", args.to_wire()))

    async def get(self, args) -> tuple:
        r = await self.c.rpc("KVS.Get", args.to_wire())
        return _meta_from_wire(r.get("meta")), [DirEntry.from_wire(e)
                                      for e in r.get("data") or []]

    async def list(self, args) -> tuple:
        r = await self.c.rpc("KVS.List", args.to_wire())
        return _meta_from_wire(r.get("meta")), [DirEntry.from_wire(e)
                                      for e in r.get("data") or []]

    async def list_keys(self, args) -> tuple:
        r = await self.c.rpc("KVS.ListKeys", args.to_wire())
        return _meta_from_wire(r.get("meta")), list(r.get("data") or [])


class _RemoteSession(_Remote):
    async def apply(self, args) -> str:
        return await self.c.rpc("Session.Apply", args.to_wire())

    async def get(self, sid: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Session.Get",
                             {"id": sid, "opts": _opts_to_wire(opts)})
        data = r.get("data")
        return _meta_from_wire(r.get("meta")), (Session.from_wire(data)
                                      if data is not None else None)

    async def list(self, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Session.List", {"opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [Session.from_wire(s)
                                      for s in r.get("data") or []]

    async def node_sessions(self, node: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Session.NodeSessions",
                             {"node": node, "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [Session.from_wire(s)
                                      for s in r.get("data") or []]

    async def renew(self, sid: str) -> Optional[Session]:
        data = await self.c.rpc("Session.Renew", {"id": sid})
        return Session.from_wire(data) if data is not None else None


class _RemoteACL(_Remote):
    async def apply(self, args) -> str:
        return await self.c.rpc("ACL.Apply", args.to_wire())

    async def get(self, acl_id: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("ACL.Get",
                             {"id": acl_id, "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [ACL.from_wire(a)
                                      for a in r.get("data") or []]

    async def list(self, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("ACL.List", {"opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [ACL.from_wire(a)
                                      for a in r.get("data") or []]


def _dump_row(d: Dict) -> Dict:
    """Rehydrate one node-dump row (state/store.py _dump_one) so UI
    summarizers can use attribute access on services/checks."""
    return {
        "node": d.get("node", ""),
        "address": d.get("address", ""),
        "services": [NodeService.from_wire(s) if isinstance(s, dict) else s
                     for s in d.get("services") or []],
        "checks": [HealthCheck.from_wire(c) if isinstance(c, dict) else c
                   for c in d.get("checks") or []],
    }


class _RemoteInternal(_Remote):
    async def node_info(self, node: str, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Internal.NodeInfo",
                             {"node": node, "opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [_dump_row(d)
                                      for d in r.get("data") or []]

    async def node_dump(self, opts: QueryOptions) -> tuple:
        r = await self.c.rpc("Internal.NodeDump",
                             {"opts": _opts_to_wire(opts)})
        return _meta_from_wire(r.get("meta")), [_dump_row(d)
                                      for d in r.get("data") or []]
