"""Server RPC endpoints: Status, Catalog, Health, KVS, Session, Internal.

Parity targets (reference, all under ``consul/``):
``status_endpoint.go`` (30 LoC), ``catalog_endpoint.go`` (208),
``health_endpoint.go`` (143), ``kvs_endpoint.go`` (212),
``session_endpoint.go`` (190), ``internal_endpoint.go`` (141).

All share the pattern: validate → (ACL resolve) → ``raft_apply`` for
writes, ``blocking_query`` + store read for reads.  DC/leader forwarding
(the ``forward()`` prologue) lands with the RPC mesh; single-node mode
forwards to nobody.  ACL enforcement is wired through
``server.resolve_token`` once the ACL engine lands.
"""

from __future__ import annotations

import re
import uuid
from typing import Any, List, Optional

from consul_tpu.server.blocking import blocking_query
from consul_tpu.structs.structs import (
    CONSUL_SERVICE_NAME,
    DeregisterRequest,
    DirEntry,
    HEALTH_ANY,
    KeyListRequest,
    KeyRequest,
    KVSOp,
    KVSRequest,
    MessageType,
    QueryMeta,
    QueryOptions,
    RegisterRequest,
    SESSION_BEHAVIOR_DELETE,
    SESSION_BEHAVIOR_RELEASE,
    SESSION_TTL_MAX,
    Session,
    SessionOp,
    SessionRequest,
    VALID_HEALTH_STATES,
)

_UNIT_S = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(s: str) -> float:
    """Go-style duration strings ('10s', '1.5m', '90ms') to seconds."""
    if isinstance(s, (int, float)):
        return float(s)
    total, pos = 0.0, 0
    matched = False
    for m in re.finditer(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)", s):
        if m.start() != pos:
            raise ValueError(f"invalid duration '{s}'")
        total += float(m.group(1)) * _UNIT_S[m.group(2)]
        pos = m.end()
        matched = True
    if not matched or pos != len(s):
        raise ValueError(f"invalid duration '{s}'")
    return total


class EndpointError(Exception):
    """Validation failure surfaced as HTTP 4xx/5xx by the edge layer."""


class _Endpoint:
    def __init__(self, srv) -> None:
        self.srv = srv

    def _set_meta(self, meta: QueryMeta) -> None:
        """setQueryMeta (consul/rpc.go:401-409)."""
        if self.srv.is_leader():
            meta.last_contact = 0.0
            meta.known_leader = True
        else:
            import time as _t
            meta.known_leader = bool(self.srv.leader_addr())
            # Staleness: seconds since this server last heard from a
            # leader — drives the DNS max_stale re-query and clients'
            # staleness budgeting (rpc.go:404-406).
            contact = getattr(self.srv.raft, "last_leader_contact", None)
            if contact is not None:
                meta.last_contact = max(0.0, _t.monotonic() - contact)

    async def _blocking(self, opts: QueryOptions, meta: QueryMeta, run,
                        tables=(), kv_prefix=None) -> None:
        if opts.require_consistent:
            await self.srv.consistent_read_barrier()
        await blocking_query(self.srv.store, opts, meta, run,
                             tables=tables, kv_prefix=kv_prefix,
                             set_meta=self._set_meta)


class Status(_Endpoint):
    """No forwarding — answers about the local raft state
    (status_endpoint.go:9-25)."""

    async def ping(self) -> bool:
        return True

    async def leader(self) -> str:
        return self.srv.leader_addr()

    async def peers(self) -> List[str]:
        return self.srv.raft_peers()

    async def lease(self) -> dict:
        """Leader-lease state of THIS server (no forwarding): drives
        read-replica routing — a worker or follower seeing
        ``valid: true`` knows consistent reads here are barrier-free
        at ``read_index`` (served locally once ``applied_index``
        catches up via wait_applied)."""
        return self.srv.lease_state()


class Catalog(_Endpoint):
    async def register(self, args: RegisterRequest) -> None:
        """catalog_endpoint.go:18-75."""
        if not args.node or not args.address:
            raise EndpointError("Must provide node and address")
        if args.service is not None:
            if not args.service.id and args.service.service:
                args.service.id = args.service.service
            if args.service.id and not args.service.service:
                raise EndpointError("Must provide service name with ID")
            if args.service.service != CONSUL_SERVICE_NAME:
                acl = await self.srv.resolve_token(args.token)
                if acl is not None and not acl.service_write(args.service.service):
                    raise PermissionError("Permission denied")
        if args.check is not None:
            args.checks.append(args.check)
            args.check = None
        for check in args.checks:
            if not check.check_id and check.name:
                check.check_id = check.name
            if not check.node:
                check.node = args.node
            if check.status and check.status not in VALID_HEALTH_STATES:
                raise EndpointError(f"Invalid check status: '{check.status}'")
        await self.srv.raft_apply(MessageType.REGISTER, args)

    async def deregister(self, args: DeregisterRequest) -> None:
        if not args.node:
            raise EndpointError("Must provide node")
        await self.srv.raft_apply(MessageType.DEREGISTER, args)

    async def list_datacenters(self) -> List[str]:
        return self.srv.known_datacenters()

    async def list_nodes(self, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), []

        async def run():
            idx, nodes = self.srv.store.nodes()
            meta.index = idx
            out[:] = nodes

        await self._blocking(opts, meta, run, tables=self.srv.store.query_tables("Nodes"))
        return meta, out

    async def list_services(self, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), {}
        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_services_map
            idx, services = self.srv.store.services()
            meta.index = idx
            out.clear()
            out.update(filter_services_map(acl, services))

        await self._blocking(opts, meta, run, tables=self.srv.store.query_tables("Services"))
        return meta, out

    async def service_nodes(self, service: str, opts: QueryOptions, tag: str = "") -> tuple:
        if not service:
            raise EndpointError("Must provide service name")
        meta, out = QueryMeta(), []

        async def run():
            idx, nodes = self.srv.store.service_nodes(service, tag)
            meta.index = idx
            out[:] = await self.srv.filter_acl_service_nodes(opts.token, nodes)

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("ServiceNodes"))
        return meta, out

    async def node_services(self, node: str, opts: QueryOptions) -> tuple:
        if not node:
            raise EndpointError("Must provide node")
        meta = QueryMeta()
        holder: List[Any] = [None]

        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_node_services
            idx, services = self.srv.store.node_services(node)
            meta.index = idx
            holder[0] = filter_node_services(acl, services)

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("NodeServices"))
        return meta, holder[0]


class Health(_Endpoint):
    """health_endpoint.go:15-143."""

    async def checks_in_state(self, state: str, opts: QueryOptions) -> tuple:
        if state not in (HEALTH_ANY,) + VALID_HEALTH_STATES:
            raise EndpointError(f"Invalid state: '{state}'")
        meta, out = QueryMeta(), []
        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_health_checks
            idx, checks = self.srv.store.checks_in_state(state)
            meta.index = idx
            out[:] = filter_health_checks(acl, checks)

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("ChecksInState"))
        return meta, out

    async def node_checks(self, node: str, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), []

        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_health_checks
            idx, checks = self.srv.store.node_checks(node)
            meta.index = idx
            out[:] = filter_health_checks(acl, checks)

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("NodeChecks"))
        return meta, out

    async def service_checks(self, service: str, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), []

        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_health_checks
            idx, checks = self.srv.store.service_checks(service)
            meta.index = idx
            out[:] = filter_health_checks(acl, checks)

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("ServiceChecks"))
        return meta, out

    async def service_nodes(self, service: str, opts: QueryOptions, tag: str = "",
                            passing_only: bool = False) -> tuple:
        """CheckServiceNodes join; ?passing filters at the server
        (health_endpoint.go:75-143)."""
        if not service:
            raise EndpointError("Must provide service name")
        meta, out = QueryMeta(), []
        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_check_service_nodes
            idx, csns = self.srv.store.check_service_nodes(service, tag)
            csns = filter_check_service_nodes(acl, csns)
            meta.index = idx
            if passing_only:
                from consul_tpu.structs.structs import HEALTH_PASSING
                csns = [c for c in csns
                        if all(ch.status == HEALTH_PASSING for ch in c.checks)]
            out[:] = csns

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("CheckServiceNodes"))
        return meta, out


class KVS(_Endpoint):
    """kvs_endpoint.go — Apply with lock-delay enforcement, blocking reads."""

    async def apply(self, args: KVSRequest) -> bool:
        d = args.dir_ent
        if d is None or not d.key:
            raise EndpointError("Must provide key")
        acl = await self.srv.resolve_token(args.token)
        if acl is not None:
            # Recursive delete needs write over the whole subtree
            # (kvs_endpoint.go: KeyWritePrefix for KVSDeleteTree).
            if args.op == KVSOp.DELETE_TREE.value:
                if not acl.key_write_prefix(d.key):
                    raise PermissionError("Permission denied")
            elif not acl.key_write(d.key):
                raise PermissionError("Permission denied")

        # Lock-delay must be checked on the leader's wall clock, pre-commit
        # (kvs_endpoint.go:46-61): a lock attempt within the delay window
        # after a session invalidation is refused without a Raft write.
        if args.op == KVSOp.LOCK.value:
            if self.srv.store.kvs_lock_delay(d.key) > 0:
                return False

        resp = await self.srv.raft_apply(MessageType.KVS, args)
        return bool(resp) if isinstance(resp, bool) else True

    async def get(self, args: KeyRequest) -> tuple:
        acl = await self.srv.resolve_token(args.token)
        if acl is not None and not acl.key_read(args.key):
            raise PermissionError("Permission denied")
        meta = QueryMeta()
        out: List[DirEntry] = []

        async def run():
            idx, ent = self.srv.store.kvs_get(args.key)
            meta.index = ent.modify_index if ent else idx
            out[:] = [ent] if ent is not None else []

        await self._blocking(args, meta, run, kv_prefix=args.key)
        return meta, out

    async def list(self, args: KeyListRequest) -> tuple:
        acl = await self.srv.resolve_token(args.token)
        meta = QueryMeta()
        out: List[DirEntry] = []

        async def run():
            from consul_tpu.server.acl import filter_dir_entries
            tomb_idx, idx, ents = self.srv.store.kvs_list(args.prefix)
            ents = filter_dir_entries(acl, ents)
            # Index semantics (consul/kvs_endpoint.go:116-142): use the max
            # entry index if non-zero, else the tombstone index, else table.
            ent_max = max((e.modify_index for e in ents), default=0)
            meta.index = max(ent_max, tomb_idx) or idx
            out[:] = ents

        await self._blocking(args, meta, run, kv_prefix=args.prefix)
        return meta, out

    async def list_keys(self, args: KeyListRequest) -> tuple:
        acl = await self.srv.resolve_token(args.token)
        meta = QueryMeta()
        out: List[str] = []

        async def run():
            from consul_tpu.server.acl import filter_keys
            idx, keys = self.srv.store.kvs_list_keys(args.prefix, args.separator)
            keys = filter_keys(acl, keys)
            meta.index = idx
            out[:] = keys

        await self._blocking(args, meta, run, kv_prefix=args.prefix)
        return meta, out


class SessionEndpoint(_Endpoint):
    """session_endpoint.go — UUID generation on the leader (NEVER in the
    FSM: once in the log, the update must be deterministic)."""

    async def apply(self, args: SessionRequest) -> str:
        session = args.session
        if args.op == SessionOp.DESTROY.value and not session.id:
            raise EndpointError("Must provide ID")
        if args.op == SessionOp.CREATE.value:
            if not session.node:
                raise EndpointError("Must provide Node")
            if not session.behavior:
                session.behavior = SESSION_BEHAVIOR_RELEASE
            elif session.behavior not in (SESSION_BEHAVIOR_RELEASE,
                                          SESSION_BEHAVIOR_DELETE):
                raise EndpointError(f"Invalid Behavior setting '{session.behavior}'")
            if session.ttl:
                try:
                    ttl = parse_duration(session.ttl)
                except ValueError as e:
                    raise EndpointError(f"Session TTL '{session.ttl}' invalid: {e}")
                if ttl != 0 and not (
                        self.srv.config.session_ttl_min <= ttl <= SESSION_TTL_MAX):
                    raise EndpointError(
                        f"Invalid Session TTL '{session.ttl}', must be between "
                        f"[{self.srv.config.session_ttl_min}s={SESSION_TTL_MAX}s]")
            # Generate a unique ID outside the replicated path
            # (session_endpoint.go:60-74).
            while True:
                session.id = str(uuid.uuid4())
                _, existing = self.srv.store.session_get(session.id)
                if existing is None:
                    break

        resp = await self.srv.raft_apply(MessageType.SESSION, args)

        if args.op == SessionOp.CREATE.value and session.ttl:
            self.srv.reset_session_timer(session.id, session)
        elif args.op == SessionOp.DESTROY.value:
            self.srv.clear_session_timer(session.id)
        return resp if isinstance(resp, str) else session.id

    async def get(self, sid: str, opts: QueryOptions) -> tuple:
        meta = QueryMeta()
        holder: List[Optional[Session]] = [None]

        async def run():
            idx, sess = self.srv.store.session_get(sid)
            meta.index = idx
            holder[0] = sess

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("SessionGet"))
        return meta, holder[0]

    async def list(self, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), []

        async def run():
            idx, sessions = self.srv.store.session_list()
            meta.index = idx
            out[:] = sessions

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("SessionList"))
        return meta, out

    async def node_sessions(self, node: str, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), []

        async def run():
            idx, sessions = self.srv.store.node_sessions(node)
            meta.index = idx
            out[:] = sessions

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("NodeSessions"))
        return meta, out

    async def renew(self, sid: str) -> Optional[Session]:
        """Reset the TTL timer (session_endpoint.go Renew + session_ttl.go)."""
        _, session = self.srv.store.session_get(sid)
        if session is not None and session.ttl:
            self.srv.reset_session_timer(sid, session)
        return session


class ACLEndpoint(_Endpoint):
    """acl_endpoint.go (203 LoC) — Apply is only served in the ACL
    datacenter; GetPolicy serves other DCs' caches with ETag + TTL."""

    def _check_auth_dc(self) -> None:
        cfg = self.srv.config
        if not cfg.acl_datacenter:
            raise EndpointError("ACL support disabled")
        if cfg.acl_datacenter != cfg.datacenter:
            # The RPC mesh forwards to the auth DC before this point;
            # reaching here means no route exists.
            raise EndpointError(
                f"ACL modifications must route to datacenter '{cfg.acl_datacenter}'")

    async def apply(self, args) -> str:
        """Set/Delete a token (acl_endpoint.go:18-103).  The token id is
        generated here on the leader, NEVER in the FSM."""
        from consul_tpu.acl.policy import PolicyError, parse_policy
        from consul_tpu.structs.structs import (
            ACL_ANONYMOUS_ID, ACL_TYPE_CLIENT, ACL_TYPE_MANAGEMENT, ACLOp)
        self._check_auth_dc()
        acl = await self.srv.resolve_token(args.token)
        if acl is not None and not acl.acl_modify():
            raise PermissionError("Permission denied")

        a = args.acl
        if args.op == ACLOp.SET.value:
            if a.type not in (ACL_TYPE_CLIENT, ACL_TYPE_MANAGEMENT):
                raise EndpointError(f"Invalid ACL Type: '{a.type}'")
            try:
                parse_policy(a.rules)
            except PolicyError as e:
                raise EndpointError(f"ACL rule compilation failed: {e}")
            if not a.id:
                while True:
                    a.id = str(uuid.uuid4())
                    _, existing = self.srv.store.acl_get(a.id)
                    if existing is None:
                        break
        else:
            if not a.id:
                raise EndpointError("Must provide ID")
            if a.id == ACL_ANONYMOUS_ID:
                raise EndpointError("Cannot delete anonymous token")

        resp = await self.srv.raft_apply(MessageType.ACL, args)
        self.srv.acl_resolver.cache.invalidate(a.id)
        return resp if isinstance(resp, str) else a.id

    async def get(self, acl_id: str, opts: QueryOptions) -> tuple:
        meta = QueryMeta()
        out: List[Any] = []

        async def run():
            idx, acl = self.srv.store.acl_get(acl_id)
            meta.index = idx
            out[:] = [acl] if acl is not None else []

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("ACLGet"))
        return meta, out

    async def get_policy(self, args):
        """Serve a compiled policy to another DC's cache
        (acl_endpoint.go:141+)."""
        if self.srv.config.acl_datacenter != self.srv.config.datacenter:
            raise EndpointError("ACL replication must query the ACL datacenter")
        return self.srv.acl_resolver.policy_reply(args.acl_id, args.etag)

    async def list(self, opts: QueryOptions) -> tuple:
        acl = await self.srv.resolve_token(opts.token)
        if acl is not None and not acl.acl_list():
            raise PermissionError("Permission denied")
        meta, out = QueryMeta(), []

        async def run():
            idx, acls = self.srv.store.acl_list()
            meta.index = idx
            out[:] = acls

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("ACLList"))
        return meta, out


class Internal(_Endpoint):
    """internal_endpoint.go — UI support queries + event fire."""

    async def node_info(self, node: str, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), []
        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_node_dump
            idx, dump = self.srv.store.node_info(node)
            meta.index = idx
            out[:] = filter_node_dump(acl, dump)

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("NodeInfo"))
        return meta, out

    async def node_dump(self, opts: QueryOptions) -> tuple:
        meta, out = QueryMeta(), []
        acl = await self.srv.resolve_token(opts.token)

        async def run():
            from consul_tpu.server.acl import filter_node_dump
            idx, dump = self.srv.store.node_dump()
            meta.index = idx
            out[:] = filter_node_dump(acl, dump)

        await self._blocking(opts, meta, run,
                             tables=self.srv.store.query_tables("NodeDump"))
        return meta, out

    async def event_fire(self, event) -> None:
        """Internal.EventFire — broadcast a user event.  Routed into the
        gossip plane once the event pipeline lands."""
        await self.srv.fire_user_event(event)
