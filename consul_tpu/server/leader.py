"""Leader-only duties: establishment barrier, session TTLs, tombstone GC.

Parity target: ``consul/leader.go`` (monitorLeadership/leaderLoop,
establishLeadership at leader.go:60-140) + ``consul/session_ttl.go`` +
the tombstone reap timer (leader.go:553-566).  The reference runs a
goroutine per concern; here one LeaderDuties object owns asyncio timer
handles, started when the local Raft node gains leadership and torn
down when it loses it.  Serf→catalog reconciliation plugs in here once
the gossip event pipeline lands (leader.go:242-339).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from consul_tpu.structs.structs import (
    SESSION_TTL_MULTIPLIER, Session, SessionOp, SessionRequest, MessageType,
    TombstoneRequest)


def _parse_ttl(s: str) -> float:
    from consul_tpu.server.endpoints import parse_duration
    try:
        return parse_duration(s)
    except (ValueError, TypeError):
        return 0.0


class LeaderDuties:
    def __init__(self, server) -> None:
        self.srv = server
        self._session_timers: Dict[str, asyncio.TimerHandle] = {}
        self._tombstone_task: Optional[asyncio.Task] = None
        self._establish_task: Optional[asyncio.Task] = None
        self._reconcile_task: Optional[asyncio.Task] = None
        # Armed by the batched _reconcile_loop; bundle + chaos campaign
        # read its stats surface (agent/reconcile.py).
        self.reconciler = None
        # revoke() is sync (called from the role-change callback), so
        # cancelled tasks park here until stop() can await them out
        self._cancelled: List[asyncio.Task] = []
        self._active = False

    # -- leadership transitions (monitorLeadership, leader.go:29-58) -------

    def on_leader_change(self, is_leader: bool) -> None:
        if is_leader:
            self._establish_task = asyncio.get_event_loop().create_task(
                self._establish())
        else:
            self.revoke()

    async def _establish(self) -> None:
        """establishLeadership (leader.go:60-140): barrier so the local FSM
        is caught up, then arm leader-owned timers."""
        try:
            await self.srv.raft.barrier()
        except Exception:
            return
        if not self.srv.raft.is_leader():
            return
        self._active = True
        self.srv.gc.set_enabled(True, time.monotonic())
        await self._bootstrap_acls()
        self.initialize_session_timers()
        self._tombstone_task = asyncio.get_event_loop().create_task(
            self._tombstone_loop())
        self._reconcile_task = asyncio.get_event_loop().create_task(
            self._reconcile_loop())

    async def _bootstrap_acls(self) -> None:
        """Seed the anonymous token and the configured master token in the
        auth DC (initializeACL, leader.go:173-236)."""
        cfg = self.srv.config
        if not cfg.acl_datacenter or cfg.acl_datacenter != cfg.datacenter:
            return
        from consul_tpu.structs.structs import (
            ACL, ACL_ANONYMOUS_ID, ACL_TYPE_CLIENT, ACL_TYPE_MANAGEMENT,
            ACLOp, ACLRequest)
        _, anon = self.srv.store.acl_get(ACL_ANONYMOUS_ID)
        if anon is None:
            await self.srv.raft_apply(MessageType.ACL, ACLRequest(
                op=ACLOp.SET.value,
                acl=ACL(id=ACL_ANONYMOUS_ID, name="Anonymous Token",
                        type=ACL_TYPE_CLIENT)))
        if cfg.acl_master_token:
            _, master = self.srv.store.acl_get(cfg.acl_master_token)
            if master is None:
                await self.srv.raft_apply(MessageType.ACL, ACLRequest(
                    op=ACLOp.SET.value,
                    acl=ACL(id=cfg.acl_master_token, name="Master Token",
                            type=ACL_TYPE_MANAGEMENT)))

    def revoke(self) -> None:
        """revokeLeadership: drop timers; the next leader re-arms from the
        replicated state (leader.go:139-152)."""
        self._active = False
        self.srv.gc.set_enabled(False, time.monotonic())
        self.clear_all_session_timers()
        for attr in ("_tombstone_task", "_reconcile_task",
                     "_establish_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                self._cancelled.append(task)
                setattr(self, attr, None)

    async def drain(self) -> None:
        """Await every task revoke() cancelled.  cancel() only
        schedules the CancelledError; without this, a loop that closes
        right after step-down logs "Task was destroyed but it is
        pending!" for each leader loop."""
        tasks, self._cancelled = self._cancelled, []
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    # -- session TTLs (consul/session_ttl.go) ------------------------------

    def initialize_session_timers(self) -> None:
        """Re-arm a timer per TTL session after failover
        (initializeSessionTimers, session_ttl.go:14-33)."""
        _, sessions = self.srv.store.session_list()
        for session in sessions:
            if session.ttl:
                self.reset_session_timer(session.id, session)

    def reset_session_timer(self, sid: str, session: Session) -> None:
        if not self._active:
            return
        ttl = _parse_ttl(session.ttl)
        if ttl <= 0:
            return
        self.clear_session_timer(sid)
        # 2x grace: lenient on the contract, covers leader failover gaps
        # (session_ttl.go:11, SESSION_TTL_MULTIPLIER).
        delay = ttl * SESSION_TTL_MULTIPLIER
        loop = asyncio.get_event_loop()
        self._session_timers[sid] = loop.call_later(
            delay, lambda: loop.create_task(self._invalidate_session(sid)))
        self._update_ttl_gauge()

    def clear_session_timer(self, sid: str) -> None:
        h = self._session_timers.pop(sid, None)
        if h is not None:
            h.cancel()
        self._update_ttl_gauge()

    def _update_ttl_gauge(self) -> None:
        """Active-timer gauge (the updateSessionTimers loop,
        session_ttl.go:150-163, folded into each mutation)."""
        from consul_tpu.utils.telemetry import metrics
        metrics.set_gauge(("consul", "session_ttl", "active"),
                          float(len(self._session_timers)))

    def clear_all_session_timers(self) -> None:
        for h in self._session_timers.values():
            h.cancel()
        self._session_timers.clear()
        self._update_ttl_gauge()

    async def _invalidate_session(self, sid: str) -> None:
        """TTL expired → destroy through Raft (invalidateSession,
        session_ttl.go:120-146)."""
        self._session_timers.pop(sid, None)
        self._update_ttl_gauge()
        if not self._active:
            return
        req = SessionRequest(op=SessionOp.DESTROY.value,
                            session=Session(id=sid))
        try:
            await self.srv.raft_apply(MessageType.SESSION, req)
        except Exception:  # noqa: E02 — lost leadership mid-destroy
            pass  # next leader re-arms the timer

    def session_timer_count(self) -> int:
        return len(self._session_timers)

    # -- serf→catalog reconciliation (leader.go:242-501) -------------------

    async def _reconcile_loop(self) -> None:
        """Drain gossip member events; on idle, run the periodic full
        reconcile (leaderLoop's select over reconcileCh + the
        ReconcileInterval ticker, leader.go:104-117).

        Batched by default (PR 18): one drain cadence's worth of member
        transitions coalesces into a single BATCH raft envelope
        (agent/reconcile.py) so append→quorum is paid once per cadence.
        ``extra["reconcile_batched"] = False`` keeps the per-member
        sequential loop — the A side of tools/bench_fuse.py."""
        extra = self.srv.config.extra
        if not extra.get("reconcile_batched", True):
            await self._reconcile_loop_sequential()
            return
        from consul_tpu.agent.reconcile import (
            DEFAULT_BATCH_MAX, DEFAULT_LINGER_S, Reconciler)
        interval = self.srv.config.reconcile_interval
        batch_max = int(extra.get("reconcile_batch_max", 0)
                        or DEFAULT_BATCH_MAX)
        linger = float(extra.get("reconcile_linger_s", DEFAULT_LINGER_S))
        rec = Reconciler(self.srv, batch_max=batch_max)
        self.reconciler = rec  # introspection: bundle + chaos detect
        try:
            while self._active:
                ch = self.srv.reconcile_ch
                if ch is None:
                    await asyncio.sleep(interval)
                    continue
                try:
                    _kind, member = await asyncio.wait_for(
                        ch.get(), timeout=interval)
                except asyncio.TimeoutError:
                    await self._reconcile_full()
                    continue
                rec.note(member)
                # Greedy drain + linger: a gossip evbatch lands as a
                # burst of put_nowait's; collect the whole burst (and
                # any stragglers inside the cadence-coupled linger
                # window) before paying the one append.
                deadline = time.monotonic() + linger
                while len(rec) < rec.batch_max:
                    try:
                        _k, m = ch.get_nowait()
                    except asyncio.QueueEmpty:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        try:
                            _k, m = await asyncio.wait_for(
                                ch.get(), timeout=remaining)
                        except asyncio.TimeoutError:
                            break
                    rec.note(m)
                try:
                    await rec.flush()
                except Exception:  # noqa: E02 — lost leadership mid-apply
                    pass  # next leader repairs
        except asyncio.CancelledError:
            pass

    async def _reconcile_loop_sequential(self) -> None:
        """The pre-batching loop: one catalog write per member event.
        Retained as the bench baseline and the escape hatch."""
        interval = self.srv.config.reconcile_interval
        try:
            while self._active:
                ch = self.srv.reconcile_ch
                if ch is None:
                    await asyncio.sleep(interval)
                    continue
                try:
                    _kind, member = await asyncio.wait_for(
                        ch.get(), timeout=interval)
                except asyncio.TimeoutError:
                    await self._reconcile_full()
                    continue
                try:
                    await self._reconcile_member(member)
                except Exception:  # noqa: E02 — lost leadership mid-apply
                    pass  # next leader repairs
        except asyncio.CancelledError:
            pass

    async def _reconcile_full(self) -> None:
        """Full pass (reconcile, leader.go:242-260): every pool member is
        re-checked, and catalog nodes that vanished from the pool while a
        different server was leader are reaped (reconcileReaped,
        leader.go:261-306).  Nodes without a serfHealth check are
        external registrations and never touched."""
        fn = self.srv.lan_members_fn
        if fn is None:
            return
        members = list(fn())
        known = set()
        for m in members:
            known.add(m.name)
            try:
                await self._reconcile_member(m)
            except Exception:
                return
        from consul_tpu.structs.structs import SERF_CHECK_ID
        _, nodes = self.srv.store.nodes()
        for node in nodes:
            if node.node in known:
                continue
            _, checks = self.srv.store.node_checks(node.node)
            if not any(c.check_id == SERF_CHECK_ID for c in checks):
                continue  # no serfHealth ⇒ externally registered
            try:
                await self._handle_left(node.node)
            except Exception:
                return

    async def _reconcile_member(self, member) -> None:
        """Dispatch one member to its state handler (reconcileMember,
        leader.go:310-339; MeasureSince at leader.go:316)."""
        from consul_tpu.membership.swim import (
            STATE_ALIVE, STATE_DEAD, STATE_LEFT, STATE_SUSPECT)
        from consul_tpu.utils.telemetry import metrics
        t0 = time.monotonic()
        try:
            state = getattr(member, "state", STATE_ALIVE)
            if state in (STATE_ALIVE, STATE_SUSPECT):
                await self._handle_alive(member)
            elif state == STATE_DEAD:
                await self._handle_failed(member)
            elif state == STATE_LEFT:
                await self._handle_left(member.name)
        finally:
            metrics.measure_since(("consul", "leader", "reconcileMember"), t0)

    async def _handle_alive(self, member) -> None:
        """handleAliveMember (leader.go:354-421): ensure the catalog has
        the node, a passing serfHealth, and the consul service for
        servers; raft-join new servers (joinConsulServer, leader.go:504)."""
        from consul_tpu.membership.serf import parse_server
        from consul_tpu.structs.structs import (
            CONSUL_SERVICE_ID, CONSUL_SERVICE_NAME, HEALTH_PASSING,
            HealthCheck, NodeService, RegisterRequest, SERF_ALIVE_OUTPUT,
            SERF_CHECK_ID, SERF_CHECK_NAME)
        sp = parse_server(member)
        if sp is not None and sp["dc"] == self.srv.config.datacenter and \
                member.name != self.srv.config.node_name and \
                member.name not in self.srv.raft.peers:
            await self.srv.raft.add_peer(member.name)
        # skip if the catalog already matches (leader.go:367-401)
        _, addr = self.srv.store.get_node(member.name)
        if addr == member.addr:
            _, checks = self.srv.store.node_checks(member.name)
            serf_ok = any(c.check_id == SERF_CHECK_ID
                          and c.status == HEALTH_PASSING for c in checks)
            _, svcs = self.srv.store.node_services(member.name)
            svc_ok = (sp is None or sp["dc"] != self.srv.config.datacenter
                      or bool(svcs and CONSUL_SERVICE_ID in svcs))
            if serf_ok and svc_ok:
                return
        req = RegisterRequest(
            node=member.name, address=member.addr,
            check=HealthCheck(node=member.name, check_id=SERF_CHECK_ID,
                              name=SERF_CHECK_NAME, status=HEALTH_PASSING,
                              output=SERF_ALIVE_OUTPUT))
        if sp is not None and sp["dc"] == self.srv.config.datacenter:
            req.service = NodeService(id=CONSUL_SERVICE_ID,
                                      service=CONSUL_SERVICE_NAME,
                                      port=sp["port"])
        await self.srv.catalog.register(req)

    async def _handle_failed(self, member) -> None:
        """handleFailedMember (leader.go:423-460): keep the node, flip
        serfHealth critical so health-filtered queries drop it."""
        from consul_tpu.structs.structs import (
            HEALTH_CRITICAL, HealthCheck, RegisterRequest, SERF_CHECK_ID,
            SERF_CHECK_NAME)
        _, checks = self.srv.store.node_checks(member.name)
        if any(c.check_id == SERF_CHECK_ID and c.status == HEALTH_CRITICAL
               for c in checks):
            return
        await self.srv.catalog.register(RegisterRequest(
            node=member.name, address=member.addr,
            check=HealthCheck(node=member.name, check_id=SERF_CHECK_ID,
                              name=SERF_CHECK_NAME, status=HEALTH_CRITICAL,
                              output="Agent not live or unreachable")))

    async def _handle_left(self, name: str) -> None:
        """handleLeftMember/handleReapMember (leader.go:462-501):
        deregister entirely; a departed server also leaves the raft
        peer set (removeConsulServer, leader.go:540)."""
        if name == self.srv.config.node_name:
            return  # never deregister self (leader.go:468-471)
        from consul_tpu.structs.structs import DeregisterRequest
        if name in self.srv.raft.peers:
            await self.srv.raft.remove_peer(name)
        _, addr = self.srv.store.get_node(name)
        if addr is None:
            return
        await self.srv.catalog.deregister(DeregisterRequest(node=name))

    # -- tombstone reaping (leader.go:553-566) -----------------------------

    async def _tombstone_loop(self) -> None:
        gran = self.srv.gc.granularity
        try:
            while self._active:
                now = time.monotonic()
                deadline = self.srv.gc.next_deadline(now)
                sleep_for = gran / 2 if deadline is None else max(
                    0.0, min(deadline - now, gran / 2))
                await asyncio.sleep(sleep_for if sleep_for > 0 else gran / 10)
                for idx in self.srv.gc.collect(time.monotonic()):
                    try:
                        await self.srv.raft_apply(
                            MessageType.TOMBSTONE, TombstoneRequest(reap_index=idx))
                    except Exception:
                        return
        except asyncio.CancelledError:
            pass
