"""Server RPC core: endpoints, blocking queries, apply path.

Parity layer for the reference's consul/server.go + consul/rpc.go +
per-domain *_endpoint.go files (SURVEY.md §2.4).
"""

from consul_tpu.server.server import NotLeaderError, Server, ServerConfig

__all__ = ["NotLeaderError", "Server", "ServerConfig"]
