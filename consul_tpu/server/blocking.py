"""Blocking (long-poll) query support.

Parity target: ``consul/rpc.go:301-398`` — a read with MinQueryIndex
registers on the watched tables' NotifyGroups, runs the query, and if
the result index hasn't advanced past MinQueryIndex, sleeps until a
mutation notifies or the (clamped, jittered) wait expires, then re-runs.
Bounds: max 600s, default 300s, jitter subtracts up to 1/16
(rpc.go:29-41 — jitter staggers the thundering re-poll herd).
"""

from __future__ import annotations

import asyncio
import random
from typing import Awaitable, Callable, Iterable, Optional

from consul_tpu.obs import journey as _journey
from consul_tpu.state.store import StateStore
from consul_tpu.structs.structs import QueryMeta, QueryOptions

MAX_QUERY_TIME = 600.0      # rpc.go:31-34
DEFAULT_QUERY_TIME = 300.0  # rpc.go:36-40
JITTER_FRACTION = 16


class AsyncWaiter:
    """Adapter giving NotifyGroup a ``set()`` that wakes an asyncio task.

    Safe to call from the event-loop thread (the normal case) or from
    another thread (e.g. a check runner mutating local state)."""

    def __init__(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop
        self._event = asyncio.Event()

    def set(self) -> None:
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop:
            self._event.set()
        else:
            self._loop.call_soon_threadsafe(self._event.set)

    async def wait(self, timeout: float) -> None:
        try:
            await asyncio.wait_for(self._event.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    def clear(self) -> None:
        self._event.clear()


def clamp_wait(requested: float) -> float:
    """Apply the default/max/jitter rules (rpc.go:366-377)."""
    wait = requested if requested > 0 else DEFAULT_QUERY_TIME
    wait = min(wait, MAX_QUERY_TIME)
    return wait - random.uniform(0, wait / JITTER_FRACTION)


async def blocking_query(
    store: StateStore,
    opts: QueryOptions,
    meta: QueryMeta,
    run: Callable[[], Awaitable[None]],
    tables: Iterable[str] = (),
    kv_prefix: Optional[str] = None,
    set_meta: Optional[Callable[[QueryMeta], None]] = None,
) -> None:
    """Run ``run`` (which must fill meta.index) with long-poll semantics.

    ``tables`` registers on table NotifyGroups; ``kv_prefix`` registers a
    radix KV watch instead (blockingRPCOpt's kvWatch path,
    rpc.go:342-360).
    """
    if set_meta is not None:
        set_meta(meta)

    if opts.min_query_index == 0:
        await run()
        return

    # Counted once per long-poll entry (consul/rpc.go:386).
    from consul_tpu.utils.telemetry import metrics
    metrics.incr_counter(("consul", "rpc", "query"))

    deadline = asyncio.get_running_loop().time() + clamp_wait(opts.max_query_time)
    loop = asyncio.get_running_loop()
    waiter = AsyncWaiter(loop)
    while True:
        # Register *before* running so a write between run and sleep
        # can't be missed (rpc.go:378-391 re-registers each iteration).
        if kv_prefix is not None:
            store.watch_kv(kv_prefix, waiter)
        if tables:
            store.watch(tables, waiter)
        try:
            await run()
            if meta.index > opts.min_query_index:
                # Journey wake stage: the first long-poll that RETURNS
                # fresh data after a reconcile batch arms is "a watcher
                # saw it" — stamped here (post re-query, the same point
                # an external client measures) rather than at the
                # waiter signal, which fires before any watcher task
                # has actually resumed.  One None test when the ledger
                # is off or nothing is armed (obs/journey.py).
                jy = _journey.journey
                if jy is not None:
                    jy.note_wake()
                return
            remaining = deadline - loop.time()
            if remaining <= 0:
                return
            await waiter.wait(remaining)
            waiter.clear()
        finally:
            if kv_prefix is not None:
                store.stop_watch_kv(kv_prefix, waiter)
            if tables:
                store.stop_watch(tables, waiter)
