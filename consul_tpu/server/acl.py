"""Server-side ACL resolution, replication caching, and result filtering.

Parity target: ``consul/acl.go`` (367 LoC) + ``consul/filter.go`` (70).

Resolution path (consul/acl.go:70-148):
- ACLs disabled (no ``acl_datacenter`` configured) -> None (no checks).
- empty token -> the anonymous token; master token short-circuits to
  manage (in the auth DC).
- in the ACL datacenter the fault function reads the local state store;
- other DCs RPC ``ACL.GetPolicy`` to the auth DC with ETag + TTL
  caching, and on RPC failure apply ``acl_down_policy``
  (allow / deny / extend-cache).
"""

from __future__ import annotations

from typing import List, Optional

from consul_tpu.acl.acl import ACLEval, manage_all, root_acl
from consul_tpu.acl.cache import ACLCache, ACLNotFound
from consul_tpu.structs.structs import (
    ACL_ANONYMOUS_ID, ACL_TYPE_MANAGEMENT, ACLPolicyReply)


class PermissionDenied(PermissionError):
    def __init__(self, msg: str = "Permission denied") -> None:
        super().__init__(msg)


class ServerACLResolver:
    """Owned by Server; answers resolve_token for every endpoint."""

    def __init__(self, server) -> None:
        self.srv = server
        cfg = server.config
        self.enabled = bool(cfg.acl_datacenter)
        self.is_auth_dc = cfg.acl_datacenter == cfg.datacenter
        self.cache = ACLCache(self._fault, ttl=cfg.acl_ttl)

    # -- fault path --------------------------------------------------------

    async def _fault(self, token_id: str):
        """FaultFunc: (parent, rules) for a token id.  Auth DC serves the
        state store (consul/acl.go:150-172); other DCs fetch the policy
        from the auth DC.  Counted (MeasureSince at consul/acl.go:49)."""
        from consul_tpu.utils.telemetry import metrics
        metrics.incr_counter(("consul", "acl", "fault"))
        if self.is_auth_dc:
            _, acl = self.srv.store.acl_get(token_id)
            if acl is None:
                raise ACLNotFound("ACL not found")
            parent = ("manage" if acl.type == ACL_TYPE_MANAGEMENT
                      else self.srv.config.acl_default_policy)
            return parent, acl.rules
        reply = await self._remote_policy(token_id, etag="")
        if reply is None:
            raise ACLNotFound("ACL not found")
        return reply.parent, (reply.policy or {}).get("rules", "")

    async def _remote_policy(self, token_id: str,
                             etag: str) -> Optional[ACLPolicyReply]:
        """RPC ACL.GetPolicy to the auth DC (consul/acl.go:104-121).
        Raises on transport failure so the down-policy can apply."""
        return await self.srv.rpc_get_remote_acl_policy(token_id, etag)

    # -- resolution --------------------------------------------------------

    async def resolve(self, token: str) -> Optional[ACLEval]:
        if not self.enabled:
            return None
        token = token or ACL_ANONYMOUS_ID
        cfg = self.srv.config
        if cfg.acl_master_token and token == cfg.acl_master_token:
            return manage_all()
        try:
            return await self.cache.get_acl(token)
        except ACLNotFound:
            raise PermissionDenied("ACL not found")
        except (ConnectionError, TimeoutError, OSError):
            # Only transport failures to the auth DC trigger the
            # down-policy (consul/acl.go:123-139); local faults (e.g. a
            # token whose stored rules no longer parse) must NOT fail
            # open under down-policy=allow — deny-by-error instead.
            down = cfg.acl_down_policy
            if down == "extend-cache":
                hit = self.cache.get_cached(token)
                if hit is not None:
                    return hit.acl
                down = "deny"
            return root_acl("allow" if down == "allow" else "deny")
        except Exception as e:
            raise PermissionDenied(f"ACL resolution failed: {e}")

    # -- serving GetPolicy to other DCs (consul/acl_endpoint.go:141+) ------

    def policy_reply(self, token_id: str, etag: str) -> Optional[ACLPolicyReply]:
        _, acl = self.srv.store.acl_get(token_id)
        if acl is None:
            return None
        import hashlib
        new_etag = hashlib.md5(acl.rules.encode()).hexdigest()
        parent = ("manage" if acl.type == ACL_TYPE_MANAGEMENT
                  else self.srv.config.acl_default_policy)
        reply = ACLPolicyReply(etag=new_etag, ttl=self.srv.config.acl_ttl,
                               parent=parent)
        if new_etag != etag:
            reply.policy = {"rules": acl.rules}
        return reply


# -- result filtering (consul/acl.go:199-367 + consul/filter.go) ------------


def filter_dir_entries(acl: Optional[ACLEval], entries: List) -> List:
    if acl is None:
        return entries
    return [e for e in entries if acl.key_read(e.key)]


def filter_keys(acl: Optional[ACLEval], keys: List[str]) -> List[str]:
    if acl is None:
        return keys
    return [k for k in keys if acl.key_read(k)]


def filter_service_nodes(acl: Optional[ACLEval], nodes: List) -> List:
    if acl is None:
        return nodes
    return [n for n in nodes if acl.service_read(n.service_name)]


def filter_health_checks(acl: Optional[ACLEval], checks: List) -> List:
    if acl is None:
        return checks
    return [c for c in checks
            if not c.service_name or acl.service_read(c.service_name)]


def filter_check_service_nodes(acl: Optional[ACLEval], csns: List) -> List:
    if acl is None:
        return csns
    return [c for c in csns if acl.service_read(c.service.service)]


def filter_node_services(acl: Optional[ACLEval], services):
    """Compact a node's {service_id: NodeService} map (consul/acl.go:288-301)."""
    if acl is None or services is None:
        return services
    return {sid: svc for sid, svc in services.items()
            if acl.service_read(svc.service)}


def filter_node_dump(acl: Optional[ACLEval], dump: List) -> List:
    """Filter the NodeInfo/NodeDump rows served to the UI
    (consul/acl.go:303-324): drop denied services and their checks."""
    if acl is None:
        return dump
    out = []
    for row in dump:
        services = [s for s in row["services"] if acl.service_read(s.service)]
        checks = filter_health_checks(acl, row["checks"])
        out.append({**row, "services": services, "checks": checks})
    return out


def filter_services_map(acl: Optional[ACLEval], services: dict) -> dict:
    if acl is None:
        return services
    return {name: tags for name, tags in services.items()
            if acl.service_read(name)}
