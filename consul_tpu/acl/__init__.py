"""ACL engine: policy language, evaluator, cache.

Parity target: the reference's ``acl/`` package (policy.go, acl.go,
cache.go) plus the server-side resolution in ``consul/acl.go``.
"""

from consul_tpu.acl.policy import Policy, KeyPolicy, ServicePolicy, parse_policy
from consul_tpu.acl.acl import (
    ACLEval, StaticACL, PolicyACL, allow_all, deny_all, manage_all, root_acl)
from consul_tpu.acl.cache import ACLCache

__all__ = [
    "Policy", "KeyPolicy", "ServicePolicy", "parse_policy",
    "ACLEval", "StaticACL", "PolicyACL",
    "allow_all", "deny_all", "manage_all", "root_acl",
    "ACLCache",
]
