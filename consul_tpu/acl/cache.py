"""ACL compilation cache.

Parity target: ``acl/cache.go`` (164 LoC) — three LRU layers so hot
tokens never re-parse rules:

- policy cache: rules-hash -> parsed Policy
- evaluator cache: (parent, rules-hash) -> compiled PolicyACL
- id cache: token id -> (evaluator, cached-at), backfilled by a fault
  function when missing (the FaultFunc contract, acl/cache.go:20-28)

The fault function returns ``(parent_name, rules)`` for a token id —
served locally in the ACL datacenter, fetched over RPC elsewhere
(consul/acl.go:70-148 wires both).
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict
from typing import Awaitable, Callable, Optional, Tuple

from consul_tpu.acl.acl import ACLEval, PolicyACL, root_acl
from consul_tpu.acl.policy import Policy, parse_policy

FaultFunc = Callable[[str], Awaitable[Tuple[str, str]]]


class ACLNotFound(Exception):
    """Token id does not exist (reference: errACLNotFound 'ACL not found')."""


class _LRU:
    def __init__(self, size: int) -> None:
        self._size = size
        self._d: OrderedDict = OrderedDict()

    def get(self, key):
        if key not in self._d:
            return None
        self._d.move_to_end(key)
        return self._d[key]

    def put(self, key, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self._size:
            self._d.popitem(last=False)

    def delete(self, key) -> None:
        self._d.pop(key, None)

    def clear(self) -> None:
        self._d.clear()


def _rules_hash(parent: str, rules: str) -> str:
    return hashlib.md5((parent + "\x00" + rules).encode()).hexdigest()


class CachedACL:
    __slots__ = ("acl", "expires", "etag")

    def __init__(self, acl: ACLEval, expires: float, etag: str) -> None:
        self.acl = acl
        self.expires = expires
        self.etag = etag


class ACLCache:
    def __init__(self, fault_fn: FaultFunc, ttl: float = 30.0,
                 size: int = 256) -> None:
        self._fault = fault_fn
        self._ttl = ttl
        self._policies = _LRU(size)
        self._evals = _LRU(size)
        self._ids = _LRU(size)

    def get_policy(self, rules: str) -> Policy:
        h = hashlib.md5(rules.encode()).hexdigest()
        pol = self._policies.get(h)
        if pol is None:
            pol = parse_policy(rules)
            self._policies.put(h, pol)
        return pol

    def compile(self, parent_name: str, rules: str) -> ACLEval:
        """parent + rules -> evaluator, via both content caches."""
        h = _rules_hash(parent_name, rules)
        ev = self._evals.get(h)
        if ev is None:
            parent = root_acl(parent_name) or root_acl("deny")
            ev = PolicyACL(parent, self.get_policy(rules))
            self._evals.put(h, ev)
        return ev

    async def get_acl(self, token_id: str, now: Optional[float] = None) -> ACLEval:
        """Resolve a token id, faulting on miss/expiry.  Raises ACLNotFound
        if the fault function does."""
        now = time.monotonic() if now is None else now
        hit: Optional[CachedACL] = self._ids.get(token_id)
        # ttl <= 0 disables caching entirely (every resolve re-faults),
        # matching the reference where a zero TTL expires immediately.
        if hit is not None and self._ttl > 0 and now < hit.expires:
            return hit.acl
        parent_name, rules = await self._fault(token_id)
        acl = self.compile(parent_name, rules)
        self._ids.put(token_id, CachedACL(
            acl, now + self._ttl, _rules_hash(parent_name, rules)))
        return acl

    def get_cached(self, token_id: str) -> Optional[CachedACL]:
        """The raw cache entry, expired or not — feeds the down-policy
        'extend-cache' path (consul/acl.go:123-130)."""
        return self._ids.get(token_id)

    def put_cached(self, token_id: str, acl: ACLEval, etag: str,
                   now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._ids.put(token_id, CachedACL(acl, now + self._ttl, etag))

    def invalidate(self, token_id: str) -> None:
        self._ids.delete(token_id)

    def clear(self) -> None:
        self._ids.clear()
