"""ACL policy language: HCL-subset + JSON rule documents.

Parity target: ``acl/policy.go`` in the reference (9-46 for the types,
49+ for hcl.Decode).  Rules look like::

    key "" {
      policy = "read"
    }
    key "foo/" {
      policy = "write"
    }
    service "web" {
      policy = "deny"
    }

The reference parses these with the full HCL library; the grammar the
ACL system actually uses is the tiny block subset above, so we ship a
self-contained tokenizer/parser for it (plus the JSON object form HCL
also accepts) rather than a generic HCL engine.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List

POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_WRITE = "write"
_VALID = (POLICY_DENY, POLICY_READ, POLICY_WRITE)


@dataclass
class KeyPolicy:
    prefix: str = ""
    policy: str = POLICY_READ


@dataclass
class ServicePolicy:
    name: str = ""
    policy: str = POLICY_READ


@dataclass
class Policy:
    id: str = ""
    keys: List[KeyPolicy] = field(default_factory=list)
    services: List[ServicePolicy] = field(default_factory=list)


class PolicyError(ValueError):
    pass


# -- tokenizer --------------------------------------------------------------

_PUNCT = {"{", "}", "=", ","}


def _tokenize(src: str) -> List[str]:
    toks: List[str] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
        elif c == "#" or src.startswith("//", i):
            while i < n and src[i] != "\n":
                i += 1
        elif src.startswith("/*", i):
            j = src.find("*/", i + 2)
            if j < 0:
                raise PolicyError("unterminated block comment")
            i = j + 2
        elif c == '"':
            j = i + 1
            buf = []
            while j < n and src[j] != '"':
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc, esc))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise PolicyError("unterminated string")
            toks.append('"' + "".join(buf))  # leading quote marks string tokens
            i = j + 1
        elif c in _PUNCT:
            toks.append(c)
            i += 1
        else:
            j = i
            while j < n and (src[j].isalnum() or src[j] in "_-./"):
                j += 1
            if j == i:
                raise PolicyError(f"unexpected character {c!r}")
            toks.append(src[i:j])
            i = j
    return toks


def _parse_hcl(src: str) -> Policy:
    toks = _tokenize(src)
    pol = Policy()
    i = 0

    def expect(tok: str) -> None:
        nonlocal i
        if i >= len(toks) or toks[i] != tok:
            got = toks[i] if i < len(toks) else "<eof>"
            raise PolicyError(f"expected {tok!r}, got {got!r}")
        i += 1

    def string() -> str:
        nonlocal i
        if i >= len(toks) or not toks[i].startswith('"'):
            got = toks[i] if i < len(toks) else "<eof>"
            raise PolicyError(f"expected string, got {got!r}")
        s = toks[i][1:]
        i += 1
        return s

    while i < len(toks):
        kind = toks[i]
        i += 1
        if kind not in ("key", "service"):
            raise PolicyError(f"unknown block type {kind!r}")
        name = string()
        expect("{")
        attrs = {}
        while i < len(toks) and toks[i] != "}":
            attr = toks[i]
            i += 1
            expect("=")
            attrs[attr] = string()
        expect("}")
        if set(attrs) - {"policy"}:
            raise PolicyError(f"unknown attributes {sorted(set(attrs) - {'policy'})}")
        disp = attrs.get("policy", POLICY_READ)
        if kind == "key":
            pol.keys.append(KeyPolicy(prefix=name, policy=disp))
        else:
            pol.services.append(ServicePolicy(name=name, policy=disp))
    return pol


def _parse_json(obj: dict) -> Policy:
    pol = Policy()
    for kind, target in (("key", pol.keys), ("service", pol.services)):
        block = obj.get(kind) or {}
        if not isinstance(block, dict):
            raise PolicyError(f"{kind!r} must be an object")
        for name, attrs in block.items():
            disp = (attrs or {}).get("policy", POLICY_READ)
            if kind == "key":
                target.append(KeyPolicy(prefix=name, policy=disp))
            else:
                target.append(ServicePolicy(name=name, policy=disp))
    return pol


def parse_policy(rules: str) -> Policy:
    """Parse + validate a rule document (acl/policy.go:49+).  Accepts the
    HCL block form or a JSON object; empty rules yield an empty policy."""
    rules = rules or ""
    stripped = rules.strip()
    if not stripped:
        return Policy()
    if stripped.startswith("{"):
        try:
            obj = json.loads(stripped)
        except json.JSONDecodeError as e:
            raise PolicyError(f"invalid JSON policy: {e}") from e
        pol = _parse_json(obj)
    else:
        pol = _parse_hcl(rules)
    for kp in pol.keys:
        if kp.policy not in _VALID:
            raise PolicyError(f"invalid key policy: {kp.policy!r}")
    for sp in pol.services:
        if sp.policy not in _VALID:
            raise PolicyError(f"invalid service policy: {sp.policy!r}")
    return pol
