"""ACL evaluators: static roots + radix longest-prefix policy ACLs.

Parity target: ``acl/acl.go`` (37-127 static roots and rule layout,
129+ PolicyACL evaluation).  An evaluator answers the seven questions
the reference interface defines: KeyRead/KeyWrite/KeyWritePrefix,
ServiceRead/ServiceWrite, ACLList/ACLModify.
"""

from __future__ import annotations

from consul_tpu.acl.policy import (
    POLICY_READ, POLICY_WRITE, Policy, parse_policy)
from consul_tpu.state.radix import RadixTree


class ACLEval:
    """Interface (acl/acl.go:23-35)."""

    def key_read(self, key: str) -> bool:
        raise NotImplementedError

    def key_write(self, key: str) -> bool:
        raise NotImplementedError

    def key_write_prefix(self, prefix: str) -> bool:
        raise NotImplementedError

    def service_read(self, name: str) -> bool:
        raise NotImplementedError

    def service_write(self, name: str) -> bool:
        raise NotImplementedError

    def acl_list(self) -> bool:
        raise NotImplementedError

    def acl_modify(self) -> bool:
        raise NotImplementedError


class StaticACL(ACLEval):
    """allow-all / deny-all / manage-all roots (acl/acl.go:37-107)."""

    def __init__(self, default_allow: bool, manage: bool = False) -> None:
        self._allow = default_allow
        self._manage = manage

    def key_read(self, key: str) -> bool:
        return self._allow

    def key_write(self, key: str) -> bool:
        return self._allow

    def key_write_prefix(self, prefix: str) -> bool:
        return self._allow

    def service_read(self, name: str) -> bool:
        return self._allow

    def service_write(self, name: str) -> bool:
        return self._allow

    def acl_list(self) -> bool:
        return self._manage

    def acl_modify(self) -> bool:
        return self._manage


_ALLOW_ALL = StaticACL(True)
_DENY_ALL = StaticACL(False)
_MANAGE_ALL = StaticACL(True, manage=True)


def allow_all() -> StaticACL:
    return _ALLOW_ALL


def deny_all() -> StaticACL:
    return _DENY_ALL


def manage_all() -> StaticACL:
    return _MANAGE_ALL


def root_acl(name: str):
    """RootACL (acl/acl.go:109-120): 'allow' | 'deny' | 'manage' or None."""
    return {"allow": _ALLOW_ALL, "deny": _DENY_ALL, "manage": _MANAGE_ALL}.get(name)


class PolicyACL(ACLEval):
    """Rule-set evaluation by longest-prefix radix match, falling back to a
    parent evaluator (acl/acl.go:122-229)."""

    def __init__(self, parent: ACLEval, policy: Policy) -> None:
        self.parent = parent
        self._key_rules = RadixTree()
        self._service_rules = RadixTree()
        for kp in policy.keys:
            self._key_rules.insert(kp.prefix, kp.policy)
        for sp in policy.services:
            self._service_rules.insert(sp.name, sp.policy)

    @classmethod
    def from_rules(cls, parent: ACLEval, rules: str) -> "PolicyACL":
        return cls(parent, parse_policy(rules))

    def key_read(self, key: str) -> bool:
        hit = self._key_rules.longest_prefix(key)
        if hit is not None:
            return hit[1] in (POLICY_READ, POLICY_WRITE)
        return self.parent.key_read(key)

    def key_write(self, key: str) -> bool:
        hit = self._key_rules.longest_prefix(key)
        if hit is not None:
            return hit[1] == POLICY_WRITE
        return self.parent.key_write(key)

    def key_write_prefix(self, prefix: str) -> bool:
        """Write to an entire subtree (DeleteTree): no rule under the prefix
        may be non-write, and the governing rule at the prefix must allow
        write (acl/acl.go:188-211)."""
        for _, disp in self._key_rules.walk_prefix(prefix):
            if disp != POLICY_WRITE:
                return False
        hit = self._key_rules.longest_prefix(prefix)
        if hit is not None:
            return hit[1] == POLICY_WRITE
        return self.parent.key_write_prefix(prefix)

    def service_read(self, name: str) -> bool:
        hit = self._service_rules.longest_prefix(name)
        if hit is not None:
            return hit[1] in (POLICY_READ, POLICY_WRITE)
        return self.parent.service_read(name)

    def service_write(self, name: str) -> bool:
        hit = self._service_rules.longest_prefix(name)
        if hit is not None:
            return hit[1] == POLICY_WRITE
        return self.parent.service_write(name)

    def acl_list(self) -> bool:
        return self.parent.acl_list()

    def acl_modify(self) -> bool:
        return self.parent.acl_modify()
