"""TPU-friendly primitive ops used by the gossip kernel."""

from consul_tpu.ops.feistel import (  # noqa: F401
    feistel_permute,
    feistel_inverse,
    random_targets,
)
