"""The block-divisibility contract, stated once.

Pallas block windows only tile an axis cleanly when the block count
divides the axis (``gossip/fused.py``: the observer axis splits into
``fused_nb`` column blocks of width ``n // fused_nb``; a remainder
column would silently fall off the grid).  Before PR 13 that contract
lived in two places that could drift apart: a runtime ``ValueError``
inside ``_fused_single`` and whatever the static analyzer happened to
grep for.  Both now consume THIS module — the kernel calls
:func:`require_divisible` as its runtime guard, and the vet P01 pass
(``tools/vet/pallas_safety.py``) both recognizes that call as guard
evidence and imports :func:`divides` to constant-fold statically known
cases — so the static check and the runtime error cannot disagree
(pinned by ``tests/test_vet.py::TestPallasSafety``).

Host-only integer math: no jax imports, callable at trace time on
static shape ints.
"""

from __future__ import annotations


def divides(n: int, d: int) -> bool:
    """True iff ``d`` is a positive exact divisor of ``n``."""
    return d > 0 and n % d == 0


def require_divisible(n: int, d: int, *, what: str = "n",
                      by: str = "divisor") -> None:
    """Raise ``ValueError`` unless ``divides(n, d)`` — the runtime half
    of the block-window contract (module docstring)."""
    if not divides(n, d):
        raise ValueError(
            f"{what}={n} must be divisible by {by}={d} "
            f"(block windows must tile the axis exactly)")
