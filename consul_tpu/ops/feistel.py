"""Invertible pseudorandom permutations on TPU.

Why this exists: SWIM gossip is a *push* protocol — each node sends its
queued broadcasts to ``fanout`` random peers per round (reference
behavior: memberlist's gossip tick, documented at
``website/source/docs/internals/gossip.html.markdown:10-43`` and consumed
via Serf at ``consul/config.go:268-272``).  Delivering pushes on TPU
naively needs a scatter keyed by destination (or a sort of N*fanout
edges per round).  Drawing each round's communication graph as
``fanout`` independent pseudorandom *permutations* of the node set makes
delivery ``fanout`` vectorized gathers: node ``i`` pushes to
``perm_f(i)``, so the senders into node ``d`` are ``perm_f^{-1}(d)``.
The in-degree is exactly ``fanout`` instead of Poisson(fanout); the
epidemic growth statistics are nearly identical (quantified against the
discrete-event reference model, gossip/refmodel.py, in the
cross-validation test tier) and the tails are *tighter*.

HISTORY NOTE (round 3): the production kernels no longer use these —
on the v5e an arbitrary-permutation gather costs ~6.5ns per random
index while a contiguous roll moves at memory bandwidth, so
``kernel.gossip_offsets`` replaced per-node permutations with per-round
circulant shifts (the same exact-in-degree property, ~25x cheaper
delivery).  The module remains the general-purpose invertible-PRP op
(used by the profiler as the gather-cost yardstick and exercised by
tests/test_feistel.py); anything needing per-node — rather than
per-round — randomized routing starts here.

The permutation is a balanced Feistel network over ``2^(2*h)`` with a
murmur-style round function, plus cycle-walking for arbitrary domain
sizes (walking a point until it lands back inside ``[0, n)`` preserves
the permutation property and its invertibility).  Everything is uint32
arithmetic — no data-dependent shapes, scan/while-safe under jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_M1 = jnp.uint32(0x85EBCA6B)
_M2 = jnp.uint32(0xC2B2AE35)
_GOLD = jnp.uint32(0x9E3779B9)


def _round_fn(half: jnp.ndarray, round_key: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Murmur3-finalizer-style mixing of one Feistel half with a round key."""
    v = (half * _GOLD + round_key).astype(jnp.uint32)
    v = v ^ (v >> 16)
    v = v * _M1
    v = v ^ (v >> 13)
    v = v * _M2
    v = v ^ (v >> 16)
    return v & jnp.uint32((1 << bits) - 1)


def _derive_round_keys(key: jax.Array, rounds: int) -> jnp.ndarray:
    return jax.random.bits(key, (rounds,), dtype=jnp.uint32)


def _feistel(x, round_keys, half_bits: int, forward: bool):
    mask = jnp.uint32((1 << half_bits) - 1)
    left = (x >> half_bits) & mask
    right = x & mask
    rounds = round_keys.shape[0]
    order = range(rounds) if forward else range(rounds - 1, -1, -1)
    for r in order:
        if forward:
            left, right = right, left ^ _round_fn(right, round_keys[r], half_bits)
        else:
            left, right = right ^ _round_fn(left, round_keys[r], half_bits), left
    return ((left << half_bits) | right).astype(jnp.uint32)


def _half_bits(n: int) -> int:
    b = max(2, (n - 1).bit_length())
    return (b + 1) // 2


def _cycle_walk(x: jnp.ndarray, key: jax.Array, n: int, rounds: int,
                forward: bool) -> jnp.ndarray:
    """Apply the (possibly inverse) Feistel network, cycle-walking
    out-of-domain points so the map is an exact bijection on ``[0, n)``."""
    h = _half_bits(n)
    rk = _derive_round_keys(key, rounds)
    x = x.astype(jnp.uint32)

    if n == 1 << (2 * h):
        return _feistel(x, rk, h, forward)

    def cond(state):
        y, _ = state
        return jnp.any(y >= n)

    def body(state):
        y, _ = state
        walk = _feistel(y, rk, h, forward)
        y = jnp.where(y >= n, walk, y)
        return y, 0

    y = _feistel(x, rk, h, forward)
    y, _ = lax.while_loop(cond, body, (y, 0))
    return y


@functools.partial(jax.jit, static_argnames=("n", "rounds"))
def feistel_permute(x: jnp.ndarray, key: jax.Array, n: int, rounds: int = 4) -> jnp.ndarray:
    """Apply a keyed pseudorandom permutation of ``[0, n)`` to ``x``.

    ``x`` must contain values in ``[0, n)``.  Cycle-walks out-of-domain
    intermediate points, so this is an exact bijection for any ``n``.
    """
    return _cycle_walk(x, key, n, rounds, True)


@functools.partial(jax.jit, static_argnames=("n", "rounds"))
def feistel_inverse(y: jnp.ndarray, key: jax.Array, n: int, rounds: int = 4) -> jnp.ndarray:
    """Inverse of :func:`feistel_permute` under the same key."""
    return _cycle_walk(y, key, n, rounds, False)


def random_targets(key: jax.Array, n: int, shape,
                   ids: jnp.ndarray | None = None) -> jnp.ndarray:
    """Uniform random peer ids excluding self for the given probers.

    Prober ``i`` gets a target uniform over ``[0, n) \\ {i}`` via the
    shifted-draw trick (no rejection loop): ``(i + 1 + U[0, n-1)) % n``.
    Matches memberlist's uniform random member selection for probe and
    indirect-probe targets.  ``ids`` defaults to ``0..shape[0]`` (all
    nodes probing); pass explicit ids for a staggered prober block.
    """
    offs = jax.random.randint(key, shape, 0, n - 1, dtype=jnp.int32)
    if ids is None:
        ids = jnp.arange(shape[0], dtype=jnp.int32)
    if len(shape) == 2:
        ids = ids[:, None]
    return (ids + 1 + offs) % n


# -- hot-path source permutations (multiply-free, fixed trip count) ----------
#
# The exact feistel_permute/inverse above cycle-walk with a
# data-dependent while_loop and a murmur round function (three u32
# multiplies per round).  Neither is cheap on the VPU, and the gossip
# kernel calls this every round for every fanout edge.  gossip_sources
# is the same balanced-Feistel construction with (a) an ARX round
# function — xorshift mixing, zero multiplies — and (b) a FIXED number
# of cycle-walk iterations with a final modulo clamp.  The number of
# walks is chosen statically from the pad fraction
# ``(4^h - n) / 4^h`` (up to 3/4 for n just above a power of four) so
# the residual out-of-domain probability is ≤1%; a clamped straggler
# draws a ~uniform random source instead of a bijective one.  Effect on
# the gossip graph: in-degree stays exactly ``fanout`` for every
# destination; out-degree varies slightly for ≤1% of edges — which is
# *between* the exact-permutation graph and stock memberlist's push
# (out-degree exact, in-degree Poisson), so the epidemic statistics
# stay inside the envelope the cross-validation tier checks.  Exact
# bijectivity is traded for straight-line code.


def _walks_for(n: int, residual: float = 0.01, lo: int = 2, hi: int = 16) -> int:
    """Static walk count: pad_fraction^walks <= residual."""
    import math
    h = _half_bits(n)
    dom = 1 << (2 * h)
    pad = (dom - n) / dom
    if pad <= 0.0:
        return 1
    return max(lo, min(hi, math.ceil(math.log(residual) / math.log(pad))))


def _arx_round_fn(half: jnp.ndarray, round_key: jnp.ndarray, bits: int) -> jnp.ndarray:
    v = (half + round_key).astype(jnp.uint32)
    v = v ^ (v << 13)
    v = v ^ (v >> 17)
    v = v ^ (v << 5)
    return v & jnp.uint32((1 << bits) - 1)


def _arx_feistel(x, round_keys, half_bits: int, forward: bool):
    mask = jnp.uint32((1 << half_bits) - 1)
    left = (x >> half_bits) & mask
    right = x & mask
    rounds = round_keys.shape[0]
    order = range(rounds) if forward else range(rounds - 1, -1, -1)
    for r in order:
        if forward:
            left, right = right, left ^ _arx_round_fn(right, round_keys[r], half_bits)
        else:
            left, right = right ^ _arx_round_fn(left, round_keys[r], half_bits), left
    return ((left << half_bits) | right).astype(jnp.uint32)


@functools.partial(jax.jit, static_argnames=("n", "fanout", "rounds", "walks"))
def gossip_sources(key: jax.Array, n: int, fanout: int,
                   rounds: int = 4, walks: int = 0) -> jnp.ndarray:
    """``(fanout, n)`` i32: senders into each destination this round.

    Row ``f`` is (approximately — see module note) the inverse of an
    independent keyed pseudorandom permutation of ``[0, n)``: delivery
    of every push is ``fanout`` vectorized gathers.  ``walks=0`` picks
    the static count for a ≤1% clamp residual.
    """
    h = _half_bits(n)
    walks = walks or _walks_for(n)
    dests = jnp.broadcast_to(jnp.arange(n, dtype=jnp.uint32), (fanout, n))
    rk = jax.random.bits(key, (fanout, rounds), dtype=jnp.uint32)

    def per_row(d_row, rk_row):
        y = _arx_feistel(d_row, rk_row, h, forward=False)
        for _ in range(walks - 1):
            y = jnp.where(y >= n, _arx_feistel(y, rk_row, h, forward=False), y)
        return jnp.where(y >= n, y % jnp.uint32(n), y)

    return jax.vmap(per_row)(dests, rk).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n", "rounds", "walks"))
def gossip_partners(key: jax.Array, n: int,
                    rounds: int = 4, walks: int = 0) -> tuple:
    """One pseudorandom pairing for push/pull: ``(fwd, rev)`` where
    ``fwd[d]`` dials d and ``rev[i]`` is whom i dials (approximate
    inverse pair under the same key, same clamp rules as
    :func:`gossip_sources`)."""
    h = _half_bits(n)
    walks = walks or _walks_for(n)
    ids = jnp.arange(n, dtype=jnp.uint32)
    rk = jax.random.bits(key, (rounds,), dtype=jnp.uint32)

    def walk(x, forward):
        y = _arx_feistel(x, rk, h, forward)
        for _ in range(walks - 1):
            y = jnp.where(y >= n, _arx_feistel(y, rk, h, forward), y)
        return jnp.where(y >= n, y % jnp.uint32(n), y).astype(jnp.int32)

    return walk(ids, False), walk(ids, True)
