"""Invertible pseudorandom permutations on TPU.

Why this exists: SWIM gossip is a *push* protocol — each node sends its
queued broadcasts to ``fanout`` random peers per round (reference
behavior: memberlist's gossip tick, documented at
``website/source/docs/internals/gossip.html.markdown:10-43`` and consumed
via Serf at ``consul/config.go:268-272``).  Delivering pushes on TPU
naively needs a scatter keyed by destination (or a sort of N*fanout
edges per round).  Instead we draw each round's communication graph as
``fanout`` independent pseudorandom *permutations* of the node set: node
``i`` pushes to ``perm_f(i)``, so the senders into node ``d`` are exactly
``perm_f^{-1}(d)`` — delivery becomes ``fanout`` vectorized gathers.
The in-degree is exactly ``fanout`` instead of Poisson(fanout); the
epidemic growth statistics are nearly identical (quantified against the
discrete-event reference model, gossip/refmodel.py, in the
cross-validation test tier) and the tails are *tighter*.

The permutation is a balanced Feistel network over ``2^(2*h)`` with a
murmur-style round function, plus cycle-walking for arbitrary domain
sizes (walking a point until it lands back inside ``[0, n)`` preserves
the permutation property and its invertibility).  Everything is uint32
arithmetic — no data-dependent shapes, scan/while-safe under jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_M1 = jnp.uint32(0x85EBCA6B)
_M2 = jnp.uint32(0xC2B2AE35)
_GOLD = jnp.uint32(0x9E3779B9)


def _round_fn(half: jnp.ndarray, round_key: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Murmur3-finalizer-style mixing of one Feistel half with a round key."""
    v = (half * _GOLD + round_key).astype(jnp.uint32)
    v = v ^ (v >> 16)
    v = v * _M1
    v = v ^ (v >> 13)
    v = v * _M2
    v = v ^ (v >> 16)
    return v & jnp.uint32((1 << bits) - 1)


def _derive_round_keys(key: jax.Array, rounds: int) -> jnp.ndarray:
    return jax.random.bits(key, (rounds,), dtype=jnp.uint32)


def _feistel(x, round_keys, half_bits: int, forward: bool):
    mask = jnp.uint32((1 << half_bits) - 1)
    left = (x >> half_bits) & mask
    right = x & mask
    rounds = round_keys.shape[0]
    order = range(rounds) if forward else range(rounds - 1, -1, -1)
    for r in order:
        if forward:
            left, right = right, left ^ _round_fn(right, round_keys[r], half_bits)
        else:
            left, right = right ^ _round_fn(left, round_keys[r], half_bits), left
    return ((left << half_bits) | right).astype(jnp.uint32)


def _half_bits(n: int) -> int:
    b = max(2, (n - 1).bit_length())
    return (b + 1) // 2


def _cycle_walk(x: jnp.ndarray, key: jax.Array, n: int, rounds: int,
                forward: bool) -> jnp.ndarray:
    """Apply the (possibly inverse) Feistel network, cycle-walking
    out-of-domain points so the map is an exact bijection on ``[0, n)``."""
    h = _half_bits(n)
    rk = _derive_round_keys(key, rounds)
    x = x.astype(jnp.uint32)

    if n == 1 << (2 * h):
        return _feistel(x, rk, h, forward)

    def cond(state):
        y, _ = state
        return jnp.any(y >= n)

    def body(state):
        y, _ = state
        walk = _feistel(y, rk, h, forward)
        y = jnp.where(y >= n, walk, y)
        return y, 0

    y = _feistel(x, rk, h, forward)
    y, _ = lax.while_loop(cond, body, (y, 0))
    return y


@functools.partial(jax.jit, static_argnames=("n", "rounds"))
def feistel_permute(x: jnp.ndarray, key: jax.Array, n: int, rounds: int = 4) -> jnp.ndarray:
    """Apply a keyed pseudorandom permutation of ``[0, n)`` to ``x``.

    ``x`` must contain values in ``[0, n)``.  Cycle-walks out-of-domain
    intermediate points, so this is an exact bijection for any ``n``.
    """
    return _cycle_walk(x, key, n, rounds, True)


@functools.partial(jax.jit, static_argnames=("n", "rounds"))
def feistel_inverse(y: jnp.ndarray, key: jax.Array, n: int, rounds: int = 4) -> jnp.ndarray:
    """Inverse of :func:`feistel_permute` under the same key."""
    return _cycle_walk(y, key, n, rounds, False)


def random_targets(key: jax.Array, n: int, shape) -> jnp.ndarray:
    """Uniform random peer ids excluding self for probers ``0..shape[0]``.

    Node ``i`` gets a target uniform over ``[0, n) \\ {i}`` via the
    shifted-draw trick (no rejection loop): ``(i + 1 + U[0, n-1)) % n``.
    Matches memberlist's uniform random member selection for probe and
    indirect-probe targets.
    """
    offs = jax.random.randint(key, shape, 0, n - 1, dtype=jnp.int32)
    ids = jnp.arange(shape[0], dtype=jnp.int32)
    if len(shape) == 2:
        ids = ids[:, None]
    return (ids + 1 + offs) % n
