"""Cross-cutting utilities (telemetry, small helpers)."""

from consul_tpu.utils.telemetry import Metrics, metrics

__all__ = ["Metrics", "metrics"]
