"""In-process metrics: counters/gauges/timers with interval aggregation.

Parity target: the reference's go-metrics wiring
(``command/agent/command.go:569-605``) — an in-memory sink aggregating
into fixed intervals (go-metrics ``NewInmemSink(10s, 1min)``), dumped
on **SIGUSR1**, optionally fanned out to a statsite/statsd UDP
collector, with ``MeasureSince`` calls at every hot point (e.g. raft
apply ``consul/fsm.go:121``, blocking queries ``consul/rpc.go:386``,
leader reconcile ``consul/leader.go:243,316``, ACL faults
``consul/acl.go:49``).

Design: one process-global :class:`Metrics` registry (``metrics``)
that call sites hit directly — no plumbing through constructors, same
as go-metrics' package-global.  Sinks are attached at agent startup
from the ``telemetry`` config block.  All paths are non-blocking: the
statsd sink is a fire-and-forget UDP datagram per emission, and the
inmem sink is plain dict math (the agent is single-threaded asyncio;
the lock is for the check-runner thread pool).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Tuple

DEFAULT_INTERVAL_S = 10.0
DEFAULT_RETAIN = 6  # 6 x 10s = one minute of history (go-metrics default)


class AggregateSample:
    """Running aggregate of one timer/sample series inside an interval."""

    __slots__ = ("count", "sum", "min", "max", "sumsq")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.sumsq = 0.0

    def ingest(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.sumsq += v * v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def wire(self) -> Dict[str, float]:
        return {"count": self.count, "sum": round(self.sum, 3),
                "min": round(self.min, 3) if self.count else 0.0,
                "max": round(self.max, 3) if self.count else 0.0,
                "mean": round(self.mean, 3)}


class _Interval:
    __slots__ = ("start", "counters", "gauges", "samples")

    def __init__(self, start: float) -> None:
        self.start = start
        self.counters: Dict[str, AggregateSample] = {}
        self.gauges: Dict[str, float] = {}
        self.samples: Dict[str, AggregateSample] = {}


class InmemSink:
    """Fixed-width interval ring (NewInmemSink role)."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 retain: int = DEFAULT_RETAIN) -> None:
        self.interval_s = interval_s
        self.retain = retain
        self._intervals: List[_Interval] = []

    def _bucket(self, now: float) -> _Interval:
        start = now - (now % self.interval_s)
        if not self._intervals or self._intervals[-1].start != start:
            self._intervals.append(_Interval(start))
            if len(self._intervals) > self.retain:
                del self._intervals[: len(self._intervals) - self.retain]
        return self._intervals[-1]

    def incr_counter(self, name: str, n: float, now: float) -> None:
        b = self._bucket(now)
        b.counters.setdefault(name, AggregateSample()).ingest(n)

    def set_gauge(self, name: str, v: float, now: float) -> None:
        self._bucket(now).gauges[name] = v

    def add_sample(self, name: str, v: float, now: float) -> None:
        b = self._bucket(now)
        b.samples.setdefault(name, AggregateSample()).ingest(v)

    def snapshot(self) -> List[Dict]:
        """JSON-able interval dump (/v1/agent/metrics shape)."""
        out = []
        for iv in self._intervals:
            out.append({
                "Interval": iv.start,
                "Counters": {k: v.wire() for k, v in sorted(iv.counters.items())},
                "Gauges": {k: round(v, 3) for k, v in sorted(iv.gauges.items())},
                "Samples": {k: v.wire() for k, v in sorted(iv.samples.items())},
            })
        return out

    def dump(self) -> str:
        """Human dump, one interval per block (the SIGUSR1 format)."""
        lines: List[str] = []
        for iv in self._intervals:
            ts = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(iv.start))
            lines.append(f"[{ts}]")
            for k, v in sorted(iv.gauges.items()):
                lines.append(f"  [G] '{k}': {v:.3f}")
            for k, s in sorted(iv.counters.items()):
                lines.append(f"  [C] '{k}': count={s.count} sum={s.sum:.3f}")
            for k, s in sorted(iv.samples.items()):
                lines.append(f"  [S] '{k}': count={s.count} "
                             f"min={s.min:.3f} mean={s.mean:.3f} "
                             f"max={s.max:.3f}")
        return "\n".join(lines)


class StatsdSink:
    """Fire-and-forget UDP `name:value|type` datagrams (statsd line
    protocol; the statsite sink speaks the same format)."""

    def __init__(self, addr: str) -> None:
        host, _, port = addr.partition(":")
        try:
            portno = int(port) if port else 8125
        except ValueError:
            portno = 8125  # malformed port: default rather than die
        self._addr = (host, portno)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)

    def _send(self, line: str) -> None:
        try:
            self._sock.sendto(line.encode(), self._addr)
        except OSError:
            pass  # metrics must never take the agent down

    def incr_counter(self, name: str, n: float, now: float) -> None:
        self._send(f"{name}:{n:g}|c")

    def set_gauge(self, name: str, v: float, now: float) -> None:
        self._send(f"{name}:{v:g}|g")

    def add_sample(self, name: str, v: float, now: float) -> None:
        self._send(f"{name}:{v:g}|ms")

    def close(self) -> None:
        self._sock.close()


class Metrics:
    """The registry call sites hit.  Key parts are dot-joined; when a
    hostname is configured (and not disabled) it is interposed after
    the service name, matching go-metrics' HostName behavior."""

    def __init__(self) -> None:
        self.inmem = InmemSink()
        self._sinks: List[object] = [self.inmem]
        self._lock = threading.Lock()
        self.hostname = ""

    def configure(self, statsd_addr: str = "", statsite_addr: str = "",
                  hostname: str = "", disable_hostname: bool = False) -> None:
        """Apply the agent's telemetry config block
        (command/agent/command.go:569-605)."""
        with self._lock:
            self.hostname = "" if disable_hostname else hostname
            for s in self._sinks[1:]:
                if hasattr(s, "close"):
                    s.close()
            self._sinks = [self.inmem]
            for addr in (statsd_addr, statsite_addr):
                if addr:
                    self._sinks.append(StatsdSink(addr))

    def _name(self, key: Tuple[str, ...]) -> str:
        parts = list(key)
        if self.hostname and len(parts) > 1:
            parts = [parts[0], self.hostname, *parts[1:]]
        return ".".join(parts)

    def incr_counter(self, key: Tuple[str, ...], n: float = 1.0) -> None:
        name, now = self._name(key), time.time()
        with self._lock:
            for s in self._sinks:
                s.incr_counter(name, n, now)

    def set_gauge(self, key: Tuple[str, ...], v: float) -> None:
        name, now = self._name(key), time.time()
        with self._lock:
            for s in self._sinks:
                s.set_gauge(name, v, now)

    def add_sample(self, key: Tuple[str, ...], v: float) -> None:
        name, now = self._name(key), time.time()
        with self._lock:
            for s in self._sinks:
                s.add_sample(name, v, now)

    def measure_since(self, key: Tuple[str, ...], t0: float) -> None:
        """Record elapsed milliseconds since ``t0`` (a time.monotonic()
        stamp) — the MeasureSince idiom."""
        self.add_sample(key, (time.monotonic() - t0) * 1000.0)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return self.inmem.snapshot()

    def dump(self) -> str:
        with self._lock:
            return self.inmem.dump()


# The process-global registry, mirroring go-metrics' package global.
metrics = Metrics()
