"""Host-plane state: MVCC-style tables, watches, tombstones, sessions.

Parity layer for the reference's ``consul/state_store.go`` +
``consul/mdb_table.go`` + ``consul/notify.go`` (SURVEY.md §2.3).
"""

from consul_tpu.state.notify import NotifyGroup
from consul_tpu.state.radix import RadixTree
from consul_tpu.state.store import QUERY_TABLES, StateStore, StateStoreError

__all__ = ["NotifyGroup", "RadixTree", "QUERY_TABLES", "StateStore", "StateStoreError"]
