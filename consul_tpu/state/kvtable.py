"""Pluggable KV-table backends for the state store.

Parity target: the reference's LMDB role (``consul/state_store.go:15``,
``consul/mdb_table.go``).  Key fact about that design: LMDB is opened
**ephemeral** in a fresh temp dir each boot with NOSYNC
(state_store.go:190-196) — durability always comes from the Raft log
and FSM snapshots above; the mmap store exists for MVCC isolation and
for keeping a dataset bigger than RAM addressable.  We mirror that
split exactly:

- :class:`DictKVTable` — in-process dict + sorted keys (dev mode, and
  the fastest option when the dataset fits comfortably in RAM).
- :class:`NativeKVTable` — rows live in the C++ mmap MVCC store
  (native/cstore.cpp) as msgpack-encoded DirEntries under ``k:<key>``,
  with a ``x:<session>\\0<key>`` secondary index maintaining the
  session→held-keys relation the invalidation cascades walk.  The
  backing file is recreated empty at open (the reference's temp-dir
  behavior); crash recovery is raft-log replay, not file reuse.

The surface is the narrow set of row operations ``StateStore`` needs;
everything above it (CAS/lock modes, tombstones, watches, cascades)
stays in the store, so both backends share one semantics
implementation.
"""

from __future__ import annotations

import bisect
import os
import shutil
from typing import Dict, Iterator, List, Optional, Set, Tuple

import msgpack

from consul_tpu.structs.structs import DirEntry


class DictKVTable:
    """Rows in a dict; ordered key scans via a sorted list."""

    def __init__(self) -> None:
        self._rows: Dict[str, DirEntry] = {}
        self._keys: List[str] = []
        self._by_session: Dict[str, Set[str]] = {}

    def get(self, key: str) -> Optional[DirEntry]:
        return self._rows.get(key)

    def put(self, d: DirEntry, old: Optional[DirEntry]) -> None:
        if old is not None and old.session:
            s = self._by_session.get(old.session)
            if s is not None:
                s.discard(d.key)
                if not s:
                    del self._by_session[old.session]
        if d.key not in self._rows:
            bisect.insort(self._keys, d.key)
        self._rows[d.key] = d
        if d.session:
            self._by_session.setdefault(d.session, set()).add(d.key)

    def pop(self, key: str) -> Optional[DirEntry]:
        ent = self._rows.pop(key, None)
        if ent is None:
            return None
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            del self._keys[i]
        if ent.session:
            s = self._by_session.get(ent.session)
            if s is not None:
                s.discard(key)
                if not s:
                    del self._by_session[ent.session]
        return ent

    def prefix_keys(self, prefix: str) -> List[str]:
        if not prefix:
            return list(self._keys)
        lo = bisect.bisect_left(self._keys, prefix)
        hi = lo
        # Forward scan, not a synthetic upper-bound key: a sentinel char
        # would exclude keys whose next char sorts above it.
        while hi < len(self._keys) and self._keys[hi].startswith(prefix):
            hi += 1
        return self._keys[lo:hi]

    def items(self, prefix: str = "") -> Iterator[Tuple[str, DirEntry]]:
        for k in self.prefix_keys(prefix):
            yield k, self._rows[k]

    def session_keys(self, sid: str) -> List[str]:
        return sorted(self._by_session.get(sid, ()))

    def close(self) -> None:
        pass


class NativeKVTable:
    """Rows in the C++ mmap MVCC store (the LMDB role)."""

    _ROW = b"k:"
    _SIDX = b"x:"

    def __init__(self, directory: str) -> None:
        from consul_tpu.native.store import NativeStore
        # Fresh each boot, like the reference's temp-dir LMDB: state is
        # an FSM product, never read back from a previous run's file.
        if os.path.isdir(directory):
            shutil.rmtree(directory, ignore_errors=True)
        os.makedirs(directory, exist_ok=True)
        self._store = NativeStore(os.path.join(directory, "kv.cstore"))

    @staticmethod
    def _encode(d: DirEntry) -> bytes:
        return msgpack.packb(d.to_wire(), use_bin_type=True)

    @staticmethod
    def _decode(raw: bytes) -> DirEntry:
        return DirEntry.from_wire(
            msgpack.unpackb(raw, raw=False, strict_map_key=False))

    def get(self, key: str) -> Optional[DirEntry]:
        raw = self._store.get(self._ROW + key.encode())
        return self._decode(raw) if raw is not None else None

    def put(self, d: DirEntry, old: Optional[DirEntry]) -> None:
        kb = d.key.encode()
        if old is not None and old.session and old.session != d.session:
            self._store.delete(
                self._SIDX + old.session.encode() + b"\x00" + kb)
        self._store.put(self._ROW + kb, self._encode(d))
        if d.session:
            self._store.put(
                self._SIDX + d.session.encode() + b"\x00" + kb, b"")

    def pop(self, key: str) -> Optional[DirEntry]:
        kb = key.encode()
        raw = self._store.get(self._ROW + kb)
        if raw is None:
            return None
        ent = self._decode(raw)
        self._store.delete(self._ROW + kb)
        if ent.session:
            self._store.delete(
                self._SIDX + ent.session.encode() + b"\x00" + kb)
        return ent

    def prefix_keys(self, prefix: str) -> List[str]:
        pre = self._ROW + prefix.encode()
        return [k[len(self._ROW):].decode()
                for k, _ in self._store.scan(pre)]

    def items(self, prefix: str = "") -> Iterator[Tuple[str, DirEntry]]:
        pre = self._ROW + prefix.encode()
        for k, v in self._store.scan(pre):
            yield k[len(self._ROW):].decode(), self._decode(v)

    def session_keys(self, sid: str) -> List[str]:
        pre = self._SIDX + sid.encode() + b"\x00"
        return [k[len(pre):].decode() for k, _ in self._store.scan(pre)]

    def close(self) -> None:
        self._store.close()
