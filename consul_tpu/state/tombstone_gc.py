"""Tombstone garbage collection.

Parity target: ``consul/tombstone_gc.go:22-150`` — KV-delete tombstones
must eventually be reaped or storage grows without bound, but reaping is
a Raft write (TombstoneReap, consul/leader.go:553-566), so expiry is
batched into granularity buckets to bound the number of Raft entries.
Only the leader arms timers (SetEnabled, leader.go:126-131).

Departure: the reference arms one ``time.AfterFunc`` per bucket; our
host plane is an asyncio loop, so the GC exposes ``next_deadline()`` /
``collect(now)`` and the leader loop owns the single timer — same
batching semantics, one fewer concurrency primitive, and fully
deterministic under test clocks.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

DEFAULT_TOMBSTONE_TTL = 15 * 60.0  # consul/config.go:257
DEFAULT_GRANULARITY = 30.0         # consul/config.go:258


class TombstoneGC:
    def __init__(self, ttl: float = DEFAULT_TOMBSTONE_TTL,
                 granularity: float = DEFAULT_GRANULARITY) -> None:
        if ttl <= 0 or granularity <= 0:
            raise ValueError("TTL and granularity must be positive")
        self.ttl = ttl
        self.granularity = granularity
        self._enabled = False
        # bucket expiry time -> highest index hinted into that bucket
        self._buckets: Dict[float, int] = {}

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool, now: float) -> None:
        """Leader gate (tombstone_gc.go:49-63): disabling drops all
        pending buckets — the next leader re-hints via fresh deletes and
        the periodic reap catches strays."""
        if enabled == self._enabled:
            return
        self._enabled = enabled
        if not enabled:
            self._buckets.clear()

    def hint(self, index: int, now: float) -> None:
        """Record that ``index`` contains tombstones needing expiry
        (tombstone_gc.go:65-95): rounded up to the granularity bucket."""
        if not self._enabled:
            return
        expires = self._bucket_time(now)
        cur = self._buckets.get(expires, 0)
        if index > cur:
            self._buckets[expires] = index

    def next_deadline(self, now: float) -> Optional[float]:
        if not self._buckets:
            return None
        return min(self._buckets)

    def collect(self, now: float) -> List[int]:
        """Expired bucket indexes, each destined for one TombstoneReap
        Raft entry (leader.go:553-566)."""
        due = sorted(t for t in self._buckets if t <= now)
        return [self._buckets.pop(t) for t in due]

    def pending_expiration(self) -> bool:
        return bool(self._buckets)

    def _bucket_time(self, now: float) -> float:
        expires = now + self.ttl
        return math.ceil(expires / self.granularity) * self.granularity
