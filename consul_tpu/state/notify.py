"""Watch notification: pure predicates + host-side notify plumbing.

Parity target: ``consul/notify.go`` — NotifyGroup lets blocking queries
register a wakeup, mutations fire every registered wakeup exactly once
and clear the registry (notify.go:11-55: non-blocking channel send, then
the waiter re-registers on its next loop iteration).

PR 11 splits the watch machinery into two layers so the device twin
(state/device_store.py) can share the *decision* logic without touching
the *wakeup* logic:

- **Pure predicates** (`WatchPredicate`, `StoreMutation`, `match_batch`):
  side-effect-free evaluation of "does this mutation fire this watch".
  This is the host oracle the device watch matcher is cross-validated
  against, and the fallback evaluator for watches the device encoding
  can't carry (keys longer than its hash window).
- **Plumbing** (`NotifyGroup`, `KVWatchSet`): waiter registries and the
  radix-backed KV prefix watch table, moved here from store.py so the
  store mutates state and *describes* what changed, while firing is one
  pluggable step (host walk today, device bitmask when a bridge is
  attached).

The waiter handle is anything with a ``set()`` method: ``threading.Event``
for synchronous callers, or an adapter around ``asyncio.Event`` supplied
by the RPC layer (which routes the set through its event loop).

KV watch semantics (reference notifyKV, state_store.go:463-491) are
*symmetric-prefix*: a watch registered at ``w`` fires for a mutation at
``path`` iff ``path.startswith(w)``, and a prefix mutation (delete-tree
at ``path``) additionally fires any strictly-longer ``w`` with
``w.startswith(path)``. Registration does not distinguish "key" from
"prefix" watches — the kinds below exist so encoders/observability can
tell intent apart; KIND_KEY and KIND_PREFIX match identically, exactly
like the host radix walk treats them.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Protocol, Sequence, Set, Tuple

from consul_tpu.state.radix import RadixTree

# Predicate kinds. KEY and PREFIX share the symmetric-prefix rule (see
# module docstring); TABLE fires on whole-table mutations only.
KIND_KEY = 0
KIND_PREFIX = 1
KIND_TABLE = 2


@dataclasses.dataclass(frozen=True)
class StoreMutation:
    """One watch-relevant event, as the store would have notified it.

    ``kv=True``: a KV mutation at ``path`` (``prefix=True`` when it was a
    delete-tree covering everything under ``path``). ``kv=False``: a
    table mutation; ``path`` holds the table name. ``index`` is the raft
    index that produced it.
    """

    path: str
    index: int
    kv: bool = True
    prefix: bool = False


@dataclasses.dataclass(frozen=True)
class WatchPredicate:
    """Pure watch predicate: (kind, value, min_index).

    ``min_index`` mirrors blocking_query's MinQueryIndex: a mutation only
    *usefully* wakes a watcher when its index advanced past it. The host
    NotifyGroup plumbing registers with min_index=0 (it wakes on any
    covered mutation and lets the query re-check), and the device matcher
    honors whatever the encoder supplied.
    """

    kind: int
    value: str
    min_index: int = 0

    def matches(self, m: StoreMutation) -> bool:
        if m.index <= self.min_index:
            return False
        if self.kind == KIND_TABLE:
            return (not m.kv) and m.path == self.value
        if not m.kv:
            return False
        if m.path.startswith(self.value):
            return True
        return (m.prefix and len(self.value) > len(m.path)
                and self.value.startswith(m.path))


def match_batch(predicates: Sequence[WatchPredicate],
                mutations: Iterable[StoreMutation]) -> Set[int]:
    """Host reference evaluator: indices of predicates fired by any
    mutation in the batch. This is the oracle the device watch matcher
    is cross-validated against (bit-identical fired sets)."""
    fired: Set[int] = set()
    muts = list(mutations)
    for i, p in enumerate(predicates):
        for m in muts:
            if p.matches(m):
                fired.add(i)
                break
    return fired


class Waiter(Protocol):
    def set(self) -> None: ...


class NotifyGroup:
    def __init__(self) -> None:
        self._waiters: Set[Waiter] = set()

    def wait(self, w: Waiter) -> None:
        """Register ``w`` for the next notify (reference Wait: notify.go:30)."""
        self._waiters.add(w)

    def clear(self, w: Waiter) -> None:
        """Deregister without waiting (reference Clear: notify.go:40)."""
        self._waiters.discard(w)

    def notify(self) -> None:
        """Wake everyone registered, exactly once (notify.go:15-27)."""
        waiters, self._waiters = self._waiters, set()
        for w in waiters:
            w.set()

    def __len__(self) -> int:
        return len(self._waiters)


class KVWatchSet:
    """Radix-backed prefix → NotifyGroup registry (the KV half of the
    reference's state-store watch plumbing, moved out of store.py).

    ``version`` bumps whenever the *set of registered prefixes* changes
    (not on waiter churn within a group) — the device bridge uses it to
    know when its padded watch arrays are stale.
    """

    def __init__(self) -> None:
        self._tree = RadixTree()  # prefix -> NotifyGroup
        self.version = 0

    def watch(self, prefix: str, waiter: Waiter) -> None:
        grp = self._tree.get(prefix)
        if grp is None:
            grp = NotifyGroup()
            self._tree.insert(prefix, grp)
            self.version += 1
        grp.wait(waiter)

    def stop(self, prefix: str, waiter: Waiter) -> None:
        grp = self._tree.get(prefix)
        if grp is not None:
            grp.clear(waiter)
            if len(grp) == 0:
                self._tree.delete(prefix)
                self.version += 1

    def matched(self, path: str, prefix: bool) -> List[Tuple[str, NotifyGroup]]:
        """Groups the reference walk would notify for this mutation —
        pure lookup, nothing fired (reference notifyKV's match set,
        state_store.go:463-477)."""
        out = list(self._tree.walk_path(path))
        if prefix:
            out += [(p, g) for p, g in self._tree.walk_prefix(path)
                    if len(p) > len(path)]
        return out

    def notify(self, path: str, prefix: bool) -> None:
        """Walk + fire + prune (reference notifyKV, state_store.go:463-491)."""
        self.notify_groups(self.matched(path, prefix))

    def notify_groups(self, groups: Iterable[Tuple[str, NotifyGroup]]) -> None:
        """Fire pre-matched groups, pruning ones left empty (reference
        toDelete loop, state_store.go:478-489). The device bridge feeds
        this from its fired-watcher bitmask."""
        for p, g in groups:
            g.notify()
            if len(g) == 0 and self._tree.get(p) is g:
                self._tree.delete(p)
                self.version += 1

    def registered(self) -> List[Tuple[str, NotifyGroup]]:
        """All live (prefix, group) pairs — the device bridge encodes
        these into its padded watch arrays."""
        return list(self._tree.walk_prefix(""))

    def group(self, prefix: str) -> "NotifyGroup | None":
        return self._tree.get(prefix)

    def __len__(self) -> int:
        return len(self.registered())
