"""Watch notification groups.

Parity target: ``consul/notify.go`` — NotifyGroup lets blocking queries
register a wakeup, mutations fire every registered wakeup exactly once
and clear the registry (notify.go:11-55: non-blocking channel send, then
the waiter re-registers on its next loop iteration).

The waiter handle is anything with a ``set()`` method: ``threading.Event``
for synchronous callers, or an adapter around ``asyncio.Event`` supplied
by the RPC layer (which routes the set through its event loop).
"""

from __future__ import annotations

from typing import Protocol, Set


class Waiter(Protocol):
    def set(self) -> None: ...


class NotifyGroup:
    def __init__(self) -> None:
        self._waiters: Set[Waiter] = set()

    def wait(self, w: Waiter) -> None:
        """Register ``w`` for the next notify (reference Wait: notify.go:30)."""
        self._waiters.add(w)

    def clear(self, w: Waiter) -> None:
        """Deregister without waiting (reference Clear: notify.go:40)."""
        self._waiters.discard(w)

    def notify(self) -> None:
        """Wake everyone registered, exactly once (notify.go:15-27)."""
        waiters, self._waiters = self._waiters, set()
        for w in waiters:
            w.set()

    def __len__(self) -> int:
        return len(self._waiters)
