"""Character-trie with path/prefix walks.

Parity target: ``armon/go-radix`` as used by the reference for KV prefix
watches (``consul/state_store.go:432-491``) and ACL longest-prefix rule
evaluation (``acl/acl.go:37-127``).  A plain character trie (no edge
compression) keeps every operation O(len(key)) with trivially correct
walks; the watch and ACL sets it holds are small (hundreds), so the
compressed-edge memory optimization of go-radix buys nothing here.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Tuple

_SENTINEL = object()


class _TrieNode:
    __slots__ = ("children", "value")

    def __init__(self) -> None:
        self.children: Dict[str, "_TrieNode"] = {}
        self.value: Any = _SENTINEL


class RadixTree:
    """Insert/get/delete plus the two walks the state store needs:

    - walk_path(key): visit every entry whose key is a prefix of ``key``
      (go-radix WalkPath — used to notify watchers above a changed key).
    - walk_prefix(prefix): visit every entry whose key starts with
      ``prefix`` (go-radix WalkPrefix — used to notify watchers below a
      deleted tree), and for ACL longest-prefix matching.
    """

    def __init__(self) -> None:
        self._root = _TrieNode()
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def insert(self, key: str, value: Any) -> Optional[Any]:
        node = self._root
        for ch in key:
            nxt = node.children.get(ch)
            if nxt is None:
                nxt = _TrieNode()
                node.children[ch] = nxt
            node = nxt
        old = node.value
        node.value = value
        if old is _SENTINEL:
            self._size += 1
            return None
        return old

    def get(self, key: str) -> Optional[Any]:
        node = self._find(key)
        if node is None or node.value is _SENTINEL:
            return None
        return node.value

    def delete(self, key: str) -> bool:
        # Track the path for pruning empty branches on the way back.
        path = [(None, self._root)]
        node = self._root
        for ch in key:
            nxt = node.children.get(ch)
            if nxt is None:
                return False
            path.append((ch, nxt))
            node = nxt
        if node.value is _SENTINEL:
            return False
        node.value = _SENTINEL
        self._size -= 1
        for i in range(len(path) - 1, 0, -1):
            ch, nd = path[i]
            if nd.children or nd.value is not _SENTINEL:
                break
            del path[i - 1][1].children[ch]
        return True

    def _find(self, key: str) -> Optional[_TrieNode]:
        node = self._root
        for ch in key:
            node = node.children.get(ch)
            if node is None:
                return None
        return node

    def walk_path(self, key: str) -> Iterator[Tuple[str, Any]]:
        """Yield (prefix, value) for every stored key that prefixes ``key``."""
        node = self._root
        if node.value is not _SENTINEL:
            yield "", node.value
        acc = []
        for ch in key:
            node = node.children.get(ch)
            if node is None:
                return
            acc.append(ch)
            if node.value is not _SENTINEL:
                yield "".join(acc), node.value

    def walk_prefix(self, prefix: str) -> Iterator[Tuple[str, Any]]:
        """Yield (key, value) for every stored key starting with ``prefix``."""
        node = self._find(prefix)
        if node is None:
            return
        stack = [(prefix, node)]
        while stack:
            key, nd = stack.pop()
            if nd.value is not _SENTINEL:
                yield key, nd.value
            for ch, child in nd.children.items():
                stack.append((key + ch, child))

    def longest_prefix(self, key: str) -> Optional[Tuple[str, Any]]:
        """The longest stored key that is a prefix of ``key`` (ACL rules)."""
        best = None
        for item in self.walk_path(key):
            best = item
        return best
