"""The strongly-consistent state store of the host plane.

Parity target: ``consul/state_store.go`` (2140 LoC) + ``consul/mdb_table.go``
in the reference — eight tables (nodes, services, checks, kvs, tombstones,
sessions, session_checks, acls), per-table last-modified indexes feeding
blocking queries, table-level NotifyGroups plus a radix-tree KV prefix
watch, KV tombstones, Chubby-style lock delays, and the session
invalidation cascades that encode the split-brain protections.

Design departure from the reference: the reference stores rows in LMDB
(cgo) for MVCC reader/writer isolation across goroutines; durability
always comes from the Raft log above, not the store (state_store.go:190-196
opens LMDB with NOSYNC).  Our host plane is a single-threaded asyncio
event loop, so isolation is by construction and the natural store is
in-process dicts plus sorted key arrays for range scans.  The interface
is kept narrow and transactional-looking so the planned C++ mmap MVCC
store (SURVEY.md §2.1) can drop in underneath.

Determinism contract (enforced by scripts/verify_no_uuid — the reference's
guard, Makefile:37): methods taking an ``index`` are called from the
replicated apply path and must derive *all* state from their arguments.
Wall-clock is only read for lock-delay bookkeeping, which the reference
also keeps node-local and out of the replicated state (KVSLockDelay is
checked on the leader, kvs_endpoint.go:52-61).
"""

from __future__ import annotations

import bisect
import contextlib
import dataclasses
import time
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from consul_tpu.state.notify import KVWatchSet, NotifyGroup, Waiter
from consul_tpu.structs.structs import (
    ACL,
    CheckServiceNode,
    DirEntry,
    HEALTH_CRITICAL,
    HealthCheck,
    Node,
    NodeService,
    RegisterRequest,
    SESSION_BEHAVIOR_DELETE,
    SESSION_BEHAVIOR_RELEASE,
    ServiceNode,
    Session,
)

MAX_LOCK_DELAY = 60.0  # seconds (reference structs.MaxLockDelay)

TABLE_NODES = "nodes"
TABLE_SERVICES = "services"
TABLE_CHECKS = "checks"
TABLE_KVS = "kvs"
TABLE_TOMBSTONES = "tombstones"
TABLE_SESSIONS = "sessions"
TABLE_ACLS = "acls"

# Which tables a named query watches (reference: state_store.go:397-413).
QUERY_TABLES: Dict[str, Tuple[str, ...]] = {
    "Nodes": (TABLE_NODES,),
    "Services": (TABLE_SERVICES,),
    "ServiceNodes": (TABLE_NODES, TABLE_SERVICES),
    "NodeServices": (TABLE_NODES, TABLE_SERVICES),
    "ChecksInState": (TABLE_CHECKS,),
    "NodeChecks": (TABLE_CHECKS,),
    "ServiceChecks": (TABLE_CHECKS,),
    "CheckServiceNodes": (TABLE_NODES, TABLE_SERVICES, TABLE_CHECKS),
    "NodeInfo": (TABLE_NODES, TABLE_SERVICES, TABLE_CHECKS),
    "NodeDump": (TABLE_NODES, TABLE_SERVICES, TABLE_CHECKS),
    "SessionGet": (TABLE_SESSIONS,),
    "SessionList": (TABLE_SESSIONS,),
    "NodeSessions": (TABLE_SESSIONS,),
    "ACLGet": (TABLE_ACLS,),
    "ACLList": (TABLE_ACLS,),
}


class StateStoreError(Exception):
    pass


class _SortedKeys:
    """Sorted key array giving O(log n) prefix range scans (the role LMDB's
    B-tree 'id_prefix' virtual index plays at mdb_table.go:283-288)."""

    def __init__(self) -> None:
        self._keys: List[str] = []

    def add(self, key: str) -> None:
        i = bisect.bisect_left(self._keys, key)
        if i >= len(self._keys) or self._keys[i] != key:
            self._keys.insert(i, key)

    def remove(self, key: str) -> None:
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            del self._keys[i]

    def prefix_range(self, prefix: str) -> List[str]:
        if not prefix:
            return list(self._keys)
        lo = bisect.bisect_left(self._keys, prefix)
        hi = lo
        # Forward scan instead of a synthetic upper-bound key: appending a
        # sentinel char excludes keys whose next char sorts above it
        # (e.g. astral code points), and we touch every match anyway.
        while hi < len(self._keys) and self._keys[hi].startswith(prefix):
            hi += 1
        return self._keys[lo:hi]


class ApplyCapture:
    """Record of one committed-entry batch (PR 11, device state store).

    ``kv_ops`` carries per-key row mutations for the device table scatter
    — op, key, index, plus the host's verdict (existed/old_index) that
    the device apply must reproduce to stay in lockstep. ``notifies``
    carries the watch events exactly as the sequential path would have
    fired them, in order.
    """

    __slots__ = ("kv_ops", "notifies", "consumed")

    def __init__(self) -> None:
        # ("set", key, index, old_index, existed, flags, value) |
        # ("del", key, index, old_index)
        self.kv_ops: List[tuple] = []
        # ("kv", path, prefix, index) | ("table", table, index)
        self.notifies: List[tuple] = []
        self.consumed = False

    def note_kv(self, path: str, prefix: bool, index: int) -> None:
        self.notifies.append(("kv", path, prefix, index))

    def note_table(self, table: str, index: int) -> None:
        self.notifies.append(("table", table, index))

    def note_set(self, key: str, index: int, old_index: int, existed: bool,
                 flags: int, value: bytes) -> None:
        self.kv_ops.append(("set", key, index, old_index, existed, flags, value))

    def note_del(self, key: str, index: int, old_index: int) -> None:
        self.kv_ops.append(("del", key, index, old_index))


class StateStore:
    def __init__(self, gc_hint: Optional[Callable[[int], None]] = None,
                 kv_backend: Optional[object] = None) -> None:
        # nodes: name -> Node
        self._nodes: Dict[str, Node] = {}
        # services: (node, service_id) -> ServiceNode
        self._services: Dict[Tuple[str, str], ServiceNode] = {}
        # checks: (node, check_id) -> HealthCheck
        self._checks: Dict[Tuple[str, str], HealthCheck] = {}
        # kvs rows live behind a pluggable table backend: in-process
        # dicts (default) or the C++ mmap MVCC store (the LMDB role) —
        # see state/kvtable.py for the durability rationale.
        if kv_backend is None:
            from consul_tpu.state.kvtable import DictKVTable
            kv_backend = DictKVTable()
        self._kv = kv_backend
        # tombstones: key -> DirEntry with cleared value
        self._tombstones: Dict[str, DirEntry] = {}
        self._tombstone_keys = _SortedKeys()
        # sessions: id -> Session; session_checks: (node, check_id) -> {session}
        self._sessions: Dict[str, Session] = {}
        self._session_checks: Dict[Tuple[str, str], Set[str]] = {}
        # acls: id -> ACL
        self._acls: Dict[str, ACL] = {}

        self._last_index: Dict[str, int] = {
            t: 0 for t in (TABLE_NODES, TABLE_SERVICES, TABLE_CHECKS, TABLE_KVS,
                           TABLE_TOMBSTONES, TABLE_SESSIONS, TABLE_ACLS)
        }
        self._watch: Dict[str, NotifyGroup] = {t: NotifyGroup() for t in self._last_index}
        self._kv_watch = KVWatchSet()  # prefix -> NotifyGroup plumbing
        # key -> monotonic expiry of the anti-split-brain lock delay
        self._lock_delay: Dict[str, float] = {}
        self._gc_hint = gc_hint
        # Active ApplyCapture while an apply-batch scope is open (PR 11):
        # mutation methods record what changed instead of firing watches;
        # the scope exit (or the device bridge) fires them in one pass.
        self._capture: Optional[ApplyCapture] = None

    # -- index / watch plumbing -------------------------------------------

    def last_index(self, *tables: str) -> int:
        return max(self._last_index[t] for t in tables)

    def query_tables(self, q: str) -> Tuple[str, ...]:
        return QUERY_TABLES[q]

    def watch(self, tables: Iterable[str], waiter: Waiter) -> None:
        for t in tables:
            self._watch[t].wait(waiter)

    def stop_watch(self, tables: Iterable[str], waiter: Waiter) -> None:
        for t in tables:
            self._watch[t].clear(waiter)

    def watch_kv(self, prefix: str, waiter: Waiter) -> None:
        self._kv_watch.watch(prefix, waiter)

    def stop_watch_kv(self, prefix: str, waiter: Waiter) -> None:
        self._kv_watch.stop(prefix, waiter)

    def _notify(self, table: str) -> None:
        if self._capture is not None:
            self._capture.note_table(table, self._last_index[table])
            return
        self._watch[table].notify()

    def _notify_kv(self, path: str, prefix: bool,
                   index: Optional[int] = None) -> None:
        """Wake watchers whose registered prefix covers ``path``
        (reference notifyKV, state_store.go:463-491). Inside an
        apply-batch scope the event is recorded instead; the scope exit
        replays it through the same KVWatchSet walk (or the device
        bridge fires from its bitmask)."""
        if self._capture is not None:
            if index is None:
                index = self._last_index[TABLE_KVS]
            self._capture.note_kv(path, prefix, index)
            return
        self._kv_watch.notify(path, prefix)

    @contextlib.contextmanager
    def capture_apply(self):
        """Scope for one committed-entry batch: watch firing is deferred
        and per-key KV ops are recorded for the device store. Safe only
        because the replicated apply path is synchronous — no waiter can
        run (and so none can re-register) between the mutations and the
        deferred fire, making deferred firing observably identical to
        the reference's fire-per-mutation ordering.

        On exit the capture is flushed through the host walk unless a
        device bridge already consumed it (``cap.consumed = True``).
        """
        prev, self._capture = self._capture, ApplyCapture()
        cap = self._capture
        try:
            yield cap
        finally:
            self._capture = prev
            if not cap.consumed:
                self.flush_capture(cap)

    def flush_capture(self, cap: "ApplyCapture") -> None:
        """Fire deferred notifies exactly as the sequential path would
        have (same events, same order, same prune semantics)."""
        for ev in cap.notifies:
            if ev[0] == "kv":
                self._kv_watch.notify(ev[1], ev[2])
            else:
                self._watch[ev[1]].notify()

    # -- catalog: nodes / services / checks --------------------------------

    def ensure_registration(self, index: int, req: RegisterRequest) -> None:
        """Atomic node+service+check(s) upsert (state_store.go:499-534).
        The reference aborts the whole LMDB txn on any failure; we get the
        same all-or-nothing by validating every piece before mutating."""
        self._validate_registration(req)
        self._ensure_node(index, Node(node=req.node, address=req.address))
        if req.service is not None:
            self._ensure_service(index, req.node, req.service)
        if req.check is not None:
            self._ensure_check(index, req.check)
        for check in req.checks:
            self._ensure_check(index, check)

    def _validate_registration(self, req: RegisterRequest) -> None:
        svc_ids = {req.service.id} if req.service is not None else set()
        checks = list(req.checks) + ([req.check] if req.check is not None else [])
        for check in checks:
            if check.node and check.node != req.node:
                # Reference keys checks by (node, id); a check for another
                # node would need that node registered already.
                if check.node not in self._nodes:
                    raise StateStoreError("Missing node registration")
            if check.service_id and check.service_id not in svc_ids and \
                    (req.node, check.service_id) not in self._services:
                raise StateStoreError("Missing service registration")

    def ensure_node(self, index: int, node: Node) -> None:
        self._ensure_node(index, node)

    def _ensure_node(self, index: int, node: Node) -> None:
        self._nodes[node.node] = dataclasses.replace(node)
        self._last_index[TABLE_NODES] = index
        self._notify(TABLE_NODES)

    def get_node(self, name: str) -> Tuple[int, Optional[str]]:
        n = self._nodes.get(name)
        return self._last_index[TABLE_NODES], (n.address if n else None)

    def nodes(self) -> Tuple[int, List[Node]]:
        return self._last_index[TABLE_NODES], [
            dataclasses.replace(n)
            for n in sorted(self._nodes.values(), key=lambda n: n.node)]

    def ensure_service(self, index: int, node: str, ns: NodeService) -> None:
        self._ensure_service(index, node, ns)

    def _ensure_service(self, index: int, node: str, ns: NodeService) -> None:
        if node not in self._nodes:
            raise StateStoreError("Missing node registration")
        self._services[(node, ns.id)] = ServiceNode(
            node=node, service_id=ns.id, service_name=ns.service,
            service_tags=list(ns.tags), service_address=ns.address,
            service_port=ns.port)
        self._last_index[TABLE_SERVICES] = index
        self._notify(TABLE_SERVICES)

    def node_services(self, name: str) -> Tuple[int, Optional[Dict[str, NodeService]]]:
        idx = self.last_index(TABLE_NODES, TABLE_SERVICES)
        node = self._nodes.get(name)
        if node is None:
            return idx, None
        out: Dict[str, NodeService] = {}
        for (n, sid), sn in self._services.items():
            if n == name:
                out[sid] = _to_node_service(sn)
        return idx, out

    def services(self) -> Tuple[int, Dict[str, List[str]]]:
        """service name -> union of tags (state_store.go:772-795)."""
        out: Dict[str, List[str]] = {}
        for sn in self._services.values():
            tags = out.setdefault(sn.service_name, [])
            for t in sn.service_tags:
                if t not in tags:
                    tags.append(t)
        return self._last_index[TABLE_SERVICES], out

    def service_nodes(self, service: str, tag: str = "") -> Tuple[int, List[ServiceNode]]:
        idx = self.last_index(TABLE_NODES, TABLE_SERVICES)
        out = []
        for sn in sorted(self._services.values(), key=lambda s: (s.node, s.service_id)):
            if sn.service_name != service:
                continue
            if tag and tag not in sn.service_tags:
                continue
            node = self._nodes.get(sn.node)
            out.append(ServiceNode(
                node=sn.node, address=node.address if node else "",
                service_id=sn.service_id, service_name=sn.service_name,
                service_tags=list(sn.service_tags),
                service_address=sn.service_address, service_port=sn.service_port))
        return idx, out

    def delete_node_service(self, index: int, node: str, service_id: str) -> None:
        """Remove one service and its checks (state_store.go:692-730)."""
        if self._services.pop((node, service_id), None) is not None:
            self._last_index[TABLE_SERVICES] = index
            self._notify(TABLE_SERVICES)
        victims = [k for k, c in self._checks.items()
                   if k[0] == node and c.service_id == service_id]
        for key in victims:
            self._invalidate_check(index, key[0], key[1])
        if victims:
            for key in victims:
                del self._checks[key]
            self._last_index[TABLE_CHECKS] = index
            self._notify(TABLE_CHECKS)

    def delete_node(self, index: int, node: str) -> None:
        """Remove a node, all its services/checks, and invalidate its
        sessions (state_store.go:732-770)."""
        self._invalidate_node(index, node)
        svc = [k for k in self._services if k[0] == node]
        for key in svc:
            del self._services[key]
        if svc:
            self._last_index[TABLE_SERVICES] = index
            self._notify(TABLE_SERVICES)
        chk = [k for k in self._checks if k[0] == node]
        for key in chk:
            del self._checks[key]
        if chk:
            self._last_index[TABLE_CHECKS] = index
            self._notify(TABLE_CHECKS)
        if self._nodes.pop(node, None) is not None:
            self._last_index[TABLE_NODES] = index
            self._notify(TABLE_NODES)

    def ensure_check(self, index: int, check: HealthCheck) -> None:
        self._ensure_check(index, check)

    def _ensure_check(self, index: int, check: HealthCheck) -> None:
        """Upsert a check; critical status invalidates dependent sessions
        (state_store.go:887-934)."""
        check = dataclasses.replace(check)
        if not check.status:
            check.status = HEALTH_CRITICAL
        if check.node not in self._nodes:
            raise StateStoreError("Missing node registration")
        if check.service_id:
            sn = self._services.get((check.node, check.service_id))
            if sn is None:
                raise StateStoreError("Missing service registration")
            check.service_name = sn.service_name
        if check.status == HEALTH_CRITICAL:
            self._invalidate_check(index, check.node, check.check_id)
        self._checks[(check.node, check.check_id)] = check
        self._last_index[TABLE_CHECKS] = index
        self._notify(TABLE_CHECKS)

    def delete_node_check(self, index: int, node: str, check_id: str) -> None:
        self._invalidate_check(index, node, check_id)
        if self._checks.pop((node, check_id), None) is not None:
            self._last_index[TABLE_CHECKS] = index
            self._notify(TABLE_CHECKS)

    def node_checks(self, node: str) -> Tuple[int, List[HealthCheck]]:
        return self._last_index[TABLE_CHECKS], [
            dataclasses.replace(c) for c in sorted(
                (c for k, c in self._checks.items() if k[0] == node),
                key=lambda c: c.check_id)]

    def service_checks(self, service: str) -> Tuple[int, List[HealthCheck]]:
        return self._last_index[TABLE_CHECKS], [
            dataclasses.replace(c) for c in sorted(
                (c for c in self._checks.values() if c.service_name == service),
                key=lambda c: (c.node, c.check_id))]

    def checks_in_state(self, state: str) -> Tuple[int, List[HealthCheck]]:
        from consul_tpu.structs.structs import HEALTH_ANY
        return self._last_index[TABLE_CHECKS], [
            dataclasses.replace(c) for c in sorted(
                (c for c in self._checks.values()
                 if state == HEALTH_ANY or c.status == state),
                key=lambda c: (c.node, c.check_id))]

    def check_service_nodes(self, service: str, tag: str = "") -> Tuple[int, List[CheckServiceNode]]:
        """Join of nodes, service instances, and their checks + node-level
        checks (state_store.go:998-1076)."""
        idx = self.last_index(TABLE_NODES, TABLE_SERVICES, TABLE_CHECKS)
        out = []
        for sn in sorted(self._services.values(), key=lambda s: (s.node, s.service_id)):
            if sn.service_name != service:
                continue
            if tag and tag not in sn.service_tags:
                continue
            node = self._nodes.get(sn.node)
            if node is None:
                continue
            checks = [dataclasses.replace(c) for k, c in sorted(self._checks.items())
                      if k[0] == sn.node and c.service_id in ("", sn.service_id)]
            out.append(CheckServiceNode(
                node=dataclasses.replace(node), service=_to_node_service(sn),
                checks=checks))
        return idx, out

    def node_info(self, node: str) -> Tuple[int, List[dict]]:
        idx = self.last_index(TABLE_NODES, TABLE_SERVICES, TABLE_CHECKS)
        n = self._nodes.get(node)
        if n is None:
            return idx, []
        return idx, [self._dump_one(n)]

    def node_dump(self) -> Tuple[int, List[dict]]:
        idx = self.last_index(TABLE_NODES, TABLE_SERVICES, TABLE_CHECKS)
        return idx, [self._dump_one(n)
                     for _, n in sorted(self._nodes.items())]

    def _dump_one(self, n: Node) -> dict:
        return {
            "node": n.node,
            "address": n.address,
            "services": [_to_node_service(sn)
                         for k, sn in sorted(self._services.items()) if k[0] == n.node],
            "checks": [dataclasses.replace(c)
                       for k, c in sorted(self._checks.items()) if k[0] == n.node],
        }

    # -- KV ----------------------------------------------------------------

    def kvs_set(self, index: int, d: DirEntry) -> None:
        self._kvs_set(index, d, mode="set")

    def kvs_check_and_set(self, index: int, d: DirEntry) -> bool:
        return self._kvs_set(index, d, mode="cas")

    def kvs_lock(self, index: int, d: DirEntry) -> bool:
        return self._kvs_set(index, d, mode="lock")

    def kvs_unlock(self, index: int, d: DirEntry) -> bool:
        return self._kvs_set(index, d, mode="unlock")

    def _kvs_set(self, index: int, d: DirEntry, mode: str) -> bool:
        """Reference kvsSet (state_store.go:1469-1564), all four modes."""
        d = d.clone()  # never alias caller-owned structs into the store
        exist = self._kv.get(d.key)

        if mode == "cas":
            # modify_index 0 = set-if-not-exists, else exact match required.
            if d.modify_index == 0 and exist is not None:
                return False
            if d.modify_index > 0 and (exist is None or exist.modify_index != d.modify_index):
                return False

        if mode == "lock":
            if not d.session:
                raise StateStoreError("Missing session")
            if exist is not None and exist.session:
                return False  # already locked
            if d.session not in self._sessions:
                raise StateStoreError("Invalid session")
            d.lock_index = exist.lock_index + 1 if exist is not None else 1

        if mode == "unlock":
            if exist is None or exist.session != d.session:
                return False

        if exist is None:
            d.create_index = index
        else:
            # The caller's entry (with its new value) is what gets stored;
            # lock bookkeeping is inherited per mode (kvsSet's single
            # copy-forward block, state_store.go:1540-1551 — for unlock the
            # session was just cleared on `exist` before that block runs).
            d.create_index = exist.create_index
            if mode in ("set", "cas"):
                d.lock_index = exist.lock_index
                d.session = exist.session
            elif mode == "unlock":
                d.lock_index = exist.lock_index
                d.session = ""
        d.modify_index = index

        self._kv.put(d, old=exist)
        self._last_index[TABLE_KVS] = index
        if self._capture is not None:
            self._capture.note_set(
                d.key, index,
                old_index=exist.modify_index if exist is not None else 0,
                existed=exist is not None, flags=d.flags, value=d.value)
        self._notify_kv(d.key, prefix=False, index=index)
        return True

    def kvs_get(self, key: str) -> Tuple[int, Optional[DirEntry]]:
        idx = max(self._last_index[TABLE_KVS], self._last_index[TABLE_TOMBSTONES])
        ent = self._kv.get(key)
        return idx, ent.clone() if ent is not None else None

    def kvs_list(self, prefix: str) -> Tuple[int, int, List[DirEntry]]:
        """Returns (tombstone_max_index, table_index, entries)
        (state_store.go:1202-1236): the endpoint uses the tombstone index
        to keep blocking list queries advancing after deletes."""
        idx = max(self._last_index[TABLE_KVS], self._last_index[TABLE_TOMBSTONES])
        ents = [ent.clone() for _, ent in self._kv.items(prefix)]
        tomb_idx = 0
        for k in self._tombstone_keys.prefix_range(prefix):
            tomb_idx = max(tomb_idx, self._tombstones[k].modify_index)
        return tomb_idx, idx, ents

    def kvs_list_keys(self, prefix: str, separator: str) -> Tuple[int, List[str]]:
        """Key listing rolled up to ``separator`` (state_store.go:1238-1320)."""
        idx = self._last_index[TABLE_KVS]
        if idx == 0:
            idx = 1  # non-zero so blocking queries can block (ref comment)
        keys: List[str] = []
        max_index = 0
        last = ""
        plen = len(prefix)
        for k, ent in self._kv.items(prefix):
            max_index = max(max_index, ent.modify_index)
            if not separator:
                keys.append(k)
                continue
            pos = k[plen:].find(separator)
            if pos >= 0:
                to_sep = k[: plen + pos + len(separator)]
                if to_sep != last:
                    keys.append(to_sep)
                    last = to_sep
            else:
                keys.append(k)
        for k in self._tombstone_keys.prefix_range(prefix):
            max_index = max(max_index, self._tombstones[k].modify_index)
        return (max_index or idx), keys

    def kvs_delete(self, index: int, key: str) -> None:
        self._kvs_delete(index, [key], notify_prefix=False, notify_path=key)

    def kvs_delete_check_and_set(self, index: int, key: str, cas_index: int) -> bool:
        """Atomic delete-CAS (state_store.go:1327-1361): cas_index 0 means
        delete-if-exists always proceeds."""
        exist = self._kv.get(key)
        if cas_index > 0 and (exist is None or exist.modify_index != cas_index):
            return False
        self._kvs_delete(index, [key] if exist is not None else [],
                         notify_prefix=False, notify_path=key)
        return True

    def kvs_delete_tree(self, index: int, prefix: str) -> None:
        keys = self._kv.prefix_keys(prefix)
        self._kvs_delete(index, keys, notify_prefix=True, notify_path=prefix)

    def _kvs_delete(self, index: int, keys: List[str], notify_prefix: bool,
                    notify_path: str) -> None:
        """Delete + tombstone creation (kvsDeleteWithIndexTxn,
        state_store.go:1384-1441)."""
        deleted = 0
        for key in list(keys):
            ent = self._kv.pop(key)
            if ent is None:
                continue
            deleted += 1
            if self._capture is not None:
                self._capture.note_del(key, index,
                                       old_index=ent.modify_index)
            tomb = ent.clone()
            tomb.modify_index = index
            tomb.value = b""
            tomb.session = ""
            self._tombstones[key] = tomb
            self._tombstone_keys.add(key)
        if deleted:
            self._last_index[TABLE_KVS] = index
            self._last_index[TABLE_TOMBSTONES] = index
            self._notify_kv(notify_path, prefix=notify_prefix, index=index)
            if self._gc_hint is not None:
                self._gc_hint(index)

    def kvs_lock_delay(self, key: str) -> float:
        """Remaining lock-delay in seconds, 0 if none (state_store.go:1461-1467).
        Checked on the leader's clock, never inside the replicated path."""
        exp = self._lock_delay.get(key)
        if exp is None:
            return 0.0
        rem = exp - time.monotonic()
        if rem <= 0:
            del self._lock_delay[key]
            return 0.0
        return rem

    def reap_tombstones(self, index: int) -> None:
        """Drop tombstones with modify_index <= index (state_store.go:1566-1613)."""
        for key in [k for k, t in self._tombstones.items() if t.modify_index <= index]:
            del self._tombstones[key]
            self._tombstone_keys.remove(key)

    # -- sessions ----------------------------------------------------------

    def session_create(self, index: int, session: Session) -> None:
        """Validates node + non-critical checks (state_store.go:1631-1701)."""
        if not session.id:
            raise StateStoreError("Missing Session ID")
        session = dataclasses.replace(session, checks=list(session.checks))
        if not session.behavior:
            session.behavior = SESSION_BEHAVIOR_RELEASE
        elif session.behavior not in (SESSION_BEHAVIOR_RELEASE, SESSION_BEHAVIOR_DELETE):
            raise StateStoreError(
                f"Invalid Session Behavior setting '{session.behavior}'")
        session.create_index = index
        if session.node not in self._nodes:
            raise StateStoreError("Missing node registration")
        for check_id in session.checks:
            chk = self._checks.get((session.node, check_id))
            if chk is None:
                raise StateStoreError(f"Missing check '{check_id}' registration")
            if chk.status == HEALTH_CRITICAL:
                raise StateStoreError(f"Check '{check_id}' is in {chk.status} state")
        self._sessions[session.id] = session
        for check_id in session.checks:
            self._session_checks.setdefault((session.node, check_id), set()).add(session.id)
        self._last_index[TABLE_SESSIONS] = index
        self._notify(TABLE_SESSIONS)

    def session_get(self, sid: str) -> Tuple[int, Optional[Session]]:
        sess = self._sessions.get(sid)
        return self._last_index[TABLE_SESSIONS], (
            dataclasses.replace(sess, checks=list(sess.checks))
            if sess is not None else None)

    def session_list(self) -> Tuple[int, List[Session]]:
        return self._last_index[TABLE_SESSIONS], [
            dataclasses.replace(s, checks=list(s.checks))
            for s in sorted(self._sessions.values(), key=lambda s: s.id)]

    def node_sessions(self, node: str) -> Tuple[int, List[Session]]:
        return self._last_index[TABLE_SESSIONS], [
            dataclasses.replace(s, checks=list(s.checks))
            for s in sorted((s for s in self._sessions.values() if s.node == node),
                            key=lambda s: s.id)]

    def session_destroy(self, index: int, sid: str) -> None:
        self._invalidate_session(index, sid)

    def _invalidate_node(self, index: int, node: str) -> None:
        for sid in [s.id for s in self._sessions.values() if s.node == node]:
            self._invalidate_session(index, sid)

    def _invalidate_check(self, index: int, node: str, check_id: str) -> None:
        for sid in list(self._session_checks.get((node, check_id), ())):
            self._invalidate_session(index, sid)

    def _invalidate_session(self, index: int, sid: str) -> None:
        """Destroy a session and handle its held locks per behavior
        (state_store.go:1820-1869)."""
        session = self._sessions.get(sid)
        if session is None:
            return
        delay = min(session.lock_delay, MAX_LOCK_DELAY)
        if session.behavior == SESSION_BEHAVIOR_DELETE:
            self._delete_locks(index, delay, sid)
        else:
            self._invalidate_locks(index, delay, sid)
        del self._sessions[sid]
        for check_id in session.checks:
            grp = self._session_checks.get((session.node, check_id))
            if grp is not None:
                grp.discard(sid)
                if not grp:
                    del self._session_checks[(session.node, check_id)]
        self._last_index[TABLE_SESSIONS] = index
        self._notify(TABLE_SESSIONS)

    def _held_keys(self, sid: str) -> List[str]:
        return self._kv.session_keys(sid)

    def _invalidate_locks(self, index: int, delay: float, sid: str) -> None:
        """Release-behavior: clear lock holder, arm lock-delay
        (state_store.go:1871-1912)."""
        keys = self._held_keys(sid)
        expires = time.monotonic() + delay if delay > 0 else 0.0
        for key in keys:
            old = self._kv.get(key)
            kv = old.clone()
            kv.session = ""
            kv.modify_index = index
            self._kv.put(kv, old=old)
            if self._capture is not None:
                self._capture.note_set(key, index,
                                       old_index=old.modify_index,
                                       existed=True, flags=kv.flags,
                                       value=kv.value)
            if delay > 0:
                self._lock_delay[key] = expires
            self._notify_kv(key, prefix=False, index=index)
        if keys:
            self._last_index[TABLE_KVS] = index

    def _delete_locks(self, index: int, delay: float, sid: str) -> None:
        """Delete-behavior: remove held keys entirely (state_store.go:1914-1947)."""
        keys = self._held_keys(sid)
        expires = time.monotonic() + delay if delay > 0 else 0.0
        for key in keys:
            self._kvs_delete(index, [key], notify_prefix=False, notify_path=key)
            if delay > 0:
                self._lock_delay[key] = expires

    # -- ACLs --------------------------------------------------------------

    def acl_set(self, index: int, acl: ACL) -> None:
        """Upsert (state_store.go:1949-1993); ID generation happens in the
        endpoint on the leader, never here (determinism contract)."""
        if not acl.id:
            raise StateStoreError("Missing ACL ID")
        acl = dataclasses.replace(acl)
        exist = self._acls.get(acl.id)
        if exist is None:
            acl.create_index = index
        else:
            acl.create_index = exist.create_index
        acl.modify_index = index
        self._acls[acl.id] = acl
        self._last_index[TABLE_ACLS] = index
        self._notify(TABLE_ACLS)

    def acl_get(self, aid: str) -> Tuple[int, Optional[ACL]]:
        acl = self._acls.get(aid)
        return self._last_index[TABLE_ACLS], (
            dataclasses.replace(acl) if acl is not None else None)

    def acl_list(self) -> Tuple[int, List[ACL]]:
        return self._last_index[TABLE_ACLS], [
            dataclasses.replace(a)
            for a in sorted(self._acls.values(), key=lambda a: a.id)]

    def acl_delete(self, index: int, aid: str) -> None:
        if self._acls.pop(aid, None) is not None:
            self._last_index[TABLE_ACLS] = index
            self._notify(TABLE_ACLS)

    # -- snapshot / restore -------------------------------------------------

    def snapshot_records(self):
        """Deterministic stream of (kind, payload) records mirroring the
        FSM snapshot layout (consul/fsm.go:262-404): per-node registration
        with its services and checks, then kvs, tombstones, sessions, acls."""
        for name, node in sorted(self._nodes.items()):
            yield ("registration", RegisterRequest(node=node.node, address=node.address))
            for k, sn in sorted(self._services.items()):
                if k[0] == name:
                    yield ("service", (name, _to_node_service(sn)))
            for k, c in sorted(self._checks.items()):
                if k[0] == name:
                    yield ("check", c)
        for _key, ent in self._kv.items(""):
            yield ("kvs", ent)
        for key in self._tombstone_keys.prefix_range(""):
            yield ("tombstone", self._tombstones[key])
        for sid, sess in sorted(self._sessions.items()):
            yield ("session", sess)
        for aid, acl in sorted(self._acls.items()):
            yield ("acl", acl)

    def close(self) -> None:
        """Release the KV backend (the native table holds an mmap+fd)."""
        self._kv.close()

    def kvs_restore(self, d: DirEntry) -> None:
        d = d.clone()
        self._kv.put(d, old=self._kv.get(d.key))
        self._last_index[TABLE_KVS] = max(self._last_index[TABLE_KVS], d.modify_index)

    def tombstone_restore(self, d: DirEntry) -> None:
        d = d.clone()
        self._tombstones[d.key] = d
        self._tombstone_keys.add(d.key)
        self._last_index[TABLE_TOMBSTONES] = max(
            self._last_index[TABLE_TOMBSTONES], d.modify_index)

    def session_restore(self, session: Session) -> None:
        session = dataclasses.replace(session, checks=list(session.checks))
        self._sessions[session.id] = session
        for check_id in session.checks:
            self._session_checks.setdefault(
                (session.node, check_id), set()).add(session.id)
        self._last_index[TABLE_SESSIONS] = max(
            self._last_index[TABLE_SESSIONS], session.create_index)

    def acl_restore(self, acl: ACL) -> None:
        acl = dataclasses.replace(acl)
        self._acls[acl.id] = acl
        self._last_index[TABLE_ACLS] = max(
            self._last_index[TABLE_ACLS], acl.modify_index)


def _to_node_service(sn: ServiceNode) -> NodeService:
    return NodeService(id=sn.service_id, service=sn.service_name,
                       tags=list(sn.service_tags), address=sn.service_address,
                       port=sn.service_port)
