"""Device-resident state store: batched FSM apply + device watch matching.

The gossip plane put membership on the device (gossip/kernel.py); this
module does the same for the raft/FSM/KV path — ROADMAP item 3, and the
"consensus data path is offloadable" thesis of Network Hardware-
Accelerated Consensus (PAPERS.md). Two jitted entry points over a
device-resident open-addressed key-hash table:

* **Batched apply** (``_build_apply``): one committed-entry batch from
  the FSM (consensus/fsm.py ``apply_batch``) becomes one device
  dispatch — a ``lax.scan`` over the batch (entries in a batch may
  touch the same key, so within-batch order is sequential, exactly like
  the host) scattering insert/update/delete-with-tombstone into the
  table arrays, returning per-entry (existed, old_modify_index)
  verdicts.
* **Batched watch matching** (``_build_match``): the registered watch
  set — padded (kind, key-hash, key-length, min-index) arrays for up to
  10⁵–10⁶ watchers — is evaluated against the batch's mutation events
  in one pass, emitting a fired-watcher bitmask the host NotifyGroup
  plumbing (state/notify.py ``KVWatchSet``) consumes.

Authority and lockstep
----------------------
The host store stays authoritative: the FSM applies each entry to the
host store first (capturing per-key ops and watch events —
``store.ApplyCapture``), then ships the whole batch to the device in one
dispatch. Lockstep is *verified*, continuously: device (existed,
old_index) verdicts must equal the host's observed pre-state, and the
device fired-watcher set must equal the host radix-walk match set —
any difference increments ``consul_store_divergence_total`` (crossval
asserts it stays 0). Wakeups fire the *union* of host and device
verdicts, so a (never-observed) divergence can only produce a spurious
wakeup — harmless, blocking queries re-check their index — never a
missed one. This ordering also resolves delete-tree circularity: the
victim key set depends on pre-state the host already has.

Watch-match semantics (must equal state/notify.py's host walk):
a watch registered at ``w`` fires for a mutation at ``path`` iff
``path.startswith(w)`` — evaluated on device by comparing the hash of
``path``'s first ``len(w)`` bytes (rolling FNV-1a prefix-hash rows
shipped per event) against ``w``'s stored hash. The delete-tree extra
direction (``w.startswith(path)``, strictly longer ``w``) would need
every watch's full prefix-hash matrix ([W, Lmax] memory); tree deletes
are rare, so that one direction is host-walked and unioned in.
Hash matches are two independent 32-bit FNV streams → ~2⁻⁶⁴ false-fire
probability per (watch, event) pair; a false fire is a spurious wakeup,
and the host-union keeps wakeup semantics exact regardless.

Index wrap convention (vet O01): modify/create indexes live on device
as ``uint32`` — raft indexes folded mod 2³². Verdict comparison folds
the host index the same way, and the ``index > min_index`` watch gate
uses plain uint32 compare, which is exact while true indexes stay
below 2³² (~5 days at 10k writes/s before a wrap; the gossip kernel's
counters accept the same convention, gossip/kernel.py).

Keys longer than ``lmax`` bytes can't ride the prefix-hash rows; such
watches go on a host-evaluated fallback list, and events at such paths
still match device watches up to ``lmax`` (the event row carries hashes
for lengths 0..lmax and its true byte length).
"""

from __future__ import annotations

import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from consul_tpu.state.notify import (
    KIND_KEY, KIND_PREFIX, KIND_TABLE, StoreMutation, WatchPredicate,
    match_batch)

# Two independent FNV-1a-style 32-bit streams (second uses different
# offset basis and prime, gossip/ops/feistel.py keeps the same style of
# fixed odd multipliers).
_FNV1_BASIS = np.uint32(2166136261)
_FNV1_PRIME = np.uint32(16777619)
_FNV2_BASIS = np.uint32(0x811C9DC5 ^ 0x5BD1E995)
_FNV2_PRIME = np.uint32(0x01000193 ^ 0x00010146)  # odd → invertible mod 2^32

# Table slot states.
SLOT_EMPTY = 0
SLOT_LIVE = 1
SLOT_TOMB = 2

# Op codes in the batched-apply stream (pad rows are OP_PAD).
OP_SET = 0
OP_DEL = 1
OP_PAD = -1

# Event kinds in the watch-match stream.
EV_KV = 0
EV_TABLE = 1
EV_PAD = -1


def _pow2(n: int, floor: int = 1) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def _encode_keys(keys: Sequence[bytes], lmax: int) -> np.ndarray:
    """[N, lmax] uint32 byte matrix, zero-padded."""
    mat = np.zeros((len(keys), lmax), dtype=np.uint32)
    for i, kb in enumerate(keys):
        kb = kb[:lmax]
        if kb:
            mat[i, : len(kb)] = np.frombuffer(kb, dtype=np.uint8)
    return mat


def _full_hashes(keys: Sequence[bytes], lmax: int
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(h1, h2, byte_len) of each key's first min(len, lmax) bytes —
    vectorized across keys: O(lmax) numpy passes however many keys."""
    lens = np.array([min(len(k), lmax) for k in keys], dtype=np.int32)
    mat = _encode_keys(keys, lmax)
    h1 = np.full(len(keys), _FNV1_BASIS, dtype=np.uint32)
    h2 = np.full(len(keys), _FNV2_BASIS, dtype=np.uint32)
    for j in range(int(lens.max()) if len(keys) else 0):
        act = j < lens
        h1 = np.where(act, (h1 ^ mat[:, j]) * _FNV1_PRIME, h1)
        h2 = np.where(act, (h2 ^ mat[:, j]) * _FNV2_PRIME, h2)
    return h1, h2, lens


def _prefix_hashes(paths: Sequence[bytes], lmax: int
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Rolling prefix hashes: [N, lmax+1] rows where column ``l`` is the
    hash of the path's first ``l`` bytes (frozen once l exceeds the
    path length — guarded by the length compare at match time)."""
    n = len(paths)
    lens = np.array([min(len(p), lmax) for p in paths], dtype=np.int32)
    mat = _encode_keys(paths, lmax)
    hp1 = np.empty((n, lmax + 1), dtype=np.uint32)
    hp2 = np.empty((n, lmax + 1), dtype=np.uint32)
    h1 = np.full(n, _FNV1_BASIS, dtype=np.uint32)
    h2 = np.full(n, _FNV2_BASIS, dtype=np.uint32)
    hp1[:, 0] = h1
    hp2[:, 0] = h2
    for j in range(lmax):
        act = j < lens
        h1 = np.where(act, (h1 ^ mat[:, j]) * _FNV1_PRIME, h1)
        h2 = np.where(act, (h2 ^ mat[:, j]) * _FNV2_PRIME, h2)
        hp1[:, j + 1] = h1
        hp2[:, j + 1] = h2
    return hp1, hp2, lens


def _digest(value: bytes) -> int:
    """uint32 value digest — crc32 (C-speed, stdlib)."""
    return zlib.crc32(value) & 0xFFFFFFFF


def _build_apply(jnp, lax, jax, capacity: int, probe: int):
    """Jitted batched apply over the table carry (donated)."""

    cap_mask = np.uint32(capacity - 1)
    probe_off = np.arange(probe, dtype=np.uint32)

    def step(tab, op):
        state, fp1, fp2, modify, create, digest, flags, full = tab
        opc, h1, h2, index, dig, flg = op
        idx = ((h1 + probe_off) & cap_mask).astype(jnp.int32)  # [P]
        st = state[idx]
        match = (st != SLOT_EMPTY) & (fp1[idx] == h1) & (fp2[idx] == h2)
        any_match = jnp.any(match)
        first_match = jnp.argmax(match)
        empty = st == SLOT_EMPTY
        window_ok = any_match | jnp.any(empty)
        t = jnp.where(any_match, first_match, jnp.argmax(empty))
        slot = idx[t]
        existed = any_match & (st[first_match] == SLOT_LIVE)
        old_index = jnp.where(existed, modify[idx[first_match]],
                              jnp.uint32(0))
        is_set = opc == OP_SET
        is_del = opc == OP_DEL
        # SET needs a slot (match or empty); DEL only acts on a live key.
        write = (is_set & window_ok) | (is_del & existed)
        new_state = jnp.where(is_set, SLOT_LIVE, SLOT_TOMB)
        # Host create_index semantics: live key keeps create; empty or
        # tombstone (host popped it on delete) re-creates at this index.
        new_create = jnp.where(is_set & ~existed, index, create[slot])
        state = state.at[slot].set(jnp.where(write, new_state, state[slot]))
        fp1 = fp1.at[slot].set(jnp.where(write, h1, fp1[slot]))
        fp2 = fp2.at[slot].set(jnp.where(write, h2, fp2[slot]))
        modify = modify.at[slot].set(jnp.where(write, index, modify[slot]))
        create = create.at[slot].set(jnp.where(write, new_create,
                                               create[slot]))
        digest = digest.at[slot].set(
            jnp.where(write, jnp.where(is_set, dig, jnp.uint32(0)),
                      digest[slot]))
        flags = flags.at[slot].set(jnp.where(write & is_set, flg,
                                             flags[slot]))
        # Probe window exhausted on a SET: table degraded (counted; the
        # authoritative host store is unaffected).
        # O01 decision: uint32 with intended mod-2³² wrap, like every
        # device-side index here (module docstring).  A wrap needs 2³²
        # degraded SETs — the table is declared degraded (and sized up)
        # at the FIRST one; the counter's only job is "zero or not".
        full = full + jnp.where(is_set & ~window_ok, jnp.uint32(1),  # noqa: O01
                                jnp.uint32(0))
        return ((state, fp1, fp2, modify, create, digest, flags, full),
                (existed, old_index))

    def apply_batch(tab, ops):
        return lax.scan(step, tab, ops)

    return jax.jit(apply_batch, donate_argnums=(0,))


def _build_match(jnp, lax, jax, lmax: int):
    """Jitted watch matcher: scan over events OR-ing a fired mask [W]
    (O(W) memory — never materializes the [B, W] cross product), then
    packs it into a uint32 bitmask."""

    def step(carry, ev):
        fired, w_kind, w_h1, w_h2, w_len, w_min = carry
        kind, e_len, e_index, hp1, hp2, th1, th2 = ev
        at = jnp.clip(w_len, 0, lmax)
        kv = kind == EV_KV
        cond_kv = (kv & (w_kind != KIND_TABLE) & (w_len <= e_len)
                   & (hp1[at] == w_h1) & (hp2[at] == w_h2))
        cond_tab = ((kind == EV_TABLE) & (w_kind == KIND_TABLE)
                    & (th1 == w_h1) & (th2 == w_h2))
        # uint32 index gate (wrap convention in module docstring).
        gate = (w_kind >= 0) & (e_index > w_min)
        fired = fired | ((cond_kv | cond_tab) & gate)
        return (fired, w_kind, w_h1, w_h2, w_len, w_min), None

    def match(w_kind, w_h1, w_h2, w_len, w_min, events):
        fired0 = jnp.zeros(w_kind.shape, dtype=bool)
        carry, _ = lax.scan(step, (fired0, w_kind, w_h1, w_h2, w_len,
                                   w_min), events)
        fired = carry[0]
        bits = fired.reshape(-1, 32).astype(jnp.uint32)
        packed = (bits << jnp.arange(32, dtype=jnp.uint32)).sum(
            axis=1, dtype=jnp.uint32)
        return fired, packed

    return jax.jit(match)


class DeviceKVTable:
    """Fixed-capacity open-addressed hash table in device memory.

    Arrays: slot state (empty/live/tombstone), two uint32 key
    fingerprints, modify/create indexes (uint32, mod-2³² convention),
    crc32 value digest, flags. Probing is a static ``probe``-slot
    linear window gathered per op; a tombstone keeps its fingerprints so
    a re-set of the same key reuses its slot (no duplicate rows).
    """

    def __init__(self, capacity: int = 1 << 16, probe: int = 16) -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        self._jax, self._jnp = jax, jnp
        self.capacity = _pow2(max(int(capacity), probe))
        self.probe = int(probe)
        self._apply = _build_apply(jnp, lax, jax, self.capacity, self.probe)
        self._occupancy = jax.jit(
            lambda st: ((st == SLOT_LIVE).sum(dtype=jnp.int32),
                        (st == SLOT_TOMB).sum(dtype=jnp.int32)))
        self.reset()

    def reset(self) -> None:
        jnp = self._jnp
        c = self.capacity
        self.tab = (jnp.zeros(c, jnp.int32),    # state
                    jnp.zeros(c, jnp.uint32),   # fp1
                    jnp.zeros(c, jnp.uint32),   # fp2
                    jnp.zeros(c, jnp.uint32),   # modify
                    jnp.zeros(c, jnp.uint32),   # create
                    jnp.zeros(c, jnp.uint32),   # digest
                    jnp.zeros(c, jnp.uint32),   # flags
                    jnp.uint32(0))              # table-full degradations

    def apply(self, ops: Tuple[np.ndarray, ...]) -> Tuple[np.ndarray,
                                                          np.ndarray]:
        """Apply one padded op batch; returns host (existed, old_index)
        arrays (padding rows included — callers slice)."""
        self.tab, (existed, old_index) = self._apply(self.tab, ops)
        return np.asarray(existed), np.asarray(old_index)

    def occupancy(self) -> Tuple[int, int, int]:
        """(live, tombstone, degraded-sets) — one small jit reduction."""
        live, tomb = self._occupancy(self.tab[0])
        return int(live), int(tomb), int(self.tab[7])


# Auto-gate floor for the device watch matcher on a CPU backend.  The
# measured crossover (BENCH_WATCH.json, 10k standing watches on this
# box): host radix walk 0.6231 ms/batch vs device 14.1468 ms/batch —
# the interpreted device pass is 22.71x SLOWER, dominated by per-batch
# dispatch overhead that a real chip amortizes.  On CPU the device leg
# only has a chance once the O(W x B) evaluation itself dwarfs
# dispatch, far above the measured 10k point; on a non-CPU backend the
# device matcher is taken unconditionally.
WATCH_DEVICE_MIN_CPU = 1 << 16

# Knobs this module resolves through the autotune verdict — the
# consumer-side claim for the ``autotune-knob`` vet group
# (tools/vet/table_drift.py): the constant above is only the fallback;
# a measured crossover (tools/watchstorm.py --sweep, settled by
# obs/tuner.py) replaces it per platform.
TUNED_FIELDS = ("watch_device_min",)


class DeviceStoreBridge:
    """Glue between the host store/FSM and the device twin.

    ``on_batch(cap, store)`` is called by the FSM once per committed
    batch (consensus/fsm.py ``apply_batch``) with the store's
    ``ApplyCapture``: it ships the per-key ops as one device scatter,
    runs the watch matcher over the batch's events, cross-checks both
    against the host verdicts, fires the NotifyGroups (host∪device),
    and feeds the PR-7 hotpath byte cache via ``render_hook``.

    Dispatch bracketing mirrors ``gossip/plane._dispatch()``: wall time
    around the jit call *including* fetching the verdicts (which forces
    the device work), recorded per dispatch class (``store_apply``,
    ``watch_match``) in obs/storestats.py.
    """

    def __init__(self, capacity: int = 1 << 16, probe: int = 16,
                 lmax: int = 64, max_batch: int = 4096,
                 stats: Optional[object] = None,
                 match_backend: str = "auto") -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        if match_backend not in ("auto", "device", "host"):
            raise ValueError(
                f"match_backend must be auto|device|host, got "
                f"{match_backend!r}")
        self._jax, self._jnp = jax, jnp
        self.table = DeviceKVTable(capacity, probe)
        self.capacity = self.table.capacity
        self.lmax = int(lmax)
        self.max_batch = int(max_batch)
        self.match_backend = match_backend
        self._platform = jax.default_backend()
        # CPU break-even for the "auto" matcher: the measured crossover
        # from the persisted autotune verdict when one exists
        # (obs/tuner.py "watch_device_min"), else the constant above.
        try:
            from consul_tpu.obs import tuner
            self._min_cpu = int(tuner.resolved_value(
                "watch_device_min", default=WATCH_DEVICE_MIN_CPU,
                platform=self._platform,
                device_count=len(jax.devices())))
        except Exception:  # noqa: E02 — tuning is advisory, never fatal
            self._min_cpu = WATCH_DEVICE_MIN_CPU
        self._match = _build_match(jnp, lax, jax, self.lmax)
        if stats is None:
            from consul_tpu.obs import storestats
            stats = storestats.StoreStats() if storestats.enabled() else None
        self.stats = stats
        # Rebuilt lazily from KVWatchSet when its version moves.
        self._w_version = -1
        self._w_arrays: Optional[Tuple] = None
        self._w_groups: List[Tuple[str, object]] = []
        self._w_fallback: List[Tuple[str, object]] = []  # len > lmax
        self.divergence = 0
        self.render_hook = None  # set by Server: fired keys -> byte cache

    # -- watch-set encoding -------------------------------------------

    def _encode_watches(self, watchset) -> None:
        jnp = self._jnp
        reg = watchset.registered()
        self._w_groups = [(p, g) for p, g in reg
                          if len(p.encode("utf-8")) <= self.lmax]
        self._w_fallback = [(p, g) for p, g in reg
                            if len(p.encode("utf-8")) > self.lmax]
        w = len(self._w_groups)
        wp = _pow2(max(w, 1), floor=32)
        kind = np.full(wp, -1, dtype=np.int32)
        kind[:w] = KIND_PREFIX
        keys = [p.encode("utf-8") for p, _ in self._w_groups]
        h1 = np.zeros(wp, dtype=np.uint32)
        h2 = np.zeros(wp, dtype=np.uint32)
        ln = np.zeros(wp, dtype=np.int32)
        if w:
            h1[:w], h2[:w], ln[:w] = _full_hashes(keys, self.lmax)
        wmin = np.zeros(wp, dtype=np.uint32)  # plumbing registers min=0
        self._w_arrays = tuple(jnp.asarray(a)
                               for a in (kind, h1, h2, ln, wmin))
        self._w_version = watchset.version
        if self.stats is not None:
            self.stats.watch_registered = len(reg)

    def encode_predicates(self, preds: Sequence[WatchPredicate]) -> Tuple:
        """Encode explicit predicates (crossval / watchstorm path —
        exercises KIND_TABLE and min_index, which the NotifyGroup
        plumbing never sets)."""
        jnp = self._jnp
        w = len(preds)
        wp = _pow2(max(w, 1), floor=32)
        kind = np.full(wp, -1, dtype=np.int32)
        h1 = np.zeros(wp, dtype=np.uint32)
        h2 = np.zeros(wp, dtype=np.uint32)
        ln = np.zeros(wp, dtype=np.int32)
        wmin = np.zeros(wp, dtype=np.uint32)
        if w:
            keys = [p.value.encode("utf-8") for p in preds]
            h1[:w], h2[:w], ln[:w] = _full_hashes(keys, self.lmax)
            kind[:w] = [p.kind for p in preds]
            wmin[:w] = [p.min_index & 0xFFFFFFFF for p in preds]
            ln[:w] = np.where(np.array([p.kind for p in preds]) == KIND_TABLE,
                              0, ln[:w])
        return tuple(jnp.asarray(a) for a in (kind, h1, h2, ln, wmin))

    def _encode_events(self, notifies: Sequence[tuple]) -> Tuple:
        """Pack capture notify events into padded device rows."""
        jnp = self._jnp
        b = len(notifies)
        bp = _pow2(max(b, 1))
        kind = np.full(bp, EV_PAD, dtype=np.int32)
        e_len = np.zeros(bp, dtype=np.int32)
        e_index = np.zeros(bp, dtype=np.uint32)
        th1 = np.zeros(bp, dtype=np.uint32)
        th2 = np.zeros(bp, dtype=np.uint32)
        kv_paths: List[bytes] = []
        for i, ev in enumerate(notifies):
            if ev[0] == "kv":
                kind[i] = EV_KV
                kv_paths.append(ev[1].encode("utf-8"))
                e_index[i] = ev[3] & 0xFFFFFFFF
            else:
                kind[i] = EV_TABLE
                kv_paths.append(b"")
                e_index[i] = ev[2] & 0xFFFFFFFF
        hp1, hp2, lens = _prefix_hashes(kv_paths, self.lmax)
        hp1_p = np.zeros((bp, self.lmax + 1), dtype=np.uint32)
        hp2_p = np.zeros((bp, self.lmax + 1), dtype=np.uint32)
        hp1_p[:b], hp2_p[:b] = hp1, hp2
        for i, ev in enumerate(notifies):
            if ev[0] == "kv":
                # True byte length (uncapped) so w_len <= e_len is exact
                # for long paths; hashes only cover the first lmax bytes.
                e_len[i] = len(ev[1].encode("utf-8"))
            else:
                t1, t2, _ = _full_hashes([ev[1].encode("utf-8")], self.lmax)
                th1[i], th2[i] = t1[0], t2[0]
        return tuple(jnp.asarray(a) for a in
                     (kind, e_len, e_index, hp1_p, hp2_p, th1, th2))

    # -- op-stream encoding -------------------------------------------

    def _encode_ops(self, kv_ops: Sequence[tuple]) -> Tuple[Tuple, int]:
        jnp = self._jnp
        b = len(kv_ops)
        bp = _pow2(max(b, 1))  # callers chunk to max_batch first
        opc = np.full(bp, OP_PAD, dtype=np.int32)
        index = np.zeros(bp, dtype=np.uint32)
        dig = np.zeros(bp, dtype=np.uint32)
        flg = np.zeros(bp, dtype=np.uint32)
        keys = []
        for i, op in enumerate(kv_ops):
            keys.append(op[1].encode("utf-8"))
            index[i] = op[2] & 0xFFFFFFFF
            if op[0] == "set":
                opc[i] = OP_SET
                dig[i] = _digest(op[6])
                flg[i] = op[5] & 0xFFFFFFFF
            else:
                opc[i] = OP_DEL
        h1 = np.zeros(bp, dtype=np.uint32)
        h2 = np.zeros(bp, dtype=np.uint32)
        if b:
            # Full-key hashing beyond lmax for table fingerprints: hash
            # the whole key (table identity must distinguish keys that
            # share their first lmax bytes).
            h1[:b], h2[:b], _ = _full_hashes(keys, max(
                self.lmax, max(len(k) for k in keys)))
        return (tuple(jnp.asarray(a)
                      for a in (opc, h1, h2, index, dig, flg)), b)

    # -- the per-batch entry point ------------------------------------

    def on_batch(self, cap, store) -> None:
        """One committed batch: device scatter + device watch match,
        host cross-check, union-fire, cache render."""
        t0 = time.monotonic()
        n_ops = len(cap.kv_ops)
        if n_ops:
            chunks = [cap.kv_ops[i:i + self.max_batch]
                      for i in range(0, n_ops, self.max_batch)]
            for chunk in chunks:
                ops, _b = self._encode_ops(chunk)
                existed, old_index = self.table.apply(ops)
                for i, op in enumerate(chunk):
                    # set: ("set", key, index, old_index, existed, ...);
                    # del: ("del", key, index, old_index) — only ever
                    # recorded for keys that existed (store pops first).
                    h_existed = op[4] if op[0] == "set" else True
                    h_old = (op[3] & 0xFFFFFFFF) if h_existed else 0
                    if (bool(existed[i]) != bool(h_existed)
                            or int(old_index[i]) != h_old):
                        self.divergence += 1
            if self.stats is not None:
                ms = (time.monotonic() - t0) * 1e3
                self.stats.note_apply(ms, n_ops)

        self._fire_watches(cap, store)
        if self.render_hook is not None:
            keys = [op[1] for op in cap.kv_ops]
            if keys:
                self.render_hook(keys)
        cap.consumed = True

    def _use_device_match(self) -> bool:
        """The watch-matching backend decision (``match_backend``).

        "auto" picks the device matcher off-CPU, or on CPU once the
        standing-watch population is large enough that the O(W x B)
        evaluation dominates dispatch overhead (the verdict-resolved
        ``watch_device_min`` crossover, WATCH_DEVICE_MIN_CPU fallback;
        BENCH_WATCH.json medians).  Below that, the host radix walk —
        which runs anyway as the authoritative path — is strictly
        cheaper and the device leg is skipped entirely."""
        if self.match_backend != "auto":
            return self.match_backend == "device"
        if self._platform != "cpu":
            return True
        return len(self._w_groups) >= self._min_cpu

    def _fire_watches(self, cap, store) -> None:
        """Device bitmask ∪ host walk → NotifyGroup firing + prune."""
        watchset = store._kv_watch
        if watchset.version != self._w_version:
            self._encode_watches(watchset)
        use_device = self._use_device_match()
        if self.stats is not None:
            self.stats.match_backend_device = use_device

        # Host-authoritative match set (ordered as the sequential path
        # would have fired), incl. the delete-tree reverse direction and
        # any over-lmax fallback watches the device can't encode.
        host_fired: List[Tuple[str, object]] = []
        seen: Set[int] = set()
        for ev in cap.notifies:
            if ev[0] != "kv":
                continue
            for p, g in watchset.matched(ev[1], ev[2]):
                if id(g) not in seen:
                    seen.add(id(g))
                    host_fired.append((p, g))

        kv_events = [ev for ev in cap.notifies if ev[0] == "kv"]
        device_fired: List[Tuple[str, object]] = []
        host_keys = {id(g) for p, g in host_fired}
        if kv_events and self._w_groups and use_device:
            t0 = time.monotonic()
            events = self._encode_events(kv_events)
            fired, _packed = self._match(*self._w_arrays, events)
            fired = np.asarray(fired)[: len(self._w_groups)]
            device_fired = [self._w_groups[i]
                            for i in np.nonzero(fired)[0]]
            if self.stats is not None:
                ms = (time.monotonic() - t0) * 1e3
                self.stats.note_match(ms, len(kv_events),
                                      int(fired.sum()))

            # Device must agree with the host walk on every watch it
            # encodes, *except* the delete-tree reverse direction which
            # is host-only by design (module docstring).  The
            # cross-check only means something when the device matcher
            # actually ran — a host-gated batch has nothing to compare.
            dev_keys = {id(g) for p, g in device_fired}
            encoded = {id(g) for _, g in self._w_groups}
            expect_dev = set()
            for p, g in host_fired:
                if id(g) not in encoded:
                    continue  # over-lmax fallback watch, host-only
                if any(ev[1].startswith(p) for ev in kv_events):
                    # The forward (path startswith watch) direction is
                    # the device's; reverse-only tree matches are
                    # host-only.
                    expect_dev.add(id(g))
            missing = {k for k in expect_dev if k not in dev_keys}
            spurious = dev_keys - host_keys
            if missing or spurious:
                self.divergence += len(missing) + len(spurious)
        if self.stats is not None:
            self.stats.divergence = self.divergence

        # Fire the union: host order first (authoritative), then any
        # device-only extras (spurious-wake-safe).
        union = host_fired + [(p, g) for p, g in device_fired
                              if id(g) not in host_keys]
        watchset.notify_groups(union)  # prune bumps version → re-encode

        # Table notify events stay host-fired (one standing group per
        # table, never pruned — nothing for the device to win there).
        for ev in cap.notifies:
            if ev[0] == "table":
                store._watch[ev[1]].notify()

    # -- lifecycle ----------------------------------------------------

    def rebuild_from_store(self, store) -> None:
        """Reset + re-apply every live host row (snapshot restore path —
        fsm.restore builds a fresh store, the device twin follows)."""
        self.table.reset()
        rows: List[tuple] = []
        for _, ent in store._kv.items(""):
            if ent.create_index != ent.modify_index:
                # Two-step so the device's create_index lands on the
                # host's: first set creates at create_index, second set
                # (existed) keeps it and moves modify_index.
                rows.append(("set", ent.key, ent.create_index, 0, False,
                             ent.flags, b""))
                rows.append(("set", ent.key, ent.modify_index,
                             ent.create_index, True, ent.flags, ent.value))
            else:
                rows.append(("set", ent.key, ent.modify_index, 0, False,
                             ent.flags, ent.value))
        for i in range(0, len(rows), self.max_batch):
            ops, _ = self._encode_ops(rows[i:i + self.max_batch])
            self.table.apply(ops)
        self._w_version = -1

    def occupancy(self) -> Tuple[int, int, int]:
        return self.table.occupancy()


# ---------------------------------------------------------------------
# Crossval oracle (the contract): randomized apply/watch workloads
# through device AND host, asserting identical verdicts and fired sets.
# ---------------------------------------------------------------------

def _random_key(rng, prefixes: Sequence[str], long_tail: bool) -> str:
    p = prefixes[rng.randrange(len(prefixes))]
    leaf = f"{rng.randrange(64):x}"
    if long_tail and rng.random() < 0.05:
        leaf += "x" * 80  # push past lmax to exercise the fallback list
    return f"{p}{leaf}"


def crossval(n_batches: int = 20, batch: int = 32, n_watches: int = 200,
             capacity: int = 1 << 12, seed: int = 0,
             lmax: int = 64) -> Dict[str, Any]:
    """Drive randomized batches through host store + device bridge.

    Asserts (1) zero verdict/fired divergence via the bridge's own
    continuous cross-check, (2) the device fired set equals the pure
    ``match_batch`` oracle on explicit predicates (exact/prefix/table
    kinds incl. min_index gates), (3) blocking-style waiters wake
    identically. Returns a summary dict for tools/store_crossval.py.
    """
    import random

    from consul_tpu.state.store import StateStore
    from consul_tpu.structs.structs import DirEntry

    rng = random.Random(seed)
    store = StateStore()
    # match_backend forced: the lockstep oracle exists to exercise the
    # device matcher, so the CPU auto-gate must not silently skip it.
    bridge = DeviceStoreBridge(capacity=capacity, lmax=lmax, stats=None,
                               match_backend="device")
    prefixes = ["web/", "web/a/", "db/", "db/shard/", "cfg/", ""]

    class Flag:
        def __init__(self) -> None:
            self.sets = 0

        def set(self) -> None:
            self.sets += 1

    # Standing watch population with churn.
    flags: Dict[str, Flag] = {}
    for i in range(n_watches):
        w = _random_key(rng, prefixes, long_tail=True)
        flags[w] = Flag()
        store.watch_kv(w, flags[w])

    index = 0
    fired_total = 0
    for bi in range(n_batches):
        before = {w: f.sets for w, f in flags.items()}
        with store.capture_apply() as cap:
            for _ in range(batch):
                index += 1
                r = rng.random()
                key = _random_key(rng, prefixes, long_tail=True)
                if r < 0.55:
                    store.kvs_set(index, DirEntry(
                        key=key, value=rng.randbytes(8),
                        flags=rng.randrange(1 << 16)))
                elif r < 0.7:
                    store.kvs_check_and_set(index, DirEntry(
                        key=key, value=b"cas",
                        modify_index=rng.choice([0, index - 1])))
                elif r < 0.85:
                    store.kvs_delete(index, key)
                else:
                    store.kvs_delete_tree(
                        index, prefixes[rng.randrange(len(prefixes) - 1)])
            bridge.on_batch(cap, store)

        # Host-semantics oracle for wakeups: re-walk the events against
        # the *pre-batch* watch registry via the pure evaluator.
        muts = [StoreMutation(path=ev[1], index=ev[3], kv=True,
                              prefix=ev[2])
                for ev in cap.notifies if ev[0] == "kv"]
        preds = [WatchPredicate(KIND_PREFIX, w) for w in before]
        oracle = match_batch(preds, muts)
        for i, w in enumerate(before):
            woke = flags[w].sets > before[w]
            if woke != (i in oracle):
                raise AssertionError(
                    f"wakeup divergence batch {bi}: watch {w!r} "
                    f"woke={woke} oracle={i in oracle}")
            if woke:
                fired_total += 1
                # Exactly-once + re-register (NotifyGroup contract).
                assert flags[w].sets == before[w] + 1
                store.watch_kv(w, flags[w])

        if bridge.divergence:
            raise AssertionError(
                f"device/host divergence after batch {bi}: "
                f"{bridge.divergence}")

    # Verify the device table mirrors the host live set (digest +
    # indexes), via one rebuilt op-stream comparison.
    live, tomb, degraded = bridge.occupancy()
    host_live = sum(1 for _ in store._kv.items(""))
    if degraded == 0 and live != host_live:
        raise AssertionError(f"occupancy mismatch: device {live} "
                             f"host {host_live}")

    # Explicit predicate-kind sweep (KIND_KEY/TABLE + min_index) through
    # the low-level matcher against the pure evaluator.
    preds = ([WatchPredicate(KIND_KEY, _random_key(rng, prefixes, False))
              for _ in range(32)]
             + [WatchPredicate(KIND_PREFIX, p) for p in prefixes[:-1]]
             + [WatchPredicate(KIND_TABLE, "nodes"),
                WatchPredicate(KIND_TABLE, "sessions"),
                WatchPredicate(KIND_KEY, "web/", min_index=index + 10)])
    muts = ([StoreMutation(path=_random_key(rng, prefixes, False),
                           index=index + 1 + i) for i in range(16)]
            + [StoreMutation(path="nodes", index=index + 1, kv=False)])
    arrays = bridge.encode_predicates(preds)
    events = bridge._encode_events(
        [("kv", m.path, m.prefix, m.index) if m.kv
         else ("table", m.path, m.index) for m in muts])
    fired, packed = bridge._match(*arrays, events)
    fired = set(np.nonzero(np.asarray(fired)[:len(preds)])[0].tolist())
    want = match_batch(preds, muts)
    if fired != want:
        raise AssertionError(f"predicate sweep divergence: "
                             f"device {sorted(fired)} oracle {sorted(want)}")
    # Bitmask packing is exact.
    unpacked = {i for i in range(len(preds))
                if (int(np.asarray(packed)[i // 32]) >> (i % 32)) & 1}
    assert unpacked == fired

    return {"batches": n_batches, "batch": batch, "ops": index,
            "watches": n_watches, "fired_wakeups": fired_total,
            "device_live": live, "device_tombstones": tomb,
            "degraded": degraded, "divergence": bridge.divergence,
            "predicate_sweep": {"fired": len(fired), "total": len(preds)}}
