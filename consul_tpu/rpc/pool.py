"""Connection pool + pooled RPC client + raft TCP transport.

Parity target: ``consul/pool.go`` (399 LoC — per-address pooled
multiplexed sessions with stream reuse and a reaper) and
``consul/raft_rpc.go`` (RaftLayer dialing with a protocol byte).

One :class:`ConnPool` per process: ``rpc(addr, method, body)`` opens a
stream on the address's pooled mux session (dialing with the
``RPC_MULTIPLEX`` selector byte on first use) and runs one
request/response exchange.  :class:`TCPTransport` adapts the pool to
the consensus layer's ``call(src, dst, method, msg)`` contract, so the
same port carries raft the way the reference multiplexes RaftLayer
onto port 8300.
"""

from __future__ import annotations

import asyncio
import random
import ssl
import time
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from consul_tpu.obs import trace as obs_trace
from consul_tpu.rpc.mux import MuxError, MuxSession
from consul_tpu.rpc.wire import (
    raft_msg_to_wire, raft_resp_from_wire, trace_to_wire)

# Protocol selector bytes (consul/rpc.go:19-27).
RPC_CONSUL = 0x01
RPC_RAFT = 0x02
RPC_TLS = 0x03
RPC_MULTIPLEX = 0x05  # the yamux-era selector


class RPCError(Exception):
    pass


# Dial backoff (satellite of the chaos PR): repeated dial failures to
# one address back off exponentially with jitter instead of hammering
# the peer every rpc() — during a partition window every forwarded
# request used to redial the dead address, and the heal then faced a
# thundering herd of simultaneous reconnects.  Base doubles per
# consecutive failure up to the cap; jitter decorrelates the herd.
DIAL_BACKOFF_BASE = 0.05
DIAL_BACKOFF_CAP = 2.0
DIAL_BACKOFF_JITTER = 0.25  # +/- fraction of the computed delay


class ConnPool:
    def __init__(self, tls_wrap: Optional[Any] = None,
                 dial_timeout: float = 5.0) -> None:
        self._sessions: Dict[str, MuxSession] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self._tls_wrap = tls_wrap  # callable(dc) -> ssl.SSLContext | None
        self._dial_timeout = dial_timeout
        # addr -> (consecutive dial failures, monotonic not-before)
        self._dial_backoff: Dict[str, Tuple[int, float]] = {}
        # Chaos seam: optional async callable(addr, method) awaited
        # before each exchange; may delay or raise to emulate
        # directional partitions at the TCP layer (chaos/broker.py).
        self.fault_filter: Optional[Callable] = None

    def dial_backoff_remaining(self, addr: str) -> float:
        """Seconds until the next dial to ``addr`` is permitted (0.0 =
        no backoff in force)."""
        _, not_before = self._dial_backoff.get(addr, (0, 0.0))
        return max(0.0, not_before - time.monotonic())

    def _dial_failed(self, addr: str) -> None:
        fails, _ = self._dial_backoff.get(addr, (0, 0.0))
        fails += 1
        delay = min(DIAL_BACKOFF_CAP, DIAL_BACKOFF_BASE * (2 ** (fails - 1)))
        delay *= 1.0 + random.uniform(-DIAL_BACKOFF_JITTER,
                                      DIAL_BACKOFF_JITTER)
        self._dial_backoff[addr] = (fails, time.monotonic() + delay)

    async def _session(self, addr: str, dc: str = "") -> MuxSession:
        sess = self._sessions.get(addr)
        if sess is not None and not sess.closed:
            return sess
        lock = self._locks.setdefault(addr, asyncio.Lock())
        async with lock:
            sess = self._sessions.get(addr)
            if sess is not None and not sess.closed:
                return sess
            remaining = self.dial_backoff_remaining(addr)
            if remaining > 0.0:
                # Fail fast inside the backoff window: the caller's
                # retry policy (forward fallback, raft replication
                # retry) decides what to do; this pool only refuses to
                # open yet another doomed socket.
                raise ConnectionError(
                    f"dial backoff to {addr}: {remaining:.3f}s remaining")
            try:
                host, _, port = addr.rpartition(":")
                ctx: Optional[ssl.SSLContext] = None
                if self._tls_wrap is not None:
                    ctx = self._tls_wrap(dc)
                if ctx is not None:
                    # TLS wrap: selector byte first in the clear, then the
                    # handshake (rpcTLS, consul/rpc.go:100-112).  Wait for
                    # the server's ack byte before sending the ClientHello —
                    # see RPCServer._handle for the upgrade-race rationale.
                    reader, writer = await asyncio.wait_for(
                        asyncio.open_connection(host, int(port)),
                        self._dial_timeout)
                    writer.write(bytes([RPC_TLS]))
                    await writer.drain()
                    ack = await asyncio.wait_for(reader.readexactly(1),
                                                 self._dial_timeout)
                    if ack[0] != RPC_TLS:
                        raise ConnectionError("bad TLS upgrade ack")
                    await writer.start_tls(
                        ctx, server_hostname=self._server_hostname(dc))
                    writer.write(bytes([RPC_MULTIPLEX]))
                    await writer.drain()
                else:
                    reader, writer = await asyncio.wait_for(
                        asyncio.open_connection(host, int(port)),
                        self._dial_timeout)
                    writer.write(bytes([RPC_MULTIPLEX]))
                    await writer.drain()
            except (OSError, ConnectionError, asyncio.TimeoutError):
                self._dial_failed(addr)
                raise
            self._dial_backoff.pop(addr, None)
            sess = MuxSession(reader, writer, client=True)
            self._sessions[addr] = sess
            return sess

    def _server_hostname(self, dc: str) -> Optional[str]:
        if self._tls_wrap is None:
            return None
        getter = getattr(self._tls_wrap, "server_hostname", None)
        return getter(dc) if getter else None

    async def rpc(self, addr: str, method: str, body: Any,
                  dc: str = "", timeout: float = 30.0) -> Any:
        """One request/response on a pooled stream (ConnPool.RPC,
        pool.go:342-361).  A dead session is dropped and redialed once.

        Default timeout covers plain RPCs; callers forwarding blocking
        queries pass an explicit budget (max_query_time + margin) —
        see Server.forward_leader / forward_dc."""
        # Only requests already inside a trace carry context (keeps the
        # raft replication background chatter untraced); the response's
        # backhauled spans are re-homed into the local tracer so the
        # originating node's ring holds the whole cross-process trace.
        span = obs_trace.child_span(f"rpc-forward:{method}",
                                    tags={"addr": addr})
        env: Dict[str, Any] = {"Method": method, "Body": body}
        if span is not None:
            env["Trace"] = trace_to_wire(span.context)
        try:
            if self.fault_filter is not None:
                await self.fault_filter(addr, method)  # chaos: outbound leg
            for attempt in (0, 1):
                sess = await self._session(addr, dc)
                try:
                    stream = await sess.open_stream()
                    try:
                        await stream.send(msgpack.packb(
                            env, use_bin_type=True))
                        raw = await asyncio.wait_for(stream.recv(), timeout)
                    finally:
                        await stream.close()
                    resp = msgpack.unpackb(raw, raw=False,
                                           strict_map_key=False)
                    if span is not None and resp.get("Spans"):
                        obs_trace.tracer.ingest(resp["Spans"])
                    if resp.get("Error"):
                        raise RPCError(resp["Error"])
                    return resp.get("Body")
                except asyncio.TimeoutError:
                    # Surface a timed-out exchange immediately
                    # (re-waiting the full budget would double the
                    # stall) — and close the evicted session, or its
                    # socket + pump task leak.
                    evicted = self._sessions.pop(addr, None)
                    if evicted is not None:
                        await evicted.close()
                    raise
                except (MuxError, ConnectionError,
                        asyncio.IncompleteReadError):
                    self._sessions.pop(addr, None)
                    if attempt:
                        raise
            raise RPCError("unreachable")  # pragma: no cover
        except BaseException as e:
            if span is not None:
                span.set_error(e)
            raise
        finally:
            obs_trace.finish_span(span)

    async def close(self) -> None:
        for sess in list(self._sessions.values()):
            await sess.close()
        self._sessions.clear()


class TCPTransport:
    """consensus.raft transport over the pooled RPC mesh.

    The address book maps node id -> "host:port" of its RPC listener;
    register() keeps the reference's MemoryTransport API shape so the
    Server wiring is backend-agnostic."""

    def __init__(self, pool: Optional[ConnPool] = None) -> None:
        self.pool = pool or ConnPool()
        self.addrs: Dict[str, str] = {}
        self._local: Dict[str, Any] = {}

    def register(self, node) -> None:
        self._local[node.id] = node

    def set_addr(self, node_id: str, addr: str) -> None:
        self.addrs[node_id] = addr

    async def call(self, src: str, dst: str, method: str, msg: Any) -> Any:
        local = self._local.get(dst)
        if local is not None and dst not in self.addrs:
            return local.handle(method, msg)
        addr = self.addrs.get(dst)
        if addr is None:
            from consul_tpu.consensus.raft import TransportError
            raise TransportError(f"no address for {dst}")
        try:
            body = await self.pool.rpc(addr, f"Raft.{method}",
                                       raft_msg_to_wire(msg), timeout=5.0)
        except (RPCError, OSError, asyncio.TimeoutError) as e:
            from consul_tpu.consensus.raft import TransportError
            raise TransportError(str(e)) from e
        return raft_resp_from_wire(method, body)
