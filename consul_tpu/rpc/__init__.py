"""RPC mesh: single-port wire protocol, stream mux, conn pool, forwarding.

Parity target: ``consul/rpc.go`` + ``consul/pool.go`` +
``consul/raft_rpc.go`` — one TCP port per server, first byte selects the
protocol (consul/rpc.go:19-27), msgpack request/response streams
multiplexed yamux-style, pooled per-address sessions, and
leader/cross-DC request forwarding.
"""
