"""Message-oriented stream multiplexer over one TCP connection.

Parity target: the yamux/muxado session layer the reference pools RPC
streams on (consul/pool.go:238-263, deps yamux + muxado).  Design
departure: yamux is a byte-stream mux and the reference stacks msgpack
framing on top; our only payloads are discrete msgpack messages, so the
mux frames whole messages — ``[stream_id:u32][flags:u8][len:u32]`` +
body — which removes one framing layer and any partial-read states.

Client-opened streams use odd ids, server-opened even (yamux
convention), so both sides can open without coordination.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, Optional

_HDR = struct.Struct("<IBI")

FLAG_SYN = 0x1
FLAG_FIN = 0x2
FLAG_RST = 0x4
FLAG_DATA = 0x0

MAX_FRAME = 32 * 1024 * 1024


class MuxError(Exception):
    pass


class Stream:
    def __init__(self, session: "MuxSession", sid: int) -> None:
        self.session = session
        self.sid = sid
        self._rx: asyncio.Queue = asyncio.Queue()
        self.closed = False

    async def send(self, payload: bytes) -> None:
        if self.closed:
            raise MuxError(f"stream {self.sid} closed")
        await self.session._send_frame(self.sid, FLAG_DATA, payload)

    async def recv(self) -> bytes:
        if self.closed and self._rx.empty():
            raise MuxError(f"stream {self.sid} closed")
        msg = await self._rx.get()
        if msg is None:
            self.closed = True
            raise MuxError(f"stream {self.sid} closed by peer")
        return msg

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                await self.session._send_frame(self.sid, FLAG_FIN, b"")
            except (MuxError, ConnectionError):
                pass
            self.session._streams.pop(self.sid, None)

    def _push(self, payload: Optional[bytes]) -> None:
        self._rx.put_nowait(payload)


class MuxSession:
    """One multiplexed connection.  `client=True` opens odd stream ids."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, client: bool) -> None:
        self._reader = reader
        self._writer = writer
        self._next_sid = 1 if client else 2
        self._streams: Dict[int, Stream] = {}
        self._accept_q: asyncio.Queue = asyncio.Queue()
        self._wlock = asyncio.Lock()
        self.closed = False
        self._pump_task = asyncio.get_event_loop().create_task(self._pump())

    async def open_stream(self) -> Stream:
        if self.closed:
            raise MuxError("session closed")
        sid = self._next_sid
        self._next_sid += 2
        st = Stream(self, sid)
        self._streams[sid] = st
        await self._send_frame(sid, FLAG_SYN, b"")
        return st

    async def accept_stream(self) -> Stream:
        st = await self._accept_q.get()
        if st is None:
            raise MuxError("session closed")
        return st

    async def _send_frame(self, sid: int, flags: int, payload: bytes) -> None:
        if self.closed:
            raise MuxError("session closed")
        async with self._wlock:
            self._writer.write(_HDR.pack(sid, flags, len(payload)) + payload)
            await self._writer.drain()

    async def _pump(self) -> None:
        try:
            while True:
                hdr = await self._reader.readexactly(_HDR.size)
                sid, flags, length = _HDR.unpack(hdr)
                if length > MAX_FRAME:
                    raise MuxError(f"frame too large: {length}")
                payload = await self._reader.readexactly(length) if length else b""
                if flags & FLAG_SYN:
                    st = Stream(self, sid)
                    self._streams[sid] = st
                    self._accept_q.put_nowait(st)
                elif flags & (FLAG_FIN | FLAG_RST):
                    st = self._streams.pop(sid, None)
                    if st is not None:
                        st._push(None)
                else:
                    st = self._streams.get(sid)
                    if st is not None:
                        st._push(payload)
        except (asyncio.IncompleteReadError, ConnectionError, MuxError):
            pass  # peer closed; the finally block tears down the streams
        except asyncio.CancelledError:
            pass  # cancelled by close(); same teardown path, don't escape
        finally:
            self.closed = True
            for st in self._streams.values():
                st._push(None)
            self._streams.clear()
            self._accept_q.put_nowait(None)

    async def close(self) -> None:
        self.closed = True
        self._pump_task.cancel()
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except (ConnectionError, OSError):
            pass
