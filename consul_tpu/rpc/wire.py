"""Wire codecs for raft messages crossing the TCP RPC port.

Parity target: the reference serializes raft RPCs with msgpack over the
RaftLayer stream (consul/raft_rpc.go); our equivalents are the
dataclasses in consensus/raft.py.
"""

from __future__ import annotations

from typing import Any, Dict

from consul_tpu.consensus.log import LogEntry
from consul_tpu.consensus.raft import (
    AppendReq, AppendResp, SnapReq, SnapResp, VoteReq, VoteResp)


def entry_to_wire(e: LogEntry) -> list:
    return [e.index, e.term, e.type, e.data]


def entry_from_wire(v: list) -> LogEntry:
    return LogEntry(index=v[0], term=v[1], type=v[2], data=v[3])


_TO_WIRE = {
    VoteReq: lambda m: {"t": m.term, "c": m.candidate,
                        "li": m.last_log_index, "lt": m.last_log_term},
    VoteResp: lambda m: {"t": m.term, "g": m.granted},
    AppendReq: lambda m: {"t": m.term, "l": m.leader,
                          "pi": m.prev_log_index, "pt": m.prev_log_term,
                          "e": [entry_to_wire(x) for x in m.entries],
                          "lc": m.leader_commit},
    AppendResp: lambda m: {"t": m.term, "s": m.success, "m": m.match_index},
    SnapReq: lambda m: {"t": m.term, "l": m.leader, "li": m.last_index,
                        "lt": m.last_term, "p": m.peers, "d": m.data},
    SnapResp: lambda m: {"t": m.term, "s": m.success},
}

_REQ_FROM_WIRE = {
    "request_vote": lambda d: VoteReq(d["t"], d["c"], d["li"], d["lt"]),
    "append_entries": lambda d: AppendReq(
        d["t"], d["l"], d["pi"], d["pt"],
        [entry_from_wire(x) for x in d["e"]], d["lc"]),
    "install_snapshot": lambda d: SnapReq(
        d["t"], d["l"], d["li"], d["lt"], d["p"], d["d"]),
}

_RESP_FROM_WIRE = {
    "request_vote": lambda d: VoteResp(d["t"], d["g"]),
    "append_entries": lambda d: AppendResp(d["t"], d["s"], d["m"]),
    "install_snapshot": lambda d: SnapResp(d["t"], d["s"]),
}


def trace_to_wire(ctx: Any) -> Dict:
    """SpanContext -> the optional ``"Trace"`` envelope field."""
    return {"tid": ctx.trace_id, "sid": ctx.span_id}


def trace_from_wire(d: Any) -> Any:
    """Envelope ``"Trace"`` field -> SpanContext (None when absent or
    malformed — tracing is best-effort, never a protocol error)."""
    if not isinstance(d, dict):
        return None
    tid, sid = d.get("tid"), d.get("sid")
    if not (isinstance(tid, str) and isinstance(sid, str)):
        return None
    from consul_tpu.obs.trace import SpanContext
    return SpanContext(tid, sid)


def raft_msg_to_wire(msg: Any) -> Dict:
    return _TO_WIRE[type(msg)](msg)


def raft_req_from_wire(method: str, d: Dict) -> Any:
    return _REQ_FROM_WIRE[method](d)


def raft_resp_from_wire(method: str, d: Dict) -> Any:
    return _RESP_FROM_WIRE[method](d)
