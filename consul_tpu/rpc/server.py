"""TCP RPC server: one port, protocol-selector byte, mux dispatch.

Parity target: ``consul/rpc.go`` (417 LoC).  The listener reads one
selector byte per connection (:19-27): ``RPC_CONSUL`` (single-exchange
msgpack RPC), ``RPC_RAFT`` (raft stream handoff, consul/rpc.go:96-98),
``RPC_TLS`` (TLS upgrade, then recurse), ``RPC_MULTIPLEX`` (mux
session; every stream is an independent request/response exchange —
the yamux path the reference pools, pool.go:238-263).

Dispatch applies the reference's ``forward()`` prologue centrally
(rpc.go:182-201): a request naming another datacenter hops to a random
server there (wire-in/wire-out, no re-marshalling); a write or
consistent read on a non-leader hops to the leader.  Stale reads are
served wherever they land.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from consul_tpu.obs import trace as obs_trace
from consul_tpu.rpc.mux import MuxError, MuxSession
from consul_tpu.rpc.pool import RPC_CONSUL, RPC_MULTIPLEX, RPC_RAFT, RPC_TLS
from consul_tpu.rpc.wire import (
    raft_msg_to_wire, raft_req_from_wire, trace_from_wire)
from consul_tpu.structs.structs import (
    ACLPolicyRequest, ACLRequest, DeregisterRequest, KeyListRequest,
    KeyRequest, KVSRequest, QueryOptions, RegisterRequest, SessionRequest,
    UserEvent)

# handler kinds drive the forward() prologue
LOCAL = "local"   # never forwarded (Status.*, raft internals)
READ = "read"     # forwarded to leader unless allow_stale
WRITE = "write"   # always to the leader


def _opts_from_wire(o: Optional[Dict]) -> QueryOptions:
    o = o or {}
    return QueryOptions(
        token=o.get("token", ""), datacenter=o.get("datacenter", ""),
        min_query_index=o.get("min_query_index", 0),
        max_query_time=o.get("max_query_time", 0.0),
        allow_stale=o.get("allow_stale", False),
        require_consistent=o.get("require_consistent", False))


def _meta_to_wire(meta) -> Dict:
    return {"index": meta.index, "known_leader": meta.known_leader,
            "last_contact": meta.last_contact}


def _w(x: Any) -> Any:
    if hasattr(x, "to_wire"):
        return x.to_wire()
    if isinstance(x, dict):
        return {k: _w(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_w(v) for v in x]
    return x


class RPCServer:
    def __init__(self, server, tls_incoming=None) -> None:
        self.srv = server
        self.tls_incoming = tls_incoming  # ssl.SSLContext or None
        self._listener: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._handlers = _build_handlers()
        self._conns: set = set()  # live connection writers, closed on stop
        self._stream_tasks: set = set()  # anchor mux stream servers
        # Chaos seam: optional async callable(req) awaited before
        # dispatch; may delay (slow server) or raise (inbound drop —
        # surfaced to the caller as an RPC error).  None in production.
        self.fault_filter = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._listener = await asyncio.start_server(self._serve, host, port)
        self.addr = self._listener.sockets[0].getsockname()[:2]

    async def stop(self) -> None:
        if self._listener is not None:
            self._listener.close()
            # Connection handlers loop until the PEER hangs up; 3.12's
            # wait_closed() waits for every handler, so without forcing
            # our side shut, shutdown deadlocks on remote pools' idle
            # sessions until their 610s timeout.
            for w in list(self._conns):
                w.close()
            await self._listener.wait_closed()

    # -- connection handling (handleConn, rpc.go:73-120) --------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            await self._handle(reader, writer, tls_done=False)
        except (asyncio.IncompleteReadError, ConnectionError, MuxError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _handle(self, reader, writer, tls_done: bool) -> None:
        selector = (await reader.readexactly(1))[0]
        if selector == RPC_TLS:
            if self.tls_incoming is None:
                return  # TLS not configured; drop (rpc.go TLS checks)
            # Ack the upgrade in the clear before the handshake.  The
            # client MUST NOT send its ClientHello until this byte
            # arrives: bytes buffered in our StreamReader before
            # start_tls() switches protocols are silently lost (asyncio
            # upgrade race; Go's synchronous reads make the reference's
            # ack-less upgrade safe, ours needs the barrier).
            writer.write(bytes([RPC_TLS]))
            await writer.drain()
            await writer.start_tls(self.tls_incoming)
            await self._handle(reader, writer, tls_done=True)
        elif selector == RPC_MULTIPLEX:
            sess = MuxSession(reader, writer, client=False)
            while True:
                stream = await sess.accept_stream()
                task = asyncio.get_event_loop().create_task(
                    self._serve_stream(stream))
                self._stream_tasks.add(task)
                task.add_done_callback(self._stream_tasks.discard)
        elif selector in (RPC_CONSUL, RPC_RAFT):
            # single-exchange loop on the raw connection
            unpacker = msgpack.Unpacker(raw=False, strict_map_key=False)
            while True:
                req = await _next_obj(reader, unpacker)
                resp = await self._dispatch(req)
                writer.write(msgpack.packb(resp, use_bin_type=True))
                await writer.drain()

    async def _serve_stream(self, stream) -> None:
        try:
            while True:
                raw = await stream.recv()
                req = msgpack.unpackb(raw, raw=False, strict_map_key=False)
                resp = await self._dispatch(req)
                await stream.send(msgpack.packb(resp, use_bin_type=True))
        except (MuxError, ConnectionError):
            pass

    # -- dispatch + forward prologue ---------------------------------------

    async def _dispatch(self, req: Dict) -> Dict:
        """Trace-aware dispatch shell: when the envelope carries a
        ``"Trace"`` context, handle the request under a server span and
        backhaul every span this node finished for that trace in the
        response's ``"Spans"`` field (the caller's tracer re-homes
        them, stitching the cross-process tree — see obs/trace.py)."""
        if self.fault_filter is not None:
            try:
                await self.fault_filter(req)
            except Exception as e:
                return {"Error": f"{e}" or type(e).__name__}
        remote = trace_from_wire(req.get("Trace"))
        if remote is None:
            return await self._dispatch_inner(req)
        span = obs_trace.server_span(f"rpc:{req.get('Method', '')}", remote)
        try:
            resp = await self._dispatch_inner(req)
        except BaseException as e:
            span.set_error(e)
            span.finish()
            obs_trace.tracer.take(span.trace_id)  # drop orphaned spans
            raise
        span.finish()
        spans = obs_trace.tracer.take(span.trace_id)
        if spans:
            resp["Spans"] = spans
        return resp

    async def _dispatch_inner(self, req: Dict) -> Dict:
        method = req.get("Method", "")
        body = req.get("Body")
        entry = self._handlers.get(method)
        if entry is None:
            return {"Error": f"rpc: can't find method {method}"}
        kind, fn = entry
        try:
            # forward() (rpc.go:182-201)
            if kind != LOCAL:
                dc = (body or {}).get("opts", {}).get("datacenter", "") or \
                     (body or {}).get("datacenter", "")
                if dc and dc != self.srv.config.datacenter:
                    out = await self.srv.forward_dc(dc, method, body)
                    return {"Error": "", "Body": out}
                stale = (body or {}).get("opts", {}).get("allow_stale", False) \
                    or (body or {}).get("allow_stale", False)
                if not self.srv.is_leader() and (kind == WRITE or not stale):
                    out = await self.srv.forward_leader(method, body)
                    return {"Error": "", "Body": out}
            out = await fn(self.srv, body or {})
            return {"Error": "", "Body": out}
        except Exception as e:
            return {"Error": f"{e}" or type(e).__name__}


async def _next_obj(reader, unpacker):
    while True:
        try:
            return next(unpacker)
        except StopIteration:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError("closed")
            unpacker.feed(data)


# -- method handlers ---------------------------------------------------------


def _build_handlers() -> Dict[str, Tuple[str, Callable]]:
    H: Dict[str, Tuple[str, Callable]] = {}

    def reg(name: str, kind: str):
        def deco(fn):
            H[name] = (kind, fn)
            return fn
        return deco

    # raft internals (the RaftLayer handoff, consul/rpc.go:96-98)
    for m in ("request_vote", "append_entries", "install_snapshot"):
        def mk(m):
            async def fn(srv, body):
                msg = raft_req_from_wire(m, body)
                resp = await srv.raft.handle(m, msg)
                return raft_msg_to_wire(resp)
            return fn
        H[f"Raft.{m}"] = (LOCAL, mk(m))

    @reg("Status.Ping", LOCAL)
    async def status_ping(srv, body):
        return True

    @reg("Status.Leader", LOCAL)
    async def status_leader(srv, body):
        return srv.leader_addr()

    @reg("Status.Peers", LOCAL)
    async def status_peers(srv, body):
        return srv.raft_peers()

    @reg("Status.Lease", LOCAL)
    async def status_lease(srv, body):
        return srv.lease_state()

    # The generic write-forward target: the originating server validated
    # and ACL-checked; the leader applies through consensus.
    @reg("Server.Apply", WRITE)
    async def server_apply(srv, body):
        resp = await srv.raft_apply_raw(body["buf"])
        return _w(resp)

    @reg("Catalog.Register", WRITE)
    async def catalog_register(srv, body):
        await srv.catalog.register(RegisterRequest.from_wire(body))
        return True

    @reg("Catalog.Deregister", WRITE)
    async def catalog_deregister(srv, body):
        await srv.catalog.deregister(DeregisterRequest.from_wire(body))
        return True

    @reg("Catalog.ListDatacenters", LOCAL)
    async def catalog_dcs(srv, body):
        return srv.known_datacenters()

    @reg("Catalog.ListNodes", READ)
    async def catalog_nodes(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.catalog.list_nodes(opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Catalog.ListServices", READ)
    async def catalog_services(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.catalog.list_services(opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Catalog.ServiceNodes", READ)
    async def catalog_service_nodes(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.catalog.service_nodes(
            body.get("service", ""), opts, body.get("tag", ""))
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Catalog.NodeServices", READ)
    async def catalog_node_services(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.catalog.node_services(
            body.get("node", ""), opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Health.ChecksInState", READ)
    async def health_state(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.health.checks_in_state(
            body.get("state", "any"), opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Health.NodeChecks", READ)
    async def health_node(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.health.node_checks(body.get("node", ""), opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Health.ServiceChecks", READ)
    async def health_checks(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.health.service_checks(body.get("service", ""),
                                                    opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Health.ServiceNodes", READ)
    async def health_service(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.health.service_nodes(
            body.get("service", ""), opts, body.get("tag", ""),
            body.get("passing", False))
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("KVS.Apply", WRITE)
    async def kvs_apply(srv, body):
        return await srv.kvs.apply(KVSRequest.from_wire(body))

    @reg("KVS.Get", READ)
    async def kvs_get(srv, body):
        meta, out = await srv.kvs.get(KeyRequest.from_wire(body))
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("KVS.List", READ)
    async def kvs_list(srv, body):
        meta, out = await srv.kvs.list(KeyListRequest.from_wire(body))
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("KVS.ListKeys", READ)
    async def kvs_list_keys(srv, body):
        meta, out = await srv.kvs.list_keys(KeyListRequest.from_wire(body))
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Session.Apply", WRITE)
    async def session_apply(srv, body):
        return await srv.session.apply(SessionRequest.from_wire(body))

    @reg("Session.Get", READ)
    async def session_get(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.session.get(body.get("id", ""), opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Session.List", READ)
    async def session_list(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.session.list(opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Session.NodeSessions", READ)
    async def session_node(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.session.node_sessions(body.get("node", ""), opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Session.Renew", WRITE)
    async def session_renew(srv, body):
        # Renew must land on the leader — the TTL timer lives there
        # (session_ttl.go ResetSessionTimer).
        out = await srv.session.renew(body.get("id", ""))
        return _w(out)

    @reg("ACL.Apply", WRITE)
    async def acl_apply(srv, body):
        return await srv.acl.apply(ACLRequest.from_wire(body))

    @reg("ACL.Get", READ)
    async def acl_get(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.acl.get(body.get("id", ""), opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("ACL.GetPolicy", LOCAL)
    async def acl_get_policy(srv, body):
        reply = await srv.acl.get_policy(ACLPolicyRequest.from_wire(body))
        return _w(reply)

    @reg("ACL.List", READ)
    async def acl_list(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.acl.list(opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Internal.NodeInfo", READ)
    async def internal_node_info(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.internal.node_info(body.get("node", ""), opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    @reg("Internal.NodeDump", READ)
    async def internal_node_dump(srv, body):
        opts = _opts_from_wire(body.get("opts"))
        meta, out = await srv.internal.node_dump(opts)
        return {"meta": _meta_to_wire(meta), "data": _w(out)}

    # READ, not LOCAL: the forward() prologue routes a fire naming
    # another datacenter over the WAN (internal_endpoint.go EventFire
    # calls srv.forward first).
    @reg("Internal.EventFire", READ)
    async def internal_event_fire(srv, body):
        await srv.fire_user_event(UserEvent.from_wire(body))
        return True

    # ReadIndex service for follower consistent reads (Raft §6.4):
    # LOCAL — the caller already routed to the node it believes leads,
    # and the handler is leader-only (no forwarding bounce).
    @reg("Server.ReadIndex", LOCAL)
    async def server_read_index(srv, body):
        return {"index": await srv.leader_read_index()}

    @reg("Internal.KeyringOperation", LOCAL)
    async def internal_keyring(srv, body):
        return await srv.keyring_operation_local(body.get("op", "list"),
                                                 body.get("key", ""))

    return H
