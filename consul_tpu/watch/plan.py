"""Watch plans: parse generic params, run a blocking-query loop.

Parity target: ``watch/watch.go`` (plan parse, :42-104),
``watch/plan.go`` (run loop: index-change + DeepEqual dedup +
exponential backoff to 10s, :23-97) and the 7 watch-type factories of
``watch/funcs.go:16-193``: key, keyprefix, services, nodes, service,
checks (by service or state), event.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

from consul_tpu.api.client import Client, Config, QueryOptions

MAX_BACKOFF = 10.0  # maxBackoffTime (plan.go:16)

# watch type -> (required params, watcher factory)
_FUNCS: Dict[str, Callable] = {}


class WatchError(Exception):
    pass


def _register(name: str):
    def deco(fn):
        _FUNCS[name] = fn
        return fn
    return deco


def _take(params: Dict[str, Any], key: str, required: bool = False,
          default: Any = None) -> Any:
    if key in params:
        return params.pop(key)
    if required:
        raise WatchError(f"Must specify a single {key}")
    return default


class WatchPlan:
    """One watch: type + params + handler, driven by blocking queries."""

    def __init__(self, watch_type: str, watcher: Callable,
                 params: Dict[str, Any]) -> None:
        self.type = watch_type
        self.watcher = watcher  # (client, q) -> (index, result)
        self.params = params
        self.handler: Optional[Callable[[int, Any], None]] = None
        self.token: str = params.pop("token", "")
        self.datacenter: str = params.pop("datacenter", "")
        self._stop = threading.Event()
        self.last_index = 0
        self.last_result: Any = None
        self._seen_first = False

    # -- run loop (plan.go:23-97) ------------------------------------------

    def run(self, address: str) -> None:
        """Blocks until stop(); invokes handler on each observed change."""
        client = Client(Config(address=address, token=self.token,
                               datacenter=self.datacenter))
        try:
            failures = 0
            while not self._stop.is_set():
                q = QueryOptions(wait_index=self.last_index, wait_time=60.0,
                                 token=self.token, datacenter=self.datacenter)
                try:
                    index, result = self.watcher(client, q)
                except Exception:
                    failures += 1
                    backoff = min(MAX_BACKOFF, 0.25 * (2 ** failures))
                    if self._stop.wait(backoff):
                        break
                    continue
                failures = 0
                if self._stop.is_set():
                    break
                # Index regression guard + dedup identical results
                # (plan.go:71-85: skip when the index is unchanged, then
                # skip when the result deep-equals the last one)
                if index < self.last_index:
                    index = 0
                changed = (not self._seen_first
                           or (index != self.last_index
                               and result != self.last_result))
                self.last_index = index
                if changed:
                    self._seen_first = True
                    self.last_result = result
                    if self.handler is not None:
                        self.handler(index, result)
        finally:
            client.close()

    def run_in_thread(self, address: str) -> threading.Thread:
        t = threading.Thread(target=self.run, args=(address,), daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()


# -- factories (watch/funcs.go:16-193) --------------------------------------


@_register("key")
def _key_watch(params: Dict[str, Any]) -> Callable:
    key = _take(params, "key", required=True)

    def watcher(client: Client, q: QueryOptions) -> Tuple[int, Any]:
        pair, meta = client.kv.get(key, q)
        if pair is None:
            return meta.last_index, None
        return meta.last_index, {
            "Key": pair.key, "Value": pair.value,
            "Flags": pair.flags, "Session": pair.session,
            "CreateIndex": pair.create_index, "ModifyIndex": pair.modify_index}

    return watcher


@_register("keyprefix")
def _keyprefix_watch(params: Dict[str, Any]) -> Callable:
    prefix = _take(params, "prefix", required=True)

    def watcher(client: Client, q: QueryOptions) -> Tuple[int, Any]:
        pairs, meta = client.kv.list(prefix, q)
        return meta.last_index, [
            {"Key": p.key, "Value": p.value, "ModifyIndex": p.modify_index}
            for p in pairs]

    return watcher


@_register("services")
def _services_watch(params: Dict[str, Any]) -> Callable:
    def watcher(client: Client, q: QueryOptions) -> Tuple[int, Any]:
        services, meta = client.catalog.services(q)
        return meta.last_index, services

    return watcher


@_register("nodes")
def _nodes_watch(params: Dict[str, Any]) -> Callable:
    def watcher(client: Client, q: QueryOptions) -> Tuple[int, Any]:
        nodes, meta = client.catalog.nodes(q)
        return meta.last_index, nodes

    return watcher


@_register("service")
def _service_watch(params: Dict[str, Any]) -> Callable:
    service = _take(params, "service", required=True)
    tag = _take(params, "tag", default="")
    raw_passing = _take(params, "passingonly", default=False)
    if isinstance(raw_passing, str):
        if raw_passing.lower() not in ("true", "false"):
            raise WatchError("passingonly must be a boolean")
        passing = raw_passing.lower() == "true"
    elif isinstance(raw_passing, bool):
        passing = raw_passing
    else:
        raise WatchError("passingonly must be a boolean")

    def watcher(client: Client, q: QueryOptions) -> Tuple[int, Any]:
        entries, meta = client.health.service(service, tag, passing, q)
        return meta.last_index, entries

    return watcher


@_register("checks")
def _checks_watch(params: Dict[str, Any]) -> Callable:
    service = _take(params, "service", default="")
    state = _take(params, "state", default="")
    if service and state:
        raise WatchError("Cannot specify service and state")

    def watcher(client: Client, q: QueryOptions) -> Tuple[int, Any]:
        if service:
            checks, meta = client.health.checks(service, q)
        else:
            checks, meta = client.health.state(state or "any", q)
        return meta.last_index, checks

    return watcher


@_register("event")
def _event_watch(params: Dict[str, Any]) -> Callable:
    name = _take(params, "name", default="")

    def watcher(client: Client, q: QueryOptions) -> Tuple[int, Any]:
        events, meta = client.event.list(name, q)
        return meta.last_index, events

    return watcher


def parse(params: Dict[str, Any]) -> WatchPlan:
    """Build a plan from generic params (watch.go:42-104).  Unconsumed
    keys are an error, matching the reference's strict parse."""
    params = dict(params)
    watch_type = params.pop("type", None)
    if not watch_type:
        raise WatchError("Must specify watch type")
    factory = _FUNCS.get(watch_type)
    if factory is None:
        raise WatchError(f"Unsupported watch type: {watch_type}")
    token = params.pop("token", "")
    datacenter = params.pop("datacenter", "")
    handler_cmd = params.pop("handler", None)
    watcher = factory(params)  # factories pop the params they consume
    if params:
        raise WatchError(f"Invalid parameters: {sorted(params)}")
    plan = WatchPlan(watch_type, watcher,
                     {"token": token, "datacenter": datacenter})
    if handler_cmd:
        from consul_tpu.watch.handler import make_shell_handler
        plan.handler = make_shell_handler(handler_cmd)
    return plan
