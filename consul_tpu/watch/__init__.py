"""Watch framework: long-poll plans over the client SDK.

Parity target: the reference's ``watch/`` package (439 LoC).
"""

from consul_tpu.watch.plan import WatchPlan, parse
from consul_tpu.watch.handler import make_shell_handler

__all__ = ["WatchPlan", "parse", "make_shell_handler"]
