"""Shell handler execution for watch firings.

Parity target: ``command/agent/watch_handler.go:36-80`` — spawn the
configured shell command per firing, JSON result on stdin,
``CONSUL_INDEX`` in the environment.
"""

from __future__ import annotations

import base64
import json
import os
import subprocess
from typing import Any, Callable


def _jsonable(value: Any) -> Any:
    if isinstance(value, bytes):
        return base64.b64encode(value).decode("ascii")
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return value


def make_shell_handler(script: str, timeout: float = 30.0
                       ) -> Callable[[int, Any], None]:
    def handler(index: int, result: Any) -> None:
        env = dict(os.environ)
        env["CONSUL_INDEX"] = str(index)
        payload = json.dumps(_jsonable(result)).encode() + b"\n"
        try:
            subprocess.run(["/bin/sh", "-c", script], input=payload,
                           env=env, timeout=timeout,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
        except (subprocess.TimeoutExpired, OSError):
            pass

    return handler
