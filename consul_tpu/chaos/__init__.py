"""Host-plane fault injection for the consensus/serving stack.

The gossip kernel got its nemesis in PR 6 (gossip/nemesis.py:
compiled-in correlated faults cross-validated against the refmodel
oracle).  This package is the symmetric subsystem for the HOST plane —
raft, leader leases, the durability pump, the RPC mesh, and the
SO_REUSEPORT worker front:

- ``broker``   — the injectable fault broker threaded through the
  seams (clock skew/jumps, fsync stalls/errors, directional message
  drop/delay, worker kill/restart).
- ``scenarios`` — the declarative scenario catalog (``ChaosParams``,
  mirroring ``NemesisParams``): seven named faults with seeded
  determinism.
- ``campaign`` — the runner: boots a 3-node in-process cluster per
  scenario, drives concurrent KV clients, checks linearizability and
  the deposed-leader-never-serves invariant, and reads fault
  *detection* out of the PR-9 raft observatory.
"""

from consul_tpu.chaos.broker import FaultBroker, FaultClock, NodeFaults
from consul_tpu.chaos.scenarios import CATALOG, FAST_SCENARIOS, ChaosParams

__all__ = ["FaultBroker", "FaultClock", "NodeFaults", "ChaosParams",
           "CATALOG", "FAST_SCENARIOS"]
