"""Chaos campaign runner: one cluster per scenario, hard safety gates.

For every scenario in :mod:`consul_tpu.chaos.scenarios` this module
boots a fresh 3-server in-process cluster on the fault-injecting
``MemoryTransport`` + :class:`~consul_tpu.chaos.broker.FaultBroker`
pair, drives concurrent register clients through the fault window, and
holds the run to three verdicts:

* **linearizable** — the recorded client history passes the Wing&Gong
  checker (``tests/linearize.py``, the same oracle as the jepsen tier).
* **lease safety** — sampled continuously, at no instant do two nodes
  both consider their leader lease valid; and a node whose term trails
  the cluster maximum never serves a lease read (the deposed-leader
  gate, watched by wrapping ``lease_read_index`` on every node).
* **detected** — the injected fault must be *visible* in the PR-9 raft
  observatory (lease-margin collapse, timeline lease/leadership events,
  append-quorum tail growth, per-peer failure counters).  A fault the
  telemetry cannot see is a fault an operator cannot page on.

``worker_crash_under_load`` is the black-box leg: it forks the real
agent (``tests/blackbox_util.TestServer``) with SO_REUSEPORT workers,
SIGKILLs one worker PID mid-load, and requires the supervisor to
respawn it while the HTTP front keeps serving.

Everything is seeded: the per-scenario seed derives from the campaign
seed via crc32 (not the salted ``hash()``), so two runs with the same
``--seed`` produce the same fault schedule and the same verdicts —
the property ``make chaos-fast`` pins in CI.
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import random
import sys
import time
import zlib
from dataclasses import asdict
from typing import Any, Callable, Dict, List, Optional

from consul_tpu.chaos.broker import FaultBroker
from consul_tpu.chaos.scenarios import CATALOG, ChaosParams
from consul_tpu.consensus.raft import MemoryTransport, RaftConfig
from consul_tpu.obs import raftstats
from consul_tpu.obs.prom import render_prometheus
from consul_tpu.server.server import Server, ServerConfig
from consul_tpu.structs.structs import (
    DirEntry, HEALTH_CRITICAL, HEALTH_PASSING, KVSOp, KVSRequest,
    KeyRequest, SERF_CHECK_ID)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

KEY = "chaos/register"
NODE_NAMES = ("c0", "c1", "c2")


def scenario_seed(seed: int, name: str) -> int:
    """Stable per-scenario seed (crc32: deterministic across processes,
    unlike ``hash()`` under PYTHONHASHSEED)."""
    return (seed * 1_000_003 + zlib.crc32(name.encode())) & 0x7FFFFFFF


def _checker() -> Callable[[List[Dict[str, Any]]], bool]:
    """Borrow the single Wing&Gong implementation in the tree
    (tests/linearize.py) instead of growing a second one."""
    try:
        from linearize import check_linearizable
    except ImportError:
        sys.path.insert(0, os.path.join(_REPO_ROOT, "tests"))
        from linearize import check_linearizable
    return check_linearizable


def _prom_errors(text: str) -> List[str]:
    try:
        from tools.check_prom import check_text
    except ImportError:
        sys.path.insert(0, _REPO_ROOT)
        from tools.check_prom import check_text
    return check_text(text)


def _campaign_raft() -> RaftConfig:
    # The tests/test_leases.py fast envelope: lease window =
    # min(0.1, 0.1) * (1 - 0.15) = 85 ms, so sub-second fault windows
    # move the lease margin through whole histogram buckets.
    return RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.1,
                      election_timeout_max=0.2, rpc_timeout=0.05)


def _leader(servers: List[Server]) -> Optional[Server]:
    for s in servers:
        if s.is_leader():
            return s
    return None


# ---------------------------------------------------------------------------
# Telemetry snapshots + histogram arithmetic (detection evidence).
# ---------------------------------------------------------------------------


def _hist_counts(h: raftstats.LatencyHist) -> Dict[str, Any]:
    """De-cumulated bucket counts from the public family() shape."""
    fam = h.family()
    counts, prev = [], 0
    for _le, cum in fam["buckets"]:
        counts.append(cum - prev)
        prev = cum
    return {"edges": [le for le, _ in fam["buckets"]], "counts": counts,
            "count": fam["count"], "overflow": fam["count"] - prev}


def _hist_delta(before: Dict[str, Any], after: Dict[str, Any]
                ) -> Dict[str, Any]:
    return {"edges": after["edges"],
            "counts": [a - b for a, b in zip(after["counts"],
                                             before["counts"])],
            "count": after["count"] - before["count"],
            "overflow": after["overflow"] - before["overflow"]}


def _hist_p50(snap: Dict[str, Any]) -> Optional[float]:
    """Upper-edge p50 over a (possibly delta) bucket-count snapshot."""
    total = snap["count"]
    if total <= 0:
        return None
    need, cum = total / 2.0, 0
    for edge, c in zip(snap["edges"], snap["counts"]):
        cum += c
        if cum >= need:
            return float(edge)
    return float(snap["edges"][-1])


def _hist_tail(snap: Dict[str, Any], ge_edge_ms: float) -> int:
    """Observations at/above ``ge_edge_ms`` (overflow included)."""
    n = sum(c for edge, c in zip(snap["edges"], snap["counts"])
            if float(edge) >= ge_edge_ms)
    return n + snap["overflow"]


def _hist_low_share(snap: Dict[str, Any], le_edge_ms: float
                    ) -> Optional[float]:
    """Fraction of observations in buckets at/below ``le_edge_ms``."""
    if snap["count"] <= 0:
        return None
    low = sum(c for edge, c in zip(snap["edges"], snap["counts"])
              if float(edge) <= le_edge_ms)
    return low / snap["count"]


def _telemetry_snapshot(servers: List[Server]) -> Dict[str, Any]:
    snap: Dict[str, Any] = {}
    for s in servers:
        obs = s.raft.obs
        if obs is None:
            continue
        snap[s.config.node_name] = {
            "lease_margin": _hist_counts(obs.lease_margin),
            "append_quorum": _hist_counts(obs.append_quorum),
            "elections_started": obs.elections_started,
            "leadership_gained": obs.leadership_gained,
            "leadership_lost": obs.leadership_lost,
            "peer_failed": {r["peer"]: r["rpc_failed"]
                            for r in obs.peer_rows(s.raft)},
        }
    return snap


def _timeline_since(server: Server, t_wall: float,
                    kinds: Optional[tuple] = None) -> List[Dict[str, Any]]:
    obs = server.raft.obs
    if obs is None:
        return []
    return [ev for ev in obs.timeline()
            if ev["t"] >= t_wall and (kinds is None or ev["kind"] in kinds)]


# ---------------------------------------------------------------------------
# Hard-gate monitors.
# ---------------------------------------------------------------------------


class _LeaseMonitors:
    """Live watchers for the two lease hard gates.

    Single-holder: every few milliseconds, count the nodes whose
    ``lease_valid()`` is true — two simultaneous holders is a
    split-brain lease.  Deposed-serve: wrap every node's
    ``lease_read_index`` so a non-None return from a node whose term
    trails the cluster max (a leader that has been deposed but does not
    know it yet) is recorded as a violation.
    """

    def __init__(self, servers: List[Server]) -> None:
        self.servers = servers
        self.multi_holder: List[Dict[str, Any]] = []
        self.deposed_serve: List[Dict[str, Any]] = []
        self._task: Optional[asyncio.Task] = None
        for s in servers:
            self._wrap(s)

    def _wrap(self, srv: Server) -> None:
        orig = srv.raft.lease_read_index

        def wrapped() -> Optional[int]:
            idx = orig()
            if idx is not None:
                mx = max(x.raft.current_term for x in self.servers)
                if srv.raft.current_term < mx:
                    self.deposed_serve.append({
                        "t": time.time(), "node": srv.config.node_name,
                        "term": srv.raft.current_term, "max_term": mx,
                        "read_index": idx})
            return idx

        srv.raft.lease_read_index = wrapped  # type: ignore[method-assign]

    def start(self) -> None:
        self._task = asyncio.create_task(self._sample())

    async def _sample(self) -> None:
        while True:
            holders = [s.config.node_name for s in self.servers
                       if s.raft.lease_valid()]
            if len(holders) > 1:
                self.multi_holder.append(
                    {"t": time.time(), "holders": holders})
            await asyncio.sleep(0.004)

    async def stop(self) -> None:
        # Swap-then-cancel: overlapping stop() calls would otherwise
        # both await the same task and both try to null it afterwards.
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass


# ---------------------------------------------------------------------------
# Register clients (the jepsen-tier shape, time-bounded).
# ---------------------------------------------------------------------------


async def _write_any(servers: List[Server], val: int,
                     rng: random.Random) -> None:
    last: Optional[Exception] = None
    for s in rng.sample(servers, len(servers)):
        try:
            await s.kvs.apply(KVSRequest(
                datacenter="dc1", op=KVSOp.SET.value,
                dir_ent=DirEntry(key=KEY, value=str(val).encode())))
            return
        except Exception as e:  # not leader / partitioned: try next
            last = e
            await asyncio.sleep(0.02)
    assert last is not None
    raise last


async def _read_any(servers: List[Server], rng: random.Random
                    ) -> Optional[int]:
    last: Optional[Exception] = None
    for s in rng.sample(servers, len(servers)):
        try:
            _, out = await s.kvs.get(KeyRequest(
                datacenter="dc1", key=KEY, require_consistent=True))
            if not out:
                return None
            return int(out[0].value.decode())
        except Exception as e:
            last = e
            await asyncio.sleep(0.02)
    assert last is not None
    raise last


async def _client(cid: int, servers: List[Server],
                  clock: Callable[[], float],
                  history: List[Dict[str, Any]], p: ChaosParams,
                  rng: random.Random) -> None:
    seq = 0
    # Time-bounded so clients always straddle the fault window; the op
    # cap is a runaway guard, not the planned volume.
    while clock() < p.run_s and seq < p.ops_per_client * 6:
        val = cid * 100_000 + seq
        seq += 1
        do_write = rng.random() < 0.5
        t_inv = clock()
        ok, ret = False, None
        try:
            if do_write:
                await asyncio.wait_for(
                    _write_any(servers, val, rng), timeout=2.0)
            else:
                ret = await asyncio.wait_for(
                    _read_any(servers, rng), timeout=2.0)
            ok = True
        except Exception:
            ok = False
        history.append({
            "op": "w" if do_write else "r",
            "arg": val if do_write else None,
            "ret": ret,
            "t_inv": t_inv,
            "t_ret": clock() if ok else math.inf,
            "ok": ok,
        })
        await asyncio.sleep(rng.uniform(0.005, 0.03))


# ---------------------------------------------------------------------------
# Fault drivers: translate ChaosParams into broker/clock actions.
# ---------------------------------------------------------------------------


def _heal(broker: FaultBroker, servers: List[Server]) -> None:
    broker.clear_links()
    for s in servers:
        nf = broker.node(s.config.node_name)
        nf.clock.set_rate(1.0)
        nf.fsync_stall_s = 0.0
        nf.fsync_err_p = 0.0


async def _drive_fault(name: str, p: ChaosParams, broker: FaultBroker,
                       servers: List[Server], ev: Dict[str, Any]) -> None:
    loop = asyncio.get_event_loop()
    await asyncio.sleep(p.start)
    leader = _leader(servers)
    lname = (leader.config.node_name if leader is not None
             else servers[0].config.node_name)
    ev["leader_at_start"] = lname
    ev["window_wall"] = [time.time(), None]
    ev["baseline"] = _telemetry_snapshot(servers)
    window = p.stop - p.start
    try:
        if name == "clock_skew":
            broker.node(lname).clock.set_rate(p.clock_rate)
            await asyncio.sleep(window)
            broker.node(lname).clock.set_rate(1.0)
        elif name == "clock_jump":
            broker.node(lname).clock.jump(p.clock_jump_s)
            await asyncio.sleep(window)
        elif name == "fsync_stall":
            # All nodes: a 3-node quorum commits on two follower acks,
            # so stalling only the leader's pump stalls nothing.
            for s in servers:
                broker.node(s.config.node_name).fsync_stall_s = \
                    p.fsync_stall_s
                broker.node(s.config.node_name).fsync_err_p = p.fsync_err_p
            await asyncio.sleep(window)
            for s in servers:
                broker.node(s.config.node_name).fsync_stall_s = 0.0
                broker.node(s.config.node_name).fsync_err_p = 0.0
        elif name == "reconcile_fsync_stall":
            # The fused write path (PR 18) under the disk fault: stream
            # synthetic member transitions into the leader's reconcile
            # queue while every fsync stalls — the batched reconciler
            # must coalesce each burst into one BATCH envelope and every
            # ghost must still land in the catalog.
            from consul_tpu.agent.reconcile import reconstats
            from consul_tpu.membership.swim import (
                STATE_ALIVE, STATE_DEAD, Node as GossipNode)
            ev["reconcile_base"] = {
                "batches_total": reconstats.batches_total,
                "entries_coalesced": reconstats.entries_coalesced,
                "submit_failures": reconstats.submit_failures,
            }
            # Journey-ledger baseline: the detect pass diffs the
            # per-stage sums across the fault window and requires the
            # stalled append->quorum stage to dominate the delta.
            # None when the ledger is compiled out (gate skipped).
            from consul_tpu.obs import journey as _journey
            ev["journey_base"] = (
                _journey.journey.stage_sums()
                if _journey.journey is not None else None)
            ghosts = [f"ghost{i}" for i in range(8)]
            ev["ghosts"] = ghosts
            ev["ghost_failed"] = ghosts[:4]
            for s in servers:
                broker.node(s.config.node_name).fsync_stall_s = \
                    p.fsync_stall_s
            ld = _leader(servers) or servers[0]
            # One synchronous burst of put_nowait's: the whole join wave
            # is queued before the reconcile loop wakes, so it must
            # share one append.
            for i, g in enumerate(ghosts):
                ld.membership_notify("member-join", GossipNode(
                    name=g, addr=f"10.99.0.{i + 1}", port=8301,
                    state=STATE_ALIVE))
            await asyncio.sleep(window / 2)
            ld = _leader(servers) or ld
            for i, g in enumerate(ev["ghost_failed"]):
                ld.membership_notify("member-failed", GossipNode(
                    name=g, addr=f"10.99.0.{i + 1}", port=8301,
                    state=STATE_DEAD))
            await asyncio.sleep(window / 2)
            for s in servers:
                broker.node(s.config.node_name).fsync_stall_s = 0.0
        elif name == "leader_flap":
            t_end = loop.time() + window
            while loop.time() < t_end:
                ld = _leader(servers)
                if ld is not None:
                    victim = ld.config.node_name
                    broker.isolate(victim)
                    await asyncio.sleep(p.flap_down_s)
                    broker.rejoin(victim)
                rest = min(max(p.flap_period_s - p.flap_down_s, 0.05),
                           max(t_end - loop.time(), 0.0))
                if rest <= 0:
                    break
                await asyncio.sleep(rest)
        elif name in ("asym_partition", "slow_follower"):
            followers = sorted(s.config.node_name for s in servers
                               if s.config.node_name != lname)
            victim = followers[0]
            ev["victim"] = victim
            # a = leader, b = victim (the scenarios.py convention).
            if p.drop_ab or p.delay_ab_s:
                broker.set_link(lname, victim, drop=p.drop_ab,
                                delay_s=p.delay_ab_s)
            if p.drop_ba or p.delay_ba_s:
                broker.set_link(victim, lname, drop=p.drop_ba,
                                delay_s=p.delay_ba_s)
            await asyncio.sleep(window)
            broker.clear_links()
        else:  # pragma: no cover - catalog and driver move together
            raise ValueError(f"no driver for scenario {name!r}")
    finally:
        ev["window_wall"][1] = time.time()


# ---------------------------------------------------------------------------
# Detection: the fault must be visible in the observatory.
# ---------------------------------------------------------------------------


def _detect(name: str, p: ChaosParams, servers: List[Server],
            ev: Dict[str, Any]) -> Dict[str, Any]:
    base = ev.get("baseline") or {}
    lname = ev.get("leader_at_start")
    t_start = (ev.get("window_wall") or [0.0, None])[0]
    end = _telemetry_snapshot(servers)
    by_name = {s.config.node_name: s for s in servers}
    detected, evidence = False, {}

    if name in ("clock_skew",):
        # A fast leader oscillator burns the lease window early: the
        # send-time margin samples slide into the low buckets (and,
        # through heartbeat-paced gaps, under zero — lease-lost flips).
        b, e = base.get(lname), end.get(lname)
        if b and e:
            delta = _hist_delta(b["lease_margin"], e["lease_margin"])
            base_low = _hist_low_share(b["lease_margin"], 50.0)
            win_low = _hist_low_share(delta, 50.0)
            lost = _timeline_since(by_name[lname], t_start, ("lease-lost",))
            detected = bool(
                (win_low is not None and base_low is not None
                 and win_low > base_low + 0.10) or lost)
            evidence = {"baseline_low_share": base_low,
                        "window_low_share": win_low,
                        "window_samples": delta["count"],
                        "lease_lost_events": len(lost)}
    elif name == "clock_jump":
        events = _timeline_since(
            by_name[lname], t_start,
            ("lease-lost", "lease-acquired", "leader-deposed"))
        detected = any(ev_["kind"] == "lease-lost" for ev_ in events)
        evidence = {"timeline": events}
    elif name == "fsync_stall":
        b, e = base.get(lname), end.get(lname)
        if b and e:
            delta = _hist_delta(b["append_quorum"], e["append_quorum"])
            tail = _hist_tail(delta, 100.0)
            lost = _timeline_since(by_name[lname], t_start, ("lease-lost",))
            detected = tail >= 1
            evidence = {"append_quorum_ge_100ms": tail,
                        "window_appends": delta["count"],
                        "lease_lost_events": len(lost)}
    elif name == "reconcile_fsync_stall":
        # Three-way evidence: the batched reconciler coalesced (its
        # counters moved), every injected ghost reached the catalog with
        # the right serfHealth verdict, and the disk fault itself shows
        # in the append_quorum tail like plain fsync_stall.
        from consul_tpu.agent.reconcile import reconstats
        base_rc = ev.get("reconcile_base") or {}
        ld = _leader(servers) or by_name.get(lname) or servers[0]
        batches = (reconstats.batches_total
                   - base_rc.get("batches_total", 0))
        coalesced = (reconstats.entries_coalesced
                     - base_rc.get("entries_coalesced", 0))
        failures = (reconstats.submit_failures
                    - base_rc.get("submit_failures", 0))
        ghosts = ev.get("ghosts") or []
        failed_set = set(ev.get("ghost_failed") or [])
        landed = states_ok = 0
        for g in ghosts:
            _, addr = ld.store.get_node(g)
            if addr is None:
                continue
            landed += 1
            _, checks = ld.store.node_checks(g)
            serf = next((c for c in checks
                         if c.check_id == SERF_CHECK_ID), None)
            want = (HEALTH_CRITICAL if g in failed_set
                    else HEALTH_PASSING)
            if serf is not None and serf.status == want:
                states_ok += 1
        b, e = base.get(lname), end.get(lname)
        tail = 0
        if b and e:
            delta = _hist_delta(b["append_quorum"], e["append_quorum"])
            tail = _hist_tail(delta, 100.0)
        # Journey detectability: across the fault window the ledger's
        # stage-sum delta must be DOMINATED by append_quorum — the
        # stalled disk is where the transition time went, and the
        # ledger must say so.  Skipped (vacuously true) when the
        # ledger is compiled out.
        from consul_tpu.obs import journey as _journey
        jbase = ev.get("journey_base")
        journey_ok = True
        jev: Dict[str, Any] = {"journey_dominant_stage": None}
        if jbase is not None and _journey.journey is not None:
            sums = _journey.journey.stage_sums()
            jdelta = {s: round(sums[s] - jbase.get(s, 0.0), 3)
                      for s in sums}
            dominant = max(jdelta, key=lambda s: jdelta[s])
            journey_ok = (dominant == "append_quorum"
                          and jdelta["append_quorum"] > 0.0)
            jev = {"journey_dominant_stage": dominant,
                   "journey_stage_delta_ms": jdelta}
        detected = (batches >= 1 and coalesced >= 1
                    and landed == len(ghosts)
                    and states_ok == len(ghosts) and tail >= 1
                    and journey_ok)
        evidence = {"batches_delta": batches,
                    "entries_coalesced_delta": coalesced,
                    "submit_failures_delta": failures,
                    "ghosts": len(ghosts), "ghosts_in_catalog": landed,
                    "ghost_states_correct": states_ok,
                    "append_quorum_ge_100ms": tail, **jev}
    elif name == "leader_flap":
        lost = sum(e["leadership_lost"] - base.get(n, e)["leadership_lost"]
                   for n, e in end.items())
        gained = sum(e["leadership_gained"]
                     - base.get(n, e)["leadership_gained"]
                     for n, e in end.items())
        events: List[Dict[str, Any]] = []
        for s in servers:
            events += _timeline_since(
                s, t_start, ("leader-deposed", "leader-elected"))
        detected = lost >= 1 and gained >= 1
        evidence = {"leadership_lost": lost, "leadership_gained": gained,
                    "timeline": sorted(events, key=lambda x: x["t"])}
    elif name in ("asym_partition", "slow_follower"):
        victim = ev.get("victim")
        b, e = base.get(lname), end.get(lname)
        if b and e and victim:
            failed = (e["peer_failed"].get(victim, 0)
                      - b["peer_failed"].get(victim, 0))
            v_elections = (end.get(victim, {}).get("elections_started", 0)
                           - base.get(victim, {}).get("elections_started", 0))
            obs = by_name[lname].raft.obs
            rows = obs.peer_rows(by_name[lname].raft) if obs else []
            row = next((r for r in rows if r["peer"] == victim), None)
            detected = failed >= 3
            if name == "slow_follower":
                # Delayed-but-delivered heartbeats must keep the victim
                # from starting elections: slow, not partitioned.
                detected = detected and v_elections == 0
            evidence = {"victim": victim, "rpc_failed_delta": failed,
                        "victim_elections_delta": v_elections,
                        "peer_row": row}
    return {"detected": detected, "evidence": evidence}


# ---------------------------------------------------------------------------
# Per-scenario runs.
# ---------------------------------------------------------------------------


def _sanitize(obj: Any) -> Any:
    """JSON-safe copy: math.inf (timed-out t_ret) -> None."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _write_bundle(sdir: str, p: ChaosParams, history: List[Dict[str, Any]],
                  telemetry: Dict[str, Any], prom_text: str,
                  result: Dict[str, Any]) -> None:
    os.makedirs(sdir, exist_ok=True)
    with open(os.path.join(sdir, "params.json"), "w") as f:
        json.dump(asdict(p), f, indent=2)
    with open(os.path.join(sdir, "history.json"), "w") as f:
        json.dump(_sanitize(history), f, indent=2)
    with open(os.path.join(sdir, "telemetry.json"), "w") as f:
        json.dump(_sanitize(telemetry), f, indent=2)
    with open(os.path.join(sdir, "prom.txt"), "w") as f:
        f.write(prom_text)
    with open(os.path.join(sdir, "verdict.json"), "w") as f:
        json.dump(_sanitize(result), f, indent=2)


async def _scenario_main(name: str, p: ChaosParams, sseed: int,
                         sdir: str) -> Dict[str, Any]:
    check = _checker()
    broker = FaultBroker(seed=sseed)
    tr = MemoryTransport(faults=broker)
    names = list(NODE_NAMES)
    servers = [Server(ServerConfig(node_name=nm, peers=names,
                                   raft=_campaign_raft(),
                                   faults=broker.node(nm)), transport=tr)
               for nm in names]
    for s in servers:
        await s.start()
    deadline = asyncio.get_event_loop().time() + 10.0
    while _leader(servers) is None:
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"{name}: no leader elected")
        await asyncio.sleep(0.01)

    monitors = _LeaseMonitors(servers)
    monitors.start()
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    clock = lambda: loop.time() - t0  # noqa: E731

    history: List[Dict[str, Any]] = []
    ev: Dict[str, Any] = {}
    driver = asyncio.create_task(_drive_fault(name, p, broker, servers, ev))
    clients = [asyncio.create_task(
        _client(cid, servers, clock, history, p,
                random.Random(f"{sseed}/client/{cid}")))
        for cid in range(p.clients)]
    try:
        await asyncio.wait_for(asyncio.gather(*clients), timeout=60.0)
        await asyncio.wait_for(driver, timeout=30.0)
    finally:
        driver.cancel()
        _heal(broker, servers)
        await monitors.stop()
    await asyncio.sleep(0.1)  # let post-heal lease transitions land

    detection = _detect(name, p, servers, ev)
    telemetry = {s.config.node_name:
                 (s.raft.obs.wire(s.raft) if s.raft.obs is not None else None)
                 for s in servers}
    prom_node = _leader(servers) or servers[0]
    hists, gauges, counters = raftstats.prom_families(prom_node.raft)
    prom_text = render_prometheus([], histograms=hists,
                                  labeled_gauges=gauges,
                                  labeled_counters=counters)
    prom_errs = _prom_errors(prom_text)
    for s in servers:
        await s.stop()

    n_w = sum(1 for h in history if h["ok"] and h["op"] == "w")
    n_r = sum(1 for h in history if h["ok"] and h["op"] == "r")
    linearizable = check(history)
    gates = {
        "linearizable": bool(linearizable),
        "single_lease_holder": not monitors.multi_holder,
        "no_deposed_serve": not monitors.deposed_serve,
        "progress": n_w >= 3 and n_r >= 3,
        "prom_valid": not prom_errs,
    }
    result = {
        "scenario": name,
        "seed": sseed,
        "mode": "in-process",
        "ops": {"total": len(history), "writes_ok": n_w, "reads_ok": n_r,
                "failed": sum(1 for h in history if not h["ok"])},
        "gates": gates,
        "violations": {"multi_holder": monitors.multi_holder,
                       "deposed_serve": monitors.deposed_serve},
        "detection": detection,
        "prom_errors": prom_errs,
        "fault_window": ev.get("window_wall"),
        "leader_at_fault": ev.get("leader_at_start"),
        "pass": all(gates.values()) and detection["detected"],
    }
    _write_bundle(sdir, p, history, telemetry, prom_text, result)
    return result


# ---------------------------------------------------------------------------
# Black-box leg: kill a real SO_REUSEPORT worker under HTTP load.
# ---------------------------------------------------------------------------


def _worker_pids(agent_pid: int) -> List[int]:
    """Live worker children of the forked agent, via /proc."""
    try:
        with open(f"/proc/{agent_pid}/task/{agent_pid}/children") as f:
            kids = [int(x) for x in f.read().split()]
    except OSError:
        return []
    out = []
    for pid in kids:
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode()
        except OSError:
            continue
        if "consul_tpu.agent.workers" in cmd:
            out.append(pid)
    return out


def _run_worker_crash(name: str, p: ChaosParams, sseed: int,
                      sdir: str) -> Dict[str, Any]:
    import base64
    import signal as _signal
    import urllib.error

    sys.path.insert(0, os.path.join(_REPO_ROOT, "tests"))
    from blackbox_util import TestServer

    check = _checker()
    rng = random.Random(f"{sseed}/bb")
    history: List[Dict[str, Any]] = []
    killed: Optional[int] = None
    respawned: Optional[int] = None
    before: List[int] = []
    t_kill = t_respawn = None
    ok_after_kill = 0

    ts = TestServer(name="chaos-wc", config_extra={"http_workers": 3})
    ts.start()
    try:
        ts.wait_for_api(30.0)
        ts.wait_for_leader(30.0)
        agent_pid = ts.proc.pid
        # http_workers=3 forks workers-1 = 2 children.
        deadline = time.monotonic() + 10.0
        while len(_worker_pids(agent_pid)) < 2:
            if time.monotonic() > deadline:
                raise TimeoutError("worker children never appeared")
            time.sleep(0.1)

        t0 = time.monotonic()
        clock = lambda: time.monotonic() - t0  # noqa: E731
        seq = 0
        while clock() < p.run_s:
            now = clock()
            if killed is None and now >= p.start:
                before = sorted(_worker_pids(agent_pid))
                killed = before[0]
                os.kill(killed, _signal.SIGKILL)
                t_kill = now
            if killed is not None and respawned is None:
                fresh = [pid for pid in _worker_pids(agent_pid)
                         if pid not in before]
                if fresh:
                    respawned = fresh[0]
                    t_respawn = clock()
            do_write = rng.random() < 0.5
            t_inv = clock()
            ok, ret, val = False, None, seq
            try:
                if do_write:
                    ts.http_put(f"/v1/kv/{KEY}", str(val).encode())
                else:
                    try:
                        got = ts.http_get(f"/v1/kv/{KEY}?consistent")
                        if got:
                            ret = int(base64.b64decode(
                                got[0]["Value"]).decode())
                    except urllib.error.HTTPError as he:
                        if he.code != 404:  # 404 = empty register
                            raise
                ok = True
            except Exception:
                ok = False
            if ok and killed is not None:
                ok_after_kill += 1
            history.append({"op": "w" if do_write else "r",
                            "arg": val if do_write else None, "ret": ret,
                            "t_inv": t_inv,
                            "t_ret": clock() if ok else math.inf, "ok": ok})
            if do_write:
                seq += 1
            time.sleep(rng.uniform(0.01, 0.04))

        # Give the 0.5 s supervisor poll one more beat if needed.
        deadline = time.monotonic() + 3.0
        while respawned is None and time.monotonic() < deadline:
            fresh = [pid for pid in _worker_pids(agent_pid)
                     if pid not in before]
            if fresh:
                respawned = fresh[0]
                t_respawn = clock()
            time.sleep(0.1)
        agent_log = ts.output()[-4000:]
    finally:
        ts.stop()

    n_w = sum(1 for h in history if h["ok"] and h["op"] == "w")
    n_r = sum(1 for h in history if h["ok"] and h["op"] == "r")
    linearizable = check(history)
    detection = {
        "detected": (killed is not None and respawned is not None
                     and ok_after_kill >= 1),
        "evidence": {"killed_pid": killed, "respawned_pid": respawned,
                     "workers_before_kill": before,
                     "t_kill_s": t_kill, "t_respawn_s": t_respawn,
                     "ok_ops_after_kill": ok_after_kill},
    }
    gates = {
        "linearizable": bool(linearizable),
        # Single forked agent = single raft node; the lease gates are
        # held by construction and by the in-process scenarios.
        "single_lease_holder": True,
        "no_deposed_serve": True,
        "progress": n_w >= 3 and n_r >= 3,
        "prom_valid": True,
    }
    result = {
        "scenario": name,
        "seed": sseed,
        "mode": "blackbox",
        "ops": {"total": len(history), "writes_ok": n_w, "reads_ok": n_r,
                "failed": sum(1 for h in history if not h["ok"])},
        "gates": gates,
        "violations": {"multi_holder": [], "deposed_serve": []},
        "detection": detection,
        "prom_errors": [],
        "pass": all(gates.values()) and detection["detected"],
    }
    _write_bundle(sdir, p, history, {"agent_log_tail": agent_log}, "", result)
    return result


# ---------------------------------------------------------------------------
# Campaign entry point.
# ---------------------------------------------------------------------------


def run_campaign(scenarios: List[str], seed: int = 1234,
                 out_dir: str = "chaos_debug") -> Dict[str, Any]:
    """Run ``scenarios`` (names into CATALOG) and return the CHAOS.json
    report dict.  Each scenario gets a fresh event loop, a fresh
    cluster, and a crc32-derived per-scenario seed."""
    os.environ["CONSUL_TPU_RAFT_OBS"] = "1"
    results = []
    for name in scenarios:
        p = CATALOG[name]
        sseed = scenario_seed(seed, name)
        sdir = os.path.join(out_dir, name)
        try:
            if p.blackbox:
                res = _run_worker_crash(name, p, sseed, sdir)
            else:
                res = asyncio.run(_scenario_main(name, p, sseed, sdir))
        except Exception as e:
            res = {"scenario": name, "seed": sseed, "pass": False,
                   "error": f"{type(e).__name__}: {e}"}
        results.append(res)
    return {"campaign_seed": seed,
            "scenarios": results,
            "passed": all(r.get("pass") for r in results)}
