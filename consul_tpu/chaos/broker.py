"""The fault broker: injectable failure seams for the host plane.

Three independent fault surfaces, all OFF by default so a broker-less
node (``faults=None`` everywhere) pays a single is-None test per seam:

- **Clocks** — every ``RaftNode`` time read that feeds lease/election
  safety goes through ``NodeFaults.clock`` (a :class:`FaultClock`)
  instead of ``time.monotonic``.  The virtual clock can run at a
  skewed *rate* (a slow or fast oscillator) or take step *jumps*
  (NTP slew, VM migration) — the two failure modes the
  ``lease_clock_skew`` discount exists to survive.
- **Durability** — ``NodeFaults.wrap_fsync`` wraps the log store's
  ``sync`` callable (Python segment log or the C++ mmap store alike —
  the pump is the single choke point both backends share).  The wrapper
  runs in the executor thread the durability pump already uses, so an
  injected stall blocks exactly what a pathological disk would block:
  the fsync, never the event loop (BENCH_NOTES §2 is the incident this
  reproduces on demand).
- **Links** — directional per-edge drop probability and delay,
  consulted by ``MemoryTransport.call`` once for the request leg
  (src→dst) and once for the reply leg (dst→src), so asymmetric
  partitions ("acks die, probes arrive") are expressible the same way
  ``NemesisParams.p_ab``/``p_ba`` express them for gossip.

Worker kill/restart control needs no broker state: ``WorkerPool``
(agent/workers.py) exposes ``kill_one``/``reap_dead``/``respawn_dead``
by tracked PID and the campaign drives those directly.

Determinism: the broker owns one seeded ``random.Random`` for link
decisions (event-loop thread) and hands each node a *derived* seed for
fsync error draws (executor threads), so no RNG is shared across
threads.
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Callable, Dict, Optional, Tuple

from consul_tpu.consensus.raft import TransportError


class FaultClock:
    """A monotonic-ish virtual clock: ``virt = anchor + (real -
    real_anchor) * rate``.  Rate changes re-anchor so the virtual time
    is continuous across them; ``jump`` deliberately is NOT continuous
    (that is the fault).  ``base`` is injectable for deterministic
    tests."""

    def __init__(self, base: Callable[[], float] = time.monotonic) -> None:
        self._base = base
        self._rate = 1.0
        self._real_anchor = base()
        self._virt_anchor = self._real_anchor

    @property
    def rate(self) -> float:
        return self._rate

    def monotonic(self) -> float:
        return (self._virt_anchor
                + (self._base() - self._real_anchor) * self._rate)

    def set_rate(self, rate: float) -> None:
        now_virt = self.monotonic()
        self._real_anchor = self._base()
        self._virt_anchor = now_virt
        self._rate = float(rate)

    def jump(self, dt: float) -> None:
        """Step the clock by ``dt`` seconds (negative = backward — the
        direction that eats the lease safety margin)."""
        self._virt_anchor += dt

    def drift(self) -> float:
        """Accumulated virtual-minus-real offset, seconds.  The
        campaign records this as ground truth of what was injected."""
        return self.monotonic() - self._base()


class NodeFaults:
    """Per-node fault view handed to ``RaftNode`` via
    ``ServerConfig.faults``.  Knobs are read at use time, so the
    campaign can flip them mid-run."""

    def __init__(self, broker: "FaultBroker", name: str) -> None:
        self.broker = broker
        self.name = name
        self.clock = FaultClock()
        self.fsync_stall_s = 0.0
        self.fsync_err_p = 0.0
        # Executor-thread RNG, derived seed: never shared with the
        # broker's event-loop RNG.
        self._fsync_rng = random.Random(f"{broker.seed}/{name}/fsync")

    def wrap_fsync(self, sync_fn: Callable[[], None]) -> Callable[[], None]:
        """Wrap a log store's ``sync`` for the durability pump.  The
        wrapper body runs in the pump's executor thread — ``time.sleep``
        here stalls the fsync exactly like a seized disk, and an
        injected ``OSError`` rides the pump's existing retry path."""
        def synced() -> None:
            stall = self.fsync_stall_s
            if stall > 0.0:
                time.sleep(stall)
            if self.fsync_err_p > 0.0 \
                    and self._fsync_rng.random() < self.fsync_err_p:
                raise OSError(f"chaos: injected fsync error on {self.name}")
            sync_fn()
        return synced


class FaultBroker:
    """Cluster-wide fault state: per-node views + the directional link
    table.  One broker per (in-process) cluster; attach with
    ``MemoryTransport(faults=broker)`` and
    ``ServerConfig(faults=broker.node(name))``."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self.rng = random.Random(seed)
        self._nodes: Dict[str, NodeFaults] = {}
        # (src, dst) -> (drop probability, delay seconds)
        self._links: Dict[Tuple[str, str], Tuple[float, float]] = {}

    def node(self, name: str) -> NodeFaults:
        nf = self._nodes.get(name)
        if nf is None:
            nf = self._nodes[name] = NodeFaults(self, name)
        return nf

    def nodes(self) -> Dict[str, NodeFaults]:
        return dict(self._nodes)

    # -- directional links --------------------------------------------------

    def set_link(self, src: str, dst: str, drop: float = 0.0,
                 delay_s: float = 0.0) -> None:
        if drop <= 0.0 and delay_s <= 0.0:
            self._links.pop((src, dst), None)
        else:
            self._links[(src, dst)] = (drop, delay_s)

    def clear_links(self) -> None:
        self._links.clear()

    def isolate(self, name: str) -> None:
        """Full bidirectional cut between ``name`` and every other
        registered node (the leader_flap down-phase)."""
        for other in self._nodes:
            if other != name:
                self.set_link(name, other, drop=1.0)
                self.set_link(other, name, drop=1.0)

    def rejoin(self, name: str) -> None:
        for other in list(self._nodes):
            self.set_link(name, other)
            self.set_link(other, name)

    async def on_message(self, src: str, dst: str) -> None:
        """One directed message leg.  Raises ``TransportError`` on a
        drop; sleeps the configured delay otherwise.  Called by the
        transport for the request leg and again (reversed) for the
        reply leg."""
        entry = self._links.get((src, dst))
        if entry is None:
            return
        drop, delay = entry
        if drop > 0.0 and (drop >= 1.0 or self.rng.random() < drop):
            raise TransportError(f"chaos: {src} -> {dst} dropped")
        if delay > 0.0:
            await asyncio.sleep(delay)


def filter_from_broker(broker: Optional[FaultBroker], src: str,
                       dst: str) -> Optional[Callable]:
    """Adapt a broker edge into the TCP-layer ``fault_filter`` hook
    shape (rpc/pool.py outbound, rpc/server.py inbound): an async
    callable that drops or delays one exchange.  ``None`` broker →
    ``None`` filter (the hooks stay cold)."""
    if broker is None:
        return None

    async def _filter(*_a, **_kw) -> None:
        await broker.on_message(src, dst)
    return _filter
