"""Chaos scenario catalog: declarative fault schedules for the host plane.

The consensus-plane mirror of ``gossip/nemesis.py``: one frozen
:class:`ChaosParams` per scenario, scalars only, with the fault window
``[start, stop)`` expressed in seconds of campaign wall time (the
gossip catalog counts protocol rounds; the host plane has no round
clock).  The campaign (chaos/campaign.py) interprets the schedule
against a live 3-node cluster; nothing here touches asyncio.

Every scenario is calibrated to sit INSIDE the safety envelope the
stack claims to survive — e.g. ``clock_skew`` runs the leader's clock
fast, the conservative direction (the lease expires early and reads
fall back to the barrier path; a slow clock beyond
``lease_clock_skew`` would genuinely break the invariant, and pinning
that exact boundary is tests/test_leases.py's job, not the campaign's).
The campaign therefore gates on linearizability + deposed-leader-
never-serves for every scenario, and separately asserts the fault was
*detected* in the raft observatory (lease-margin histogram shifts,
leadership-timeline events, per-peer replication counters).

The ``fault`` membership check in ``__post_init__`` is the governing
key set for the table-drift vet pass (tools/vet/table_drift.py K01/K02):
``CATALOG``'s keys and the campaign CLI's ``--scenario`` choices are
drift-checked against it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class ChaosParams:
    """One scenario's injection schedule + run shape.  Frozen scalars
    only, mirroring ``NemesisParams`` — a schedule is a value, not a
    process."""

    fault: str = ""            # scenario name (governing set below)
    start: float = 0.5         # fault window [start, stop), seconds
    stop: float = 1.6
    run_s: float = 2.4         # total client-traffic duration
    clients: int = 3
    ops_per_client: int = 22

    # -- clock faults (applied to the elected leader's node clock) -------
    clock_rate: float = 1.0    # virtual-clock rate during the window
    clock_jump_s: float = 0.0  # one step jump at window start

    # -- durability faults (applied to every node's fsync path) ----------
    fsync_stall_s: float = 0.0   # per-fsync stall inside the window
    fsync_err_p: float = 0.0     # P(injected OSError per fsync)

    # -- link faults (leader -> victim follower = a->b direction) --------
    drop_ab: float = 0.0
    drop_ba: float = 0.0
    delay_ab_s: float = 0.0
    delay_ba_s: float = 0.0

    # -- leader flapping (full isolate/heal square wave) -----------------
    flap_period_s: float = 0.0   # isolate leader every period...
    flap_down_s: float = 0.0     # ...for this long

    # -- serving-front faults (blackbox worker plane) --------------------
    worker_kills: int = 0        # SIGKILLed workers under HTTP load

    def __post_init__(self) -> None:
        if self.fault not in ("clock_skew", "clock_jump", "fsync_stall",
                              "leader_flap", "asym_partition",
                              "slow_follower", "worker_crash_under_load",
                              "reconcile_fsync_stall"):
            raise ValueError(f"unknown chaos scenario {self.fault!r}")
        if not 0.0 <= self.start <= self.stop:
            raise ValueError("fault window must satisfy 0 <= start <= stop")

    @property
    def blackbox(self) -> bool:
        """True when the scenario forks a real agent (worker plane)
        instead of booting the in-process cluster."""
        return self.worker_kills > 0


# The catalog.  Timing is calibrated for the campaign's compressed raft
# config (heartbeat 20ms, election 100-200ms, lease window
# 100ms * (1 - 0.15) = 85ms):
#
# - clock_skew: leader oscillator 5x fast — virtual time between lease
#   renewals (a 20ms heartbeat gap reads as 100ms > the 85ms window)
#   eats the window, so the send-time lease-margin samples slide into
#   the low buckets and heartbeat-paced gaps flip the lease invalid
#   (the detection signals), while staying on the SAFE side (a fast
#   clock only ever under-claims the lease).
# - clock_jump: one +200ms step (> the whole window) invalidates the
#   lease instantly — a lease-lost / lease-acquired pair on the
#   leadership timeline is the detection signal.
# - fsync_stall: 300ms per fsync on EVERY node (stalling only the
#   leader does nothing in a 3-node cluster: the quorum-th match index
#   comes from the two followers).  Commits stall behind durability,
#   pushing append_quorum mass into the >=250ms buckets; empty
#   heartbeats still renew leadership, so the cluster slows rather
#   than flaps — exactly the BENCH_NOTES §2 disk incident, minus the
#   leadership collapse the durability pump was built to prevent.
# - leader_flap: isolate the current leader 250ms out of every 700ms —
#   repeated depose/elect cycles on the timeline, the PR-13 shutdown
#   fixes' natural habitat.
# - asym_partition: victim->leader direction drops (acks die, appends
#   arrive): the victim's log stays current but its match index
#   freezes, so peer_rpc_failed and match-lag gauges carry the signal.
# - slow_follower: 40ms each way to the victim pushes its replication
#   round-trip past rpc_timeout (50ms): every round times out yet
#   delivers, so the victim never misses a heartbeat while its
#   rpc_failed counter climbs.
# - worker_crash_under_load: blackbox — fork a real agent with 3
#   SO_REUSEPORT workers, SIGKILL one mid-load, and require the
#   supervisor to respawn it while HTTP traffic keeps succeeding.
# - reconcile_fsync_stall: the PR-18 fused write path under the disk
#   fault — synthetic membership transitions stream into the leader's
#   reconcile queue while every fsync stalls 300ms.  The stall widens
#   the batched reconciler's linger window, so transitions MUST
#   coalesce (entries_coalesced climbs) and every injected node must
#   still land in the catalog with a serfHealth verdict; append_quorum
#   tail shows the stall like plain fsync_stall.
CATALOG = {
    "clock_skew": ChaosParams(fault="clock_skew", clock_rate=5.0),
    "clock_jump": ChaosParams(fault="clock_jump", clock_jump_s=0.2,
                              run_s=2.0, stop=1.4),
    "fsync_stall": ChaosParams(fault="fsync_stall", fsync_stall_s=0.3,
                               ops_per_client=16),
    "leader_flap": ChaosParams(fault="leader_flap", flap_period_s=0.7,
                               flap_down_s=0.25, run_s=2.8, stop=2.2),
    "asym_partition": ChaosParams(fault="asym_partition", drop_ba=1.0),
    "slow_follower": ChaosParams(fault="slow_follower", delay_ab_s=0.04,
                                 delay_ba_s=0.04),
    "worker_crash_under_load": ChaosParams(
        fault="worker_crash_under_load", worker_kills=1, run_s=6.0,
        start=1.0, stop=5.0),
    "reconcile_fsync_stall": ChaosParams(
        fault="reconcile_fsync_stall", fsync_stall_s=0.3,
        ops_per_client=16),
}

# The `make chaos-fast` slice: cheapest in-process scenarios with the
# strongest per-second signal (one clock fault, the disk fault, the
# partition-role fault).  Kept to ~8s wall so it rides in `make ci`.
FAST_SCENARIOS: Tuple[str, ...] = ("clock_jump", "fsync_stall",
                                   "leader_flap")
