"""ctypes binding for the C++ gossip-bridge client (native/gbridge.cpp).

The native library owns the agent↔plane transport and the heartbeat
clock (a dedicated thread — the agent's liveness signal must survive a
busy Python event loop / held GIL).  The host side does msgpack
encode/decode and polls received frames from the native queue.

Build: ``g++ -O2 -shared -fPIC -pthread`` on first use, cached next to
this file, same discipline as :mod:`consul_tpu.native.store`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Dict, Optional

import msgpack

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SRC = os.path.join(_REPO, "native", "gbridge.cpp")
_LIB = os.path.join(_HERE, "libgbridge.so")
_BUILD_LOCK = threading.Lock()

_lib = None
_build_error: Optional[str] = None


def build_native(force: bool = False) -> Optional[str]:
    global _build_error
    with _BUILD_LOCK:
        if not force and os.path.exists(_LIB) and (
                not os.path.exists(_SRC)
                or os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        if not os.path.exists(_SRC):
            _build_error = f"source missing: {_SRC}"
            return None
        tmp = _LIB + f".tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               "-o", tmp, _SRC]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            _build_error = f"g++ invocation failed: {e}"
            return None
        if proc.returncode != 0:
            _build_error = proc.stderr[-2000:]
            return None
        os.replace(tmp, _LIB)
        return _LIB


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = build_native()
    if path is None:
        raise RuntimeError(f"gbridge build failed: {_build_error}")
    lib = ctypes.CDLL(path)
    lib.gb_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p]
    lib.gb_connect.restype = ctypes.c_int64
    lib.gb_send.argtypes = [ctypes.c_int64, ctypes.c_char_p, ctypes.c_int]
    lib.gb_send.restype = ctypes.c_int
    lib.gb_set_heartbeat.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                     ctypes.c_int, ctypes.c_int]
    lib.gb_set_heartbeat.restype = ctypes.c_int
    lib.gb_poll.argtypes = [ctypes.c_int64, ctypes.c_char_p, ctypes.c_int]
    lib.gb_poll.restype = ctypes.c_int
    lib.gb_connected.argtypes = [ctypes.c_int64]
    lib.gb_connected.restype = ctypes.c_int
    lib.gb_close.argtypes = [ctypes.c_int64]
    lib.gb_close.restype = None
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


class BridgeClient:
    """One connection to the gossip plane over the native transport."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 unix_path: str = "") -> None:
        lib = _load()
        h = lib.gb_connect(host.encode(), port,
                           unix_path.encode() if unix_path else b"")
        if h <= 0:
            raise ConnectionError(
                f"gossip plane unreachable at "
                f"{unix_path or f'{host}:{port}'} (errno {-h})")
        self._lib = lib
        self._h = h
        self._buf = ctypes.create_string_buffer(1 << 16)

    def send(self, payload: Dict[str, Any]) -> None:
        raw = msgpack.packb(payload, use_bin_type=True)
        if self._lib.gb_send(self._h, raw, len(raw)) != 0:
            raise ConnectionError("gossip plane connection lost")

    def set_heartbeat(self, payload: Dict[str, Any], period_s: float) -> None:
        """Arm the native heartbeat thread with a preframed message."""
        raw = msgpack.packb(payload, use_bin_type=True)
        self._lib.gb_set_heartbeat(self._h, raw, len(raw),
                                   max(1, int(period_s * 1000)))

    def stop_heartbeat(self) -> None:
        self._lib.gb_set_heartbeat(self._h, b"", 0, 0)

    def poll(self) -> Optional[Dict[str, Any]]:
        """One received frame, or None.  Raises on closed connection."""
        n = self._lib.gb_poll(self._h, self._buf, len(self._buf))
        if n == 0:
            return None
        if n == -1:
            raise ConnectionError("gossip plane connection closed")
        if n == -2:  # frame larger than buffer: grow and retry
            self._buf = ctypes.create_string_buffer(len(self._buf) * 4)
            return self.poll()
        return msgpack.unpackb(self._buf.raw[:n], raw=False)

    def connected(self) -> bool:
        return bool(self._lib.gb_connected(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.gb_close(self._h)
            self._h = 0

    def __enter__(self) -> "BridgeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
