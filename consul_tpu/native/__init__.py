"""Native (C++) components: the mmap MVCC store.

Parity: the reference's native deps — LMDB for MVCC state tables and
BoltDB for the raft log (SURVEY.md §2.1).  ``native/cstore.cpp`` plays
both roles; this package builds and binds it via ctypes.
"""

from consul_tpu.native.store import (
    NativeStore, NativeLogStore, native_available, build_native)

__all__ = ["NativeStore", "NativeLogStore", "native_available",
           "build_native"]
