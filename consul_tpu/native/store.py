"""ctypes binding for the C++ MVCC store + the raft-log facade.

Build: ``g++ -O2 -shared -fPIC`` on first use (no pybind11 in the
image — plain C ABI + ctypes per the environment constraints), cached
next to the source with a lock against concurrent test workers.

Two facades:

- :class:`NativeStore` — ordered KV with snapshots and prefix scans
  (the LMDB role behind the state store).
- :class:`NativeLogStore` — the raft LogStore/StableStore contract of
  ``consensus/log.py`` (the raft-boltdb role): log entries live at
  ``l:<index be64>``, stable kv at ``s:<name>``.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Iterator, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SRC = os.path.join(_REPO, "native", "cstore.cpp")
_LIB = os.path.join(_HERE, "libcstore.so")
_BUILD_LOCK = threading.Lock()

_lib = None
_build_error: Optional[str] = None


def build_native(force: bool = False) -> Optional[str]:
    """Compile the shared library; returns its path or None on failure."""
    global _build_error
    with _BUILD_LOCK:
        if not force and os.path.exists(_LIB) and (
                not os.path.exists(_SRC)
                or os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        if not os.path.exists(_SRC):
            _build_error = f"source not found: {_SRC}"
            return None
        # Per-process tmp name: the threading lock doesn't cover other
        # processes (pytest-xdist workers), but os.replace of a complete
        # per-pid artifact is atomic — last writer wins with a VALID .so.
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               "-o", tmp, _SRC]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            _build_error = str(e)
            return None
        if proc.returncode != 0:
            _build_error = proc.stderr[-2000:]
            try:
                os.remove(tmp)
            except FileNotFoundError:
                pass
            return None
        os.replace(tmp, _LIB)
        return _LIB


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = build_native()
    if path is None:
        raise RuntimeError(f"native store unavailable: {_build_error}")
    lib = ctypes.CDLL(path)
    lib.cs_open.restype = ctypes.c_void_p
    lib.cs_open.argtypes = [ctypes.c_char_p]
    lib.cs_close.argtypes = [ctypes.c_void_p]
    lib.cs_error.restype = ctypes.c_char_p
    lib.cs_error.argtypes = [ctypes.c_void_p]
    lib.cs_last_seq.restype = ctypes.c_uint64
    lib.cs_last_seq.argtypes = [ctypes.c_void_p]
    lib.cs_put.restype = ctypes.c_int64
    lib.cs_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                           ctypes.c_char_p, ctypes.c_uint32]
    lib.cs_del.restype = ctypes.c_int64
    lib.cs_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.cs_snapshot.restype = ctypes.c_uint64
    lib.cs_snapshot.argtypes = [ctypes.c_void_p]
    lib.cs_release.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.cs_get.restype = ctypes.c_int
    lib.cs_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
                           ctypes.c_uint32,
                           ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                           ctypes.POINTER(ctypes.c_uint32)]
    lib.cs_scan_begin.restype = ctypes.c_void_p
    lib.cs_scan_begin.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_char_p, ctypes.c_uint32]
    lib.cs_scan_next.restype = ctypes.c_int
    lib.cs_scan_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_uint32)]
    lib.cs_scan_end.argtypes = [ctypes.c_void_p]
    lib.cs_sync.restype = ctypes.c_int
    lib.cs_sync.argtypes = [ctypes.c_void_p]
    lib.cs_count.restype = ctypes.c_uint64
    lib.cs_count.argtypes = [ctypes.c_void_p]
    lib.cs_compact.restype = ctypes.c_int
    lib.cs_compact.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def native_available() -> bool:
    global _build_error
    try:
        _load()
        return True
    except Exception as e:  # incl. OSError from a corrupt cached .so
        _build_error = str(e)
        return False


class NativeStore:
    """Ordered KV with MVCC snapshots over the C++ store."""

    def __init__(self, path: str) -> None:
        lib = _load()
        self._lib = lib
        self._h = lib.cs_open(path.encode())
        if not self._h:
            raise RuntimeError(f"cs_open failed for {path}")

    def close(self) -> None:
        if self._h:
            self._lib.cs_close(self._h)
            self._h = None

    def __enter__(self) -> "NativeStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def put(self, key: bytes, value: bytes) -> int:
        seq = self._lib.cs_put(self._h, key, len(key), value, len(value))
        if seq < 0:
            raise RuntimeError(self._lib.cs_error(self._h).decode())
        return seq

    def delete(self, key: bytes) -> int:
        seq = self._lib.cs_del(self._h, key, len(key))
        if seq < 0:
            raise RuntimeError(self._lib.cs_error(self._h).decode())
        return seq

    def get(self, key: bytes, snap: int = 0) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        out_len = ctypes.c_uint32()
        rc = self._lib.cs_get(self._h, snap, key, len(key),
                              ctypes.byref(out), ctypes.byref(out_len))
        if rc == 1:
            return None
        if rc != 0:
            raise RuntimeError(self._lib.cs_error(self._h).decode())
        return ctypes.string_at(out, out_len.value)

    def snapshot(self) -> int:
        return self._lib.cs_snapshot(self._h)

    def release(self, snap: int) -> None:
        self._lib.cs_release(self._h, snap)

    def scan(self, prefix: bytes = b"", snap: int = 0
             ) -> Iterator[Tuple[bytes, bytes]]:
        it = self._lib.cs_scan_begin(self._h, snap, prefix, len(prefix))
        try:
            key = ctypes.POINTER(ctypes.c_ubyte)()
            klen = ctypes.c_uint32()
            val = ctypes.POINTER(ctypes.c_ubyte)()
            vlen = ctypes.c_uint32()
            while True:
                rc = self._lib.cs_scan_next(
                    it, ctypes.byref(key), ctypes.byref(klen),
                    ctypes.byref(val), ctypes.byref(vlen))
                if rc == 1:
                    return
                if rc != 0:
                    raise RuntimeError("scan failed")
                yield (ctypes.string_at(key, klen.value),
                       ctypes.string_at(val, vlen.value))
        finally:
            self._lib.cs_scan_end(it)

    def count(self) -> int:
        return self._lib.cs_count(self._h)

    def last_seq(self) -> int:
        return self._lib.cs_last_seq(self._h)

    def sync(self) -> None:
        if self._lib.cs_sync(self._h) != 0:
            raise RuntimeError("fsync failed")

    def compact(self) -> None:
        if self._lib.cs_compact(self._h) != 0:
            raise RuntimeError(self._lib.cs_error(self._h).decode())


def _log_key(index: int) -> bytes:
    return b"l:" + struct.pack(">Q", index)


class NativeLogStore:
    """The consensus/log.py LogStore + StableStore contract over the
    native store (the raft-boltdb role, consul/server.go:357-368)."""

    def __init__(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        self._store = NativeStore(os.path.join(path, "raft.cstore"))
        self._first = 0
        self._last = 0
        for k, _ in self._store.scan(b"l:"):
            idx = struct.unpack(">Q", k[2:])[0]
            if self._first == 0:
                self._first = idx
            self._first = min(self._first, idx)
            self._last = max(self._last, idx)

    # -- LogStore ----------------------------------------------------------

    def first_index(self) -> int:
        return self._first

    def last_index(self) -> int:
        return self._last

    def get(self, index: int):
        from consul_tpu.consensus.log import LogEntry
        raw = self._store.get(_log_key(index))
        return LogEntry.unpack(raw) if raw is not None else None

    def append(self, entries: List, sync: bool = True) -> None:
        for e in entries:
            self._store.put(_log_key(e.index), e.pack())
            if self._first == 0:
                self._first = e.index
            self._last = max(self._last, e.index)
        if sync:
            self._store.sync()

    def delete_from(self, index: int) -> None:
        for i in range(index, self._last + 1):
            self._store.delete(_log_key(i))
        self._last = max(index - 1, 0)
        if self._last < self._first:
            self._first = 0
        self._store.sync()

    def delete_to(self, index: int) -> None:
        lo = self._first or 1
        for i in range(lo, index + 1):
            self._store.delete(_log_key(i))
        self._first = index + 1 if self._last > index else 0
        if self._first == 0:
            self._last = 0
        self._store.compact()  # reclaim the dead range on disk
        self._store.sync()

    # -- StableStore -------------------------------------------------------

    def set_stable(self, key: str, val) -> None:
        import json
        self._store.put(b"s:" + key.encode(), json.dumps(val).encode())
        self._store.sync()

    def get_stable(self, key: str, default=None):
        import json
        raw = self._store.get(b"s:" + key.encode())
        return json.loads(raw) if raw is not None else default

    def sync(self) -> None:
        self._store.sync()

    def close(self) -> None:
        self._store.close()
