"""Shared request/reply wire types + codec (reference: consul/structs/)."""

from consul_tpu.structs.structs import *  # noqa: F401,F403
from consul_tpu.structs.codec import encode, decode, encode_payload, decode_payload  # noqa: F401
