"""Msgpack framing with a leading type byte.

Parity target: ``consul/structs/structs.go:575-588`` — Encode prepends a
one-byte message type to the msgpack body; Decode strips it.  Used for
Raft log entries and snapshot records.  Generic payload helpers wrap
dataclass <-> msgpack conversion for the RPC layer.
"""

from __future__ import annotations

from typing import Any, Tuple, Type

import msgpack


def encode_payload(obj: Any) -> bytes:
    """Serialize a Struct/dataclass (or plain value) to msgpack bytes."""
    if hasattr(obj, "to_wire"):
        obj = obj.to_wire()
    return msgpack.packb(obj, use_bin_type=True)


def decode_payload(buf: bytes, cls: Type | None = None) -> Any:
    out = msgpack.unpackb(buf, raw=False, strict_map_key=False)
    if cls is not None and hasattr(cls, "from_wire"):
        return cls.from_wire(out)
    return out


def encode(msg_type: int, obj: Any) -> bytes:
    """Type byte + msgpack body (structs.go:575-581)."""
    return bytes([msg_type & 0xFF]) + encode_payload(obj)


def decode(buf: bytes, cls: Type | None = None) -> Tuple[int, Any]:
    """Split type byte, decode body (structs.go:583-588)."""
    if not buf:
        raise ValueError("empty buffer")
    return buf[0], decode_payload(buf[1:], cls)
