"""Wire types shared by the RPC, Raft/FSM, and HTTP layers.

Parity target: ``consul/structs/structs.go`` (648 LoC) in the reference —
message-type bytes for the replicated log, QueryOptions/QueryMeta for
blocking queries and consistency modes, and the request/reply structs for
every endpoint.  We keep the same *semantics* (field meaning, defaults,
the RPCInfo forwarding contract) but express them as slotted dataclasses
that serialize to msgpack maps, which is the natural codec for a Python
host plane (the reference uses go-msgpack, structs.go:575-588).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = [
    "MessageType", "Struct",
    "HEALTH_ANY", "HEALTH_UNKNOWN", "HEALTH_PASSING", "HEALTH_WARNING",
    "HEALTH_CRITICAL", "VALID_HEALTH_STATES",
    "SERF_CHECK_ID", "SERF_CHECK_NAME", "SERF_ALIVE_OUTPUT", "SERF_FAILED_OUTPUT",
    "CONSUL_SERVICE_ID", "CONSUL_SERVICE_NAME",
    "QueryOptions", "QueryMeta", "WriteRequest",
    "NodeService", "HealthCheck", "Node", "RegisterRequest", "DeregisterRequest",
    "NodeServices", "ServiceNode", "CheckServiceNode",
    "KVSOp", "DirEntry", "KVSRequest", "KeyRequest", "KeyListRequest",
    "SESSION_BEHAVIOR_RELEASE", "SESSION_BEHAVIOR_DELETE",
    "SESSION_TTL_MIN", "SESSION_TTL_MAX", "SESSION_TTL_MULTIPLIER",
    "Session", "SessionOp", "SessionRequest",
    "ACL_TYPE_CLIENT", "ACL_TYPE_MANAGEMENT", "ACL_ANONYMOUS_ID",
    "ACL", "ACLOp", "ACLRequest", "ACLPolicyRequest", "ACLPolicyReply",
    "TombstoneRequest", "UserEvent", "CompoundResponse",
    "KeyringRequest", "KeyringResponse", "now",
]


class MessageType(enum.IntEnum):
    """Raft log entry type byte (reference: consul/structs/structs.go:20-34).

    The FSM dispatches on this leading byte.  IGNORE_UNKNOWN_FLAG mirrors
    msgpackHandle's ignore bit (consul/fsm.go:83-88): entries whose type
    has the high bit set may be safely skipped by older versions.
    """

    REGISTER = 0
    DEREGISTER = 1
    KVS = 2
    SESSION = 3
    ACL = 4
    TOMBSTONE = 5
    # Batched reconcile envelope (PR 18): one log entry carrying a
    # msgpack list of sub-entry buffers, each itself a type byte +
    # payload.  Append->quorum is paid once for the whole batch; the
    # FSM applies the sub-entries in order at the envelope's index.
    BATCH = 6

    @staticmethod
    def ignore_unknown(t: int) -> int:
        return t | 0x80


# ---------------------------------------------------------------------------
# Health check states (reference: consul/structs/structs.go:36-47)
# ---------------------------------------------------------------------------

HEALTH_ANY = "any"
HEALTH_UNKNOWN = "unknown"
HEALTH_PASSING = "passing"
HEALTH_WARNING = "warning"
HEALTH_CRITICAL = "critical"

VALID_HEALTH_STATES = (HEALTH_PASSING, HEALTH_WARNING, HEALTH_CRITICAL, HEALTH_UNKNOWN)

# Built-in serf-health check (reference: consul/leader.go:17-22).
SERF_CHECK_ID = "serfHealth"
SERF_CHECK_NAME = "Serf Health Status"
SERF_ALIVE_OUTPUT = "Agent alive and reachable"
SERF_FAILED_OUTPUT = "Agent not live or unreachable"

CONSUL_SERVICE_ID = "consul"
CONSUL_SERVICE_NAME = "consul"


def _wire(v: Any) -> Any:
    if dataclasses.is_dataclass(v):
        return {f.name: _wire(getattr(v, f.name)) for f in dataclasses.fields(v)}
    if isinstance(v, list):
        return [_wire(x) for x in v]
    if isinstance(v, dict):
        return {k: _wire(x) for k, x in v.items()}
    return v


def _asdict(obj) -> Dict[str, Any]:
    return _wire(obj)


class Struct:
    """Base for wire structs: dict round-trip used by the msgpack codec."""

    def to_wire(self) -> Dict[str, Any]:
        return _asdict(self)

    @classmethod
    def from_wire(cls, d: Dict[str, Any]):
        names = {f.name for f in dataclasses.fields(cls)}
        kw = {}
        for k, v in d.items():
            if k in names:
                kw[k] = v
        obj = cls(**kw)  # type: ignore[call-arg]
        obj._rehydrate()
        return obj

    def _rehydrate(self) -> None:
        """Re-nest child dataclasses after a wire decode (override as needed)."""


# ---------------------------------------------------------------------------
# Query options / meta — blocking queries + consistency modes
# (reference: consul/structs/structs.go:78-147)
# ---------------------------------------------------------------------------


@dataclass
class QueryOptions(Struct):
    token: str = ""
    datacenter: str = ""
    # Blocking query: re-run until index > min_query_index or wait expires.
    min_query_index: int = 0
    max_query_time: float = 0.0  # seconds; server clamps (rpc.go:29-41)
    # Consistency: allow_stale serves from any server (rpc.go:191-193);
    # require_consistent forces a leader round-trip (rpc.go:413-417).
    allow_stale: bool = False
    require_consistent: bool = False

    def request_datacenter(self) -> str:
        return self.datacenter

    def is_read(self) -> bool:
        return True

    def blocking_allowed(self) -> bool:
        return True


@dataclass
class QueryMeta(Struct):
    index: int = 0
    last_contact: float = 0.0  # seconds since last leader contact (stale reads)
    known_leader: bool = True


@dataclass
class WriteRequest(Struct):
    token: str = ""
    datacenter: str = ""

    def request_datacenter(self) -> str:
        return self.datacenter

    def is_read(self) -> bool:
        return False

    def blocking_allowed(self) -> bool:
        return False


# ---------------------------------------------------------------------------
# Catalog / node / service / check types
# (reference: consul/structs/structs.go:149-319)
# ---------------------------------------------------------------------------


@dataclass
class NodeService(Struct):
    id: str = ""
    service: str = ""
    tags: List[str] = field(default_factory=list)
    address: str = ""
    port: int = 0


@dataclass
class HealthCheck(Struct):
    node: str = ""
    check_id: str = ""
    name: str = ""
    status: str = HEALTH_CRITICAL
    notes: str = ""
    output: str = ""
    service_id: str = ""
    service_name: str = ""


@dataclass
class Node(Struct):
    node: str = ""
    address: str = ""


@dataclass
class RegisterRequest(WriteRequest):
    """Catalog registration; node + optional service + optional check(s).

    Reference: structs.go:149-162 — a register may update any subset.
    """

    node: str = ""
    address: str = ""
    service: Optional[NodeService] = None
    check: Optional[HealthCheck] = None
    checks: List[HealthCheck] = field(default_factory=list)

    def _rehydrate(self) -> None:
        if isinstance(self.service, dict):
            self.service = NodeService.from_wire(self.service)
        if isinstance(self.check, dict):
            self.check = HealthCheck.from_wire(self.check)
        self.checks = [
            HealthCheck.from_wire(c) if isinstance(c, dict) else c for c in self.checks
        ]


@dataclass
class DeregisterRequest(WriteRequest):
    """Reference: structs.go:170-180 — node / service / check granularity."""

    node: str = ""
    service_id: str = ""
    check_id: str = ""


@dataclass
class NodeServices(Struct):
    node: Optional[Node] = None
    services: Dict[str, NodeService] = field(default_factory=dict)

    def _rehydrate(self) -> None:
        if isinstance(self.node, dict):
            self.node = Node.from_wire(self.node)
        self.services = {
            k: (NodeService.from_wire(v) if isinstance(v, dict) else v)
            for k, v in self.services.items()
        }


@dataclass
class ServiceNode(Struct):
    node: str = ""
    address: str = ""
    service_id: str = ""
    service_name: str = ""
    service_tags: List[str] = field(default_factory=list)
    service_address: str = ""
    service_port: int = 0


@dataclass
class CheckServiceNode(Struct):
    node: Optional[Node] = None
    service: Optional[NodeService] = None
    checks: List[HealthCheck] = field(default_factory=list)

    def _rehydrate(self) -> None:
        if isinstance(self.node, dict):
            self.node = Node.from_wire(self.node)
        if isinstance(self.service, dict):
            self.service = NodeService.from_wire(self.service)
        self.checks = [
            HealthCheck.from_wire(c) if isinstance(c, dict) else c for c in self.checks
        ]


# ---------------------------------------------------------------------------
# KV types (reference: consul/structs/structs.go:321-389)
# ---------------------------------------------------------------------------


class KVSOp(str, enum.Enum):
    SET = "set"
    DELETE = "delete"
    DELETE_CAS = "delete-cas"
    DELETE_TREE = "delete-tree"
    CAS = "cas"
    LOCK = "lock"
    UNLOCK = "unlock"


@dataclass
class DirEntry(Struct):
    """One KV entry.  lock_index counts successful acquisitions
    (structs.go:350-358); session is the current lock holder."""

    key: str = ""
    value: bytes = b""
    flags: int = 0
    session: str = ""
    lock_index: int = 0
    create_index: int = 0
    modify_index: int = 0

    def clone(self) -> "DirEntry":
        return dataclasses.replace(self)


@dataclass
class KVSRequest(WriteRequest):
    op: str = KVSOp.SET.value
    dir_ent: Optional[DirEntry] = None

    def _rehydrate(self) -> None:
        if isinstance(self.dir_ent, dict):
            self.dir_ent = DirEntry.from_wire(self.dir_ent)


@dataclass
class KeyRequest(QueryOptions):
    key: str = ""


@dataclass
class KeyListRequest(QueryOptions):
    prefix: str = ""
    separator: str = ""


# ---------------------------------------------------------------------------
# Per-domain read request envelopes (reference: consul/structs/structs.go —
# DCSpecificRequest, NodeSpecificRequest, ServiceSpecificRequest,
# ChecksInStateRequest, SessionSpecificRequest).  These carry the RPC mesh's
# method arguments so reads forward across servers/DCs like writes do.
# ---------------------------------------------------------------------------


@dataclass
class NodeSpecificRequest(QueryOptions):
    node: str = ""


@dataclass
class ServiceSpecificRequest(QueryOptions):
    service_name: str = ""
    service_tag: str = ""
    tag_filter: bool = False
    passing_only: bool = False


@dataclass
class ChecksInStateRequest(QueryOptions):
    state: str = ""


@dataclass
class SessionSpecificRequest(QueryOptions):
    session: str = ""


# ---------------------------------------------------------------------------
# Session types (reference: consul/structs/structs.go:391-448)
# ---------------------------------------------------------------------------

SESSION_BEHAVIOR_RELEASE = "release"
SESSION_BEHAVIOR_DELETE = "delete"

SESSION_TTL_MIN = 10.0  # seconds (session_endpoint.go bounds)
SESSION_TTL_MAX = 3600.0
SESSION_TTL_MULTIPLIER = 2  # grace multiplier (session_ttl.go:11)


@dataclass
class Session(Struct):
    id: str = ""
    name: str = ""
    node: str = ""
    checks: List[str] = field(default_factory=list)
    lock_delay: float = 15.0  # seconds, max 60 (state_store lock-delay)
    behavior: str = SESSION_BEHAVIOR_RELEASE
    ttl: str = ""  # duration string, e.g. "15s"; empty = no TTL
    create_index: int = 0
    modify_index: int = 0


class SessionOp(str, enum.Enum):
    CREATE = "create"
    DESTROY = "destroy"


@dataclass
class SessionRequest(WriteRequest):
    op: str = SessionOp.CREATE.value
    session: Optional[Session] = None

    def _rehydrate(self) -> None:
        if isinstance(self.session, dict):
            self.session = Session.from_wire(self.session)


# ---------------------------------------------------------------------------
# ACL types (reference: consul/structs/structs.go:450-500)
# ---------------------------------------------------------------------------

ACL_TYPE_CLIENT = "client"
ACL_TYPE_MANAGEMENT = "management"
ACL_ANONYMOUS_ID = "anonymous"


@dataclass
class ACL(Struct):
    id: str = ""
    name: str = ""
    type: str = ACL_TYPE_CLIENT
    rules: str = ""
    create_index: int = 0
    modify_index: int = 0


class ACLOp(str, enum.Enum):
    SET = "set"
    DELETE = "delete"


@dataclass
class ACLRequest(WriteRequest):
    op: str = ACLOp.SET.value
    acl: Optional[ACL] = None

    def _rehydrate(self) -> None:
        if isinstance(self.acl, dict):
            self.acl = ACL.from_wire(self.acl)


@dataclass
class ACLPolicyRequest(QueryOptions):
    acl_id: str = ""
    etag: str = ""


@dataclass
class ACLPolicyReply(Struct):
    etag: str = ""
    ttl: float = 30.0
    parent: str = "deny"
    policy: Optional[Dict[str, Any]] = None  # serialized acl.Policy


# ---------------------------------------------------------------------------
# Tombstone reap (reference: consul/structs/structs.go:502-514)
# ---------------------------------------------------------------------------


@dataclass
class TombstoneRequest(WriteRequest):
    op: str = "reap"
    reap_index: int = 0


# ---------------------------------------------------------------------------
# Events (reference: command/agent/user_event.go:19-44)
# ---------------------------------------------------------------------------


@dataclass
class UserEvent(Struct):
    id: str = ""
    name: str = ""
    payload: bytes = b""
    node_filter: str = ""
    service_filter: str = ""
    tag_filter: str = ""
    version: int = 1
    ltime: int = 0
    # Target DC (EventFireRequest.Datacenter, event_endpoint.go:33-40):
    # a fire naming another datacenter forwards over the WAN and floods
    # THERE; empty = local DC.
    datacenter: str = ""


# ---------------------------------------------------------------------------
# Cross-DC fan-out (reference: consul/structs/structs.go:590-597)
# ---------------------------------------------------------------------------


class CompoundResponse:
    """Merges per-DC responses for globalRPC fan-out."""

    def __init__(self) -> None:
        self.responses: List[Any] = []

    def add(self, resp: Any) -> None:
        self.responses.append(resp)


@dataclass
class KeyringRequest(WriteRequest):
    op: str = "list"  # list|install|use|remove
    key: str = ""
    forwarded: bool = False


@dataclass
class KeyringResponse(Struct):
    wan: bool = False
    datacenter: str = ""
    messages: Dict[str, str] = field(default_factory=dict)
    keys: Dict[str, int] = field(default_factory=dict)
    num_nodes: int = 0
    error: str = ""


def now() -> float:
    return time.time()
