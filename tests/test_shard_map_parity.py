"""Sharded-vs-single-device kernel parity (ISSUE 3 tentpole).

The shard_map lowering (kernel.py "ICI sharding") is only allowed to
change WHERE the belief matrix lives, never a single bit of the
dynamics: every merge back to replicated space is a psum of disjoint
integer contributions, so the final SwimState — counters, membership,
slot registers, the heard matrix itself — must equal the unsharded
kernel exactly.  These tests run both kernels on the conftest-forced
8-device virtual CPU mesh with the same seed/params and compare every
field bit-for-bit, across the regimes with distinct code paths:
failures (probe/suspect/dead), joins, push-pull, packet loss, the hot
tail, and the flight recorder + trace.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.timeout_s(600)


def _assert_state_equal(a, b, ctx=""):
    for f in a._fields:
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(x, y), f"{ctx}SwimState.{f} diverged"


def _fail_join(jnp, n):
    NEVER = 2**31 - 1
    fail = jnp.full((n,), NEVER, jnp.int32)
    fail = fail.at[:5].set(jnp.arange(5, dtype=jnp.int32) * 30 + 10)
    join = jnp.full((n,), NEVER, jnp.int32)
    join = join.at[n - 4:].set(jnp.arange(4, dtype=jnp.int32) * 40 + 25)
    return fail, join


def _run_both(n, steps, *, slots=8, hot_slots=0, loss_rate=0.0,
              pushpull_every=0, flight_rounds=0, trace=False, hist=False,
              ndev=8):
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (
        init_flight, init_hist, init_state, run_rounds, run_rounds_sharded,
        shard_state)
    from consul_tpu.gossip.params import lan_profile

    p = lan_profile(n, slots=slots, hot_slots=hot_slots,
                    loss_rate=loss_rate, pushpull_every=pushpull_every)
    key = jax.random.PRNGKey(7)
    fail, join = _fail_join(jnp, n)

    ref = run_rounds(init_state(p), key, fail, p, steps=steps, trace=trace,
                     join_round=join,
                     flight=init_flight(64) if flight_rounds else None,
                     hist=init_hist() if hist else None)
    out = run_rounds_sharded(
        shard_state(init_state(p), ndev), key, fail, p, steps=steps,
        trace=trace, join_round=join,
        flight=init_flight(64) if flight_rounds else None,
        hist=init_hist() if hist else None, ndev=ndev)
    return ref, out, p


def _assert_hist_equal(a, b, ctx=""):
    for f in a._fields:
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(x, y), f"{ctx}HistBank.{f} diverged"


class TestShardedParity:
    def test_state_parity_failures_joins(self):
        """Core regime: failures + joins, no extras — every SwimState
        field must match bit-for-bit after 400 rounds."""
        (ref, _), (out, _) = _run_both(640, 400)[:2]
        _assert_state_equal(ref, out)

    @pytest.mark.slow

    def test_state_parity_loss_pushpull_hot(self):
        """The branchy regimes at once: iid packet loss, periodic
        push-pull anti-entropy, and the hot-tier tail dispatch."""
        (ref, _), (out, _) = _run_both(
            640, 400, hot_slots=4, loss_rate=0.02, pushpull_every=50)[:2]
        _assert_state_equal(ref, out)

    @pytest.mark.slow

    def test_trace_and_flight_parity(self):
        """RoundTrace series and the FlightRing rows are derived from
        sharded values via psum — they must match too (the plane's
        dead-verdict fanout and the obs pipeline read them)."""
        (refc, rtr), (outc, otr) = _run_both(
            640, 200, trace=True, flight_rounds=64)[:2]
        ref_st, ref_fl = refc
        out_st, out_fl = outc
        _assert_state_equal(ref_st, out_st)
        for f in ref_fl._fields:
            assert np.array_equal(np.asarray(getattr(ref_fl, f)),
                                  np.asarray(getattr(out_fl, f))), \
                f"FlightRing.{f} diverged"
        for f in rtr._fields:
            assert np.array_equal(np.asarray(getattr(rtr, f)),
                                  np.asarray(getattr(otr, f))), \
                f"RoundTrace.{f} diverged"

    def test_hist_bank_parity_failures_joins(self):
        """Observatory acceptance (ISSUE 4): the histogram banks the
        sharded kernel accumulates — detection latency, suspicion
        dwell, refutation latency, dissemination spread — must equal
        the unsharded kernel's bit-for-bit.  Every on-device merge is a
        psum of disjoint integer contributions; the spread bucketing is
        integer shift-and-count, so there is no float path to drift."""
        (ref, _), (out, _) = _run_both(640, 400, hist=True)[:2]
        ref_st, ref_hb = ref
        out_st, out_hb = out
        _assert_state_equal(ref_st, out_st)
        _assert_hist_equal(ref_hb, out_hb)
        # Not vacuous: the regime has 5 failures, so the detect bank
        # carries observations and the spread bank saw recycled slots.
        assert int(np.asarray(ref_hb.detect).sum()) >= 5
        assert int(np.asarray(ref_hb.spread).sum()) > 0

    @pytest.mark.slow

    def test_hist_bank_parity_loss_pushpull_hot(self):
        """Banks stay bit-identical through the branchy regimes too:
        iid packet loss, push-pull anti-entropy, the hot tail."""
        (ref, _), (out, _) = _run_both(
            640, 400, hot_slots=4, loss_rate=0.02, pushpull_every=50,
            hist=True)[:2]
        _assert_state_equal(ref[0], out[0])
        _assert_hist_equal(ref[1], out[1])
        assert int(np.asarray(ref[1].detect).sum()) > 0

    @pytest.mark.slow

    def test_hist_flight_trace_triple_carry(self):
        """All three observability carriers at once — (state, flight,
        hist) + trace — keep parity; this is exactly the plane's
        dispatch shape."""
        (refc, rtr), (outc, otr) = _run_both(
            640, 200, trace=True, flight_rounds=64, hist=True)[:2]
        ref_st, ref_fl, ref_hb = refc
        out_st, out_fl, out_hb = outc
        _assert_state_equal(ref_st, out_st)
        _assert_hist_equal(ref_hb, out_hb)
        for f in ref_fl._fields:
            assert np.array_equal(np.asarray(getattr(ref_fl, f)),
                                  np.asarray(getattr(out_fl, f))), \
                f"FlightRing.{f} diverged"
        for f in rtr._fields:
            assert np.array_equal(np.asarray(getattr(rtr, f)),
                                  np.asarray(getattr(otr, f))), \
                f"RoundTrace.{f} diverged"

    def test_single_round_parity_and_donation(self):
        """swim_round_sharded: one round matches, and the donated input
        state is actually consumed (buffers deleted on CPU)."""
        import jax
        import jax.numpy as jnp

        from consul_tpu.gossip.kernel import (
            init_state, shard_state, swim_round, swim_round_sharded)
        from consul_tpu.gossip.params import lan_profile

        p = lan_profile(640, slots=8)
        key = jax.random.PRNGKey(0)
        fail, _ = _fail_join(jnp, p.n)
        ref = swim_round(init_state(p), key, fail, p)
        donated = shard_state(init_state(p))
        out = swim_round_sharded(donated, key, fail, p)
        _assert_state_equal(ref, out)
        with pytest.raises(RuntimeError):
            # the use-after-donate IS the assertion here (vet D01)
            np.asarray(donated.heard)  # noqa: D01 — deliberate read of a deleted buffer to prove donation happened

    def test_alignment_rejected(self):
        """n not divisible by ndev or probe_every is a loud ValueError,
        not silent wrong halos."""
        from consul_tpu.gossip.kernel import _check_shardable
        from consul_tpu.gossip.params import lan_profile

        with pytest.raises(ValueError):
            _check_shardable(lan_profile(641), 8)  # 641 % 8 != 0
        with pytest.raises(ValueError):
            _check_shardable(lan_profile(8 * 13), 8)  # 104 % probe_every(5)
        _check_shardable(lan_profile(640), 8)  # aligned: no raise

    @pytest.mark.slow

    def test_hot_default_parity(self):
        """Satellite: lan_profile now defaults hot_slots=8; the hot
        tail must engage (few live episodes, S > hot_slots) and stay
        bit-identical to a full-tail-only run."""
        import jax
        import jax.numpy as jnp

        from consul_tpu.gossip.kernel import init_state, run_rounds
        from consul_tpu.gossip.params import lan_profile

        p_hot = lan_profile(256, slots=32)
        assert p_hot.hot_slots == 8  # the new default
        p_full = lan_profile(256, slots=32, hot_slots=0)
        key = jax.random.PRNGKey(11)
        fail = jnp.full((256,), 2**31 - 1, jnp.int32).at[3].set(
            10).at[99].set(60)  # <= hot_slots live episodes: hot path taken
        a, _ = run_rounds(init_state(p_hot), key, fail, p_hot, steps=300)
        b, _ = run_rounds(init_state(p_full), key, fail, p_full, steps=300)
        _assert_state_equal(a, b)

    @pytest.mark.slow

    def test_multidc_lan_devices_parity(self):
        """DC x shard composition: multidc with lan_devices=8 equals
        the single-device multidc bit-for-bit, events included."""
        import jax
        import jax.numpy as jnp

        from consul_tpu.gossip.multidc import (
            init_multidc, make_params, run_multidc_rounds)

        D, nl = 2, 320
        p0 = make_params(D, nl, slots=8)
        p8 = make_params(D, nl, slots=8, lan_devices=8)
        key = jax.random.PRNGKey(3)
        NEVER = 2**31 - 1
        lan_fail = jnp.full((D, nl), NEVER, jnp.int32
                            ).at[0, 3].set(5).at[1, 7].set(9)
        wan_fail = jnp.full((D * 3,), NEVER, jnp.int32)
        a, cov_a = run_multidc_rounds(
            init_multidc(p0), key, lan_fail, wan_fail, p0, 120)
        b, cov_b = run_multidc_rounds(
            init_multidc(p8), key, lan_fail, wan_fail, p8, 120)
        _assert_state_equal(a.lan, b.lan, "lan ")
        _assert_state_equal(a.wan, b.wan, "wan ")
        assert np.array_equal(np.asarray(cov_a), np.asarray(cov_b))

    @pytest.mark.slow

    def test_multidc_hist_parity(self):
        """Per-DC observatory banks through the DC x shard composition:
        lan_devices=8 banks equal the single-device banks bit-for-bit,
        and threading them does not perturb the dynamics."""
        import jax
        import jax.numpy as jnp

        from consul_tpu.gossip.multidc import (
            init_multidc, init_multidc_hist, make_params,
            run_multidc_rounds)

        D, nl = 2, 320
        p0 = make_params(D, nl, slots=8)
        p8 = make_params(D, nl, slots=8, lan_devices=8)
        key = jax.random.PRNGKey(3)
        NEVER = 2**31 - 1
        lan_fail = jnp.full((D, nl), NEVER, jnp.int32
                            ).at[0, 3].set(5).at[1, 7].set(9)
        wan_fail = jnp.full((D * 3,), NEVER, jnp.int32)
        (a, ha), _ = run_multidc_rounds(
            init_multidc(p0), key, lan_fail, wan_fail, p0, 120,
            lan_hist=init_multidc_hist(p0))
        (b, hb), _ = run_multidc_rounds(
            init_multidc(p8), key, lan_fail, wan_fail, p8, 120,
            lan_hist=init_multidc_hist(p8))
        _assert_state_equal(a.lan, b.lan, "lan ")
        _assert_hist_equal(ha, hb, "multidc ")
        # one failure per DC in-window: each DC's detect bank counts it
        assert np.asarray(ha.detect).sum(axis=1).tolist() == [1, 1]
        # no-hist run is bit-identical: banks are observers, not actors
        c, _ = run_multidc_rounds(
            init_multidc(p0), key, lan_fail, wan_fail, p0, 120)
        _assert_state_equal(a.lan, c.lan, "hist-on vs off lan ")


def _run_both_nemesis(name, n=320, steps=150, ndev=8):
    """Both kernels under a nemesis scenario (gossip/nemesis.py) with
    the HistBank threaded; returns (ref_carry, sharded_carry, nem).
    Carries unpack as (state, hist[, nem_state])."""
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (
        init_hist, init_nem_state, init_state, run_rounds,
        run_rounds_sharded, shard_state)
    from consul_tpu.gossip.nemesis import build
    from consul_tpu.gossip.params import lan_profile

    sc = build(name, n)
    p = lan_profile(n, slots=16)
    key = jax.random.PRNGKey(13)
    fail = jnp.asarray(sc.fail_round)

    def kw():
        # fresh donated carriers per run
        out = dict(steps=steps, nem=sc.nem, hist=init_hist())
        if sc.join_round is not None:
            out["join_round"] = jnp.asarray(sc.join_round)
        if sc.nem.needs_state:
            out["nem_state"] = init_nem_state(n)
        return out

    ref, _ = run_rounds(init_state(p), key, fail, p, **kw())
    out, _ = run_rounds_sharded(shard_state(init_state(p), ndev), key,
                                fail, p, ndev=ndev, **kw())
    return ref, out, sc.nem


def _assert_nemesis_parity(ref, out, nem, ctx=""):
    _assert_state_equal(ref[0], out[0], ctx)
    _assert_hist_equal(ref[1], out[1], ctx)
    if nem.needs_state:
        for f in ref[2]._fields:
            assert np.array_equal(np.asarray(getattr(ref[2], f)),
                                  np.asarray(getattr(out[2], f))), \
                f"{ctx}NemState.{f} diverged"


class TestNemesisParity:
    """ISSUE 6 acceptance (c): injection schedules stay bit-identical
    between the single-device and shard_map kernels — every nemesis
    mask is derived in-jit from jnp.arange + uint32 hashing, and the
    LHM carry merges like every other psum of disjoint contributions.
    Tier-1 runs the maximal-carry scenario (degraded_observer: state +
    hist + NemState) at compile-budget scale; the rest of the catalog
    (including partition_heal's dwell coverage) is @slow."""

    @pytest.mark.slow

    def test_degraded_observer_parity(self):
        ref, out, nem = _run_both_nemesis("degraded_observer", n=160,
                                          steps=120)
        _assert_nemesis_parity(ref, out, nem, "degraded_observer ")
        # Not vacuous: true kills at round 30 must be detected, and the
        # scenario threads NemState (checked bit-for-bit above).
        assert nem.needs_state
        assert int(np.asarray(ref[1].detect).sum()) > 0


@pytest.mark.slow
class TestShardedParitySlow:
    def test_state_parity_large(self):
        """Larger N (8 x 5 x 128 = 5120) with every feature on."""
        (ref, _), (out, _) = _run_both(
            5120, 600, slots=16, hot_slots=8, loss_rate=0.01,
            pushpull_every=150)[:2]
        _assert_state_equal(ref, out)

    def test_state_parity_ndev_sweep(self):
        """Parity holds at every divisor device count, not just 8."""
        for ndev in (1, 2, 4):
            (ref, _), (out, _) = _run_both(640, 200, ndev=ndev)[:2]
            _assert_state_equal(ref, out, f"ndev={ndev} ")

    def test_partition_heal_parity(self):
        ref, out, nem = _run_both_nemesis("partition_heal", steps=200)
        _assert_nemesis_parity(ref, out, nem, "partition_heal ")
        # Not vacuous: the bisection must have opened suspicion
        # episodes that reached a verdict inside the run.
        assert int(np.asarray(ref[1].dwell).sum()) > 0

    @pytest.mark.parametrize("name", ["block_kill", "zone_kill",
                                      "asym_loss", "flapping"])
    def test_nemesis_parity_full_catalog(self, name):
        """The rest of the nemesis catalog (tier-1 covers
        degraded_observer): state + HistBank (+ NemState)
        bit-identical under shard_map for every scenario."""
        ref, out, nem = _run_both_nemesis(name, steps=150)
        _assert_nemesis_parity(ref, out, nem, f"{name} ")
