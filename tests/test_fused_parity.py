"""Dissemination-strategy parity (PR 12 tentpole): the Pallas one-pass
``fused`` kernel (gossip/fused.py, interpret-mode on this CPU box) and
the roll-commuted ``prefused`` XLA tail must be bit-identical to the
SWAR reference — at the single-call level on small shapes, over full
round loops in every regime with a distinct code path (healthy, churn,
loss, push-pull, hot tier), through the 8-device shard_map lowering
(fused's halo-hop hybrid), and under nemesis injection.  The slow tier
sweeps the fused kernel's column-block grid (``SwimParams.fused_nb``)
across divisors of n, including single-column blocks.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.timeout_s(600)

NEVER = 2**31 - 1
STRATEGIES = ("prefused", "fused")


def _assert_state_equal(a, b, ctx=""):
    for f in a._fields:
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(x, y), f"{ctx}SwimState.{f} diverged"


def _random_round_inputs(S, N, seed=0):
    """A saturated, adversarial belief matrix + masks: every message
    kind, confirmation count, and age (incl. the _AGE_FRESH sentinel
    and budget-edge values) so each merge branch is exercised."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    heard = ((rng.integers(0, 4, (S, N)) << 6)
             | (rng.integers(0, 4, (S, N)) << 4)
             | rng.integers(0, 16, (S, N))).astype(np.uint8)
    mf = rng.choice(np.asarray([-1, 10, 200, NEVER], np.int32), (N,))
    rx_ok = rng.random(N) < 0.9
    conf_cap = rng.integers(0, 4, (S,)).astype(np.int32)
    return (jnp.asarray(heard), jnp.asarray(mf), jnp.asarray(rx_ok),
            jnp.asarray(conf_cap))


def _dis(p, heard, mf, rx_ok, conf_cap, rnd=50, seed=3):
    import jax

    from consul_tpu.gossip.kernel import _disseminate
    return np.asarray(_disseminate(p, rnd, jax.random.key(seed), heard,
                                   mf, rx_ok, conf_cap))


def _end_state(p, fail, steps, ndev=0, seed=7):
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (init_state, run_rounds,
                                          run_rounds_sharded, shard_state)
    key = jax.random.PRNGKey(seed)
    if ndev > 1:
        st, _ = run_rounds_sharded(shard_state(init_state(p), ndev), key,
                                   jnp.asarray(fail), p, steps, ndev=ndev)
    else:
        st, _ = run_rounds(init_state(p), key, jnp.asarray(fail), p, steps)
    return st


def _fails(n, spec):
    f = np.full(n, NEVER, np.int32)
    for idx, rnd in spec:
        f[idx] = rnd
    return f


class TestSingleCallParity:
    """One _disseminate call on adversarial inputs — the finest-grained
    pin: any divergence here names the exact output bytes."""

    @pytest.mark.parametrize("shape", [(4, 24), (8, 120), (16, 96)])
    def test_all_strategies_match_swar(self, shape):
        from consul_tpu.gossip.params import SwimParams

        S, N = shape
        heard, mf, rx_ok, cap = _random_round_inputs(S, N)
        ref = _dis(SwimParams(n=N, slots=S), heard, mf, rx_ok, cap)
        for dissem in ("planes",) + STRATEGIES:
            p = SwimParams(n=N, slots=S, dissem=dissem)
            np.testing.assert_array_equal(
                _dis(p, heard, mf, rx_ok, cap), ref, err_msg=dissem)

    def test_fused_block_grid_small(self):
        """A first block sweep rides tier-1 (nb=1 whole-row, nb=4, and
        a residue-heavy nb); the divisor sweep is @slow."""
        from consul_tpu.gossip.params import SwimParams

        S, N = 8, 120
        heard, mf, rx_ok, cap = _random_round_inputs(S, N, seed=1)
        ref = _dis(SwimParams(n=N, slots=S), heard, mf, rx_ok, cap)
        for nb in (1, 4, 24):
            p = SwimParams(n=N, slots=S, dissem="fused", fused_nb=nb)
            np.testing.assert_array_equal(
                _dis(p, heard, mf, rx_ok, cap), ref, err_msg=f"nb={nb}")

    def test_fused_nb_must_divide_n(self):
        from consul_tpu.gossip.params import SwimParams

        S, N = 4, 24
        heard, mf, rx_ok, cap = _random_round_inputs(S, N)
        p = SwimParams(n=N, slots=S, dissem="fused", fused_nb=7)
        with pytest.raises(ValueError, match="fused_nb"):
            _dis(p, heard, mf, rx_ok, cap)

    def test_dissem_value_validated(self):
        from consul_tpu.gossip.params import SwimParams

        with pytest.raises(ValueError, match="dissem"):
            # deliberately invalid strategy name — the point of the test
            SwimParams(n=64, dissem="bogus")  # noqa: K02
        with pytest.raises(ValueError, match="fused_nb"):
            SwimParams(n=64, fused_nb=0)


REGIMES = {
    "healthy": (dict(), []),
    "churn": (dict(), [(40, 20), (90, 35), (170, 50), (230, 65)]),
    "loss": (dict(loss_rate=0.1), [(40, 20), (170, 50)]),
    "pushpull": (dict(pushpull_every=20, loss_rate=0.05),
                 [(40, 20), (170, 50)]),
    "hot_tier": (dict(hot_slots=4), [(40, 20), (170, 50)]),
}


class TestFullRoundParity:
    @pytest.mark.parametrize("regime", sorted(REGIMES))
    @pytest.mark.slow
    def test_regime_parity(self, regime):
        """200 full rounds per regime: the entire SwimState — heard
        matrix, slot registers, counters — bit-identical to SWAR."""
        from consul_tpu.gossip.params import SwimParams

        kw, spec = REGIMES[regime]
        n, steps = 240, 200
        fail = _fails(n, spec)
        base = dict(n=n, slots=16, probe_every=5, **kw)
        ref = _end_state(SwimParams(**base), fail, steps)
        if spec:  # churny regimes must actually detect something
            assert int(ref.n_detected) > 0
        for dissem in STRATEGIES:
            st = _end_state(SwimParams(**base, dissem=dissem), fail, steps)
            _assert_state_equal(ref, st, f"{regime}/{dissem} ")

    def test_sharded8_parity(self):
        """The halo-hop composition: fused/prefused through the
        8-device shard_map lowering vs the UNSHARDED SWAR reference —
        one comparison spanning both the strategy and the sharding."""
        from consul_tpu.gossip.params import SwimParams

        n, steps = 320, 200
        fail = _fails(n, [(40, 20), (90, 35), (170, 50), (310, 65)])
        base = dict(n=n, slots=16, probe_every=5, loss_rate=0.05)
        ref = _end_state(SwimParams(**base), fail, steps)
        assert int(ref.n_detected) > 0
        for dissem in STRATEGIES:
            st = _end_state(SwimParams(**base, dissem=dissem), fail,
                            steps, ndev=8)
            _assert_state_equal(ref, st, f"sharded8/{dissem} ")

    @pytest.mark.slow

    def test_nemesis_parity(self):
        """Fault-mask composition: _src_masks folds the nemesis edge
        drops into the fused path in XLA; the asym_loss schedule must
        leave all strategies bit-identical."""
        import jax
        import jax.numpy as jnp

        from consul_tpu.gossip.kernel import init_state, run_rounds
        from consul_tpu.gossip.nemesis import build
        from consul_tpu.gossip.params import SwimParams

        n, steps = 160, 120
        sc = build("asym_loss", n)
        key = jax.random.PRNGKey(13)
        fail = jnp.asarray(sc.fail_round)

        def end(dissem):
            p = SwimParams(n=n, slots=16, probe_every=5, dissem=dissem)
            st, _ = run_rounds(init_state(p), key, fail, p, steps,
                               nem=sc.nem)
            return st

        ref = end("swar")
        for dissem in STRATEGIES:
            _assert_state_equal(ref, end(dissem), f"asym_loss/{dissem} ")


@pytest.mark.slow
class TestFusedParitySlow:
    def test_fused_block_divisor_sweep(self):
        """Every divisor of n as the grid's column-block count,
        including nb=n (single-column blocks, maximal residue splicing)
        — the index-map / residue algebra must hold at every Bn."""
        from consul_tpu.gossip.params import SwimParams

        S, N = 8, 120
        heard, mf, rx_ok, cap = _random_round_inputs(S, N, seed=2)
        ref = _dis(SwimParams(n=N, slots=S), heard, mf, rx_ok, cap)
        divisors = [d for d in range(1, N + 1) if N % d == 0]
        for nb in divisors:
            p = SwimParams(n=N, slots=S, dissem="fused", fused_nb=nb)
            np.testing.assert_array_equal(
                _dis(p, heard, mf, rx_ok, cap), ref, err_msg=f"nb={nb}")

    def test_full_round_fused_block_sweep(self):
        """Block-size sweep through full round loops (slot recycling,
        probe marks, refutes all live), not just one call."""
        from consul_tpu.gossip.params import SwimParams

        n, steps = 240, 150
        fail = _fails(n, [(40, 20), (170, 50)])
        base = dict(n=n, slots=16, probe_every=5, loss_rate=0.05)
        ref = _end_state(SwimParams(**base), fail, steps)
        for nb in (2, 8, 30, 240):
            p = SwimParams(**base, dissem="fused", fused_nb=nb)
            _assert_state_equal(ref, _end_state(p, fail, steps),
                                f"nb={nb} ")

    def test_sharded_ndev_sweep(self):
        """Parity at every divisor device count, both strategies."""
        from consul_tpu.gossip.params import SwimParams

        n, steps = 320, 150
        fail = _fails(n, [(40, 20), (170, 50)])
        base = dict(n=n, slots=16, probe_every=5)
        ref = _end_state(SwimParams(**base), fail, steps)
        for ndev in (2, 4, 8):
            for dissem in STRATEGIES:
                st = _end_state(SwimParams(**base, dissem=dissem), fail,
                                steps, ndev=ndev)
                _assert_state_equal(ref, st, f"ndev={ndev}/{dissem} ")
