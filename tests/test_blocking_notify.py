"""Pin tests for the blocking-query timeout clamp and NotifyGroup.

These nail down the host-side watch plumbing semantics *before* the
device-store refactor (PR 11): ``clamp_wait``'s default/max/jitter
bounds (consul/rpc.go:366-377) and NotifyGroup's exactly-once +
re-register contract (consul/notify.go:15-55). The refactored KVWatchSet
and device watch matcher must keep every behavior pinned here.
"""

from __future__ import annotations

import asyncio
import threading

from consul_tpu.server.blocking import (
    DEFAULT_QUERY_TIME, JITTER_FRACTION, MAX_QUERY_TIME,
    AsyncWaiter, blocking_query, clamp_wait)
from consul_tpu.state.notify import NotifyGroup
from consul_tpu.state.store import StateStore
from consul_tpu.structs.structs import (
    DirEntry, QueryMeta, QueryOptions, RegisterRequest)


class Flag:
    """Minimal Waiter: records every set() call."""

    def __init__(self) -> None:
        self.sets = 0

    def set(self) -> None:
        self.sets += 1


class TestClampWait:
    def test_zero_uses_default(self):
        for _ in range(64):
            w = clamp_wait(0)
            assert DEFAULT_QUERY_TIME * (1 - 1 / JITTER_FRACTION) <= w
            assert w <= DEFAULT_QUERY_TIME

    def test_negative_uses_default(self):
        w = clamp_wait(-5.0)
        assert DEFAULT_QUERY_TIME * (1 - 1 / JITTER_FRACTION) <= w
        assert w <= DEFAULT_QUERY_TIME

    def test_capped_at_max(self):
        for _ in range(64):
            w = clamp_wait(10_000.0)
            assert MAX_QUERY_TIME * (1 - 1 / JITTER_FRACTION) <= w
            assert w <= MAX_QUERY_TIME

    def test_explicit_wait_jittered_downward(self):
        for _ in range(64):
            w = clamp_wait(160.0)
            assert 160.0 * (1 - 1 / JITTER_FRACTION) <= w <= 160.0

    def test_jitter_varies(self):
        # rpc.go:29-41: jitter staggers the re-poll herd — repeated
        # clamps of the same request must not all collapse to one value.
        vals = {round(clamp_wait(600.0), 9) for _ in range(32)}
        assert len(vals) > 1


class TestNotifyGroup:
    def test_notify_fires_each_waiter_exactly_once(self):
        g = NotifyGroup()
        a, b = Flag(), Flag()
        g.wait(a)
        g.wait(b)
        g.notify()
        assert (a.sets, b.sets) == (1, 1)
        # Registry swapped out: a second notify fires nobody.
        g.notify()
        assert (a.sets, b.sets) == (1, 1)

    def test_double_register_is_idempotent(self):
        g = NotifyGroup()
        a = Flag()
        g.wait(a)
        g.wait(a)
        assert len(g) == 1
        g.notify()
        assert a.sets == 1

    def test_clear_deregisters(self):
        g = NotifyGroup()
        a, b = Flag(), Flag()
        g.wait(a)
        g.wait(b)
        g.clear(a)
        g.notify()
        assert (a.sets, b.sets) == (0, 1)

    def test_clear_unregistered_is_noop(self):
        g = NotifyGroup()
        g.clear(Flag())  # must not raise
        assert len(g) == 0

    def test_reregister_after_notify(self):
        # notify.go:15-27 — the waiter re-registers on its next loop
        # iteration and is woken again by the next mutation.
        g = NotifyGroup()
        a = Flag()
        g.wait(a)
        g.notify()
        g.wait(a)
        g.notify()
        assert a.sets == 2


class TestStoreWatchPlumbing:
    """Pin the store-side registration API the refactor must preserve."""

    def test_table_watch_fires_on_mutation(self):
        # KV writes fire only the radix KV watch; table groups fire on
        # catalog/session/acl mutations (state_store.go notify sites).
        s = StateStore()
        a = Flag()
        s.watch(("nodes",), a)
        s.ensure_registration(1, RegisterRequest(node="n1", address="1.2.3.4"))
        assert a.sets == 1
        # One-shot: a second write without re-register fires nothing.
        s.ensure_registration(2, RegisterRequest(node="n2", address="1.2.3.5"))
        assert a.sets == 1

    def test_kv_prefix_watch_path_and_prefix(self):
        s = StateStore()
        exact, pfx, other = Flag(), Flag(), Flag()
        s.watch_kv("web/a", exact)     # woken: key under this path
        s.watch_kv("web/", pfx)        # woken: watch prefixes the key
        s.watch_kv("db/", other)       # untouched prefix stays asleep
        s.kvs_set(1, DirEntry(key="web/a/leaf", value=b"v"))
        assert (exact.sets, pfx.sets, other.sets) == (1, 1, 0)

    def test_stop_watch_kv_prunes(self):
        s = StateStore()
        a = Flag()
        s.watch_kv("web/", a)
        s.stop_watch_kv("web/", a)
        s.kvs_set(1, DirEntry(key="web/x", value=b"v"))
        assert a.sets == 0


class TestAsyncWaiter:
    def test_set_from_loop_and_thread(self):
        async def main():
            loop = asyncio.get_running_loop()
            w = AsyncWaiter(loop)
            w.set()  # same-loop path
            await asyncio.wait_for(w._event.wait(), 1.0)
            w.clear()
            t = threading.Thread(target=w.set)  # cross-thread path
            t.start()
            await asyncio.wait_for(w._event.wait(), 1.0)
            t.join()

        asyncio.run(main())


class TestBlockingQuery:
    def _opts(self, min_index: int, wait: float = 5.0) -> QueryOptions:
        return QueryOptions(min_query_index=min_index, max_query_time=wait)

    def test_min_index_zero_runs_once(self):
        s = StateStore()
        runs = []

        async def main():
            meta = QueryMeta()

            async def run():
                runs.append(1)
                meta.index = 7

            await blocking_query(s, self._opts(0), meta, run,
                                 tables=("kvs",))

        asyncio.run(main())
        assert runs == [1]

    def test_wakes_on_kv_write(self):
        s = StateStore()
        s.kvs_set(5, DirEntry(key="web/a", value=b"v"))

        async def main():
            meta = QueryMeta()

            async def run():
                _, e = s.kvs_get("web/a")
                meta.index = e.modify_index if e else 0

            async def writer():
                await asyncio.sleep(0.05)
                s.kvs_set(9, DirEntry(key="web/a", value=b"v2"))

            t = asyncio.get_running_loop().create_task(writer())
            await asyncio.wait_for(
                blocking_query(s, self._opts(5), meta, run,
                               kv_prefix="web/a"),
                timeout=3.0)
            await t
            return meta.index

        assert asyncio.run(main()) == 9

    def test_returns_on_deadline_without_write(self):
        s = StateStore()
        s.kvs_set(5, DirEntry(key="web/a", value=b"v"))

        async def main():
            meta = QueryMeta()

            async def run():
                meta.index = 5

            # max_query_time is clamped+jittered but never inflated, so
            # a 0.1s budget returns well inside the watchdog window.
            await asyncio.wait_for(
                blocking_query(s, self._opts(5, wait=0.1), meta, run,
                               kv_prefix="web/a"),
                timeout=3.0)
            return meta.index

        assert asyncio.run(main()) == 5
