"""State store on the C++ mmap MVCC backend (the LMDB role).

Re-runs the KVS/session/watch semantics suites from test_state_store
with every ``StateStore()`` backed by :class:`NativeKVTable`, plus
backend-direct tests and a kill-and-restart recovery test through the
forked daemon (recovery = raft-log replay rebuilding the store, the
reference's model at state_store.go:190-196).
"""

import base64
import signal
import time

import pytest

import test_state_store as tss
from consul_tpu.native.store import build_native, native_available
from consul_tpu.state import store as store_mod
from consul_tpu.state.kvtable import DictKVTable, NativeKVTable
from consul_tpu.structs.structs import DirEntry

build_native()
pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native toolchain unavailable")


@pytest.fixture(autouse=True)
def native_backend(tmp_path, monkeypatch):
    """Every StateStore() in these tests gets a fresh native KV table."""
    orig = store_mod.StateStore.__init__
    seq = [0]

    def patched(self, gc_hint=None, kv_backend=None):
        if kv_backend is None:
            seq[0] += 1
            kv_backend = NativeKVTable(str(tmp_path / f"kv{seq[0]}"))
        orig(self, gc_hint=gc_hint, kv_backend=kv_backend)

    monkeypatch.setattr(store_mod.StateStore, "__init__", patched)
    yield


# The full KV semantics suite (set/CAS/lock/unlock/list/delete-tree/
# tombstones), the session-invalidation cascades that walk the
# session->keys index, and the watch plumbing — all on native rows.
class TestKVSOnNative(tss.TestKVS):
    pass


class TestSessionsOnNative(tss.TestSessions):
    pass


class TestWatchesOnNative(tss.TestWatches):
    pass


class TestBackendDirect:
    def test_roundtrip_and_prefix_scan(self, tmp_path):
        t = NativeKVTable(str(tmp_path / "d"))
        for k in ("a/1", "a/2", "b/1", "a!", "a0"):
            t.put(DirEntry(key=k, value=k.encode()), old=None)
        assert t.get("a/1").value == b"a/1"
        assert t.prefix_keys("a/") == ["a/1", "a/2"]
        assert [k for k, _ in t.items("a/")] == ["a/1", "a/2"]
        assert t.pop("a/1").key == "a/1"
        assert t.get("a/1") is None
        t.close()

    def test_session_index_maintained(self, tmp_path):
        t = NativeKVTable(str(tmp_path / "d"))
        t.put(DirEntry(key="lock1", session="s1"), old=None)
        t.put(DirEntry(key="lock2", session="s1"), old=None)
        t.put(DirEntry(key="lock3", session="s2"), old=None)
        assert t.session_keys("s1") == ["lock1", "lock2"]
        # steal the lock: index rows follow the session change
        old = t.get("lock1")
        t.put(DirEntry(key="lock1", session="s2"), old=old)
        assert t.session_keys("s1") == ["lock2"]
        assert sorted(t.session_keys("s2")) == ["lock1", "lock3"]
        t.pop("lock3")
        assert t.session_keys("s2") == ["lock1"]
        t.close()

    def test_unicode_keys(self, tmp_path):
        t = NativeKVTable(str(tmp_path / "d"))
        keys = ["café/1", "café/2", "caf\U0001F600"]
        for k in keys:
            t.put(DirEntry(key=k, value=b"v"), old=None)
        assert t.prefix_keys("café/") == ["café/1", "café/2"]
        assert t.get("caf\U0001F600") is not None
        t.close()

    def test_parity_with_dict_backend(self, tmp_path):
        """Same op sequence, byte-identical observable state."""
        import random
        rng = random.Random(7)
        nat = NativeKVTable(str(tmp_path / "d"))
        ref = DictKVTable()
        keys = [f"k/{i % 17}" for i in range(200)]
        for i, k in enumerate(keys):
            op = rng.choice(["put", "put", "put", "pop"])
            if op == "put":
                d = DirEntry(key=k, value=f"v{i}".encode(),
                             session=rng.choice(["", "s1", "s2"]),
                             modify_index=i)
                nat.put(d, old=nat.get(k))
                ref.put(d.clone(), old=ref.get(k))
            else:
                a, b = nat.pop(k), ref.pop(k)
                assert (a is None) == (b is None)
        assert nat.prefix_keys("") == ref.prefix_keys("")
        for k in nat.prefix_keys(""):
            assert nat.get(k).to_wire() == ref.get(k).to_wire()
        for s in ("s1", "s2"):
            assert nat.session_keys(s) == ref.session_keys(s)
        nat.close()


class TestCrashRecovery:
    def test_kill9_restart_replays_kv_from_raft_log(self, tmp_path):
        """SIGKILL the daemon mid-flight; a restart on the same data dir
        must rebuild the KV state by replaying the native raft log into
        a fresh native KV table."""
        from blackbox_util import TestServer
        data_dir = str(tmp_path / "data")
        s = TestServer("bb-crash",
                       config_extra={"data_dir": data_dir}).start()
        ports = s.ports
        try:
            s.wait_for_api()
            s.wait_for_leader()
            for i in range(5):
                assert s.http_put(f"/v1/kv/crash/{i}", f"v{i}".encode()) is True
            # no graceful anything — the store file must not matter
            s.proc.send_signal(signal.SIGKILL)
            s.proc.wait(10)
        finally:
            s.tmp.cleanup()

        s2 = TestServer("bb-crash", config_extra={"data_dir": data_dir,
                                                  "ports": ports}).start()
        s2.ports = ports
        try:
            s2.wait_for_api()
            s2.wait_for_leader()
            deadline = time.monotonic() + 15
            got = None
            while time.monotonic() < deadline:
                try:
                    got = s2.http_get("/v1/kv/crash/3")
                    if got:
                        break
                except Exception:
                    pass
                time.sleep(0.2)
            assert got and base64.b64decode(got[0]["Value"]) == b"v3"
        finally:
            s2.stop()
