"""Multi-server cluster tests: Raft-backed servers + leader duties.

The reference's in-process multi-server tier (SURVEY.md §4 tier 1,
consul/leader_test.go / session_ttl_test.go shape): N Servers share one
transport with compressed timers; writes land on the leader, replicate
everywhere; leader-owned timers (session TTL, tombstone GC) fire through
Raft so every FSM converges.
"""

from __future__ import annotations

import asyncio

import pytest

from consul_tpu.consensus.raft import MemoryTransport, RaftConfig
from consul_tpu.server.server import NotLeaderError, Server, ServerConfig
from consul_tpu.structs.structs import (
    DirEntry, KVSOp, KVSRequest, KeyRequest, RegisterRequest, Session,
    SessionOp, SessionRequest)


def fast_raft() -> RaftConfig:
    return RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.06,
                      election_timeout_max=0.12, rpc_timeout=0.05)


def make_servers(n, **cfg_kw):
    tr = MemoryTransport()
    names = [f"s{i}" for i in range(n)]
    servers = [Server(ServerConfig(node_name=name, peers=names,
                                   raft=fast_raft(), **cfg_kw), transport=tr)
               for name in names]
    return tr, servers


async def start_and_elect(servers):
    for s in servers:
        await s.start()
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline:
        leaders = [s for s in servers if s.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        await asyncio.sleep(0.01)
    raise AssertionError("no leader")


async def stop_all(servers):
    for s in servers:
        await s.stop()


async def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timeout: {msg}")


def test_cluster_replicates_writes():
    async def main():
        _, servers = make_servers(3)
        leader = await start_and_elect(servers)
        await leader.kvs.apply(KVSRequest(
            op=KVSOp.SET.value, dir_ent=DirEntry(key="foo", value=b"bar")))
        await wait_until(
            lambda: all(s.store.kvs_get("foo")[1] is not None
                        and s.store.kvs_get("foo")[1].value == b"bar"
                        for s in servers),
            msg="KV replication")
        # Catalog registration replicates too.
        await leader.catalog.register(
            RegisterRequest(node="web1", address="10.0.0.1"))
        await wait_until(
            lambda: all(any(n.node == "web1" for n in s.store.nodes()[1])
                        for s in servers),
            msg="catalog replication")
        await stop_all(servers)
    asyncio.run(main())


def test_follower_write_raises_not_leader():
    async def main():
        _, servers = make_servers(3)
        leader = await start_and_elect(servers)
        follower = next(s for s in servers if s is not leader)
        with pytest.raises(NotLeaderError):
            await follower.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="x", value=b"y")))
        await stop_all(servers)
    asyncio.run(main())


def test_session_ttl_expires_on_leader():
    async def main():
        _, servers = make_servers(3, session_ttl_min=0.05)
        leader = await start_and_elect(servers)
        await leader.catalog.register(
            RegisterRequest(node="web1", address="10.0.0.1"))
        sid = await leader.session.apply(SessionRequest(
            op=SessionOp.CREATE.value,
            session=Session(node="web1", ttl="0.1s")))
        assert sid
        _, got = leader.store.session_get(sid)
        assert got is not None
        # TTL*2 grace then destroyed through Raft on every server.
        await wait_until(
            lambda: all(s.store.session_get(sid)[1] is None for s in servers),
            msg="session TTL expiry")
        await stop_all(servers)
    asyncio.run(main())


def test_session_timers_rearm_on_failover():
    async def main():
        _, servers = make_servers(3, session_ttl_min=0.05)
        leader = await start_and_elect(servers)
        await leader.catalog.register(
            RegisterRequest(node="web1", address="10.0.0.1"))
        sid = await leader.session.apply(SessionRequest(
            op=SessionOp.CREATE.value,
            session=Session(node="web1", ttl="0.15s")))
        await leader.stop()
        rest = [s for s in servers if s is not leader]
        new_leader = await start_and_elect(rest)
        # New leader re-armed the timer (initializeSessionTimers) and the
        # session still expires.
        await wait_until(
            lambda: all(s.store.session_get(sid)[1] is None for s in rest),
            timeout=8.0, msg="post-failover session expiry")
        assert new_leader.leader_duties.session_timer_count() == 0
        await stop_all(rest)
    asyncio.run(main())


def test_tombstone_reap_through_raft():
    async def main():
        _, servers = make_servers(3, tombstone_ttl=0.1,
                                  tombstone_granularity=0.05)
        leader = await start_and_elect(servers)
        await leader.kvs.apply(KVSRequest(
            op=KVSOp.SET.value, dir_ent=DirEntry(key="doomed", value=b"v")))
        await leader.kvs.apply(KVSRequest(
            op=KVSOp.DELETE.value, dir_ent=DirEntry(key="doomed")))
        assert len(leader.store._tombstones) == 1
        await wait_until(
            lambda: all(len(s.store._tombstones) == 0 for s in servers),
            msg="tombstone reap replicated")
        await stop_all(servers)
    asyncio.run(main())


def test_consistent_read_barrier_on_leader_only():
    async def main():
        _, servers = make_servers(3)
        leader = await start_and_elect(servers)
        await leader.consistent_read_barrier()
        follower = next(s for s in servers if s is not leader)
        with pytest.raises(NotLeaderError):
            await follower.consistent_read_barrier()
        await stop_all(servers)
    asyncio.run(main())


def test_blocking_query_wakes_on_replicated_write():
    async def main():
        _, servers = make_servers(3)
        leader = await start_and_elect(servers)
        follower = next(s for s in servers if s is not leader)
        idx, _ = follower.store.kvs_get("watched")

        async def writer():
            await asyncio.sleep(0.05)
            await leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value,
                dir_ent=DirEntry(key="watched", value=b"now")))

        w = asyncio.get_event_loop().create_task(writer())
        # Blocking read against the FOLLOWER's store wakes when the write
        # replicates through its FSM.
        meta, out = await follower.kvs.get(KeyRequest(
            key="watched", min_query_index=max(idx, 1), max_query_time=3.0))
        await w
        assert out and out[0].value == b"now"
        await stop_all(servers)
    asyncio.run(main())
