"""Agent edge features: check runners, anti-entropy, maintenance,
persistence (reference tier: command/agent/check_test.go,
local_test.go, agent_test.go)."""

import asyncio
import time

import pytest

from consul_tpu.agent.agent import (
    Agent, AgentConfig, NODE_MAINT_CHECK_ID, SERVICE_MAINT_PREFIX)
from consul_tpu.agent.checks import CheckTTL, CheckType
from consul_tpu.agent.local import ae_scale
from consul_tpu.structs.structs import (
    HEALTH_CRITICAL, HEALTH_PASSING, HEALTH_WARNING, HealthCheck, NodeService)


class Recorder:
    """Minimal CheckNotifier."""

    def __init__(self):
        self.updates = []

    def update_check(self, check_id, status, output):
        self.updates.append((check_id, status, output))


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.close()


def _mk_agent(tmp_path=None, **kw):
    cfg = AgentConfig(http_port=0, dns_port=0, ae_interval=0.2,
                      data_dir=str(tmp_path) if tmp_path else "", **kw)
    return Agent(cfg)


async def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(0.02)
    return False


class TestCheckType:
    def test_validity(self):
        assert CheckType(ttl=10).valid()
        assert CheckType(script="true", interval=10).valid()
        assert CheckType(http="http://x", interval=10).valid()
        assert not CheckType().valid()
        assert not CheckType(script="true").valid()  # no interval
        assert not CheckType(http="http://x").valid()


class TestRunners:
    def test_monitor_exit_codes(self, loop):
        async def body():
            from consul_tpu.agent.checks import CheckMonitor
            rec = Recorder()
            for script, want in (("exit 0", HEALTH_PASSING),
                                 ("exit 1", HEALTH_WARNING),
                                 ("exit 2", HEALTH_CRITICAL)):
                m = CheckMonitor(rec, "c", script, 10)
                await m._check()
                assert rec.updates[-1][1] == want

        loop.run_until_complete(body())

    def test_monitor_captures_output(self, loop):
        async def body():
            from consul_tpu.agent.checks import CheckMonitor
            rec = Recorder()
            m = CheckMonitor(rec, "c", "echo hello-output", 10)
            await m._check()
            assert "hello-output" in rec.updates[-1][2]

        loop.run_until_complete(body())

    def test_ttl_expiry_and_heartbeat(self, loop):
        async def body():
            rec = Recorder()
            ttl = CheckTTL(rec, "t", 0.1)
            ttl.start()
            await asyncio.sleep(0.25)
            assert rec.updates[-1][1] == HEALTH_CRITICAL
            ttl.set_status(HEALTH_PASSING, "ok")
            assert rec.updates[-1][1] == HEALTH_PASSING
            # heartbeats keep it alive
            for _ in range(3):
                await asyncio.sleep(0.05)
                ttl.set_status(HEALTH_PASSING, "ok")
            assert rec.updates[-1][1] == HEALTH_PASSING
            ttl.stop()

        loop.run_until_complete(body())


class TestAEScale:
    def test_thresholds(self):
        # util.go:27-37 table: <=128 nodes unscaled; doubles add a multiple
        assert ae_scale(60, 100) == 60
        assert ae_scale(60, 128) == 60
        assert ae_scale(60, 256) == 120
        assert ae_scale(60, 512) == 180
        assert ae_scale(60, 8192) == 420


class TestAgentRegistry:
    def test_service_and_check_sync_to_catalog(self, loop):
        async def body():
            agent = _mk_agent()
            await agent.start()
            await agent.add_service(
                NodeService(id="web", service="web", port=80),
                [CheckType(ttl=30)])
            # anti-entropy pushes it into the catalog
            ok = await _wait_for(
                lambda: "web" in (agent.server.store.node_services("node1")[1] or {}))
            assert ok
            _, checks = agent.server.store.node_checks("node1")
            ids = {c.check_id for c in checks}
            assert "service:web" in ids
            # TTL pass flows through local -> catalog
            agent.update_ttl_check("service:web", HEALTH_PASSING, "beating")
            ok = await _wait_for(lambda: any(
                c.check_id == "service:web" and c.status == HEALTH_PASSING
                for c in agent.server.store.node_checks("node1")[1]))
            assert ok
            # removal deregisters
            await agent.remove_service("web")
            ok = await _wait_for(
                lambda: "web" not in (agent.server.store.node_services("node1")[1] or {}))
            assert ok
            await agent.stop()

        loop.run_until_complete(body())

    def test_maintenance_mode(self, loop):
        async def body():
            agent = _mk_agent()
            await agent.start()
            await agent.add_service(NodeService(id="db", service="db", port=1))
            agent.enable_node_maintenance("fixing stuff")
            agent.enable_service_maintenance("db", "db down")
            assert NODE_MAINT_CHECK_ID in agent.local.checks
            maint_id = SERVICE_MAINT_PREFIX + "db"
            assert maint_id in agent.local.checks
            assert agent.local.checks[maint_id].status == HEALTH_CRITICAL
            ok = await _wait_for(lambda: any(
                c.check_id == NODE_MAINT_CHECK_ID
                for c in agent.server.store.node_checks("node1")[1]))
            assert ok
            agent.disable_node_maintenance()
            agent.disable_service_maintenance("db")
            ok = await _wait_for(lambda: not any(
                c.check_id in (NODE_MAINT_CHECK_ID, maint_id)
                for c in agent.server.store.node_checks("node1")[1]))
            assert ok
            with pytest.raises(ValueError):
                agent.enable_service_maintenance("nope")
            await agent.stop()

        loop.run_until_complete(body())

    def test_ttl_state_survives_restart_within_window(self, loop, tmp_path):
        """persistCheckState/loadCheckState (agent.go:890-959): a TTL
        check restarted inside its window resumes the app's last
        heartbeat instead of flipping critical; expired state is
        discarded."""
        async def body():
            agent = _mk_agent(tmp_path)
            await agent.start()
            await agent.add_check(
                HealthCheck(node="node1", check_id="hb", name="hb"),
                CheckType(ttl=60))
            agent.update_ttl_check("hb", HEALTH_PASSING, "app alive")
            await agent.stop()

            agent2 = _mk_agent(tmp_path)
            await agent2.start()
            ok = await _wait_for(lambda: "hb" in agent2.local.checks)
            assert ok
            assert agent2.local.checks["hb"].status == HEALTH_PASSING
            assert agent2.local.checks["hb"].output == "app alive"
            await agent2.stop()

            # expired saved state must NOT be restored
            import glob
            import json as _json
            state_files = glob.glob(str(tmp_path / "checks" / "state" / "*"))
            assert state_files
            for sf in state_files:
                with open(sf) as f:
                    st = _json.load(f)
                st["expires"] = 1.0  # long past
                with open(sf, "w") as f:
                    _json.dump(st, f)
            agent3 = _mk_agent(tmp_path)
            await agent3.start()
            ok = await _wait_for(lambda: "hb" in agent3.local.checks)
            assert ok
            assert agent3.local.checks["hb"].status == HEALTH_CRITICAL
            await agent3.stop()

        loop.run_until_complete(body())

    def test_persistence_roundtrip(self, loop, tmp_path):
        async def body():
            agent = _mk_agent(tmp_path)
            await agent.start()
            await agent.add_service(
                NodeService(id="web", service="web", port=80,
                            tags=["v1"]), [CheckType(ttl=60)])
            await agent.add_check(
                HealthCheck(node="node1", check_id="standalone",
                            name="standalone"), CheckType(ttl=60))
            await agent.stop()

            # new agent, same data-dir: definitions reload at boot
            agent2 = _mk_agent(tmp_path)
            await agent2.start()
            ok = await _wait_for(lambda: "web" in agent2.local.services
                                 and "standalone" in agent2.local.checks)
            assert ok
            assert agent2.local.services["web"].tags == ["v1"]
            # reloaded TTL runner is live
            agent2.update_ttl_check("standalone", HEALTH_PASSING, "ok")
            assert agent2.local.checks["standalone"].status == HEALTH_PASSING
            # deregistration removes the persisted file
            await agent2.remove_service("web")
            await agent2.stop()
            agent3 = _mk_agent(tmp_path)
            await agent3.start()
            await asyncio.sleep(0.2)
            assert "web" not in agent3.local.services
            await agent3.stop()

        loop.run_until_complete(body())


class TestAgentHTTPEndpoints:
    def test_register_ttl_maintenance_over_http(self, loop):
        async def body():
            import httpx
            agent = _mk_agent()
            await agent.start()
            host, port = agent.http.addr
            base = f"http://{host}:{port}"
            async with httpx.AsyncClient() as c:
                r = await c.put(f"{base}/v1/agent/service/register", json={
                    "ID": "redis", "Name": "redis", "Port": 6379,
                    "Check": {"TTL": "30s"}})
                assert r.status_code == 200, r.text
                r = await c.get(f"{base}/v1/agent/services")
                assert "redis" in r.json()
                r = await c.put(f"{base}/v1/agent/check/pass/service:redis")
                assert r.status_code == 200, r.text
                r = await c.get(f"{base}/v1/agent/checks")
                assert r.json()["service:redis"]["Status"] == HEALTH_PASSING
                # unknown TTL check -> 404
                r = await c.put(f"{base}/v1/agent/check/pass/nope")
                assert r.status_code == 404
                # standalone check registration
                r = await c.put(f"{base}/v1/agent/check/register", json={
                    "Name": "mem", "TTL": "10s"})
                assert r.status_code == 200, r.text
                r = await c.put(f"{base}/v1/agent/check/warn/mem?note=high")
                assert r.status_code == 200
                r = await c.get(f"{base}/v1/agent/checks")
                body_checks = r.json()
                assert body_checks["mem"]["Status"] == HEALTH_WARNING
                assert body_checks["mem"]["Output"] == "high"
                # maintenance
                r = await c.put(f"{base}/v1/agent/maintenance?enable=true&reason=why")
                assert r.status_code == 200
                r = await c.get(f"{base}/v1/agent/checks")
                assert NODE_MAINT_CHECK_ID in r.json()
                r = await c.put(f"{base}/v1/agent/maintenance?enable=false")
                r = await c.get(f"{base}/v1/agent/checks")
                assert NODE_MAINT_CHECK_ID not in r.json()
                # bad enable param
                r = await c.put(f"{base}/v1/agent/maintenance")
                assert r.status_code == 400
                # deregister service
                r = await c.put(f"{base}/v1/agent/service/deregister/redis")
                assert r.status_code == 200
                r = await c.get(f"{base}/v1/agent/services")
                assert "redis" not in r.json()
            await agent.stop()

        loop.run_until_complete(body())
