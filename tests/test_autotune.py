"""Autotune control plane (obs/tuner.py): evidence admission, decision
rules, verdict determinism, resolution precedence, fingerprint
re-settles, and a plane boot that resolves its kernel knobs from a
persisted verdict on the CPU mesh.

The verdict directory is a per-session temp dir (tests/conftest.py sets
CONSUL_TPU_AUTOTUNE_DIR) so a developer's real ``make tune`` verdict
never leaks into these boots; tests that need a private dir repoint
the env var at their own tmp_path.
"""

import asyncio
import json
import os

import pytest

from consul_tpu.obs import tuner
from consul_tpu.obs.tuner import Evidence, EvidenceTable

CPU_FP = {"platform": "cpu", "device_count": 8, "jax": "0.0.test"}


def _rps(tail, value, platform="", stamp=100.0):
    return Evidence(f"bench.rps.{tail}", value, "test", platform, stamp)


def _baseline_rows(stamp=100.0):
    """A small admissible evidence set where swar wins the dissemination
    A/B by >2% and one-device sharding wins the ladder."""
    return [
        _rps("swim_gossip_rounds_per_sec_4096_nodes", 120.0, stamp=stamp),
        _rps("swim_gossip_rounds_per_sec_4096_nodes_planes", 90.0,
             stamp=stamp),
        _rps("swim_gossip_rounds_per_sec_4096_nodes_shard4", 80.0,
             stamp=stamp),
    ]


@pytest.fixture
def autotune_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("CONSUL_TPU_AUTOTUNE_DIR", str(tmp_path))
    return tmp_path


# -- evidence admission ------------------------------------------------------


class TestEvidenceTable:
    def test_foreign_platform_rejected_both_directions(self):
        rows = [Evidence("k1", 1.0, "s", "axon", 10.0),
                Evidence("k2", 2.0, "s", "cpu", 10.0),
                Evidence("k3", 3.0, "s", "", 10.0)]
        cpu = EvidenceTable(rows, "cpu")
        assert set(cpu.rows) == {"k2", "k3"}
        assert [why for _, why in cpu.rejected] == ["foreign-platform"]
        chip = EvidenceTable(rows, "axon")
        assert set(chip.rows) == {"k1", "k3"}

    def test_chip_platforms_are_one_class(self):
        rows = [Evidence("k", 1.0, "s", "axon", 10.0)]
        assert "k" in EvidenceTable(rows, "tpu").rows

    def test_stale_vs_epoch_rejected(self):
        fresh = Evidence("fresh", 1.0, "s", "", 1e9)
        stale = Evidence("old", 2.0, "s", "",
                         1e9 - tuner.MAX_EVIDENCE_AGE_S - 1)
        table = EvidenceTable([fresh, stale], "cpu")
        assert "fresh" in table.rows and "old" not in table.rows
        assert [why for _, why in table.rejected] == ["stale"]

    def test_duplicate_keys_newest_wins(self):
        rows = [Evidence("k", 1.0, "a", "", 10.0),
                Evidence("k", 2.0, "b", "", 20.0)]
        assert EvidenceTable(rows, "cpu").value("k") == 2.0
        assert EvidenceTable(list(reversed(rows)), "cpu").value("k") == 2.0


# -- decision rules ----------------------------------------------------------


class TestRules:
    def _table(self, rows):
        return EvidenceTable(rows, "cpu")

    def test_dissem_needs_two_strategies(self):
        t = self._table([_rps("swim_gossip_rounds_per_sec_4096_nodes",
                              100.0)])
        assert tuner._rule_dissem(t, CPU_FP) is None

    def test_dissem_argmax_with_clear_win(self):
        t = self._table([
            _rps("swim_gossip_rounds_per_sec_4096_nodes", 100.0),
            _rps("swim_gossip_rounds_per_sec_4096_nodes_fused", 110.0)])
        value, used, _reason = tuner._rule_dissem(t, CPU_FP)
        assert value == "fused" and len(used) == 2

    def test_dissem_within_noise_ties_to_swar(self):
        t = self._table([
            _rps("swim_gossip_rounds_per_sec_4096_nodes", 100.0),
            _rps("swim_gossip_rounds_per_sec_4096_nodes_fused", 101.0)])
        value, _used, _reason = tuner._rule_dissem(t, CPU_FP)
        assert value == "swar"

    def test_hot_slots_threshold(self):
        mk = lambda h, v: _rps(  # noqa: E731
            f"swim_gossip_rounds_per_sec_2000_nodes_churn10ppm_hot{h}"
            if h else "swim_gossip_rounds_per_sec_2000_nodes_churn10ppm",
            v)
        value, _, _ = tuner._rule_hot_slots(
            self._table([mk(0, 100.0), mk(8, 110.0)]), CPU_FP)
        assert value == 8
        value, _, _ = tuner._rule_hot_slots(
            self._table([mk(0, 100.0), mk(8, 101.0)]), CPU_FP)
        assert value == 0

    def test_shard_ladder_argmax(self):
        t = self._table(_baseline_rows())
        value, _, _ = tuner._rule_shard_devices(t, CPU_FP)
        assert value == 1

    def test_flight_drain_overhead(self):
        mk = lambda flight, v: _rps(  # noqa: E731
            "swim_gossip_rounds_per_sec_2000_nodes_churn0ppm"
            + ("_flight" if flight else ""), v)
        value, _, _ = tuner._rule_flight_drain_every(
            self._table([mk(False, 100.0), mk(True, 90.0)]), CPU_FP)
        assert value == 32  # 10% overhead -> halve the cadence
        value, _, _ = tuner._rule_flight_drain_every(
            self._table([mk(False, 100.0), mk(True, 99.0)]), CPU_FP)
        assert value == 16

    def test_http_workers_argmax(self):
        rows = [Evidence("serve.kv_get_rps.workers1", 4000.0, "s", "", 1.0),
                Evidence("serve.kv_get_rps.workers4", 5000.0, "s", "", 1.0)]
        value, _, _ = tuner._rule_http_workers(self._table(rows), CPU_FP)
        assert value == 4

    def test_device_store_by_platform_class(self):
        on, _, _ = tuner._rule_device_store(self._table([]), CPU_FP)
        assert on is False
        on, _, _ = tuner._rule_device_store(
            self._table([]), {"platform": "axon", "device_count": 8})
        assert on is True

    def test_watch_device_min_prefers_measured_crossover(self):
        rows = [Evidence("watch.crossover_watches", 40000, "s", "", 1.0),
                Evidence("watch.sweep_max", 65536, "s", "", 1.0)]
        value, used, _ = tuner._rule_watch_device_min(
            self._table(rows), CPU_FP)
        assert value == 40000 and used == ["watch.crossover_watches"]

    def test_watch_device_min_floors_above_sweep_cap(self):
        rows = [Evidence("watch.sweep_max", 65536, "s", "", 1.0)]
        value, _, _ = tuner._rule_watch_device_min(self._table(rows), CPU_FP)
        assert value == max(tuner.DEFAULT_WATCH_DEVICE_MIN, 2 * 65536)
        assert tuner._rule_watch_device_min(self._table([]), CPU_FP) is None

    def test_lease_floor_detectability(self):
        mk = lambda s, det: Evidence(  # noqa: E731
            f"chaos.detected.{s}", det, "s", "", 1.0)
        all_det = [mk(s, True) for s in ("clock_skew", "clock_jump",
                                         "fsync_stall")]
        value, _, _ = tuner._rule_lease_timeout_floor(
            self._table(all_det), CPU_FP)
        assert value == 0.0
        one_miss = all_det[:2] + [mk("fsync_stall", False)]
        value, _, reason = tuner._rule_lease_timeout_floor(
            self._table(one_miss), CPU_FP)
        assert value == -1.0 and "fsync_stall" in reason
        assert tuner._rule_lease_timeout_floor(
            self._table([]), CPU_FP) is None


# -- settle determinism + verdict hygiene ------------------------------------


class TestSettle:
    def test_settle_is_byte_deterministic(self):
        rows = _baseline_rows()
        a = tuner.settle(rows, CPU_FP)
        b = tuner.settle(list(reversed(rows)), CPU_FP)
        assert tuner.verdict_bytes(a) == tuner.verdict_bytes(b)

    def test_settle_covers_whole_registry(self):
        verdict = tuner.settle([], CPU_FP)
        assert set(verdict["knobs"]) == set(tuner.KNOBS)
        assert verdict["format"] == tuner.VERDICT_FORMAT
        for name, row in verdict["knobs"].items():
            if name == "device_store":
                # decided from the fingerprint itself, never starved
                assert row["source"] == "evidence"
                assert row["evidence"] == ["fingerprint.platform"]
            else:
                assert row["source"] == "default"

    def test_settle_records_rejections(self):
        rows = _baseline_rows() + [
            Evidence("bench.rps.swim_gossip_rounds_per_sec_8_nodes",
                     1.0, "s", "axon", 100.0)]
        verdict = tuner.settle(rows, CPU_FP)
        assert any("foreign-platform" in r
                   for r in verdict["rejected_rows"])

    def test_one_bad_rule_degrades_to_default(self, monkeypatch):
        knob = tuner.KNOBS["dissem"]
        def boom(table, fp):
            raise RuntimeError("rule crashed")
        monkeypatch.setitem(
            tuner.KNOBS, "dissem",
            tuner.Knob(default=knob.default, kind=knob.kind,
                       choices=knob.choices, target=knob.target,
                       rule=boom, evidence=knob.evidence, doc=knob.doc))
        verdict = tuner.settle(_baseline_rows(), CPU_FP)
        assert verdict["knobs"]["dissem"]["source"] == "default"
        # the other rules still ran
        assert verdict["knobs"]["shard_devices"]["source"] == "evidence"

    def test_valid_domain_checks(self):
        assert tuner._valid(tuner.KNOBS["dissem"], "swar")
        assert not tuner._valid(tuner.KNOBS["dissem"], "florp")
        assert not tuner._valid(tuner.KNOBS["dissem"], 3)
        assert tuner._valid(tuner.KNOBS["hot_slots"], 8)
        assert not tuner._valid(tuner.KNOBS["hot_slots"], True)
        assert not tuner._valid(tuner.KNOBS["hot_slots"], "8")
        assert tuner._valid(tuner.KNOBS["device_store"], False)
        assert not tuner._valid(tuner.KNOBS["device_store"], 1)
        assert tuner._valid(tuner.KNOBS["lease_timeout_floor_s"], -1.0)


# -- persistence + resolution precedence -------------------------------------


class TestResolve:
    def _persist(self, fp=None, rows=None):
        # The REAL fingerprint for cpu x8 (conftest mesh): a fake jax
        # version would mismatch at resolve() and trigger a re-settle.
        verdict = tuner.settle(
            _baseline_rows() if rows is None else rows,
            fp or tuner.fingerprint("cpu", 8))
        path = tuner.save_verdict(verdict)
        assert path is not None
        return verdict, path

    def test_save_load_roundtrip(self, autotune_dir):
        verdict, path = self._persist()
        assert os.path.dirname(path) == str(autotune_dir)
        assert tuner.load_verdict("cpu") == verdict

    def test_flag_beats_verdict_beats_default(self, autotune_dir):
        self._persist()
        res = tuner.resolve(
            ["dissem", "shard_devices", "unroll"],
            {"dissem": "planes"},
            platform="cpu", device_count=8)
        assert res.rows["dissem"] == {
            "value": "planes", "source": "flag", "evidence": [],
            "reason": "explicit configuration"}
        # evidence-backed verdict row resolves as "verdict"
        assert res.rows["shard_devices"]["source"] == "verdict"
        assert res.rows["shard_devices"]["value"] == 1
        # default-restating verdict row reports "default"
        assert res.rows["unroll"]["source"] == "default"
        assert res.rows["unroll"]["value"] == tuner.KNOBS["unroll"].default
        assert res.meta["verdict_found"] is True

    def test_invalid_verdict_value_degrades_to_default(self, autotune_dir):
        verdict, path = self._persist()
        verdict["knobs"]["shard_devices"]["value"] = "four"
        with open(path, "wb") as f:
            f.write(tuner.verdict_bytes(verdict))
        res = tuner.resolve(["shard_devices"], {},
                            platform="cpu", device_count=8)
        assert res.rows["shard_devices"]["source"] == "default"
        assert res.rows["shard_devices"]["value"] == 1

    def test_corrupt_verdict_file_degrades_to_default(self, autotune_dir):
        path = tuner.verdict_path("cpu")
        with open(path, "w", encoding="utf-8") as f:
            f.write("{not json")
        res = tuner.resolve(["dissem"], {}, platform="cpu", device_count=8)
        assert res.rows["dissem"]["source"] == "default"
        assert res.meta["verdict_found"] is False
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"format": 999, "knobs": {}}, f)
        res = tuner.resolve(["dissem"], {}, platform="cpu", device_count=8)
        assert res.meta["verdict_found"] is False

    def test_kill_switch_ignores_verdict(self, autotune_dir, monkeypatch):
        self._persist()
        monkeypatch.setenv("CONSUL_TPU_AUTOTUNE", "0")
        res = tuner.resolve(["shard_devices"], {},
                            platform="cpu", device_count=8)
        assert res.rows["shard_devices"]["source"] == "default"
        assert res.rows["shard_devices"]["reason"] == "autotune disabled"
        assert res.meta["autotune_enabled"] is False
        # flags still win under the kill switch
        res = tuner.resolve(["shard_devices"], {"shard_devices": 2},
                            platform="cpu", device_count=8)
        assert res.rows["shard_devices"]["source"] == "flag"

    def test_fingerprint_change_resettles(self, autotune_dir, tmp_path):
        # persist a verdict for a DIFFERENT topology of the same
        # platform; resolving on this one must re-settle and re-persist
        fp_old = tuner.fingerprint("cpu", 2)
        self._persist(fp=fp_old)
        before = tuner.resettles()
        empty_root = tmp_path / "no-artifacts"
        empty_root.mkdir()
        res = tuner.resolve(["dissem"], {}, platform="cpu",
                            device_count=8, root=str(empty_root))
        assert tuner.resettles() == before + 1
        new = tuner.load_verdict("cpu")
        assert new["fingerprint"] == res.meta["fingerprint"]
        assert new["fingerprint"]["device_count"] == 8
        # no artifacts behind the re-settle -> all defaults
        assert res.rows["dissem"]["source"] == "default"

    def test_matching_fingerprint_does_not_resettle(self, autotune_dir):
        _, path = self._persist(fp=tuner.fingerprint("cpu", 8))
        before = tuner.resettles()
        res = tuner.resolve(["shard_devices"], {},
                            platform="cpu", device_count=8)
        assert tuner.resettles() == before
        assert res.rows["shard_devices"]["source"] == "verdict"

    def test_resolved_value_only_trusts_evidence(self, autotune_dir):
        self._persist(rows=[
            Evidence("watch.crossover_watches", 40000, "s", "", 1.0)])
        got = tuner.resolved_value("watch_device_min", default=12345,
                                   platform="cpu", device_count=8)
        assert got == 40000
        # default-restating verdict rows fall back to the caller's value
        assert tuner.resolved_value("unroll", default=7, platform="cpu",
                                    device_count=8) == 7


# -- prometheus families -----------------------------------------------------


class TestPromFamilies:
    def test_family_shape(self, autotune_dir):
        verdict = tuner.settle(_baseline_rows(), tuner.fingerprint("cpu", 8))
        tuner.save_verdict(verdict)
        res = tuner.resolve(list(tuner.KNOBS), {},
                            platform="cpu", device_count=8)
        gauges, counters = tuner.prom_families(res.wire(), now=200.0)
        by_name = {f["name"]: f for f in gauges + counters}
        assert set(by_name) == {
            "consul_autotune_knob_info", "consul_autotune_knob_value",
            "consul_autotune_evidence_age_seconds",
            "consul_autotune_resettles_total"}
        info = by_name["consul_autotune_knob_info"]["rows"]
        assert {labels["knob"] for labels, _ in info} == set(tuner.KNOBS)
        assert all(labels["source"] in ("flag", "verdict", "default")
                   for labels, _ in info)
        value_rows = dict(
            (labels["knob"], v) for labels, v in
            by_name["consul_autotune_knob_value"]["rows"])
        assert "dissem" not in value_rows      # string-valued: info only
        assert value_rows["device_store"] in (0.0, 1.0)
        assert value_rows["shard_devices"] == 1.0
        (_, age), = by_name["consul_autotune_evidence_age_seconds"]["rows"]
        assert age == pytest.approx(200.0 - verdict["evidence_epoch_unix"])

    def test_evidence_age_without_verdict(self):
        gauges, _ = tuner.prom_families({"knobs": {}}, now=50.0)
        by_name = {f["name"]: f for f in gauges}
        (_, age), = by_name["consul_autotune_evidence_age_seconds"]["rows"]
        assert age == -1.0

    def test_families_render_clean(self, autotune_dir):
        from consul_tpu.obs.prom import render_prometheus
        from tools.check_prom import check_text
        res = tuner.resolve(list(tuner.KNOBS), {},
                            platform="cpu", device_count=8)
        gauges, counters = tuner.prom_families(res.wire(), now=10.0)
        text = render_prometheus([], labeled_gauges=gauges,
                                 labeled_counters=counters)
        assert check_text(text) == []


# -- boot-with-verdict on the CPU mesh ---------------------------------------


class TestPlaneBoot:
    def _settle_for_this_backend(self):
        """A verdict whose fingerprint matches THIS process (the
        conftest 8-device CPU mesh), with evidence-backed dissem/shard
        rows that restate safe values."""
        fp = tuner.fingerprint()
        verdict = tuner.settle(_baseline_rows(), fp)
        assert verdict["knobs"]["dissem"]["source"] == "evidence"
        assert tuner.save_verdict(verdict) is not None
        return verdict

    @pytest.mark.timeout_s(120)
    def test_plane_boots_with_verdict_sources(self, autotune_dir):
        from consul_tpu.gossip.plane import GossipPlane, PlaneConfig
        self._settle_for_this_backend()

        async def body():
            plane = GossipPlane(PlaneConfig(
                bind_port=0, capacity=16, slots=16,
                gossip_interval_s=0.02, suspicion_mult=1.0,
                hb_lapse_s=0.3))
            await plane.start()
            try:
                rows = plane._autotune.rows
                assert rows["dissem"]["source"] == "verdict"
                assert rows["dissem"]["value"] == "swar"
                assert rows["shard_devices"]["source"] == "verdict"
                assert plane._p.dissem == "swar"
                assert plane._ndev == 1
                # knobs without evidence rode the registry defaults
                assert rows["unroll"]["source"] == "default"
                assert plane._unroll == tuner.KNOBS["unroll"].default
                frame = plane._autotune_wire()
                assert frame["t"] == "autotune"
                assert frame["verdict_found"] is True
            finally:
                await plane.stop()

        asyncio.run(body())

    @pytest.mark.timeout_s(120)
    def test_explicit_config_beats_verdict(self, autotune_dir):
        from consul_tpu.gossip.plane import GossipPlane, PlaneConfig
        self._settle_for_this_backend()

        async def body():
            plane = GossipPlane(PlaneConfig(
                bind_port=0, capacity=16, slots=16,
                gossip_interval_s=0.02, suspicion_mult=1.0,
                hb_lapse_s=0.3, dissem="planes"))
            await plane.start()
            try:
                row = plane._autotune.rows["dissem"]
                assert row["source"] == "flag"
                assert plane._p.dissem == "planes"
            finally:
                await plane.stop()

        asyncio.run(body())
