"""Leader-lease safety and the zero-RPC consistent-read fast path.

The lease argument (Raft §6.4 / leases as in "Scaling Strongly
Consistent Replication"): a quorum of followers processed an
AppendEntries round the leader SENT at time t, so none of them starts
an election before t + election_timeout_min; the effective lease
min(lease_timeout, election_timeout_min) * (1 - clock_skew) expires
strictly earlier.  These tests pin the safety edges:

  * a lease-holding leader serves a consistent read with ZERO
    barrier/ReadIndex RPCs (the ISSUE acceptance test);
  * lease expiry (stopped heartbeats, partition) falls back to the
    coalesced barrier path — never an unprotected local read;
  * a deposed leader that still THINKS it leads cannot serve a stale
    consistent read: its lease dies with the role, and any same-term
    survivor window is shorter than the minimum election timeout;
  * the effective window is clamped and skew-discounted;
  * single-node clusters are always freshly anchored (leases are pure
    win there).
"""

from __future__ import annotations

import asyncio
import time

import pytest

from consul_tpu.consensus.raft import (
    LEADER, MemoryTransport, RaftConfig, RaftNode)
from consul_tpu.server.server import Server, ServerConfig
from consul_tpu.structs.structs import DirEntry, KVSOp, KVSRequest


def fast_raft(**kw) -> RaftConfig:
    base = dict(heartbeat_interval=0.02, election_timeout_min=0.1,
                election_timeout_max=0.2, rpc_timeout=0.05)
    base.update(kw)
    return RaftConfig(**base)


def make_servers(n, **raft_kw):
    tr = MemoryTransport()
    names = [f"s{i}" for i in range(n)]
    servers = [Server(ServerConfig(node_name=name, peers=names,
                                   raft=fast_raft(**raft_kw)), transport=tr)
               for name in names]
    return tr, servers


async def start_and_elect(servers):
    for s in servers:
        await s.start()
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline:
        leaders = [s for s in servers if s.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        await asyncio.sleep(0.01)
    raise AssertionError("no leader")


async def stop_all(servers):
    for s in servers:
        await s.stop()


async def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timeout: {msg}")


async def wait_for_lease(srv, timeout=5.0):
    await wait_until(lambda: srv.raft.lease_valid(), timeout=timeout,
                     msg="leader lease")


def run(coro):
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class BarrierSpy:
    """Counts every leadership-proof RPC avenue a consistent read could
    take: barrier commits, AppendEntries sends, and leader-forwarded
    ReadIndex calls."""

    def __init__(self, srv):
        self.srv = srv
        self.barriers = 0
        self.transport_calls = 0
        self.forwards = 0
        self._orig_barrier = srv.raft.barrier
        self._orig_call = srv.raft.transport.call
        self._orig_fwd = srv.forward_leader

    def install(self):
        async def barrier(*a, **kw):
            self.barriers += 1
            return await self._orig_barrier(*a, **kw)

        async def call(src, dst, method, msg):
            if src == self.srv.raft.id:
                self.transport_calls += 1
            return await self._orig_call(src, dst, method, msg)

        async def fwd(*a, **kw):
            self.forwards += 1
            return await self._orig_fwd(*a, **kw)

        self.srv.raft.barrier = barrier
        self.srv.raft.transport.call = call
        self.srv.forward_leader = fwd
        return self

    def uninstall(self):
        self.srv.raft.barrier = self._orig_barrier
        self.srv.raft.transport.call = self._orig_call
        self.srv.forward_leader = self._orig_fwd


class TestLeaseFastPath:
    def test_consistent_read_zero_rpcs_under_lease(self):
        """THE acceptance test: consistent read on a lease-holding
        leader performs no barrier and no ReadIndex RPC — only the
        background heartbeat traffic continues."""
        async def main():
            _, servers = make_servers(3)
            leader = await start_and_elect(servers)
            await leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="k", value=b"v")))
            await wait_for_lease(leader)
            spy = BarrierSpy(leader).install()
            try:
                # Heartbeats race through transport.call concurrently;
                # distinguish read-path RPCs by running the read with
                # the event loop otherwise idle: the read must finish
                # without yielding to a replication round it caused.
                before = spy.barriers
                idx = await leader._leader_confirm()
                assert spy.barriers == before == 0, \
                    "lease-holding leader ran a barrier commit"
                assert idx == leader.raft.commit_index
                # Full endpoint path: the read itself (not the prologue)
                await leader.consistent_read_barrier()
                assert spy.barriers == 0
                assert spy.forwards == 0
                _, ent = leader.store.kvs_get("k")
                assert ent is not None and bytes(ent.value) == b"v"
            finally:
                spy.uninstall()
                await stop_all(servers)
        run(main())

    def test_lease_metrics_counters(self):
        """Lease-served and barrier-served reads are separately
        countable (consul.read.lease / consul.read.barrier)."""
        async def main():
            from consul_tpu.utils.telemetry import metrics
            _, servers = make_servers(3)
            leader = await start_and_elect(servers)
            await wait_for_lease(leader)
            base = _counter_sum(metrics, "read.lease")
            await leader.consistent_read_barrier()
            assert _counter_sum(metrics, "read.lease") == base + 1
            await stop_all(servers)
        run(main())

    def test_single_node_lease_always_anchored(self):
        async def main():
            srv = Server(ServerConfig(node_name="solo",
                                      raft=fast_raft()))
            await srv.start()
            await srv.wait_for_leader()
            await wait_for_lease(srv)
            spy = BarrierSpy(srv).install()
            try:
                await srv.consistent_read_barrier()
                assert spy.barriers == 0
                assert spy.transport_calls == 0
            finally:
                spy.uninstall()
                await srv.stop()
        run(main())

    def test_follower_readindex_rides_leader_lease(self):
        """_ri_leader_runner short-circuits to commit_index under the
        lease: the follower ReadIndex costs one forward RPC and no
        barrier commit."""
        async def main():
            _, servers = make_servers(3)
            leader = await start_and_elect(servers)
            await wait_for_lease(leader)
            spy = BarrierSpy(leader).install()
            try:
                idx = await leader._ri_leader_runner()
                assert idx == leader.raft.commit_index
                assert spy.barriers == 0
            finally:
                spy.uninstall()
                await stop_all(servers)
        run(main())


class TestLeaseFallback:
    def test_expired_lease_falls_back_to_barrier(self):
        """Cut the leader off from its followers: once the lease
        window lapses, lease_read_index is None and a consistent read
        attempts the barrier path (which can no longer succeed against
        a lost quorum — it must NOT serve locally)."""
        async def main():
            tr, servers = make_servers(3)
            leader = await start_and_elect(servers)
            await wait_for_lease(leader)
            tr.isolate(leader.raft.id)
            dur = leader.raft._lease_duration()
            await asyncio.sleep(dur + 0.05)
            assert not leader.raft.lease_valid()
            assert leader.raft.lease_read_index() is None
            spy = BarrierSpy(leader).install()
            try:
                with pytest.raises(asyncio.TimeoutError):
                    await asyncio.wait_for(leader._leader_confirm(),
                                           timeout=0.5)
                assert spy.barriers == 1, "expiry must take the barrier path"
            finally:
                spy.uninstall()
                await stop_all(servers)
        run(main())

    def test_stepdown_invalidates_lease(self):
        """A deposed leader's lease dies WITH the role (not just by
        timeout): _stop_leading clears the ack table, so even within
        the old window lease_valid() is False."""
        async def main():
            tr, servers = make_servers(3)
            leader = await start_and_elect(servers)
            await wait_for_lease(leader)
            tr.isolate(leader.raft.id)
            others = [s for s in servers if s is not leader]
            await wait_until(lambda: any(s.is_leader() for s in others),
                             msg="new leader elected")
            tr.rejoin(leader.raft.id)
            await wait_until(lambda: not leader.is_leader(),
                             msg="old leader stepped down")
            assert not leader.raft.lease_valid()
            assert leader.raft._lease_ack == {}
            # ...and the fast path refuses it even if role flaps back:
            assert leader.raft.lease_read_index() is None
            await stop_all(servers)
        run(main())

    def test_deposed_leader_never_serves_stale_consistent_read(self):
        """The money property: partition the leader, elect a new one,
        write through the new leader — the OLD leader (still in LEADER
        role, unaware) must not serve a consistent read that misses the
        new write.  Its lease expired before the new election could
        finish, so the fast path is closed and the barrier path cannot
        commit against a lost quorum."""
        async def main():
            tr, servers = make_servers(3)
            leader = await start_and_elect(servers)
            await leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="x", value=b"old")))
            await wait_for_lease(leader)
            tr.isolate(leader.raft.id)
            others = [s for s in servers if s is not leader]
            await wait_until(lambda: any(s.is_leader() for s in others),
                             msg="new leader")
            new_leader = next(s for s in others if s.is_leader())
            await new_leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="x", value=b"new")))
            # Old leader may still be in LEADER role behind the wall.
            if leader.raft.role == LEADER:
                # Lease safety: by the time ANY new leader exists, the
                # old lease has expired (the clock-skew margin is what
                # guarantees the strict ordering).
                assert not leader.raft.lease_valid()
                with pytest.raises(Exception):
                    await asyncio.wait_for(leader.consistent_read_barrier(),
                                           timeout=0.5)
            await stop_all(servers)
        run(main())


class TestLeaseWindow:
    def test_duration_clamped_and_skew_discounted(self):
        node = RaftNode("n", ["n"], fsm=None, transport=MemoryTransport(),
                        config=fast_raft(lease_timeout=10.0,
                                         lease_clock_skew=0.15))
        # 10s config clamps to election_timeout_min (0.1) then takes
        # the 15% skew discount.
        assert node._lease_duration() == pytest.approx(0.1 * 0.85)

    def test_negative_timeout_disables(self):
        node = RaftNode("n", ["n"], fsm=None, transport=MemoryTransport(),
                        config=fast_raft(lease_timeout=-1.0))
        assert node._lease_duration() == 0.0
        assert not node.lease_valid()

    def test_anchor_is_quorum_th_most_recent(self):
        node = RaftNode("a", ["a", "b", "c", "d", "e"], fsm=None,
                        transport=MemoryTransport(), config=fast_raft())
        node.role = LEADER
        now = time.monotonic()
        # quorum of 5 = 3; self implicit, need 2 follower acks.
        node._lease_ack = {"b": now - 0.01, "c": now - 0.05,
                           "d": now - 0.50}
        # 2nd most recent follower ack anchors the lease.
        assert node._lease_anchor() == pytest.approx(now - 0.05)

    def test_insufficient_acks_no_anchor(self):
        node = RaftNode("a", ["a", "b", "c"], fsm=None,
                        transport=MemoryTransport(), config=fast_raft())
        node.role = LEADER
        assert node._lease_anchor() == 0.0
        assert not node.lease_valid()

    def test_fresh_leader_guard_blocks_until_own_term_commit(self):
        """Raft §6.4 precondition: before the no-op of its own term
        commits, a fresh leader's commit_index may lag — the lease may
        not serve reads even with fresh acks."""
        node = RaftNode("a", ["a", "b", "c"], fsm=None,
                        transport=MemoryTransport(), config=fast_raft())
        node.role = LEADER
        now = time.monotonic()
        node._lease_ack = {"b": now, "c": now}
        node._lease_guard_index = 7
        node.commit_index = 6
        assert not node.lease_valid()
        node.commit_index = 7
        assert node.lease_valid()

    def test_lease_in_stats(self):
        async def main():
            _, servers = make_servers(3)
            leader = await start_and_elect(servers)
            await wait_for_lease(leader)
            st = leader.raft.stats()
            assert st["lease"] == "valid"
            assert int(st["lease_remaining_ms"]) >= 0
            follower = next(s for s in servers if not s.is_leader())
            assert follower.raft.stats()["lease"] == "invalid"
            ls = leader.lease_state()
            assert ls["valid"] and ls["is_leader"]
            assert ls["read_index"] == leader.raft.commit_index
            await stop_all(servers)
        run(main())


def _counter_sum(metrics, suffix: str) -> float:
    total = 0.0
    for iv in metrics.snapshot():
        for k, c in iv.get("Counters", {}).items():
            if k.endswith(suffix):
                total += c["sum"]
    return total


class TestLeaseClockSkewBounds:
    """lease_clock_skew edge cases under an injected virtual oscillator.

    The skew discount buys the leader a budget of
    eto_min * lease_clock_skew seconds of clock error: with W =
    eto_min * (1 - skew), a backward step (or a slow rate down to
    1 - skew) still has the leader drop its lease before any follower
    can possibly start an election at anchor + eto_min.  These tests
    pin the acceptance flip EXACTLY at that bound, in both directions,
    with a chaos FaultClock on a hand-driven time base.
    """

    @staticmethod
    def _skewed_node(t):
        from consul_tpu.chaos.broker import FaultBroker, FaultClock
        broker = FaultBroker(seed=0)
        nf = broker.node("a")
        nf.clock = FaultClock(base=lambda: t[0])
        node = RaftNode("a", ["a", "b", "c"], fsm=None,
                        transport=MemoryTransport(), config=fast_raft(),
                        faults=nf)
        node.role = LEADER
        node.commit_index = node._lease_guard_index = 0
        return node, nf.clock

    @staticmethod
    def _anchor(node, clock):
        a = clock.monotonic()
        node._lease_ack = {"b": a, "c": a}
        return a

    def test_flip_exactly_at_window_edge(self):
        t = [1000.0]
        node, clock = self._skewed_node(t)
        self._anchor(node, clock)
        w = node._lease_duration()
        assert w == pytest.approx(0.1 * 0.85)
        t[0] = 1000.0 + w - 1e-6
        assert node.lease_valid()
        assert node.lease_remaining() == pytest.approx(1e-6, abs=1e-7)
        t[0] = 1000.0 + w          # now < anchor + dur is strict
        assert not node.lease_valid()
        assert node.lease_remaining() == 0.0

    def test_backward_jump_inside_budget_keeps_invariant(self):
        # Budget = eto_min - W = eto_min * skew = 15ms.  A backward
        # step strictly inside it: at the earliest possible follower
        # election (real anchor + eto_min) the leader has ALREADY
        # dropped its lease.
        t = [1000.0]
        node, clock = self._skewed_node(t)
        self._anchor(node, clock)
        w = node._lease_duration()
        budget = node.config.election_timeout_min - w
        clock.jump(-(budget - 0.001))
        t[0] = 1000.0 + node.config.election_timeout_min
        assert not node.lease_valid()

    def test_backward_jump_beyond_budget_breaks_invariant(self):
        # Just past the budget the lease OUTLIVES the election floor —
        # the bound is tight, which is exactly why the campaign's
        # clock faults stay on the safe side of it.
        t = [1000.0]
        node, clock = self._skewed_node(t)
        self._anchor(node, clock)
        w = node._lease_duration()
        budget = node.config.election_timeout_min - w
        clock.jump(-(budget + 0.001))
        t[0] = 1000.0 + node.config.election_timeout_min
        assert node.lease_valid()  # stale claim: the unsafe direction

    def test_forward_jump_only_expires_early(self):
        t = [1000.0]
        node, clock = self._skewed_node(t)
        self._anchor(node, clock)
        clock.jump(0.2)            # bigger than the whole window
        assert not node.lease_valid()

    def test_slow_rate_acceptance_flips_at_one_minus_skew(self):
        # Sustained slow oscillator: safe iff rate > 1 - skew = 0.85
        # (virtual W elapses within real eto_min).  Check both sides
        # of the flip at real time anchor + eto_min.
        for rate, still_claims in ((0.84, True), (0.86, False)):
            t = [1000.0]
            node, clock = self._skewed_node(t)
            self._anchor(node, clock)
            clock.set_rate(rate)
            t[0] = 1000.0 + node.config.election_timeout_min
            assert node.lease_valid() is still_claims, (
                f"rate {rate}: lease_valid should be {still_claims}")
