"""Cross-validation: TPU kernel vs discrete-event memberlist-semantics model.

BASELINE.md config 2: the kernel's detection-time distribution must track
the reference model's (which faithfully implements per-node SWIM/Lifeguard
semantics).  These tests gate on the SAME statistics the published
CROSSVAL.json artifact reports (consul_tpu.gossip.crossval.run_config):
p99 relative latency error and detection completeness — the round-3
lesson was that a loose mean-ratio check in-suite let an 87% detection
loss and a p99 drift ship invisibly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.gossip.crossval import (kernel_event_latencies,
                                        loss_sized_slots, run_config)
from consul_tpu.gossip.kernel import NEVER, init_state, run_rounds
from consul_tpu.gossip.params import SwimParams
from consul_tpu.gossip.refmodel import RefModel


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_detection_latency_tracks_reference():
    """CI-sized version of the CROSSVAL.json lossless config: n=1k,
    2 seeds.  Gates: p99 relative error <= 15%, completeness >= 95%
    (lossless detection must be essentially total), both models inside
    the Lifeguard envelope.  Tool-run evidence at full seed count:
    p99 err 2-6% at 1k/10k (CROSSVAL.json)."""
    out = run_config(n=1000, n_victims=8, seeds=2)
    assert out["completeness"]["kernel"] >= 0.95, out["completeness"]
    assert out["completeness"]["refmodel"] >= 0.95, out["completeness"]
    assert out["relative_error"]["p99"] is not None
    assert out["relative_error"]["p99"] <= 0.15, out["relative_error"]
    assert out["relative_error"]["p50"] <= 0.15, out["relative_error"]
    # Both models must sit within the Lifeguard envelope: fail -> first
    # probe window + suspicion timeout in [min, max].
    lo, hi = out["lifeguard_envelope_rounds"]
    for model in ("kernel", "refmodel"):
        mean = out["detection_latency_rounds"][model]["mean"]
        assert lo * 0.8 < mean < hi + 30, (model, mean, lo, hi)


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_loss_regime_detection_completeness():
    """Round-3 regression (CROSSVAL config 3): at 25% loss the kernel
    detected 2/16 injected failures — spurious refuted episodes held
    their slots for the full TTL and starved the table.  With verdict-
    based refuted-slot GC + loss-sized provisioning, completeness must
    stay >= 90% inside the Lifeguard envelope.  Kernel-only (the oracle
    needs no slots, and its lossy runs cost minutes)."""
    n, loss = 500, 0.25
    slots = loss_sized_slots(n, loss)
    p = SwimParams(n=n, slots=slots, probe_every=5, loss_rate=loss)
    first_fail = 30
    spacing = 10
    n_victims = 8
    fail_at = {(n // (n_victims + 1)) * (i + 1): first_fail + i * spacing
               for i in range(n_victims)}
    steps = (first_fail + n_victims * spacing + p.suspicion_max_rounds
             + 2 * p.spread_budget_rounds + 8 * p.probe_every)
    detected = 0
    expected = 0
    for seed in (0, 1):
        lats, _fp, _ref, drops = kernel_event_latencies(p, fail_at, steps,
                                                        seed=seed)
        detected += len(lats)
        expected += len(fail_at)
    completeness = detected / expected
    assert completeness >= 0.9, (
        f"loss-regime completeness {completeness:.2f} ({detected}/{expected})"
        f" — slot starvation is back? slots={slots}")


@pytest.mark.slow
def test_false_positive_behavior_under_loss():
    p = SwimParams(n=128, slots=32, probe_every=5, loss_rate=0.25)
    fail = np.full(p.n, NEVER, np.int32)
    st, _ = run_rounds(init_state(p), jax.random.key(5), jnp.asarray(fail), p, 500)
    m = RefModel(p, {}, seed=5)
    m.run(500)
    # Both models must refute aggressively and produce ~no false deaths.
    assert int(st.n_refuted) > 0 and m.n_refuted > 0
    assert int(st.n_false_dead) <= 2
    assert m.n_false_dead <= 2


@pytest.mark.slow
def test_refmodel_dissemination_completes():
    p = SwimParams(n=128, slots=16, probe_every=5)
    victim = 7
    m = RefModel(p, {victim: 20}, seed=3)
    m.run(20 + p.slot_ttl_rounds + 40)
    assert len(m.events) == 1
    curve = m.dissemination[victim]
    peak = max(k for _, k in curve)
    assert peak >= 0.9 * (p.n - 1)


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_event_convergence_tracks_oracle():
    """BASELINE config #3: event convergence statistics must track
    stock gossip.  The kernel floods over per-round circulant shifts;
    the oracle pushes to iid uniform targets (memberlist's actual
    behavior).  Gates: every flood completes, and rounds-to-50%/99%
    stay within 15% of the oracle — as tight as the detection-side
    gates (measured: 0% at 1k, ~11% at 10k — the exact-in-degree
    circulant graph runs one round AHEAD of Poisson at the tail)."""
    from consul_tpu.gossip.crossval import run_event_config
    out = run_event_config(n=1024, seeds=3)
    assert out["completed"]["kernel"] == 3, out
    assert out["completed"]["oracle"] == 3, out
    assert out["rounds_to_50pct"]["relative_error"] <= 0.15, out
    assert out["rounds_to_99pct"]["relative_error"] <= 0.15, out


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_join_churn_tracks_oracle():
    """Concurrent joins + failures (gossip.html.markdown:10-43: joins
    propagate as gossiped alive messages).  Gates: the SAME detection
    gates as the static configs (p99 err <= 15%, completeness >= 0.95,
    no false deads) with join churn running concurrently, plus the
    join announcement's propagation latency within 15% of the oracle
    and every join covered in both models."""
    from consul_tpu.gossip.crossval import run_join_config
    out = run_join_config(n=1000, n_joiners=8, n_victims=8, seeds=2)
    assert out["completeness"]["kernel"] >= 0.95, out
    assert out["completeness"]["refmodel"] >= 0.95, out
    assert out["relative_error"]["p99"] is not None
    assert out["relative_error"]["p99"] <= 0.15, out["relative_error"]
    assert out["false_dead"]["kernel"] == 0, out
    js = out["join_spread_rounds_to_95pct"]
    assert js["completed"]["kernel"] == js["completed"]["expected"], js
    assert js["completed"]["refmodel"] == js["completed"]["expected"], js
    assert js["relative_error"] <= 0.15, js


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_pushpull_loss_regime_tracks_oracle():
    """25%-loss with push/pull anti-entropy armed in BOTH models
    (memberlist PushPullInterval / kernel _maybe_pushpull): exactly the
    regime where anti-entropy matters — rumors whose retransmit budget
    expires under loss are recovered by the periodic full sync.  Gates:
    completeness >= 0.95 both models, p99 err <= 15%, kernel declares
    no false deads (its refutation is globally instantaneous — the
    documented bias is toward FEWER false positives than the oracle).
    CI-sized (n=400, 1 seed — the lossy oracle costs minutes); the
    published artifact runs the full n=500 config
    (tools/crossval_report.py)."""
    out = run_config(400, 4, 1, loss=0.25, pushpull=True)
    assert out["completeness"]["kernel"] >= 0.95, out["completeness"]
    assert out["completeness"]["refmodel"] >= 0.95, out["completeness"]
    assert out["relative_error"]["p99"] is not None
    assert out["relative_error"]["p99"] <= 0.15, out["relative_error"]
    assert out["false_dead"]["kernel"] == 0, out["false_dead"]


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_lifeguard_envelope_at_scale_with_pushpull():
    """BASELINE table row 4 (CI-sized): Lifeguard + push/pull at scale,
    kernel-only, gated on the row's own published criterion — detection
    p99 inside the Lifeguard envelope, full completeness, no false
    deads.  The artifact runs the full 100k config
    (tools/crossval_report.py); 20k keeps this under a minute."""
    out = run_config(20_000, 8, 1, pushpull=True, oracle=False)
    assert out["completeness"]["kernel"] == 1.0, out["completeness"]
    lo, hi = out["lifeguard_envelope_rounds"]
    p99 = out["detection_latency_rounds"]["kernel"]["p99"]
    assert lo * 0.8 <= p99 <= hi, (p99, lo, hi)
    assert out["false_dead"]["kernel"] == 0
    assert out["kernel_slot_drops"] == 0


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_nemesis_partition_heal_tracks_oracle():
    """Nemesis catalog (gossip/nemesis.py): full bisection rounds
    [40, 160), then heal.  Both models must manufacture false dead
    verdicts during the partition — each half declaring the other dead
    IS the fault being modeled — and must fully recover membership
    through the heal-rejoin path.  Tool-run evidence (n=256, 2 seeds):
    false_dead 256/256, member_frac_end 1.0/1.0."""
    from consul_tpu.gossip.crossval import run_nemesis_config
    out = run_nemesis_config("partition_heal", 256, seeds=2)
    assert out["false_dead"]["kernel"] > 0, out["false_dead"]
    assert out["false_dead"]["refmodel"] > 0, out["false_dead"]
    assert out["member_frac_end"]["kernel"] >= 0.95, out["member_frac_end"]
    assert out["member_frac_end"]["refmodel"] >= 0.95, out["member_frac_end"]
    assert out["kernel_slot_drops"] == 0


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_nemesis_flapping_tracks_oracle():
    """Nemesis catalog: flapping ids through [30, 310), down phases
    sized past the Lifeguard suspicion timeout.  Gates: both models
    detect every flap victim (completeness), every victim rejoins
    through the join tick by the end (membership recovery), and the
    detection-latency medians track.  Tool-run evidence (n=256,
    2 seeds): completeness 1.0/1.0, p50 50 vs 51.5."""
    from consul_tpu.gossip.crossval import run_nemesis_config
    out = run_nemesis_config("flapping", 256, seeds=2)
    assert out["completeness"]["kernel"] >= 0.9, out["completeness"]
    assert out["completeness"]["refmodel"] >= 0.9, out["completeness"]
    assert out["member_frac_end"]["kernel"] >= 0.95, out["member_frac_end"]
    assert out["member_frac_end"]["refmodel"] >= 0.95, out["member_frac_end"]
    assert out["relative_error"]["p50"] is not None
    assert out["relative_error"]["p50"] <= 0.25, out["relative_error"]
