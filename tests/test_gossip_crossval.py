"""Cross-validation: TPU kernel vs discrete-event memberlist-semantics model.

BASELINE.md config 2: the kernel's detection-time distribution must track
the reference model's (which faithfully implements per-node SWIM/Lifeguard
semantics).  These tests quantify the kernel's documented approximations
(permutation gossip, episode-start timers, receipt-based confirmations).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.gossip.kernel import NEVER, init_state, run_rounds
from consul_tpu.gossip.params import SwimParams
from consul_tpu.gossip.refmodel import RefModel


def kernel_latencies(p, fail_at, n_seeds):
    """Mean detection latency (rounds) per seed for one injected failure."""
    out = []
    fail = np.full(p.n, NEVER, np.int32)
    victim = p.n // 3
    fail[victim] = fail_at
    steps = fail_at + p.slot_ttl_rounds + 8 * p.probe_every
    for s in range(n_seeds):
        st, _ = run_rounds(init_state(p), jax.random.key(s), jnp.asarray(fail), p, steps)
        det = int(st.n_detected)
        assert det == 1, f"kernel seed {s}: detected {det} != 1"
        out.append(int(st.sum_detect_rounds) / det)
    return np.asarray(out)


def refmodel_latencies(p, fail_at, n_seeds):
    out = []
    victim = p.n // 3
    steps = fail_at + p.slot_ttl_rounds + 8 * p.probe_every
    for s in range(n_seeds):
        m = RefModel(p, {victim: fail_at}, seed=1000 + s)
        m.run(steps)
        lats = m.detection_latencies()
        assert len(lats) == 1, f"refmodel seed {s}: detected {len(lats)} != 1"
        out.append(lats[0])
    return np.asarray(out)


@pytest.mark.slow
def test_detection_latency_tracks_reference():
    p = SwimParams(n=192, slots=16, probe_every=5)
    fail_at = 25
    k = kernel_latencies(p, fail_at, 12)
    r = refmodel_latencies(p, fail_at, 12)
    ratio = k.mean() / r.mean()
    # Observed calibration: ~0.91 (kernel slightly fast — episode-start
    # timers fire earlier for late hearers; permutation gossip spreads
    # slightly faster than Poisson push).  Alert if drift exceeds ±30%.
    assert 0.7 < ratio < 1.3, f"kernel {k.mean():.1f} vs ref {r.mean():.1f} rounds"
    # Both must sit within the Lifeguard envelope: fail -> first probe
    # window + suspicion timeout in [min, max].
    for lat in (k.mean(), r.mean()):
        assert p.suspicion_min_rounds * 0.8 < lat < p.suspicion_max_rounds + 6 * p.probe_every


@pytest.mark.slow
def test_false_positive_behavior_under_loss():
    p = SwimParams(n=128, slots=32, probe_every=5, loss_rate=0.25)
    fail = np.full(p.n, NEVER, np.int32)
    st, _ = run_rounds(init_state(p), jax.random.key(5), jnp.asarray(fail), p, 500)
    m = RefModel(p, {}, seed=5)
    m.run(500)
    # Both models must refute aggressively and produce ~no false deaths.
    assert int(st.n_refuted) > 0 and m.n_refuted > 0
    assert int(st.n_false_dead) <= 2
    assert m.n_false_dead <= 2


@pytest.mark.slow
def test_refmodel_dissemination_completes():
    p = SwimParams(n=128, slots=16, probe_every=5)
    victim = 7
    m = RefModel(p, {victim: 20}, seed=3)
    m.run(20 + p.slot_ttl_rounds + 40)
    assert len(m.events) == 1
    curve = m.dissemination[victim]
    peak = max(k for _, k in curve)
    assert peak >= 0.9 * (p.n - 1)
