"""Jepsen-role tier: partitions + concurrent clients + linearizability.

The reference's partition-tolerance claims are backed by an external
Jepsen suite (``website/source/docs/internals/jepsen.html.markdown``:
CP for consistent reads, writes linearized through Raft).  This tier
reproduces that posture in-process: a 3-server cluster on the
partition-injecting MemoryTransport, a nemesis that repeatedly cuts the
leader away and heals, concurrent clients doing unique-value writes and
``require_consistent`` reads of one register key, and a Wing&Gong-style
checker (tests/linearize.py) over the recorded history.
"""

from __future__ import annotations

import asyncio
import math
import random


from consul_tpu.structs.structs import DirEntry, KVSOp, KVSRequest, KeyRequest

from linearize import check_linearizable
from test_server_cluster import make_servers, start_and_elect, stop_all

# ---------------------------------------------------------------------------
# Checker self-tests: known-good and known-bad histories.
# ---------------------------------------------------------------------------


def _h(op, arg=None, ret=None, t0=0.0, t1=1.0, ok=True):
    return {"op": op, "arg": arg, "ret": ret, "t_inv": t0, "t_ret": t1,
            "ok": ok}


def test_sequential_history_ok():
    hist = [
        _h("w", 1, t0=0, t1=1),
        _h("r", ret=1, t0=2, t1=3),
        _h("w", 2, t0=4, t1=5),
        _h("r", ret=2, t0=6, t1=7),
    ]
    assert check_linearizable(hist)


def test_stale_read_rejected():
    # Read of 1 strictly after w(2) completed: not linearizable.
    hist = [
        _h("w", 1, t0=0, t1=1),
        _h("w", 2, t0=2, t1=3),
        _h("r", ret=1, t0=4, t1=5),
    ]
    assert not check_linearizable(hist)


def test_concurrent_read_may_see_either():
    # r overlaps w(2): may return old or new value.
    base = [_h("w", 1, t0=0, t1=1), _h("w", 2, t0=2, t1=6)]
    assert check_linearizable(base + [_h("r", ret=1, t0=3, t1=4)])
    assert check_linearizable(base + [_h("r", ret=2, t0=3, t1=4)])
    assert not check_linearizable(base + [_h("r", ret=7, t0=3, t1=4)])


def test_lost_write_rejected():
    # w(2) completed, but a later read still sees 1 and an even later
    # read sees 2 — the 1-read is a linearizability violation.
    hist = [
        _h("w", 1, t0=0, t1=1),
        _h("w", 2, t0=2, t1=3),
        _h("r", ret=1, t0=4, t1=5),
        _h("r", ret=2, t0=6, t1=7),
    ]
    assert not check_linearizable(hist)


def test_unknown_write_may_apply_late():
    # w(2) timed out (unknown): a much later read may legally see it.
    hist = [
        _h("w", 1, t0=0, t1=1),
        _h("w", 2, t0=2, t1=3, ok=False),
        _h("r", ret=1, t0=4, t1=5),
        _h("r", ret=2, t0=6, t1=7),
    ]
    assert check_linearizable(hist)


def test_unknown_write_may_never_apply():
    hist = [
        _h("w", 1, t0=0, t1=1),
        _h("w", 2, t0=2, t1=3, ok=False),
        _h("r", ret=1, t0=4, t1=5),
        _h("r", ret=1, t0=6, t1=7),
    ]
    assert check_linearizable(hist)


def test_value_from_nowhere_rejected():
    hist = [
        _h("w", 1, t0=0, t1=1),
        _h("r", ret=9, t0=2, t1=3),
    ]
    assert not check_linearizable(hist)


def test_big_history_path():
    # >63 ops exercises the frozenset fallback.
    hist = []
    t = 0.0
    for v in range(40):
        hist.append(_h("w", v, t0=t, t1=t + 1)); t += 2
        hist.append(_h("r", ret=v, t0=t, t1=t + 1)); t += 2
    assert check_linearizable(hist)
    hist.append(_h("r", ret=0, t0=t, t1=t + 1))
    assert not check_linearizable(hist)


# ---------------------------------------------------------------------------
# Live tier: 3 servers, nemesis partitions, concurrent register clients.
# ---------------------------------------------------------------------------

KEY = "jepsen/register"


async def _client(cid, servers, clock, history, n_ops, rng):
    for seq in range(n_ops):
        val = cid * 10_000 + seq
        do_write = rng.random() < 0.5
        t_inv = clock()
        ok = False
        ret = None
        try:
            if do_write:
                await asyncio.wait_for(
                    _write_any(servers, val, rng), timeout=2.0)
                ok = True
            else:
                ret = await asyncio.wait_for(
                    _read_any(servers, rng), timeout=2.0)
                ok = True
        except Exception:
            ok = False
        history.append({
            "op": "w" if do_write else "r",
            "arg": val if do_write else None,
            "ret": ret,
            "t_inv": t_inv,
            "t_ret": clock() if ok else math.inf,
            "ok": ok,
        })
        await asyncio.sleep(rng.uniform(0.0, 0.03))


async def _write_any(servers, val, rng):
    last = None
    for s in rng.sample(servers, len(servers)):
        try:
            await s.kvs.apply(KVSRequest(
                datacenter="dc1", op=KVSOp.SET.value,
                dir_ent=DirEntry(key=KEY, value=str(val).encode())))
            return
        except Exception as e:  # not leader / partitioned: try next
            last = e
            await asyncio.sleep(0.02)
    raise last


async def _read_any(servers, rng):
    last = None
    for s in rng.sample(servers, len(servers)):
        try:
            _, out = await s.kvs.get(KeyRequest(
                datacenter="dc1", key=KEY, require_consistent=True))
            if not out:
                return None
            return int(out[0].value.decode())
        except Exception as e:
            last = e
            await asyncio.sleep(0.02)
    raise last


async def _nemesis(tr, servers, stop_evt, rng):
    """Repeatedly cut the current leader off from the majority, wait for
    a new election + traffic under the partition, then heal."""
    while not stop_evt.is_set():
        await asyncio.sleep(rng.uniform(0.3, 0.6))
        leaders = [s for s in servers if s.is_leader()]
        if not leaders:
            continue
        victim = leaders[0].config.node_name
        tr.isolate(victim)
        await asyncio.sleep(rng.uniform(0.4, 0.8))
        tr.rejoin(victim)


def test_register_linearizable_under_partitions():
    asyncio.run(_run_partition_scenario())


async def _run_partition_scenario():
    rng = random.Random(11)
    tr, servers = make_servers(3)
    await start_and_elect(servers)
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    clock = lambda: loop.time() - t0

    history = []
    stop_evt = asyncio.Event()
    nem = asyncio.create_task(_nemesis(tr, servers, stop_evt, rng))
    clients = [asyncio.create_task(
        _client(cid, servers, clock, history, n_ops=25,
                rng=random.Random(100 + cid)))
        for cid in range(4)]
    try:
        await asyncio.wait_for(asyncio.gather(*clients), timeout=120)
    finally:
        stop_evt.set()
        nem.cancel()
        for s in servers:
            tr.rejoin(s.config.node_name)
        await asyncio.sleep(0)
        await stop_all(servers)

    n_ok = sum(1 for e in history if e["ok"])
    n_writes_ok = sum(1 for e in history if e["ok"] and e["op"] == "w")
    n_reads_ok = sum(1 for e in history if e["ok"] and e["op"] == "r")
    # The run must have made real progress through the partitions, or
    # the linearizability claim is vacuous.
    assert n_ok >= 40, f"only {n_ok} completed ops"
    assert n_writes_ok >= 10, f"only {n_writes_ok} completed writes"
    assert n_reads_ok >= 10, f"only {n_reads_ok} completed reads"
    assert check_linearizable(history), (
        f"history not linearizable ({len(history)} ops, {n_ok} ok)")


def test_register_linearizable_without_nemesis():
    """Control run: no partitions; everything should complete and check."""
    asyncio.run(_run_control_scenario())


async def _run_control_scenario():
    rng = random.Random(7)
    tr, servers = make_servers(3)
    await start_and_elect(servers)
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    clock = lambda: loop.time() - t0

    history = []
    clients = [asyncio.create_task(
        _client(cid, servers, clock, history, n_ops=15,
                rng=random.Random(200 + cid)))
        for cid in range(3)]
    await asyncio.wait_for(asyncio.gather(*clients), timeout=60)
    await stop_all(servers)

    assert sum(1 for e in history if not e["ok"]) <= 5
    assert sum(1 for e in history if e["ok"] and e["op"] == "r") >= 10
    assert check_linearizable(history)
