"""Test harness config.

Multi-chip behavior is tested the way SURVEY.md §4 prescribes for the
reference (multi-node simulated in one process with compressed timers):
an 8-device virtual CPU mesh via XLA host-platform device count.

The interpreter-start hook in this environment registers the ``axon``
TPU-tunnel backend and pins ``jax.config``'s
``jax_platforms="axon,cpu"`` — env-var overrides after interpreter
start are ineffective against that, and the first ``jax.devices()``
would dial the single-chip tunnel (and hang when it is unreachable).
So conftest overrides BOTH the env (for child processes) and the live
jax config, before any test imports jax: tests always run on the
virtual CPU mesh, benches on the real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

try:  # jax was already imported by the interpreter-start hook
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

# Hermetic autotune: a developer's `make tune` verdict next to the
# compile cache must not leak knob values into unit boots.  Point the
# verdict dir at an empty per-run temp dir (no verdict => registry
# defaults, no re-settle writes outside it); tests that exercise the
# verdict path override this themselves via monkeypatch.
import tempfile

os.environ.setdefault(
    "CONSUL_TPU_AUTOTUNE_DIR",
    tempfile.mkdtemp(prefix="consul_tpu_autotune_test_"))

# -- per-test watchdog -------------------------------------------------------
# One hung test must not eat the whole suite (round-1 failure: a single
# deadlocked RPC test blocked the run for the full pool timeout).
# pytest-timeout isn't in the image; SIGALRM gives the same guarantee
# for this suite's single-threaded tests.  First jit compiles on the
# CPU mesh can take ~1-2 min, hence the generous default; tests may
# override via `@pytest.mark.timeout_s(N)`.

import signal

import pytest

DEFAULT_TEST_TIMEOUT_S = 180


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "timeout_s(n): per-test watchdog seconds (default 180)")


@pytest.hookimpl(wrapper=True)
def pytest_runtest_protocol(item, nextitem):
    # Wraps the WHOLE lifecycle (setup/call/teardown): a deadlocked
    # cluster fixture must trip the watchdog the same as a test body.
    marker = item.get_closest_marker("timeout_s")
    budget = int(marker.args[0]) if marker else DEFAULT_TEST_TIMEOUT_S

    def _expired(signum, frame):
        raise TimeoutError(
            f"test watchdog: exceeded {budget}s (frame: "
            f"{frame.f_code.co_filename}:{frame.f_lineno})")

    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(budget)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
