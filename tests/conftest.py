"""Test harness config.

Multi-chip behavior is tested the way SURVEY.md §4 prescribes for the
reference (multi-node simulated in one process with compressed timers):
an 8-device virtual CPU mesh via XLA host-platform device count.  Must
run before jax is imported anywhere.  The axon sitecustomize pins the
real-TPU platform at interpreter start; conftest runs after it, so a
plain assignment here wins — tests always run on the virtual CPU mesh,
benches on the real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
