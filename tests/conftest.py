"""Test harness config.

Multi-chip behavior is tested the way SURVEY.md §4 prescribes for the
reference (multi-node simulated in one process with compressed timers):
an 8-device virtual CPU mesh via XLA host-platform device count.  Must
run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
