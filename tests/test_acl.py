"""ACL engine tests: policy parsing, evaluation, cache, server
enforcement (reference tiers: acl/*_test.go + consul/acl_test.go)."""

import asyncio

import pytest

from consul_tpu.acl import (
    ACLCache, PolicyACL, allow_all, deny_all, manage_all, parse_policy,
    root_acl)
from consul_tpu.acl.cache import ACLNotFound
from consul_tpu.acl.policy import PolicyError

HCL_RULES = """
# default deny at the root
key "" {
  policy = "read"
}
key "foo/" {
  policy = "write"
}
key "foo/private/" {
  policy = "deny"
}
service "" {
  policy = "read"
}
service "web" {
  policy = "write"
}
"""

JSON_RULES = """
{"key": {"": {"policy": "read"}, "bar/": {"policy": "write"}},
 "service": {"db": {"policy": "deny"}}}
"""


class TestPolicyParse:
    def test_hcl(self):
        pol = parse_policy(HCL_RULES)
        assert [(k.prefix, k.policy) for k in pol.keys] == [
            ("", "read"), ("foo/", "write"), ("foo/private/", "deny")]
        assert [(s.name, s.policy) for s in pol.services] == [
            ("", "read"), ("web", "write")]

    def test_json(self):
        pol = parse_policy(JSON_RULES)
        assert ("bar/", "write") in [(k.prefix, k.policy) for k in pol.keys]
        assert [(s.name, s.policy) for s in pol.services] == [("db", "deny")]

    def test_empty(self):
        pol = parse_policy("")
        assert pol.keys == [] and pol.services == []

    def test_invalid_policy_value(self):
        with pytest.raises(PolicyError):
            parse_policy('key "x" { policy = "banana" }')

    def test_invalid_block(self):
        with pytest.raises(PolicyError):
            parse_policy('frob "x" { policy = "read" }')

    def test_comments(self):
        pol = parse_policy('// line\n/* block */ key "a" { policy = "deny" }')
        assert pol.keys[0].prefix == "a"


class TestPolicyACL:
    def test_longest_prefix_keys(self):
        acl = PolicyACL.from_rules(deny_all(), HCL_RULES)
        assert acl.key_read("anything")          # root "" read
        assert not acl.key_write("anything")
        assert acl.key_write("foo/bar")
        assert acl.key_read("foo/bar")
        assert not acl.key_read("foo/private/x")  # deny beats shorter write
        assert not acl.key_write("foo/private/x")

    def test_key_write_prefix(self):
        acl = PolicyACL.from_rules(deny_all(), HCL_RULES)
        # "foo/" subtree contains a deny rule -> recursive write refused.
        assert not acl.key_write_prefix("foo/")
        assert acl.key_write_prefix("foo/bar/")   # no deny below this point

    def test_services(self):
        acl = PolicyACL.from_rules(deny_all(), HCL_RULES)
        assert acl.service_read("anything")
        assert not acl.service_write("anything")
        assert acl.service_write("web")

    def test_parent_fallback(self):
        acl = PolicyACL.from_rules(allow_all(), 'key "a/" { policy = "deny" }')
        assert not acl.key_read("a/x")
        assert acl.key_read("b/x")  # falls through to allow-all parent

    def test_static_roots(self):
        assert root_acl("allow").key_write("x")
        assert not root_acl("deny").key_read("x")
        assert root_acl("manage").acl_modify()
        assert not allow_all().acl_list()
        assert manage_all().acl_list()
        assert root_acl("bogus") is None


class TestACLCache:
    def run(self, coro):
        return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)

    def test_fault_and_cache(self):
        calls = []

        async def fault(tid):
            calls.append(tid)
            if tid == "missing":
                raise ACLNotFound("ACL not found")
            return "deny", 'key "" { policy = "write" }'

        async def body():
            cache = ACLCache(fault, ttl=30.0)
            acl = await cache.get_acl("tok1")
            assert acl.key_write("anything")
            await cache.get_acl("tok1")
            assert calls == ["tok1"]  # second hit served from cache
            with pytest.raises(ACLNotFound):
                await cache.get_acl("missing")

        self.run(body())

    def test_expiry_refaults(self):
        calls = []

        async def fault(tid):
            calls.append(tid)
            return "deny", ""

        async def body():
            cache = ACLCache(fault, ttl=30.0)
            await cache.get_acl("t", now=0.0)
            await cache.get_acl("t", now=10.0)   # fresh
            await cache.get_acl("t", now=31.0)   # expired -> refault
            assert len(calls) == 2

        self.run(body())

    def test_compile_shares_evaluators(self):
        async def fault(tid):
            return "deny", ""

        cache = ACLCache(fault)
        a = cache.compile("deny", 'key "x" { policy = "read" }')
        b = cache.compile("deny", 'key "x" { policy = "read" }')
        assert a is b


class TestServerEnforcement:
    """End-to-end: server with ACLs on, default deny, master + client tokens
    (consul/acl_test.go shape)."""

    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def _mk_server(self):
        from consul_tpu.server.server import Server, ServerConfig
        from consul_tpu.consensus.raft import RaftConfig
        return Server(ServerConfig(
            node_name="s1", datacenter="dc1",
            acl_datacenter="dc1", acl_default_policy="deny",
            acl_master_token="root",
            raft=RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.04,
                            election_timeout_max=0.08)))

    def test_kv_denied_without_token(self, loop):
        async def body():
            from consul_tpu.structs.structs import (
                DirEntry, KVSOp, KVSRequest, KeyRequest)
            srv = self._mk_server()
            await srv.start()
            await srv.wait_for_leader()
            req = KVSRequest(op=KVSOp.SET.value,
                             dir_ent=DirEntry(key="secret", value=b"x"))
            with pytest.raises(PermissionError):
                await srv.kvs.apply(req)
            # master token passes
            req.token = "root"
            assert await srv.kvs.apply(req)
            # anonymous read denied under default deny
            with pytest.raises(PermissionError):
                await srv.kvs.get(KeyRequest(key="secret"))
            await srv.stop()

        loop.run_until_complete(body())

    def test_client_token_scoping(self, loop):
        async def body():
            from consul_tpu.structs.structs import (
                ACL, ACLOp, ACLRequest, DirEntry, KVSOp, KVSRequest, KeyRequest)
            srv = self._mk_server()
            await srv.start()
            await srv.wait_for_leader()
            args = ACLRequest(op=ACLOp.SET.value, token="root", acl=ACL(
                name="app", rules='key "app/" { policy = "write" }'))
            tok = await srv.acl.apply(args)
            assert tok

            ok = KVSRequest(op=KVSOp.SET.value, token=tok,
                            dir_ent=DirEntry(key="app/cfg", value=b"1"))
            assert await srv.kvs.apply(ok)
            bad = KVSRequest(op=KVSOp.SET.value, token=tok,
                             dir_ent=DirEntry(key="other/cfg", value=b"1"))
            with pytest.raises(PermissionError):
                await srv.kvs.apply(bad)
            meta, ents = await srv.kvs.get(KeyRequest(key="app/cfg", token=tok))
            assert ents and ents[0].value == b"1"
            await srv.stop()

        loop.run_until_complete(body())

    def test_delete_tree_needs_write_prefix(self, loop):
        """Recursive delete must be refused when any rule under the prefix
        denies write (reference: KeyWritePrefix for KVSDeleteTree)."""
        async def body():
            from consul_tpu.structs.structs import (
                ACL, ACLOp, ACLRequest, DirEntry, KVSOp, KVSRequest)
            srv = self._mk_server()
            await srv.start()
            await srv.wait_for_leader()
            tok = await srv.acl.apply(ACLRequest(op=ACLOp.SET.value, token="root",
                acl=ACL(name="app", rules='key "app/" { policy = "write" } '
                                          'key "app/secret/" { policy = "deny" }')))
            assert await srv.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, token="root",
                dir_ent=DirEntry(key="app/secret/k", value=b"s")))
            with pytest.raises(PermissionError):
                await srv.kvs.apply(KVSRequest(
                    op=KVSOp.DELETE_TREE.value, token=tok,
                    dir_ent=DirEntry(key="app/")))
            # subtree without a deny below it is fine
            assert await srv.kvs.apply(KVSRequest(
                op=KVSOp.DELETE_TREE.value, token=tok,
                dir_ent=DirEntry(key="app/public/"))) is not False
            await srv.stop()

        loop.run_until_complete(body())

    def test_ui_dump_filtered(self, loop):
        async def body():
            from consul_tpu.structs.structs import (
                ACL, ACLOp, ACLRequest, NodeService, QueryOptions,
                RegisterRequest)
            srv = self._mk_server()
            await srv.start()
            await srv.wait_for_leader()
            await srv.catalog.register(RegisterRequest(
                node="n1", address="10.0.0.1", token="root",
                service=NodeService(id="db", service="db", port=5432)))
            tok = await srv.acl.apply(ACLRequest(op=ACLOp.SET.value, token="root",
                acl=ACL(name="none", rules="")))
            meta, dump = await srv.internal.node_dump(QueryOptions(token=tok))
            assert all(not row["services"] for row in dump)
            meta, dump = await srv.internal.node_dump(QueryOptions(token="root"))
            assert any(row["services"] for row in dump)
            await srv.stop()

        loop.run_until_complete(body())

    def test_acl_endpoint_validation(self, loop):
        async def body():
            from consul_tpu.server.endpoints import EndpointError
            from consul_tpu.structs.structs import ACL, ACLOp, ACLRequest
            srv = self._mk_server()
            await srv.start()
            await srv.wait_for_leader()
            # bad rules rejected before raft
            with pytest.raises(EndpointError):
                await srv.acl.apply(ACLRequest(op=ACLOp.SET.value, token="root",
                                               acl=ACL(rules='key "x" { policy = "zap" }')))
            # non-management token can't modify ACLs
            with pytest.raises(PermissionError):
                await srv.acl.apply(ACLRequest(op=ACLOp.SET.value, token="",
                                               acl=ACL(name="x")))
            # anonymous token bootstrap happened on leader establishment
            _, anon = srv.store.acl_get("anonymous")
            assert anon is not None
            # can't delete the anonymous token
            with pytest.raises(EndpointError):
                await srv.acl.apply(ACLRequest(op=ACLOp.DELETE.value, token="root",
                                               acl=ACL(id="anonymous")))
            await srv.stop()

        loop.run_until_complete(body())

    def test_health_and_catalog_filtering(self, loop):
        async def body():
            from consul_tpu.structs.structs import (
                ACL, ACLOp, ACLRequest, HealthCheck, NodeService,
                QueryOptions, RegisterRequest)
            srv = self._mk_server()
            await srv.start()
            await srv.wait_for_leader()
            for name in ("web", "db"):
                await srv.catalog.register(RegisterRequest(
                    node="n1", address="10.0.0.1", token="root",
                    service=NodeService(id=name, service=name, port=80),
                    checks=[HealthCheck(node="n1", check_id=f"c-{name}",
                                        name=f"c-{name}", status="passing",
                                        service_id=name, service_name=name)]))
            tok = await srv.acl.apply(ACLRequest(op=ACLOp.SET.value, token="root",
                acl=ACL(name="webonly", rules='service "web" { policy = "read" }')))

            meta, services = await srv.catalog.list_services(QueryOptions(token=tok))
            assert "web" in services and "db" not in services
            meta, csns = await srv.health.service_nodes("db", QueryOptions(token=tok))
            assert csns == []
            meta, csns = await srv.health.service_nodes("web", QueryOptions(token=tok))
            assert len(csns) == 1
            await srv.stop()

        loop.run_until_complete(body())
