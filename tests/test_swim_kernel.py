"""SWIM kernel behavior: detection, dissemination, refutation, recycling.

Mirrors the reference's deterministic-logic test tier (SURVEY.md §4):
seeded PRNG, compressed timers, assertions on protocol invariants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.gossip.kernel import NEVER, PHASE_FREE, init_state, run_rounds
from consul_tpu.gossip.params import SwimParams


def small_params(n=64, **kw):
    kw.setdefault("slots", 8)
    kw.setdefault("probe_every", 2)
    return SwimParams(n=n, **kw)


def run(p, fail_round, steps, seed=0, trace=False):
    st = init_state(p)
    fr = jnp.asarray(fail_round, jnp.int32)
    return run_rounds(st, jax.random.key(seed), fr, p, steps, trace=trace)


def test_no_failures_no_rumors():
    p = small_params()
    fail = np.full(p.n, NEVER, np.int32)
    st, _ = run(p, fail, 40)
    assert int(st.n_detected) == 0
    assert int(st.n_false_dead) == 0
    assert int(jnp.sum(st.slot_phase)) == 0
    assert bool(jnp.all(st.member))
    assert int(jnp.sum(st.heard)) == 0


def test_single_failure_detected_and_disseminated():
    p = small_params(n=64)
    fail = np.full(p.n, NEVER, np.int32)
    fail[17] = 10
    steps = 10 + p.slot_ttl_rounds + 40
    st, tr = run(p, fail, steps, trace=True)
    assert int(st.n_detected) == 1
    assert int(st.n_false_dead) == 0
    assert not bool(st.member[17])
    assert bool(jnp.all(st.member[np.arange(64) != 17]))
    # dead verdict reached (nearly) every member before the slot recycled
    dead_counts = np.asarray(tr.n_heard_dead).max(axis=0)
    assert dead_counts.max() >= 0.95 * 63
    # detection happened after the failure and within the suspicion bound
    mean_rounds = int(st.sum_detect_rounds) / int(st.n_detected)
    assert 0 < mean_rounds <= p.suspicion_max_rounds + 4 * p.probe_every


def test_multiple_failures():
    p = small_params(n=128, slots=16)
    rng = np.random.default_rng(1)
    fail = np.full(p.n, NEVER, np.int32)
    victims = rng.choice(p.n, 6, replace=False)
    fail[victims] = rng.integers(5, 40, 6)
    steps = 40 + p.slot_ttl_rounds + 60
    st, _ = run(p, fail, steps)
    assert int(st.n_detected) == 6
    assert int(st.n_false_dead) == 0
    assert not np.asarray(st.member)[victims].any()
    assert np.asarray(st.member).sum() == p.n - 6


def test_no_false_positives_without_loss():
    p = small_params(n=256, slots=8)
    fail = np.full(p.n, NEVER, np.int32)
    st, _ = run(p, fail, 200)
    assert int(st.n_false_dead) == 0
    assert int(st.n_refuted) == 0


def test_lossy_network_refutation_protects():
    # With heavy packet loss some probes fail and suspicion starts, but
    # refutation (plus indirect probes) must keep false deaths rare.
    p = small_params(n=128, slots=32, loss_rate=0.30)
    fail = np.full(p.n, NEVER, np.int32)
    st, _ = run(p, fail, 400, seed=3)
    # suspicion should actually have been exercised
    assert int(st.n_refuted) > 0
    assert int(st.n_false_dead) <= 2
    assert np.asarray(st.member).sum() >= p.n - 2


def test_refute_disabled_causes_false_positives():
    p = small_params(n=128, slots=32, loss_rate=0.45, refute=False,
                     suspicion_mult=1.0, suspicion_max_mult=1.0, indirect_k=0)
    fail = np.full(p.n, NEVER, np.int32)
    st, _ = run(p, fail, 400, seed=3)
    assert int(st.n_false_dead) > 0


def test_slots_recycle():
    p = small_params(n=64, slots=4)
    rng = np.random.default_rng(2)
    fail = np.full(p.n, NEVER, np.int32)
    # 8 failures through 4 slots — forces recycling
    victims = rng.choice(p.n, 8, replace=False)
    fail[victims[:4]] = 5
    fail[victims[4:]] = 5 + p.slot_ttl_rounds + 30
    steps = int(fail[victims[4:]][0]) + p.slot_ttl_rounds + 60
    st, _ = run(p, fail, steps)
    assert int(st.n_detected) == 8
    assert int(jnp.sum(st.slot_phase == PHASE_FREE)) == 4


def test_determinism():
    p = small_params(n=64)
    fail = np.full(p.n, NEVER, np.int32)
    fail[5] = 7
    st1, _ = run(p, fail, 80, seed=9)
    st2, _ = run(p, fail, 80, seed=9)
    for a, b in zip(st1, st2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow


def test_detection_time_scales_with_suspicion_mult():
    fail = None
    times = []
    for mult in (2.0, 8.0):
        p = small_params(n=64, suspicion_mult=mult, suspicion_max_mult=1.0)
        fail = np.full(p.n, NEVER, np.int32)
        fail[11] = 6
        st, _ = run(p, fail, 6 + p.slot_ttl_rounds + 50, seed=4)
        assert int(st.n_detected) == 1
        times.append(int(st.sum_detect_rounds))
    assert times[1] > times[0]


def test_hot_tier_matches_full_path():
    """hot_slots (non-default) must be a pure execution-strategy switch:
    the gathered-subset tail and the full-width tail produce bit-equal
    states — inactive rows are all-zero, so excluding them is exact."""
    fail = np.full(128, NEVER, np.int32)
    fail[7] = 10
    fail[90] = 25
    states = []
    for hot in (0, 4):
        p = SwimParams(n=128, slots=16, probe_every=2, hot_slots=hot)
        st, _ = run(p, fail, 120, seed=3)
        states.append(st)
    for a, b in zip(states[0], states[1]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quiescent_path_is_exact():
    """A run that passes through quiescent -> active -> quiescent again
    must detect exactly like one that was never quiescent-optimized:
    the final membership and counters depend only on protocol inputs."""
    p = small_params(n=96, slots=8)
    fail = np.full(p.n, NEVER, np.int32)
    fail[11] = 30  # long quiescent prefix before the only failure
    st, _ = run(p, fail, 500, seed=5)
    assert int(st.n_detected) == 1
    assert not bool(st.member[11])
    assert int(st.n_false_dead) == 0
    # All slots recycled after the episode: back to quiescent.
    assert int(jnp.sum((st.slot_phase != PHASE_FREE).astype(jnp.int32))) == 0
    assert int(jnp.sum(st.heard)) == 0


@pytest.mark.slow


def test_dissemination_strategies_bit_identical():
    """dissem is a pure execution-strategy switch: the SWAR merge, the
    per-byte-plane merge, the roll-commuted prefused tail, and the
    Pallas fused kernel must all produce identical state (the deeper
    per-regime matrix lives in tests/test_fused_parity.py)."""
    import numpy as np
    fail = np.full(256, NEVER, np.int32)
    for i in range(4):
        fail[50 * (i + 1)] = 20 + 9 * i
    outs = []
    for dissem in ("swar", "planes", "prefused", "fused"):
        p = SwimParams(n=256, slots=16, probe_every=5, loss_rate=0.1,
                       dissem=dissem)
        st, _ = run_rounds(init_state(p), jax.random.key(11),
                           jnp.asarray(fail), p, 200)
        outs.append(st)
    for other in outs[1:]:
        for name in outs[0]._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(outs[0], name)),
                np.asarray(getattr(other, name)), err_msg=name)


def run_with_joins(p, fail_round, join_round, steps, seed=0, trace=False):
    st = init_state(p)
    # Unjoined ids start outside the membership.
    st = st._replace(member=jnp.asarray(join_round == NEVER) | jnp.asarray(
        np.zeros(p.n, bool)))
    fr = jnp.asarray(fail_round, jnp.int32)
    jr = jnp.asarray(join_round, jnp.int32)
    return run_rounds(st, jax.random.key(seed), fr, p, steps, trace=trace,
                      join_round=jr)


def test_join_disseminates_alive_rumor():
    """A joining node's alive@inc floods the pool on-device (gossip.html
    behavior contract: joins propagate as gossiped alive messages)."""
    from consul_tpu.gossip.kernel import PHASE_JOIN
    p = small_params(n=64)
    fail = np.full(p.n, NEVER, np.int32)
    join = np.full(p.n, NEVER, np.int32)
    join[13] = 10  # id 13 joins at round 10
    st, tr = run_with_joins(p, fail, join, 60, trace=True)
    # it became a member on-device, with a bumped incarnation
    assert bool(st.member[13])
    assert int(st.incarnation[13]) == 1
    assert int(st.n_false_dead) == 0
    # a JOIN slot carried the announcement and reached (nearly) everyone
    phases = np.asarray(tr.slot_phase)
    nodes = np.asarray(tr.slot_node)
    jmask = (phases == PHASE_JOIN) & (nodes == 13)
    assert jmask.any(), "no JOIN slot was allocated"
    alive_counts = np.asarray(tr.n_heard_alive)
    assert alive_counts[jmask].max() >= 0.95 * 64
    # the slot recycled after its dissemination window
    assert int(jnp.sum((st.slot_phase == PHASE_JOIN).astype(jnp.int32))) == 0


def test_join_then_fail_detected():
    """A joiner that later dies is detected like any member: the JOIN
    slot re-arms into a suspicion episode on probe failure."""
    p = small_params(n=64)
    fail = np.full(p.n, NEVER, np.int32)
    join = np.full(p.n, NEVER, np.int32)
    join[20] = 5
    fail[20] = 12  # dies shortly after joining
    steps = 12 + p.slot_ttl_rounds + 40
    st, _ = run_with_joins(p, fail, join, steps)
    assert int(st.n_detected) == 1
    assert int(st.n_false_dead) == 0
    assert not bool(st.member[20])


def test_rejoin_after_dead_verdict():
    """Failed -> detected -> rejoins at a fresh incarnation: the stale
    episode clears and the node is a member again (serf failed->rejoin
    choreography, driven entirely by the join_round input)."""
    p = small_params(n=64)
    fail = np.full(p.n, NEVER, np.int32)
    join = np.full(p.n, NEVER, np.int32)
    fail[9] = 8
    rejoin_at = 8 + p.slot_ttl_rounds + 30
    join[9] = rejoin_at
    # Two phases: after the restart the node answers probes again, so
    # fail_round moves to NEVER for the rejoin window.
    st = init_state(p)
    fr = jnp.asarray(fail, jnp.int32)
    jr = jnp.asarray(join, jnp.int32)
    st, _ = run_rounds(st, jax.random.key(0), fr, p, rejoin_at, join_round=jr)
    assert not bool(st.member[9])  # dead verdict landed
    n_det = int(st.n_detected)
    assert n_det == 1
    # process restarts: answers probes again, join fires at rejoin_at
    fail[9] = NEVER
    st, _ = run_rounds(st, jax.random.key(0), jnp.asarray(fail), p, 60,
                       join_round=jr)
    assert bool(st.member[9])
    assert int(st.incarnation[9]) >= 1
    assert int(st.n_false_dead) == 0


def test_join_burst_defers_never_loses():
    """More simultaneous joiners than slots: joins queue and retry
    (memberlist never loses an alive message) — every joiner
    eventually becomes a member AND gets its announcement slot."""
    from consul_tpu.gossip.kernel import PHASE_JOIN
    p = small_params(n=64, slots=4)
    fail = np.full(p.n, NEVER, np.int32)
    join = np.full(p.n, NEVER, np.int32)
    join[10:30] = 5  # 20 joiners, 4 slots
    st, tr = run_with_joins(p, fail, join, 160, trace=True)
    assert bool(jnp.all(st.member))
    # every joiner held a JOIN slot at some point (the announcement
    # was deferred, not dropped)
    nodes = np.asarray(tr.slot_node)
    phases = np.asarray(tr.slot_phase)
    announced = set(nodes[(phases == PHASE_JOIN)].tolist())
    assert set(range(10, 30)) <= announced, sorted(announced)
    assert int(st.drops) == 0


def test_no_joins_bit_identical_to_baseline():
    """join_round=None and join_round=all-NEVER produce byte-identical
    state to each other and to the no-join API (the join machinery is
    free when unused)."""
    p = small_params(n=128)
    fail = np.full(p.n, NEVER, np.int32)
    fail[3] = 7
    st_none, _ = run(p, fail, 80)
    join = np.full(p.n, NEVER, np.int32)
    st_never, _ = run_with_joins(p, fail, join, 80)
    for a, b, name in zip(st_none, st_never, st_none._fields):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name
