"""Client agent mode: no Raft/state on the edge, RPC forwarding only.

Round-3 acceptance tier (VERDICT item 5; reference shape:
consul/client_test.go + command/agent tests with a client agent):
server + client agents on loopback — clients discover servers from LAN
gossip, forward KV/catalog/health traffic over the mesh with
last-server affinity, sync their local services via anti-entropy RPCs,
and resolve DNS through the same remote path.
"""

import asyncio

import pytest

from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.consensus.raft import RaftConfig
from consul_tpu.server.client import ConsulClient, NoServersError
from consul_tpu.structs.structs import (
    DirEntry, HEALTH_PASSING, KVSOp, KVSRequest, KeyRequest, SERF_CHECK_ID)

FAST_RAFT = RaftConfig(heartbeat_interval=0.03, election_timeout_min=0.06,
                       election_timeout_max=0.12, rpc_timeout=0.5)
TIMING = dict(probe_interval=0.05, probe_timeout=0.02, gossip_interval=0.02,
              suspicion_mult=3.0, push_pull_interval=0.5, reap_interval=0.2)


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.close()


async def _wait(cond, timeout=15.0, interval=0.03):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


async def _mk_server(name, seeds=(), expect=0, **kw):
    cfg = AgentConfig(
        node_name=name, server=True,
        bootstrap=not expect, bootstrap_expect=expect,
        rpc_mesh_port=0, http_port=0, dns_port=0,
        serf_timing=dict(TIMING), raft_config=FAST_RAFT,
        reconcile_interval=0.3, ae_interval=0.5, **kw)
    a = Agent(cfg)
    await a.start()
    if seeds:
        assert await a.join(list(seeds)) > 0
    return a


async def _mk_client(name, seeds, **kw):
    cfg = AgentConfig(
        node_name=name, server=False, bootstrap=False,
        http_port=0, dns_port=0,
        serf_timing=dict(TIMING), ae_interval=0.5, **kw)
    a = Agent(cfg)
    await a.start()
    assert await a.join(list(seeds)) > 0
    return a


def _lan_seed(agent):
    return [f"127.0.0.1:{agent.lan_pool.local_addr[1]}"]


class TestClientCore:
    def test_client_has_no_raft_or_store(self, loop):
        async def body():
            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))
            assert isinstance(client.server, ConsulClient)
            assert not hasattr(client.server, "raft")
            with pytest.raises(NoServersError):
                client.server.store  # noqa: B018 — the access raises
            # discovery: the LAN pool taught the client where srv1's
            # RPC endpoint lives (nodeJoin, consul/client.go:178-192)
            assert await _wait(lambda: "srv1" in client.server.route_table)
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())

    def test_members_parity_and_tags(self, loop):
        async def body():
            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))
            for a in (server, client):
                assert await _wait(
                    lambda a=a: len([m for m in a.lan_members()
                                     if m["Status"] == "alive"]) == 2)
            tags = {m["Name"]: m["Tags"] for m in server.lan_members()}
            assert tags["srv1"]["role"] == "consul"
            assert tags["cli1"]["role"] == "node"
            # clients never appear in the WAN pool (consul/client.go has
            # no WAN serf)
            assert client.wan_members() == []
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())

    def test_kv_write_via_client_lands_on_server(self, loop):
        async def body():
            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))
            await _wait(lambda: client.server.server_count() > 0)
            ok = await client.server.kvs.apply(KVSRequest(
                op=KVSOp.SET.value,
                dir_ent=DirEntry(key="edge", value=b"written-by-client")))
            assert ok
            _, ent = server.server.store.kvs_get("edge")
            assert ent is not None and ent.value == b"written-by-client"
            # read back through the client (leader-consistency path)
            _, entries = await client.server.kvs.get(KeyRequest(key="edge"))
            assert entries and entries[0].value == b"written-by-client"
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())

    def test_kv_via_client_http_surface(self, loop):
        async def body():
            import aiohttp
            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))
            await _wait(lambda: client.server.server_count() > 0)
            host, port = client.http.addr
            async with aiohttp.ClientSession() as s:
                async with s.put(f"http://{host}:{port}/v1/kv/http-edge",
                                 data=b"v1") as r:
                    assert await r.json() is True
                async with s.get(f"http://{host}:{port}/v1/kv/http-edge") as r:
                    body_json = await r.json()
                    assert body_json[0]["Key"] == "http-edge"
                    assert r.headers.get("X-Consul-Index")
            _, ent = server.server.store.kvs_get("http-edge")
            assert ent is not None
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())

    def test_client_edge_skips_hot_path_and_forwards_lease(self, loop):
        """The serving fast path reads raft/store locally — a client
        agent must keep routing KV through the generic mesh-forwarded
        handlers, and /v1/status/lease must answer via Status.Lease
        RPC (the client holds no lease of its own)."""
        async def body():
            import aiohttp
            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))
            await _wait(lambda: client.server.server_count() > 0)
            assert not client.http._hot_capable
            assert client.worker_pool is None
            host, port = client.http.addr
            async with aiohttp.ClientSession() as s:
                # stale falls inside the hot subset on servers; on the
                # client it must take the generic path, not 500.
                async with s.put(f"http://{host}:{port}/v1/kv/hk",
                                 data=b"x") as r:
                    assert await r.json() is True
                async with s.get(f"http://{host}:{port}"
                                 "/v1/kv/hk?stale") as r:
                    assert r.status == 200
                    assert (await r.json())[0]["Key"] == "hk"
                async with s.get(f"http://{host}:{port}"
                                 "/v1/status/lease") as r:
                    lease = await r.json()
                    assert lease["is_leader"] is True  # the server's
                    assert lease["valid"] is True
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())


class TestClientCatalog:
    def test_reconcile_registers_client_with_serf_health(self, loop):
        async def body():
            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))

            def registered():
                _, checks = server.server.store.node_checks("cli1")
                return any(c.check_id == SERF_CHECK_ID
                           and c.status == HEALTH_PASSING for c in checks)
            assert await _wait(registered), \
                "leader reconcile never registered the client node"
            # but it is NOT a raft peer and has no consul service
            assert "cli1" not in server.server.raft.peers
            _, svcs = server.server.store.node_services("cli1")
            assert not svcs or "consul" not in svcs
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())

    def test_client_service_syncs_via_anti_entropy(self, loop):
        async def body():
            from consul_tpu.structs.structs import NodeService
            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))
            await _wait(lambda: client.server.server_count() > 0)
            await client.add_service(NodeService(id="web", service="web",
                                                 port=80), [])

            def in_catalog():
                _, nodes = server.server.store.service_nodes("web", "")
                return any(sn.node == "cli1" for sn in nodes)
            assert await _wait(in_catalog), \
                "client service never reached the server catalog"
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())

    def test_client_dns_resolves_over_mesh(self, loop):
        async def body():
            from consul_tpu.agent.dns import QTYPE_SRV
            from consul_tpu.structs.structs import NodeService
            import struct

            server = await _mk_server("srv1")
            client = await _mk_client("cli1", _lan_seed(server))
            await _wait(lambda: client.server.server_count() > 0)
            await client.add_service(NodeService(id="web", service="web",
                                                 port=8080), [])

            def in_catalog():
                _, nodes = server.server.store.service_nodes("web", "")
                return bool(nodes)
            assert await _wait(in_catalog)

            # raw SRV query against the CLIENT's DNS server
            q = b"\x12\x34" + struct.pack("!HHHHH", 0x0100, 1, 0, 0, 0)
            for label in ("web", "service", "consul"):
                q += bytes([len(label)]) + label.encode()
            q += b"\x00" + struct.pack("!HH", QTYPE_SRV, 1)
            resp = await client.dns.handle(q, udp=True)
            msg_id, flags, qd, an, ns, ar = struct.unpack("!HHHHHH",
                                                          resp[:12])
            assert an >= 1, "client DNS returned no SRV answers"
            await client.stop()
            await server.stop()
        loop.run_until_complete(body())


class TestClientFailover:
    def test_client_rotates_to_surviving_server(self, loop):
        async def body():
            # three servers so quorum (and the committed entry) survives
            # the kill — with two, the dead leader takes quorum with it
            s1 = await _mk_server("srv1", expect=3)
            s2 = await _mk_server("srv2", seeds=_lan_seed(s1), expect=3)
            s3 = await _mk_server("srv3", seeds=_lan_seed(s1), expect=3)
            servers = [s1, s2, s3]
            assert await _wait(lambda: any(a.server.is_leader()
                                           for a in servers))
            client = await _mk_client("cli1", _lan_seed(s1))
            assert await _wait(
                lambda: client.server.server_count() == 3)
            # prime affinity
            ok = await client.server.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="a", value=b"1")))
            assert ok
            affine = client.server._preferred
            victim = next(a for a in servers
                          if f":{a.server.rpc_server.addr[1]}" in affine)
            survivors = [a for a in servers if a is not victim]
            await victim.stop()
            # next RPC must rotate to a survivor (client.go:352-366);
            # retried because replication/election need a beat
            async def read_ok():
                try:
                    _, entries = await client.server.kvs.get(
                        KeyRequest(key="a", allow_stale=True))
                    return bool(entries)
                except NoServersError:
                    return False
            got = False
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if await read_ok():
                    got = True
                    break
                await asyncio.sleep(0.1)
            assert got, "client never failed over to a surviving server"
            assert client.server._preferred != affine
            for a in survivors:
                await a.stop()
            await client.stop()
        loop.run_until_complete(body())

    def test_client_with_no_servers_errors_loudly(self, loop):
        async def body():
            cfg = AgentConfig(node_name="lonely", server=False,
                              bootstrap=False, http_port=0, dns_port=0,
                              serf_timing=dict(TIMING))
            a = Agent(cfg)
            await a.start()
            with pytest.raises(NoServersError):
                await a.server.kvs.get(KeyRequest(key="x"))
            await a.stop()
        loop.run_until_complete(body())
